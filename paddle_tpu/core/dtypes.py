"""Dtype table (reference: paddle/framework/data_type.h, framework.proto DataType).

TPU policy: parameters live in float32 (or the flag-selected default), matmul/
conv compute in bfloat16 on the MXU, reductions/softmax accumulate in float32.
"""

import jax.numpy as jnp

FP32 = jnp.float32
BF16 = jnp.bfloat16
FP16 = jnp.float16
INT32 = jnp.int32
INT64 = jnp.int64
BOOL = jnp.bool_

_NAMES = {
    "float32": FP32, "fp32": FP32,
    "bfloat16": BF16, "bf16": BF16,
    "float16": FP16, "fp16": FP16,
    "int32": INT32, "int64": INT64,
    "bool": BOOL,
}


def resolve(name_or_dtype):
    if isinstance(name_or_dtype, str):
        return _NAMES[name_or_dtype]
    return name_or_dtype


def param_dtype():
    from paddle_tpu.utils.flags import GLOBAL_FLAGS
    return resolve(GLOBAL_FLAGS.get("default_dtype", "float32"))


def compute_dtype():
    """Dtype fed to the MXU for matmuls/convs."""
    from paddle_tpu.utils.flags import GLOBAL_FLAGS
    return resolve(GLOBAL_FLAGS.get("compute_dtype", "bfloat16"))
