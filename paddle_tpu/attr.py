"""Parameter / layer attributes (reference: python/paddle/trainer_config_helpers/
attrs.py — ParameterAttribute and ExtraLayerAttribute)."""

from paddle_tpu.core.param import ParamAttr as _CoreParamAttr


def ParamAttr(name=None, initial_std=None, initial_mean=0.0, initial_value=None,
              initializer=None, learning_rate=1.0, l1_rate=None, l2_rate=None,
              is_static=False, sparse_update=False):
    """Factory mirroring ParameterAttribute's signature."""
    if initial_value is not None and initializer is None:
        initializer = "constant"
    return _CoreParamAttr(
        name=name, initializer=initializer, initial_mean=initial_mean,
        initial_std=initial_std, initial_value=initial_value,
        learning_rate=learning_rate, l1_rate=l1_rate, l2_rate=l2_rate,
        is_static=is_static, sparse_update=sparse_update)


class ExtraAttr:
    """Extra layer attributes (reference: ExtraLayerAttribute — drop_rate,
    error_clipping_threshold, device)."""

    def __init__(self, drop_rate=None, error_clipping_threshold=None,
                 sharding=None):
        self.drop_rate = drop_rate
        self.error_clipping_threshold = error_clipping_threshold
        self.sharding = sharding  # TPU-native: per-layer mesh-axis hints


ExtraLayerAttribute = ExtraAttr
ParameterAttribute = ParamAttr

# v2 aliases (reference: python/paddle/v2/attr.py __all__ = Param/Extra/Hook)
Param = ParamAttr
Extra = ExtraAttr
