"""User-facing parameter dict.

Reference: python/paddle/v2/parameters.py:44 — a numpy-backed dict mirroring
GradientMachine parameters, with to_tar/from_tar serialization. Here the
backing store is the jax pytree itself; numpy views are produced on access.
Non-trainable state (batch-norm stats) lives alongside in ``.state``.
"""

import pickle
from typing import Dict, List

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.param import ParamSpec, init_params
from paddle_tpu.topology import Topology


class Parameters:
    def __init__(self, specs: List[ParamSpec], values: Dict = None,
                 state_specs: List[ParamSpec] = (), state: Dict = None):
        self.specs = {s.name: s for s in specs}
        self.state_specs = {s.name: s for s in state_specs}
        self.values: Dict = values or {}
        self.state: Dict = state or {}

    # -- dict-ish API (reference: parameters.py __getitem__/__setitem__) ----
    def names(self):
        return list(self.specs)

    def keys(self):
        return self.names()

    def __contains__(self, name):
        return name in self.specs

    def __getitem__(self, name) -> np.ndarray:
        return np.asarray(self.values[name])

    def __setitem__(self, name, arr):
        spec = self.specs[name]
        arr = np.asarray(arr)
        if tuple(arr.shape) != tuple(spec.shape):
            raise ValueError(f"shape mismatch for {name}: "
                             f"{arr.shape} vs {spec.shape}")
        self.values[name] = jnp.asarray(arr, spec.resolved_dtype())

    def get_shape(self, name):
        return tuple(self.specs[name].shape)

    # -- (de)serialisation (replaces to_tar/from_tar, v2 parameters.py) -----
    def to_tar(self, f):
        payload = {
            "values": {k: np.asarray(v) for k, v in self.values.items()},
            "state": {k: np.asarray(v) for k, v in self.state.items()},
        }
        pickle.dump(payload, f, protocol=4)

    def from_tar_into(self, f):
        payload = pickle.load(f)
        for k, v in payload["values"].items():
            if k in self.specs:
                self.values[k] = jnp.asarray(v)
        for k, v in payload.get("state", {}).items():
            self.state[k] = jnp.asarray(v)
        return self

    @staticmethod
    def from_tar(f, topology=None):
        payload = pickle.load(f)
        specs = [ParamSpec(k, tuple(v.shape)) for k, v in payload["values"].items()]
        p = Parameters(specs)
        p.values = {k: jnp.asarray(v) for k, v in payload["values"].items()}
        p.state = {k: jnp.asarray(v) for k, v in payload.get("state", {}).items()}
        return p


def create(output_or_topology, key_source=None) -> Parameters:
    """paddle.parameters.create(cost) (reference: v2 parameters.py create)."""
    topo = output_or_topology if isinstance(output_or_topology, Topology) \
        else Topology(output_or_topology)
    specs = topo.param_specs()
    state_specs = topo.state_specs()
    p = Parameters(specs, state_specs=state_specs)
    p.values = init_params(specs, key_source)
    p.state = init_params(state_specs, key_source)
    return p
