"""Trace scopes: nested named timing regions that show up in xprof.

Wraps ``utils/stat.py``'s StatSet (the reference's REGISTER_TIMER_INFO
accumulators) and, when profiling is enabled AND jax is importable, also
opens ``jax.profiler.TraceAnnotation`` / ``StepTraceAnnotation`` scopes so
hot-loop regions land in the xprof timeline on real TPUs. On CPU (or with
profiling off, or without jax at all) the same scopes degrade to pure
wall-clock timers — observability code never becomes a hard jax
dependency.

Scopes nest: a ``trace_scope("backward")`` inside ``trace_scope("step")``
accumulates under the qualified name ``step/backward`` (per thread), so a
StatSet print shows the call tree, flattened.
"""

import contextlib
import threading
import time
from typing import Optional

from paddle_tpu.observe import chrome_trace as _chrome
from paddle_tpu.utils import stat as _stat

_tls = threading.local()


def _stack():
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    return _tls.stack


def current_scope() -> str:
    """The '/'-joined active scope path of this thread ('' at top level)."""
    return "/".join(_stack())


def _profiler_ctx(kind: str, name: str, **kw):
    """A profiler annotation context, or nullcontext when the profiler
    is unavailable (jax absent / too old) — never an ImportError."""
    try:
        import jax.profiler
        cls = getattr(jax.profiler, kind, None)
        if cls is None:
            return contextlib.nullcontext()
        return cls(name, **kw)
    except Exception:  # noqa: BLE001 — observability must not crash the job
        return contextlib.nullcontext()


def _profiling_enabled(use_profiler: Optional[bool]) -> bool:
    if use_profiler is not None:
        return use_profiler
    from paddle_tpu.utils.flags import GLOBAL_FLAGS
    return bool(GLOBAL_FLAGS.get("profile", False))


@contextlib.contextmanager
def trace_scope(name: str, stats: Optional[_stat.StatSet] = None,
                use_profiler: Optional[bool] = None):
    """Open a named timing scope.

    - accumulates wall time into ``stats`` (default: the global StatSet)
      under the nesting-qualified name, e.g. ``train_step/forward``
    - opens a ``jax.profiler.TraceAnnotation`` when profiling is on
    """
    stats = stats or _stat.global_stats
    stack = _stack()
    stack.append(name)
    qualified = "/".join(stack)
    ctx = (_profiler_ctx("TraceAnnotation", name)
           if _profiling_enabled(use_profiler) else contextlib.nullcontext())
    wall0 = time.time()
    start = time.perf_counter()
    try:
        with ctx:
            yield qualified
    finally:
        dur = time.perf_counter() - start
        stats.get(qualified).add(dur)
        _chrome.record_span(qualified, wall0, dur)
        stack.pop()


@contextlib.contextmanager
def step_scope(step_num: int, name: str = "train",
               stats: Optional[_stat.StatSet] = None,
               use_profiler: Optional[bool] = None):
    """Mark one training step. With profiling on this is a
    ``jax.profiler.StepTraceAnnotation`` (xprof's step-time view keys on
    it); always accumulates into the ``name`` timer. Participates in the
    nesting stack like trace_scope, so an inner ``trace_scope("region")``
    accumulates under ``train_step/region``."""
    stats = stats or _stat.global_stats
    stack = _stack()
    stack.append(name)
    qualified = "/".join(stack)
    ctx = (_profiler_ctx("StepTraceAnnotation", name, step_num=step_num)
           if _profiling_enabled(use_profiler) else contextlib.nullcontext())
    wall0 = time.time()
    start = time.perf_counter()
    try:
        with ctx:
            yield
    finally:
        dur = time.perf_counter() - start
        stats.get(qualified).add(dur)
        _chrome.record_span(qualified, wall0, dur, args={"step": step_num})
        stack.pop()


def traced(name: Optional[str] = None, **scope_kw):
    """Decorator form: ``@traced("encode")`` wraps the call in a
    trace_scope named after the function by default."""

    def deco(fn):
        import functools
        scope = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with trace_scope(scope, **scope_kw):
                return fn(*a, **kw)

        return wrapper

    return deco
