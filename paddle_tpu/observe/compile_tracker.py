"""Compile tracker: count XLA compilations and what caused them.

JAX's jit cache has no public hit/miss hook on this version, but a
cache miss is fully determined by the (function, abstract-signature)
pair — so tracking the signatures we have *seen* per function gives an
exact miss count from pure Python: a new signature on a tracked call IS
a compilation. The tracker records, per function:

- the miss count (``compile_cache_misses_total{fn=...}`` counter),
- the wall time of each miss-triggering call (compilation dominates it;
  ``compile_wall_seconds_total{fn=...}`` counter),
- the argument-shape signature that caused each miss (bounded list) —
  the evidence a recompile-storm postmortem needs ("the ragged last
  batch flips between 64 and 37").

A *recompile storm* — one function compiling ``storm_threshold``+ times
— logs a warning naming the latest offending signature, because the
usual cause (shape churn from the data pipeline) silently turns every
affected step into a multi-second compile.

jax-free at import time; ``arg_signature`` imports jax lazily and falls
back to a duck-typed container walk.
"""

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from paddle_tpu.observe import metrics as _metrics
from paddle_tpu.utils.logger import get_logger

log = get_logger("observe.compile")

_m_misses = _metrics.counter(
    "compile_cache_misses_total",
    "jit cache misses observed per tracked function (each is one "
    "XLA compilation)")
_m_compile_s = _metrics.counter(
    "compile_wall_seconds_total",
    "wall time of miss-triggering calls (compile-dominated)")


def _walk_leaves(obj, out):
    if isinstance(obj, dict):
        for k in sorted(obj, key=str):
            _walk_leaves(obj[k], out)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            _walk_leaves(v, out)
    else:
        out.append(obj)


def arg_signature(*args) -> Tuple:
    """Abstract signature of a call: the (shape, dtype) of every array
    leaf, plus repr for non-array leaves (static scalars). Two calls
    with equal signatures hit the same jit cache entry."""
    try:
        import jax
        leaves = jax.tree_util.tree_leaves(args)
    except Exception:  # noqa: BLE001 — jax absent: best-effort walk
        leaves = []
        _walk_leaves(args, leaves)
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            sig.append((tuple(shape), str(dtype)))
        else:
            sig.append(repr(leaf))
    return tuple(sig)


class CompileTracker:
    """Per-function signature sets + miss records (thread-safe)."""

    def __init__(self, storm_threshold: int = 5, max_miss_records: int = 64):
        self.storm_threshold = max(1, int(storm_threshold))
        self.max_miss_records = max_miss_records
        self._lock = threading.Lock()
        self._seen: Dict[str, set] = {}
        self._misses: Dict[str, List[dict]] = {}
        self._compile_s: Dict[str, float] = {}

    def record(self, name: str, sig: Tuple,
               wall_s: Optional[float] = None) -> bool:
        """Record one call of ``name`` with signature ``sig`` (from
        ``arg_signature``); ``wall_s`` is the call's wall time. Returns
        True when the signature is new — i.e. this call compiled."""
        with self._lock:
            seen = self._seen.setdefault(name, set())
            if sig in seen:
                return False
            seen.add(sig)
            miss = {"signature": repr(sig)[:512],
                    "wall_s": round(wall_s, 6) if wall_s else None,
                    "ts": round(time.time(), 3),
                    "miss_index": len(seen)}
            records = self._misses.setdefault(name, [])
            if len(records) < self.max_miss_records:
                records.append(miss)
            if wall_s:
                self._compile_s[name] = (self._compile_s.get(name, 0.0)
                                         + wall_s)
            n = len(seen)
        _m_misses.inc(fn=name)
        if wall_s:
            _m_compile_s.inc(wall_s, fn=name)
        if n >= self.storm_threshold and \
                (n - self.storm_threshold) % self.storm_threshold == 0:
            log.warning(
                "recompile storm: %r has compiled %d times — the jit "
                "cache is being missed repeatedly (usually shape churn "
                "from the data pipeline). Last miss signature: %s",
                name, n, miss["signature"])
        return True

    def track_call(self, name: str, fn, *args, **kwargs):
        """Call ``fn(*args, **kwargs)``, timing it and recording the
        signature — the one-liner for call sites that don't need the
        wrapper object. kwargs participate in the signature: a shape
        change in a keyword argument is a cache miss like any other."""
        sig = arg_signature(args, kwargs)
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        self.record(name, sig, time.perf_counter() - t0)
        return out

    def count(self, name: Optional[str] = None) -> int:
        """Compilations observed (for one function, or all)."""
        with self._lock:
            if name is not None:
                return len(self._seen.get(name, ()))
            return sum(len(s) for s in self._seen.values())

    def compile_seconds(self, name: Optional[str] = None) -> float:
        with self._lock:
            if name is not None:
                return self._compile_s.get(name, 0.0)
            return sum(self._compile_s.values())

    def misses(self, name: str) -> List[dict]:
        with self._lock:
            return list(self._misses.get(name, ()))

    def snapshot(self) -> Dict[str, dict]:
        """Per-function {count, compile_seconds, misses} — the flight
        recorder / healthz view."""
        with self._lock:
            return {name: {"count": len(seen),
                           "compile_seconds": round(
                               self._compile_s.get(name, 0.0), 6),
                           "misses": list(self._misses.get(name, ()))}
                    for name, seen in self._seen.items()}

    def clear(self):
        with self._lock:
            self._seen.clear()
            self._misses.clear()
            self._compile_s.clear()


_default = CompileTracker()


def default_compile_tracker() -> CompileTracker:
    return _default


def track_compiles(fn, name: Optional[str] = None,
                   tracker: Optional[CompileTracker] = None):
    """Wrap a jitted callable so every call is signature-tracked:
    ``step = observe.track_compiles(jax.jit(step), "train_step")``."""
    import functools
    tracker = tracker or _default
    label = name or getattr(fn, "__name__", repr(fn))

    @functools.wraps(fn, assigned=("__name__", "__doc__"), updated=())
    def wrapper(*args, **kwargs):
        return tracker.track_call(label, fn, *args, **kwargs)

    wrapper.tracker = tracker
    return wrapper
