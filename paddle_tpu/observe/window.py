"""Sliding-window quantile estimation + SLO policy.

The registry's ``Histogram`` is cumulative: its buckets count every
observation since process start, so "TTFT p99 over the last minute" —
the number an SLO-aware scheduler steers on and a `/healthz` probe
reports — is unrecoverable from it once traffic has been flowing for a
while (an hour of good requests hides a bad minute). ``WindowedQuantiles``
keeps the raw samples of a bounded time window and answers EXACT
nearest-rank quantiles over it; on a stationary stream the answers
agree with the cumulative histogram's bucket-resolution estimate
(pinned by tests/test_request_observability.py).

Bounded two ways: samples older than ``window_s`` expire at every
observe/read, and at most ``max_samples`` are kept (oldest evicted) so
a request flood cannot grow host memory — with eviction active the
window simply narrows to the newest ``max_samples`` observations.

``SloConfig`` is the declarative policy the serving engine evaluates
over such a window: a TTFT objective (``ttft_s`` met by ``target`` of
requests) and the burn-rate threshold past which `/healthz` degrades.
Burn rate follows the SRE convention: observed violation fraction over
the error budget (``1 - target``) — 1.0 means the budget is being
spent exactly as fast as it accrues; the default threshold flags
anything past that.

Stdlib-only (the CLI and bench orchestrator import observe).
"""

import dataclasses
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence


def _nearest_rank(sorted_vals: List[float], q: float) -> float:
    """The repo-wide percentile convention (benchmarks/serving_bench
    ``_pct``): index round(q * (n-1)) of the sorted sample."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[i]


class WindowedQuantiles:
    """Exact quantiles over a sliding time window of scalar samples."""

    def __init__(self, window_s: float = 60.0, max_samples: int = 2048,
                 clock=time.monotonic):
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        if max_samples < 1:
            raise ValueError(f"max_samples must be >= 1, "
                             f"got {max_samples}")
        self.window_s = float(window_s)
        self.max_samples = int(max_samples)
        self._clock = clock
        self._lock = threading.Lock()
        self._dq: deque = deque(maxlen=self.max_samples)   # (t, value)

    def observe(self, value: float, t: Optional[float] = None):
        """Record one sample (``t`` defaults to the clock's now; tests
        pass explicit times to pin expiry deterministically)."""
        now = self._clock() if t is None else float(t)
        with self._lock:
            self._dq.append((now, float(value)))
            self._expire(now)

    def _expire(self, now: float):
        cutoff = now - self.window_s
        dq = self._dq
        while dq and dq[0][0] <= cutoff:
            dq.popleft()

    def _values(self, now: Optional[float]) -> List[float]:
        now = self._clock() if now is None else float(now)
        with self._lock:
            self._expire(now)
            return [v for _, v in self._dq]

    def count(self, now: Optional[float] = None) -> int:
        return len(self._values(now))

    def __len__(self):
        return self.count()

    def quantile(self, q: float, now: Optional[float] = None) -> float:
        """Exact nearest-rank quantile of the live window (0.0 empty)."""
        return _nearest_rank(sorted(self._values(now)), q)

    def quantiles(self, qs: Sequence[float],
                  now: Optional[float] = None) -> Dict[float, float]:
        """Several quantiles off ONE sort of the window."""
        vals = sorted(self._values(now))
        return {q: _nearest_rank(vals, q) for q in qs}

    def samples(self, now: Optional[float] = None) -> List[tuple]:
        """Raw ``(t, value)`` pairs of the live window, oldest first.

        This is the export fleet aggregation pools. Quantiles are rank
        statistics of a distribution, not means: the fleet p99 is the
        99th percentile of EVERY request the fleet served, which only
        the pooled samples can answer. Averaging per-replica p99s is
        wrong twice over — it weights a replica that served 3 requests
        the same as one that served 3000, and a mean of per-replica
        tails neither bounds nor tracks the pooled tail (one slow
        replica's p99 dilutes into the average instead of dominating
        the fleet tail the way its requests actually do).
        """
        now = self._clock() if now is None else float(now)
        with self._lock:
            self._expire(now)
            return list(self._dq)

    def export_samples(self, now: Optional[float] = None) -> List[list]:
        """Clock-free wire form of :meth:`samples`: ``[age_s, value]``
        pairs (age relative to now). Timestamps here are this process's
        monotonic clock — meaningless to another process — so the wire
        carries ages and :meth:`absorb` re-stamps them into the
        importer's clock domain."""
        now = self._clock() if now is None else float(now)
        return [[now - t, v] for t, v in self.samples(now)]

    def absorb(self, aged_samples, now: Optional[float] = None):
        """Ingest ``[age_s, value]`` pairs (an :meth:`export_samples`
        payload, possibly from another process), re-stamped into this
        window's clock domain. Samples older than ``window_s`` are
        dropped; the pooled set is re-ordered by time so deque eviction
        stays oldest-first."""
        now = self._clock() if now is None else float(now)
        incoming = [(now - float(age), float(v))
                    for age, v in aged_samples
                    if float(age) < self.window_s]
        if not incoming:
            return
        with self._lock:
            self._expire(now)
            pooled = sorted(list(self._dq) + incoming)
            self._dq.clear()
            self._dq.extend(pooled[-self.max_samples:])

    def merge(self, *others: "WindowedQuantiles",
              now: Optional[float] = None):
        """Pool other windows' live samples into this one (same clock
        domain — in-process replicas; across processes go through
        :meth:`export_samples` / :meth:`absorb`). After merging,
        ``quantile(q)`` equals the quantile of the concatenated sample
        sets — the ONLY correct fleet quantile (see :meth:`samples` on
        why averaging per-replica quantiles is not)."""
        now = self._clock() if now is None else float(now)
        incoming = []
        for other in others:
            incoming.extend(other.samples(now))
        incoming = [(t, v) for t, v in incoming
                    if t > now - self.window_s]
        if not incoming:
            return
        with self._lock:
            self._expire(now)
            pooled = sorted(list(self._dq) + incoming)
            self._dq.clear()
            self._dq.extend(pooled[-self.max_samples:])

    def fraction_over(self, threshold: float,
                      now: Optional[float] = None) -> float:
        """Fraction of windowed samples strictly above ``threshold``
        (0.0 on an empty window — no traffic is not a violation)."""
        vals = self._values(now)
        if not vals:
            return 0.0
        return sum(1 for v in vals if v > threshold) / len(vals)

    def clear(self):
        with self._lock:
            self._dq.clear()


@dataclasses.dataclass(frozen=True)
class SloConfig:
    """A TTFT service-level objective evaluated over a sliding window.

    ``ttft_s`` met by at least ``target`` of the window's requests;
    burn rate = (fraction over ``ttft_s``) / (1 - ``target``). The
    engine's `/healthz` reports ``degraded`` (with the burn rate as
    reason) once the burn rate exceeds ``burn_threshold`` — HTTP 200
    still, so load balancers keep routing while schedulers/operators
    see the budget bleeding; only ``unhealthy`` maps to 503.
    """

    ttft_s: float
    target: float = 0.99
    window_s: float = 60.0
    burn_threshold: float = 1.0

    def __post_init__(self):
        if self.ttft_s <= 0:
            raise ValueError(f"ttft_s must be > 0, got {self.ttft_s}")
        if not 0.0 <= self.target < 1.0:
            raise ValueError(f"target must be in [0, 1), "
                             f"got {self.target}")
        if self.window_s <= 0:
            raise ValueError(f"window_s must be > 0, "
                             f"got {self.window_s}")

    @property
    def budget(self) -> float:
        """Allowed violation fraction (the error budget)."""
        return 1.0 - self.target

    def burn_rate(self, violation_fraction: float) -> float:
        return float(violation_fraction) / self.budget

    def exceeded(self, violation_fraction: float) -> bool:
        return self.burn_rate(violation_fraction) > self.burn_threshold
