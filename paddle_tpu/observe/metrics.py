"""Metrics registry: Counter / Gauge / Histogram with labeled series.

Reference slot: paddle/utils/Stat.h accumulated timers and BarrierStat —
but where the reference only had timers printed per-pass, a production
serving/training stack needs typed, labeled, exportable series. Two sinks:

- ``JsonlSink`` — one JSON record per step (TensorBoard-style scalar log);
  machine-readable trail next to ``BENCH_*.json``, tailed by
  ``paddle_tpu stats``.
- ``render_prometheus()`` — Prometheus text exposition format, so a
  scrape endpoint (or a test) can read a snapshot of any registry.

Deliberately stdlib-only: bench.py's orchestrator (which never imports
jax) and the CLI both import this module.
"""

import json
import math
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

# Prometheus' default buckets, in seconds — right-sized for request/step
# latencies from 1 ms to 10 s.
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0)


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape_label(v: str) -> str:
    """Prometheus text-format label escaping (backslash, quote, newline)
    — one raw quote in a label value would invalidate the whole scrape
    response, not just the one series."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(key: Tuple[Tuple[str, str], ...]) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in key)
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    return repr(int(f)) if f.is_integer() else repr(f)


class Metric:
    """Base: one named metric holding one series per label combination."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 registry: Optional["Registry"] = None):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._series: Dict[Tuple[Tuple[str, str], ...], object] = {}
        if registry is not None:
            registry.register(self)

    def _zero(self):
        raise NotImplementedError

    def _get(self, labels: Dict[str, str]):
        key = _label_key(labels)
        with self._lock:
            if key not in self._series:
                self._series[key] = self._zero()
            return self._series[key]

    def _peek(self, labels: Dict[str, str]):
        """Read-only lookup: never creates a series — value() and
        snapshot() must not grow label cardinality from probe paths."""
        with self._lock:
            return self._series.get(_label_key(labels))

    def series(self) -> Dict[Tuple[Tuple[str, str], ...], object]:
        with self._lock:
            return dict(self._series)

    def remove(self, **labels):
        """Drop one labelled series (no-op when absent) — the
        bounded-cardinality hygiene hook for per-entity samples whose
        entity set changes at runtime (e.g. a tenant whose budget is
        removed: its gauge must not freeze at the last written value
        forever)."""
        with self._lock:
            self._series.pop(_label_key(labels), None)

    def clear(self):
        with self._lock:
            self._series.clear()


class Counter(Metric):
    """Monotonically increasing count (requests, tokens, errors)."""

    kind = "counter"

    class _Cell:
        __slots__ = ("value",)

        def __init__(self):
            self.value = 0.0

    def _zero(self):
        return Counter._Cell()

    def inc(self, amount: float = 1.0, **labels):
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative increment "
                             f"{amount}")
        cell = self._get(labels)
        with self._lock:
            cell.value += amount

    def value(self, **labels) -> float:
        cell = self._peek(labels)
        return cell.value if cell is not None else 0.0


class Gauge(Metric):
    """Point-in-time value (queue depth, memory bytes, temperature)."""

    kind = "gauge"

    class _Cell:
        __slots__ = ("value",)

        def __init__(self):
            self.value = 0.0

    def _zero(self):
        return Gauge._Cell()

    def set(self, value: float, **labels):
        cell = self._get(labels)
        with self._lock:
            cell.value = float(value)

    def inc(self, amount: float = 1.0, **labels):
        cell = self._get(labels)
        with self._lock:
            cell.value += amount

    def dec(self, amount: float = 1.0, **labels):
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        cell = self._peek(labels)
        return cell.value if cell is not None else 0.0


class Histogram(Metric):
    """Cumulative-bucket histogram (Prometheus semantics): each bucket
    counts observations <= its upper bound; +Inf is implicit."""

    kind = "histogram"

    class _Cell:
        __slots__ = ("counts", "sum", "count", "min", "max")

        def __init__(self, n_buckets):
            self.counts = [0] * n_buckets
            self.sum = 0.0
            self.count = 0
            self.min = math.inf
            self.max = -math.inf

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = DEFAULT_BUCKETS,
                 registry: Optional["Registry"] = None):
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError(f"histogram {name}: needs at least one bucket")
        super().__init__(name, help, registry)

    def _zero(self):
        return Histogram._Cell(len(self.buckets))

    def observe(self, value: float, **labels):
        cell = self._get(labels)
        with self._lock:
            for i, b in enumerate(self.buckets):
                if value <= b:
                    cell.counts[i] += 1
                    break
            cell.sum += value
            cell.count += 1
            cell.min = min(cell.min, value)
            cell.max = max(cell.max, value)

    def _read_cell(self, cell) -> Dict[str, object]:
        """A consistent copy of one cell under the lock — renderers must
        not read counts/sum/count piecewise while observe() is mid-update
        in another thread (a torn read emits a non-monotonic histogram
        that Prometheus clients reject)."""
        with self._lock:
            return {"counts": list(cell.counts), "sum": cell.sum,
                    "count": cell.count, "min": cell.min, "max": cell.max}

    def snapshot(self, **labels) -> Dict[str, float]:
        cell = self._peek(labels)
        if cell is None:
            return {"count": 0, "sum": 0.0, "avg": 0.0,
                    "min": 0.0, "max": 0.0}
        c = self._read_cell(cell)
        return {"count": c["count"], "sum": c["sum"],
                "avg": c["sum"] / c["count"] if c["count"] else 0.0,
                "min": c["min"] if c["count"] else 0.0,
                "max": c["max"] if c["count"] else 0.0}

    def quantile(self, q: float, **labels) -> float:
        """Bucket-resolution quantile estimate from the cumulative
        counts: the upper bound of the first bucket whose cumulative
        count covers rank ``q*count`` (the Prometheus convention,
        without interpolation — the answer is exact to one bucket
        width). Observations above the last bucket report the tracked
        max; empty series report 0. The sliding-window estimator
        (``observe/window.py``) must agree with this on a stationary
        stream — pinned by tests."""
        cell = self._peek(labels)
        if cell is None:
            return 0.0
        c = self._read_cell(cell)
        if not c["count"]:
            return 0.0
        rank = q * c["count"]
        cum = 0
        for ub, n in zip(self.buckets, c["counts"]):
            cum += n
            if cum >= rank and cum > 0:
                return ub
        return c["max"]          # the +Inf bucket: report the real max

    def time(self, **labels):
        """Context manager observing the elapsed wall time in seconds."""
        return _HistTimer(self, labels)


class _HistTimer:
    def __init__(self, hist, labels):
        self._hist = hist
        self._labels = labels

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._hist.observe(time.perf_counter() - self._t0, **self._labels)
        return False


class Registry:
    """Thread-safe collection of metrics; the unit of export."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Metric] = {}

    def register(self, metric: Metric) -> Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                if type(existing) is not type(metric):
                    raise ValueError(
                        f"metric {metric.name!r} already registered as "
                        f"{existing.kind}, cannot re-register as "
                        f"{metric.kind}")
                if (isinstance(metric, Histogram)
                        and metric.buckets != existing.buckets):
                    # silently returning the old buckets would drop the
                    # caller's chosen resolution with no signal
                    raise ValueError(
                        f"histogram {metric.name!r} already registered "
                        f"with buckets {existing.buckets}, requested "
                        f"{metric.buckets}")
                return existing
            self._metrics[metric.name] = metric
            return metric

    def counter(self, name: str, help: str = "") -> Counter:
        return self.register(Counter(name, help))

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self.register(Gauge(name, help))

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self.register(Histogram(name, help, buckets))

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> List[Metric]:
        with self._lock:
            return sorted(self._metrics.values(), key=lambda m: m.name)

    def clear(self):
        with self._lock:
            self._metrics.clear()

    def clear_series(self):
        """Zero every metric's series without dropping registrations —
        module-level metrics (master.py, distributed.py) stay wired."""
        for m in self.metrics():
            m.clear()

    # -- export ------------------------------------------------------------
    def snapshot(self) -> Dict[str, dict]:
        """Nested plain-python snapshot: {name: {kind, help, series:
        [{labels, ...values}]}} — the CLI pretty-printer's input."""
        out = {}
        for m in self.metrics():
            series = []
            for key, cell in sorted(m.series().items()):
                rec = {"labels": dict(key)}
                if m.kind == "histogram":
                    rec.update(m.snapshot(**dict(key)))
                else:
                    rec["value"] = cell.value
                series.append(rec)
            out[m.name] = {"kind": m.kind, "help": m.help, "series": series}
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition format (version 0.0.4)."""
        lines = []
        for m in self.metrics():
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for key, cell in sorted(m.series().items()):
                if m.kind == "histogram":
                    c = m._read_cell(cell)     # consistent under the lock
                    cum = 0
                    for ub, n in zip(m.buckets, c["counts"]):
                        cum += n
                        bkey = key + (("le", _fmt_value(ub)),)
                        lines.append(f"{m.name}_bucket"
                                     f"{_fmt_labels(bkey)} {cum}")
                    bkey = key + (("le", "+Inf"),)
                    lines.append(f"{m.name}_bucket{_fmt_labels(bkey)} "
                                 f"{c['count']}")
                    lines.append(f"{m.name}_sum{_fmt_labels(key)} "
                                 f"{_fmt_value(c['sum'])}")
                    lines.append(f"{m.name}_count{_fmt_labels(key)} "
                                 f"{c['count']}")
                else:
                    lines.append(f"{m.name}{_fmt_labels(key)} "
                                 f"{_fmt_value(cell.value)}")
        return "\n".join(lines) + ("\n" if lines else "")


def _parse_labels(s: str) -> Dict[str, str]:
    """Parse one ``{k="v",...}`` label block (inverse of _fmt_labels,
    including the escaping)."""
    out: Dict[str, str] = {}
    i = 0
    while i < len(s):
        eq = s.index("=", i)
        name = s[i:eq].strip().lstrip(",").strip()
        assert s[eq + 1] == '"', f"malformed label block {s!r}"
        j = eq + 2
        buf = []
        while s[j] != '"':
            if s[j] == "\\":
                j += 1
                buf.append({"n": "\n"}.get(s[j], s[j]))
            else:
                buf.append(s[j])
            j += 1
        out[name] = "".join(buf)
        i = j + 1
    return out


def parse_prometheus(text: str) -> Dict[str, dict]:
    """Parse Prometheus text exposition back into the
    :meth:`Registry.snapshot` shape: ``{name: {kind, series: [{labels,
    value}]}}`` — the inverse the fleet aggregator uses to scrape a
    replica's ``/metrics`` over HTTP into its own labeled registry.

    Histogram exposition (``_bucket``/``_sum``/``_count`` lines) folds
    back under the base name as ``{labels, sum, count}`` records (the
    per-bucket counts are not reconstructed — fleet aggregation pools
    raw window samples for quantiles, never merges bucket estimates).
    Unknown/malformed lines are skipped, not fatal: a scrape is
    best-effort observability, not a parser contract.
    """
    kinds: Dict[str, str] = {}
    out: Dict[str, dict] = {}

    def _series(name: str, labels: Dict[str, str]) -> dict:
        doc = out.setdefault(name, {"kind": kinds.get(name, "untyped"),
                                    "series": []})
        key = _label_key(labels)
        for rec in doc["series"]:
            if _label_key(rec["labels"]) == key:
                return rec
        rec = {"labels": labels}
        doc["series"].append(rec)
        return rec

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                kinds[parts[2]] = parts[3]
            continue
        try:
            lhs, val_s = line.rsplit(None, 1)
            value = float(val_s)
            if "{" in lhs:
                name, rest = lhs.split("{", 1)
                rest = rest.rstrip()
                if not rest.endswith("}"):
                    raise ValueError(f"unterminated label block: "
                                     f"{line!r}")
                labels = _parse_labels(rest[:-1])
            else:
                name, labels = lhs, {}
            base = None
            for suffix, field in (("_bucket", None), ("_sum", "sum"),
                                  ("_count", "count")):
                cand = name[:-len(suffix)] if name.endswith(suffix) else None
                if cand and kinds.get(cand) == "histogram":
                    base, comp = cand, field
                    break
            if base is not None:
                if comp is None:
                    continue             # bucket lines: not reconstructed
                _series(base, labels)[comp] = value
            else:
                _series(name, labels)["value"] = value
        except (ValueError, AssertionError, IndexError):
            continue
    for name, doc in out.items():
        doc["kind"] = kinds.get(name, doc["kind"])
    return out


# -- the global default registry -------------------------------------------

_default = Registry()


def default_registry() -> Registry:
    return _default


def counter(name: str, help: str = "") -> Counter:
    return _default.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return _default.gauge(name, help)


def histogram(name: str, help: str = "",
              buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
    return _default.histogram(name, help, buckets)


# -- JSONL scalar sink ------------------------------------------------------

class JsonlSink:
    """One JSON record per step, appended to a file — the TensorBoard-
    scalars equivalent a shell can grep and `paddle_tpu stats` can tail.

    Records carry ``ts`` (epoch seconds) plus whatever scalars the caller
    passes; non-finite floats serialize as strings so the file stays
    valid JSON line-by-line.

    Writes are block-buffered and flushed every ``flush_every`` records
    or at least once a second — a per-line flush costs a ~100 µs syscall
    that would dominate sub-ms train steps (the <5% overhead budget).
    """

    def __init__(self, path: str, flush_every: int = 32):
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._f = open(path, "a")
        self._lock = threading.Lock()
        self._n = 0
        self._last_flush = time.monotonic()
        self.flush_every = max(1, flush_every)

    @staticmethod
    def _clean(v):
        """Stringify non-finite floats at ANY depth (a diverged run's
        metrics dict carries NaN) — bare NaN/Infinity is not valid JSON
        and would break strict parsers line-by-line."""
        if isinstance(v, float) and not math.isfinite(v):
            return repr(v)
        if isinstance(v, dict):
            return {k: JsonlSink._clean(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [JsonlSink._clean(x) for x in v]
        return v

    def write(self, record: Optional[dict] = None, **scalars):
        rec = {"ts": round(time.time(), 3)}
        if record:
            rec.update(record)
        rec.update(scalars)
        line = json.dumps(self._clean(rec))
        with self._lock:
            if self._f.closed:
                return
            self._f.write(line + "\n")
            self._n += 1
            now = time.monotonic()
            if (self._n % self.flush_every == 0
                    or now - self._last_flush >= 1.0):
                self._f.flush()
                self._last_flush = now

    def flush(self):
        with self._lock:
            if not self._f.closed:
                self._f.flush()

    def close(self):
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def read_jsonl(path: str, last: Optional[int] = None) -> List[dict]:
    """Parse a JSONL metrics file; malformed lines (a crash mid-write)
    are skipped, not fatal. ``last`` keeps only the trailing N records."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out[-last:] if last else out
