"""Fleet metrics aggregation: N replica registries merged into one.

PR 15 made serving horizontal, but each replica's registry was only
ever readable one at a time — "what is the FLEET's TTFT p99" had no
answer. :class:`FleetAggregator` is the router-side half: on the
router's existing health-poll cadence it ingests every replica's
metrics view (the in-process handle passes the engine registry's
``snapshot()`` dict; the TCP handle scrapes HTTP ``/metrics`` and
parses it back with ``metrics.parse_prometheus`` — same shape either
way) and merges it into one labeled fleet registry:

- **counters** are summed across replicas under ``fleet_<name>``
  (per-replica DELTAS summed, clamped at zero, so a replica restart —
  its counters reset — never subtracts from the fleet total);
- **gauges** are kept per-replica under ``fleet_<name>{replica=...}``
  (a fleet-summed queue depth would hide exactly the placement skew a
  gauge exists to show);
- **histograms** are not merged (bucket estimates don't pool) — fleet
  quantiles come from the raw windowed TTFT samples every replica
  exports in its ``/healthz`` ``window.ttft_samples`` (clock-free
  ``[age_s, value]`` pairs), pooled through
  ``WindowedQuantiles.absorb`` into ``fleet_ttft_window_seconds{q}``.
  Averaging per-replica p99s instead would weight a 3-request replica
  like a 3000-request one and lose the fleet tail entirely — see
  ``WindowedQuantiles.samples`` for the full argument.

Each scrape can append one record to a JSONL time-series (``kind:
"fleet"``) for post-hoc analysis, and :func:`death_postmortem` bundles
a dead replica's last-known state with the router's view into one
flight-recorder artifact.

The member handle is generalized past serving replicas: the TRAINING
gang supervisor (runtime/supervisor.py) constructs the same aggregator
with ``prefix="gang"``, ``entity_label="rank"`` and
``window_keys=("step_time", "barrier_wait")`` — workers embed their
registry snapshot + raw window exports in their heartbeat files, and
the supervisor's ``/metrics`` then serves ``gang_<name>{rank=...}``
gauges, delta-summed counters, and pooled
``gang_step_time_window_seconds{q}`` with the identical
never-average-per-rank-p99s semantics.

Stdlib-only (the CLI and bench orchestrator import observe).
"""

import os
import time
from typing import Dict, List, Optional

from paddle_tpu.observe import metrics as _metrics
from paddle_tpu.observe.window import WindowedQuantiles

_QS = (("p50", 0.5), ("p95", 0.95), ("p99", 0.99))


class FleetAggregator:
    """Scrape-and-merge of N replica metric views into one registry.

    ``registry`` is where the fleet series land — the router passes its
    OWN registry so one ``/metrics`` scrape answers for the whole
    fleet; defaults to a fresh one. ``jsonl_path`` appends one record
    per scrape for post-hoc time-series analysis.

    ``prefix``/``entity_label``/``window_keys`` generalize the member
    handle: the serving router keeps the defaults
    (``fleet_*{replica=...}`` with the pooled ``ttft`` window); the
    training-gang supervisor passes ``prefix="gang"``,
    ``entity_label="rank"``, ``window_keys=("step_time",
    "barrier_wait")`` so the same delta-summed-counter /
    labeled-gauge / pooled-raw-samples semantics serve the gang. Each
    window key ``k`` is fed from the member doc's ``window.
    <k>_samples`` export and lands as ``<prefix>_<k>_window_seconds{q}``
    plus a sample-count gauge (suffix ``count_suffix`` — "_requests"
    for serving, "_samples" reads better for step times).
    """

    def __init__(self, *, registry: Optional[_metrics.Registry] = None,
                 window_s: float = 60.0,
                 jsonl_path: Optional[str] = None,
                 clock=time.monotonic,
                 prefix: str = "fleet",
                 entity_label: str = "replica",
                 window_keys=("ttft",),
                 count_suffix: str = "_requests"):
        self.registry = (registry if registry is not None
                         else _metrics.Registry())
        self.window_s = float(window_s)
        self._clock = clock
        self.prefix = str(prefix)
        self.entity_label = str(entity_label)
        self.window_keys = tuple(window_keys)
        self._sink = (_metrics.JsonlSink(jsonl_path)
                      if jsonl_path else None)
        # (member, metric, label_key) -> last seen cumulative value:
        # the delta base that makes counter summing reset-safe
        self._last_counts: Dict[tuple, float] = {}
        # member -> (scrape_t, {window_key: [[age_s, value], ...]}) —
        # the LATEST window export per member, pooled on demand
        # (re-absorbing every scrape would duplicate samples)
        self._samples: Dict[str, tuple] = {}
        self._states: Dict[str, str] = {}
        reg = self.registry
        self._m_scrapes = reg.counter(
            f"{self.prefix}_scrapes_total",
            "aggregator scrape rounds completed")
        # the serving census gauge predates the generalization and its
        # name is pinned by dashboards/alert rules; other prefixes get
        # the neutral "<prefix>_members"
        census = ("fleet_replicas" if self.prefix == "fleet"
                  else f"{self.prefix}_members")
        self._m_members = reg.gauge(
            census, "members per admission state (label state) — the "
            "dead-member alert rule's input")
        self._m_windows = {}
        for key in self.window_keys:
            self._m_windows[key] = (
                reg.gauge(
                    f"{self.prefix}_{key}_window_seconds",
                    f"rolling {key} quantile over the window (label "
                    "q), POOLED from every member's raw windowed "
                    "samples — never an average of per-member "
                    "quantiles"),
                reg.gauge(
                    f"{self.prefix}_{key}_window{count_suffix}",
                    f"samples behind the pooled {key} window "
                    "quantiles"))

    # -- ingestion ---------------------------------------------------------
    def observe_replica(self, name: str, *, state: str = "ok",
                        health: Optional[dict] = None,
                        snapshot: Optional[dict] = None,
                        now: Optional[float] = None):
        """Ingest one member's view: its admission state, its
        ``/healthz``-shaped document (source of the raw window samples
        under ``window.<key>_samples``) and its registry snapshot
        (counters + gauges). Either doc may be None (endpoint
        unreachable) — the aggregator keeps the last window view and
        simply skips the counter round."""
        now = self._clock() if now is None else float(now)
        name = str(name)
        self._states[name] = str(state)
        if snapshot:
            self._merge_snapshot(name, snapshot)
        win = (health or {}).get("window") or {}
        found = {key: list(win[f"{key}_samples"])
                 for key in self.window_keys
                 if f"{key}_samples" in win}
        if found:
            # a partial export keeps the other keys' last view
            prev = self._samples.get(name)
            merged = dict(prev[1]) if prev else {}
            merged.update(found)
            self._samples[name] = (now, merged)

    def members(self):
        """The members currently in the state census (census order is
        insertion order — callers sort)."""
        return list(self._states)

    def _merge_snapshot(self, name: str, snapshot: Dict[str, dict]):
        for mname, doc in snapshot.items():
            kind = doc.get("kind")
            series = doc.get("series") or []
            if kind == "counter":
                m = self.registry.counter(f"{self.prefix}_{mname}")
                for rec in series:
                    labels = dict(rec.get("labels") or {})
                    try:
                        value = float(rec.get("value", 0.0))
                    except (TypeError, ValueError):
                        continue
                    key = (name, mname,
                           tuple(sorted(labels.items())))
                    delta = value - self._last_counts.get(key, 0.0)
                    self._last_counts[key] = value
                    if delta > 0:
                        m.inc(delta, **labels)
            elif kind == "gauge":
                m = self.registry.gauge(f"{self.prefix}_{mname}")
                for rec in series:
                    labels = dict(rec.get("labels") or {})
                    try:
                        value = float(rec.get("value", 0.0))
                    except (TypeError, ValueError):
                        continue
                    labels[self.entity_label] = name  # ours wins
                    m.set(value, **labels)
            # histograms: deliberately skipped (see module docstring)

    def drop_replica(self, name: str):
        """Forget a member's window samples and counter bases (it
        died; its gauges stay at their last value under its label —
        the post-mortem view — until the next scrape overwrites or a
        restart re-registers it)."""
        name = str(name)
        self._samples.pop(name, None)
        for key in [k for k in self._last_counts if k[0] == name]:
            self._last_counts.pop(key, None)

    def forget_state(self, name: str):
        """Drop a member from the state census entirely (admin
        removal — as opposed to ``drop_replica``, which keeps the
        ``dead`` entry so the dead-member alert can fire), and remove
        every aggregated gauge series carrying its entity label (the
        stale-sample hygiene a gang shrink relies on). The next
        ``finish_scrape`` stops counting it, which is what RESOLVES
        that alert."""
        self._states.pop(str(name), None)
        for mname, doc in list(self.registry.snapshot().items()):
            if (not mname.startswith(f"{self.prefix}_")
                    or doc["kind"] != "gauge"):
                continue
            m = self.registry.get(mname)
            for rec in doc.get("series") or []:
                labels = dict(rec.get("labels") or {})
                if labels.get(self.entity_label) == name:
                    m.remove(**labels)

    # -- derived fleet series ----------------------------------------------
    def pooled(self, key: str,
               now: Optional[float] = None) -> WindowedQuantiles:
        """The pooled window for one key: every member's latest
        raw-sample export pooled (ages shifted by time-since-scrape)
        into one WindowedQuantiles. Built fresh per call — the
        per-member exports are the state; re-pooling is how expiry
        stays exact."""
        now = self._clock() if now is None else float(now)
        pool = WindowedQuantiles(window_s=self.window_s,
                                 max_samples=65536, clock=self._clock)
        for scrape_t, by_key in self._samples.values():
            drift = now - scrape_t
            pool.absorb([[age + drift, v]
                         for age, v in by_key.get(key, ())], now=now)
        return pool

    def pooled_ttft(self, now: Optional[float] = None
                    ) -> WindowedQuantiles:
        """The serving-era name for ``pooled("ttft")``."""
        return self.pooled("ttft", now)

    def finish_scrape(self, now: Optional[float] = None) -> dict:
        """Close one scrape round: refresh the derived gauges (state
        counts, pooled window quantiles per key), append the JSONL
        record, return a summary dict (what the record carried)."""
        now = self._clock() if now is None else float(now)
        self._m_scrapes.inc()
        by_state: Dict[str, int] = {}
        for s in self._states.values():
            by_state[s] = by_state.get(s, 0) + 1
        for s in ("ok", "degraded", "unhealthy", "dead", "done"):
            if s == "done" and self.prefix == "fleet":
                continue       # serving has no clean-exit state
            self._m_members.set(by_state.get(s, 0), state=s)
        summary = {"kind": self.prefix,
                   "replicas": dict(self._states)}
        for key in self.window_keys:
            pool = self.pooled(key, now)
            qs = pool.quantiles([q for _, q in _QS], now=now)
            m_win, m_n = self._m_windows[key]
            for lbl, q in _QS:
                m_win.set(qs[q], q=lbl)
            m_n.set(pool.count(now))
            summary[f"{key}_p50_s"] = round(qs[0.5], 6)
            summary[f"{key}_p99_s"] = round(qs[0.99], 6)
            summary.setdefault("window_requests", pool.count(now))
        if self._sink is not None:
            self._sink.write(dict(summary))
        return summary

    def ttft_quantile(self, q: float,
                      now: Optional[float] = None) -> float:
        return self.pooled("ttft", now).quantile(q, now=now)

    def close(self):
        if self._sink is not None:
            self._sink.close()


def death_postmortem(name: str, *, router_view: Optional[dict] = None,
                     last_health: Optional[dict] = None,
                     outstanding: Optional[List[dict]] = None,
                     alerts: Optional[List[dict]] = None,
                     path: Optional[str] = None) -> Optional[str]:
    """Bundle a dead replica's post-mortem with the router's view into
    ONE flight artifact: the member's last-known ``/healthz`` document,
    the work it held when the transport died, the router's fleet
    health document and firing alerts — plus the standard flight
    snapshot (metrics registry, env, compile tracker). Written as
    ``fleet_death_<replica>_<utc>.json`` in the flight dir; returns
    the path (None when the write failed — post-mortems never raise
    into the requeue path)."""
    from paddle_tpu.observe import flight as _flight
    rec = _flight.default_flight_recorder()
    rec.record({"kind": "replica_death", "replica": str(name),
                "last_health": last_health or {},
                "outstanding": outstanding or [],
                "router": router_view or {},
                "alerts": alerts or []})
    if path is None:
        path = os.path.join(
            _flight.flight_dir(),
            time.strftime(f"fleet_death_{name}_%Y%m%d_%H%M%S",
                          time.gmtime()) + f"_{os.getpid()}.json")
    return rec.dump(path, reason=f"replica {name} died")
