"""XLA cost accounting: FLOPs/bytes per compiled step, and MFU.

``jax.stages.Lowered.cost_analysis()`` reports the HLO cost model's
FLOP and byte counts for a lowered (traced, pre-XLA-optimization)
computation — the *model* FLOPs of the step, before rematerialization
inflates them. Pulling it costs one extra trace of the function (no
XLA compile), so the trainer does it lazily, once per step signature,
and only when an observability consumer exists.

MFU (model FLOPs utilisation) = flops_per_step / (step_seconds ×
peak_flops), against the declared per-chip peak table in
``core/place.py`` (override: ``PADDLE_TPU_PEAK_TFLOPS``). This is the
number the perf program steers by — "15.9% MFU" says exactly how far
from "as fast as the hardware allows" a run is, where images/sec says
nothing across models.

jax-free at import time (the CLI and bench orchestrator import
``observe``); every jax touch is inside a function and failure-tolerant
— cost accounting must never take down a training loop.
"""

from typing import Optional


def _abstract(args):
    """Concrete args → ShapeDtypeStruct pytree (lower() traces shapes,
    it never needs the buffers — donated args stay valid)."""
    import jax

    def to_sds(leaf):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            return jax.ShapeDtypeStruct(tuple(shape), dtype)
        return leaf

    return jax.tree_util.tree_map(to_sds, args)


def normalize_cost(analysis) -> Optional[dict]:
    """cost_analysis() output (dict here, list-of-dicts on some
    versions) → {"flops", "bytes_accessed"} floats, or None."""
    if analysis is None:
        return None
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
        if analysis is None:
            return None
    flops = analysis.get("flops")
    nbytes = analysis.get("bytes accessed",
                          analysis.get("bytes_accessed"))
    if flops is None and nbytes is None:
        return None
    return {"flops": float(flops or 0.0),
            "bytes_accessed": float(nbytes or 0.0)}


def lowered_cost(fn, *args) -> Optional[dict]:
    """FLOPs/bytes of ``fn(*args)`` from the lowered HLO cost model.

    ``fn`` is a jitted function; ``args`` may be concrete arrays or
    ShapeDtypeStructs (concrete args are abstracted first — nothing
    executes). Returns ``{"flops", "bytes_accessed"}`` or None when the
    lowering or the cost model is unavailable.
    """
    try:
        lowered = fn.lower(*_abstract(args))
        return normalize_cost(lowered.cost_analysis())
    except Exception:  # noqa: BLE001 — accounting is best-effort
        return None


def compiled_cost(compiled) -> Optional[dict]:
    """Same normalization for a ``jax.stages.Compiled`` (post-XLA
    numbers — includes rematerialization; use for AOT artifacts where
    the compiled object already exists)."""
    try:
        return normalize_cost(compiled.cost_analysis())
    except Exception:  # noqa: BLE001
        return None


def device_peak_flops() -> Optional[float]:
    """Declared peak FLOP/s of the default device (core.place table /
    PADDLE_TPU_PEAK_TFLOPS override); None when unknown."""
    try:
        from paddle_tpu.core import place
        return place.peak_flops()
    except Exception:  # noqa: BLE001 — no backend / no table entry
        return None


def mfu(flops_per_step: Optional[float], step_seconds: float,
        peak_flops: Optional[float] = None) -> Optional[float]:
    """Model-FLOPs utilisation of one step; None when inputs unknown."""
    if peak_flops is None:
        peak_flops = device_peak_flops()
    if not flops_per_step or not peak_flops or step_seconds <= 0:
        return None
    return flops_per_step / (step_seconds * peak_flops)
