"""Flight recorder: the last K step records + a config/env snapshot,
dumped as a JSON post-mortem when a run dies.

A diverged or crashed run's most valuable evidence — the trajectory of
its final steps, what was compiled, what the environment looked like —
lives in process memory and is gone by the time anyone looks. The
recorder keeps a bounded ring of recent step records (the trainer feeds
it every step; a deque append, no I/O) and ``dump()`` writes one
self-contained artifact:

- ``last_steps``: the ring (loss / wall time / mfu / compile_count ...)
- ``config``: GLOBAL_FLAGS values
- ``env``: PADDLE_* / JAX_* / XLA_* environment variables
- ``metrics``: the default registry snapshot
- ``compile_tracker``: per-function compile counts + miss signatures
- ``exception``: type/message/traceback when dumping from a failure

Dump triggers: the trainer's NaN tripwire (debug_nans), any exception
escaping the training loop, and — opt-in via ``install_excepthook()`` —
unhandled exceptions anywhere in the process. Artifacts land in
``PADDLE_TPU_FLIGHT_DIR`` (flag ``flight_dir``; default the working
directory) as ``flight_<utc>_<pid>.json``.

Stdlib-only at import time.
"""

import collections
import json
import os
import sys
import threading
import time
import traceback
from typing import List, Optional

from paddle_tpu.utils.logger import get_logger

log = get_logger("observe.flight")

_ENV_PREFIXES = ("PADDLE_", "JAX_", "XLA_", "LIBTPU_", "TPU_")


class FlightRecorder:
    """Bounded ring of step records + the dump machinery."""

    def __init__(self, capacity: int = 256):
        self._lock = threading.Lock()
        self._ring = collections.deque(maxlen=max(1, int(capacity)))
        self._dumped_paths: List[str] = []

    def record(self, rec: dict):
        """Append one step record (cheap: no copy beyond the dict the
        caller already built, no I/O)."""
        with self._lock:
            self._ring.append(rec)

    def records(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def clear(self):
        with self._lock:
            self._ring.clear()
            self._dumped_paths = []

    @property
    def dumped_paths(self) -> List[str]:
        with self._lock:
            return list(self._dumped_paths)

    def _snapshot(self, reason: str, exc: Optional[BaseException]) -> dict:
        from paddle_tpu.observe import metrics as _metrics
        from paddle_tpu.observe.chrome_trace import _process_index
        from paddle_tpu.observe.compile_tracker import \
            default_compile_tracker
        from paddle_tpu.utils.flags import GLOBAL_FLAGS

        snap = {
            "kind": "flight_recorder",
            "reason": reason,
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime()),
            "pid": os.getpid(),
            "argv": list(sys.argv),
            # shared guarded resolution (env → jax → 0): a malformed
            # PADDLE_PROCESS_ID must not cost the post-mortem its one job
            "process_index": _process_index(),
            "config": {k: v for k, (v, _) in
                       GLOBAL_FLAGS.describe().items()},
            "env": {k: v for k, v in sorted(os.environ.items())
                    if k.startswith(_ENV_PREFIXES)},
            "versions": {"python": sys.version.split()[0]},
            "metrics": _metrics.default_registry().snapshot(),
            "compile_tracker": default_compile_tracker().snapshot(),
            "last_steps": self.records(),
        }
        jax = sys.modules.get("jax")
        if jax is not None:
            try:
                snap["versions"]["jax"] = jax.__version__
                snap["devices"] = [str(d) for d in jax.devices()]
            except Exception:  # noqa: BLE001 — backend may be wedged
                pass
        if exc is not None:
            snap["exception"] = {
                "type": type(exc).__name__,
                "message": str(exc),
                "traceback": "".join(traceback.format_exception(
                    type(exc), exc, exc.__traceback__))[-8192:],
            }
        return snap

    def dump(self, path: Optional[str] = None, reason: str = "",
             exc: Optional[BaseException] = None) -> Optional[str]:
        """Write the post-mortem artifact; returns its path (None when
        the write failed — dumping must never mask the original error)."""
        from paddle_tpu.observe.metrics import JsonlSink

        if path is None:
            path = os.path.join(
                flight_dir(),
                time.strftime("flight_%Y%m%d_%H%M%S", time.gmtime())
                + f"_{os.getpid()}.json")
        try:
            snap = JsonlSink._clean(self._snapshot(reason, exc))
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(snap, f, indent=1, default=repr)
            os.replace(tmp, path)
        except Exception as e:  # noqa: BLE001 — a snapshot/serialization
            # failure must not bury the crash being post-mortemed
            log.warning("flight recorder dump to %s failed: %s: %s",
                        path, type(e).__name__, e)
            return None
        with self._lock:
            self._dumped_paths.append(path)
        log.warning("flight recorder: post-mortem written to %s (%s)",
                    path, reason or "no reason given")
        return path


def flight_dir() -> str:
    """Artifact directory: flight_dir flag → PADDLE_TPU_FLIGHT_DIR env
    (the flag already reads the env) → the working directory."""
    try:
        from paddle_tpu.utils.flags import GLOBAL_FLAGS
        d = GLOBAL_FLAGS.get("flight_dir")
    except Exception:  # noqa: BLE001
        d = None
    return d or os.environ.get("PADDLE_TPU_FLIGHT_DIR") or "."


def configured() -> bool:
    """True when an explicit flight directory is set (flag or env) —
    the trainer's generic crash dump is gated on it so default runs
    never litter artifacts; the NaN tripwire dumps regardless. An
    explicit ``.`` counts as configured (opting INTO cwd dumps)."""
    try:
        from paddle_tpu.utils.flags import GLOBAL_FLAGS
        if GLOBAL_FLAGS.get("flight_dir"):
            return True
    except Exception:  # noqa: BLE001
        pass
    return bool(os.environ.get("PADDLE_TPU_FLIGHT_DIR"))


_default = FlightRecorder()


def default_flight_recorder() -> FlightRecorder:
    return _default


_hook_installed = False


def install_excepthook():
    """Chain a sys.excepthook that dumps the default recorder on any
    unhandled exception, then defers to the previous hook. Idempotent;
    opt-in (library code must not hijack the hook by default)."""
    global _hook_installed
    if _hook_installed:
        return
    prev = sys.excepthook

    def hook(exc_type, exc, tb):
        try:
            e = exc if isinstance(exc, BaseException) else exc_type(exc)
            if e.__traceback__ is None:
                e = e.with_traceback(tb)
            _default.dump(reason="unhandled exception", exc=e)
        except Exception:  # noqa: BLE001 — never mask the real crash
            pass
        prev(exc_type, exc, tb)

    sys.excepthook = hook
    _hook_installed = True
