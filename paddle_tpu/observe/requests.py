"""Per-request records: bounded ring + tail-latency attribution.

Cumulative histograms say *that* TTFT p99 moved; this module keeps the
evidence of *which* requests paid and *why*. Each finished (or
rejected) serving-engine request leaves one flat record — timestamps,
token counts, prefix-cache hit fraction, and its latency split into
the four places a request can spend time:

- ``queue_wait_s``     submitted -> admitted to a slot
- ``prefill_own_s``    device time of the request's OWN prefill
                       chunk(s)
- ``prefill_stall_s``  admitted -> first token, minus own prefill:
                       time spent parked behind OTHER requests' chunks
                       and interleaved decode steps (the chunked-
                       prefill scheduling artifact the Ascend field
                       study calls out)
- ``decode_s``         first token -> finish

``attribute()`` turns a record into component fractions of its TTFT-
plus-decode span and names the dominant component — the "top-k slowest,
attributed" view `/requests` and ``paddle_tpu stats --requests`` serve.

The ring is bounded (default 512 records, ``PADDLE_TPU_REQUEST_LOG``
overrides; 0 disables) so a full serving trace can never grow host
memory — the acceptance test pins this. Engines write both their own
log and the process default (one CLI flag inspects everything).

Stdlib-only.
"""

import os
import threading
from collections import deque
from typing import Dict, List, Optional

# the latency components of one request, in lifecycle order
COMPONENTS = ("queue_wait_s", "prefill_own_s", "prefill_stall_s",
              "decode_s")


def _env_capacity(default: int = 512) -> int:
    try:
        return int(os.environ.get("PADDLE_TPU_REQUEST_LOG", default))
    except ValueError:
        return default


DEFAULT_CAPACITY = _env_capacity()


def attribute(rec: Dict) -> Dict:
    """Attribution of one request record: per-component seconds and
    fractions (of the components' sum — the submit->finish span minus
    unaccounted scheduler slack) plus TWO dominance answers:

    - ``dominant``       over all four components — where the request's
                         LIFETIME went;
    - ``ttft_dominant``  over the three pre-first-token components
                         (queue wait, own prefill, prefill stall) —
                         where its TTFT went. Decode time is not part
                         of TTFT, so a long generation must not mask a
                         scheduling artifact.

    Both are ``none`` for a record with no measured time (a rejection).
    """
    comps = {c: max(float(rec.get(c) or 0.0), 0.0) for c in COMPONENTS}
    total = sum(comps.values())
    if total <= 0:
        return {"components": comps,
                "fractions": {c: 0.0 for c in comps},
                "dominant": "none", "ttft_dominant": "none"}
    dominant = max(COMPONENTS, key=lambda c: comps[c])
    ttft_comps = COMPONENTS[:3]              # queue, own, stall
    ttft_total = sum(comps[c] for c in ttft_comps)
    ttft_dominant = (max(ttft_comps, key=lambda c: comps[c])[:-2]
                     if ttft_total > 0 else "none")
    return {"components": comps,
            "fractions": {c: comps[c] / total for c in comps},
            "dominant": dominant[:-2],       # strip the trailing "_s"
            "ttft_dominant": ttft_dominant}


class RequestLog:
    """Thread-safe bounded ring of request records (oldest evicted)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._capacity = max(0, int(capacity))
        self._dq: deque = deque(maxlen=self._capacity or 1)
        self._evicted = 0

    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def enabled(self) -> bool:
        return self._capacity > 0

    def add(self, rec: Dict):
        if not self._capacity:
            return
        with self._lock:
            if len(self._dq) == self._capacity:
                self._evicted += 1
            self._dq.append(dict(rec))

    def records(self) -> List[Dict]:
        with self._lock:
            return [dict(r) for r in self._dq]

    def evicted(self) -> int:
        with self._lock:
            return self._evicted

    def __len__(self):
        with self._lock:
            return len(self._dq)

    def clear(self):
        with self._lock:
            self._dq.clear()
            self._evicted = 0

    def slowest(self, k: int = 10, by: str = "ttft_s") -> List[Dict]:
        """Top-``k`` completed requests by ``by`` (descending), each
        with its ``attribution`` attached — the tail-latency evidence.
        Records without the key (rejections when sorting by latency)
        sort last."""
        recs = [r for r in self.records() if r.get(by) is not None]
        recs.sort(key=lambda r: float(r[by]), reverse=True)
        out = []
        for r in recs[:max(0, int(k))]:
            r = dict(r)
            r["attribution"] = attribute(r)
            out.append(r)
        return out

    def summary(self) -> Dict:
        """Aggregate view for `/requests`: counts by finish reason and
        by dominant component."""
        reasons: Dict[str, int] = {}
        dominant: Dict[str, int] = {}
        for r in self.records():
            reasons[str(r.get("finish_reason"))] = (
                reasons.get(str(r.get("finish_reason")), 0) + 1)
            d = attribute(r)["dominant"]
            dominant[d] = dominant.get(d, 0) + 1
        return {"count": len(self), "evicted": self.evicted(),
                "capacity": self.capacity, "by_reason": reasons,
                "by_dominant_component": dominant}


_default = RequestLog()


def default_request_log() -> RequestLog:
    return _default
