"""Step bottleneck attribution: input_bound | compute_bound | sync_bound.

The trainer has timed ``feed`` / ``train_step/dispatch`` / ``host_sync``
spans since PR 2, but nothing *classified* a step — an operator watching
step time regress still had to eyeball a trace. This module derives the
classification from those same three measurements:

- ``feed_s``      time obtaining the step's feeds (next() on the feed
                  iterator: DataFeeder convert + H2D on the sync path,
                  the blocking staging-ring get on the pipelined path)
- ``dispatch_s``  host-side dispatch of the jitted step (python +
                  tracing; balloons on a recompile)
- ``sync_s``      host blocked reading back the loss. Under jax's
                  asynchronous dispatch this is where the DEVICE's
                  execution time surfaces — compute, but also any
                  cross-replica collective / straggler wait.

Because device work hides inside ``sync_s``, naming a sync-dominated
step requires a compute estimate: when the step's lowered-HLO FLOPs and
the declared peak (``observe/costs.py`` — the MFU machinery) are known,
``est_compute_s = flops / peak`` splits ``sync_s`` into modeled compute
and unexplained excess. A step whose sync wait far exceeds its modeled
compute is *sync_bound* (stragglers, collectives, backpressure); without
a cost model the excess is unknowable and sync-dominated steps report
*compute_bound* (documented in docs/howto_observability.md).

Classification is by dominant fraction:

- ``input_bound``    feeds dominate — speed up the input pipeline
                     (``SGD.train(prefetch=N)``, docs/howto_data.md)
- ``compute_bound``  dispatch + modeled device compute dominate — the
                     healthy state for a device-saturated step
- ``sync_bound``     sync wait UNEXPLAINED by modeled compute dominates

Pure functions, stdlib-only; the trainer's ``_StepMonitor`` feeds the
result into gauges, step records, and flight-recorder post-mortems.
"""

from typing import Dict, Optional, Tuple

COMPONENTS = ("input", "compute", "sync")


def attribute_step(feed_s: float, dispatch_s: float, sync_s: float,
                   est_compute_s: Optional[float] = None
                   ) -> Tuple[str, Dict[str, float]]:
    """Classify one step; returns ``(label, fractions)`` where
    ``fractions`` maps ``input`` / ``compute`` / ``sync`` to their
    share of the measured step time (they sum to 1, or all-zero for a
    zero-length step labelled ``unknown``)."""
    feed_s = max(float(feed_s), 0.0)
    dispatch_s = max(float(dispatch_s), 0.0)
    sync_s = max(float(sync_s), 0.0)
    total = feed_s + dispatch_s + sync_s
    if total <= 0.0:
        return "unknown", {c: 0.0 for c in COMPONENTS}
    if est_compute_s is None:
        compute_s = dispatch_s + sync_s
        sync_excess = 0.0
    else:
        modeled = min(sync_s, max(float(est_compute_s), 0.0))
        compute_s = dispatch_s + modeled
        sync_excess = sync_s - modeled
    fractions = {"input": feed_s / total, "compute": compute_s / total,
                 "sync": sync_excess / total}
    # ties break toward the earlier pipeline stage (input before
    # compute before sync): the earlier stage is the one a fix targets
    label = max(COMPONENTS, key=lambda c: (fractions[c],
                                           -COMPONENTS.index(c)))
    return f"{label}_bound", fractions
