"""Straggler attribution: joining per-rank step and barrier windows.

The reference built ``BarrierStat`` (paddle/utils/Stat.h) for exactly
this judgment, and ``distributed.barrier`` already records the per-rank
wait histogram it implies. The rule, stated there and implemented
here: in a synchronous gang every rank waits at the barrier for the
SLOWEST rank — so the rank whose barrier wait is consistently
near-zero while its peers wait IS the straggler (it arrives last; it
never waits). A big MEAN barrier wait across the gang is load
imbalance; a big SPREAD with one near-zero rank is one sick host.

:class:`StragglerDetector` consumes the per-rank raw windows the gang
supervisor scrapes out of worker heartbeats (``runtime/supervisor.py``
telemetry contract) and publishes two series the training alert rules
(``observe/alerts.py`` ``default_training_rules``) key off:

- ``gang_step_skew_seconds{q}`` — max-over-ranks minus min-over-ranks
  of the per-rank step-time quantile, per q. Computed per rank FIRST
  and spread SECOND: the skew of pooled quantiles would be zero by
  construction.
- ``gang_straggler_rank`` — the attributed rank, -1 while the gang is
  balanced. Attribution prefers the barrier rule; when no barrier
  data exists (CPU-sim gangs never block at a collective) it falls
  back to step-time dominance: the rank whose median step is
  ``margin``x the fastest rank's median.

Stdlib-only (the supervisor and CLI import observe without jax).
"""

import time
from typing import Dict, List, Optional, Sequence

from paddle_tpu.observe import metrics as _metrics

_QS = (("p50", 0.5), ("p95", 0.95), ("p99", 0.99))


def _quantile(vals: List[float], q: float) -> float:
    """The repo-wide nearest-rank convention (observe/window.py)."""
    if not vals:
        return 0.0
    s = sorted(vals)
    i = min(len(s) - 1, int(q * (len(s) - 1) + 0.5))
    return s[i]


def judge_gang(per_rank: Dict[str, Dict[str, Sequence[float]]], *,
               min_samples: int = 4, wait_floor_s: float = 0.02,
               margin: float = 2.0) -> dict:
    """One skew report from per-rank raw windows.

    ``per_rank`` maps rank -> {"step": [wall_s...], "barrier":
    [wait_s...]} (raw values, newest window). Returns::

        {"straggler_rank": int | None, "rule": "barrier" |
         "step_time" | None, "skew": {"p50": s, "p95": s, "p99": s},
         "per_rank": {rank: {"step_p50_s", "barrier_p50_s", "n_step",
                             "n_barrier"}}}

    Barrier rule: among ranks with >= ``min_samples`` barrier waits,
    the candidate is the rank with the smallest median wait; it is THE
    straggler when its median is under ``wait_floor_s`` while every
    peer's median is both over the floor and ``margin``x the
    candidate's (one rank always arriving last while the rest wait).
    Step fallback (no barrier data): the slowest rank's median step
    must be ``margin``x the fastest rank's — a gang that is merely
    noisy names nobody.
    """
    stats = {}
    for rank, wins in per_rank.items():
        step = [float(v) for v in (wins.get("step") or ())]
        barrier = [float(v) for v in (wins.get("barrier") or ())]
        stats[str(rank)] = {
            "step_p50_s": round(_quantile(step, 0.5), 6),
            "barrier_p50_s": round(_quantile(barrier, 0.5), 6),
            "n_step": len(step), "n_barrier": len(barrier),
            "_step": step, "_barrier": barrier}

    skew = {}
    ranked = [s for s in stats.values() if s["n_step"] >= min_samples]
    for lbl, q in _QS:
        if len(ranked) >= 2:
            qs = [_quantile(s["_step"], q) for s in ranked]
            skew[lbl] = round(max(qs) - min(qs), 6)
        else:
            skew[lbl] = 0.0

    straggler, rule = None, None
    with_barrier = {r: s for r, s in stats.items()
                    if s["n_barrier"] >= min_samples}
    if len(with_barrier) >= 2:
        cand = min(with_barrier, key=lambda r:
                   with_barrier[r]["barrier_p50_s"])
        cand_med = with_barrier[cand]["barrier_p50_s"]
        peers = [s["barrier_p50_s"] for r, s in with_barrier.items()
                 if r != cand]
        if (cand_med <= wait_floor_s
                and min(peers) >= wait_floor_s
                and min(peers) >= margin * max(cand_med, 1e-6)):
            straggler, rule = cand, "barrier"
    if straggler is None:
        with_step = {r: s for r, s in stats.items()
                     if s["n_step"] >= min_samples}
        if len(with_step) >= 2:
            cand = max(with_step, key=lambda r:
                       with_step[r]["step_p50_s"])
            meds = [s["step_p50_s"] for s in with_step.values()]
            if (min(meds) > 0
                    and with_step[cand]["step_p50_s"]
                    >= margin * min(meds)):
                straggler, rule = cand, "step_time"
    for s in stats.values():
        s.pop("_step"), s.pop("_barrier")
    return {"straggler_rank": (int(straggler)
                               if straggler is not None else None),
            "rule": rule, "skew": skew, "per_rank": stats}


class StragglerDetector:
    """Stateful wrapper publishing :func:`judge_gang` into a registry
    on the supervisor's scrape cadence. Keeps only the latest report —
    windows are the workers' state; the detector just joins them."""

    def __init__(self, registry: Optional[_metrics.Registry] = None, *,
                 min_samples: int = 4, wait_floor_s: float = 0.02,
                 margin: float = 2.0, clock=time.monotonic):
        reg = (registry if registry is not None
               else _metrics.default_registry())
        self.registry = reg
        self.min_samples = int(min_samples)
        self.wait_floor_s = float(wait_floor_s)
        self.margin = float(margin)
        self._clock = clock
        self.report: dict = {"straggler_rank": None, "rule": None,
                             "skew": {}, "per_rank": {}}
        self._m_skew = reg.gauge(
            "gang_step_skew_seconds",
            "per-rank step-time quantile spread: max over ranks minus "
            "min over ranks at quantile q (label q) — the step-skew "
            "alert's input")
        self._m_straggler = reg.gauge(
            "gang_straggler_rank",
            "rank attributed as the gang straggler by the BarrierStat "
            "rule (near-zero barrier wait while peers wait) or the "
            "step-time-dominance fallback; -1 while balanced")

    def update(self, per_rank: Dict[str, Dict[str, Sequence[float]]]
               ) -> dict:
        """Join one scrape's per-rank windows, refresh the gauges,
        return (and retain) the report."""
        rep = judge_gang(per_rank, min_samples=self.min_samples,
                         wait_floor_s=self.wait_floor_s,
                         margin=self.margin)
        for lbl, _ in _QS:
            self._m_skew.set(rep["skew"].get(lbl, 0.0), q=lbl)
        self._m_straggler.set(
            rep["straggler_rank"] if rep["straggler_rank"] is not None
            else -1)
        self.report = rep
        return rep
