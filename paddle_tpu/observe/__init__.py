"""paddle_tpu.observe — the unified observability layer.

The pieces:

- ``observe.metrics`` — Counter/Gauge/Histogram registry with a JSONL
  scalar sink and a Prometheus text renderer (stdlib-only).
- ``observe.trace`` — nested trace scopes over ``utils/stat.py`` that
  open ``jax.profiler`` annotations when profiling is enabled, and
  record spans into the Chrome-trace buffer.
- ``observe.chrome_trace`` — the bounded span buffer + ``trace_export``
  rendering chrome://tracing / Perfetto JSON.
- ``observe.costs`` — XLA cost-model FLOPs/bytes per step + MFU against
  the ``core/place.py`` peak-FLOPs table.
- ``observe.compile_tracker`` — jit cache-miss counting with the
  arg-shape signature behind each miss and a recompile-storm warning.
- ``observe.flight`` — flight recorder: last-K step ring + config/env
  snapshot dumped as a JSON post-mortem on NaN/crash.
- ``observe.health`` — stdlib HTTP ``/metrics`` + ``/healthz`` server
  attachable to the trainer, LMServer, and MasterServer.
- ``observe.fleet`` — router-side aggregator merging N replica metric
  registries into one labeled fleet registry (pooled-sample quantiles).
- ``observe.alerts`` — declarative alert rules with for-duration
  debounce over any registry, feeding ``/alerts`` and the trace ring.
- ``observe.report()`` — the one funnel the trainer (and anything else)
  pushes per-step records through: every record goes to the configured
  JSONL sink and to any registered handlers, while the existing
  event-handler path keeps working untouched.

Typical wiring::

    from paddle_tpu import observe
    observe.configure(jsonl_path="metrics.jsonl")   # or
    # PADDLE_TPU_METRICS_PATH=metrics.jsonl in the environment
    ...train...
    # then: paddle_tpu stats --metrics_file=metrics.jsonl
    #       paddle_tpu stats --trace trace.json   (Perfetto timeline)
"""

import os
import threading
from typing import Callable, List, Optional

from paddle_tpu.observe.alerts import (  # noqa: F401
    AlertEvaluator, AlertRule, default_fleet_rules,
    default_training_rules)
from paddle_tpu.observe.chrome_trace import (  # noqa: F401
    SpanBuffer, alignments, clear_alignments, default_buffer,
    merge_traces, note_alignment, record_event, record_span,
    set_trace_capacity, trace_enabled, trace_export)
from paddle_tpu.observe.fleet import (  # noqa: F401
    FleetAggregator, death_postmortem)
from paddle_tpu.observe.goodput import (  # noqa: F401
    GoodputLedger, StepAccountant)
from paddle_tpu.observe.straggler import (  # noqa: F401
    StragglerDetector, judge_gang)
from paddle_tpu.observe import bottleneck  # noqa: F401
from paddle_tpu.observe.bottleneck import attribute_step  # noqa: F401
from paddle_tpu.observe import costs  # noqa: F401 — observe.costs.*
from paddle_tpu.observe.compile_tracker import (  # noqa: F401
    CompileTracker, arg_signature, default_compile_tracker,
    track_compiles)
from paddle_tpu.observe.flight import (  # noqa: F401
    FlightRecorder, default_flight_recorder, flight_dir,
    install_excepthook)
from paddle_tpu.observe.health import HealthServer  # noqa: F401
from paddle_tpu.observe.metrics import (  # noqa: F401 — public surface
    Counter, Gauge, Histogram, JsonlSink, Registry, counter,
    default_registry, gauge, histogram, parse_prometheus, read_jsonl)
from paddle_tpu.observe import requests  # noqa: F401 — observe.requests.*
from paddle_tpu.observe.requests import (  # noqa: F401
    RequestLog, default_request_log)
from paddle_tpu.observe.trace import (  # noqa: F401
    current_scope, step_scope, trace_scope, traced)
from paddle_tpu.observe.window import (  # noqa: F401
    SloConfig, WindowedQuantiles)

_lock = threading.Lock()
_sink: Optional[JsonlSink] = None
_sink_source = None        # "configure" | "flag" | "env" — see sink_source()
_explicit_off = False      # configure(None) from user code: defaults (env
                           # var, metrics_path flag) must not resurrect one
_env_checked = False       # PADDLE_TPU_METRICS_PATH probed once
_handlers: List[Callable[[dict], None]] = []


def configure(jsonl_path: Optional[str] = None,
              flush_every: int = 32,
              _source: str = "configure") -> Optional[JsonlSink]:
    """Install (or with ``jsonl_path=None`` remove) the process-wide JSONL
    metrics sink that ``report()`` feeds. Returns the sink. ``_source``
    tags where the sink came from ("configure" | "flag" | "env") so
    precedence between them stays decidable — callers other than the
    framework itself should leave it alone."""
    global _sink, _sink_source, _env_checked, _explicit_off
    with _lock:
        if _sink is not None:
            _sink.close()
            _sink = None
        _sink_source = None
        # explicit configuration settles the question — configure(None)
        # from user code means "no sink", and neither the env var nor
        # the metrics_path flag may resurrect one
        _env_checked = True
        _explicit_off = jsonl_path is None and _source == "configure"
        if jsonl_path:
            _sink = JsonlSink(jsonl_path, flush_every=flush_every)
            _sink_source = _source
        return _sink


def _env_autoconfigure():
    """PADDLE_TPU_METRICS_PATH wires the sink without code changes (the
    env contract every other knob in utils/flags.py follows). Probed once
    per process (and again after reset()) — not on every hot-loop call."""
    global _sink, _sink_source, _env_checked
    if _env_checked:
        return
    path = os.environ.get("PADDLE_TPU_METRICS_PATH")
    with _lock:
        _env_checked = True
        if path and _sink is None:
            try:
                _sink = JsonlSink(path)
                _sink_source = "env"
            except OSError as e:
                # a bad env path must not kill the training loop — the
                # explicit configure() API still raises for real callers
                from paddle_tpu.utils.logger import get_logger
                get_logger("observe").warning(
                    "PADDLE_TPU_METRICS_PATH=%s unusable (%s); "
                    "metrics sink disabled", path, e)


def sink() -> Optional[JsonlSink]:
    if not _env_checked:
        _env_autoconfigure()
    return _sink


def sink_source() -> Optional[str]:
    """Where the active sink came from: "configure" (explicit code),
    "flag" (metrics_path flag via the trainer), or "env"
    (PADDLE_TPU_METRICS_PATH autoconfiguration); None without a sink.
    Lets callers honor explicit configuration over the defaults."""
    sink()                     # settle the env probe first
    return _sink_source


def explicitly_disabled() -> bool:
    """True after a user-code ``configure(None)``: the trainer's flag
    path must not resurrect the sink the user just turned off."""
    return _explicit_off


def has_consumers() -> bool:
    """True when report() would reach a sink or handler — hot loops use
    this to skip building record dicts nobody will read."""
    return sink() is not None or bool(_handlers)


def add_report_handler(fn: Callable[[dict], None]) -> None:
    """Register a callback invoked with every report() record — the
    programmatic tap (dashboards, tests) next to the JSONL file."""
    with _lock:
        _handlers.append(fn)


def remove_report_handler(fn: Callable[[dict], None]) -> None:
    with _lock:
        if fn in _handlers:
            _handlers.remove(fn)


def report(record: Optional[dict] = None, **scalars) -> dict:
    """Emit one observability record (a flat dict of scalars). Fans out
    to the JSONL sink (when configured) and all registered handlers.
    Never raises — a broken handler must not kill the training loop."""
    rec = dict(record or {})
    rec.update(scalars)
    s = sink()
    if s is not None:
        try:
            s.write(rec)
        except (OSError, ValueError, TypeError):
            pass       # incl. json.dumps on non-serializable values
    with _lock:
        handlers = list(_handlers)
    for fn in handlers:
        try:
            fn(rec)
        except Exception:  # noqa: BLE001 — observability is best-effort
            pass
    return rec


def reset():
    """Drop the sink and handlers, zero every default-registry series,
    and clear the span buffer / flight ring / compile tracker (test
    isolation). Registrations survive — module-level metric objects
    (trainer, master, distributed) must stay wired to the registry."""
    global _sink, _sink_source, _env_checked, _explicit_off
    with _lock:
        if _sink is not None:
            _sink.close()
        _sink = None
        _sink_source = None
        _env_checked = False
        _explicit_off = False
        _handlers.clear()
    default_registry().clear_series()
    default_buffer().clear()
    clear_alignments()
    default_flight_recorder().clear()
    default_compile_tracker().clear()
    default_request_log().clear()
