"""Health endpoints: a tiny stdlib HTTP server for /metrics + /healthz.

One ``HealthServer`` serves two GET routes:

- ``/metrics`` — the Prometheus text exposition of a registry (default:
  the process-wide default registry), scrape-ready;
- ``/healthz`` — a JSON liveness/progress document from a caller-
  provided ``health_fn()`` (step progress for a trainer, queue depths
  for a master, request counters for an LMServer). A ``"healthy":
  False`` key turns the response into HTTP 503 so load balancers and
  kubelets can act on it without parsing the body. Three-state status:
  the document's ``status`` may also be ``"degraded"`` (SLO burn-rate
  breach — still HTTP 200 with the reason in the body, so traffic
  keeps flowing while schedulers/operators react) — only
  ``unhealthy`` maps to 503.
- ``/requests`` — present when a ``requests_fn`` is supplied (the
  decode engines pass theirs): the top-k slowest requests with their
  attributed latency components (``observe/requests.py``), the
  tail-latency post-mortem a dashboard links to.
- ``/alerts`` — present when an ``alerts_fn`` is supplied (the fleet
  router passes its evaluator's ``doc``): per-rule state + the recent
  firing/resolved transition log (``observe/alerts.py``), the surface
  ``paddle_tpu top`` polls.

Attach points: ``SGD.attach_observability()``, ``LMServer.serve()``,
``MasterServer(http_port=...)`` — or construct one directly around any
registry. ``port=0`` binds an ephemeral port (tests); the server runs
on a daemon thread and must be ``close()``d for a clean shutdown.

Stdlib-only: serving observability must not add dependencies to the
serving path.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional


class HealthServer:
    def __init__(self, registry=None, health_fn: Optional[Callable[[],
                 dict]] = None, host: str = "127.0.0.1", port: int = 0,
                 requests_fn: Optional[Callable[[], dict]] = None,
                 metrics_fn: Optional[Callable[[], str]] = None,
                 alerts_fn: Optional[Callable[[], dict]] = None):
        if registry is None:
            from paddle_tpu.observe.metrics import default_registry
            registry = default_registry()
        self.registry = registry
        self.health_fn = health_fn
        self.requests_fn = requests_fn
        self.alerts_fn = alerts_fn
        # metrics_fn overrides the registry render for `/metrics` so an
        # owner can refresh derived gauges per scrape (the engines'
        # window quantiles expire with time and must not scrape stale)
        self.metrics_fn = metrics_fn
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):   # silence per-request spam
                pass

            def _send(self, code, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        text = (outer.metrics_fn() if outer.metrics_fn
                                else outer.registry.render_prometheus())
                        self._send(200, text.encode(),
                                   "text/plain; version=0.0.4")
                    elif path == "/healthz":
                        code, doc = outer._health()
                        self._send(code, json.dumps(doc).encode(),
                                   "application/json")
                    elif (path == "/requests"
                          and outer.requests_fn is not None):
                        from paddle_tpu.observe.metrics import JsonlSink
                        doc = JsonlSink._clean(outer.requests_fn() or {})
                        self._send(200, json.dumps(doc).encode(),
                                   "application/json")
                    elif (path == "/alerts"
                          and outer.alerts_fn is not None):
                        from paddle_tpu.observe.metrics import JsonlSink
                        doc = JsonlSink._clean(outer.alerts_fn() or {})
                        self._send(200, json.dumps(doc).encode(),
                                   "application/json")
                    else:
                        self._send(404, b'{"error": "not found"}\n',
                                   "application/json")
                except (ConnectionError, BrokenPipeError, OSError):
                    # scraper timed out / hung up mid-write: nothing to
                    # answer and nobody to answer it to — swallow, or a
                    # traceback hits the job's stderr per scrape timeout
                    pass
                except Exception as e:  # noqa: BLE001 — a broken probe
                    # must answer 500, not kill the handler thread
                    try:
                        self._send(500, json.dumps(
                            {"error": str(e)}).encode(),
                            "application/json")
                    except OSError:
                        pass       # the 500 reply can hit a dead socket too

        self._srv = ThreadingHTTPServer((host, port), _Handler)
        self._srv.daemon_threads = True
        self.addr = self._srv.server_address
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    def _health(self):
        from paddle_tpu.observe.metrics import JsonlSink
        doc = {}
        if self.health_fn is not None:
            doc = dict(self.health_fn() or {})
        healthy = bool(doc.pop("healthy", True))
        status = doc.get("status")
        if not healthy:
            status = "unhealthy"          # the bool always wins: a probe
            #                               saying healthy=False must 503
        elif status not in ("ok", "degraded", "unhealthy"):
            status = "ok"
        doc["status"] = status
        return (503 if status == "unhealthy" else 200), \
            JsonlSink._clean(doc)

    @property
    def port(self) -> int:
        return self.addr[1]

    @property
    def url(self) -> str:
        return f"http://{self.addr[0]}:{self.addr[1]}"

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()
