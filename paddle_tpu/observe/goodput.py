"""Goodput ledger: where did this elastic run's wall-clock go.

The Ascend field study (PAPERS.md) diagnoses accelerator deployments
through utilization/latency ATTRIBUTION, and the reference's Go master
kept per-task accounting — raw counters don't answer "what fraction of
this run trained". This module decomposes a supervised training run's
wall-clock, across coordination epochs, into buckets:

- ``useful_step``      — step execution at the run's steady median
  (dispatch + host sync, compile excess removed);
- ``input_stall``      — the feed wait (pipeline get / convert+H2D);
- ``recompile``        — step wall beyond the steady median on steps
  the compile tracker attributes to a jit cache miss;
- ``checkpoint_save``  — the synchronous part of async checkpoint
  saves (device->host snapshot + enqueue);
- ``restore``          — checkpoint load + reshard on (re)entry;
- ``startup``          — gang launch to the worker's accountant birth
  (process spawn, imports, backend init), supervisor-attributed;
- ``restart_gap``      — failure detection to the NEXT gang's launch
  (teardown, post-mortem, backoff), supervisor-attributed;
- ``other``            — in-worker wall the loop didn't classify
  (event handlers, logging, pass turnaround) so worker buckets sum to
  the worker's elapsed wall exactly.

Two halves:

:class:`StepAccountant` is the worker side — O(1) float adds in the
training loop, published to the supervisor inside the heartbeat
telemetry (``runtime/supervisor.py``). Its buckets are CUMULATIVE for
the incarnation, so the supervisor folds them idempotently (last write
per epoch wins).

:class:`GoodputLedger` is the supervisor side — per-epoch buckets
persisted to a CHECKSUMMED JSON file in ``state_dir`` next to the
flight posts, so the accounting survives both worker and supervisor
restarts (a torn or tampered file is detected and the ledger starts
fresh rather than reporting garbage). Exported as
``training_goodput_fraction`` + ``training_overhead_seconds_total
{bucket}`` and stamped into every restart post-mortem.

Stdlib-only (the supervisor and CLI import observe without jax).
"""

import hashlib
import json
import os
import threading
import time
from typing import Dict, Optional

from paddle_tpu.observe import metrics as _metrics

#: every bucket the ledger accounts; useful_step is the goodput
BUCKETS = ("useful_step", "input_stall", "recompile", "checkpoint_save",
           "restore", "startup", "restart_gap", "other")

#: the subset a worker accounts in-process (supervisor owns the rest)
WORKER_BUCKETS = ("useful_step", "input_stall", "recompile",
                  "checkpoint_save", "restore")


class StepAccountant:
    """In-trainer wall-clock bucketing for one worker incarnation.

    ``snapshot()`` closes the books up to now: ``other`` is elapsed
    wall minus every classified bucket (clamped at zero), so the
    worker's buckets always sum to its elapsed wall — the property the
    ledger's >=95%-accounted contract rides on.
    """

    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self._t0 = clock()
        self.t_start_wall = time.time()
        self._lock = threading.Lock()
        self._b = {b: 0.0 for b in WORKER_BUCKETS}

    def add(self, bucket: str, seconds: float):
        if bucket not in self._b:
            raise ValueError(f"unknown worker bucket {bucket!r} "
                             f"(one of {WORKER_BUCKETS})")
        with self._lock:
            self._b[bucket] += max(0.0, float(seconds))

    def step(self, dt: float, *, feed_s: float = 0.0,
             compile_miss: bool = False,
             median_s: Optional[float] = None):
        """Account one trained batch: ``dt`` is the step wall
        (dispatch + sync), ``feed_s`` the feed wait. On a jit cache
        miss the steady median (when known) stays useful and the
        excess is recompile — the first-ever step has no median yet,
        so its whole wall is compile, which is what it is."""
        with self._lock:
            self._b["input_stall"] += max(0.0, float(feed_s))
            dt = max(0.0, float(dt))
            if compile_miss:
                useful = min(dt, median_s) if median_s else 0.0
                self._b["useful_step"] += useful
                self._b["recompile"] += dt - useful
            else:
                self._b["useful_step"] += dt

    def elapsed(self) -> float:
        return max(0.0, self._clock() - self._t0)

    def snapshot(self) -> dict:
        """Cumulative buckets including the derived ``other``."""
        el = self.elapsed()
        with self._lock:
            b = dict(self._b)
        b["other"] = max(0.0, el - sum(b.values()))
        return {"buckets": {k: round(v, 6) for k, v in b.items()},
                "elapsed_s": round(el, 6),
                "t_start_wall": self.t_start_wall}


def _checksum(doc: dict) -> str:
    body = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(body.encode()).hexdigest()


class GoodputLedger:
    """Run-lifetime per-epoch bucket accounting, crash-persistent.

    File format (atomic-replace, like every state file here)::

        {"v": 1, "epochs": {"1": {bucket: seconds}},
         "meta": {...}, "checksum": sha256-of-the-rest}

    A load failure (missing/torn/bad checksum) starts a fresh ledger
    and remembers why in ``load_error`` — accounting is observability,
    never a reason to refuse a restart.
    """

    def __init__(self, path: Optional[str] = None, clock=time.time):
        self.path = path
        self._clock = clock
        self._lock = threading.Lock()
        self.epochs: Dict[int, Dict[str, float]] = {}
        self.meta: dict = {"run_started": clock()}
        self.load_error: Optional[str] = None
        # last exported totals per bucket: the delta base that keeps
        # the overhead counter monotone across export rounds
        self._exported: Dict[str, float] = {}
        if path and os.path.exists(path):
            self._load(path)

    def _load(self, path: str):
        try:
            with open(path) as f:
                doc = json.load(f)
            want = doc.pop("checksum", None)
            if want != _checksum(doc):
                raise ValueError("checksum mismatch")
            self.epochs = {int(e): {str(k): float(v)
                                    for k, v in b.items()}
                           for e, b in doc.get("epochs", {}).items()}
            self.meta = dict(doc.get("meta") or {})
            self.meta.setdefault("run_started", self._clock())
        except (OSError, ValueError, KeyError, TypeError) as e:
            self.load_error = f"{type(e).__name__}: {e}"
            self.epochs, self.meta = {}, {"run_started": self._clock()}

    # -- writes ------------------------------------------------------------
    def set_bucket(self, epoch: int, bucket: str, seconds: float):
        """Absolute (idempotent) write — the fold for cumulative
        worker buckets and for supervisor-owned one-shot spans."""
        if bucket not in BUCKETS:
            raise ValueError(f"unknown bucket {bucket!r}")
        with self._lock:
            self.epochs.setdefault(int(epoch), {})[bucket] = \
                max(0.0, float(seconds))

    def add(self, epoch: int, bucket: str, seconds: float):
        if bucket not in BUCKETS:
            raise ValueError(f"unknown bucket {bucket!r}")
        with self._lock:
            b = self.epochs.setdefault(int(epoch), {})
            b[bucket] = b.get(bucket, 0.0) + max(0.0, float(seconds))

    def fold_worker(self, epoch: int, buckets: Dict[str, float]):
        """Fold one worker's cumulative bucket snapshot into the
        epoch (absolute overwrite: the snapshot is cumulative for the
        incarnation, so the latest one supersedes every earlier one).
        Unknown keys are dropped — telemetry is a loose contract."""
        for k, v in (buckets or {}).items():
            if k in BUCKETS:
                try:
                    self.set_bucket(epoch, k, float(v))
                except (TypeError, ValueError):
                    continue

    # -- reads -------------------------------------------------------------
    def totals(self) -> Dict[str, float]:
        with self._lock:
            out = {b: 0.0 for b in BUCKETS}
            for buckets in self.epochs.values():
                for k, v in buckets.items():
                    out[k] = out.get(k, 0.0) + v
        return out

    def wall_accounted(self) -> float:
        return sum(self.totals().values())

    def goodput_fraction(self) -> float:
        """useful_step over everything accounted (0.0 on an empty
        ledger — no accounting is not perfect goodput)."""
        t = self.totals()
        wall = sum(t.values())
        return t["useful_step"] / wall if wall > 0 else 0.0

    def summary(self) -> dict:
        t = self.totals()
        return {"goodput_fraction": round(self.goodput_fraction(), 6),
                "wall_accounted_s": round(sum(t.values()), 3),
                "totals": {k: round(v, 3) for k, v in t.items()},
                "epochs": {str(e): {k: round(v, 3)
                                    for k, v in b.items()}
                           for e, b in sorted(self.epochs.items())},
                "load_error": self.load_error}

    # -- persistence -------------------------------------------------------
    def save(self, path: Optional[str] = None) -> Optional[str]:
        """Atomic checksummed write; never raises into the supervision
        loop (a full disk must not kill the run it measures)."""
        path = path or self.path
        if not path:
            return None
        with self._lock:
            doc = {"v": 1,
                   "epochs": {str(e): {k: round(v, 6)
                                       for k, v in b.items()}
                              for e, b in self.epochs.items()},
                   "meta": dict(self.meta)}
        doc["checksum"] = _checksum(doc)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        except OSError:
            return None
        return path

    # -- registry export ---------------------------------------------------
    def export(self, registry: Optional[_metrics.Registry] = None):
        """Refresh the ledger's registry series: the goodput-fraction
        gauge, the per-bucket overhead counter (delta-inc'd so scrape
        deltas stay meaningful), and the input-stall fraction the
        input-bound alert rule keys off."""
        reg = (registry if registry is not None
               else _metrics.default_registry())
        g = reg.gauge("training_goodput_fraction",
                      "useful-step seconds over all accounted "
                      "wall-clock, run lifetime (goodput ledger)")
        c = reg.counter("training_overhead_seconds_total",
                        "non-useful wall-clock by bucket (label "
                        "bucket; goodput ledger)")
        stall = reg.gauge("training_input_stall_fraction",
                          "input_stall seconds over all accounted "
                          "wall-clock — the input-bound alert's input")
        acc = reg.gauge("training_wall_seconds_accounted",
                        "total wall-clock the goodput ledger has "
                        "attributed to a bucket")
        t = self.totals()
        wall = sum(t.values())
        g.set(round(t["useful_step"] / wall, 6) if wall > 0 else 0.0)
        stall.set(round(t["input_stall"] / wall, 6) if wall > 0
                  else 0.0)
        acc.set(round(wall, 3))
        for b in BUCKETS:
            if b == "useful_step":
                continue
            delta = t[b] - self._exported.get(b, 0.0)
            if delta > 0:
                c.inc(delta, bucket=b)
                self._exported[b] = t[b]
