"""Declarative alert rules over a metrics registry.

The fleet aggregator (``observe/fleet.py``) answers "what is the
fleet's state"; this module answers "is that state WRONG" — the
machine-readable signal surface a self-healing autoscaler (ROADMAP
item 2: spawn/drain replicas from queue-depth + burn-rate signals)
keys off, and the firing-alert panel ``paddle_tpu top`` renders.

An :class:`AlertRule` is one threshold over one registry series::

    AlertRule("fleet_dead_replicas", metric="fleet_replicas",
              labels={"state": "dead"}, op=">=", threshold=1,
              for_s=0.0, description="a replica transport died")

``for_s`` is the for-duration debounce (Prometheus semantics): the
condition must hold CONTINUOUSLY that long before the rule fires —
``pending`` in between — so a one-poll queue spike never pages.
Four states per rule: ``inactive`` → ``pending`` (condition true,
clock running) → ``firing`` (held for ``for_s``) → back to
``inactive`` (emitting ``resolved``). Transitions emit:

- a nestable-async trace slice (cat ``alert``, id ``alert.<rule>``):
  ``b`` at firing, ``e`` at resolved — the alert's lifetime renders as
  one span NEXT TO the request timelines that caused it;
- the ``alerts_transitions_total{rule, event}`` counter and the
  ``alert_firing{rule}`` 0/1 gauge;
- a record into the evaluator's bounded event log, served by the
  router's ``/alerts`` endpoint.

A rule whose metric (or labeled series) does not exist yet evaluates
as NOT breached — absence of traffic is not an incident.

Stdlib-only (the CLI and bench orchestrator import observe).
"""

import dataclasses
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

from paddle_tpu.observe import chrome_trace as _chrome
from paddle_tpu.observe import metrics as _metrics

_OPS = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
}


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One declarative threshold: ``<metric>{labels} <op> <threshold>``
    held for ``for_s`` seconds fires the alert named ``name``.

    ``min_samples`` guards ratio/quantile rules against cold starts: a
    second gated metric (``samples_metric``, same label semantics) must
    be at least ``min_samples`` for the rule to evaluate at all — a
    prefix-hit-rate of 0.0 over zero placements is not a breach.
    """

    name: str
    metric: str
    op: str
    threshold: float
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    for_s: float = 0.0
    description: str = ""
    samples_metric: Optional[str] = None
    min_samples: float = 1.0

    def __post_init__(self):
        if self.op not in _OPS:
            raise ValueError(f"alert rule {self.name!r}: op must be one "
                             f"of {sorted(_OPS)}, got {self.op!r}")
        if self.for_s < 0:
            raise ValueError(f"alert rule {self.name!r}: for_s must be "
                             f">= 0, got {self.for_s}")


class _RuleState:
    __slots__ = ("state", "pending_t", "fired_t", "value")

    def __init__(self):
        self.state = "inactive"     # inactive | pending | firing
        self.pending_t: Optional[float] = None
        self.fired_t: Optional[float] = None
        self.value = 0.0


class AlertEvaluator:
    """Evaluate a rule set against one registry on the caller's
    cadence (the router does it per health-poll round). ``buffer``
    receives the firing/resolved trace events (default: the process
    span buffer, so ``stats --trace`` shows alert spans next to the
    requests that caused them)."""

    def __init__(self, registry: _metrics.Registry,
                 rules: Sequence[AlertRule], *,
                 counter_registry: Optional[_metrics.Registry] = None,
                 clock=time.monotonic, max_events: int = 256):
        self.registry = registry
        self.rules = list(rules)
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate alert rule names: {names}")
        self._clock = clock
        self._states = {r.name: _RuleState() for r in self.rules}
        self.events: deque = deque(maxlen=max(1, int(max_events)))
        # alert spans carry wall-clock timestamps like every other
        # trace event (monotonic clocks don't merge across processes)
        self._wall_anchor = time.time() - time.perf_counter()
        reg = counter_registry if counter_registry is not None \
            else registry
        self._m_transitions = reg.counter(
            "alerts_transitions_total", "alert state transitions, by "
            "rule and event (firing | resolved)")
        self._m_firing = reg.gauge(
            "alert_firing", "1 while the rule is firing, else 0")
        for r in self.rules:
            self._m_firing.set(0, rule=r.name)

    # -- evaluation --------------------------------------------------------
    def _value(self, rule: AlertRule) -> Optional[float]:
        m = self.registry.get(rule.metric)
        if m is None or m.kind == "histogram":
            return None
        cell = m._peek(rule.labels)
        if cell is None:
            return None
        return float(cell.value)

    def _enough_samples(self, rule: AlertRule) -> bool:
        if rule.samples_metric is None:
            return True
        m = self.registry.get(rule.samples_metric)
        if m is None or m.kind == "histogram":
            return False
        total = sum(c.value for c in m.series().values())
        return total >= rule.min_samples

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """One evaluation round; returns the transition events it
        emitted (firing/resolved records, also kept in ``events``)."""
        now = self._clock() if now is None else float(now)
        out: List[dict] = []
        for rule in self.rules:
            st = self._states[rule.name]
            value = self._value(rule)
            breached = (value is not None
                        and self._enough_samples(rule)
                        and _OPS[rule.op](value, rule.threshold))
            st.value = value if value is not None else 0.0
            if breached:
                if st.state == "inactive":
                    st.state, st.pending_t = "pending", now
                if (st.state == "pending"
                        and now - st.pending_t >= rule.for_s):
                    st.state, st.fired_t = "firing", now
                    out.append(self._transition(rule, st, "firing", now))
            else:
                if st.state == "firing":
                    out.append(self._transition(rule, st, "resolved",
                                                now))
                st.state, st.pending_t, st.fired_t = \
                    "inactive", None, None
        return out

    def _transition(self, rule: AlertRule, st: _RuleState,
                    event: str, now: float) -> dict:
        self._m_transitions.inc(rule=rule.name, event=event)
        self._m_firing.set(1 if event == "firing" else 0,
                           rule=rule.name)
        wall = self._wall_anchor + time.perf_counter()
        _chrome.record_event(
            f"alert:{rule.name}", wall,
            "b" if event == "firing" else "e",
            f"alert.{rule.name}", cat="alert",
            args={"event": event, "value": round(st.value, 6),
                  "threshold": rule.threshold, "op": rule.op})
        rec = {"rule": rule.name, "event": event,
               "value": round(st.value, 6),
               "metric": rule.metric, "labels": dict(rule.labels),
               "op": rule.op, "threshold": rule.threshold,
               "for_s": rule.for_s,
               "description": rule.description,
               "ts": round(time.time(), 3)}
        self.events.append(rec)
        return rec

    # -- read side ---------------------------------------------------------
    def firing(self) -> List[dict]:
        """The rules currently firing, with their live values."""
        out = []
        for rule in self.rules:
            st = self._states[rule.name]
            if st.state == "firing":
                out.append({"rule": rule.name,
                            "value": round(st.value, 6),
                            "op": rule.op,
                            "threshold": rule.threshold,
                            "description": rule.description})
        return out

    def doc(self) -> dict:
        """The ``/alerts`` endpoint document: per-rule state + the
        recent transition log."""
        return {
            "rules": [{
                "rule": r.name, "metric": r.metric,
                "labels": dict(r.labels), "op": r.op,
                "threshold": r.threshold, "for_s": r.for_s,
                "state": self._states[r.name].state,
                "value": round(self._states[r.name].value, 6),
                "description": r.description,
            } for r in self.rules],
            "firing": self.firing(),
            "events": list(self.events),
        }


def default_fleet_rules(*, burn_threshold: float = 1.0,
                        queue_depth: float = 32,
                        dead_replicas: float = 1,
                        prefix_hit_rate: float = 0.2,
                        min_placements: float = 20,
                        for_s: float = 0.0) -> List[AlertRule]:
    """The stock rule set over the router + fleet registry — the four
    signals ROADMAP item 2's admission-control/autoscaler steers on.
    Thresholds are constructor knobs; ``for_s`` applies to the rate
    rules (the dead-replica rule always fires immediately: a lost
    transport is not noise)."""
    return [
        AlertRule("fleet_ttft_burn_rate",
                  metric="router_slo_burn_rate", op=">",
                  threshold=burn_threshold, for_s=for_s,
                  description="fleet TTFT SLO error budget burning "
                  "faster than it accrues"),
        AlertRule("fleet_queue_depth",
                  metric="router_queue_depth", op=">",
                  threshold=queue_depth, for_s=for_s,
                  description="requests backing up unplaced — the "
                  "scale-up signal"),
        AlertRule("fleet_dead_replicas",
                  metric="fleet_replicas", labels={"state": "dead"},
                  op=">=", threshold=dead_replicas, for_s=0.0,
                  description="a replica transport died (its work was "
                  "requeued onto survivors)"),
        AlertRule("fleet_prefix_hit_rate",
                  metric="router_placement_hit_rate", op="<",
                  threshold=prefix_hit_rate, for_s=for_s,
                  samples_metric="router_placements_total",
                  min_samples=min_placements,
                  description="placements mostly landing cold — "
                  "placement keying drifted or the hot set churned"),
    ]


def default_training_rules(*, skew_s: float = 1.0,
                           wedge_s: float = 30.0,
                           restarts_10m: float = 3,
                           input_fraction: float = 0.25,
                           min_scrapes: float = 3,
                           for_s: float = 0.0) -> List[AlertRule]:
    """The stock rule set over the gang supervisor's registry — the
    training-side mirror of :func:`default_fleet_rules`, keyed off the
    series the supervisor's scrape loop maintains (`runtime/
    supervisor.py`): straggler skew, per-rank step recency, the
    restart-rate window, and the goodput ledger's input-stall split.

    ``wedge_s`` should sit WELL UNDER the supervisor's hard
    ``wedge_window`` — this alert is the early warning that pages a
    human before the supervisor's judge kills the gang."""
    return [
        AlertRule("gang_step_skew",
                  metric="gang_step_skew_seconds", labels={"q": "p50"},
                  op=">", threshold=skew_s, for_s=for_s,
                  description="median step wall diverging across ranks "
                  "— one host is consistently slower (see "
                  "gang_straggler_rank for the attribution)"),
        AlertRule("gang_wedge_suspect",
                  metric="gang_max_seconds_since_step", op=">",
                  threshold=wedge_s, for_s=for_s,
                  description="a rank is heartbeating but has not "
                  "advanced its step — wedged collective or stuck "
                  "input, ahead of the supervisor's hard wedge kill"),
        AlertRule("training_restart_storm",
                  metric="training_restarts_last_10m", op=">=",
                  threshold=restarts_10m, for_s=0.0,
                  description="gang restarting repeatedly — crash "
                  "looping instead of recovering (a storm is never "
                  "noise: no for_s debounce)"),
        AlertRule("training_input_bound",
                  metric="training_input_stall_fraction", op=">",
                  threshold=input_fraction, for_s=for_s,
                  samples_metric="gang_scrapes_total",
                  min_samples=min_scrapes,
                  description="the input pipeline, not the accelerator, "
                  "is pacing training (goodput ledger input_stall "
                  "share of accounted wall-clock)"),
    ]
