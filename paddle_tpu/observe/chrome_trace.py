"""Chrome-trace export: trace scopes recorded into a bounded buffer.

``observe.trace_scope`` / ``step_scope`` already accumulate wall time
into StatSet timers; this module additionally records each closed scope
as a *span* — (qualified name, wall-clock start, duration, thread) —
into a bounded in-memory ring buffer, and renders the buffer as
``chrome://tracing`` / Perfetto JSON (the Trace Event Format, "X"
complete events).

Multi-host: the event ``pid`` is the distributed process index
(PADDLE_PROCESS_ID from the launcher, or ``jax.process_index()`` when a
backend is already up), so traces exported by every host of a
``distributed`` run concatenate into one timeline that Perfetto groups
per process. Timestamps are wall-clock epoch microseconds for the same
reason — hosts share a clock to NTP precision, which is enough to line
up multi-second training steps.

Stdlib-only and jax-free at import time (the bench orchestrator and the
CLI both import ``observe``).
"""

import collections
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional

def _env_capacity(default: int = 16384) -> int:
    """Spans kept in the ring buffer; ~100 bytes each. 0 disables
    recording. A malformed env value falls back to the default — it
    must not kill every entry point that imports observe (same guard
    as PADDLE_TPU_PEAK_TFLOPS)."""
    try:
        return int(os.environ.get("PADDLE_TPU_TRACE_BUFFER", default))
    except ValueError:
        return default


DEFAULT_CAPACITY = _env_capacity()


class SpanBuffer:
    """Thread-safe bounded ring of closed spans (oldest evicted first)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._capacity = max(0, int(capacity))
        self._spans = collections.deque(maxlen=self._capacity or 1)
        self._dropped = 0

    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def enabled(self) -> bool:
        return self._capacity > 0

    def add(self, name: str, ts_s: float, dur_s: float,
            tid: Optional[int] = None, args: Optional[dict] = None,
            ph: str = "X", ev_id: Optional[str] = None,
            cat: Optional[str] = None):
        """Record one closed span (``ph="X"``, the default) or one
        async/instant lifecycle event (``ph`` in ``b``/``n``/``e`` with
        an ``ev_id`` joining the events of one logical flow — a serving
        request's timeline)."""
        if not self._capacity:
            return
        if tid is None:
            tid = threading.get_ident()
        with self._lock:
            if len(self._spans) == self._capacity:
                self._dropped += 1
            self._spans.append((name, ts_s, dur_s, tid, args, ph,
                                ev_id, cat))

    def spans(self) -> List[tuple]:
        with self._lock:
            return list(self._spans)

    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def clear(self):
        with self._lock:
            self._spans.clear()
            self._dropped = 0

    def __len__(self):
        with self._lock:
            return len(self._spans)


_default = SpanBuffer()

# wall-clock alignment marks for offline multi-rank merge: name -> the
# wall-clock second at which this process exited a gang-wide rendezvous
# (first exit per name wins — every rank leaves a barrier at the same
# true instant, so the pairwise difference of the stamps IS the clock
# skew between the ranks)
_alignments: Dict[str, float] = {}
_align_lock = threading.Lock()


def note_alignment(key: str, wall_s: Optional[float] = None):
    """Record a wall-clock instant known to be simultaneous across the
    gang (a barrier exit). Only the FIRST stamp per key is kept."""
    if wall_s is None:
        wall_s = time.time()
    with _align_lock:
        _alignments.setdefault(str(key), float(wall_s))


def alignments() -> Dict[str, float]:
    with _align_lock:
        return dict(_alignments)


def clear_alignments():
    with _align_lock:
        _alignments.clear()


def default_buffer() -> SpanBuffer:
    return _default


def set_trace_capacity(capacity: int) -> SpanBuffer:
    """Resize (or with 0 disable) the default span buffer. Existing
    spans are dropped — call before the run, not mid-trace."""
    global _default
    _default = SpanBuffer(capacity)
    return _default


def record_span(name: str, ts_s: float, dur_s: float,
                args: Optional[dict] = None):
    """Append one closed span to the default buffer (no-op when trace
    recording is disabled). ``ts_s`` is wall-clock epoch seconds."""
    _default.add(name, ts_s, dur_s, args=args)


def record_event(name: str, ts_s: float, ph: str, ev_id: str,
                 cat: str = "request", args: Optional[dict] = None):
    """Append one async lifecycle event to the default buffer. Phases
    follow the Trace Event Format's nestable-async family: ``b`` opens
    a slice, ``e`` closes the most recent open slice, ``n`` is an
    instant marker — all joined per ``(cat, ev_id)``, so Perfetto
    renders the events of one request as one track next to the engine's
    step spans. No-op when trace recording is disabled."""
    if ph not in ("b", "n", "e"):
        raise ValueError(f"record_event: ph must be b/n/e, got {ph!r}")
    _default.add(name, ts_s, 0.0, args=args, ph=ph, ev_id=str(ev_id),
                 cat=cat)


def trace_enabled() -> bool:
    return _default.enabled


def _process_index() -> int:
    """Distributed process index without forcing a jax backend init:
    the launcher env contract first, then jax only if already imported
    (export runs after training, when the backend is long up)."""
    env = os.environ.get("PADDLE_PROCESS_ID")
    if env is not None:
        try:
            return int(env)
        except ValueError:
            pass
    if "jax" in sys.modules:
        try:
            return sys.modules["jax"].process_index()
        except Exception:  # noqa: BLE001 — observability is best-effort
            pass
    return 0


def trace_export(path: Optional[str] = None,
                 buffer: Optional[SpanBuffer] = None,
                 process_index: Optional[int] = None,
                 align: Optional[Dict[str, float]] = None) -> dict:
    """Render the span buffer as a Chrome Trace Event Format object
    (open in chrome://tracing or https://ui.perfetto.dev). Writes JSON
    to ``path`` when given; always returns the trace dict.

    ``process_index`` overrides the pid (tests / offline merge tools);
    by default it comes from the distributed process index so per-host
    exports merge cleanly. The export stamps ``otherData`` with that
    pid plus the process's :func:`alignments` marks (override with
    ``align``), so :func:`merge_traces` can join N per-rank exports on
    a shared clock even when the hosts' wall clocks drift.
    """
    buffer = buffer or _default
    pid = _process_index() if process_index is None else int(process_index)
    # stable small tids per thread ident, in first-seen order
    tid_map: Dict[int, int] = {}
    events = [{"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
               "args": {"name": f"paddle_tpu p{pid}"}}]
    for span in buffer.spans():
        # pre-PR-7 5-tuples may survive in caller-held buffers; treat
        # the missing fields as a plain "X" span
        name, ts_s, dur_s, ident, args = span[:5]
        ph, ev_id, cat = (span[5:8] if len(span) >= 8
                          else ("X", None, None))
        tid = tid_map.setdefault(ident, len(tid_map))
        ev = {"name": name, "cat": cat or "paddle_tpu", "ph": ph,
              "ts": round(ts_s * 1e6, 3), "pid": pid, "tid": tid}
        if ph == "X":
            ev["dur"] = round(dur_s * 1e6, 3)
        else:
            # nestable-async events join on (cat, id); the engine bakes
            # its engine-instance id into ev_id so exports never collide
            ev["id"] = ev_id
        if args:
            ev["args"] = args
        events.append(ev)
    for ident, tid in tid_map.items():
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid, "args": {"name": f"thread-{tid}"}})
    trace = {"traceEvents": events, "displayTimeUnit": "ms",
             "otherData": {"dropped_spans": buffer.dropped(),
                           "process_index": pid,
                           "alignments": (dict(align) if align
                                          is not None
                                          else alignments())}}
    if path:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(trace, f)
    return trace


def merge_traces(traces: List[dict],
                 path: Optional[str] = None) -> dict:
    """Join N per-rank trace exports into one aligned gang timeline.

    The first trace is the clock reference. Every other trace is
    shifted by the mean, over alignment keys both sides stamped, of
    ``ref_mark - own_mark`` — each mark names the SAME true instant (a
    barrier exit), so the difference is that rank's wall-clock offset
    from the reference. Traces sharing no alignment key merge unshifted
    (NTP-level agreement, the pre-merge status quo). Colliding pids are
    remapped so two exports that both claim pid 0 (single-process test
    runs) still render as distinct process tracks.
    """
    merged: List[dict] = []
    offsets: Dict[str, float] = {}
    used_pids: Dict[int, int] = {}
    ref_align: Dict[str, float] = {}
    dropped = 0
    for i, tr in enumerate(traces):
        other = tr.get("otherData") or {}
        al = {str(k): float(v)
              for k, v in (other.get("alignments") or {}).items()}
        if i == 0:
            ref_align = al
            off = 0.0
        else:
            shared = sorted(set(ref_align) & set(al))
            off = (sum(ref_align[k] - al[k] for k in shared)
                   / len(shared)) if shared else 0.0
        src_pid = other.get("process_index")
        dropped += int(other.get("dropped_spans") or 0)
        pid_map: Dict[int, int] = {}
        for ev in tr.get("traceEvents", ()):
            ev = dict(ev)
            old = int(ev.get("pid", 0))
            if old not in pid_map:
                new = old
                while new in used_pids:
                    new += 1000
                used_pids[new] = i
                pid_map[old] = new
            ev["pid"] = pid_map[old]
            if "ts" in ev:
                ev["ts"] = round(ev["ts"] + off * 1e6, 3)
            merged.append(ev)
        key = f"p{src_pid if src_pid is not None else i}#{i}"
        offsets[key] = round(off, 6)
    trace = {"traceEvents": merged, "displayTimeUnit": "ms",
             "otherData": {"merged_from": len(traces),
                           "offsets_s": offsets,
                           "dropped_spans": dropped}}
    if path:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(trace, f)
    return trace
