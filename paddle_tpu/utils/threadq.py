"""Stop-aware queue plumbing shared by the producer-thread machinery
(paddle_tpu/pipeline/ stage threads, reader/decorator.py worker
threads). Stdlib-only — reader decorators must stay importable without
jax."""

import queue
import time
from typing import List, Sequence


def put_stoppable(q: "queue.Queue", item, stop) -> bool:
    """Backpressured put that stays interruptible: a producer blocked on
    a full queue must notice the consumer's stop event instead of
    hanging. The check comes BEFORE the put — consumers drain the queue
    to wake blocked producers, which keeps the puts succeeding and
    would leave a Full-only check unreached. Returns False on abort."""
    while True:
        if stop.is_set():
            return False
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            pass


def drain_join(queues: Sequence["queue.Queue"], threads, stop,
               deadline_s: float = 10.0) -> List:
    """Shut down producer threads: signal stop, then keep draining the
    queues (so any blocked put wakes and sees the event) until every
    thread exits or ``deadline_s`` passes. Returns the threads still
    alive at the deadline — a producer stuck inside user code (a socket
    read in a reader fn) cannot be joined; the caller decides whether
    that is a warning (generator close) or an error (pipeline close)."""
    stop.set()
    deadline = time.time() + deadline_s
    alive = [t for t in threads if t.is_alive()]
    while alive and time.time() < deadline:
        for q in queues:
            try:
                q.get_nowait()
            except queue.Empty:
                pass
        for t in alive:
            t.join(timeout=0.05)
        alive = [t for t in alive if t.is_alive()]
    return alive
