"""Deterministic RNG key management.

Reference: parameter init randomisation was per-Parameter seeded RNG
(paddle/parameter/Parameter.cpp randomize paths; ThreadLocalRand). TPU-native
replacement: a fold-in key tree — one root ``jax.random.key`` split by
parameter name / purpose, so initialisation is reproducible and order-free.
"""

import time
import zlib

import jax


class KeySource:
    """Derives named subkeys from a root seed via fold_in on a stable hash."""

    def __init__(self, seed: int = None):
        if seed is None or seed == 0:
            from paddle_tpu.utils.flags import GLOBAL_FLAGS
            seed = GLOBAL_FLAGS.get("seed", 0)
            if seed == 0:
                seed = int(time.time()) & 0x7FFFFFFF
        self.seed = int(seed)
        self._root = jax.random.key(self.seed)

    def named(self, name: str) -> jax.Array:
        """Stable per-name key: fold_in(root, crc32(name))."""
        return jax.random.fold_in(self._root, zlib.crc32(name.encode()) & 0x7FFFFFFF)

    def step(self, name: str, step: int) -> jax.Array:
        """Per-name, per-step key (dropout etc.)."""
        return jax.random.fold_in(self.named(name), step)


_GLOBAL = None


def global_key_source() -> KeySource:
    global _GLOBAL
    if _GLOBAL is None:
        _GLOBAL = KeySource()
    return _GLOBAL


def reset_global_seed(seed: int):
    global _GLOBAL
    _GLOBAL = KeySource(seed)
    return _GLOBAL
