"""HBM budgeting (reference: paddle/memory/ BuddyAllocator — the slot the
XLA runtime mostly absorbs: allocation itself belongs to XLA/PJRT, but the
*budgeting* decisions the reference made with its pool — "will this fit,
what batch size should I run" — live here).

Tools:
- ``device_memory_stats()`` — per-device HBM capacity/usage
- ``step_memory(fn, *args)`` — compiled peak/argument/temp bytes for a step
- ``max_batch_size(build_step, ...)`` — largest batch whose compiled peak
  fits the budget, found by geometric probe + bisection WITHOUT executing
  (AOT lowering only; the reference's equivalent was trial-and-OOM)
"""

import os
from typing import Callable, Dict, Optional

import jax

from paddle_tpu.utils.logger import get_logger

log = get_logger("memory")


def device_memory_stats(device=None) -> Dict[str, int]:
    """bytes_limit/bytes_in_use etc. for a device (empty dict when the
    backend does not expose memory stats, e.g. CPU)."""
    device = device or jax.devices()[0]
    stats = getattr(device, "memory_stats", lambda: None)()
    return dict(stats) if stats else {}


def host_memory_stats() -> Dict[str, int]:
    """Host-process memory: {rss_bytes, peak_rss_bytes} (best-effort;
    empty dict on platforms without /proc or resource). Feeds the
    trainer's host-memory gauge next to the device HBM gauge."""
    out: Dict[str, int] = {}
    try:
        import resource
        import sys
        ru = resource.getrusage(resource.RUSAGE_SELF)
        # linux reports KiB, macOS bytes
        scale = 1 if sys.platform == "darwin" else 1024
        out["peak_rss_bytes"] = int(ru.ru_maxrss) * scale
    except (ImportError, ValueError):
        pass
    try:
        with open("/proc/self/statm") as f:
            rss_pages = int(f.read().split()[1])
        out["rss_bytes"] = rss_pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        pass
    return out


def step_memory(fn: Callable, *args, static_argnums=()) -> Dict[str, int]:
    """Compile ``fn`` ahead-of-time and report its memory footprint:
    {peak, arguments, outputs, temps} in bytes. Nothing executes."""
    compiled = jax.jit(fn, static_argnums=static_argnums).lower(
        *args).compile()
    ma = compiled.memory_analysis()
    # older jaxlib lacks peak_memory_in_bytes; args+outputs+temps is the
    # upper bound the budgeting decisions need (aliasing makes it safe)
    peak = getattr(ma, "peak_memory_in_bytes", None)
    if peak is None:
        peak = (ma.argument_size_in_bytes + ma.output_size_in_bytes
                + ma.temp_size_in_bytes)
    return {
        "peak": int(peak),
        "arguments": int(ma.argument_size_in_bytes),
        "outputs": int(ma.output_size_in_bytes),
        "temps": int(ma.temp_size_in_bytes),
        "aliased": int(ma.alias_size_in_bytes),
    }


def max_batch_size(build_step: Callable[[int], tuple], *,
                   budget_bytes: Optional[int] = None,
                   headroom: float = 0.92, start: int = 8,
                   limit: int = 4096) -> int:
    """Largest power-of-two-probed batch size whose compiled step fits.

    ``build_step(batch) -> (fn, example_args)`` builds the step for a batch
    size (shapes only — jax.eval_shape-compatible abstract args are fine).
    ``budget_bytes`` defaults to the device's bytes_limit * headroom (falls
    back to 16 GiB when the backend hides its stats). Probes geometrically
    then bisects; compile-only, no step executes (the reference's
    BuddyAllocator learned this by OOM-ing at runtime)."""
    if budget_bytes is None:
        stats = device_memory_stats()
        cap = stats.get("bytes_limit") or (16 << 30)
        budget_bytes = int(cap * headroom)

    _cache: Dict[int, bool] = {}

    def fits(b):
        if b in _cache:
            return _cache[b]
        try:
            fn, args = build_step(b)
            peak = step_memory(fn, *args)["peak"]
            log.info("batch %d: peak %.2f GiB (budget %.2f GiB)", b,
                     peak / 2**30, budget_bytes / 2**30)
            ok = peak <= budget_bytes
        except Exception as e:  # noqa: BLE001 — compile failure = no fit
            log.info("batch %d failed to compile: %s", b, e)
            ok = False
        _cache[b] = ok
        return ok

    start = min(start, limit)
    if not fits(start):
        return 0
    lo = start
    while lo * 2 <= limit and fits(lo * 2):
        lo *= 2
    hi = min(lo * 2, limit)
    # bisect (lo fits, hi doesn't — unless hi==limit and fits)
    if hi == limit and hi != lo and fits(hi):
        return hi
    while hi - lo > max(1, lo // 8):      # ~12% resolution is plenty
        mid = (lo + hi) // 2
        if fits(mid):
            lo = mid
        else:
            hi = mid
    return lo
