"""Device-synchronisation helper for timing and profiling loops.

On the tunneled (axon) TPU platform ``jax.block_until_ready`` has been
observed returning before the dispatch chain actually finished, which
silently corrupts any wall-clock measurement taken after it. The reliable
barrier is a HOST-READ of a value data-dependent on the last computation:
transferring a reduction of an updated array cannot be faked. Every
measurement loop (bench.py, cli.measure_time, benchmarks/*) shares this
helper so the workaround lives in one place.
"""

import jax
import jax.numpy as jnp


def host_sync(tree, *scalars) -> float:
    """Block until ``tree``'s first leaf (and any extra device scalars)
    are computed, by reading reductions back to the host. Returns the
    float of the last scalar (or the leaf reduction if none given) so
    call sites can use the value they already forced."""
    leaves = jax.tree_util.tree_leaves(tree)
    out = 0.0
    if leaves:
        out = float(jnp.sum(leaves[0].astype(jnp.float32)))
    for s in scalars:
        out = float(s)
    return out
