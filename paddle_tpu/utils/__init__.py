"""Foundation utilities (reference: paddle/utils/ — Flags.cpp, Logging.cpp,
Stat.h, Error.h, CustomStackTrace.h)."""

from paddle_tpu.utils import flags
from paddle_tpu.utils import logger
from paddle_tpu.utils import stat
from paddle_tpu.utils import enforce
from paddle_tpu.utils import rng

from paddle_tpu.utils.enforce import enforce as check, EnforceError
from paddle_tpu.utils.stat import timer_scope, global_stats
