"""Named accumulating timers (reference: paddle/utils/Stat.h — Stat/StatSet,
REGISTER_TIMER_INFO, printed periodically and at exit).

On TPU the analog also opens a ``jax.profiler`` named trace scope when
profiling is enabled, so hot-loop scopes show up in xprof.
"""

import contextlib
import threading
import time
from dataclasses import dataclass, field
from typing import Dict


@dataclass
class Stat:
    name: str
    total_s: float = 0.0
    count: int = 0
    max_s: float = 0.0
    min_s: float = field(default=float("inf"))

    def add(self, seconds: float):
        self.total_s += seconds
        self.count += 1
        self.max_s = max(self.max_s, seconds)
        self.min_s = min(self.min_s, seconds)

    @property
    def avg_ms(self):
        return 1e3 * self.total_s / max(1, self.count)

    def reset(self):
        """Zero the accumulators (per-pass printing must not accumulate
        forever — reference: StatSet::reset, Stat.h)."""
        self.total_s = 0.0
        self.count = 0
        self.max_s = 0.0
        self.min_s = float("inf")

    def __str__(self):
        if self.count == 0:
            return f"{self.name}: total 0.0ms count 0"
        return (f"{self.name}: total {self.total_s*1e3:.1f}ms count {self.count} "
                f"avg {self.avg_ms:.3f}ms max {self.max_s*1e3:.3f}ms "
                f"min {self.min_s*1e3:.3f}ms")


class StatSet:
    """Global registry of named timers (reference: Stat.h:114 StatSet)."""

    def __init__(self, name="global"):
        self.name = name
        self._stats: Dict[str, Stat] = {}
        self._lock = threading.Lock()

    def get(self, name) -> Stat:
        with self._lock:
            if name not in self._stats:
                self._stats[name] = Stat(name)
            return self._stats[name]

    def reset(self, clear: bool = False):
        """Zero every timer (``clear=True`` drops the entries entirely).
        Zeroing keeps registered names visible in the next print, which
        per-pass reporting wants."""
        with self._lock:
            if clear:
                self._stats.clear()
            else:
                for s in self._stats.values():
                    s.reset()

    def print_status(self, log=print):
        with self._lock:
            stats = sorted(self._stats.values(), key=lambda s: -s.total_s)
        log(f"======= StatSet: [{self.name}] status ======")
        for s in stats:
            log("  " + str(s))


global_stats = StatSet()


@contextlib.contextmanager
def timer_scope(name: str, stats: StatSet = None, use_profiler: bool = None):
    """REGISTER_TIMER_INFO equivalent; optionally also a profiler trace
    scope. Thin alias for ``observe.trace_scope`` (the one
    implementation: nesting-qualified names, profiler annotations that
    degrade gracefully without jax) kept for source compatibility."""
    from paddle_tpu.observe.trace import trace_scope  # lazy: avoids cycle
    with trace_scope(name, stats=stats, use_profiler=use_profiler):
        yield


class Timer:
    """Manual start/stop timer (reference: Stat.h:166)."""

    def __init__(self):
        self._start = None
        self.elapsed_s = 0.0

    def start(self):
        self._start = time.perf_counter()

    def stop(self):
        if self._start is not None:
            self.elapsed_s += time.perf_counter() - self._start
            self._start = None
        return self.elapsed_s

    def reset(self):
        self._start = None
        self.elapsed_s = 0.0
