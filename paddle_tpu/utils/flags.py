"""Global runtime flags registry.

Reference: paddle/utils/Flags.cpp:18-81 centralises every runtime knob as a
gflag (use_gpu, trainer_count, port, trainer_id, num_gradient_servers,
parallel_nn, beam_size, ...). Here flags are a typed registry usable from
Python and settable via paddle_tpu.init(**kwargs) or environment variables
(PADDLE_TPU_<NAME>).
"""

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional


@dataclass
class _FlagSpec:
    name: str
    default: Any
    help: str
    parser: Callable[[str], Any]


def _parse_bool(s):
    if isinstance(s, bool):
        return s
    return str(s).lower() in ("1", "true", "yes", "on")


class FlagRegistry:
    """Typed flag registry with env-var overrides (PADDLE_TPU_<NAME>)."""

    def __init__(self):
        self._specs: Dict[str, _FlagSpec] = {}
        self._values: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def define(self, name: str, default: Any, help: str = "",
               parser: Optional[Callable] = None):
        if parser is None:
            if isinstance(default, bool):
                parser = _parse_bool
            elif isinstance(default, int):
                parser = int
            elif isinstance(default, float):
                parser = float
            else:
                parser = str
        with self._lock:
            self._specs[name] = _FlagSpec(name, default, help, parser)
            env = os.environ.get("PADDLE_TPU_" + name.upper())
            self._values[name] = parser(env) if env is not None else default
        return self

    def __getattr__(self, name):
        # only called when normal attribute lookup fails
        values = self.__dict__.get("_values", {})
        if name in values:
            return values[name]
        raise AttributeError(f"unknown flag {name!r}")

    def get(self, name, default=None):
        return self._values.get(name, default)

    def set(self, name, value):
        with self._lock:
            if name not in self._specs:
                raise KeyError(f"unknown flag {name!r}")
            spec = self._specs[name]
            self._values[name] = spec.parser(value) if isinstance(value, str) else value

    def set_if_known(self, name, value):
        """Silently ignore unknown flags — paddle.init() historically accepted
        arbitrary gflags (python/paddle/v2/__init__.py:123)."""
        if name in self._specs:
            self.set(name, value)

    def describe(self):
        return {n: (self._values[n], s.help) for n, s in self._specs.items()}


def set_xla_host_device_count(n: int) -> None:
    """Force ``--xla_force_host_platform_device_count=n`` into XLA_FLAGS,
    replacing any existing setting of that flag (token-level — a naive
    substring check would treat '...count=80' as already containing
    '...count=8' and silently skip). Must run before the CPU backend
    initialises; newer JAX also accepts jax_num_cpu_devices at runtime."""
    prefix = "--xla_force_host_platform_device_count="
    toks = [t for t in os.environ.get("XLA_FLAGS", "").split()
            if not t.startswith(prefix)]
    toks.append(f"{prefix}{int(n)}")
    os.environ["XLA_FLAGS"] = " ".join(toks)


GLOBAL_FLAGS = FlagRegistry()

# Mirrors of the reference's core flags (paddle/utils/Flags.cpp) that still
# make sense on TPU, plus TPU-native additions.
GLOBAL_FLAGS.define("use_tpu", True, "prefer TPU devices when present (was: use_gpu)")
GLOBAL_FLAGS.define("trainer_count", 1, "data-parallel shards on the local mesh")
GLOBAL_FLAGS.define("trainer_id", 0, "distributed trainer index")
GLOBAL_FLAGS.define("seed", 0, "global RNG seed; 0 derives from time")
GLOBAL_FLAGS.define("log_period", 100, "batches between metric log lines")
GLOBAL_FLAGS.define("test_period", 0, "batches between mid-pass tests (0=off)")
GLOBAL_FLAGS.define("beam_size", 7, "default beam width for sequence generation")
GLOBAL_FLAGS.define("show_layer_stat", False, "print per-layer stats each batch")
GLOBAL_FLAGS.define("enable_x64", False, "enable float64/int64 (jax_enable_x64)")
GLOBAL_FLAGS.define("default_dtype", "float32", "parameter dtype")
GLOBAL_FLAGS.define("compute_dtype", "bfloat16", "matmul/conv compute dtype on TPU")
GLOBAL_FLAGS.define("profile", False, "emit jax.profiler traces around hot loops")
GLOBAL_FLAGS.define("debug_nans", False,
                    "trap NaNs: re-run jitted code op-by-op and raise at the "
                    "producing op (was: feenableexcept FE_INVALID, "
                    "TrainerMain.cpp:49)")
GLOBAL_FLAGS.define("debug_infs", False,
                    "trap Infs like debug_nans (was: feenableexcept "
                    "FE_OVERFLOW|FE_DIVBYZERO)")
GLOBAL_FLAGS.define("checkpoint_period", 0, "batches between async checkpoints (0=per pass)")
GLOBAL_FLAGS.define("metrics_path", "", "JSONL per-step metrics file (also: "
                    "PADDLE_TPU_METRICS_PATH); empty = off")
GLOBAL_FLAGS.define("flight_dir", "", "directory for flight-recorder "
                    "post-mortem artifacts (also: PADDLE_TPU_FLIGHT_DIR); "
                    "empty = working directory, and crash dumps beyond the "
                    "NaN tripwire stay off")
