"""Logging (reference: paddle/utils/Logging.cpp — glog wrappers with VLOG levels).

Thin wrapper over the stdlib so the whole framework logs through one place and
``VLOG``-style verbosity maps to levels below DEBUG.
"""

import logging
import os
import sys

_LOGGER = logging.getLogger("paddle_tpu")

if not _LOGGER.handlers:
    _handler = logging.StreamHandler(sys.stderr)
    _handler.setFormatter(logging.Formatter(
        "%(levelname).1s %(asctime)s %(name)s %(filename)s:%(lineno)d] %(message)s",
        datefmt="%m%d %H:%M:%S"))
    _LOGGER.addHandler(_handler)
    _level = os.environ.get("PADDLE_TPU_LOGLEVEL", "INFO").upper()
    _names = (logging.getLevelNamesMapping()
              if hasattr(logging, "getLevelNamesMapping")   # 3.11+
              else {**{n: v for v, n in logging._levelToName.items()},
                    # aliases getLevelNamesMapping includes but
                    # _levelToName lacks — keep 3.10 behavior identical
                    "WARN": logging.WARNING, "FATAL": logging.CRITICAL})
    if _level not in _names:
        _LOGGER.warning("invalid PADDLE_TPU_LOGLEVEL=%r, using INFO", _level)
        _level = "INFO"
    _LOGGER.setLevel(_level)
    _LOGGER.propagate = False


def get_logger(name=None):
    return _LOGGER.getChild(name) if name else _LOGGER


def vlog(level, msg, *args):
    """VLOG(level) — higher level == chattier (glog semantics)."""
    _LOGGER.log(max(1, logging.DEBUG - level), msg, *args)


info = _LOGGER.info
warning = _LOGGER.warning
error = _LOGGER.error
debug = _LOGGER.debug
