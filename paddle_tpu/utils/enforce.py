"""Error enforcement (reference: paddle/platform/enforce.h — PADDLE_ENFORCE /
PADDLE_THROW with rich messages; paddle/utils/CustomStackTrace.h layer-stack
error context).

The layer-stack context manager replaces CustomStackTrace: layer compilation /
tracing pushes the layer name, so shape errors inside jit tracing report which
layer of the user's topology failed (reference: NeuralNetwork.cpp:258-261).
"""

import contextlib
import threading


class EnforceError(RuntimeError):
    pass


_ctx = threading.local()


def _stack():
    if not hasattr(_ctx, "stack"):
        _ctx.stack = []
    return _ctx.stack


@contextlib.contextmanager
def layer_scope(name: str):
    """Push a layer name onto the error-context stack while tracing it."""
    _stack().append(name)
    try:
        yield
    except Exception as e:
        # annotate once, at the innermost frame
        if not getattr(e, "_paddle_tpu_annotated", False):
            e._paddle_tpu_annotated = True
            trace = " -> ".join(_stack())
            e.args = (f"{e.args[0] if e.args else e}\n  [layer stack: {trace}]",) + \
                tuple(e.args[1:])
        raise
    finally:
        _stack().pop()


def enforce(cond, msg="", *fmt_args):
    """PADDLE_ENFORCE equivalent."""
    if not cond:
        raise EnforceError(msg % fmt_args if fmt_args else msg)


def enforce_eq(a, b, msg=""):
    if a != b:
        raise EnforceError(f"enforce_eq failed: {a!r} != {b!r}. {msg}")


def enforce_shape_match(shape_a, shape_b, msg=""):
    if tuple(shape_a) != tuple(shape_b):
        raise EnforceError(f"shape mismatch: {tuple(shape_a)} vs {tuple(shape_b)}. {msg}")
