"""Error enforcement (reference: paddle/platform/enforce.h — PADDLE_ENFORCE /
PADDLE_THROW with rich messages; paddle/utils/CustomStackTrace.h layer-stack
error context).

The layer-stack context manager replaces CustomStackTrace: layer compilation /
tracing pushes the layer name, so shape errors inside jit tracing report which
layer of the user's topology failed (reference: NeuralNetwork.cpp:258-261).
"""

import contextlib
import threading


class EnforceError(RuntimeError):
    pass


_ctx = threading.local()


def _stack():
    if not hasattr(_ctx, "stack"):
        _ctx.stack = []
    return _ctx.stack


@contextlib.contextmanager
def layer_scope(name: str):
    """Push a layer name onto the error-context stack while tracing it."""
    _stack().append(name)
    try:
        yield
    except Exception as e:
        # annotate once, at the innermost frame
        if not getattr(e, "_paddle_tpu_annotated", False):
            e._paddle_tpu_annotated = True
            trace = " -> ".join(_stack())
            e.args = (f"{e.args[0] if e.args else e}\n  [layer stack: {trace}]",) + \
                tuple(e.args[1:])
        raise
    finally:
        _stack().pop()


def enforce(cond, msg="", *fmt_args):
    """PADDLE_ENFORCE equivalent."""
    if not cond:
        raise EnforceError(msg % fmt_args if fmt_args else msg)


def enforce_eq(a, b, msg=""):
    if a != b:
        raise EnforceError(f"enforce_eq failed: {a!r} != {b!r}. {msg}")


def enforce_shape_match(shape_a, shape_b, msg=""):
    if tuple(shape_a) != tuple(shape_b):
        raise EnforceError(f"shape mismatch: {tuple(shape_a)} vs {tuple(shape_b)}. {msg}")


def check_numerics(tree, name="value"):
    """Raise EnforceError if any leaf of ``tree`` contains NaN/Inf — the
    host-side finite tripwire (reference: the FE_* traps of TrainerMain.cpp:49
    caught non-finite arithmetic at the instruction; here the check runs on
    materialised arrays between steps)."""
    import jax
    import numpy as np
    bad = []
    import jax.numpy as jnp
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        # np.issubdtype is False for ml_dtypes.bfloat16 (kind 'V');
        # jnp.issubdtype knows the extended float types
        dt = jnp.asarray(leaf).dtype
        if not jnp.issubdtype(dt, jnp.floating):
            continue
        # widen only the narrow ml_dtypes floats numpy can't isfinite()
        # (kind 'V'); never narrow f64 (finite 1e40 would overflow in f32)
        arr = np.asarray(leaf)
        if arr.dtype.kind != "f":
            arr = arr.astype(np.float32)
        if not np.isfinite(arr).all():
            n_nan = int(np.isnan(arr).sum())
            n_inf = int(np.isinf(arr).sum())
            bad.append(f"{name}{jax.tree_util.keystr(path)}: "
                       f"{n_nan} NaN, {n_inf} Inf of {arr.size}")
    if bad:
        raise EnforceError("non-finite values detected:\n  " +
                           "\n  ".join(bad))
