"""Ring attention: exact attention over sequences sharded across a mesh axis.

The long-context scaling path (SURVEY.md §5 — the reference's capability slot
was zero-padding LoD sequences; the modern TPU-native equivalent is context
parallelism). Design follows the ring-attention pattern: each device holds a
sequence shard of Q/K/V; K/V blocks rotate around the ring via
``lax.ppermute`` over ICI while an online-softmax accumulator (m, l, o) folds
in one block per step — compute overlaps the neighbor-exchange, memory stays
O(T/P) per chip, and the result is bit-for-bit exact attention (no
approximation).

Used inside ``shard_map`` over the ``seq`` mesh axis; composes with data
(batch) and model (heads) axes.
"""

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.core import place

NEG_INF = -1e30


def _block_attn(q, k, v, mask, scale):
    """One blockwise attention piece → (scores-exp sum l, running max m,
    unnormalized out). q [B,Tq,H,D] k/v [B,Tk,H,D] mask [B,Tq,Tk] bool."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    s = jnp.where(mask[:, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)                                  # [B,H,Tq]
    # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1
    m_safe = jnp.maximum(m, -1e29)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(mask[:, None], p, 0.0)
    l = jnp.sum(p, axis=-1)                                  # [B,H,Tq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return m_safe, l, o


def ring_attention(q, k, v, *, axis_name: str, causal: bool = False,
                   lengths: Optional[jax.Array] = None,
                   scale: Optional[float] = None):
    """Exact attention with K/V rotating around the ``axis_name`` ring.

    Call inside shard_map. q/k/v: local shards [B, T_local, H, D] (sequence
    axis sharded); lengths: global per-example valid lengths [B] (replicated).
    Returns [B, T_local, H, D].
    """
    B, Tl, H, D = q.shape
    nshards = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    scale = scale or (1.0 / math.sqrt(D))
    q32 = q.astype(jnp.float32)

    q_pos = my * Tl + jnp.arange(Tl)                         # [Tq] global

    def step_mask(src):
        k_pos = src * Tl + jnp.arange(Tl)                    # [Tk] global
        m = jnp.ones((B, Tl, Tl), bool)
        if causal:
            m = m & (q_pos[None, :, None] >= k_pos[None, None, :])
        if lengths is not None:
            m = m & (k_pos[None, None, :] < lengths[:, None, None])
        return m

    perm = [(i, (i + 1) % nshards) for i in range(nshards)]

    def body(step, carry):
        o, mx, l, k_cur, v_cur = carry
        src = (my - step) % nshards
        bm, bl, bo = _block_attn(q32, k_cur, v_cur, step_mask(src), scale)
        new_m = jnp.maximum(mx, bm)
        c_old = jnp.exp(mx - new_m)
        c_new = jnp.exp(bm - new_m)
        l = l * c_old + bl * c_new
        o = (o * c_old[..., None].swapaxes(1, 2) +
             bo * c_new[..., None].swapaxes(1, 2))
        # rotate K/V to the next device; skip the final dead rotation
        k_nxt, v_nxt = jax.lax.cond(
            step < nshards - 1,
            lambda kv: (jax.lax.ppermute(kv[0], axis_name, perm),
                        jax.lax.ppermute(kv[1], axis_name, perm)),
            lambda kv: kv, (k_cur, v_cur))
        return o, new_m, l, k_nxt, v_nxt

    o0 = jnp.zeros((B, Tl, H, D), jnp.float32)
    m0 = jnp.full((B, H, Tl), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tl), jnp.float32)
    o, mx, l, _, _ = jax.lax.fori_loop(0, nshards, body, (o0, m0, l0, k, v))
    l = jnp.maximum(l, 1e-30)
    out = o / l[..., None].swapaxes(1, 2)
    return out.astype(q.dtype)


def full_attention(q, k, v, *, causal: bool = False,
                   lengths: Optional[jax.Array] = None,
                   scale: Optional[float] = None):
    """Reference single-device attention with the same masking semantics."""
    B, T, H, D = q.shape
    scale = scale or (1.0 / math.sqrt(D))
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    mask = jnp.ones((B, T, T), bool)
    if causal:
        i = jnp.arange(T)
        mask = mask & (i[None, :, None] >= i[None, None, :])
    if lengths is not None:
        mask = mask & (jnp.arange(T)[None, None, :] < lengths[:, None, None])
    s = jnp.where(mask[:, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(mask[:, None], p, 0.0)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def ring_attention_spmd(q, k, v, mesh: Mesh, *, causal: bool = False,
                        lengths: Optional[jax.Array] = None,
                        batch_axis: str = place.AXIS_DATA,
                        seq_axis: str = place.AXIS_SEQ,
                        head_axis: str = place.AXIS_MODEL,
                        scale: Optional[float] = None):
    """shard_map wrapper: q/k/v [B, T, H, D] with B over ``batch_axis``,
    T over ``seq_axis``, and heads over ``head_axis`` when the mesh has one
    (tensor parallelism: each model-shard attends its own heads — attention
    is head-separable so no collective is needed on that axis); lengths [B]
    sharded with the batch."""
    from jax import shard_map

    H = q.shape[2]
    tp = (head_axis if head_axis in mesh.axis_names
          and mesh.shape[head_axis] > 1 and H % mesh.shape[head_axis] == 0
          else None)
    qkv_spec = P(batch_axis, seq_axis, tp, None)
    len_spec = P(batch_axis)
    fn = functools.partial(ring_attention, axis_name=seq_axis, causal=causal,
                           scale=scale)

    if lengths is None:
        def wrapped(q_, k_, v_):
            return fn(q_, k_, v_, lengths=None)
        return shard_map(wrapped, mesh=mesh,
                         in_specs=(qkv_spec,) * 3,
                         out_specs=qkv_spec, check_vma=False)(q, k, v)

    def wrapped(q_, k_, v_, len_):
        return fn(q_, k_, v_, lengths=len_)
    return shard_map(wrapped, mesh=mesh,
                     in_specs=(qkv_spec, qkv_spec, qkv_spec, len_spec),
                     out_specs=qkv_spec, check_vma=False)(q, k, v, lengths)
