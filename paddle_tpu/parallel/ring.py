"""Ring attention: exact attention over sequences sharded across a mesh axis.

The long-context scaling path (SURVEY.md §5 — the reference's capability slot
was zero-padding LoD sequences; the modern TPU-native equivalent is context
parallelism). Design follows the ring-attention pattern: each device holds a
sequence shard of Q/K/V; K/V blocks rotate around the ring via
``lax.ppermute`` over ICI while an online-softmax accumulator (m, l, o) folds
in one block per step — compute overlaps the neighbor-exchange, memory stays
O(T/P) per chip, and the result is bit-for-bit exact attention (no
approximation).

Used inside ``shard_map`` over the ``seq`` mesh axis; composes with data
(batch) and model (heads) axes.
"""

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.core import place

NEG_INF = -1e30


def _block_attn(q, k, v, mask, scale):
    """One blockwise attention piece → (running max m, scores-exp sum l,
    unnormalized out). q [B,Tq,H,D]; k/v [B,Tk,Hkv,D] where H % Hkv == 0
    — Hkv < H is grouped-query attention (query head h reads kv head
    h // (H//Hkv)); the group broadcast happens HERE, in registers, so
    callers (and ring collectives) carry only Hkv-head K/V."""
    B, Tq, H, D = q.shape
    Hkv = k.shape[2]
    G = H // Hkv
    qg = q.reshape(B, Tq, Hkv, G, D)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    s = jnp.where(mask[:, None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)                                  # [B,Hkv,G,Tq]
    # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1
    m_safe = jnp.maximum(m, -1e29)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(mask[:, None, None], p, 0.0)
    l = jnp.sum(p, axis=-1)                                  # [B,Hkv,G,Tq]
    o = jnp.einsum("bhgqk,bkhd->bqhgd", p, v.astype(jnp.float32))
    return (m_safe.reshape(B, H, Tq), l.reshape(B, H, Tq),
            o.reshape(B, Tq, H, D))


def ring_attention(q, k, v, *, axis_name: str, causal: bool = False,
                   lengths: Optional[jax.Array] = None,
                   scale: Optional[float] = None,
                   wire_int8: bool = False):
    """Exact attention with K/V rotating around the ``axis_name`` ring.

    Call inside shard_map. q: local shard [B, T_local, H, D]; k/v
    [B, T_local, Hkv, D] with H % Hkv == 0 (Hkv < H = grouped-query
    attention — the ppermute collectives then move only Hkv-head K/V, the
    group broadcast happens inside the block math); lengths: global
    per-example valid lengths [B] (replicated).
    Returns [B, T_local, H, D].
    """
    B, Tl, H, D = q.shape
    nshards = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    scale = scale or (1.0 / math.sqrt(D))
    q32 = q.astype(jnp.float32)

    q_pos = my * Tl + jnp.arange(Tl)                         # [Tq] global

    def step_mask(src):
        k_pos = src * Tl + jnp.arange(Tl)                    # [Tk] global
        m = jnp.ones((B, Tl, Tl), bool)
        if causal:
            m = m & (q_pos[None, :, None] >= k_pos[None, None, :])
        if lengths is not None:
            m = m & (k_pos[None, None, :] < lengths[:, None, None])
        return m

    perm = [(i, (i + 1) % nshards) for i in range(nshards)]

    def body(step, carry):
        o, mx, l, k_cur, v_cur = carry
        src = (my - step) % nshards
        bm, bl, bo = _block_attn(q32, k_cur, v_cur, step_mask(src), scale)
        new_m = jnp.maximum(mx, bm)
        c_old = jnp.exp(mx - new_m)
        c_new = jnp.exp(bm - new_m)
        l = l * c_old + bl * c_new
        o = (o * c_old[..., None].swapaxes(1, 2) +
             bo * c_new[..., None].swapaxes(1, 2))
        # rotate K/V to the next device; skip the final dead rotation.
        # wire_int8: the rotation carries int8 + a per-shard scale
        # (ops/q8.make_ppermute_q8 — the KV-cache-int8 trick on the
        # wire; halves ICI bytes per hop, straight-through gradients).
        # Each hop re-quantizes, compounding <=0.5 LSB rounding per hop
        # (~sqrt(P) LSB total — bounded by the tolerance test at 8
        # shards); rotating raw int8 in the carry instead would sever
        # the gradient path through the integer loop carry, so the
        # re-quantizing codec is the differentiable design point.
        if wire_int8:
            from paddle_tpu.ops import q8 as ops_q8
            send = ops_q8.make_ppermute_q8(axis_name, tuple(perm))
        else:
            def send(t):
                return jax.lax.ppermute(t, axis_name, perm)
        k_nxt, v_nxt = jax.lax.cond(
            step < nshards - 1,
            lambda kv: (send(kv[0]), send(kv[1])),
            lambda kv: kv, (k_cur, v_cur))
        return o, new_m, l, k_nxt, v_nxt

    o0 = jnp.zeros((B, Tl, H, D), jnp.float32)
    m0 = jnp.full((B, H, Tl), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tl), jnp.float32)
    o, mx, l, _, _ = jax.lax.fori_loop(0, nshards, body, (o0, m0, l0, k, v))
    l = jnp.maximum(l, 1e-30)
    out = o / l[..., None].swapaxes(1, 2)
    return out.astype(q.dtype)


def full_attention(q, k, v, *, causal: bool = False,
                   lengths: Optional[jax.Array] = None,
                   scale: Optional[float] = None):
    """Reference single-device attention with the same masking semantics.
    k/v may carry Hkv <= H heads (GQA, H % Hkv == 0) — grouping is done
    in the einsum, no materialized head repetition."""
    B, T, H, D = q.shape
    Hkv = k.shape[2]
    G = H // Hkv
    scale = scale or (1.0 / math.sqrt(D))
    qg = q.reshape(B, T, Hkv, G, D)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    mask = jnp.ones((B, T, T), bool)
    if causal:
        i = jnp.arange(T)
        mask = mask & (i[None, :, None] >= i[None, None, :])
    if lengths is not None:
        mask = mask & (jnp.arange(T)[None, None, :] < lengths[:, None, None])
    s = jnp.where(mask[:, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(mask[:, None, None], p, 0.0)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", p, v.astype(jnp.float32))
    return out.reshape(B, T, H, D).astype(q.dtype)


def ring_attention_spmd(q, k, v, mesh: Mesh, *, causal: bool = False,
                        lengths: Optional[jax.Array] = None,
                        batch_axis: str = place.AXIS_DATA,
                        seq_axis: str = place.AXIS_SEQ,
                        head_axis: str = place.AXIS_MODEL,
                        scale: Optional[float] = None,
                        use_flash: bool = False,
                        interpret: Optional[bool] = None,
                        wire_int8: bool = False):
    """shard_map wrapper: q/k/v [B, T, H, D] with B over ``batch_axis``,
    T over ``seq_axis``, and heads over ``head_axis`` when the mesh has one
    (tensor parallelism: each model-shard attends its own heads — attention
    is head-separable so no collective is needed on that axis); lengths [B]
    sharded with the batch. k/v may carry Hkv < H heads (GQA) — the ring
    collectives then rotate the Hkv-head tensors; head-axis TP applies
    only when it divides BOTH head counts. ``use_flash`` swaps the
    per-block engine for the Pallas flash kernel (packed equal-length
    sequences only). ``wire_int8`` sends the rotating K/V as int8 + a
    per-shard scale in both engines. Backward precision differs: the
    flash engine's hand-written VJP keeps its dk/dv accumulators fp32
    on the wire; the jnp engine's autodiff backward sends cotangents
    through the same int8 codec per hop (bounded by the grad tolerance
    test — prefer the flash engine for training at scale)."""
    from paddle_tpu.parallel.compat import shard_map

    H, Hkv = q.shape[2], k.shape[2]
    tp = (head_axis if head_axis in mesh.axis_names
          and mesh.shape[head_axis] > 1 and H % mesh.shape[head_axis] == 0
          and Hkv % mesh.shape[head_axis] == 0
          else None)
    qkv_spec = P(batch_axis, seq_axis, tp, None)
    len_spec = P(batch_axis)
    if use_flash and lengths is not None:
        raise ValueError(_FLASH_RAGGED_MSG)
    interpret = _default_interpret(interpret)
    if wire_int8 and lengths is not None:
        # the per-shard scale is an absmax over the WHOLE rotating shard;
        # padding K/V beyond lengths would inflate it and collapse the
        # valid rows' precision — reject rather than silently degrade
        raise ValueError("wire_int8 supports packed equal-length "
                         "sequences only (padding would contaminate the "
                         "wire quantization scale); pass lengths=None")
    fn = functools.partial(ring_attention, axis_name=seq_axis, causal=causal,
                           scale=scale, wire_int8=wire_int8)

    if lengths is None:
        if use_flash:
            def wrapped(q_, k_, v_):
                return ring_flash_attention(
                    q_, k_, v_, axis_name=seq_axis, causal=causal,
                    scale=scale, interpret=interpret,
                    wire_int8=wire_int8)
        else:
            def wrapped(q_, k_, v_):
                return fn(q_, k_, v_, lengths=None)
        return shard_map(wrapped, mesh=mesh,
                         in_specs=(qkv_spec,) * 3,
                         out_specs=qkv_spec, check_vma=False)(q, k, v)

    def wrapped(q_, k_, v_, len_):
        return fn(q_, k_, v_, lengths=len_)
    return shard_map(wrapped, mesh=mesh,
                     in_specs=(qkv_spec, qkv_spec, qkv_spec, len_spec),
                     out_specs=qkv_spec, check_vma=False)(q, k, v, lengths)


def _default_interpret(interpret):
    """Off-TPU the Mosaic lowering doesn't exist; interpret mode keeps
    the same kernel code path (tests, CPU dryruns) at reduced speed."""
    if interpret is None:
        return jax.devices()[0].platform != "tpu"
    return interpret


_FLASH_RAGGED_MSG = ("flash attention in context parallelism supports "
                     "packed equal-length sequences only; pass "
                     "lengths=None or use the jnp engine "
                     "(use_flash=False)")


def alltoall_attention_spmd(q, k, v, mesh: Mesh, *, causal: bool = False,
                            lengths: Optional[jax.Array] = None,
                            batch_axis: str = place.AXIS_DATA,
                            seq_axis: str = place.AXIS_SEQ,
                            head_axis: str = place.AXIS_MODEL,
                            scale: Optional[float] = None,
                            use_flash: bool = False,
                            interpret: Optional[bool] = None):
    """All-to-all (Ulysses-style) sequence parallelism — the other
    context-parallel layout: instead of rotating K/V around a ring, one
    all-to-all RESHUFFLES [B, T/P, H, D] (sequence-sharded) into
    [B, T, H/P, D] (head-sharded), attention runs fully local per head
    group, and a second all-to-all restores sequence sharding. Two
    collectives total per attention vs P−1 ring hops — better when
    H ≥ P and the interconnect favors large all-to-alls; ring wins when
    heads are scarce or memory for the full-T K/V slice is tight.
    Autodiff transposes the all-to-alls, so no custom VJP is needed.

    q [B, T, H, D]; k/v may carry Hkv ≤ H heads (GQA) — all three are
    head-scattered, so the seq-axis size (times any head-axis TP shard)
    must divide BOTH H and Hkv. When the mesh carries a >1 ``head_axis``
    that divides the head counts, heads are ALSO tensor-parallel over it
    (as in ring_attention_spmd — each model shard scatters only its own
    heads). ``use_flash`` runs the local attention with the Pallas flash
    kernel (packed equal-length only); ragged ``lengths`` use the jnp
    engine.
    """
    from paddle_tpu.parallel.compat import shard_map

    P_ = mesh.shape[seq_axis]
    H, Hkv = q.shape[2], k.shape[2]
    tp_sz = (mesh.shape[head_axis]
             if head_axis in mesh.axis_names else 1)
    tp = (head_axis if tp_sz > 1 and H % (tp_sz * P_) == 0
          and Hkv % (tp_sz * P_) == 0 else None)
    denom = (tp_sz if tp else 1) * P_
    if H % denom or Hkv % denom:
        raise ValueError(
            f"alltoall attention: seq axis size {P_} must divide both "
            f"n_heads={H} and kv_heads={Hkv}; use ring attention for "
            f"head counts that don't split")
    if use_flash and lengths is not None:
        raise ValueError(_FLASH_RAGGED_MSG)
    interpret = _default_interpret(interpret)

    qkv_spec = P(batch_axis, seq_axis, tp, None)
    len_spec = P(batch_axis)

    def local(q_, k_, v_, len_):
        # [B, T/P, H, D] -> all_to_all -> [B, T, H/P, D]: split the head
        # axis across the group, concatenate the sequence shards
        def scatter(t):
            return jax.lax.all_to_all(t, seq_axis, split_axis=2,
                                      concat_axis=1, tiled=True)

        def gather(t):
            return jax.lax.all_to_all(t, seq_axis, split_axis=1,
                                      concat_axis=2, tiled=True)

        qg, kg, vg = scatter(q_), scatter(k_), scatter(v_)
        if use_flash:
            from paddle_tpu.ops.pallas import flash_attention
            out = flash_attention(qg, kg, vg, causal=causal,
                                  sm_scale=scale, interpret=interpret)
        else:
            out = full_attention(qg, kg, vg, causal=causal, lengths=len_,
                                 scale=scale)
        return gather(out)

    if lengths is None:
        return shard_map(
            lambda a, b, c: local(a, b, c, None), mesh=mesh,
            in_specs=(qkv_spec,) * 3, out_specs=qkv_spec,
            check_vma=False)(q, k, v)
    return shard_map(local, mesh=mesh,
                     in_specs=(qkv_spec, qkv_spec, qkv_spec, len_spec),
                     out_specs=qkv_spec, check_vma=False)(q, k, v, lengths)


def ring_flash_attention(q, k, v, *, axis_name: str, causal: bool = False,
                         scale: Optional[float] = None,
                         block_q: Optional[int] = None,
                         block_k: Optional[int] = None,
                         interpret: bool = False,
                         wire_int8: bool = False):
    """Ring attention with the Pallas flash kernel as the per-block engine.

    Same exactness and rotation scheme as ``ring_attention``, but each
    ring step runs the streaming-softmax kernel on (q_local, k_block) —
    no [Tq, Tk] score tensor exists even per step, so per-chip memory is
    O(T/P·D) and the kernel's MXU pipeline is reused across the ring.
    Blocks fold by the logsumexp combination rule; the backward re-walks
    the ring calling the flash backward kernel with the GLOBAL logsumexp
    (exact: p = exp(s − lse) under any key partition), with dk/dv
    accumulators riding the rotation so each arrives back at its owner
    after the full cycle.

    Equal-length (packed) sequences only — for ragged ``lengths`` use
    ``ring_attention``. Call inside shard_map; q [B, T_local, H, D],
    k/v [B, T_local, Hkv, D] with H % Hkv == 0 (GQA: the ring rotates
    Hkv-head K/V and dk/dv; the H-head expansion is local per step).
    """
    from paddle_tpu.ops.pallas.attention import select_block_sizes

    Tl, D = q.shape[1], q.shape[3]
    scale = scale or (1.0 / math.sqrt(D))
    if block_q and block_k:
        bq, bk = min(block_q, Tl), min(block_k, Tl)
    else:
        # block selection keyed on the LOCAL shard length (each ring step
        # runs the kernel on [Tl, D] tiles)
        bq_auto, bk_auto = select_block_sizes(Tl, D, q.dtype)
        bq = min(block_q, Tl) if block_q else bq_auto
        bk = min(block_k, Tl) if block_k else bk_auto
    return _ring_flash(q, k, v, axis_name, causal, scale, bq, bk,
                       interpret, wire_int8)


def _bhtd(x):
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _btHd(x, b, h):
    bh, t, d = x.shape
    return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _expand_groups(kv_r, b, g):
    """[B·Hkv, T, D] -> [B·H, T, D] by repeating each kv head g times —
    the LOCAL GQA broadcast done after the ring rotation, so ppermute
    only ever moves the Hkv-head tensor. Query head h = hkv·g + i maps
    to kv head hkv, matching the models' head grouping convention."""
    if g == 1:
        return kv_r
    bh, t, d = kv_r.shape
    return jnp.repeat(kv_r.reshape(b, bh // b, t, d), g,
                      axis=1).reshape(bh * g, t, d)


def _group_sum(d_r, b, g):
    """[B·H, T, D] -> [B·Hkv, T, D]: fold the q-head-group gradients back
    onto their shared kv head (adjoint of _expand_groups)."""
    if g == 1:
        return d_r
    bh, t, d = d_r.shape
    return d_r.reshape(b, bh // (b * g), g, t, d).sum(axis=2).reshape(
        bh // g, t, d)


def _fold(o, lse, ob, lseb):
    """Combine two normalized partial attentions by logsumexp weights."""
    m = jnp.maximum(lse, lseb)
    w1 = jnp.exp(lse - m)
    w2 = jnp.exp(lseb - m)
    tot = jnp.maximum(w1 + w2, 1e-30)
    o = (o * w1[..., None] + ob * w2[..., None]) / tot[..., None]
    return o, m + jnp.log(tot)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _ring_flash(q, k, v, axis_name, causal, scale, block_q, block_k,
                interpret, wire_int8=False):
    out, _ = _ring_flash_fwd(q, k, v, axis_name, causal, scale, block_q,
                             block_k, interpret, wire_int8)
    return out


def _kv_rot(axis_name, perm, wire_int8):
    """The K/V hop: full precision, or the int8+scale codec
    (ops/q8.ppermute_q8_raw). Gradient ACCUMULATORS never use this —
    re-quantizing a running sum each hop would compound error."""
    if wire_int8:
        from paddle_tpu.ops import q8 as ops_q8

        def rot1(x):
            return ops_q8.ppermute_q8_raw(x, axis_name, perm)
    else:
        def rot1(x):
            return jax.lax.ppermute(x, axis_name, perm)
    return rot1


def _ring_flash_fwd(q, k, v, axis_name, causal, scale, block_q, block_k,
                    interpret, wire_int8=False):
    from paddle_tpu.ops.pallas.attention import NEG_INF as FNEG
    from paddle_tpu.ops.pallas.attention import flash_block_fwd

    B, Tl, H, D = q.shape
    G = H // k.shape[2]                 # GQA group size (1 = MHA)
    nshards = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % nshards) for i in range(nshards)]
    # rotate k/v in the kernel's [BH, T, D] layout: one transpose per
    # tensor instead of one per ring step (ppermute is layout-agnostic).
    # Under GQA kr/vr stay at Hkv heads — the ring moves the small tensor;
    # the per-step _expand_groups broadcast is local VMEM/HBM traffic the
    # kernel would read anyway.
    qr, kr, vr = _bhtd(q), _bhtd(k), _bhtd(v)

    # step 0: the diagonal block — the only one needing the causal mask
    o, lse = flash_block_fwd(qr, _expand_groups(kr, B, G),
                             _expand_groups(vr, B, G), scale, causal,
                             block_q, block_k, interpret)
    o = o.astype(jnp.float32)

    kv_hop = _kv_rot(axis_name, perm, wire_int8)

    def body(step, carry):
        o, lse, k_cur, v_cur = carry
        # rotate first: at step j the local block is (my - j) mod n
        k_cur = kv_hop(k_cur)
        v_cur = kv_hop(v_cur)
        ob, lseb = flash_block_fwd(qr, _expand_groups(k_cur, B, G),
                                   _expand_groups(v_cur, B, G), scale,
                                   False, block_q, block_k, interpret)
        if causal:
            src = (my - step) % nshards
            lseb = jnp.where(src < my, lseb, FNEG)
        o, lse = _fold(o, lse, ob.astype(jnp.float32), lseb)
        return o, lse, k_cur, v_cur

    o, lse, _, _ = jax.lax.fori_loop(1, nshards, body, (o, lse, kr, vr))
    return _btHd(o, B, H).astype(q.dtype), lse


def _ring_flash_vjp_fwd(q, k, v, axis_name, causal, scale, block_q,
                        block_k, interpret, wire_int8=False):
    out, lse = _ring_flash_fwd(q, k, v, axis_name, causal, scale, block_q,
                               block_k, interpret, wire_int8)
    return out, (q, k, v, out, lse)


def _ring_flash_vjp_bwd(axis_name, causal, scale, block_q, block_k,
                        interpret, wire_int8, res, do):
    from paddle_tpu.ops.pallas.attention import NEG_INF as FNEG
    from paddle_tpu.ops.pallas.attention import flash_block_bwd

    q, k, v, out, lse = res
    B, Tl, H, D = q.shape
    Hkv = k.shape[2]
    G = H // Hkv
    nshards = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % nshards) for i in range(nshards)]
    qr, outr, dor = _bhtd(q), _bhtd(out), _bhtd(do)
    kr, vr = _bhtd(k), _bhtd(v)

    kv_hop = _kv_rot(axis_name, perm, wire_int8)

    def rot_kv(*xs):
        return tuple(kv_hop(x) for x in xs)

    def rot(*xs):
        return tuple(jax.lax.ppermute(x, axis_name, perm) for x in xs)

    # diagonal block first (the causal variant), then rotate the block
    # TOGETHER with its gradient accumulator: at every step the local
    # (k, v, dk, dv) all describe the same block, each device adds its
    # contribution, and after n total rotations the accumulators are home.
    # GQA: the kernel runs in the H-head layout (local expand) but dk/dv
    # are group-summed back to Hkv heads BEFORE rotating, so every
    # ppermute moves only Hkv-head tensors.
    dq0, dk0, dv0 = flash_block_bwd(qr, _expand_groups(kr, B, G),
                                    _expand_groups(vr, B, G), outr, lse,
                                    dor, scale, causal, block_q, block_k,
                                    interpret)
    dq_acc = dq0.astype(jnp.float32)        # [BH, Tl, D], stays local
    k_cur, v_cur = rot_kv(kr, vr)
    dk_acc, dv_acc = rot(
        _group_sum(dk0.astype(jnp.float32), B, G),
        _group_sum(dv0.astype(jnp.float32), B, G))

    def body(step, carry):
        dq_acc, dk_acc, dv_acc, k_cur, v_cur = carry
        lse_b = lse
        if causal:
            # excluded (future) blocks: mask INSIDE the exponent by
            # feeding lse=+big so p = exp(s - lse) is exactly 0 — zeroing
            # the kernel's output after the fact would turn an overflowed
            # p (s far above the global lse, which excludes this block)
            # into 0·inf = NaN
            src = (my - step) % nshards
            lse_b = jnp.where(src < my, lse, -FNEG)
        dqb, dkb, dvb = flash_block_bwd(qr, _expand_groups(k_cur, B, G),
                                        _expand_groups(v_cur, B, G),
                                        outr, lse_b, dor, scale, False,
                                        block_q, block_k, interpret)
        dq_acc = dq_acc + dqb.astype(jnp.float32)
        dk_acc = dk_acc + _group_sum(dkb.astype(jnp.float32), B, G)
        dv_acc = dv_acc + _group_sum(dvb.astype(jnp.float32), B, G)
        # the accumulators need all n rotations to arrive home; the K/V
        # blocks are dead after the last step — skip their final hop
        # (with wire_int8 it would also burn a quantize + extra sends)
        k_cur, v_cur = jax.lax.cond(
            step < nshards - 1, lambda kv: rot_kv(*kv), lambda kv: kv,
            (k_cur, v_cur))
        dk_acc, dv_acc = rot(dk_acc, dv_acc)
        return dq_acc, dk_acc, dv_acc, k_cur, v_cur

    dq_acc, dk_acc, dv_acc, _, _ = jax.lax.fori_loop(
        1, nshards, body, (dq_acc, dk_acc, dv_acc, k_cur, v_cur))
    return (_btHd(dq_acc, B, H).astype(q.dtype),
            _btHd(dk_acc, B, Hkv).astype(k.dtype),
            _btHd(dv_acc, B, Hkv).astype(v.dtype))


_ring_flash.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)
