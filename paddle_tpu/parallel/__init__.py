"""Distribution layer: mesh + shardings + collectives.

Replaces the reference's entire distribution stack — MultiGradientMachine
thread-ring data parallelism (gserver/gradientmachines/MultiGradientMachine.h),
the C++ parameter server (paddle/pserver/), the Go pserver/master (go/), and
the NCCL ops (operators/nccl_op.cc) — with in-graph XLA collectives over
ICI/DCN driven by jax.sharding meshes.
"""

from paddle_tpu.core.place import (AXIS_DATA, AXIS_EXPERT, AXIS_MODEL,
                                   AXIS_SEQ, AXIS_STAGE, default_mesh,
                                   make_mesh)
from paddle_tpu.parallel.spmd import (DistConfig, data_model_parallel,
                                      data_parallel, embedding_vocab_rule,
                                      fc_column_rule, fc_row_rule,
                                      zero_constrained_update)
