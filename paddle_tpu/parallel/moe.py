"""Mixture-of-Experts with expert parallelism over the ``expert`` mesh axis.

The modern occupant of the reference's "scale parameters beyond one box"
slot (SURVEY.md §2.3 — sharded sparse embeddings / pserver-sharded weights;
here the GShard/Switch design): tokens are routed by a learned gate, experts
are sharded over the ``expert`` axis, and dispatch/combine are dense one-hot
einsums so XLA lowers them to all-to-alls over ICI instead of host gathers.

Capacity-factor dispatch keeps every shape static (XLA requirement): each
expert processes at most ``capacity`` tokens per batch; overflow tokens are
dropped (standard Switch behavior) and the aux loss keeps the router
balanced so drops stay rare.
"""

import dataclasses
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core import place


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    d_model: int
    d_ff: int
    num_experts: int
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01


def init_params(key: jax.Array, cfg: MoEConfig):
    k1, k2, k3 = jax.random.split(key, 3)
    D, F, E = cfg.d_model, cfg.d_ff, cfg.num_experts
    s = 1.0 / math.sqrt(D)
    return {
        "gate": jax.random.normal(k1, (D, E), jnp.float32) * s,
        "w_in": jax.random.normal(k2, (E, D, F), jnp.float32) * s,
        "w_out": jax.random.normal(k3, (E, F, D), jnp.float32) *
        (1.0 / math.sqrt(F)),
    }


def param_shardings(cfg: MoEConfig, mesh: Mesh):
    """Experts sharded over the ``expert`` axis; gate replicated."""
    E = place.AXIS_EXPERT

    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    return {"gate": ns(), "w_in": ns(E, None, None),
            "w_out": ns(E, None, None)}


def moe_ffn(params, x: jax.Array, cfg: MoEConfig,
            mesh: Optional[Mesh] = None) -> Tuple[jax.Array, jax.Array]:
    """Top-1 (Switch) MoE feed-forward.

    x: [N, D] tokens (flatten batch*seq first) → (out [N, D], aux_loss).
    With a mesh carrying an ``expert`` axis, einsum operands get sharding
    constraints so dispatch/combine become all-to-alls over ICI.
    """
    N, D = x.shape
    E = cfg.num_experts
    cap = max(1, int(cfg.capacity_factor * N / E))

    logits = jnp.einsum("nd,de->ne", x.astype(jnp.float32), params["gate"])
    probs = jax.nn.softmax(logits, axis=-1)                 # [N, E]
    expert = jnp.argmax(probs, axis=-1)                     # [N]
    gate_val = jnp.max(probs, axis=-1)

    # position of each token within its expert's capacity buffer
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)     # [N, E]
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1           # [N, E]
    pos_in_expert = jnp.sum(pos * onehot, axis=1)           # [N]
    keep = pos_in_expert < cap

    # dispatch tensor [N, E, cap]: one-hot of (expert, slot)
    disp = (onehot.astype(jnp.float32)[:, :, None] *
            jax.nn.one_hot(jnp.clip(pos_in_expert, 0, cap - 1), cap)[:, None, :])
    disp = jnp.where(keep[:, None, None], disp, 0.0)

    def constrain(v, spec):
        if mesh is None or place.AXIS_EXPERT not in mesh.axis_names:
            return v
        return jax.lax.with_sharding_constraint(v, NamedSharding(mesh, spec))

    # expert inputs [E, cap, D] — the all-to-all boundary
    xe = jnp.einsum("nec,nd->ecd", disp, x.astype(jnp.float32))
    xe = constrain(xe, P(place.AXIS_EXPERT, None, None))
    h = jnp.einsum("ecd,edf->ecf", xe, params["w_in"])
    h = jax.nn.gelu(h)
    ye = jnp.einsum("ecf,efd->ecd", h, params["w_out"])
    ye = constrain(ye, P(place.AXIS_EXPERT, None, None))
    out = jnp.einsum("nec,ecd->nd", disp, ye)
    out = out * gate_val[:, None]                           # Switch scaling

    # load-balance aux loss (Switch eq. 4): E * Σ_e frac_tokens_e * mean_prob_e
    frac = jnp.mean(onehot.astype(jnp.float32), axis=0)
    mean_p = jnp.mean(probs, axis=0)
    aux = cfg.aux_loss_weight * E * jnp.sum(frac * mean_p)
    return out.astype(x.dtype), aux
