"""Mixture-of-Experts with expert parallelism over the ``expert`` mesh axis.

The modern occupant of the reference's "scale parameters beyond one box"
slot (SURVEY.md §2.3 — sharded sparse embeddings / pserver-sharded weights;
here the GShard/Switch design): tokens are routed by a learned gate, experts
are sharded over the ``expert`` axis, and dispatch/combine are dense one-hot
einsums so XLA lowers them to all-to-alls over ICI instead of host gathers.

Capacity-factor dispatch keeps every shape static (XLA requirement): each
expert processes at most ``capacity`` tokens per batch; overflow tokens are
dropped (standard Switch behavior) and the aux loss keeps the router
balanced so drops stay rare.
"""

import dataclasses
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core import place


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    d_model: int
    d_ff: int
    num_experts: int
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    top_k: int = 1                  # 1 = Switch; 2 = GShard-style top-2
    normalize_gates: bool = True    # renormalize the k selected gates to
                                    # sum to 1 (GShard convention; ignored
                                    # at top_k=1 where Switch keeps raw p)


def init_params(key: jax.Array, cfg: MoEConfig):
    k1, k2, k3 = jax.random.split(key, 3)
    D, F, E = cfg.d_model, cfg.d_ff, cfg.num_experts
    s = 1.0 / math.sqrt(D)
    return {
        "gate": jax.random.normal(k1, (D, E), jnp.float32) * s,
        "w_in": jax.random.normal(k2, (E, D, F), jnp.float32) * s,
        "w_out": jax.random.normal(k3, (E, F, D), jnp.float32) *
        (1.0 / math.sqrt(F)),
    }


def param_shardings(cfg: MoEConfig, mesh: Mesh):
    """Experts sharded over the ``expert`` axis; gate replicated."""
    E = place.AXIS_EXPERT

    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    return {"gate": ns(), "w_in": ns(E, None, None),
            "w_out": ns(E, None, None)}


def _route(params, x: jax.Array, cfg: MoEConfig, cap: int):
    """Shared gating + capacity accounting: returns (disp [N, E, cap],
    combine [N, E, cap], frac [E], mean_p [E]).

    One dispatch path serves every k: choice c of every token claims
    capacity AFTER all choices < c (first choices never lose their slot
    to second choices — the GShard priority rule), the [N, E, cap]
    dispatch one-hot sums over choices, and the combine tensor carries
    the per-choice gate weights, so the expert einsums are identical to
    the Switch path."""
    N, _ = x.shape
    E, k = cfg.num_experts, cfg.top_k
    logits = jnp.einsum("nd,de->ne", x.astype(jnp.float32), params["gate"])
    probs = jax.nn.softmax(logits, axis=-1)                 # [N, E]
    gate_k, expert_k = jax.lax.top_k(probs, k)              # [N, k]
    if k > 1 and cfg.normalize_gates:
        gate_k = gate_k / jnp.maximum(
            jnp.sum(gate_k, axis=-1, keepdims=True), 1e-9)

    # capacity accounting over (choice-major, token) order: flatten the
    # [k, N] assignment grid so cumsum gives all first choices priority
    # over any second choice, etc.
    oh_k = jax.nn.one_hot(expert_k.T.reshape(k * N), E,
                          dtype=jnp.int32)                  # [k*N, E]
    pos = jnp.cumsum(oh_k, axis=0) * oh_k - 1               # [k*N, E]
    pos_in_expert = jnp.sum(pos * oh_k, axis=1)             # [k*N]
    keep = pos_in_expert < cap

    # per-choice dispatch one-hots [k*N, E, cap] → summed over choices to
    # the token-level dispatch [N, E, cap] (slots are disjoint, so the
    # sum stays one-hot); combine carries gate weights on the same slots
    slot_oh = jax.nn.one_hot(jnp.clip(pos_in_expert, 0, cap - 1), cap)
    disp_k = oh_k.astype(jnp.float32)[:, :, None] * slot_oh[:, None, :]
    disp_k = jnp.where(keep[:, None, None], disp_k, 0.0)
    disp_k = disp_k.reshape(k, N, E, cap)
    disp = jnp.sum(disp_k, axis=0)                          # [N, E, cap]
    combine = jnp.einsum("knec,nk->nec", disp_k, gate_k)

    # load-balance stats (Switch eq. 4 / GShard l_aux inputs): first
    # choices drive balance
    frac = jnp.mean(jax.nn.one_hot(expert_k[:, 0], E, dtype=jnp.float32),
                    axis=0)
    mean_p = jnp.mean(probs, axis=0)
    return disp, combine, frac, mean_p


def moe_ffn(params, x: jax.Array, cfg: MoEConfig,
            mesh: Optional[Mesh] = None) -> Tuple[jax.Array, jax.Array]:
    """Top-k MoE feed-forward (k=1: Switch; k=2: GShard-style top-2).

    x: [N, D] tokens (flatten batch*seq first) → (out [N, D], aux_loss).
    With a mesh carrying an ``expert`` axis, einsum operands get sharding
    constraints so dispatch/combine become all-to-alls over ICI.
    """
    N, D = x.shape
    E, k = cfg.num_experts, cfg.top_k
    if not 1 <= k <= E:
        raise ValueError(f"top_k={k} must be in [1, num_experts={E}]")
    cap = max(1, int(cfg.capacity_factor * k * N / E))
    disp, combine, frac, mean_p = _route(params, x, cfg, cap)

    # NOTE (round-4 finding): an int8 wire codec at these sharding
    # constraints is a NO-OP — compiled HLO shows the dispatch einsum
    # ("nec,nd->ecd", contracting the token-sharded axis) communicates
    # via fp32 partial all-reduces BEFORE any constraint-point quantize
    # runs. Quantized MoE dispatch lives in the explicit-collective form
    # instead: moe_ffn_a2a(..., wire_int8=True) below (round 5).
    def constrain(v, spec):
        if mesh is None or place.AXIS_EXPERT not in mesh.axis_names:
            return v
        return jax.lax.with_sharding_constraint(v, NamedSharding(mesh, spec))

    # expert inputs [E, cap, D] — the all-to-all boundary
    xe = jnp.einsum("nec,nd->ecd", disp, x.astype(jnp.float32))
    xe = constrain(xe, P(place.AXIS_EXPERT, None, None))
    h = jnp.einsum("ecd,edf->ecf", xe, params["w_in"])
    h = jax.nn.gelu(h)
    ye = jnp.einsum("ecf,efd->ecd", h, params["w_out"])
    ye = constrain(ye, P(place.AXIS_EXPERT, None, None))
    out = jnp.einsum("nec,ecd->nd", combine, ye)            # gate-weighted

    # load-balance aux loss (Switch eq. 4 / GShard l_aux): E * Σ_e
    # frac_first_choice_e * mean_prob_e
    aux = cfg.aux_loss_weight * E * jnp.sum(frac * mean_p)
    return out.astype(x.dtype), aux


def moe_ffn_a2a(params, x: jax.Array, cfg: MoEConfig, mesh: Mesh,
                wire_int8: bool = False) -> Tuple[jax.Array, jax.Array]:
    """MoE feed-forward in the explicit-collective form: shard_map over
    the ``expert`` axis with ``lax.all_to_all`` dispatch/combine.

    Tokens are sharded over the expert axis (x: [N, D] global, N/P per
    shard); capacity is per (expert, source shard) — GShard's layout:
    cap_s = ceil(cf·k·N_s/E) slots per expert from EACH source shard, so
    total expert capacity matches the einsum path but a shard cannot
    borrow another shard's unused slots (documented divergence; drop
    patterns differ only under imbalance).

    ``wire_int8``: the dispatch AND combine all-to-alls carry int8 +
    per-destination-block fp32 scales (ops/q8.make_all_to_all_q8) — half
    the ICI bytes of the bf16 wire, straight-through gradients through
    the codec. This is the form the round-4 HLO inspection demanded: the
    quantize runs BEFORE the collective, inside the shard, so s8 is what
    crosses the wire (asserted in tests/test_moe_pipeline.py).
    """
    from paddle_tpu.parallel.compat import shard_map

    ax = place.AXIS_EXPERT
    if ax not in mesh.axis_names:
        raise ValueError(f"mesh must carry an {ax!r} axis")
    pe = mesh.shape[ax]
    N, D = x.shape
    E, k = cfg.num_experts, cfg.top_k
    if not 1 <= k <= E:
        raise ValueError(f"top_k={k} must be in [1, num_experts={E}]")
    if E % pe or N % pe:
        raise ValueError(f"num_experts={E} and N={N} must both be "
                         f"divisible by the expert axis size {pe}")
    e_local, n_s = E // pe, N // pe
    cap_s = max(1, int(math.ceil(cfg.capacity_factor * k * n_s / E)))

    if wire_int8:
        from paddle_tpu.ops import q8 as ops_q8
        a2a = ops_q8.make_all_to_all_q8(ax)
    else:
        def a2a(v):
            return jax.lax.all_to_all(v, ax, 0, 0)

    def body(gate, w_in, w_out, xs):
        # xs: [n_s, D] local tokens; w_in/w_out: [e_local, ...] local
        disp, combine, frac, mean_p = _route(
            {"gate": gate}, xs, cfg, cap_s)
        xe = jnp.einsum("nec,nd->ecd", disp, xs.astype(jnp.float32))
        # leading axis = destination shard, then its local expert group
        xe = xe.reshape(pe, e_local, cap_s, D)
        xe = a2a(xe)                      # → leading axis = source shard
        xe = xe.transpose(1, 0, 2, 3).reshape(e_local, pe * cap_s, D)
        h = jax.nn.gelu(jnp.einsum("esd,edf->esf", xe, w_in))
        ye = jnp.einsum("esf,efd->esd", h, w_out)
        ye = ye.reshape(e_local, pe, cap_s, D).transpose(1, 0, 2, 3)
        ye = a2a(ye)                      # back to the source shards
        ye = ye.reshape(E, cap_s, D)
        out = jnp.einsum("nec,ecd->nd", combine, ye)
        # aux loss over GLOBAL balance stats (token means are equal-sized
        # per shard, so pmean == the einsum path's full-batch mean)
        frac_g = jax.lax.pmean(frac, ax)
        mean_p_g = jax.lax.pmean(mean_p, ax)
        aux = cfg.aux_loss_weight * E * jnp.sum(frac_g * mean_p_g)
        return out.astype(xs.dtype), aux

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(None, None), P(ax, None, None), P(ax, None, None),
                  P(ax, None)),
        out_specs=(P(ax, None), P()),
        check_vma=False)
    return fn(params["gate"], params["w_in"], params["w_out"], x)
