"""GSPMD distribution: sharding rules + jit integration.

Replaces the reference's entire parameter-server/data-parallel machinery
(pserver/ParameterServer2.h sync addGradient+doOperation, go/pserver SendGrad/
GetParam, MultiGradientMachine.h:44 thread-ring gather/scatter, nccl_op.cc
collectives) with in-graph XLA collectives: parameters/opt-state/feeds carry
``NamedSharding``s, ``jax.jit`` partitions the whole train step, and XLA
inserts the grad all-reduces over ICI — the scaling-book recipe (mesh →
annotate → let the compiler place collectives).

Axes follow core.place: data (DP), model (TP), seq (SP/CP), expert (EP),
stage (PP). A DistConfig holds the mesh plus regex→PartitionSpec rules for
parameters; anything unmatched is replicated (pure DP). Batch-norm under
GSPMD becomes synced-BN for free — the batch mean is a global reduction.

ZeRO (``zero_stage=1..3`` / ``data_parallel(zero=N)``): pure-DP replicates
every unmatched parameter AND its optimizer state on every chip, and every
replica then applies the identical weight update. Following "Automatic
Cross-Replica Sharding of Weight Update in Data-Parallel Training" (Xu et
al.), the fix here is only sharding annotations, staged:

- **Stage 1** — optimizer-state leaves of replicated parameters lay out
  over the ``data`` axis (largest dim divisible by the axis size;
  tiny/indivisible leaves stay replicated — ``zero_report()`` says which
  and why), and the trainer constrains grads/params/updated-params around
  ``opt.update`` so XLA rewrites the gradient all-reduce into
  reduce-scatter + sharded update + post-update all-gather.
- **Stage 2** — gradients take the same ``zero_spec`` layout as first-class
  policy (``grad_shardings``): the grad at the update boundary is committed
  to its 1/N shard and the grad-accumulation scan carry rides sharded, so
  each microbatch reduce-scatters INTO the shard instead of materializing
  a full replicated gradient between microbatches. (Gradients are
  step-transients in the jitted design — no persistent grad buffer exists
  in the plain path, so stage 2's resident-memory bite is the accumulator;
  the plain-path program is identical to stage 1's, which already reduces
  at the update boundary.)
- **Stage 3** — parameters are STORED in the ``zero_spec`` layout
  (``store_shardings``; the jit inputs/outputs are 1/N shards) and
  all-gathered on use: the trainer constrains them to their compute layout
  (replicated / TP) inside the step, XLA inserts one on-use all-gather per
  leaf and schedules it under earlier compute (the prefetch), and the
  backward of that gather IS a reduce-scatter — no full gradient and no
  resident full parameter exist anywhere. The post-update all-gather of
  stages 1-2 disappears (updated params stay sharded).

Memory: Adam's 2× param-bytes of state (plus the fp32 update math) drops
to ~1/axis-size per chip at stage 1, gradients follow at stage 2, and
parameters at stage 3 (param+grad+state → ~1/N). Numerics are unchanged at
every stage (the same sums, distributed).

Multi-slice meshes (an outer ``dcn`` axis from ``distributed.hybrid_mesh``)
keep the ZeRO shard axis at ``batch_axis`` (the ICI ring inside a slice):
the batch shards over BOTH axes, gradients reduce-scatter over ICI, and
only the 1/N-sharded grads cross DCN (a shard-sized all-reduce over
``dcn``) — the hierarchical rewrite ``benchmarks/scaling_aot.py
--zero2/--zero3`` proves on the deviceless XLA:TPU multi-slice pipeline.
"""

import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core import place


@dataclasses.dataclass
class DistConfig:
    """Distribution plan for a training/inference step."""
    mesh: Mesh
    # [(param-name regex, PartitionSpec)] first match wins; unmatched -> replicated
    param_rules: Sequence[Tuple[str, P]] = ()
    batch_axis: str = place.AXIS_DATA
    # 0 = replicate optimizer state (classic DP); 1 = shard the optimizer
    # state and weight update of replicated params over batch_axis (ZeRO-1);
    # 2 = gradients/accumulators take the same layout (ZeRO-2); 3 = params
    # are stored sharded and all-gathered on use (ZeRO-3)
    zero_stage: int = 0
    # leaves with fewer elements than this stay replicated under zero>=1
    # (sharding a bias saves nothing and adds collective latency); 0 shards
    # everything divisible
    zero_min_size: int = 0

    def param_spec(self, name: str, ndim: int) -> P:
        """First matching rule wins; rules whose spec rank exceeds the
        array's rank are skipped (a regex that catches both 'fc.w' and
        'fc.b' should not try to lay a rank-2 spec onto the bias)."""
        for pattern, spec in self.param_rules:
            if re.search(pattern, name) and len(spec) <= ndim:
                return spec
        return P()  # replicated

    def param_sharding(self, name: str, arr) -> NamedSharding:
        return NamedSharding(self.mesh, self.param_spec(name, np.ndim(arr)))

    def dcn_axis(self) -> Optional[str]:
        """The cross-slice mesh axis, when this mesh carries one
        (``distributed.hybrid_mesh`` names it ``dcn``). The batch then
        shards over BOTH axes while the ZeRO shard axis stays
        ``batch_axis`` (the ICI ring inside one slice) — so every
        ZeRO collective over ``dcn`` moves only 1/N-sharded tensors
        (the hierarchical rewrite)."""
        names = tuple(getattr(self.mesh, "axis_names", ()))
        if "dcn" in names and self.batch_axis != "dcn":
            return "dcn"
        return None

    def batch_sharding(self) -> NamedSharding:
        """Axis-0 sharding for every feed leaf (batch dim); on a
        multi-slice mesh the batch shards over (dcn, batch_axis) —
        pure DP across the pod."""
        d = self.dcn_axis()
        spec = P((d, self.batch_axis)) if d else P(self.batch_axis)
        return NamedSharding(self.mesh, spec)

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    # -- pytree helpers ----------------------------------------------------
    def shard_params(self, params: Dict) -> Dict:
        """Place params in their STORED layout: the rule/TP layout, or
        the 1/N ``zero_spec`` shard under ``zero_stage>=3``."""
        return {k: jax.device_put(v, self.store_sharding(k, v))
                for k, v in params.items()}

    def param_shardings(self, params: Dict) -> Dict:
        """The COMPUTE layout of each param (rule-matched or replicated)
        — what the forward/backward consume; under ``zero_stage=3`` the
        trainer constrains stored shards to this inside the step (the
        on-use all-gather)."""
        return {k: self.param_sharding(k, v) for k, v in params.items()}

    # -- ZeRO policy -------------------------------------------------------
    def zero_axis_size(self) -> int:
        """Size of the ZeRO shard axis. On a multi-slice mesh this is
        the ICI ``batch_axis`` only — the ``dcn`` axis never divides the
        shard (hierarchical: slices keep replica copies of the 1/N
        shards, and cross-slice traffic is shard-sized)."""
        return int(dict(self.mesh.shape).get(self.batch_axis, 1))

    def _zero_dim(self, shape) -> Optional[int]:
        """The dim a replicated leaf shards over ``batch_axis`` under
        zero=1: the LARGEST dim divisible by the axis size (ties → first).
        None when the leaf is a scalar, too tiny (``zero_min_size``), or
        no dim divides — those stay replicated (see ``zero_report``)."""
        n = self.zero_axis_size()
        if n <= 1 or not shape:
            return None
        if int(np.prod(shape)) < self.zero_min_size:
            return None
        best = None
        for d, size in enumerate(shape):
            if size and size % n == 0:
                if best is None or size > shape[best]:
                    best = d
        return best

    def zero_spec(self, name: str, shape) -> P:
        """Update-time PartitionSpec of one replicated-param leaf under
        zero>=1 (``P()`` when it stays replicated). Leaves of params
        matched by a TP rule are NOT zero-eligible — their state already
        shards like the param."""
        if self.zero_stage < 1:
            return P()
        if self.param_spec(name, len(shape)) != P():
            return self.param_spec(name, len(shape))
        d = self._zero_dim(tuple(shape))
        if d is None:
            return P()
        return P(*([None] * d + [self.batch_axis]))

    def zero_update_shardings(self, params: Dict) -> Dict:
        """{name: NamedSharding} for the UPDATE-time layout of grads and
        params: ZeRO-sharded for replicated params, the param's own
        sharding otherwise. The trainer constrains grads/params to this
        around ``opt.update`` so XLA turns the grad all-reduce into
        reduce-scatter and (below stage 3) all-gathers the updated
        params afterwards."""
        return {k: NamedSharding(self.mesh, self.zero_spec(k, np.shape(v)))
                for k, v in params.items()}

    def grad_spec(self, name: str, shape, accum: bool = False) -> P:
        """Layout of the longest-lived gradient object of one param:
        the ``zero_spec`` 1/N shard at stage>=2 — and for the
        grad-accumulation scan carry already at stage>=1, where the
        carry rides sharded so each microbatch reduce-scatters into it
        — else the param's own layout (full for pure DP)."""
        if self.zero_stage >= 2 or (accum and self.zero_stage >= 1):
            return self.zero_spec(name, tuple(shape))
        return self.param_spec(name, len(shape))

    def grad_shardings(self, params: Dict, accum: bool = False) -> Dict:
        return {k: NamedSharding(self.mesh,
                                 self.grad_spec(k, np.shape(v), accum))
                for k, v in params.items()}

    def store_spec(self, name: str, shape) -> P:
        """The STORED (between-steps resident) layout of one param:
        ``zero_spec`` at stage 3 (params live as 1/N shards and are
        all-gathered on use), the compute layout otherwise."""
        if self.zero_stage >= 3:
            return self.zero_spec(name, tuple(shape))
        return self.param_spec(name, len(shape))

    def store_sharding(self, name: str, arr) -> NamedSharding:
        return NamedSharding(self.mesh,
                             self.store_spec(name, np.shape(arr)))

    def store_shardings(self, params: Dict) -> Dict:
        return {k: self.store_sharding(k, v) for k, v in params.items()}

    def _zero_classify(self, params: Dict) -> Tuple[Dict, Dict]:
        """(sharded, replicated) per-leaf decisions of the zero_spec
        policy, with the reason each replicated leaf stays replicated."""
        n = self.zero_axis_size()
        sharded, replicated = {}, {}
        for k, v in params.items():
            shape = tuple(np.shape(v))
            if self.param_spec(k, len(shape)) != P():
                replicated[k] = "matched param rule (state mirrors param)"
                continue
            d = self._zero_dim(shape)
            if d is not None:
                sharded[k] = {"dim": d, "shape": list(shape),
                              "shard_shape": [
                                  s // n if i == d else s
                                  for i, s in enumerate(shape)]}
            elif not shape:
                replicated[k] = "scalar"
            elif int(np.prod(shape)) < self.zero_min_size:
                replicated[k] = (f"tiny ({int(np.prod(shape))} < "
                                 f"zero_min_size={self.zero_min_size})")
            else:
                replicated[k] = (f"no dim of {list(shape)} divisible by "
                                 f"{self.batch_axis}={n}")
        return sharded, replicated

    def zero_report(self, params: Dict) -> Dict:
        """What the configured zero stage does to each param, per leaf —
        the debug trail for "why didn't my memory drop by 1/N". The
        top-level ``sharded``/``replicated`` keys are the optimizer-state
        view (stage>=1); ``grads`` and ``params`` carry the same per-leaf
        decisions for gradient accumulators (stage>=2) and stored
        parameters (stage 3), or name the stage gate that keeps every
        leaf in its param layout."""
        n = self.zero_axis_size()
        sharded, replicated = self._zero_classify(params)
        stage = self.zero_stage

        def view(active, gate_msg):
            if active:
                return {"sharded": sharded, "replicated": replicated}
            return {"sharded": {},
                    "replicated": {k: gate_msg for k in params}}

        return {"zero_stage": stage, "axis": self.batch_axis,
                "axis_size": n, "dcn_axis": self.dcn_axis(),
                "sharded": sharded if stage >= 1 else {},
                "replicated": replicated if stage >= 1
                else {k: "zero_stage<1 (state mirrors param layout)"
                      for k in params},
                "grads": view(stage >= 2,
                              "zero_stage<2 (grads keep param layout; "
                              "the accum carry still rides sharded at "
                              "stage 1)"),
                "params": view(stage >= 3,
                               "zero_stage<3 (params stored in compute "
                               "layout)")}

    def state_shardings(self, state: Dict) -> Dict:
        """Optimizer/model state mirrors its parameter's sharding: entries
        are keyed by param name with array/tuple values of the param's shape
        (scalars replicate). Under ``zero_stage>=1`` the state leaves of
        replicated (pure-DP) params instead lay out over ``batch_axis``
        (``zero_spec``) — the ZeRO-1 optimizer-state shard."""
        out = {}
        for k, v in state.items():
            if self.zero_stage >= 1:
                out[k] = jax.tree.map(
                    lambda leaf: NamedSharding(
                        self.mesh, self.zero_spec(k, np.shape(leaf))), v)
            else:
                out[k] = jax.tree.map(
                    lambda leaf: NamedSharding(
                        self.mesh, self.param_spec(k, np.ndim(leaf))),
                    v)
        return out

    def feed_shardings(self, feeds) -> object:
        bs = self.batch_sharding()
        return jax.tree.map(lambda leaf: bs, feeds)


def data_parallel(mesh: Optional[Mesh] = None, zero: int = 0) -> DistConfig:
    """Pure DP: replicate params, shard batch (the MultiGradientMachine +
    pserver replacement). ``zero=1`` shards the optimizer state and weight
    update over the data axis (ZeRO-1), ``zero=2`` the gradient
    accumulators too, ``zero=3`` the stored parameters with on-use
    all-gather — see the module docstring."""
    return DistConfig(mesh or place.default_mesh(), zero_stage=zero)


def zero_constrained_update(dist: DistConfig, opt, step, grads, params,
                            opt_state, update_shardings=None,
                            keep_shardings=None, state_shardings=None):
    """The ZeRO graph transform around one optimizer update, as pure
    sharding constraints (trace-time; call inside the jitted step):

        grads/params  → update layout (replicated params slice over
                        ``data`` — XLA rewrites their grad all-reduce
                        into reduce-scatter)
        opt.update    → runs elementwise on 1/N-size shards
        new params    → back to the STORED layout: the serving layout
                        below stage 3 (all-gather), the 1/N shard at
                        stage 3 (no post-update all-gather exists)
        new opt state → pinned to the sharded layout

    The three sharding dicts can be passed precomputed (the trainer
    builds them once at step-build time); they default to the config's
    own policy. With ``zero_stage<1`` this is exactly ``opt.update``."""
    if dist is None or dist.zero_stage < 1:
        return opt.update(step, grads, params, opt_state)
    wsc = jax.lax.with_sharding_constraint
    upd = update_shardings or dist.zero_update_shardings(params)
    keep = keep_shardings or dist.store_shardings(params)
    st = state_shardings or dist.state_shardings(opt_state)
    grads = wsc(grads, upd)
    params = wsc(params, upd)
    opt_state = wsc(opt_state, st)
    new_params, new_opt = opt.update(step, grads, params, opt_state)
    return wsc(new_params, keep), wsc(new_opt, st)


def data_model_parallel(mesh: Mesh, tp_rules: Sequence[Tuple[str, P]]
                        ) -> DistConfig:
    """DP x TP over a 2-D mesh (the parallel_nn slot, done as real tensor
    parallelism — reference: ParallelNeuralNetwork.h:34 placed whole layers
    on devices; here single layers shard across the model axis)."""
    return DistConfig(mesh, tp_rules)


# ZeRO-1 HLO evidence -------------------------------------------------------

_HLO_SIZE = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4, "s8": 1,
             "u8": 1, "pred": 1, "f64": 8}

# XLA:TPU lowers reduce-scatter to a kCustom fusion whose computation is
# named *reduce-scatter* — the one matcher shared by the zero-contract
# classifier below and benchmarks/scaling_aot.py's schedule analyzer
FUSED_REDUCE_SCATTER_RE = re.compile(
    r"kind=kCustom.*calls=%?[\w.\-]*reduce-scatter")


def _hlo_shape_bytes(sig: str) -> int:
    """Bytes of the result shape(s) in an HLO op line prefix like
    'f32[256,128]{1,0}' (tile/memory annotations tolerated)."""
    total = 0
    for m in re.finditer(r"(\w+)\[([\d,]*)\]", sig):
        dt, dims = m.group(1), m.group(2)
        if dt not in _HLO_SIZE:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _HLO_SIZE[dt]
    return total


# consumer opcodes that only move data — classification follows through
# them to the real consumer (a post-SPMD CPU all-gather is usually read
# via a layout copy; async collectives via their -done op)
_TRANSPARENT_OPS = frozenset((
    "copy", "bitcast", "bitcast-convert", "get-tuple-element",
    "all-gather-done", "all-reduce-done", "reduce-scatter-done",
    "optimization-barrier"))


def zero_collective_evidence(hlo_text: str, min_bytes: int) -> Dict:
    """Classify a compiled (post-SPMD) module's collectives for the
    ZeRO contracts. ``min_bytes`` separates gradient/param-sized
    collectives from scalar bookkeeping (loss means, clip norms): pass
    the largest replicated param's nbytes. NOTE the module is
    per-device-shaped post-SPMD, so callers must size the model so that
    per-device feed/state leaves stay under ``min_bytes``.

    Returns counts, accepting every lowering XLA actually emits:
    - ``reduce_scatter``: literal ``reduce-scatter`` ops; XLA:TPU's fused
      form (a kCustom fusion calling a computation named
      ``*reduce-scatter*`` — its INTERNAL full-size all-reduce is part of
      the collective, not a grad sync); and XLA:CPU's manual form (the
      CPU pipeline lacks the reduce-scatter-creator pass, so the
      partitioner leaves an all-reduce ≥ min_bytes whose every consumer
      immediately slices it to a fraction of its size).
    - ``param_all_gather``: all-gathers ≥ min_bytes (sync or async
      ``all-gather-start``), split into
      ``on_use_all_gather`` — consumed by compute: the stage-3
      gather-on-use form — and ``output_all_gather`` — flowing only to
      the module output: the stage-1/2 post-update regather. Stage 3's
      "only on-use all-gathers" contract is ``output_all_gather == 0``.
    - ``full_grad_all_reduce``: all-reduces ≥ min_bytes consumed at full
      size — the classic DP gradient sync ZeRO must eliminate at every
      stage (the stage>=2 contract extends it to the accumulation path).
    - ``resident_full_args``: ENTRY parameters ≥ min_bytes — stage 3's
      "no replicated resident parameter" is ``resident_full_args == 0``
      (a zero-sharded param enters at 1/N of ``min_bytes``).
    """
    # split the module into computations; ops inside a *reduce-scatter*
    # computation body are the collective's own implementation
    comp_re = re.compile(r"^\s*(?:ENTRY\s+)?%?([\w.\-]+)\s*\([^)]*\)\s*->")
    op_re = re.compile(
        r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*(.*?)\b"
        r"(all-reduce-start|all-reduce|"
        r"reduce-scatter-start|reduce-scatter-done|reduce-scatter|"
        r"all-gather-start|all-gather-done|all-gather|parameter)\(")
    comp = None
    entry_comp = None
    lines = hlo_text.splitlines()
    comp_of = []
    for ln in lines:
        m = comp_re.match(ln)
        if m and "=" not in ln.split("(")[0]:
            comp = m.group(1)
            if ln.lstrip().startswith("ENTRY"):
                entry_comp = comp
        comp_of.append(comp)

    # op index + per-computation consumer map (def line excluded)
    def_line_re = re.compile(r"^\s*(ROOT\s+)?%([\w.\-]+)\s*=\s*(.*)$")
    opcode_re = re.compile(r"(?:^|\s)([a-z][\w\-]*)\(")
    ref_re = re.compile(r"%([\w.\-]+)\b")
    op_at = {}            # line idx -> (name, opcode, bytes, is_root)
    uses = {}             # (comp, name) -> [consumer line idx]
    for i, ln in enumerate(lines):
        m = def_line_re.match(ln)
        if not m:
            continue
        is_root, name, rhs = bool(m.group(1)), m.group(2), m.group(3)
        om = opcode_re.search(rhs)
        if not om:
            continue
        opcode = om.group(1)
        nbytes = _hlo_shape_bytes(rhs[:om.start()])
        op_at[i] = (name, opcode, nbytes, is_root)
        for ref in ref_re.findall(rhs[om.start():]):
            if ref != name:
                uses.setdefault((comp_of[i], ref), []).append(i)

    def consumers_of(cname, name):
        return [op_at[j] for j in uses.get((cname, name), ())
                if j in op_at]

    def gather_sink(cname, name, depth=0):
        """'use' when any (transitively, through data movers) consumer
        is compute; 'output' when the value only reaches the ENTRY
        ROOT/output tuple — the post-update regather of stages 1-2.
        A ROOT of a NON-entry computation returns the value to its
        caller (TPU wraps collectives in sub-computations), which is a
        use, not a module output."""
        if depth > 6:
            return "use"            # conservative: assume consumed
        sinks = set()
        cons = consumers_of(cname, name)
        if not cons:
            # no consumer: an entry op feeds the output directly; in a
            # sub-computation the value escapes through the caller
            return "output" if cname == entry_comp else "use"
        for (cn, opcode, _b, is_root) in cons:
            if opcode in _TRANSPARENT_OPS or (opcode == "tuple"
                                              and not is_root):
                sinks.add(gather_sink(cname, cn, depth + 1))
            elif opcode == "tuple" and is_root:
                sinks.add("output" if cname == entry_comp else "use")
            else:
                sinks.add("use")
        return "use" if "use" in sinks else "output"

    out = {"reduce_scatter": 0, "param_all_gather": 0,
           "on_use_all_gather": 0, "output_all_gather": 0,
           "resident_full_args": 0,
           "full_grad_all_reduce": 0, "full_grad_all_reduce_lines": []}
    big_ars = []          # (idx, name, bytes, comp)
    big_ags = []          # (idx, name, comp, kind, is_root)
    for i, ln in enumerate(lines):
        if "reduce-scatter" in (comp_of[i] or ""):
            continue
        m = op_re.match(ln)
        if not m:
            # the TPU fused collective: one call site per fusion
            if FUSED_REDUCE_SCATTER_RE.search(ln):
                out["reduce_scatter"] += 1
            continue
        name, sig, kind = m.groups()
        nbytes = _hlo_shape_bytes(sig)
        if kind == "parameter":
            if comp_of[i] == entry_comp and nbytes >= min_bytes:
                out["resident_full_args"] += 1
        elif kind in ("reduce-scatter", "reduce-scatter-start"):
            out["reduce_scatter"] += 1
        elif kind in ("all-gather-done", "reduce-scatter-done"):
            pass          # counted at its -start; sink follows through
        elif kind.startswith("all-gather") and nbytes >= min_bytes:
            # async start shape is the (operand, result) tuple: the
            # result alone clears min_bytes whenever the sync form would
            big_ags.append((i, name, comp_of[i], kind,
                            ln.lstrip().startswith("ROOT")))
        elif kind.startswith("all-reduce") and nbytes >= min_bytes:
            if kind == "all-reduce-start":
                nbytes //= 2      # async tuple shape: (operand, result)
            big_ars.append((i, name, nbytes, comp_of[i]))

    for i, name, cname, kind, is_root in big_ags:
        out["param_all_gather"] += 1
        sink = ("output" if is_root and cname == entry_comp
                else gather_sink(cname, name))
        out["on_use_all_gather" if sink == "use"
            else "output_all_gather"] += 1

    def _consumer_result_bytes(line):
        """Bytes of a consumer op's RESULT shape: the text between '='
        and the opcode token (tuple shapes contain parens, so a naive
        split at '(' would read 0 bytes and misclassify a full-size
        tuple consumer as a shard slice)."""
        if "=" not in line:
            return 0
        seg = line.split("=", 1)[1]
        m = re.search(r"\s[a-z][\w\-]*\(", seg)
        return _hlo_shape_bytes(seg[:m.start()] if m else seg)

    for i, name, nbytes, cname in big_ars:
        # consumers: ops in the same computation reading %name (exact
        # name via the uses map — a \b regex would also prefix-match
        # %name.1, polluting the consumer set)
        consumers = [lines[j] for j in uses.get((cname, name), ())
                     if j != i]
        sliced = bool(consumers) and all(
            0 < _consumer_result_bytes(c) * 2 <= nbytes
            for c in consumers if "=" in c)
        if sliced:
            out["reduce_scatter"] += 1     # CPU manual form
        else:
            out["full_grad_all_reduce"] += 1
            out["full_grad_all_reduce_lines"].append(
                lines[i].strip()[:200])
    return out


# Canonical TP rule helpers -------------------------------------------------

def fc_column_rule(pattern: str) -> Tuple[str, P]:
    """Shard an fc weight [in, out] on the out axis (column parallel)."""
    return (pattern, P(None, place.AXIS_MODEL))


def fc_row_rule(pattern: str) -> Tuple[str, P]:
    """Shard an fc weight [in, out] on the in axis (row parallel)."""
    return (pattern, P(place.AXIS_MODEL, None))


def embedding_vocab_rule(pattern: str) -> Tuple[str, P]:
    """Shard an embedding table [vocab, dim] across vocab — the
    sparse_remote_update slot (reference: RemoteParameterUpdater.h:265,
    rows sharded across pservers; here across the model axis, the gather's
    collective is XLA's problem)."""
    return (pattern, P(place.AXIS_MODEL, None))
