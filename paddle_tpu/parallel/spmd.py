"""GSPMD distribution: sharding rules + jit integration.

Replaces the reference's entire parameter-server/data-parallel machinery
(pserver/ParameterServer2.h sync addGradient+doOperation, go/pserver SendGrad/
GetParam, MultiGradientMachine.h:44 thread-ring gather/scatter, nccl_op.cc
collectives) with in-graph XLA collectives: parameters/opt-state/feeds carry
``NamedSharding``s, ``jax.jit`` partitions the whole train step, and XLA
inserts the grad all-reduces over ICI — the scaling-book recipe (mesh →
annotate → let the compiler place collectives).

Axes follow core.place: data (DP), model (TP), seq (SP/CP), expert (EP),
stage (PP). A DistConfig holds the mesh plus regex→PartitionSpec rules for
parameters; anything unmatched is replicated (pure DP). Batch-norm under
GSPMD becomes synced-BN for free — the batch mean is a global reduction.
"""

import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core import place


@dataclasses.dataclass
class DistConfig:
    """Distribution plan for a training/inference step."""
    mesh: Mesh
    # [(param-name regex, PartitionSpec)] first match wins; unmatched -> replicated
    param_rules: Sequence[Tuple[str, P]] = ()
    batch_axis: str = place.AXIS_DATA

    def param_spec(self, name: str, ndim: int) -> P:
        """First matching rule wins; rules whose spec rank exceeds the
        array's rank are skipped (a regex that catches both 'fc.w' and
        'fc.b' should not try to lay a rank-2 spec onto the bias)."""
        for pattern, spec in self.param_rules:
            if re.search(pattern, name) and len(spec) <= ndim:
                return spec
        return P()  # replicated

    def param_sharding(self, name: str, arr) -> NamedSharding:
        return NamedSharding(self.mesh, self.param_spec(name, np.ndim(arr)))

    def batch_sharding(self) -> NamedSharding:
        """Axis-0 sharding for every feed leaf (batch dim)."""
        return NamedSharding(self.mesh, P(self.batch_axis))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    # -- pytree helpers ----------------------------------------------------
    def shard_params(self, params: Dict) -> Dict:
        return {k: jax.device_put(v, self.param_sharding(k, v))
                for k, v in params.items()}

    def param_shardings(self, params: Dict) -> Dict:
        return {k: self.param_sharding(k, v) for k, v in params.items()}

    def state_shardings(self, state: Dict) -> Dict:
        """Optimizer/model state mirrors its parameter's sharding: entries
        are keyed by param name with array/tuple values of the param's shape
        (scalars replicate)."""
        out = {}
        for k, v in state.items():
            out[k] = jax.tree.map(
                lambda leaf: NamedSharding(
                    self.mesh, self.param_spec(k, np.ndim(leaf))),
                v)
        return out

    def feed_shardings(self, feeds) -> object:
        bs = self.batch_sharding()
        return jax.tree.map(lambda leaf: bs, feeds)


def data_parallel(mesh: Optional[Mesh] = None) -> DistConfig:
    """Pure DP: replicate params, shard batch (the MultiGradientMachine +
    pserver replacement)."""
    return DistConfig(mesh or place.default_mesh())


def data_model_parallel(mesh: Mesh, tp_rules: Sequence[Tuple[str, P]]
                        ) -> DistConfig:
    """DP x TP over a 2-D mesh (the parallel_nn slot, done as real tensor
    parallelism — reference: ParallelNeuralNetwork.h:34 placed whole layers
    on devices; here single layers shard across the model axis)."""
    return DistConfig(mesh, tp_rules)


# Canonical TP rule helpers -------------------------------------------------

def fc_column_rule(pattern: str) -> Tuple[str, P]:
    """Shard an fc weight [in, out] on the out axis (column parallel)."""
    return (pattern, P(None, place.AXIS_MODEL))


def fc_row_rule(pattern: str) -> Tuple[str, P]:
    """Shard an fc weight [in, out] on the in axis (row parallel)."""
    return (pattern, P(place.AXIS_MODEL, None))


def embedding_vocab_rule(pattern: str) -> Tuple[str, P]:
    """Shard an embedding table [vocab, dim] across vocab — the
    sparse_remote_update slot (reference: RemoteParameterUpdater.h:265,
    rows sharded across pservers; here across the model axis, the gather's
    collective is XLA's problem)."""
    return (pattern, P(place.AXIS_MODEL, None))
