"""GSPMD distribution: sharding rules + jit integration.

Replaces the reference's entire parameter-server/data-parallel machinery
(pserver/ParameterServer2.h sync addGradient+doOperation, go/pserver SendGrad/
GetParam, MultiGradientMachine.h:44 thread-ring gather/scatter, nccl_op.cc
collectives) with in-graph XLA collectives: parameters/opt-state/feeds carry
``NamedSharding``s, ``jax.jit`` partitions the whole train step, and XLA
inserts the grad all-reduces over ICI — the scaling-book recipe (mesh →
annotate → let the compiler place collectives).

Axes follow core.place: data (DP), model (TP), seq (SP/CP), expert (EP),
stage (PP). A DistConfig holds the mesh plus regex→PartitionSpec rules for
parameters; anything unmatched is replicated (pure DP). Batch-norm under
GSPMD becomes synced-BN for free — the batch mean is a global reduction.

ZeRO-1 (``zero_stage=1`` / ``data_parallel(zero=1)``): pure-DP replicates
every unmatched parameter AND its optimizer state on every chip, and every
replica then applies the identical weight update. Following "Automatic
Cross-Replica Sharding of Weight Update in Data-Parallel Training" (Xu et
al.), the fix here is only sharding annotations: optimizer-state leaves of
replicated parameters lay out over the ``data`` axis (largest dim divisible
by the axis size; tiny/indivisible leaves stay replicated —
``zero_report()`` says which and why), and the trainer constrains
grads/params/updated-params around ``opt.update`` so XLA rewrites the
gradient all-reduce into reduce-scatter + sharded update + post-update
all-gather. Memory: Adam's 2× param-bytes of state (plus the fp32 update
math) drops to ~1/axis-size per chip; numerics are unchanged (the same
sums, distributed).
"""

import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core import place


@dataclasses.dataclass
class DistConfig:
    """Distribution plan for a training/inference step."""
    mesh: Mesh
    # [(param-name regex, PartitionSpec)] first match wins; unmatched -> replicated
    param_rules: Sequence[Tuple[str, P]] = ()
    batch_axis: str = place.AXIS_DATA
    # 0 = replicate optimizer state (classic DP); 1 = shard the optimizer
    # state and weight update of replicated params over batch_axis (ZeRO-1)
    zero_stage: int = 0
    # leaves with fewer elements than this stay replicated under zero=1
    # (sharding a bias saves nothing and adds collective latency); 0 shards
    # everything divisible
    zero_min_size: int = 0

    def param_spec(self, name: str, ndim: int) -> P:
        """First matching rule wins; rules whose spec rank exceeds the
        array's rank are skipped (a regex that catches both 'fc.w' and
        'fc.b' should not try to lay a rank-2 spec onto the bias)."""
        for pattern, spec in self.param_rules:
            if re.search(pattern, name) and len(spec) <= ndim:
                return spec
        return P()  # replicated

    def param_sharding(self, name: str, arr) -> NamedSharding:
        return NamedSharding(self.mesh, self.param_spec(name, np.ndim(arr)))

    def batch_sharding(self) -> NamedSharding:
        """Axis-0 sharding for every feed leaf (batch dim)."""
        return NamedSharding(self.mesh, P(self.batch_axis))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    # -- pytree helpers ----------------------------------------------------
    def shard_params(self, params: Dict) -> Dict:
        return {k: jax.device_put(v, self.param_sharding(k, v))
                for k, v in params.items()}

    def param_shardings(self, params: Dict) -> Dict:
        return {k: self.param_sharding(k, v) for k, v in params.items()}

    # -- ZeRO-1 policy -----------------------------------------------------
    def zero_axis_size(self) -> int:
        return int(dict(self.mesh.shape).get(self.batch_axis, 1))

    def _zero_dim(self, shape) -> Optional[int]:
        """The dim a replicated leaf shards over ``batch_axis`` under
        zero=1: the LARGEST dim divisible by the axis size (ties → first).
        None when the leaf is a scalar, too tiny (``zero_min_size``), or
        no dim divides — those stay replicated (see ``zero_report``)."""
        n = self.zero_axis_size()
        if n <= 1 or not shape:
            return None
        if int(np.prod(shape)) < self.zero_min_size:
            return None
        best = None
        for d, size in enumerate(shape):
            if size and size % n == 0:
                if best is None or size > shape[best]:
                    best = d
        return best

    def zero_spec(self, name: str, shape) -> P:
        """Update-time PartitionSpec of one replicated-param leaf under
        zero=1 (``P()`` when it stays replicated). Leaves of params
        matched by a TP rule are NOT zero-eligible — their state already
        shards like the param."""
        if self.zero_stage < 1:
            return P()
        if self.param_spec(name, len(shape)) != P():
            return self.param_spec(name, len(shape))
        d = self._zero_dim(tuple(shape))
        if d is None:
            return P()
        return P(*([None] * d + [self.batch_axis]))

    def zero_update_shardings(self, params: Dict) -> Dict:
        """{name: NamedSharding} for the UPDATE-time layout of grads and
        params: ZeRO-sharded for replicated params, the param's own
        sharding otherwise. The trainer constrains grads/params to this
        around ``opt.update`` so XLA turns the grad all-reduce into
        reduce-scatter and all-gathers the updated params afterwards."""
        return {k: NamedSharding(self.mesh, self.zero_spec(k, np.shape(v)))
                for k, v in params.items()}

    def zero_report(self, params: Dict) -> Dict:
        """What zero=1 does to each param's optimizer state: which leaves
        shard (and on which dim), which stay replicated and why —
        the debug trail for "why didn't my memory drop by 1/N"."""
        n = self.zero_axis_size()
        sharded, replicated = {}, {}
        for k, v in params.items():
            shape = tuple(np.shape(v))
            if self.param_spec(k, len(shape)) != P():
                replicated[k] = "matched param rule (state mirrors param)"
                continue
            d = self._zero_dim(shape)
            if d is not None:
                sharded[k] = {"dim": d, "shape": list(shape),
                              "shard_shape": [
                                  s // n if i == d else s
                                  for i, s in enumerate(shape)]}
            elif not shape:
                replicated[k] = "scalar"
            elif int(np.prod(shape)) < self.zero_min_size:
                replicated[k] = (f"tiny ({int(np.prod(shape))} < "
                                 f"zero_min_size={self.zero_min_size})")
            else:
                replicated[k] = (f"no dim of {list(shape)} divisible by "
                                 f"{self.batch_axis}={n}")
        return {"zero_stage": self.zero_stage, "axis": self.batch_axis,
                "axis_size": n, "sharded": sharded,
                "replicated": replicated}

    def state_shardings(self, state: Dict) -> Dict:
        """Optimizer/model state mirrors its parameter's sharding: entries
        are keyed by param name with array/tuple values of the param's shape
        (scalars replicate). Under ``zero_stage>=1`` the state leaves of
        replicated (pure-DP) params instead lay out over ``batch_axis``
        (``zero_spec``) — the ZeRO-1 optimizer-state shard."""
        out = {}
        for k, v in state.items():
            if self.zero_stage >= 1:
                out[k] = jax.tree.map(
                    lambda leaf: NamedSharding(
                        self.mesh, self.zero_spec(k, np.shape(leaf))), v)
            else:
                out[k] = jax.tree.map(
                    lambda leaf: NamedSharding(
                        self.mesh, self.param_spec(k, np.ndim(leaf))),
                    v)
        return out

    def feed_shardings(self, feeds) -> object:
        bs = self.batch_sharding()
        return jax.tree.map(lambda leaf: bs, feeds)


def data_parallel(mesh: Optional[Mesh] = None, zero: int = 0) -> DistConfig:
    """Pure DP: replicate params, shard batch (the MultiGradientMachine +
    pserver replacement). ``zero=1`` shards the optimizer state and weight
    update over the data axis (ZeRO-1 — see the module docstring)."""
    return DistConfig(mesh or place.default_mesh(), zero_stage=zero)


def zero_constrained_update(dist: DistConfig, opt, step, grads, params,
                            opt_state, update_shardings=None,
                            keep_shardings=None, state_shardings=None):
    """The ZeRO-1 graph transform around one optimizer update, as pure
    sharding constraints (trace-time; call inside the jitted step):

        grads/params  → update layout (replicated params slice over
                        ``data`` — XLA rewrites their grad all-reduce
                        into reduce-scatter)
        opt.update    → runs elementwise on 1/N-size shards
        new params    → back to the serving layout (all-gather)
        new opt state → pinned to the sharded layout

    The three sharding dicts can be passed precomputed (the trainer
    builds them once at step-build time); they default to the config's
    own policy. With ``zero_stage<1`` this is exactly ``opt.update``."""
    if dist is None or dist.zero_stage < 1:
        return opt.update(step, grads, params, opt_state)
    wsc = jax.lax.with_sharding_constraint
    upd = update_shardings or dist.zero_update_shardings(params)
    keep = keep_shardings or dist.param_shardings(params)
    st = state_shardings or dist.state_shardings(opt_state)
    grads = wsc(grads, upd)
    params = wsc(params, upd)
    opt_state = wsc(opt_state, st)
    new_params, new_opt = opt.update(step, grads, params, opt_state)
    return wsc(new_params, keep), wsc(new_opt, st)


def data_model_parallel(mesh: Mesh, tp_rules: Sequence[Tuple[str, P]]
                        ) -> DistConfig:
    """DP x TP over a 2-D mesh (the parallel_nn slot, done as real tensor
    parallelism — reference: ParallelNeuralNetwork.h:34 placed whole layers
    on devices; here single layers shard across the model axis)."""
    return DistConfig(mesh, tp_rules)


# ZeRO-1 HLO evidence -------------------------------------------------------

_HLO_SIZE = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4, "s8": 1,
             "u8": 1, "pred": 1, "f64": 8}

# XLA:TPU lowers reduce-scatter to a kCustom fusion whose computation is
# named *reduce-scatter* — the one matcher shared by the zero-contract
# classifier below and benchmarks/scaling_aot.py's schedule analyzer
FUSED_REDUCE_SCATTER_RE = re.compile(
    r"kind=kCustom.*calls=%?[\w.\-]*reduce-scatter")


def _hlo_shape_bytes(sig: str) -> int:
    """Bytes of the result shape(s) in an HLO op line prefix like
    'f32[256,128]{1,0}' (tile/memory annotations tolerated)."""
    total = 0
    for m in re.finditer(r"(\w+)\[([\d,]*)\]", sig):
        dt, dims = m.group(1), m.group(2)
        if dt not in _HLO_SIZE:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _HLO_SIZE[dt]
    return total


def zero_collective_evidence(hlo_text: str, min_bytes: int) -> Dict:
    """Classify a compiled (post-SPMD) module's collectives for the
    ZeRO-1 contract — "the grad all-reduce became reduce-scatter + a
    post-update all-gather". ``min_bytes`` separates gradient/param-sized
    collectives from scalar bookkeeping (loss means, clip norms): pass
    the largest replicated param's nbytes.

    Counts three things, accepting every lowering XLA actually emits:
    - ``reduce_scatter``: literal ``reduce-scatter`` ops; XLA:TPU's fused
      form (a kCustom fusion calling a computation named
      ``*reduce-scatter*`` — its INTERNAL full-size all-reduce is part of
      the collective, not a grad sync); and XLA:CPU's manual form (the
      CPU pipeline lacks the reduce-scatter-creator pass, so the
      partitioner leaves an all-reduce ≥ min_bytes whose every consumer
      immediately slices it to a fraction of its size).
    - ``param_all_gather``: all-gathers ≥ min_bytes (the updated-param
      regather).
    - ``full_grad_all_reduce``: all-reduces ≥ min_bytes consumed at full
      size — the classic DP gradient sync ZeRO-1 must eliminate.
    """
    # split the module into computations; ops inside a *reduce-scatter*
    # computation body are the collective's own implementation
    comp_re = re.compile(r"^\s*(?:ENTRY\s+)?%?([\w.\-]+)\s*\([^)]*\)\s*->")
    op_re = re.compile(
        r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*(.*?)\b"
        r"(all-reduce-start|all-reduce|reduce-scatter|all-gather)\(")
    comp = None
    lines = hlo_text.splitlines()
    comp_of = []
    for ln in lines:
        m = comp_re.match(ln)
        if m and "=" not in ln.split("(")[0]:
            comp = m.group(1)
        comp_of.append(comp)
    out = {"reduce_scatter": 0, "param_all_gather": 0,
           "full_grad_all_reduce": 0, "full_grad_all_reduce_lines": []}
    big_ars = []          # (idx, name, bytes, comp)
    for i, ln in enumerate(lines):
        if "reduce-scatter" in (comp_of[i] or ""):
            continue
        m = op_re.match(ln)
        if not m:
            # the TPU fused collective: one call site per fusion
            if FUSED_REDUCE_SCATTER_RE.search(ln):
                out["reduce_scatter"] += 1
            continue
        name, sig, kind = m.groups()
        nbytes = _hlo_shape_bytes(sig)
        if kind == "reduce-scatter":
            out["reduce_scatter"] += 1
        elif kind == "all-gather" and nbytes >= min_bytes:
            out["param_all_gather"] += 1
        elif kind.startswith("all-reduce") and nbytes >= min_bytes:
            if kind == "all-reduce-start":
                nbytes //= 2      # async tuple shape: (operand, result)
            big_ars.append((i, name, nbytes, comp_of[i]))
    def _consumer_result_bytes(line):
        """Bytes of a consumer op's RESULT shape: the text between '='
        and the opcode token (tuple shapes contain parens, so a naive
        split at '(' would read 0 bytes and misclassify a full-size
        tuple consumer as a shard slice)."""
        if "=" not in line:
            return 0
        seg = line.split("=", 1)[1]
        m = re.search(r"\s[a-z][\w\-]*\(", seg)
        return _hlo_shape_bytes(seg[:m.start()] if m else seg)

    for i, name, nbytes, cname in big_ars:
        # consumers: later lines in the same computation using %name
        ref = re.compile(r"%" + re.escape(name) + r"\b")
        consumers = [lines[j] for j in range(len(lines))
                     if j != i and comp_of[j] == cname
                     and ref.search(lines[j])]
        sliced = bool(consumers) and all(
            0 < _consumer_result_bytes(c) * 2 <= nbytes
            for c in consumers if "=" in c)
        if sliced:
            out["reduce_scatter"] += 1     # CPU manual form
        else:
            out["full_grad_all_reduce"] += 1
            out["full_grad_all_reduce_lines"].append(
                lines[i].strip()[:200])
    return out


# Canonical TP rule helpers -------------------------------------------------

def fc_column_rule(pattern: str) -> Tuple[str, P]:
    """Shard an fc weight [in, out] on the out axis (column parallel)."""
    return (pattern, P(None, place.AXIS_MODEL))


def fc_row_rule(pattern: str) -> Tuple[str, P]:
    """Shard an fc weight [in, out] on the in axis (row parallel)."""
    return (pattern, P(place.AXIS_MODEL, None))


def embedding_vocab_rule(pattern: str) -> Tuple[str, P]:
    """Shard an embedding table [vocab, dim] across vocab — the
    sparse_remote_update slot (reference: RemoteParameterUpdater.h:265,
    rows sharded across pservers; here across the model axis, the gather's
    collective is XLA's problem)."""
    return (pattern, P(place.AXIS_MODEL, None))
