"""shard_map across JAX versions: new releases expose ``jax.shard_map``
with ``check_vma=``; older ones ship
``jax.experimental.shard_map.shard_map`` whose equivalent kwarg is
``check_rep=``. Every shard_map call in this package goes through here."""


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    try:
        from jax import shard_map as _sm
        kw = {} if check_vma is None else {"check_vma": check_vma}
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm
        kw = {} if check_vma is None else {"check_rep": check_vma}
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)
