"""Pipeline parallelism over the ``stage`` mesh axis (GPipe schedule).

The modern occupant of the reference's per-layer device placement slot
(SURVEY.md §2.3 — ParallelNeuralNetwork's parallel_nn layer->device
dispatch): the network is cut into S stages with identical signatures;
each device on the ``stage`` axis holds one stage's weights; microbatches
flow through the ring via ``lax.ppermute`` under one ``shard_map``.

Schedule: T = M + S - 1 scanned steps (GPipe fill/drain bubble); step t has
stage s working on microbatch t - s. The scan is reverse-differentiable, so
the same program trains — XLA stitches the backward pipeline automatically
(activations rematerialize per jax.checkpoint policy if requested).
"""

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.core import place


def pipeline_apply(stage_params, x: jax.Array, stage_fn: Callable,
                   mesh: Mesh, num_microbatches: int,
                   stage_axis: str = place.AXIS_STAGE) -> jax.Array:
    """Run ``stage_fn`` S times (once per stage) as a pipeline.

    stage_params: pytree whose leaves have a leading stage dim [S, ...];
    x: [B, ...] with B divisible by num_microbatches; stage_fn(params_s, mb)
    must map [mb, ...] -> [mb, ...] (same shape/dtype — residual stages).
    Returns [B, ...] equal to applying the stages sequentially.
    """
    from jax import shard_map

    S = mesh.shape[stage_axis]
    M = num_microbatches
    B = x.shape[0]
    assert B % M == 0, f"batch {B} not divisible by {M} microbatches"
    mb = B // M
    xs = x.reshape((M, mb) + x.shape[1:])

    param_specs = jax.tree_util.tree_map(
        lambda leaf: P(stage_axis), stage_params)

    def run(params_local, xs_all):
        # params_local leaves: [1, ...] (this stage's slice); drop the dim
        p_here = jax.tree_util.tree_map(lambda l: l[0], params_local)
        idx = jax.lax.axis_index(stage_axis)
        nst = jax.lax.psum(1, stage_axis)
        perm = [(i, (i + 1) % S) for i in range(S)]

        def step(carry, t):
            state, outs = carry
            # stage 0 injects microbatch t (clamped; masked later)
            mb_idx = jnp.clip(t, 0, M - 1)
            inj = jax.lax.dynamic_index_in_dim(xs_all, mb_idx, 0,
                                               keepdims=False)
            cur = jnp.where(idx == 0, inj, state)
            out = stage_fn(p_here, cur)
            # last stage completes microbatch t - (S-1)
            done = t - (nst - 1)
            valid = (idx == nst - 1) & (done >= 0) & (done < M)
            outs = jax.lax.cond(
                valid,
                lambda o: jax.lax.dynamic_update_index_in_dim(
                    o, out, jnp.clip(done, 0, M - 1), 0),
                lambda o: o, outs)
            state = jax.lax.ppermute(out, stage_axis, perm)
            return (state, outs), None

        state0 = jnp.zeros_like(xs_all[0])
        outs0 = jnp.zeros_like(xs_all)
        (_, outs), _ = jax.lax.scan(step, (state0, outs0),
                                    jnp.arange(M + S - 1))
        # only the last stage holds real outputs; broadcast via psum
        outs = jax.lax.psum(
            jnp.where(idx == nst - 1, outs, jnp.zeros_like(outs)),
            stage_axis)
        return outs

    specs_x = P()          # microbatches replicated; only stage 0 reads them
    outs = shard_map(run, mesh=mesh,
                     in_specs=(param_specs, specs_x),
                     out_specs=P(), check_vma=False)(stage_params, xs)
    return outs.reshape((B,) + x.shape[1:])


def sequential_apply(stage_params, x: jax.Array,
                     stage_fn: Callable) -> jax.Array:
    """Reference semantics: apply the S stages one after another."""
    def body(h, p_s):
        return stage_fn(p_s, h), None

    out, _ = jax.lax.scan(body, x, stage_params)
    return out
