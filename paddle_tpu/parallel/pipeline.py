"""Pipeline parallelism over the ``stage`` mesh axis (GPipe + interleaved).

The modern occupant of the reference's per-layer device placement slot
(SURVEY.md §2.3 — ParallelNeuralNetwork's parallel_nn layer->device
dispatch): the network is cut into S stages with identical signatures;
each device on the ``stage`` axis holds one stage's weights; microbatches
flow through the ring via ``lax.ppermute`` under one ``shard_map``.

Memory layout: microbatches are **sharded across the stage axis** (blocked:
device d owns microbatches [d*K, (d+1)*K), K = M/S) for both inputs and
outputs — per-device activation residency is O(M/S), not O(M). Two
single-microbatch rings move data to where it is consumed:

- input ring: device d injects its slot-q microbatch m = d*K+q at step
  m - d; one down-hop per step lands it on stage 0 exactly at step m.
- output ring: stage S-1 finishes microbatch m at step m + S-1 and pushes
  it down the ring; device m//K captures it (S-1 - m//K) hops later.

Injections never collide with in-flight values: the value from device e
passes device d < e during steps [e*K - d, e*K+K-1 - d], disjoint from
d's injection window [d*K - d, d*K+K-1 - d] for e != d.

Schedule: T = M + S - 1 scanned steps (GPipe fill/drain bubble); step t has
stage s working on microbatch t - s. The scan is reverse-differentiable, so
the same program trains — XLA stitches the backward pipeline automatically
(activations rematerialize per jax.checkpoint policy if requested).

``pipeline_apply_interleaved`` is the 1F1B-family upgrade (the interleaved
virtual-stage schedule): each device holds ``v`` non-adjacent stage chunks
(device d owns virtual stages {c·S + d}), microbatches run in groups of S,
and each scan step does 1/v of a GPipe stage's work — so the fill/drain
bubble shrinks from (S−1) stage-times to (S−1)/v while the ring machinery
is untouched (every activation produced at step t is consumed at t+1 one
hop down the ring; see ``interleaved_schedule`` for the static timetable
and its validity/bubble assertions).
"""

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.core import place


def pipeline_apply(stage_params, x: jax.Array, stage_fn: Callable,
                   mesh: Mesh, num_microbatches: int,
                   stage_axis: str = place.AXIS_STAGE,
                   wire_int8: bool = False) -> jax.Array:
    """Run ``stage_fn`` S times (once per stage) as a pipeline.

    stage_params: pytree whose leaves have a leading stage dim [S, ...];
    x: [B, ...] with B divisible by num_microbatches; stage_fn(params_s, mb)
    must map [mb, ...] -> [mb, ...] (same shape/dtype — residual stages).
    Returns [B, ...] equal to applying the stages sequentially.

    GPipe is exactly the single-chunk case of the interleaved schedule
    (T(m, j) = m + j, makespan M + S − 1), so this delegates to
    ``pipeline_apply_interleaved`` with v=1 — one ring executor to
    maintain. Microbatch counts that don't divide S are padded here
    (padding slots run through the pipe, their outputs are dropped).
    """
    S = mesh.shape[stage_axis]
    M = num_microbatches
    B = x.shape[0]
    assert B % M == 0, f"batch {B} not divisible by {M} microbatches"
    mb = B // M
    Mp = -(-M // S) * S
    if Mp != M:
        pad = jnp.zeros(((Mp - M) * mb,) + x.shape[1:], x.dtype)
        x = jnp.concatenate([x, pad], 0)
    chunked = jax.tree_util.tree_map(lambda l: l[None], stage_params)
    out = pipeline_apply_interleaved(chunked, x, stage_fn, mesh, Mp,
                                     num_chunks=1, stage_axis=stage_axis,
                                     wire_int8=wire_int8)
    return out[:B]


def interleaved_schedule(num_microbatches: int, num_stages: int,
                         num_chunks: int):
    """Static timetable of the interleaved schedule (pure bookkeeping —
    used by tests and capacity planning, the executor derives the same
    arithmetic inline).

    Returns (table, makespan_steps, bubble_stage_times) where table maps
    ``(step, device) -> (microbatch, virtual_stage)`` for busy slots.
    Virtual stage j runs on device j % S; microbatch m's virtual stage j
    executes at step T(m, j) = (m // S)·S·v + (m % S) + j. One scan step
    performs 1/v of a stage's FLOPs, so the fill/drain bubble in
    stage-time units is (makespan − M·v)/v = (S−1)/v — half of GPipe's
    (S−1) at v=2.
    """
    M, S, v = num_microbatches, num_stages, num_chunks
    if M % S:
        raise ValueError(f"interleaved schedule needs microbatches ({M}) "
                         f"divisible by stages ({S})")
    table = {}
    for m in range(M):
        for j in range(S * v):
            t = (m // S) * S * v + (m % S) + j
            key = (t, j % S)
            if key in table:
                raise AssertionError(f"schedule conflict at {key}")
            table[key] = (m, j)
    makespan = M * v + S - 1
    return table, makespan, (S - 1) / v


def pipeline_apply_interleaved(stage_params, x: jax.Array,
                               stage_fn: Callable, mesh: Mesh,
                               num_microbatches: int, num_chunks: int = 2,
                               stage_axis: str = place.AXIS_STAGE,
                               wire_int8: bool = False) -> jax.Array:
    """Interleaved virtual-stage pipeline (the 1F1B-family schedule).

    stage_params: pytree with leading dim [v, S, ...] — virtual stage
    j = c·S + d lives at ``[c, d]`` (device d holds the v non-adjacent
    chunks {c·S + d}, the Megatron-interleaved placement). stage_fn maps
    (params_leaf [...], mb) -> mb with matching shape/dtype. x: [B, ...]
    with B divisible by num_microbatches and num_microbatches divisible
    by S. Semantics: virtual stages applied in order j = 0 .. S·v−1 —
    equal to ``sequential_apply`` on the [S·v, ...] stacking.

    The backward is autodiff through the scan (reverse pipeline), as in
    ``pipeline_apply``; what the interleaving buys is the halved bubble,
    not memory — pair with jax.checkpoint on stage_fn to trade the rest.

    wire_int8: the inter-stage activation sends (the ``state`` ring)
    travel as int8 + a per-shard scale in both directions (ops/q8
    make_ppermute_q8) — half the ICI bytes per hop, straight-through
    gradients; the input/output rings stay full precision so the
    pipeline's own data is untouched.
    """
    from paddle_tpu.parallel.compat import shard_map

    S = mesh.shape[stage_axis]
    v = num_chunks
    M = num_microbatches
    B = x.shape[0]
    assert B % M == 0, f"batch {B} not divisible by {M} microbatches"
    if M % S:
        raise ValueError(f"microbatches ({M}) must divide by stages ({S}) "
                         f"for the interleaved schedule")
    mb = B // M
    K = M // S                       # input/output slots per device
    for path, leaf in jax.tree_util.tree_leaves_with_path(stage_params):
        if leaf.ndim < 2 or leaf.shape[0] != v or leaf.shape[1] != S:
            # dynamic_index_in_dim would silently CLAMP an out-of-range
            # chunk index, reusing the wrong chunk's weights — reject
            # mislaid params loudly instead
            raise ValueError(
                f"stage_params leaf {jax.tree_util.keystr(path)} must "
                f"have leading dims [num_chunks={v}, stages={S}, ...], "
                f"got {leaf.shape}")
    xs = x.reshape((M, mb) + x.shape[1:])
    Sv = S * v
    # exact makespan incl. the output ring: microbatch m finishes virtual
    # stage Sv-1 at T(m, Sv-1) and its owner (device m // K) captures it
    # S-1-owner down-hops later; the scan runs to the last capture.
    # At v=1 this is exactly the GPipe M + S - 1.
    def _t_last(m):
        return (m // S) * Sv + m % S + Sv - 1
    T_steps = 1 + max(_t_last(p * K + K - 1) + (S - 1 - p)
                      for p in range(S))

    param_specs = jax.tree_util.tree_map(
        lambda leaf: P(None, stage_axis), stage_params)

    def run(params_local, xs_local):
        # params_local leaves: [v, 1, ...] — this device's chunks
        p_here = jax.tree_util.tree_map(lambda l: l[:, 0], params_local)
        idx = jax.lax.axis_index(stage_axis)
        down = [(i, (i - 1) % S) for i in range(S)]
        up = [(i, (i + 1) % S) for i in range(S)]

        def t_inject(m):
            """Arrival step of microbatch m at virtual stage 0 (device 0):
            T(m, 0) = (m // S)·S·v + m % S. Strictly increasing in m, so
            the GPipe input-ring disjointness argument carries over."""
            return (m // S) * Sv + m % S

        def step(carry, t):
            state, g, h, outs_local = carry

            # --- input ring: device d injects slot q (mb m = d·K + q) at
            # t_inject(m) - d so one down-hop/step lands it on device 0
            # exactly at its schedule slot. Injection steps are distinct
            # per m, so windows never collide (see GPipe proof above).
            m_lo = idx * K
            # find the owned m with t_inject(m) - idx == t, i.e. invert
            # w = (m//S)·Sv + m%S at w = t + idx (valid only when the
            # within-group remainder is a real schedule slot, rem < S)
            w_in = t + idx
            g_grp, g_rem = w_in // Sv, w_in % Sv
            m_in = g_grp * S + g_rem
            inject = (g_rem < S) & (m_in >= m_lo) & (m_in < m_lo + K)
            cand = jax.lax.dynamic_index_in_dim(
                xs_local, jnp.clip(m_in - m_lo, 0, K - 1), 0,
                keepdims=False)
            g = jnp.where(inject, cand, g)

            # --- which (m, j) does this device run at step t?
            # j = c·S + idx, T(m, j) = t  =>  u := t - idx,
            # c = (u mod Sv) // S, r = u mod S, group = u // Sv
            u = t - idx
            c = (u % Sv) // S
            grp = u // Sv
            m_here = grp * S + (u % S)
            busy = (u >= 0) & (m_here >= 0) & (m_here < M)
            c = jnp.clip(c, 0, v - 1)

            # virtual stage j = c·S + idx consumes the ring value; j == 0
            # (device 0, chunk 0 slot) consumes the fresh input instead
            is_first = (idx == 0) & ((u % Sv) < S)
            cur = jnp.where(is_first, g, state)
            p_c = jax.tree_util.tree_map(
                lambda l: jax.lax.dynamic_index_in_dim(
                    l, c, 0, keepdims=False), p_here)
            out = stage_fn(p_c, cur)

            # --- output ring: virtual stage Sv-1 (device S-1, last chunk)
            # finishes m at T(m, Sv-1); capture on owner p after S-1-p hops
            is_last = (idx == S - 1) & ((u % Sv) >= Sv - S) & busy
            h = jnp.where(is_last, out, h)
            w_out = t + idx - (S - 1) - (Sv - 1)
            og, orr = w_out // Sv, w_out % Sv
            m_out = og * S + orr
            own = ((w_out >= 0) & (orr < S) & (m_out >= m_lo)
                   & (m_out < m_lo + K))
            slot = jnp.clip(m_out - m_lo, 0, K - 1)
            old = jax.lax.dynamic_index_in_dim(outs_local, slot, 0,
                                               keepdims=False)
            outs_local = jax.lax.dynamic_update_index_in_dim(
                outs_local, jnp.where(own, h, old), slot, 0)

            if wire_int8:
                from paddle_tpu.ops import q8 as ops_q8
                state = ops_q8.make_ppermute_q8(stage_axis,
                                                tuple(up))(out)
            else:
                state = jax.lax.ppermute(out, stage_axis, up)
            g = jax.lax.ppermute(g, stage_axis, down)
            h = jax.lax.ppermute(h, stage_axis, down)
            return (state, g, h, outs_local), None

        zero_mb = jnp.zeros_like(xs_local[0])
        carry0 = (zero_mb, zero_mb, zero_mb, jnp.zeros_like(xs_local))
        (_, _, _, outs_local), _ = jax.lax.scan(
            step, carry0, jnp.arange(T_steps))
        return outs_local

    specs_mb = P(stage_axis)
    outs = shard_map(run, mesh=mesh,
                     in_specs=(param_specs, specs_mb),
                     out_specs=specs_mb, check_vma=False)(stage_params, xs)
    return outs.reshape((B,) + x.shape[1:])


def sequential_apply(stage_params, x: jax.Array,
                     stage_fn: Callable) -> jax.Array:
    """Reference semantics: apply the S stages one after another."""
    def body(h, p_s):
        return stage_fn(p_s, h), None

    out, _ = jax.lax.scan(body, x, stage_params)
    return out
