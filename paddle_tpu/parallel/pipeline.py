"""Pipeline parallelism over the ``stage`` mesh axis (GPipe schedule).

The modern occupant of the reference's per-layer device placement slot
(SURVEY.md §2.3 — ParallelNeuralNetwork's parallel_nn layer->device
dispatch): the network is cut into S stages with identical signatures;
each device on the ``stage`` axis holds one stage's weights; microbatches
flow through the ring via ``lax.ppermute`` under one ``shard_map``.

Memory layout: microbatches are **sharded across the stage axis** (blocked:
device d owns microbatches [d*K, (d+1)*K), K = M/S) for both inputs and
outputs — per-device activation residency is O(M/S), not O(M). Two
single-microbatch rings move data to where it is consumed:

- input ring: device d injects its slot-q microbatch m = d*K+q at step
  m - d; one down-hop per step lands it on stage 0 exactly at step m.
- output ring: stage S-1 finishes microbatch m at step m + S-1 and pushes
  it down the ring; device m//K captures it (S-1 - m//K) hops later.

Injections never collide with in-flight values: the value from device e
passes device d < e during steps [e*K - d, e*K+K-1 - d], disjoint from
d's injection window [d*K - d, d*K+K-1 - d] for e != d.

Schedule: T = M + S - 1 scanned steps (GPipe fill/drain bubble); step t has
stage s working on microbatch t - s. The scan is reverse-differentiable, so
the same program trains — XLA stitches the backward pipeline automatically
(activations rematerialize per jax.checkpoint policy if requested).
"""

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.core import place


def pipeline_apply(stage_params, x: jax.Array, stage_fn: Callable,
                   mesh: Mesh, num_microbatches: int,
                   stage_axis: str = place.AXIS_STAGE) -> jax.Array:
    """Run ``stage_fn`` S times (once per stage) as a pipeline.

    stage_params: pytree whose leaves have a leading stage dim [S, ...];
    x: [B, ...] with B divisible by num_microbatches; stage_fn(params_s, mb)
    must map [mb, ...] -> [mb, ...] (same shape/dtype — residual stages).
    Returns [B, ...] equal to applying the stages sequentially.
    """
    from jax import shard_map

    S = mesh.shape[stage_axis]
    M = num_microbatches
    B = x.shape[0]
    assert B % M == 0, f"batch {B} not divisible by {M} microbatches"
    mb = B // M
    xs = x.reshape((M, mb) + x.shape[1:])
    # microbatch dim is sharded over stages: pad M up to a multiple of S
    # (padding slots run through the pipe but their outputs are dropped)
    K = -(-M // S)
    Mp = K * S
    if Mp != M:
        pad = jnp.zeros((Mp - M, mb) + x.shape[1:], x.dtype)
        xs = jnp.concatenate([xs, pad], 0)

    param_specs = jax.tree_util.tree_map(
        lambda leaf: P(stage_axis), stage_params)

    def run(params_local, xs_local):
        # params_local leaves: [1, ...] (this stage's slice); drop the dim
        p_here = jax.tree_util.tree_map(lambda l: l[0], params_local)
        idx = jax.lax.axis_index(stage_axis)
        down = [(i, (i - 1) % S) for i in range(S)]
        up = [(i, (i + 1) % S) for i in range(S)]

        def step(carry, t):
            state, g, h, outs_local = carry

            # --- input ring: device d injects local slot q = t - d*(K-1)
            q_in = t - idx * (K - 1)
            inject = (q_in >= 0) & (q_in < K)
            cand = jax.lax.dynamic_index_in_dim(
                xs_local, jnp.clip(q_in, 0, K - 1), 0, keepdims=False)
            g = jnp.where(inject, cand, g)

            # --- stage work: stage 0 consumes the ring head
            cur = jnp.where(idx == 0, g, state)
            out = stage_fn(p_here, cur)

            # --- output ring: last stage pushes its completed microbatch
            h = jnp.where(idx == S - 1, out, h)
            # device d captures microbatch m = t + d - 2(S-1) when it owns it
            m_here = t + idx - 2 * (S - 1)
            own = (m_here >= 0) & (m_here < Mp) & (m_here // K == idx)
            slot = jnp.clip(m_here - idx * K, 0, K - 1)
            old = jax.lax.dynamic_index_in_dim(outs_local, slot, 0,
                                               keepdims=False)
            outs_local = jax.lax.dynamic_update_index_in_dim(
                outs_local, jnp.where(own, h, old), slot, 0)

            state = jax.lax.ppermute(out, stage_axis, up)
            g = jax.lax.ppermute(g, stage_axis, down)
            h = jax.lax.ppermute(h, stage_axis, down)
            return (state, g, h, outs_local), None

        zero_mb = jnp.zeros_like(xs_local[0])
        carry0 = (zero_mb, zero_mb, zero_mb, jnp.zeros_like(xs_local))
        (_, _, _, outs_local), _ = jax.lax.scan(
            step, carry0, jnp.arange(Mp + S - 1))
        return outs_local

    specs_mb = P(stage_axis)   # microbatch dim blocked over stages
    outs = shard_map(run, mesh=mesh,
                     in_specs=(param_specs, specs_mb),
                     out_specs=specs_mb, check_vma=False)(stage_params, xs)
    return outs[:M].reshape((B,) + x.shape[1:])


def sequential_apply(stage_params, x: jax.Array,
                     stage_fn: Callable) -> jax.Array:
    """Reference semantics: apply the S stages one after another."""
    def body(h, p_s):
        return stage_fn(p_s, h), None

    out, _ = jax.lax.scan(body, x, stage_params)
    return out
