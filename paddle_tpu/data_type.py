"""Input type declarations for data layers and the feeder.

Reference: python/paddle/trainer/PyDataProvider2.py:109-250 — dense_vector,
sparse_binary_vector, sparse_float_vector, integer_value, each with
(no-)sequence / sub-sequence variants; carried into v2 as paddle.data_type.
"""

import dataclasses
from enum import Enum


class SeqLevel(Enum):
    NO_SEQUENCE = 0
    SEQUENCE = 1
    SUB_SEQUENCE = 2


class Kind(Enum):
    DENSE = 0
    SPARSE_BINARY = 1
    SPARSE_FLOAT = 2
    INDEX = 3


@dataclasses.dataclass(frozen=True)
class InputType:
    dim: int
    kind: Kind
    seq: SeqLevel = SeqLevel.NO_SEQUENCE


def dense_vector(dim):
    return InputType(dim, Kind.DENSE)


def dense_array(dim):  # alias used by some v2 configs
    return InputType(dim, Kind.DENSE)


def sparse_binary_vector(dim):
    return InputType(dim, Kind.SPARSE_BINARY)


def sparse_float_vector(dim):
    return InputType(dim, Kind.SPARSE_FLOAT)


def integer_value(value_range):
    return InputType(value_range, Kind.INDEX)


def dense_vector_sequence(dim):
    return InputType(dim, Kind.DENSE, SeqLevel.SEQUENCE)


def sparse_binary_vector_sequence(dim):
    return InputType(dim, Kind.SPARSE_BINARY, SeqLevel.SEQUENCE)


def sparse_float_vector_sequence(dim):
    return InputType(dim, Kind.SPARSE_FLOAT, SeqLevel.SEQUENCE)


def integer_value_sequence(value_range):
    return InputType(value_range, Kind.INDEX, SeqLevel.SEQUENCE)


def dense_vector_sub_sequence(dim):
    return InputType(dim, Kind.DENSE, SeqLevel.SUB_SEQUENCE)


def sparse_binary_vector_sub_sequence(dim):
    return InputType(dim, Kind.SPARSE_BINARY, SeqLevel.SUB_SEQUENCE)


def sparse_float_vector_sub_sequence(dim):
    return InputType(dim, Kind.SPARSE_FLOAT, SeqLevel.SUB_SEQUENCE)


def integer_value_sub_sequence(value_range):
    return InputType(value_range, Kind.INDEX, SeqLevel.SUB_SEQUENCE)
