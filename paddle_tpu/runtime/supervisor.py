"""Elastic gang supervision — training that survives worker death and
resizes the mesh mid-run.

Reference: the Go cloud layer's elastic trainers (PAPER.md § cloud
layer: etcd-backed master task queue, fault-tolerant pserver) — any
worker may be preempted; the job continues. TPU-native composition of
the blocks this repo already has:

- liveness rides the same file-mtime lease scheme as ``LeaderLock``
  (runtime/master.py): each worker heartbeats a per-rank JSON file; the
  supervisor judges a worker dead when its process exits nonzero or its
  heartbeat goes stale past ``heartbeat_window``, and WEDGED when the
  file stays fresh (the beat thread lives) but step progress stalls
  past ``wedge_window`` — a hung collective beats but does not step.
  Workers may also publish a ``health_port`` (``SGD
  .attach_observability``-style ``/healthz``); the supervisor probes it
  as a secondary judgment.
- teardown goes through ``runtime/launch.py``: stdin-watchdog close
  (the ssh remote-tree killer) + TERM-then-KILL for local gangs.
- every relaunch is a fresh **coordination epoch**: the supervisor
  bumps ``<state_dir>/epoch.json`` and stamps ``PADDLE_ELASTIC_EPOCH``
  into the new gang; in cluster mode a fresh coordinator port re-forms
  the jax.distributed runtime from scratch. Epoch fencing closes the
  zombie hole: a worker from a torn-down gang that somehow survived the
  kill carries a stale epoch, so (a) its checkpoint commits abort
  (``io/checkpoint.py`` ``fence=``, wired automatically by
  ``SGD.train`` — write-temp + fsync + atomic rename + manifest-last
  means nothing partial is ever visible either), and (b) the master
  rejects its task RPCs (``MasterService.set_epoch_fence``).
- recovery is a restore: the relaunched trainer finds the latest
  INTACT checkpoint (torn saves are skipped), reshards it to the new
  mesh size / ZeRO layout via the manifest's ``meta.zero``, restores
  the input pipeline's stream position, and continues on the exact
  next batch.
- when a worker cannot be replaced (``replacements`` exhausted), the
  gang degrades gracefully to a smaller mesh (optionally snapped to
  ``valid_sizes``) instead of dying — the reference's elastic-trainer
  semantics.

Observability: ``training_restarts_total{reason}``,
``worker_liveness{rank}``, ``supervisor_state`` (coded; see STATES),
``supervisor_last_recovery_seconds``, plus a flight-recorder
post-mortem written into ``<state_dir>/flight/`` on every restart.

The supervisor is deliberately jax-free: it launches, watches files
and processes, and kills. Workers do the training.
"""

import json
import os
import shutil
import threading
import time
from typing import Dict, List, Optional, Sequence

from paddle_tpu.observe import metrics as _metrics
from paddle_tpu.runtime import launch as _launch
from paddle_tpu.runtime.master import DecorrelatedBackoff
from paddle_tpu.utils.logger import get_logger

log = get_logger("supervisor")

ENV_DIR = "PADDLE_ELASTIC_DIR"
ENV_EPOCH = "PADDLE_ELASTIC_EPOCH"

#: supervisor_state gauge encoding
STATES = {"idle": 0, "launching": 1, "running": 2, "teardown": 3,
          "backoff": 4, "done": 5, "failed": 6}

_m_restarts = _metrics.counter(
    "training_restarts_total",
    "supervised gang restarts (label reason = worker_exit|"
    "heartbeat_lost|wedged|no_heartbeat|unhealthy|attempt_timeout)")
_m_liveness = _metrics.gauge(
    "worker_liveness",
    "per-worker liveness judgment (label rank; 1 = beating, 0 = dead)")
_m_state = _metrics.gauge(
    "supervisor_state",
    "supervision state machine position (0 idle, 1 launching, "
    "2 running, 3 teardown, 4 backoff, 5 done, 6 failed)")
_m_recovery = _metrics.gauge(
    "supervisor_last_recovery_seconds",
    "kill-detection to first post-restore worker step, last restart")
_m_gang = _metrics.gauge(
    "supervisor_gang_size", "workers in the current gang incarnation")


# ---------------------------------------------------------------------------
# the coordination epoch (worker + supervisor side)
# ---------------------------------------------------------------------------

def _epoch_path(state_dir: str) -> str:
    return os.path.join(state_dir, "epoch.json")


def current_epoch(state_dir: str) -> int:
    """The fence value: the epoch of the newest gang the supervisor
    launched (0 before the first launch)."""
    try:
        with open(_epoch_path(state_dir)) as f:
            return int(json.load(f)["epoch"])
    except (OSError, ValueError, KeyError):
        return 0


def write_epoch(state_dir: str, epoch: int) -> None:
    os.makedirs(state_dir, exist_ok=True)
    tmp = f"{_epoch_path(state_dir)}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"epoch": int(epoch), "ts": time.time()}, f)
    os.replace(tmp, _epoch_path(state_dir))


def my_epoch() -> Optional[int]:
    """This worker's stamped coordination epoch (None outside a gang)."""
    v = os.environ.get(ENV_EPOCH)
    try:
        return int(v) if v else None
    except ValueError:
        return None


def fence_from_env() -> Optional[object]:
    """The checkpoint-commit fence for THIS worker: True while its
    stamped epoch is still the current one. None when not running under
    a supervisor (no env contract) — saves are then unfenced, exactly
    as before."""
    state_dir = os.environ.get(ENV_DIR)
    epoch = my_epoch()
    if not state_dir or epoch is None:
        return None
    return lambda: current_epoch(state_dir) <= epoch


# ---------------------------------------------------------------------------
# heartbeats (worker side)
# ---------------------------------------------------------------------------

def _hb_dir(state_dir: str) -> str:
    return os.path.join(state_dir, "hb")


class Heartbeat:
    """Worker-side liveness + progress beacon: an atomically-replaced
    per-rank JSON file. The file's mtime is the liveness lease (the
    background thread refreshes it every ``interval``, LeaderLock
    style); the ``step``/``step_ts`` fields are the PROGRESS signal the
    trainer updates per batch — a wedged worker keeps the lease fresh
    but stops stepping, which is precisely what the supervisor's
    ``wedge_window`` judges."""

    def __init__(self, state_dir: str, rank: int,
                 epoch: Optional[int] = None, interval: float = 0.5,
                 health_port: Optional[int] = None,
                 start_thread: bool = True):
        self.state_dir = state_dir
        self.rank = int(rank)
        self.epoch = epoch if epoch is not None else (my_epoch() or 0)
        self.interval = interval
        # epoch-scoped filename: a zombie from a torn-down gang that
        # survived the kill (ssh partition) keeps rewriting ITS file —
        # it must not alternate with the live replacement rank's beats
        # and make the supervisor judge a beating worker absent
        self.path = os.path.join(
            _hb_dir(state_dir),
            f"worker_{self.rank}_e{self.epoch}.json")
        os.makedirs(_hb_dir(state_dir), exist_ok=True)
        self._lock = threading.Lock()
        self._fields = {"rank": self.rank, "pid": os.getpid(),
                        "epoch": self.epoch}
        # ssh gangs run on another box: publish the host so the
        # supervisor's health probe targets the right machine
        if os.environ.get("PADDLE_GANG_HOST"):
            self._fields["host"] = os.environ["PADDLE_GANG_HOST"]
        if health_port is not None:
            self._fields["health_port"] = int(health_port)
        self._stop = threading.Event()
        self._last_write = 0.0
        self._telemetry_fn = None
        self._write()
        self._thread = None
        if start_thread:
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True)
            self._thread.start()

    def set_telemetry(self, fn) -> None:
        """Attach a zero-arg callable returning a JSON-able dict that
        rides every heartbeat write as the record's ``telemetry`` field
        — the gang scrape transport: the supervisor reads the files it
        already watches, no extra port, works over the same shared
        filesystem as ssh-mode liveness. The callable runs on the beat
        thread OUTSIDE the field lock; keep it cheap (a registry
        snapshot + window export, not a device sync)."""
        self._telemetry_fn = fn

    @classmethod
    def from_env(cls, health_port: Optional[int] = None,
                 interval: float = 0.5) -> Optional["Heartbeat"]:
        """A Heartbeat wired from the supervisor's env contract, or
        None when this process is not a supervised gang member."""
        state_dir = os.environ.get(ENV_DIR)
        rank = os.environ.get("PADDLE_PROCESS_ID", "0")
        if not state_dir:
            return None
        return cls(state_dir, int(rank), health_port=health_port,
                   interval=interval)

    def _write(self):
        fn = self._telemetry_fn
        tele = None
        if fn is not None:
            try:
                tele = fn()
            except Exception:  # noqa: BLE001 — telemetry is best-effort
                tele = None
        with self._lock:
            rec = dict(self._fields, ts=time.time())
        if tele:
            rec["telemetry"] = tele
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(rec, f)
            os.replace(tmp, self.path)
            self._last_write = time.time()
        except OSError:
            pass                 # a missed beat is survivable; dying isn't

    def _loop(self):
        while not self._stop.wait(self.interval):
            self._write()

    def beat(self, step: Optional[int] = None):
        """Record step progress (trainer: once per batch). The write
        itself is throttled to the beat-thread cadence — fast training
        steps must not pay a file rewrite (a network-filesystem round
        trip in ssh mode) per batch; the interval thread publishes the
        updated fields within one beat period anyway."""
        with self._lock:
            if step is not None:
                self._fields["step"] = int(step)
                self._fields["step_ts"] = time.time()
        if time.time() - self._last_write >= self.interval:
            self._write()

    def done(self):
        """Mark clean completion (the supervisor stops judging this
        rank's staleness) and stop the beat thread."""
        with self._lock:
            self._fields["done"] = True
        self._write()
        self.stop()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)


def read_heartbeats(state_dir: str,
                    epoch: Optional[int] = None) -> Dict[int, dict]:
    """rank -> heartbeat record (+ ``age`` seconds since last write);
    unparseable / mid-replace files are skipped. With ``epoch`` only
    records of that incarnation count (the supervisor's view — a
    zombie's stale-epoch beats are invisible, not 'absence'); without
    it the newest incarnation per rank wins (the health endpoint)."""
    out = {}
    d = _hb_dir(state_dir)
    try:
        names = os.listdir(d)
    except OSError:
        return out
    now = time.time()
    for fn in names:
        if not (fn.startswith("worker_") and fn.endswith(".json")):
            continue
        p = os.path.join(d, fn)
        try:
            with open(p) as f:
                rec = json.load(f)
            rec["age"] = now - os.path.getmtime(p)
            rank = int(rec["rank"])
        except (OSError, ValueError, KeyError):
            continue
        if epoch is not None and rec.get("epoch") != epoch:
            continue
        prev = out.get(rank)
        if prev is None or (rec.get("epoch") or 0) >= (prev.get("epoch")
                                                       or 0):
            out[rank] = rec
    return out


def _probe_healthz(port: int, host: str = "127.0.0.1",
                   timeout: float = 0.5) -> Optional[bool]:
    """True healthy / False unhealthy / None unreachable-or-unknown."""
    import urllib.error
    import urllib.request
    try:
        with urllib.request.urlopen(
                f"http://{host}:{port}/healthz", timeout=timeout):
            return True
    except urllib.error.HTTPError as e:
        return False if e.code == 503 else True
    except (OSError, ValueError):
        return None


# ---------------------------------------------------------------------------
# the supervisor
# ---------------------------------------------------------------------------

class RestartBudget:
    """Restart policy for one supervised thing (a training gang, a
    serving replica): a budget of CONSECUTIVE unstable incarnations
    plus decorrelated-jitter backoff between relaunches.

    An incarnation that did real work (``stepped``) and then survived
    ``stable_window`` seconds refills the budget and cools the backoff
    when it eventually dies — routine independent preemptions spread
    over a job's lifetime must not exhaust a crash-loop guard. The
    budget is the supervisor's inline logic extracted so the fleet
    controller heals replicas under the exact same policy."""

    def __init__(self, max_restarts: int = 5,
                 stable_window: float = 300.0,
                 backoff_base: float = 0.5,
                 backoff_cap: float = 15.0):
        self.max_restarts = int(max_restarts)
        self.stable_window = float(stable_window)
        self.backoff = DecorrelatedBackoff(backoff_base, backoff_cap)
        self.restarts = 0

    def note_failure(self, *, stepped: bool, uptime_s: float):
        """Record one incarnation's death; call before consulting
        :attr:`exhausted` / :meth:`delay` for the relaunch."""
        if stepped and uptime_s >= self.stable_window:
            self.restarts = 0
            self.backoff.reset()
        self.restarts += 1

    @property
    def exhausted(self) -> bool:
        return self.restarts > self.max_restarts

    def delay(self) -> float:
        """Jittered sleep before the next relaunch."""
        return self.backoff.next()

    def reset(self):
        self.restarts = 0
        self.backoff.reset()


class Supervisor:
    """Drive a worker gang through launch → watch → teardown → relaunch
    until it completes, the restart budget runs out, or the gang cannot
    shrink any further.

    Local mode (``hosts=None``): ``nprocs`` python processes on this
    machine via ``launch.spawn_local_procs`` — ``cluster=True`` wires
    PADDLE_COORDINATOR (one jax.distributed runtime per epoch, fresh
    port each time), ``cluster=False`` runs independent single-process
    runtimes (the CPU-simulation path; see
    ``launch.multiprocess_cpu_supported``). ``replacements`` is the
    spare-host budget: None = unlimited (a local respawn is free), an
    int = that many worker deaths can be replaced before the gang
    starts shrinking instead (graceful degradation), optionally snapped
    down to a size in ``valid_sizes`` (mesh-shape divisibility).

    SSH mode (``hosts=[...]``): one worker per host via
    ``launch.spawn_ssh_procs``; dead hosts are swapped for
    ``replacement_hosts`` entries first, dropped when the pool is dry.

    ``max_restarts`` budgets CONSECUTIVE unstable incarnations, not the
    job's lifetime: an incarnation that stepped and then survived
    ``stable_window`` seconds refills the budget and cools the backoff
    when it eventually fails — routine independent preemptions spread
    over weeks must not exhaust a crash-loop guard.

    ``master``: a MasterService/MasterClient whose ``set_epoch_fence``
    is called on every relaunch so zombies lose task-RPC rights too.
    """

    def __init__(self, argv: Sequence[str], nprocs: int, state_dir: str, *,
                 devices_per_proc: int = 1,
                 cluster: bool = False,
                 hosts: Optional[Sequence[str]] = None,
                 replacement_hosts: Sequence[str] = (),
                 ssh_port_base: int = 6007,
                 ssh_cmd: Sequence[str] = ("ssh", "-o", "BatchMode=yes"),
                 workdir: Optional[str] = None,
                 env_extra: Optional[dict] = None,
                 heartbeat_window: float = 10.0,
                 wedge_window: Optional[float] = None,
                 startup_grace: float = 120.0,
                 poll_interval: float = 0.25,
                 max_restarts: int = 5,
                 stable_window: float = 300.0,
                 backoff_base: float = 0.5,
                 backoff_cap: float = 15.0,
                 replacements: Optional[int] = None,
                 min_nprocs: int = 1,
                 valid_sizes: Optional[Sequence[int]] = None,
                 attempt_timeout: Optional[float] = None,
                 master=None,
                 probe_health: bool = True,
                 http_port: Optional[int] = None,
                 scrape_interval: float = 1.0,
                 alert_rules: Optional[Sequence] = None):
        self.argv = list(argv)
        self.state_dir = state_dir
        self.devices_per_proc = devices_per_proc
        self.cluster = cluster
        self.hosts = list(hosts) if hosts is not None else None
        self._spares = list(replacement_hosts)
        self.ssh_port_base = ssh_port_base
        self.ssh_cmd = tuple(ssh_cmd)
        self.workdir = workdir
        self.env_extra = dict(env_extra or {})
        self.nprocs = len(self.hosts) if self.hosts is not None \
            else int(nprocs)
        self.heartbeat_window = heartbeat_window
        self.wedge_window = wedge_window
        self.startup_grace = startup_grace
        self.poll_interval = poll_interval
        self.max_restarts = max_restarts
        self.stable_window = stable_window
        self._budget = RestartBudget(max_restarts, stable_window,
                                     backoff_base, backoff_cap)
        self._replacements = replacements
        self.min_nprocs = min_nprocs
        self.valid_sizes = (sorted(valid_sizes, reverse=True)
                            if valid_sizes else None)
        self.attempt_timeout = attempt_timeout
        self.master = master
        self.probe_health = probe_health
        self._state = "idle"
        self._epoch = current_epoch(state_dir)
        self._attempts: List[dict] = []
        self._last_probe: Dict[int, float] = {}
        os.makedirs(state_dir, exist_ok=True)
        # -- the gang observability plane (PR-16's fleet plane, ported
        # to training): heartbeats carry worker telemetry; the scrape
        # loop joins it into gang_* series, the straggler report, and
        # the goodput ledger, all on the default registry so the
        # supervisor's /metrics serves them.
        from paddle_tpu.observe import alerts as _alerts
        from paddle_tpu.observe import fleet as _fleet
        from paddle_tpu.observe import goodput as _goodput
        from paddle_tpu.observe import straggler as _straggler
        self.scrape_interval = float(scrape_interval)
        self.aggregator = _fleet.FleetAggregator(
            registry=_metrics.default_registry(),
            prefix="gang", entity_label="rank",
            window_keys=("step_time", "barrier_wait"),
            count_suffix="_samples")
        self.straggler = _straggler.StragglerDetector()
        self.ledger = _goodput.GoodputLedger(
            os.path.join(state_dir, "goodput_ledger.json"))
        self.alerts = _alerts.AlertEvaluator(
            _metrics.default_registry(),
            (list(alert_rules) if alert_rules is not None
             else _alerts.default_training_rules()))
        self._m_since_step = _metrics.gauge(
            "gang_seconds_since_step",
            "per-rank seconds since the last step-progress beat "
            "(label rank)")
        self._m_max_since = _metrics.gauge(
            "gang_max_seconds_since_step",
            "slowest rank's seconds since its last step-progress beat "
            "— the wedge-suspect alert's input")
        self._m_restart_rate = _metrics.gauge(
            "training_restarts_last_10m",
            "gang restarts inside the trailing 10 minutes — the "
            "restart-storm alert's input")
        self._restart_times: List[float] = []
        self._last_scrape = 0.0
        self._worker_stats: Dict[str, dict] = {}
        self.http = None
        if http_port is not None:
            from paddle_tpu.observe.health import HealthServer
            self.http = HealthServer(health_fn=self.health,
                                     port=http_port,
                                     alerts_fn=self.alerts.doc)

    # -- introspection ----------------------------------------------------
    def health(self) -> dict:
        workers = {}
        for rank, rec in read_heartbeats(self.state_dir).items():
            doc = {
                "age": round(rec.get("age", -1), 3),
                "step": rec.get("step"),
                "epoch": rec.get("epoch"),
                "done": bool(rec.get("done"))}
            derived = self._worker_stats.get(str(rank), {})
            for k in ("since_step_s", "step_p50_s", "barrier_p50_s"):
                if k in derived:
                    doc[k] = derived[k]
            workers[str(rank)] = doc
        return {"state": self._state, "epoch": self._epoch,
                "gang_size": self.nprocs, "restarts": self._restarts,
                "healthy": self._state != "failed",
                "workers": workers,
                "straggler": self.straggler.report,
                "goodput": self.ledger.summary(),
                "alerts_firing": self.alerts.firing()}

    def _set_state(self, state: str):
        self._state = state
        _m_state.set(STATES[state])

    # -- gang lifecycle ---------------------------------------------------
    def _spawn(self, epoch: int):
        env = dict(self.env_extra)
        env[ENV_DIR] = self.state_dir
        env[ENV_EPOCH] = str(epoch)
        if self.hosts is not None:
            # the coordinator binds on hosts[0], so a locally-probed
            # free_port() would be a lie — walk a per-epoch offset off
            # ssh_port_base instead: never the previous incarnation's
            # port (a lingering zombie there can't wedge the rebind),
            # and deterministic for firewall rules
            return _launch.spawn_ssh_procs(
                self.hosts, self.argv,
                port=self.ssh_port_base + (epoch % 64),
                workdir=self.workdir, env_extra=env,
                ssh_cmd=self.ssh_cmd)
        return _launch.spawn_local_procs(
            self.nprocs, self.argv,
            devices_per_proc=self.devices_per_proc,
            env_extra=env, cluster=self.cluster)

    def _judge(self, procs, epoch, t_launch, attempt):
        """One monitoring sweep. Returns (verdict, failed_ranks, reason):
        verdict 'ok' (all exited 0), 'running', or 'fail'."""
        now = time.time()
        rcs = [p.poll() for p in procs]
        failed = [r for r, rc in enumerate(rcs)
                  if rc is not None and rc != 0]
        if failed:
            for r in failed:
                _m_liveness.set(0, rank=str(r))
            return "fail", failed, f"worker_exit:{rcs[failed[0]]}"
        if all(rc == 0 for rc in rcs):
            return "ok", [], None
        hbs = read_heartbeats(self.state_dir, epoch)
        for rank, p in enumerate(procs):
            if p.poll() == 0:
                continue                       # clean exit, no judgment
            rec = hbs.get(rank)
            if rec is None:
                # nothing from THIS incarnation yet: jax import +
                # compile can take a while — the startup grace bounds it
                if now - t_launch > self.startup_grace:
                    _m_liveness.set(0, rank=str(rank))
                    return "fail", [rank], "no_heartbeat"
                continue
            if attempt.get("t_first_step") is None and "step" in rec:
                attempt["t_first_step"] = now
            if rec.get("done"):
                _m_liveness.set(1, rank=str(rank))
                continue
            if rec.get("age", 0.0) > self.heartbeat_window:
                _m_liveness.set(0, rank=str(rank))
                return "fail", [rank], "heartbeat_lost"
            _m_liveness.set(1, rank=str(rank))
            if (self.wedge_window is not None
                    and rec.get("step_ts") is not None
                    and now - rec["step_ts"] > self.wedge_window):
                return "fail", [rank], "wedged"
            port = rec.get("health_port")
            if (self.probe_health and port
                    and now - self._last_probe.get(rank, 0.0) > 2.0):
                self._last_probe[rank] = now
                if _probe_healthz(port, rec.get("host")
                                  or "127.0.0.1") is False:
                    return "fail", [rank], "unhealthy"
        if (self.attempt_timeout is not None
                and now - t_launch > self.attempt_timeout):
            return "fail", list(range(len(procs))), "attempt_timeout"
        return "running", [], None

    @property
    def _restarts(self) -> int:
        """Consecutive-unstable restart count (the budget owns it)."""
        return self._budget.restarts

    def _post_mortem(self, reason, failed_ranks, epoch):
        """Flight-recorder artifact for this restart: the judgment, the
        last heartbeats, and the standard config/env/metrics snapshot."""
        from paddle_tpu import observe
        rec = observe.default_flight_recorder()
        rec.record({"kind": "supervisor_restart", "epoch": epoch,
                    "reason": reason, "failed_ranks": failed_ranks,
                    "gang_size": self.nprocs,
                    "heartbeats": read_heartbeats(self.state_dir),
                    "goodput": self.ledger.summary(),
                    "straggler": self.straggler.report,
                    "alerts_firing": self.alerts.firing()})
        rec.dump(path=os.path.join(self.state_dir, "flight",
                                   f"restart_epoch{epoch:04d}.json"),
                 reason=f"gang restart: {reason}")

    # -- the gang scrape (telemetry -> gang_* series + ledger) -------------
    def _scrape(self, epoch: int, t_launch: float,
                final: bool = False):
        """Join the current incarnation's heartbeat telemetry into the
        observability plane: per-rank registry snapshots through the
        aggregator (gang_* series), raw step/barrier windows through
        the straggler detector, worker goodput buckets + the
        supervisor-attributed startup span into the ledger, then one
        alert evaluation round. Throttled to ``scrape_interval`` so the
        poll loop's cadence stays the liveness judge's; ``final`` forces
        a round (verdict just broke — fold the last telemetry before
        the heartbeat dir is cleared)."""
        now = time.time()
        if not final and now - self._last_scrape < self.scrape_interval:
            return
        self._last_scrape = now
        hbs = read_heartbeats(self.state_dir, epoch)
        per_rank: Dict[str, dict] = {}
        since: List[float] = []
        stats: Dict[str, dict] = {}
        gp_src = None
        for rank, rec in sorted(hbs.items()):
            tele = rec.get("telemetry") or {}
            state = "done" if rec.get("done") else "ok"
            self.aggregator.observe_replica(
                str(rank), state=state,
                health={"window": tele.get("window") or {}},
                snapshot=tele.get("snapshot") or {})
            win = tele.get("window") or {}
            per_rank[str(rank)] = {
                "step": [v for _, v in
                         (win.get("step_time_samples") or ())],
                "barrier": [v for _, v in
                            (win.get("barrier_wait_samples") or ())]}
            stats[str(rank)] = {"step": rec.get("step"),
                                "done": bool(rec.get("done")),
                                "age": round(rec.get("age", -1), 3)}
            if rec.get("step_ts") is not None and not rec.get("done"):
                s = max(0.0, now - rec["step_ts"])
                self._m_since_step.set(round(s, 3), rank=str(rank))
                stats[str(rank)]["since_step_s"] = round(s, 3)
                since.append(s)
            gp = tele.get("goodput")
            if gp and (gp_src is None or rank < gp_src[0]):
                gp_src = (rank, gp)
        self._m_max_since.set(round(max(since), 3) if since else 0.0)
        rep = self.straggler.update(per_rank)
        for rank, pr in rep.get("per_rank", {}).items():
            if rank in stats:
                stats[rank].update(
                    step_p50_s=pr.get("step_p50_s"),
                    barrier_p50_s=pr.get("barrier_p50_s"))
        self._worker_stats = stats
        if gp_src is not None:
            # one worker's accounting stands for the gang: the ranks
            # run the same synchronous loop, and summing N replicated
            # clocks would count the same wall N times
            rank, gp = gp_src
            self.ledger.fold_worker(epoch, gp.get("buckets") or {})
            t0 = gp.get("t_start_wall")
            if t0:
                self.ledger.set_bucket(epoch, "startup",
                                       max(0.0, float(t0) - t_launch))
        self.aggregator.finish_scrape()
        cut = now - 600.0
        self._restart_times = [t for t in self._restart_times
                               if t >= cut]
        self._m_restart_rate.set(len(self._restart_times))
        self.ledger.export()
        self.ledger.save()
        self.alerts.evaluate()

    def _prune_ranks(self, keep: int):
        """Stale-sample hygiene before each (re)launch: a shrink or
        replacement leaves the departed ranks' per-rank gauges frozen
        at their last value — ``Metric.remove()`` them so the next
        scrape serves survivors only."""
        for m in (_m_liveness, self._m_since_step):
            snap = m.series()
            for labels in list(snap):
                d = dict(labels)
                try:
                    rank = int(d.get("rank", -1))
                except (TypeError, ValueError):
                    continue
                if rank >= keep:
                    m.remove(**d)
        for name in list(self.aggregator.members()):
            try:
                rank = int(name)
            except ValueError:
                continue
            if rank >= keep:
                self.aggregator.drop_replica(name)
                self.aggregator.forget_state(name)

    def _next_gang(self, failed_ranks: List[int]) -> bool:
        """Replacement-host injection / graceful shrink. Returns False
        when the gang cannot be re-formed within min_nprocs."""
        nfail = max(1, len(failed_ranks))
        if self.hosts is not None:
            dead = [self.hosts[r] for r in failed_ranks
                    if r < len(self.hosts)] or [self.hosts[-1]]
            for h in dead:
                if self._spares:
                    sub = self._spares.pop(0)
                    log.warning("supervisor: replacing dead host %s "
                                "with %s", h, sub)
                    self.hosts[self.hosts.index(h)] = sub
                else:
                    log.warning("supervisor: no replacement for %s — "
                                "shrinking gang", h)
                    self.hosts.remove(h)
            self.nprocs = len(self.hosts)
        else:
            covered = nfail
            if self._replacements is not None:
                covered = min(nfail, self._replacements)
                self._replacements -= covered
            short = nfail - covered
            if short:
                log.warning("supervisor: %d worker(s) not replaceable — "
                            "shrinking gang %d -> %d", short,
                            self.nprocs, self.nprocs - short)
            self.nprocs -= short
        if self.valid_sizes is not None:
            snapped = next((s for s in self.valid_sizes
                            if s <= self.nprocs), 0)
            if snapped != self.nprocs:
                log.warning("supervisor: snapping gang size %d -> %d "
                            "(valid mesh sizes)", self.nprocs, snapped)
            self.nprocs = snapped
            if self.hosts is not None:
                self.hosts = self.hosts[:snapped]
        _m_gang.set(self.nprocs)
        return self.nprocs >= self.min_nprocs

    # -- the supervision loop ---------------------------------------------
    def run(self, total_timeout: Optional[float] = None) -> dict:
        """Supervise until success or give-up; returns a result dict:
        ``ok``, ``reason`` (on failure), ``restarts``, ``epoch``,
        ``attempts`` (per-incarnation history with detection and
        first-post-restore-step timestamps — recovery_seconds rides on
        every attempt after a restart)."""
        t_end = (time.time() + total_timeout
                 if total_timeout is not None else None)
        while True:
            epoch = current_epoch(self.state_dir) + 1
            write_epoch(self.state_dir, epoch)
            self._epoch = epoch
            if self.master is not None:
                self.master.set_epoch_fence(epoch)
            # stale beats from the previous incarnation must not count
            shutil.rmtree(_hb_dir(self.state_dir), ignore_errors=True)
            self._last_probe.clear()
            self._prune_ranks(self.nprocs)
            self._set_state("launching")
            _m_gang.set(self.nprocs)
            log.info("supervisor: launching gang epoch %d (%d workers)",
                     epoch, self.nprocs)
            procs = self._spawn(epoch)
            t_launch = time.time()
            prev_detect = (self._attempts[-1].get("t_detect")
                           if self._attempts else None)
            if prev_detect:
                # detection -> this launch: teardown + post-mortem +
                # backoff, attributed to the epoch that pays for it
                self.ledger.set_bucket(epoch, "restart_gap",
                                       t_launch - prev_detect)
            attempt = {"epoch": epoch, "nprocs": self.nprocs,
                       "t_launch": t_launch, "t_first_step": None}
            self._set_state("running")
            while True:
                time.sleep(self.poll_interval)
                verdict, failed, reason = self._judge(
                    procs, epoch, t_launch, attempt)
                if verdict != "running":
                    break
                self._scrape(epoch, t_launch)
                if t_end is not None and time.time() > t_end:
                    verdict, failed = "fail", list(range(len(procs)))
                    reason = "total_timeout"
                    break
            t_detect = time.time()
            # fold the incarnation's last telemetry before the next
            # epoch clears the heartbeat dir
            self._scrape(epoch, t_launch, final=True)
            if self._attempts and self._attempts[-1].get("t_detect") \
                    and attempt["t_first_step"]:
                rec_s = attempt["t_first_step"] \
                    - self._attempts[-1]["t_detect"]
                attempt["recovery_seconds"] = round(rec_s, 3)
                _m_recovery.set(rec_s)
            if verdict == "ok":
                attempt["rcs"] = [p.returncode for p in procs]
                self._attempts.append(attempt)
                self._set_state("done")
                log.info("supervisor: gang epoch %d completed after %d "
                         "restart(s)", epoch, self._restarts)
                return {"ok": True, "restarts": self._restarts,
                        "epoch": epoch, "attempts": self._attempts}
            attempt.update(reason=reason, failed_ranks=failed,
                           t_detect=t_detect)
            self._attempts.append(attempt)
            self._set_state("teardown")
            log.warning("supervisor: gang epoch %d failed (%s, ranks "
                        "%s) — tearing down", epoch, reason, failed)
            _m_restarts.inc(reason=(reason or "unknown").split(":")[0])
            self._restart_times.append(time.time())
            self._m_restart_rate.set(len(self._restart_times))
            self._post_mortem(reason, failed, epoch)
            _launch.terminate_procs(procs)
            # a long-stable incarnation failing is a NEW fault, not a
            # crash loop: the budget refills and the backoff cools
            # (see RestartBudget)
            self._budget.note_failure(
                stepped=attempt["t_first_step"] is not None,
                uptime_s=t_detect - t_launch)
            fail_why = None
            if reason == "total_timeout" or (
                    t_end is not None and time.time() > t_end):
                fail_why = "total_timeout"
            elif self._budget.exhausted:
                fail_why = "max_restarts"
            elif reason == "attempt_timeout":
                # a whole-gang timeout names no dead machine: retry the
                # SAME gang instead of debiting N hosts/replacements
                # for one slow incarnation
                pass
            elif not self._next_gang(failed):
                fail_why = "gang_too_small"
            if fail_why:
                self._set_state("failed")
                log.error("supervisor: giving up (%s) after %d "
                          "restart(s)", fail_why, self._restarts)
                return {"ok": False, "reason": fail_why,
                        "restarts": self._restarts, "epoch": epoch,
                        "attempts": self._attempts}
            self._set_state("backoff")
            delay = self._budget.delay()
            log.info("supervisor: restart %d/%d in %.2fs (gang -> %d)",
                     self._restarts, self.max_restarts, delay,
                     self.nprocs)
            time.sleep(delay)

    def close(self):
        if self.http is not None:
            self.http.close()
