"""Fault injection for chaos tests — the ``PADDLE_TPU_CHAOS`` knob.

The elastic chaos tests need to kill, hang, or crash a worker at a
PRECISE point (a named training step, a checkpoint-commit phase)
without threading ad-hoc ``os.kill`` plumbing through every layer.
Instead the instrumented sites — the trainer's batch loop, the
checkpoint writer's commit phases — call ``maybe_trigger(site, ...)``
with their current coordinates, and the env knob decides what fires:

    PADDLE_TPU_CHAOS="kill@step:step=5:rank=1"
    PADDLE_TPU_CHAOS="hang@step:step=3:seconds=30"
    PADDLE_TPU_CHAOS="crash@checkpoint:phase=pre_manifest"
    PADDLE_TPU_CHAOS="exit@step:step=2:rank=0:code=3,kill@step:step=9"

Grammar: comma-separated rules, each ``ACTION@SITE[:key=value...]``.
A rule fires when its site matches and EVERY key it names equals the
call's attribute (ints compare numerically; missing call attrs are
filled from the env — ``rank`` from PADDLE_PROCESS_ID, ``epoch`` from
PADDLE_ELASTIC_EPOCH — so ``epoch=1`` scopes a fault to the first gang
incarnation and a restarted worker sails past it). Each rule fires at
most ``count`` times per process (default 1; ``count=0`` = always).

Actions:
    kill   — SIGKILL this process (no cleanup, the preemption model)
    exit   — ``os._exit(code)`` (default 1): sudden but with exit code
    hang   — sleep ``seconds`` (default 3600): the wedged-worker model
    crash  — raise ``ChaosError``: an in-thread software failure

Sites instrumented in-tree: ``step`` (trainer batch loop, attrs
``step``/``rank``/``epoch``) and ``checkpoint`` (io/checkpoint.py
commit protocol, attrs ``phase`` in pre_write|pre_manifest|
pre_commit|mid_commit, plus ``step``). Anything can add a site — it is
just a ``maybe_trigger`` call.

Stdlib-only; ``maybe_trigger`` is a no-op dict lookup when the env var
is unset, so instrumented hot paths pay nothing in production.
"""

import os
import signal
import threading
import time
from typing import Dict, List, Optional

from paddle_tpu.utils.logger import get_logger

log = get_logger("chaos")

ENV_VAR = "PADDLE_TPU_CHAOS"


class ChaosError(RuntimeError):
    """The injected software failure (action ``crash``)."""


class _Rule:
    __slots__ = ("action", "site", "attrs", "count", "fired")

    def __init__(self, action: str, site: str, attrs: Dict[str, str],
                 count: int):
        self.action = action
        self.site = site
        self.attrs = attrs
        self.count = count          # 0 = unlimited
        self.fired = 0

    def __repr__(self):
        kv = ":".join(f"{k}={v}" for k, v in self.attrs.items())
        return f"{self.action}@{self.site}" + (f":{kv}" if kv else "")


def _parse(spec: str) -> List[_Rule]:
    rules = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        head, *kvs = part.split(":")
        if "@" not in head:
            log.warning("chaos: malformed rule %r (need ACTION@SITE)", part)
            continue
        action, site = head.split("@", 1)
        if action not in ("kill", "exit", "hang", "crash"):
            log.warning("chaos: unknown action %r in %r", action, part)
            continue
        attrs, count = {}, 1
        ok = True
        for kv in kvs:
            if "=" not in kv:
                log.warning("chaos: malformed attr %r in %r", kv, part)
                ok = False
                break
            k, v = kv.split("=", 1)
            if k == "count":
                try:
                    count = int(v)
                except ValueError:
                    log.warning("chaos: malformed count %r in %r", v, part)
                    ok = False
                    break
            else:
                attrs[k] = v
        if ok:
            rules.append(_Rule(action, site, attrs, count))
    return rules


_lock = threading.Lock()
_cache_spec: Optional[str] = None
_cache_rules: List[_Rule] = []


def _rules_for(spec: str) -> List[_Rule]:
    """Parse-once cache keyed on the env value; fire counts live on the
    cached rule objects so ``count`` is per-process, not per-call."""
    global _cache_spec, _cache_rules
    with _lock:
        if spec != _cache_spec:
            _cache_spec = spec
            _cache_rules = _parse(spec)
        return _cache_rules


def reset():
    """Drop the parse cache and fire counts (tests)."""
    global _cache_spec, _cache_rules
    with _lock:
        _cache_spec = None
        _cache_rules = []


def _env_default(key: str) -> Optional[str]:
    if key == "rank":
        return os.environ.get("PADDLE_PROCESS_ID")
    if key == "epoch":
        return os.environ.get("PADDLE_ELASTIC_EPOCH")
    return None


#: per-action parameter keys — consumed by the ACTION, not matched
#: against the call site (exit@step:step=2:code=3 must fire at step 2,
#: not wait for a call that passes code=)
_ACTION_PARAMS = {"exit": {"code"}, "hang": {"seconds"}}


def _matches(rule: _Rule, attrs: Dict) -> bool:
    params = _ACTION_PARAMS.get(rule.action, ())
    for k, want in rule.attrs.items():
        if k in params:
            continue
        have = attrs.get(k)
        if have is None:
            have = _env_default(k)
        if have is None:
            return False
        try:
            if int(want) == int(have):
                continue
            return False
        except (TypeError, ValueError):
            pass
        if str(want) != str(have):
            return False
    return True


def maybe_trigger(site: str, **attrs):
    """Fire any armed rule matching (site, attrs). Call this from the
    point being chaos-tested; with PADDLE_TPU_CHAOS unset it is a
    single dict lookup."""
    spec = os.environ.get(ENV_VAR)
    if not spec:
        return
    for rule in _rules_for(spec):
        if rule.site != site:
            continue
        if rule.count and rule.fired >= rule.count:
            continue
        if not _matches(rule, attrs):
            continue
        rule.fired += 1
        _fire(rule, site, attrs)


def _fire(rule: _Rule, site: str, attrs: Dict):
    log.warning("chaos: firing %r at %s %s (pid %d)", rule, site,
                attrs, os.getpid())
    if rule.action == "kill":
        # SIGKILL self: the preemption model — no atexit, no flushes
        os.kill(os.getpid(), signal.SIGKILL)
        time.sleep(60)              # the signal needs a schedule tick
    elif rule.action == "exit":
        os._exit(int(rule.attrs.get("code", 1)))
    elif rule.action == "hang":
        time.sleep(float(rule.attrs.get("seconds", 3600)))
    elif rule.action == "crash":
        raise ChaosError(f"injected crash: {rule!r} at {site} {attrs}")
