"""Background-thread prefetch loader over recordio files.

The DataProvider double-buffer equivalent (reference:
gserver/dataproviders/DataProvider.h:292 DoubleBuffer — a background thread
fills batch buffers while the trainer consumes; PyDataProvider2.cpp:195 runs
the Python provider on a worker thread). Here the hot path — disk reads,
chunk CRC, record framing — runs on native C++ threads
(runtime/native/recordio.cc Loader); Python only unpickles records as they
pop. Falls back to a Python thread when the native lib is unavailable.
"""

import ctypes
import pickle
import queue
import random
import threading
from typing import Iterator, Optional

from paddle_tpu.runtime import native, recordio


class PrefetchLoader:
    """Iterate records of a recordio file with prefetching.

    shuffle=True shuffles chunk order per epoch (record-level shuffling is
    the reader decorator's job — matching the master's chunk-task dispatch
    granularity, go/master/service.go partition). With num_threads > 1,
    record order is nondeterministic across chunk boundaries (concurrent
    chunk decoding feeds one queue); pass num_threads=1 when exact file
    order matters.
    """

    def __init__(self, path: str, shuffle: bool = False,
                 seed: Optional[int] = 0, num_threads: int = 2,
                 capacity: int = 4096):
        self.path = path
        self.shuffle = shuffle
        self.num_threads = num_threads
        self.capacity = capacity
        self._rng = random.Random(seed)
        self._chunks = recordio.chunk_offsets(path)

    def __iter__(self) -> Iterator:
        offsets = [off for off, _ in self._chunks]
        if self.shuffle:
            self._rng.shuffle(offsets)
        lib = native.get()
        if lib is not None:
            yield from self._iter_native(lib, offsets)
        else:
            yield from self._iter_python(offsets)

    def _iter_native(self, lib, offsets):
        arr = (ctypes.c_longlong * len(offsets))(*offsets)
        handle = lib.loader_create(self.path.encode(), arr, len(offsets),
                                   self.num_threads, self.capacity)
        if not handle:
            raise IOError(f"loader_create failed for {self.path}")
        try:
            buf = ctypes.POINTER(ctypes.c_uint8)()
            while True:
                n = lib.loader_next(handle, ctypes.byref(buf))
                if n == 0:
                    break
                if n < 0:
                    raise IOError(f"native loader error {n} on {self.path}")
                try:
                    rec = ctypes.string_at(buf, n)
                finally:
                    lib.rio_free(buf)
                yield pickle.loads(rec)
        finally:
            lib.loader_destroy(handle)

    def _iter_python(self, offsets):
        q: "queue.Queue" = queue.Queue(maxsize=self.capacity)
        sentinel = object()
        err: list = []
        stop = threading.Event()

        def put(item):
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                for off in offsets:
                    for rec in recordio.read_chunk(self.path, off):
                        if not put(rec):
                            return          # consumer abandoned us
            except BaseException as e:      # propagate to the consumer
                err.append(e)
            finally:
                put(sentinel)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    break
                yield item
        finally:
            stop.set()
            while not q.empty():            # unblock a full queue
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join()
        if err:
            raise err[0]


def reader_creator(path: str, shuffle: bool = False, seed: Optional[int] = 0,
                   num_threads: int = 2):
    """A v2-style reader() factory over a recordio file (reference:
    python/paddle/v2/reader/creator.py recordio)."""
    loader = PrefetchLoader(path, shuffle=shuffle, seed=seed,
                            num_threads=num_threads)

    def reader():
        return iter(loader)

    return reader


class DenseBatchLoader:
    """Whole batches of FIXED-SIZE raw records assembled in C++.

    The full native data path: recordio files written with
    ``Writer(raw=True)`` hold fixed-layout byte records; C++ reader
    threads decode chunks and ``loader_next_batch`` memcpys a whole
    [batch, record_bytes] matrix into a numpy buffer — no per-record
    Python object, pickle, or malloc anywhere (the DataProvider
    double-buffer pushed to its endpoint; reference:
    gserver/dataproviders/PyDataProvider2.cpp:195 async pool).
    Falls back to the Python chunk reader when the native lib is
    unavailable. Yields np.uint8 arrays [n, record_bytes]; the tail
    batch is short unless drop_last.

    shuffle=True shuffles CHUNK order only — record grouping within a
    batch recurs across epochs (and is fixed when chunk_records ==
    batch_size). Write files with chunk_records >> batch_size (and >1
    reader thread) for cross-epoch batch diversity, or pre-shuffle
    records at write time; sample-level reshuffling is only available on
    the per-sample reader path."""

    def __init__(self, path: str, record_bytes: int, batch_size: int,
                 shuffle: bool = False, seed: Optional[int] = 0,
                 num_threads: int = 2, capacity: Optional[int] = None,
                 drop_last: bool = False):
        self.path = path
        self.record_bytes = int(record_bytes)
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.num_threads = num_threads
        # capacity is counted in RECORDS; with large fixed-layout records
        # the default is a byte budget (a few batches, <=64 MB) so the
        # prefetch queue can't balloon to gigabytes
        if capacity is None:
            capacity = max(2 * self.batch_size,
                           min(4096, (64 << 20) // max(1, self.record_bytes)))
        self.capacity = capacity
        self.drop_last = drop_last
        self._rng = random.Random(seed)
        self._chunks = recordio.chunk_offsets(path)

    def __iter__(self):
        import numpy as np
        offsets = [off for off, _ in self._chunks]
        if self.shuffle:
            self._rng.shuffle(offsets)
        lib = native.get()
        if lib is None:
            yield from self._iter_python(np, offsets)
            return
        arr = (ctypes.c_longlong * len(offsets))(*offsets)
        handle = lib.loader_create(self.path.encode(), arr, len(offsets),
                                   self.num_threads, self.capacity)
        if not handle:
            raise IOError(f"loader_create failed for {self.path}")
        try:
            while True:
                out = np.empty((self.batch_size, self.record_bytes),
                               dtype=np.uint8)
                n = lib.loader_next_batch(
                    handle, out.ctypes.data_as(
                        ctypes.POINTER(ctypes.c_uint8)),
                    self.batch_size, self.record_bytes)
                if n < 0:
                    raise IOError(
                        f"native batch loader error {n} on {self.path} "
                        f"(-100 = record size != {self.record_bytes}; "
                        f"other codes are chunk I/O/corruption)")
                if n == 0:
                    break
                if n < self.batch_size:
                    # short batch = end-of-data OR a deferred mid-batch
                    # error (the native side returns copied records
                    # first and re-surfaces the error on the next call);
                    # poke with batch=0 to distinguish, after yielding
                    # the records that were already assembled
                    probe = lib.loader_next_batch(
                        handle, out.ctypes.data_as(
                            ctypes.POINTER(ctypes.c_uint8)),
                        0, self.record_bytes)
                    if not self.drop_last:
                        yield out[:n]
                    if probe < 0:
                        raise IOError(
                            f"native batch loader error {probe} on "
                            f"{self.path} after a partial batch of {n} "
                            f"(-100 = record size != {self.record_bytes})")
                    break
                yield out
        finally:
            lib.loader_destroy(handle)

    def _iter_python(self, np, offsets):
        buf, fill = None, 0
        for off in offsets:
            for rec in recordio.read_chunk(self.path, off, raw=True):
                if len(rec) != self.record_bytes:
                    raise IOError(
                        f"record size {len(rec)} != {self.record_bytes} "
                        f"in {self.path}")
                if buf is None:
                    buf = np.empty((self.batch_size, self.record_bytes),
                                   dtype=np.uint8)
                buf[fill] = np.frombuffer(rec, dtype=np.uint8)
                fill += 1
                if fill == self.batch_size:
                    yield buf
                    buf, fill = None, 0
        if fill and not self.drop_last:
            yield buf[:fill]


def write_dense(path: str, samples, dim: int,
                chunk_records: int = 1024) -> int:
    """Pack (features float32[dim], int label) samples as fixed-layout raw
    records for DenseBatchLoader / dense_batch_reader."""
    import numpy as np

    def encode():
        for feat, label in samples:
            f = np.ascontiguousarray(feat, dtype=np.float32).reshape(-1)
            if f.size != dim:
                raise ValueError(f"feature size {f.size} != dim {dim}")
            yield f.tobytes() + np.int32(label).tobytes()

    return recordio.write_records(path, encode(),
                                  chunk_records=chunk_records, raw=True)


def dense_batch_reader(path: str, dim: int, batch_size: int,
                       shuffle: bool = False, seed: Optional[int] = 0,
                       num_threads: int = 2, drop_last: bool = False):
    """reader() factory yielding (features [n, dim] f32, labels [n] i32)
    batches assembled natively — plug straight into a feed dict or wrap
    for trainer.SGD."""
    import numpy as np

    rec_bytes = dim * 4 + 4
    rec_dtype = np.dtype([("feat", np.float32, (dim,)),
                          ("label", np.int32)])
    assert rec_dtype.itemsize == rec_bytes
    loader = DenseBatchLoader(path, rec_bytes, batch_size, shuffle=shuffle,
                              seed=seed, num_threads=num_threads,
                              drop_last=drop_last)

    def reader():
        for raw in loader:
            # zero-copy reinterpret of the contiguous [n, rec_bytes] block
            arr = raw.reshape(-1).view(rec_dtype)
            yield arr["feat"], arr["label"]

    return reader
