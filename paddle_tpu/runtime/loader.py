"""Background-thread prefetch loader over recordio files.

The DataProvider double-buffer equivalent (reference:
gserver/dataproviders/DataProvider.h:292 DoubleBuffer — a background thread
fills batch buffers while the trainer consumes; PyDataProvider2.cpp:195 runs
the Python provider on a worker thread). Here the hot path — disk reads,
chunk CRC, record framing — runs on native C++ threads
(runtime/native/recordio.cc Loader); Python only unpickles records as they
pop. Falls back to a Python thread when the native lib is unavailable.
"""

import ctypes
import pickle
import queue
import random
import threading
from typing import Iterator, Optional

from paddle_tpu.runtime import native, recordio


class PrefetchLoader:
    """Iterate records of a recordio file with prefetching.

    shuffle=True shuffles chunk order per epoch (record-level shuffling is
    the reader decorator's job — matching the master's chunk-task dispatch
    granularity, go/master/service.go partition).
    """

    def __init__(self, path: str, shuffle: bool = False,
                 seed: Optional[int] = 0, num_threads: int = 2,
                 capacity: int = 4096):
        self.path = path
        self.shuffle = shuffle
        self.num_threads = num_threads
        self.capacity = capacity
        self._rng = random.Random(seed)
        self._chunks = recordio.chunk_offsets(path)

    def __iter__(self) -> Iterator:
        offsets = [off for off, _ in self._chunks]
        if self.shuffle:
            self._rng.shuffle(offsets)
        lib = native.get()
        if lib is not None:
            yield from self._iter_native(lib, offsets)
        else:
            yield from self._iter_python(offsets)

    def _iter_native(self, lib, offsets):
        arr = (ctypes.c_longlong * len(offsets))(*offsets)
        handle = lib.loader_create(self.path.encode(), arr, len(offsets),
                                   self.num_threads, self.capacity)
        if not handle:
            raise IOError(f"loader_create failed for {self.path}")
        try:
            buf = ctypes.POINTER(ctypes.c_uint8)()
            while True:
                n = lib.loader_next(handle, ctypes.byref(buf))
                if n == 0:
                    break
                if n < 0:
                    raise IOError(f"native loader error {n} on {self.path}")
                try:
                    rec = ctypes.string_at(buf, n)
                finally:
                    lib.rio_free(buf)
                yield pickle.loads(rec)
        finally:
            lib.loader_destroy(handle)

    def _iter_python(self, offsets):
        q: "queue.Queue" = queue.Queue(maxsize=self.capacity)
        sentinel = object()
        err: list = []
        stop = threading.Event()

        def put(item):
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                for off in offsets:
                    for rec in recordio.read_chunk(self.path, off):
                        if not put(rec):
                            return          # consumer abandoned us
            except BaseException as e:      # propagate to the consumer
                err.append(e)
            finally:
                put(sentinel)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    break
                yield item
        finally:
            stop.set()
            while not q.empty():            # unblock a full queue
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join()
        if err:
            raise err[0]


def reader_creator(path: str, shuffle: bool = False, seed: Optional[int] = 0,
                   num_threads: int = 2):
    """A v2-style reader() factory over a recordio file (reference:
    python/paddle/v2/reader/creator.py recordio)."""
    loader = PrefetchLoader(path, shuffle=shuffle, seed=seed,
                            num_threads=num_threads)

    def reader():
        return iter(loader)

    return reader
