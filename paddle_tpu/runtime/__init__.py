"""Host-side runtime: record IO, data pipeline, distributed data service.

Replaces the native runtime pieces of the reference — the recordio chunk
format consumed by the Go master (go/master/service.go), the C++ data
providers (gserver/dataproviders/), and the task-dispatch service. The
recordio codec has a pure-Python implementation and a C++ accelerated one
(paddle_tpu/runtime/native/) loaded via ctypes when built.
"""

from paddle_tpu.runtime import recordio
