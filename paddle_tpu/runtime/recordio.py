"""Chunked record file format.

Reference: the Go recordio library consumed by go/master (service.go
partitions datasets into recordio chunk tasks). Format here: sequence of
chunks, each = [u32 magic][u32 nrecords][u64 payload_len][crc32]
[payload: nrecords x (u32 len + bytes)]. Pickled python objects ride as
records. A chunk is the unit of task dispatch for the data service.
"""

import pickle
import struct
import zlib
from typing import Iterable, Iterator, List, Tuple

MAGIC = 0x0A0D5EC5
HEADER = struct.Struct("<IIQI")


def write_records(path: str, records: Iterable, chunk_records: int = 1024):
    """Write records (pickled) into chunks of chunk_records each."""
    def flush(out, buf):
        payload = b"".join(struct.pack("<I", len(r)) + r for r in buf)
        out.write(HEADER.pack(MAGIC, len(buf), len(payload),
                              zlib.crc32(payload) & 0xFFFFFFFF))
        out.write(payload)

    n = 0
    with open(path, "wb") as out:
        buf: List[bytes] = []
        for rec in records:
            buf.append(pickle.dumps(rec, protocol=4))
            n += 1
            if len(buf) >= chunk_records:
                flush(out, buf)
                buf = []
        if buf:
            flush(out, buf)
    return n


def chunk_offsets(path: str) -> List[Tuple[int, int]]:
    """Index pass: [(offset, nrecords)] per chunk — what the master
    partitions into tasks (go/master/service.go:106 partition)."""
    out = []
    with open(path, "rb") as f:
        while True:
            pos = f.tell()
            hdr = f.read(HEADER.size)
            if len(hdr) < HEADER.size:
                break
            magic, n, plen, crc = HEADER.unpack(hdr)
            if magic != MAGIC:
                raise IOError(f"bad chunk magic at {pos} in {path}")
            out.append((pos, n))
            f.seek(plen, 1)
    return out


def read_chunk(path: str, offset: int) -> Iterator:
    with open(path, "rb") as f:
        f.seek(offset)
        hdr = f.read(HEADER.size)
        magic, n, plen, crc = HEADER.unpack(hdr)
        if magic != MAGIC:
            raise IOError(f"bad chunk magic at {offset}")
        payload = f.read(plen)
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            raise IOError(f"chunk crc mismatch at {offset} in {path}")
        pos = 0
        for _ in range(n):
            (rlen,) = struct.unpack_from("<I", payload, pos)
            pos += 4
            yield pickle.loads(payload[pos:pos + rlen])
            pos += rlen


def read_records(path: str) -> Iterator:
    for offset, _ in chunk_offsets(path):
        yield from read_chunk(path, offset)
