"""Chunked record file format.

Reference: the Go recordio library consumed by go/master (service.go
partitions datasets into recordio chunk tasks). Format here: sequence of
chunks, each = [u32 magic][u32 nrecords][u64 payload_len][crc32]
[payload: nrecords x (u32 len + bytes)]. Pickled python objects ride as
records. A chunk is the unit of task dispatch for the data service.
"""

import ctypes
import pickle
import struct
import zlib
from typing import Iterable, Iterator, List, Tuple

from paddle_tpu.runtime import native

MAGIC = 0x0A0D5EC5
HEADER = struct.Struct("<IIQI")


class Writer:
    """Streaming chunk writer; each ``records_per_chunk`` records become one
    chunk (the master's task-dispatch unit). Framing + CRC run in the native
    codec when built."""

    def __init__(self, path: str, records_per_chunk: int = 1024,
                 raw: bool = False):
        self.path = path
        self.records_per_chunk = records_per_chunk
        self.raw = raw
        self._lib = native.get()
        self._buf: List[bytes] = []
        self._count = 0
        if self._lib is not None:
            open(path, "wb").close()      # native writer appends
            self._out = None
        else:
            self._out = open(path, "wb")

    def write(self, record) -> None:
        """Append one record (any picklable object; with raw=True the
        record must be bytes and is framed verbatim — the fixed-layout
        fast path the native batch loader consumes)."""
        if self.raw:
            if not isinstance(record, (bytes, bytearray, memoryview)):
                raise TypeError(
                    f"raw=True writer takes bytes-like records, got "
                    f"{type(record).__name__} (bytes(int) would silently "
                    f"write zeros)")
            self._buf.append(bytes(record))
        else:
            self._buf.append(pickle.dumps(record, protocol=4))
        self._count += 1
        if len(self._buf) >= self.records_per_chunk:
            self._flush()

    def _flush(self) -> None:
        if not self._buf:
            return
        buf, self._buf = self._buf, []
        if self._lib is not None:
            data = b"".join(buf)
            lens = (ctypes.c_uint * len(buf))(*[len(r) for r in buf])
            rc = self._lib.rio_write_chunk(self.path.encode(), data, lens,
                                           len(buf))
            if rc < 0:
                raise IOError(f"rio_write_chunk failed ({rc}) "
                              f"for {self.path}")
            return
        payload = b"".join(struct.pack("<I", len(r)) + r for r in buf)
        self._out.write(HEADER.pack(MAGIC, len(buf), len(payload),
                                    zlib.crc32(payload) & 0xFFFFFFFF))
        self._out.write(payload)

    def close(self) -> int:
        """Flush the tail chunk; returns total records written."""
        self._flush()
        if self._out is not None:
            self._out.close()
            self._out = None
        return self._count

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_records(path: str, records: Iterable, chunk_records: int = 1024,
                  raw: bool = False):
    """Write records (pickled, or verbatim bytes with raw=True) into
    chunks of chunk_records each."""
    with Writer(path, records_per_chunk=chunk_records, raw=raw) as w:
        for rec in records:
            w.write(rec)
    return w.close()


def chunk_offsets(path: str) -> List[Tuple[int, int]]:
    """Index pass: [(offset, nrecords)] per chunk — what the master
    partitions into tasks (go/master/service.go:106 partition)."""
    lib = native.get()
    if lib is not None:
        offs = ctypes.POINTER(ctypes.c_longlong)()
        cnts = ctypes.POINTER(ctypes.c_uint)()
        n = lib.rio_index(path.encode(), ctypes.byref(offs),
                          ctypes.byref(cnts))
        if n < 0:
            raise IOError(f"rio_index failed ({n}) for {path}")
        try:
            return [(int(offs[i]), int(cnts[i])) for i in range(n)]
        finally:
            lib.rio_free(offs)
            lib.rio_free(cnts)
    out = []
    with open(path, "rb") as f:
        while True:
            pos = f.tell()
            hdr = f.read(HEADER.size)
            if len(hdr) < HEADER.size:
                break
            magic, n, plen, crc = HEADER.unpack(hdr)
            if magic != MAGIC:
                raise IOError(f"bad chunk magic at {pos} in {path}")
            out.append((pos, n))
            f.seek(plen, 1)
    return out


def _iter_payload(payload: bytes, n: int, raw: bool = False) -> Iterator:
    pos = 0
    for _ in range(n):
        (rlen,) = struct.unpack_from("<I", payload, pos)
        pos += 4
        rec = payload[pos:pos + rlen]
        yield rec if raw else pickle.loads(rec)
        pos += rlen


def read_chunk(path: str, offset: int, raw: bool = False) -> Iterator:
    lib = native.get()
    if lib is not None:
        buf = ctypes.POINTER(ctypes.c_uint8)()
        nrec = ctypes.c_uint()
        plen = lib.rio_read_chunk(path.encode(), offset, ctypes.byref(buf),
                                  ctypes.byref(nrec))
        if plen < 0:
            raise IOError(f"rio_read_chunk failed ({plen}) at {offset} "
                          f"in {path}")
        try:
            payload = ctypes.string_at(buf, plen)
        finally:
            lib.rio_free(buf)
        yield from _iter_payload(payload, nrec.value, raw)
        return
    with open(path, "rb") as f:
        f.seek(offset)
        hdr = f.read(HEADER.size)
        magic, n, plen, crc = HEADER.unpack(hdr)
        if magic != MAGIC:
            raise IOError(f"bad chunk magic at {offset}")
        payload = f.read(plen)
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            raise IOError(f"chunk crc mismatch at {offset} in {path}")
        yield from _iter_payload(payload, n, raw)


def read_records(path: str) -> Iterator:
    for offset, _ in chunk_offsets(path):
        yield from read_chunk(path, offset)
