"""Elastic data-dispatch service — the Go master equivalent.

Reference: go/master/service.go — a dataset is partitioned into recordio-chunk
tasks held in todo/pending/done queues (:56-131); trainers lease tasks,
leases time out back to todo; tasks failing more than ``failure_max`` times
are discarded; state snapshots to etcd for crash recovery (:99,149-177).
Python client: python/paddle/v2/master/client.py (set_dataset/next_record).

TPU-native design: trainers are stateless task consumers (any chip-holder can
die and its chunk is re-dispatched), the state store is a JSON snapshot file
(the etcd slot — swap in any kv store), and the wire protocol is
newline-delimited JSON over TCP for multi-host, or direct calls in-process.
"""

import dataclasses
import json
import os
import socket
import socketserver
import threading
import time
from typing import Dict, List, Optional, Sequence

from paddle_tpu.runtime import recordio
from paddle_tpu.utils.logger import get_logger

log = get_logger("master")


@dataclasses.dataclass
class Task:
    """One unit of dispatch: a group of chunks of one file (go/master
    Task holds recordio chunks)."""
    task_id: int
    path: str
    chunks: List[List[int]]            # [[offset, nrecords], ...]
    fail_count: int = 0
    lease: int = 0                     # lease token; stale reports rejected

    @property
    def nrecords(self):
        return sum(c[1] for c in self.chunks)

    def to_dict(self):
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d):
        return cls(**d)


class MasterService:
    """Task queues with leases (thread-safe).

    Lifecycle per epoch (pass): todo → pending(lease) → done; expired leases
    requeue; over-failed tasks are dropped (service.go task lifecycle).
    """

    def __init__(self, lease_seconds: float = 60.0, failure_max: int = 3,
                 num_passes: Optional[int] = None,
                 snapshot_path: Optional[str] = None,
                 time_fn=time.monotonic):
        """num_passes: stop refilling after this many completed passes
        (None = refill forever; the reference's pass barriers are
        WaitPassStart/Finish, proto/ParameterService.proto:89-95)."""
        self._lock = threading.Lock()
        self._todo: List[Task] = []
        self._pending: Dict[int, tuple] = {}     # id -> (task, deadline)
        self._done: List[Task] = []
        self._discarded: List[Task] = []
        self.lease_seconds = lease_seconds
        self.failure_max = failure_max
        self.snapshot_path = snapshot_path
        self._time = time_fn
        self.num_passes = num_passes
        self._epoch = 0
        self._lease_counter = 0
        if snapshot_path and os.path.exists(snapshot_path):
            self._restore()

    # -- dataset -----------------------------------------------------------
    def set_dataset(self, paths: Sequence[str], chunks_per_task: int = 1):
        """Partition recordio files into tasks of ``chunks_per_task`` chunks
        each (service.go partition)."""
        tasks, tid = [], 0
        for path in paths:
            buf = []
            for offset, n in recordio.chunk_offsets(path):
                buf.append([offset, n])
                if len(buf) >= chunks_per_task:
                    tasks.append(Task(tid, path, buf))
                    tid += 1
                    buf = []
            if buf:
                tasks.append(Task(tid, path, buf))
                tid += 1
        with self._lock:
            self._todo = tasks
            self._pending.clear()
            self._done.clear()
            self._discarded.clear()
            self._epoch = 0
        self._snapshot()
        log.info("master: dataset set, %d tasks", len(tasks))

    # -- task protocol -----------------------------------------------------
    def get_task(self) -> Optional[Task]:
        """Lease one task; None when this pass is drained (caller should
        retry after pending tasks finish, or treat the pass as over when
        num_pending()==0)."""
        with self._lock:
            self._requeue_expired_locked()
            if not self._todo:
                return None
            task = self._todo.pop(0)
            self._lease_counter += 1
            task.lease = self._lease_counter
            self._pending[task.task_id] = (task,
                                           self._time() + self.lease_seconds)
            return task

    def report_done(self, task_id: int, lease: Optional[int] = None) -> bool:
        with self._lock:
            ent = self._pending.get(task_id)
            if ent is None or (lease is not None and ent[0].lease != lease):
                return False       # stale report from a timed-out trainer
            self._pending.pop(task_id)
            self._done.append(ent[0])
            self._maybe_finish_pass_locked()
            return True

    def report_failed(self, task_id: int, lease: Optional[int] = None):
        """Failed lease: requeue unless over the failure cap
        (service.go failureMax discard)."""
        with self._lock:
            ent = self._pending.get(task_id)
            if ent is None or (lease is not None and ent[0].lease != lease):
                return             # stale report from a timed-out trainer
            self._pending.pop(task_id)
            task = ent[0]
            task.fail_count += 1
            if task.fail_count >= self.failure_max:
                log.warning("master: task %d discarded after %d failures",
                            task.task_id, task.fail_count)
                self._discarded.append(task)
                self._maybe_finish_pass_locked()
            else:
                self._todo.append(task)

    def _requeue_expired_locked(self):
        now = self._time()
        expired = [tid for tid, (_, dl) in self._pending.items() if dl < now]
        for tid in expired:
            task, _ = self._pending.pop(tid)
            task.fail_count += 1
            if task.fail_count >= self.failure_max:
                self._discarded.append(task)
                self._maybe_finish_pass_locked()
            else:
                log.info("master: lease expired, requeueing task %d", tid)
                self._todo.append(task)

    def _maybe_finish_pass_locked(self):
        if not self._todo and not self._pending:
            # pass complete: everything done/discarded flows back to todo
            # for the next pass, unless num_passes is exhausted
            self._epoch += 1
            finished = self._done + self._discarded
            self._done, self._discarded = [], []
            if self.num_passes is not None and self._epoch >= self.num_passes:
                return                       # terminal: queues stay empty
            self._todo = finished
            for t in self._todo:
                t.fail_count = 0

    # -- introspection -----------------------------------------------------
    def num_todo(self):
        with self._lock:
            return len(self._todo)

    def num_pending(self):
        with self._lock:
            self._requeue_expired_locked()
            return len(self._pending)

    def epoch(self):
        with self._lock:
            return self._epoch

    # -- persistence (the etcd slot) ---------------------------------------
    def _snapshot(self):
        if not self.snapshot_path:
            return
        with self._lock:
            state = {
                "epoch": self._epoch,
                "todo": [t.to_dict() for t in self._todo],
                # pending leases are deliberately snapshotted as todo: after
                # a master restart their trainers may be gone (service.go
                # recover path re-dispatches)
                "pending": [t.to_dict() for t, _ in self._pending.values()],
                "done": [t.to_dict() for t in self._done],
                "discarded": [t.to_dict() for t in self._discarded],
            }
        tmp = self.snapshot_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, self.snapshot_path)

    def snapshot(self):
        self._snapshot()

    def _restore(self):
        with open(self.snapshot_path) as f:
            state = json.load(f)
        self._epoch = state["epoch"]
        self._todo = ([Task.from_dict(d) for d in state["todo"]] +
                      [Task.from_dict(d) for d in state["pending"]])
        self._done = [Task.from_dict(d) for d in state["done"]]
        self._discarded = [Task.from_dict(d)
                           for d in state.get("discarded", [])]
        log.info("master: restored %d todo / %d done (epoch %d)",
                 len(self._todo), len(self._done), self._epoch)


# ---------------------------------------------------------------------------
# TCP wire (newline-delimited JSON) — multi-host trainers
# ---------------------------------------------------------------------------

class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        for line in self.rfile:
            try:
                req = json.loads(line)
                method = req["method"]
                svc = self.server.service            # type: ignore
                if method == "get_task":
                    t = svc.get_task()
                    resp = {"task": t.to_dict() if t else None}
                elif method == "report_done":
                    resp = {"ok": svc.report_done(req["task_id"],
                                                  req.get("lease"))}
                elif method == "report_failed":
                    svc.report_failed(req["task_id"], req.get("lease"))
                    resp = {"ok": True}
                elif method == "status":
                    resp = {"todo": svc.num_todo(),
                            "pending": svc.num_pending(),
                            "epoch": svc.epoch()}
                else:
                    resp = {"error": f"unknown method {method}"}
            except Exception as e:                   # noqa: BLE001
                resp = {"error": str(e)}
            self.wfile.write((json.dumps(resp) + "\n").encode())
            self.wfile.flush()


class MasterServer:
    """Serve a MasterService over TCP (the ProtoServer/net-rpc slot)."""

    def __init__(self, service: MasterService, host: str = "127.0.0.1",
                 port: int = 0):
        self._srv = socketserver.ThreadingTCPServer((host, port), _Handler,
                                                    bind_and_activate=True)
        self._srv.daemon_threads = True
        self._srv.service = service                  # type: ignore
        self.addr = self._srv.server_address
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    def shutdown(self):
        self._srv.shutdown()
        self._srv.server_close()


class MasterClient:
    """Client for trainers. ``addr=None`` talks to an in-process service
    (reference: python/paddle/v2/master/client.py set_dataset/next_record
    over the C binding; here JSON/TCP or direct calls)."""

    def __init__(self, service: Optional[MasterService] = None,
                 addr: Optional[tuple] = None):
        assert (service is None) != (addr is None), \
            "pass exactly one of service/addr"
        self._svc = service
        self._addr = addr
        self._sock = None

    def _rpc(self, method, **kw):
        if self._svc is not None:
            if method == "get_task":
                t = self._svc.get_task()
                return {"task": t.to_dict() if t else None}
            if method == "report_done":
                return {"ok": self._svc.report_done(kw["task_id"],
                                                    kw.get("lease"))}
            if method == "report_failed":
                self._svc.report_failed(kw["task_id"], kw.get("lease"))
                return {"ok": True}
            if method == "status":
                return {"todo": self._svc.num_todo(),
                        "pending": self._svc.num_pending(),
                        "epoch": self._svc.epoch()}
        if self._sock is None:
            self._sock = socket.create_connection(self._addr)
            self._file = self._sock.makefile("rwb")
        self._file.write((json.dumps({"method": method, **kw}) + "\n")
                         .encode())
        self._file.flush()
        resp = json.loads(self._file.readline())
        if "error" in resp:
            raise RuntimeError(f"master rpc error: {resp['error']}")
        return resp

    def get_task(self) -> Optional[Task]:
        d = self._rpc("get_task")["task"]
        return Task.from_dict(d) if d else None

    def report_done(self, task_id: int, lease: Optional[int] = None):
        self._rpc("report_done", task_id=task_id, lease=lease)

    def report_failed(self, task_id: int, lease: Optional[int] = None):
        self._rpc("report_failed", task_id=task_id, lease=lease)

    def status(self):
        return self._rpc("status")

    def close(self):
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    def reader(self, poll_interval: float = 0.05, max_epochs: int = 1):
        """A v2 reader(): stream records task-by-task until ``max_epochs``
        passes complete — the trainer.train(reader=...) integration
        (reference: master client next_record consumed by the v2 reader)."""

        def gen():
            start_epoch = self.status()["epoch"]
            while True:
                st = self.status()
                if st["epoch"] >= start_epoch + max_epochs:
                    return
                task = self.get_task()
                if task is None:
                    if st["pending"] == 0 and \
                            self.status()["epoch"] >= start_epoch + max_epochs:
                        return
                    time.sleep(poll_interval)
                    continue
                try:
                    for off, _ in task.chunks:
                        yield from recordio.read_chunk(task.path, off)
                except Exception:
                    self.report_failed(task.task_id, task.lease)
                    raise
                self.report_done(task.task_id, task.lease)

        return gen
