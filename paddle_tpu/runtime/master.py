"""Elastic data-dispatch service — the Go master equivalent.

Reference: go/master/service.go — a dataset is partitioned into recordio-chunk
tasks held in todo/pending/done queues (:56-131); trainers lease tasks,
leases time out back to todo; tasks failing more than ``failure_max`` times
are discarded; state snapshots to etcd for crash recovery (:99,149-177).
Python client: python/paddle/v2/master/client.py (set_dataset/next_record).

TPU-native design: trainers are stateless task consumers (any chip-holder can
die and its chunk is re-dispatched), the state store is a JSON snapshot file
(the etcd slot — swap in any kv store), and the wire protocol is
newline-delimited JSON over TCP for multi-host, or direct calls in-process.

High availability (go/master/etcd_client.go leader election +
service.go:99,166 state recovery): the MASTER itself may die. A standby
``HAMaster`` campaigns on a file-based leader lock (the etcd election
slot); on takeover it restores the task queues from the snapshot —
in-flight leases deliberately requeue, their trainers may be gone — and
publishes its address+term in the lock file. ``MasterClient`` given a
``discovery_path`` re-reads the lock on connection failure and retries
against the new leader (lease tokens keep duplicate/stale reports safe).
"""

import dataclasses
import json
import os
import random
import socket
import socketserver
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence

from paddle_tpu.observe import metrics as _metrics
from paddle_tpu.runtime import recordio
from paddle_tpu.utils.logger import get_logger

log = get_logger("master")

# default-registry metrics, labeled by service name so several masters in
# one process (HA standby tests) stay distinguishable
_m_queue = _metrics.gauge(
    "master_task_queue_depth",
    "tasks per queue (labels: service, queue=todo|pending|done|discarded)")
_m_done = _metrics.counter("master_tasks_done_total",
                           "tasks reported done")
_m_failed = _metrics.counter("master_tasks_failed_total",
                             "tasks reported failed")
_m_discarded = _metrics.counter(
    "master_tasks_discarded_total",
    "tasks dropped after failure_max failures")
_m_expired = _metrics.counter("master_lease_expired_total",
                              "leases that timed out and requeued")
_m_passes = _metrics.counter("master_passes_total", "completed passes")
_m_task_wait = _metrics.counter(
    "master_task_wait_seconds_total",
    "client time spent polling for a task (the data-barrier wait)")
_m_fenced = _metrics.counter(
    "master_fenced_requests_total",
    "task RPCs rejected because the worker's coordination epoch is "
    "older than the fence (zombie gang members)")
_m_reconnects = _metrics.counter(
    "master_client_reconnects_total",
    "client reconnect attempts after a connection failure")


@dataclasses.dataclass
class Task:
    """One unit of dispatch: a group of chunks of one file (go/master
    Task holds recordio chunks)."""
    task_id: int
    path: str
    chunks: List[List[int]]            # [[offset, nrecords], ...]
    fail_count: int = 0
    lease: int = 0                     # lease token; stale reports rejected

    @property
    def nrecords(self):
        return sum(c[1] for c in self.chunks)

    def to_dict(self):
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d):
        return cls(**d)


class MasterService:
    """Task queues with leases (thread-safe).

    Lifecycle per epoch (pass): todo → pending(lease) → done; expired leases
    requeue; over-failed tasks are dropped (service.go task lifecycle).
    """

    def __init__(self, lease_seconds: float = 60.0, failure_max: int = 3,
                 num_passes: Optional[int] = None,
                 snapshot_path: Optional[str] = None,
                 time_fn=time.monotonic,
                 snapshot_interval: float = 0.05,
                 name: str = "master"):
        """num_passes: stop refilling after this many completed passes
        (None = refill forever; the reference's pass barriers are
        WaitPassStart/Finish, proto/ParameterService.proto:89-95).
        Snapshots are written by a debounced background thread at most
        every ``snapshot_interval`` seconds — queue mutations mark state
        dirty instead of serializing the whole queue per RPC."""
        self.name = name
        self._lock = threading.Lock()
        self._todo: List[Task] = []
        self._pending: Dict[int, tuple] = {}     # id -> (task, deadline)
        self._done: List[Task] = []
        self._discarded: List[Task] = []
        self.lease_seconds = lease_seconds
        self.failure_max = failure_max
        self.snapshot_path = snapshot_path
        self._time = time_fn
        self.num_passes = num_passes
        self._epoch = 0
        self._lease_counter = 0
        # snapshot plumbing: _version counts mutations (under _lock);
        # _snap_lock + _snap_written make concurrent writers safe and
        # monotonic (an older capture never overwrites a newer file)
        self._version = 0
        self._snap_written = -1
        self._snap_lock = threading.Lock()
        self._dirty = threading.Event()
        self._stop = threading.Event()
        # fencing hook: when set (HA mode), snapshots are written only
        # while this process still holds the leader lock — a deposed
        # zombie must not clobber the new leader's snapshot
        self.fence = None
        self.snapshot_interval = snapshot_interval
        # save-model election state: (holder trainer_id, grant expiry).
        # Deliberately NOT snapshotted — after failover re-electing a
        # saver is harmless (worst case one extra checkpoint), whereas a
        # restored stale grant could block saves for a full window.
        self._save_grant = (None, 0.0)
        # elastic epoch fence: task RPCs carrying a worker_epoch below
        # this are rejected — a zombie from a torn-down gang can never
        # lease work or commit task state (runtime/supervisor.py bumps
        # it on every gang restart). Snapshotted: a failed-over master
        # must keep fencing the same zombies.
        self._epoch_fence = 0
        if snapshot_path and os.path.exists(snapshot_path):
            self._restore()
        if snapshot_path:
            threading.Thread(target=self._snapshot_loop,
                             daemon=True).start()

    def _export_queues_locked(self):
        """Refresh the queue-depth gauges (caller holds self._lock)."""
        for queue, coll in (("todo", self._todo), ("pending", self._pending),
                            ("done", self._done),
                            ("discarded", self._discarded)):
            _m_queue.set(len(coll), service=self.name, queue=queue)

    # -- dataset -----------------------------------------------------------
    def set_dataset(self, paths: Sequence[str], chunks_per_task: int = 1):
        """Partition recordio files into tasks of ``chunks_per_task`` chunks
        each (service.go partition)."""
        tasks, tid = [], 0
        for path in paths:
            buf = []
            for offset, n in recordio.chunk_offsets(path):
                buf.append([offset, n])
                if len(buf) >= chunks_per_task:
                    tasks.append(Task(tid, path, buf))
                    tid += 1
                    buf = []
            if buf:
                tasks.append(Task(tid, path, buf))
                tid += 1
        with self._lock:
            self._todo = tasks
            self._pending.clear()
            self._done.clear()
            self._discarded.clear()
            self._epoch = 0
            self._version += 1
            self._export_queues_locked()
        self._snapshot()
        log.info("master: dataset set, %d tasks", len(tasks))

    # -- elastic epoch fencing ---------------------------------------------
    def set_epoch_fence(self, epoch: int) -> int:
        """Reject task RPCs from workers whose coordination epoch is
        below ``epoch`` (monotonic; returns the active fence). The
        supervisor calls this after every gang teardown so a zombie
        worker that survived the kill can never lease a task or commit
        one as done/failed."""
        with self._lock:
            self._epoch_fence = max(self._epoch_fence, int(epoch))
            self._version += 1
            fence = self._epoch_fence
        self._dirty.set()
        log.info("master: epoch fence now %d", fence)
        return fence

    def _fenced(self, worker_epoch) -> bool:
        """True when this RPC must be rejected. Workers that do not
        declare an epoch (pre-elastic clients) are never fenced — the
        fence is an opt-in contract between supervisor and gang."""
        if worker_epoch is None:
            return False
        with self._lock:
            fenced = int(worker_epoch) < self._epoch_fence
        if fenced:
            _m_fenced.inc(service=self.name)
        return fenced

    # -- task protocol -----------------------------------------------------
    def get_task(self, worker_epoch=None) -> Optional[Task]:
        """Lease one task; None when this pass is drained (caller should
        retry after pending tasks finish, or treat the pass as over when
        num_pending()==0)."""
        if self._fenced(worker_epoch):
            return None
        with self._lock:
            changed = self._requeue_expired_locked()
            if not self._todo:
                task = None
            else:
                task = self._todo.pop(0)
                self._lease_counter += 1
                task.lease = self._lease_counter
                self._pending[task.task_id] = (
                    task, self._time() + self.lease_seconds)
                changed = True
            if changed:
                self._version += 1
                self._export_queues_locked()
        if changed:
            # mark dirty (service.go snapshots queue transitions to etcd)
            # so a standby master can adopt fresh state on takeover;
            # expiry-only mutations count too
            self._dirty.set()
        return task

    def report_done(self, task_id: int, lease: Optional[int] = None,
                    worker_epoch=None) -> bool:
        if self._fenced(worker_epoch):
            return False       # a zombie cannot commit task state
        with self._lock:
            ent = self._pending.get(task_id)
            if ent is None or (lease is not None and ent[0].lease != lease):
                return False       # stale report from a timed-out trainer
            self._pending.pop(task_id)
            self._done.append(ent[0])
            self._maybe_finish_pass_locked()
            self._version += 1
            self._export_queues_locked()
        _m_done.inc(service=self.name)
        self._dirty.set()
        return True

    def report_failed(self, task_id: int, lease: Optional[int] = None,
                      worker_epoch=None):
        """Failed lease: requeue unless over the failure cap
        (service.go failureMax discard)."""
        if self._fenced(worker_epoch):
            return             # a zombie cannot fail a live gang's lease
        with self._lock:
            ent = self._pending.get(task_id)
            if ent is None or (lease is not None and ent[0].lease != lease):
                return             # stale report from a timed-out trainer
            self._pending.pop(task_id)
            task = ent[0]
            task.fail_count += 1
            discarded = task.fail_count >= self.failure_max
            if discarded:
                log.warning("master: task %d discarded after %d failures",
                            task.task_id, task.fail_count)
                self._discarded.append(task)
                self._maybe_finish_pass_locked()
            else:
                self._todo.append(task)
            self._version += 1
            self._export_queues_locked()
        _m_failed.inc(service=self.name)
        if discarded:
            _m_discarded.inc(service=self.name)
        self._dirty.set()

    def _requeue_expired_locked(self) -> bool:
        now = self._time()
        expired = [tid for tid, (_, dl) in self._pending.items() if dl < now]
        for tid in expired:
            task, _ = self._pending.pop(tid)
            task.fail_count += 1
            _m_expired.inc(service=self.name)
            if task.fail_count >= self.failure_max:
                self._discarded.append(task)
                _m_discarded.inc(service=self.name)
                self._maybe_finish_pass_locked()
            else:
                log.info("master: lease expired, requeueing task %d", tid)
                self._todo.append(task)
        if expired:
            self._export_queues_locked()
        return bool(expired)

    def _maybe_finish_pass_locked(self):
        if not self._todo and not self._pending:
            # pass complete: everything done/discarded flows back to todo
            # for the next pass, unless num_passes is exhausted
            self._epoch += 1
            _m_passes.inc(service=self.name)
            finished = self._done + self._discarded
            self._done, self._discarded = [], []
            if self.num_passes is not None and self._epoch >= self.num_passes:
                return                       # terminal: queues stay empty
            self._todo = finished
            for t in self._todo:
                t.fail_count = 0

    # -- save-model election ----------------------------------------------
    def request_save_model(self, trainer_id: str,
                           block_dur: float = 60.0,
                           worker_epoch=None) -> bool:
        """Elect ONE trainer to save the model: the first asker within a
        ``block_dur`` window gets True, everyone else False until the
        window expires (reference: go/master/service.go RequestSaveModel
        / python/paddle/v2/master/client.py:24 request_save_model — the
        mechanism that stops N data-parallel trainers writing N identical
        checkpoints). Re-asking while holding the grant is idempotent, so
        a saver that retries its RPC keeps its election. Epoch-fenced
        like the task RPCs: a zombie must not grab the grant and starve
        the live gang's save windows."""
        if self._fenced(worker_epoch):
            return False
        with self._lock:
            now = self._time()
            holder, expiry = self._save_grant
            if holder is not None and now < expiry and holder != trainer_id:
                return False
            self._save_grant = (trainer_id, now + block_dur)
            return True

    # -- introspection -----------------------------------------------------
    def health(self) -> dict:
        """/healthz document: queue depths + pass progress. A close()d
        master reports unhealthy (HTTP 503) — a retired dispatcher must
        drain its probers rather than keep attracting trainers."""
        with self._lock:
            changed = self._requeue_expired_locked()
            if changed:
                self._version += 1
            doc = {"service": self.name,
                   "todo": len(self._todo),
                   "pending": len(self._pending),
                   "done": len(self._done),
                   "discarded": len(self._discarded),
                   "epoch": self._epoch,
                   "epoch_fence": self._epoch_fence,
                   "healthy": not self._stop.is_set()}
        if changed:
            self._dirty.set()
        return doc

    def num_todo(self):
        with self._lock:
            return len(self._todo)

    def num_pending(self):
        with self._lock:
            changed = self._requeue_expired_locked()
            if changed:
                self._version += 1
            n = len(self._pending)
        if changed:
            self._dirty.set()
        return n

    def epoch(self):
        with self._lock:
            return self._epoch

    # -- persistence (the etcd slot) ---------------------------------------
    def _snapshot(self):
        if not self.snapshot_path:
            return
        if self.fence is not None and not self.fence():
            log.warning("master: snapshot skipped — leadership lost")
            return
        with self._lock:
            version = self._version
            state = {
                "epoch": self._epoch,
                "epoch_fence": self._epoch_fence,
                "lease_counter": self._lease_counter,
                "todo": [t.to_dict() for t in self._todo],
                # pending leases are deliberately snapshotted as todo: after
                # a master restart their trainers may be gone (service.go
                # recover path re-dispatches)
                "pending": [t.to_dict() for t, _ in self._pending.values()],
                "done": [t.to_dict() for t in self._done],
                "discarded": [t.to_dict() for t in self._discarded],
            }
        with self._snap_lock:
            # concurrent captures write in version order only — an older
            # capture must never overwrite a newer snapshot file
            if version <= self._snap_written:
                return
            tmp = (f"{self.snapshot_path}.tmp.{os.getpid()}."
                   f"{threading.get_ident()}")
            with open(tmp, "w") as f:
                json.dump(state, f)
            os.replace(tmp, self.snapshot_path)
            self._snap_written = version

    def snapshot(self):
        """Synchronous flush (set_dataset and tests use this)."""
        self._snapshot()

    def _snapshot_loop(self):
        """Debounced writer: wakes on dirty state, writes at most every
        ``snapshot_interval`` seconds regardless of RPC rate. Exits when
        close() is called (an immortal daemon thread would pin the
        service object and keep writing after shutdown)."""
        while not self._stop.is_set():
            if not self._dirty.wait(timeout=0.2):
                continue
            self._dirty.clear()
            if self._stop.is_set():
                return
            try:
                self._snapshot()
            except OSError as e:
                log.warning("master: snapshot write failed: %s", e)
            time.sleep(self.snapshot_interval)

    def close(self):
        """Stop the background snapshot writer (idempotent)."""
        self._stop.set()
        self._dirty.set()

    def _restore(self):
        with open(self.snapshot_path) as f:
            state = json.load(f)
        with self._lock:
            self._epoch = state["epoch"]
            # persisted lease counter: a failed-over master must not
            # reissue tokens that stale pre-failover reports still hold
            self._lease_counter = max(self._lease_counter,
                                      state.get("lease_counter", 0))
            self._epoch_fence = max(self._epoch_fence,
                                    state.get("epoch_fence", 0))
            self._todo = ([Task.from_dict(d) for d in state["todo"]] +
                          [Task.from_dict(d) for d in state["pending"]])
            self._pending = {}
            self._done = [Task.from_dict(d) for d in state["done"]]
            self._discarded = [Task.from_dict(d)
                               for d in state.get("discarded", [])]
            self._version += 1
            self._export_queues_locked()
        log.info("master: restored %d todo / %d done (epoch %d)",
                 len(self._todo), len(self._done), self._epoch)


# ---------------------------------------------------------------------------
# TCP wire (newline-delimited JSON) — multi-host trainers
# ---------------------------------------------------------------------------

class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        for line in self.rfile:
            try:
                req = json.loads(line)
                method = req["method"]
                svc = self.server.service            # type: ignore
                if method == "get_task":
                    t = svc.get_task(req.get("worker_epoch"))
                    resp = {"task": t.to_dict() if t else None}
                elif method == "report_done":
                    resp = {"ok": svc.report_done(req["task_id"],
                                                  req.get("lease"),
                                                  req.get("worker_epoch"))}
                elif method == "report_failed":
                    svc.report_failed(req["task_id"], req.get("lease"),
                                      req.get("worker_epoch"))
                    resp = {"ok": True}
                elif method == "set_epoch_fence":
                    resp = {"fence": svc.set_epoch_fence(req["epoch"])}
                elif method == "status":
                    resp = {"todo": svc.num_todo(),
                            "pending": svc.num_pending(),
                            "epoch": svc.epoch()}
                elif method == "metrics":
                    # poor-man's scrape endpoint: the master process's
                    # default registry in Prometheus text format
                    resp = {"text":
                            _metrics.default_registry().render_prometheus()}
                elif method == "request_save_model":
                    resp = {"ok": svc.request_save_model(
                        req["trainer_id"], req.get("block_dur", 60.0),
                        req.get("worker_epoch"))}
                else:
                    resp = {"error": f"unknown method {method}"}
            except Exception as e:                   # noqa: BLE001
                resp = {"error": str(e)}
            self.wfile.write((json.dumps(resp) + "\n").encode())
            self.wfile.flush()


class MasterServer:
    """Serve a MasterService over TCP (the ProtoServer/net-rpc slot).

    ``http_port`` (None = off, 0 = ephemeral) additionally starts an
    ``observe.HealthServer`` next to the wire protocol: ``/metrics`` is
    the process default registry (where the master gauges live) in
    Prometheus text, ``/healthz`` is ``service.health()`` — the scrape
    surface a prober hits without speaking the JSON-RPC wire."""

    def __init__(self, service: MasterService, host: str = "127.0.0.1",
                 port: int = 0, http_port: Optional[int] = None):
        self._srv = socketserver.ThreadingTCPServer((host, port), _Handler,
                                                    bind_and_activate=True)
        self._srv.daemon_threads = True
        self._srv.service = service                  # type: ignore
        self.addr = self._srv.server_address
        self.http = None
        if http_port is not None:
            from paddle_tpu.observe.health import HealthServer
            try:
                self.http = HealthServer(health_fn=service.health,
                                         host=host, port=http_port)
            except Exception:
                # a failed http bind must not leak the already-bound RPC
                # socket (a retry on a fixed port would hit EADDRINUSE)
                self._srv.server_close()
                raise
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    def shutdown(self):
        if self.http is not None:
            self.http.close()
        self._srv.shutdown()
        self._srv.server_close()


# ---------------------------------------------------------------------------
# leader election (the etcd_client.go slot) + HA master
# ---------------------------------------------------------------------------

class LeaderLock:
    """Directory-based leader lease: the holder heartbeats ``info.json``
    inside the lock DIRECTORY; a candidate takes over only when the
    heartbeat is stale (holder dead). The info file doubles as service
    discovery: the leader publishes ``{"host", "port", "term"}`` there.

    Atomicity (the split-brain guard): acquisition is ``os.mkdir`` —
    atomic, one winner. Takeover of a stale lock first ``os.rename``s the
    dead directory aside; rename is atomic on POSIX, so of N concurrent
    candidates exactly one succeeds and the rest see ENOENT and back off
    — nobody can delete a lock a new winner just created (the unlink+
    create scheme had exactly that hole). (Reference:
    go/master/etcd_client.go campaign/lock.)

    Clock assumption: staleness compares the info file's mtime (stamped
    by the FILESYSTEM) against the candidate's ``time.time()``. On one
    host (the launch.py topology) both come from the same clock and the
    comparison is exact. On a shared filesystem with replicas on
    different hosts, clock skew between the fs server and a candidate
    shifts the perceived age by the skew — keep ``stale_after`` well
    above the worst-case skew (or run candidates on one host). Term
    fencing bounds the damage of a premature takeover to one heartbeat
    interval either way."""

    def __init__(self, path: str, stale_after: float = 3.0,
                 heartbeat_interval: float = 0.5):
        self.path = path
        self.stale_after = stale_after
        self.heartbeat_interval = heartbeat_interval
        self.term = 0
        self._stop = threading.Event()
        self._thread = None

    @property
    def info_path(self):
        return os.path.join(self.path, "info.json")

    def _heartbeat_age(self) -> Optional[float]:
        """Seconds since the holder's last heartbeat; None if no lock.
        A freshly mkdir'd lock whose info.json isn't published yet ages
        from the directory mtime, so a winner mid-publish is 'live'."""
        for p in (self.info_path, self.path):
            try:
                return time.time() - os.path.getmtime(p)
            except OSError:
                continue
        return None

    def _steal_mutex(self):
        """Serialize the check-rename-mkdir critical section among LOCAL
        candidates racing for a STALE lock: an O_EXCL sidecar file with
        its own (short) staleness. Without it, a slow candidate's rename
        could grab a lock a fast winner just re-created (the TOCTOU the
        docstring promises away). The window a dead mutex holder blocks
        others is ``stale_after`` seconds, then the mutex itself is
        steal-able by age."""
        mpath = self.path + ".steal"
        try:
            mage = time.time() - os.path.getmtime(mpath)
            if mage > self.stale_after:
                os.unlink(mpath)            # holder died mid-section
        except OSError:
            pass
        try:
            fd = os.open(mpath, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.close(fd)
            return mpath
        except FileExistsError:
            return None

    def try_acquire(self) -> bool:
        """One acquisition attempt. On success the caller OWNS the lock
        directory exclusively but is not yet discoverable — finish setup,
        then call ``publish(info)``."""
        import shutil

        age = self._heartbeat_age()
        if age is not None and age < self.stale_after:
            return False                       # live holder
        mutex = self._steal_mutex()
        if mutex is None:
            return False                       # another candidate mid-steal
        try:
            age = self._heartbeat_age()        # re-check INSIDE the mutex
            if age is not None and age < self.stale_after:
                return False
            if age is not None:                # stale: move the corpse aside
                dead = (f"{self.path}.dead.{os.getpid()}."
                        f"{time.monotonic_ns()}")
                try:
                    os.rename(self.path, dead)
                except OSError:
                    return False
                shutil.rmtree(dead, ignore_errors=True)
            try:
                os.mkdir(self.path)
            except FileExistsError:
                return False
            # term continuity lives in a sidecar file that survives lock
            # generations; read-increment-write is serialized by the mutex
            term_path = self.path + ".term"
            prev_term = 0
            try:
                with open(term_path) as f:
                    prev_term = int(f.read().strip() or 0)
            except (OSError, ValueError):
                pass
            self.term = prev_term + 1
            tmp = f"{term_path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(str(self.term))
            os.replace(tmp, term_path)
            return True
        finally:
            try:
                os.unlink(mutex)
            except OSError:
                pass

    def publish(self, info: dict):
        """Make this leader discoverable and start heartbeating. Call
        only after ``try_acquire`` returned True and the service is
        ready to serve."""
        tmp = f"{self.info_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({**info, "term": self.term}, f)
        os.replace(tmp, self.info_path)
        self._stop.clear()
        self._thread = threading.Thread(target=self._beat, daemon=True)
        self._thread.start()

    def still_leader(self) -> bool:
        """Fencing check: does the published lock still carry OUR term?
        A deposed leader (frozen past stale_after, then resumed) sees a
        different term here and must stand down."""
        try:
            with open(self.info_path) as f:
                return json.load(f).get("term") == self.term
        except (OSError, ValueError):
            return False

    def _beat(self):
        while not self._stop.wait(self.heartbeat_interval):
            # fenced heartbeat: NEVER refresh a lock another leader now
            # owns — a zombie utime-ing the new leader's info.json would
            # make the lock look immortally live after that leader dies
            if not self.still_leader():
                self._stop.set()
                return
            try:
                os.utime(self.info_path)
            except OSError:
                pass

    @property
    def deposed(self) -> bool:
        """True once the heartbeat discovered another leader's term."""
        return self._stop.is_set() and self._thread is not None

    def release(self):
        import shutil

        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
        # only the CURRENT owner may remove the lock; a deposed zombie
        # must not delete the live leader's directory
        if self.still_leader():
            shutil.rmtree(self.path, ignore_errors=True)


class HAMaster:
    """A master replica: standby until it wins the leader lock, then
    serve the task queues restored from the snapshot (in-flight leases
    requeue — their trainers may be gone, service.go recover semantics).

    Run one per replica host. ``dataset`` is only installed by the FIRST
    leader (no snapshot yet); every later leader adopts snapshot state.
    """

    def __init__(self, lock_path: str, snapshot_path: str,
                 host: str = "127.0.0.1", port: int = 0,
                 stale_after: float = 3.0, heartbeat_interval: float = 0.5,
                 lease_seconds: float = 60.0, failure_max: int = 3,
                 num_passes: Optional[int] = None,
                 dataset: Optional[Sequence[str]] = None,
                 chunks_per_task: int = 1):
        self.lock = LeaderLock(lock_path, stale_after, heartbeat_interval)
        self.snapshot_path = snapshot_path
        self.host, self.port = host, port
        self.lease_seconds = lease_seconds
        self.failure_max = failure_max
        self.num_passes = num_passes
        self.dataset = dataset
        self.chunks_per_task = chunks_per_task
        self.service: Optional[MasterService] = None
        self.server: Optional[MasterServer] = None

    def campaign(self, poll_interval: float = 0.2,
                 timeout: Optional[float] = None) -> bool:
        """Block until this replica becomes leader (True) or timeout
        (False). Ordering matters: the lock is won FIRST, then state is
        restored from the snapshot, then the server starts, and only
        then is the address published — clients can never reach a
        leader whose queues are stale or mid-restore."""
        deadline = None if timeout is None else time.time() + timeout
        while True:
            if self.lock.try_acquire():
                break
            if deadline is not None and time.time() > deadline:
                return False
            time.sleep(poll_interval)
        # exclusive owner now: build state before becoming discoverable
        # (the MasterService ctor restores the previous leader's snapshot)
        self.service = MasterService(self.lease_seconds, self.failure_max,
                                     self.num_passes, self.snapshot_path)
        if (not os.path.exists(self.snapshot_path)
                and self.dataset is not None):
            self.service.set_dataset(self.dataset, self.chunks_per_task)
        self.server = MasterServer(self.service, self.host, self.port)
        self.lock.publish({"host": self.server.addr[0],
                           "port": self.server.addr[1]})
        # fence snapshot writes on CURRENT leadership from here on
        self.service.fence = self.lock.still_leader
        log.info("master: leader term %d at %s:%d", self.lock.term,
                 self.server.addr[0], self.server.addr[1])
        return True

    def shutdown(self):
        if self.server:
            self.server.shutdown()
        if self.service:
            self.service.close()
        self.lock.release()


def discover_master(discovery_path: str) -> Optional[tuple]:
    """Resolve the current leader's (host, port) from the lock
    directory's published info."""
    try:
        with open(os.path.join(discovery_path, "info.json")) as f:
            d = json.load(f)
        return (d["host"], d["port"])
    except (OSError, ValueError, KeyError):
        return None


class DecorrelatedBackoff:
    """Exponential backoff with decorrelated jitter (the AWS
    architecture-blog scheme): each delay is uniform on
    [base, 3 x previous], capped — N clients retrying against one
    recovering master spread out instead of stampeding in lockstep,
    and the cap bounds how stale a client can get after recovery."""

    def __init__(self, base: float = 0.05, cap: float = 2.0, rng=None):
        self.base = float(base)
        self.cap = float(cap)
        self._rng = rng or random.Random()
        self._prev = self.base

    def reset(self):
        self._prev = self.base

    def next(self) -> float:
        delay = min(self.cap, self._rng.uniform(self.base,
                                                self._prev * 3.0))
        self._prev = delay
        return delay


class MasterClient:
    """Client for trainers. ``addr=None`` talks to an in-process service
    (reference: python/paddle/v2/master/client.py set_dataset/next_record
    over the C binding; here JSON/TCP or direct calls). With
    ``discovery_path`` the client resolves the leader from the HA lock
    file and transparently re-resolves + retries on connection failure
    (master failover; lease tokens make replayed reports safe).
    Reconnects back off exponentially with decorrelated jitter so N
    workers do not stampede a recovering master, and each connect
    attempt is bounded by ``connect_timeout`` (a black-holed address
    must not eat the whole failover budget in one attempt).

    ``worker_epoch`` (default: the PADDLE_ELASTIC_EPOCH env the
    supervisor stamps on every gang member) rides on every task RPC —
    after a gang restart the master's epoch fence silently retires
    zombies still holding an older epoch."""

    def __init__(self, service: Optional[MasterService] = None,
                 addr: Optional[tuple] = None,
                 discovery_path: Optional[str] = None,
                 failover_timeout: float = 30.0,
                 connect_timeout: float = 5.0,
                 io_timeout: float = 10.0,
                 backoff_base: float = 0.05,
                 backoff_cap: float = 2.0,
                 worker_epoch: Optional[int] = None):
        assert sum(x is not None for x in (service, addr,
                                           discovery_path)) == 1, \
            "pass exactly one of service/addr/discovery_path"
        self._svc = service
        self._addr = addr
        self._discovery = discovery_path
        self._failover_timeout = failover_timeout
        self._connect_timeout = connect_timeout
        self._io_timeout = io_timeout
        self._backoff = DecorrelatedBackoff(backoff_base, backoff_cap)
        if worker_epoch is None and os.environ.get("PADDLE_ELASTIC_EPOCH"):
            try:
                worker_epoch = int(os.environ["PADDLE_ELASTIC_EPOCH"])
            except ValueError:
                pass
        self._worker_epoch = worker_epoch
        self._sock = None

    def _resolve(self):
        if self._discovery is None:
            return self._addr
        return discover_master(self._discovery)

    def _rpc_once(self, method, deadline=None, **kw):
        if self._sock is None:
            addr = self._resolve()
            if addr is None:
                raise ConnectionError("no master leader published")
            timeout = self._connect_timeout
            if deadline is not None:
                timeout = max(0.1, min(timeout, deadline - time.time()))
            self._sock = socket.create_connection(addr, timeout=timeout)
            self._sock.settimeout(self._io_timeout)
            self._file = self._sock.makefile("rwb")
        self._file.write((json.dumps({"method": method, **kw}) + "\n")
                         .encode())
        self._file.flush()
        line = self._file.readline()
        if not line:
            raise ConnectionError("master closed the connection")
        resp = json.loads(line)
        if "error" in resp:
            raise RuntimeError(f"master rpc error: {resp['error']}")
        return resp

    def _rpc(self, method, **kw):
        if self._svc is not None:
            if method == "get_task":
                t = self._svc.get_task(kw.get("worker_epoch"))
                return {"task": t.to_dict() if t else None}
            if method == "report_done":
                return {"ok": self._svc.report_done(
                    kw["task_id"], kw.get("lease"),
                    kw.get("worker_epoch"))}
            if method == "report_failed":
                self._svc.report_failed(kw["task_id"], kw.get("lease"),
                                        kw.get("worker_epoch"))
                return {"ok": True}
            if method == "status":
                return {"todo": self._svc.num_todo(),
                        "pending": self._svc.num_pending(),
                        "epoch": self._svc.epoch()}
            if method == "metrics":
                return {"text":
                        _metrics.default_registry().render_prometheus()}
            if method == "request_save_model":
                return {"ok": self._svc.request_save_model(
                    kw["trainer_id"], kw.get("block_dur", 60.0),
                    kw.get("worker_epoch"))}
            if method == "set_epoch_fence":
                return {"fence": self._svc.set_epoch_fence(kw["epoch"])}
        deadline = time.time() + self._failover_timeout
        self._backoff.reset()
        while True:
            try:
                resp = self._rpc_once(method, deadline=deadline, **kw)
                self._backoff.reset()
                return resp
            # ValueError: a leader SIGKILLed mid-response leaves a partial
            # line — a decode error is a failover signal, not a bug
            except (ConnectionError, OSError, ValueError) as e:
                self.close()
                if self._discovery is None or time.time() > deadline:
                    raise
                delay = self._backoff.next()
                _m_reconnects.inc()
                log.info("master client: %s; re-resolving leader in "
                         "%.2fs", e, delay)
                time.sleep(delay)

    def _epoch_kw(self):
        return ({} if self._worker_epoch is None
                else {"worker_epoch": self._worker_epoch})

    def get_task(self) -> Optional[Task]:
        d = self._rpc("get_task", **self._epoch_kw())["task"]
        return Task.from_dict(d) if d else None

    def report_done(self, task_id: int, lease: Optional[int] = None):
        self._rpc("report_done", task_id=task_id, lease=lease,
                  **self._epoch_kw())

    def report_failed(self, task_id: int, lease: Optional[int] = None):
        self._rpc("report_failed", task_id=task_id, lease=lease,
                  **self._epoch_kw())

    def set_epoch_fence(self, epoch: int) -> int:
        """Supervisor-side: retire every worker whose coordination epoch
        is below ``epoch`` (returns the active fence)."""
        return int(self._rpc("set_epoch_fence", epoch=int(epoch))["fence"])

    def status(self):
        return self._rpc("status")

    def metrics_text(self) -> str:
        """Prometheus text snapshot of the master's registry (local or
        over the wire — the observability scrape path for trainers)."""
        return self._rpc("metrics")["text"]

    def request_save_model(self, trainer_id: str,
                           block_dur: float = 60.0) -> bool:
        """True iff THIS trainer is elected to save the model for the
        next ``block_dur`` window (python/paddle/v2/master/client.py:24).
        Typical use: ``if client.request_save_model(my_id): save()``."""
        return bool(self._rpc("request_save_model", trainer_id=trainer_id,
                              block_dur=block_dur,
                              **self._epoch_kw())["ok"])

    def close(self):
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    def reader(self, poll_interval: float = 0.05, max_epochs: int = 1):
        """A v2 reader(): stream records task-by-task until ``max_epochs``
        passes complete — the trainer.train(reader=...) integration
        (reference: master client next_record consumed by the v2 reader)."""

        def gen():
            start_epoch = self.status()["epoch"]
            while True:
                st = self.status()
                if st["epoch"] >= start_epoch + max_epochs:
                    return
                task = self.get_task()
                if task is None:
                    if st["pending"] == 0 and \
                            self.status()["epoch"] >= start_epoch + max_epochs:
                        return
                    # the stragglers' barrier: this consumer is drained
                    # while others still hold leases (BarrierStat slot)
                    _m_task_wait.inc(poll_interval)
                    time.sleep(poll_interval)
                    continue
                try:
                    for off, _ in task.chunks:
                        yield from recordio.read_chunk(task.path, off)
                except Exception:
                    self.report_failed(task.task_id, task.lease)
                    raise
                self.report_done(task.task_id, task.lease)

        return gen


class ServingFleet:
    """Spawn and tend N ``paddle_tpu serve --port`` replica processes
    from one ``lm_serving`` artifact — the serving counterpart of the
    training gang supervisor, and the fleet glue the ``route`` CLI and
    the multi-process chaos tests stand on.

    Each replica binds an ephemeral TCP port for the JSONL op wire and
    an ephemeral HTTP health port, announcing both as one
    machine-readable ``{"replica_ready": {...}}`` line on stdout;
    :meth:`start` parses the announcements (with a deadline — a replica
    that dies during model load raises instead of hanging the fleet)
    and :meth:`handles` builds ``serving.replica.SocketReplica`` handles
    over them. :meth:`router` assembles a prefix-aware
    ``serving.Router``, reading the placement keying (block size /
    chunk grid) off the first replica's ``/healthz`` so the router's
    digests match the engines' prefix caches exactly. ``prefill=K``
    marks the first K replicas as the disaggregated prefill tier.

    :meth:`kill` SIGKILLs one replica (the chaos hook: the router must
    requeue its in-flight work onto survivors with zero lost requests);
    :meth:`close` tears the fleet down TERM-then-KILL via
    ``runtime.launch.terminate_procs`` — TERM is the replicas' graceful
    drain, so a closing fleet finishes what it accepted."""

    def __init__(self, model: str, replicas: int = 2, *,
                 prefill: int = 0, args_extra: Sequence[str] = (),
                 env: Optional[dict] = None,
                 startup_timeout_s: float = 240.0,
                 python: Optional[str] = None):
        if replicas < 1:
            raise ValueError(f"need >= 1 replicas, got {replicas}")
        if not 0 <= prefill < replicas:
            raise ValueError(f"prefill {prefill} must leave at least "
                             f"one of {replicas} replicas decoding")
        self.model = str(model)
        self.n = int(replicas)
        self.prefill = int(prefill)
        self.args_extra = list(args_extra)
        self.env = env
        self.startup_timeout_s = float(startup_timeout_s)
        self.python = python or sys.executable
        self.procs: List = []
        self.endpoints: List[dict] = []
        self._handles: List = []
        # name -> live process; names are CLAIMED under the lock
        # before any process exists, so two concurrent replacements
        # can never both launch under one name (and therefore never
        # share a {name}-derived spill directory)
        self._lock = threading.Lock()
        self._by_name: dict = {}
        self._spawning: set = set()

    def start(self) -> "ServingFleet":
        for i in range(self.n):
            self.procs.append(self._launch(f"replica{i}"))
        deadline = time.time() + self.startup_timeout_s
        for i, p in enumerate(self.procs):
            self.endpoints.append(self._await_ready(
                f"replica{i}", p, deadline, close_fleet=True))
            self._by_name[f"replica{i}"] = p
        return self

    def _launch(self, name: str):
        import subprocess
        env = dict(os.environ)
        if self.env:
            env.update(self.env)
        # the replicas run `python -m paddle_tpu`: make THIS package
        # importable regardless of the caller's cwd (the fleet may be
        # launched from anywhere, not just the repo root)
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + \
            env.get("PYTHONPATH", "") if env.get("PYTHONPATH") \
            else pkg_root
        # "{name}" in an extra arg expands to this replica's name:
        # per-replica state that must not be shared (a --tiers_dir
        # spill directory, say) gets its own path from ONE args_extra
        # template — and a replacement spawned under the SAME name
        # inherits that path, which is how the disk spill tier hands
        # over to the healed process
        extra = [a.replace("{name}", name) for a in self.args_extra]
        return subprocess.Popen(
            [self.python, "-m", "paddle_tpu", "serve",
             f"--model={self.model}", "--port=0", "--health_port=0",
             *extra],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=env)

    def _await_ready(self, name: str, proc, deadline: float,
                     close_fleet: bool = False) -> dict:
        """Parse the replica's ready line off its stdout, bounded by
        ``deadline`` (readline on a watchdog thread: a wedged replica
        must fail the fleet, not hang it). ``close_fleet`` tears the
        whole fleet down on failure (the start() all-or-nothing path);
        a single respawn kills only its own process."""
        box: List[Optional[str]] = [None]

        def _read():
            box[0] = proc.stdout.readline()

        t = threading.Thread(target=_read, daemon=True)
        t.start()
        t.join(max(deadline - time.time(), 0.1))
        line = box[0]
        if not line:
            rc = proc.poll()
            if close_fleet:
                self.close()
            else:
                try:
                    proc.kill()
                except OSError:
                    pass
            raise RuntimeError(
                f"replica {name} never announced readiness "
                f"({'exited rc=' + str(rc) if rc is not None else 'timed out'})")
        doc = json.loads(line)["replica_ready"]
        return {"name": name, "port": int(doc["port"]),
                "health_port": doc.get("health_port")}

    # -- named lifecycle (the fleet controller's surface) ------------------
    def allocate_name(self) -> str:
        """The smallest unclaimed ``replica{k}`` (scale-up names)."""
        with self._lock:
            k = 0
            while (f"replica{k}" in self._by_name
                   or f"replica{k}" in self._spawning):
                k += 1
            return f"replica{k}"

    def spawn(self, name: Optional[str] = None) -> dict:
        """Spawn ONE replica under ``name`` (default: a fresh name)
        and wait for its ready line. The name is claimed atomically
        before the process launches: a second concurrent spawn of the
        same name raises instead of racing it — at most one live
        process ever owns a name (and its spill directory). Replacing
        a dead replica's name is allowed once its process exited."""
        with self._lock:
            if name is None:
                k = 0
                while (f"replica{k}" in self._by_name
                       or f"replica{k}" in self._spawning):
                    k += 1
                name = f"replica{k}"
            name = str(name)
            if name in self._spawning:
                raise RuntimeError(
                    f"replica {name!r} is already being spawned")
            cur = self._by_name.get(name)
            if cur is not None and cur.poll() is None:
                raise RuntimeError(
                    f"replica {name!r} is still running — stop or "
                    f"kill it before respawning")
            self._spawning.add(name)
        try:
            proc = self._launch(name)
            ep = self._await_ready(
                name, proc, time.time() + self.startup_timeout_s)
        finally:
            with self._lock:
                self._spawning.discard(name)
        with self._lock:
            self._by_name[name] = proc
            for i, e in enumerate(self.endpoints):
                if e["name"] == name:
                    self.endpoints[i] = ep
                    self.procs[i] = proc
                    break
            else:
                self.endpoints.append(ep)
                self.procs.append(proc)
        return ep

    def handle(self, name: str):
        """A FRESH SocketReplica handle to the named replica (the
        cached :meth:`handles` list keeps the originals — a healed
        replica needs a new connection to its new process)."""
        from paddle_tpu.serving.replica import SocketReplica
        ep = next((e for e in self.endpoints if e["name"] == name),
                  None)
        if ep is None:
            raise KeyError(f"no replica named {name!r}")
        hp = ep.get("health_port")
        return SocketReplica(
            name, ("127.0.0.1", ep["port"]),
            f"http://127.0.0.1:{hp}" if hp else None)

    def stop(self, name: str):
        """Graceful SIGTERM drain of one replica (scale-down): it
        finishes what it accepted, emits every result, and exits 0."""
        import signal as _signal
        with self._lock:
            proc = self._by_name.get(name)
        if proc is not None and proc.poll() is None:
            proc.send_signal(_signal.SIGTERM)

    def kill_name(self, name: str):
        """SIGKILL by name (the controller's wedge hammer)."""
        with self._lock:
            proc = self._by_name.get(name)
        if proc is not None and proc.poll() is None:
            proc.kill()

    def proc_alive(self, name: str) -> bool:
        with self._lock:
            proc = self._by_name.get(name)
        return proc is not None and proc.poll() is None

    def handles(self) -> List:
        """SocketReplica handles, one per replica (built once)."""
        from paddle_tpu.serving.replica import SocketReplica
        if not self._handles:
            if not self.endpoints:
                raise RuntimeError("start() the fleet first")
            for ep in self.endpoints:
                hp = ep.get("health_port")
                self._handles.append(SocketReplica(
                    ep["name"], ("127.0.0.1", ep["port"]),
                    f"http://127.0.0.1:{hp}" if hp else None))
        return self._handles

    def router(self, **kw):
        """A prefix-aware Router over this fleet; keyword args pass
        through (max_in_flight, slo, ...). Placement keying (block
        size / chunk grid) is read off the first replica's /healthz so
        the router's digests match the engines' prefix caches."""
        from paddle_tpu.serving.router import Router, fleet_keying
        handles = self.handles()
        bs, chunk = fleet_keying(handles)
        prefill = [h.name for h in handles[:self.prefill]]
        kw.setdefault("block_size", bs)
        kw.setdefault("chunk_tokens", chunk)
        return Router(handles, prefill=prefill, **kw)

    def kill(self, i: int):
        """SIGKILL replica ``i`` — the chaos hook (no drain, no
        goodbye; the router discovers the death through the dead
        socket)."""
        self.procs[i].kill()

    def close(self):
        from paddle_tpu.runtime import launch
        for h in self._handles:
            try:
                h.close()
            except Exception:
                pass
        self._handles = []
        if self.procs:
            launch.terminate_procs(self.procs)
            self.procs = []
