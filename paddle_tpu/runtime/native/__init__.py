"""ctypes loader for the native runtime library.

Builds recordio.cc with g++ on first use (cached beside the source; no
pybind11 in the image — C ABI + ctypes per the environment constraints),
falling back to None so pure-Python paths keep working without a toolchain.
"""

import ctypes
import os
import subprocess
import threading

_lock = threading.Lock()
_lib = None
_tried = False

_SRC = os.path.join(os.path.dirname(__file__), "recordio.cc")
_SO = os.path.join(os.path.dirname(__file__), "_librecordio.so")


def _build() -> bool:
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return True
    tmp = f"{_SO}.tmp.{os.getpid()}"       # per-process: concurrent builds
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-pthread",
           _SRC, "-o", tmp, "-lz"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def _bind(lib):
    c = ctypes
    lib.rio_index.restype = c.c_long
    lib.rio_index.argtypes = [c.c_char_p, c.POINTER(c.POINTER(c.c_longlong)),
                              c.POINTER(c.POINTER(c.c_uint))]
    lib.rio_read_chunk.restype = c.c_longlong
    lib.rio_read_chunk.argtypes = [c.c_char_p, c.c_longlong,
                                   c.POINTER(c.POINTER(c.c_uint8)),
                                   c.POINTER(c.c_uint)]
    lib.rio_write_chunk.restype = c.c_longlong
    lib.rio_write_chunk.argtypes = [c.c_char_p, c.c_char_p,
                                    c.POINTER(c.c_uint), c.c_uint]
    lib.rio_free.restype = None
    lib.rio_free.argtypes = [c.c_void_p]
    lib.loader_create.restype = c.c_void_p
    lib.loader_create.argtypes = [c.c_char_p, c.POINTER(c.c_longlong),
                                  c.c_long, c.c_int, c.c_long]
    lib.loader_next.restype = c.c_longlong
    lib.loader_next.argtypes = [c.c_void_p, c.POINTER(c.POINTER(c.c_uint8))]
    lib.loader_next_batch.restype = c.c_longlong
    lib.loader_next_batch.argtypes = [c.c_void_p, c.POINTER(c.c_uint8),
                                      c.c_long, c.c_longlong]
    lib.loader_destroy.restype = None
    lib.loader_destroy.argtypes = [c.c_void_p]
    return lib


def get():
    """The loaded native library, or None when unavailable."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if _build():
            try:
                _lib = _bind(ctypes.CDLL(_SO))
            except OSError:
                _lib = None
        return _lib
