// Native record-IO codec + threaded prefetch loader.
//
// TPU-native replacement for the reference's native data plumbing: the Go
// recordio library feeding go/master task dispatch (go/master/service.go
// partitions datasets into recordio chunks) and the C++ data providers with
// background-thread double buffering (paddle/gserver/dataproviders/
// DataProvider.h:292, PyDataProvider2.cpp:195 DoubleBuffer).
//
// Format (must match paddle_tpu/runtime/recordio.py):
//   chunk = [u32 magic][u32 nrecords][u64 payload_len][u32 crc32]
//           [payload: nrecords x (u32 len + bytes)]
//
// C ABI only — consumed from Python via ctypes (no pybind11 in the image).

#include <zlib.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x0A0D5EC5;
constexpr size_t kHeaderSize = 4 + 4 + 8 + 4;

#pragma pack(push, 1)
struct ChunkHeader {
  uint32_t magic;
  uint32_t nrecords;
  uint64_t payload_len;
  uint32_t crc;
};
#pragma pack(pop)

static_assert(sizeof(ChunkHeader) == kHeaderSize, "header packing");

struct Chunk {
  std::vector<uint8_t> payload;
  uint32_t nrecords = 0;
};

// Reads one chunk at `offset`; returns 0 on success, negative error code.
int read_chunk_at(FILE* f, long offset, Chunk* out) {
  if (fseek(f, offset, SEEK_SET) != 0) return -2;
  ChunkHeader h;
  if (fread(&h, 1, sizeof(h), f) != sizeof(h)) return -3;
  if (h.magic != kMagic) return -4;
  out->payload.resize(h.payload_len);
  if (h.payload_len &&
      fread(out->payload.data(), 1, h.payload_len, f) != h.payload_len)
    return -5;
  uint32_t crc =
      crc32(0, out->payload.data(), static_cast<uInt>(h.payload_len));
  if (crc != h.crc) return -6;
  out->nrecords = h.nrecords;
  return 0;
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------------------
// Index: scan chunk headers. Returns #chunks (or negative errno-style code);
// fills malloc'd arrays the caller frees with rio_free.
// ---------------------------------------------------------------------------
long rio_index(const char* path, long long** offsets, unsigned int** counts) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  std::vector<long long> offs;
  std::vector<unsigned int> cnts;
  for (;;) {
    long pos = ftell(f);
    ChunkHeader h;
    size_t got = fread(&h, 1, sizeof(h), f);
    if (got == 0) break;               // clean EOF
    if (got != sizeof(h) || h.magic != kMagic) {
      fclose(f);
      return -4;
    }
    offs.push_back(pos);
    cnts.push_back(h.nrecords);
    if (fseek(f, static_cast<long>(h.payload_len), SEEK_CUR) != 0) {
      fclose(f);
      return -2;
    }
  }
  fclose(f);
  *offsets = static_cast<long long*>(malloc(offs.size() * sizeof(long long)));
  *counts =
      static_cast<unsigned int*>(malloc(cnts.size() * sizeof(unsigned int)));
  memcpy(*offsets, offs.data(), offs.size() * sizeof(long long));
  memcpy(*counts, cnts.data(), cnts.size() * sizeof(unsigned int));
  return static_cast<long>(offs.size());
}

// ---------------------------------------------------------------------------
// Read one chunk's payload (CRC-checked). Returns payload length or negative
// error; payload malloc'd, record count in *nrecords.
// ---------------------------------------------------------------------------
long long rio_read_chunk(const char* path, long long offset, uint8_t** payload,
                         unsigned int* nrecords) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  Chunk c;
  int rc = read_chunk_at(f, static_cast<long>(offset), &c);
  fclose(f);
  if (rc != 0) return rc;
  *payload = static_cast<uint8_t*>(malloc(c.payload.size()));
  memcpy(*payload, c.payload.data(), c.payload.size());
  *nrecords = c.nrecords;
  return static_cast<long long>(c.payload.size());
}

// ---------------------------------------------------------------------------
// Write chunks: records passed as one buffer + per-record lengths.
// Appends to `path` (caller truncates first if overwriting).
// ---------------------------------------------------------------------------
long long rio_write_chunk(const char* path, const uint8_t* data,
                          const unsigned int* lens, unsigned int nrecords) {
  FILE* f = fopen(path, "ab");
  if (!f) return -1;
  uint64_t payload_len = 0;
  for (unsigned int i = 0; i < nrecords; i++)
    payload_len += 4ull + lens[i];
  std::vector<uint8_t> payload(payload_len);
  size_t pos = 0;
  const uint8_t* src = data;
  for (unsigned int i = 0; i < nrecords; i++) {
    uint32_t len = lens[i];
    memcpy(payload.data() + pos, &len, 4);
    pos += 4;
    memcpy(payload.data() + pos, src, len);
    pos += len;
    src += len;
  }
  ChunkHeader h{kMagic, nrecords, payload_len,
                crc32(0, payload.data(), static_cast<uInt>(payload_len))};
  long long total = -7;
  if (fwrite(&h, 1, sizeof(h), f) == sizeof(h) &&
      fwrite(payload.data(), 1, payload.size(), f) == payload.size())
    total = static_cast<long long>(sizeof(h) + payload.size());
  fclose(f);
  return total;
}

void rio_free(void* p) { free(p); }

// ---------------------------------------------------------------------------
// Prefetch loader: N reader threads pull chunk indices off a work list,
// decode records, and push them into a bounded queue — the DataProvider
// double-buffer equivalent, decoupling disk+decode from the train loop.
// ---------------------------------------------------------------------------
struct Loader {
  std::string path;
  std::vector<long long> offsets;       // chunk order (pre-shuffled by caller)
  size_t next_chunk = 0;
  size_t capacity;
  std::deque<std::vector<uint8_t>> queue;
  std::mutex mu;
  std::condition_variable cv_push, cv_pop;
  std::vector<std::thread> threads;
  std::atomic<int> active_readers{0};
  std::atomic<bool> stop{false};
  std::atomic<int> error{0};
  // error deferred by loader_next_batch so a partially-assembled batch
  // is returned to the caller before the error surfaces
  std::atomic<long long> pending_error{0};

  void reader_loop() {
    FILE* f = fopen(path.c_str(), "rb");
    if (!f) {
      error.store(-1);
      active_readers.fetch_sub(1);
      cv_pop.notify_all();
      return;
    }
    for (;;) {
      size_t idx;
      {
        std::lock_guard<std::mutex> lk(mu);
        if (stop.load() || next_chunk >= offsets.size()) break;
        idx = next_chunk++;
      }
      Chunk c;
      int rc = read_chunk_at(f, static_cast<long>(offsets[idx]), &c);
      if (rc != 0) {
        error.store(rc);
        break;
      }
      // split payload into records, enqueue each
      size_t pos = 0;
      for (uint32_t r = 0; r < c.nrecords && !stop.load(); r++) {
        uint32_t len;
        memcpy(&len, c.payload.data() + pos, 4);
        pos += 4;
        std::vector<uint8_t> rec(c.payload.begin() + pos,
                                 c.payload.begin() + pos + len);
        pos += len;
        std::unique_lock<std::mutex> lk(mu);
        cv_push.wait(lk, [&] { return queue.size() < capacity || stop.load(); });
        if (stop.load()) break;
        queue.push_back(std::move(rec));
        cv_pop.notify_one();
      }
    }
    fclose(f);
    active_readers.fetch_sub(1);
    cv_pop.notify_all();
  }
};

void* loader_create(const char* path, const long long* offsets, long nchunks,
                    int nthreads, long capacity) {
  Loader* L = new Loader();
  L->path = path;
  L->offsets.assign(offsets, offsets + nchunks);
  L->capacity = static_cast<size_t>(capacity);
  L->active_readers.store(nthreads);
  for (int i = 0; i < nthreads; i++)
    L->threads.emplace_back([L] { L->reader_loop(); });
  return L;
}

// Pops one record; blocks. Returns length, 0 at end-of-data, negative error.
long long loader_next(void* handle, uint8_t** rec) {
  Loader* L = static_cast<Loader*>(handle);
  std::unique_lock<std::mutex> lk(L->mu);
  L->cv_pop.wait(lk, [&] {
    return !L->queue.empty() || L->active_readers.load() == 0 ||
           L->error.load() != 0;
  });
  if (L->error.load() != 0 && L->queue.empty()) return L->error.load();
  if (L->queue.empty()) return 0;  // drained
  std::vector<uint8_t> r = std::move(L->queue.front());
  L->queue.pop_front();
  L->cv_push.notify_one();
  lk.unlock();
  *rec = static_cast<uint8_t*>(malloc(r.size()));
  memcpy(*rec, r.data(), r.size());
  return static_cast<long long>(r.size());
}

// Pops up to `batch` fixed-size records straight into the caller's buffer
// (a [batch, rec_bytes] matrix) — the native batch-assembly path: no
// per-record malloc, no per-record language crossing. Returns the number
// of records copied (0 = drained), -100 on a record whose size !=
// rec_bytes (distinct from the chunk-reader's -1..-4 I/O codes), or the
// loader's error code. An error hit after n>0 records were already
// copied is DEFERRED: the partial count is returned first and the error
// surfaces on the next call, so no copied record is ever discarded.
// Short counts therefore mean end-of-data OR an error about to surface.
// The mismatched record itself cannot fit the matrix and is dropped.
long long loader_next_batch(void* handle, uint8_t* out, long batch,
                            long long rec_bytes) {
  Loader* L = static_cast<Loader*>(handle);
  long long pending = L->pending_error.exchange(0);
  if (pending != 0) return pending;
  long n = 0;
  while (n < batch) {
    std::unique_lock<std::mutex> lk(L->mu);
    L->cv_pop.wait(lk, [&] {
      return !L->queue.empty() || L->active_readers.load() == 0 ||
             L->error.load() != 0;
    });
    if (L->queue.empty()) {
      if (L->error.load() != 0) {
        if (n > 0) {
          L->pending_error.store(L->error.load());
          break;
        }
        return L->error.load();
      }
      break;  // drained: return the short tail
    }
    std::vector<uint8_t> r = std::move(L->queue.front());
    L->queue.pop_front();
    L->cv_push.notify_one();
    lk.unlock();
    if (static_cast<long long>(r.size()) != rec_bytes) {
      if (n > 0) {
        L->pending_error.store(-100);
        break;
      }
      return -100;
    }
    memcpy(out + static_cast<size_t>(n) * rec_bytes, r.data(), r.size());
    n++;
  }
  return n;
}

void loader_destroy(void* handle) {
  Loader* L = static_cast<Loader*>(handle);
  L->stop.store(true);
  L->cv_push.notify_all();
  L->cv_pop.notify_all();
  for (auto& t : L->threads) t.join();
  delete L;
}

}  // extern "C"
