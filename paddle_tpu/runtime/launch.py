"""Multi-process launcher — the cluster_train script slot.

Reference: paddle/scripts/cluster_train/paddle.py (SSH fan-out of
pserver+trainer processes with --trainer_id etc.) and submit_local.sh.in
(the `paddle` CLI wrapper).

TPU-native: every process is identical (no pserver role); the launcher
just sets the PADDLE_* env contract consumed by paddle_tpu.distributed.init
and execs the worker. Local mode spawns N processes on this machine with
the CPU platform and K virtual devices each — the no-cluster simulation of
a K-chip x N-host pod used by the tests (SURVEY §4.6's in-process-pserver
strategy, one level up).

Usage:
  python -m paddle_tpu.runtime.launch --nprocs=2 --devices-per-proc=4 \
      worker.py [worker args...]
On a real pod, run one process per host with PADDLE_COORDINATOR pointing
at host 0 (or let TPU metadata auto-configure) instead.
"""

import argparse
import os
import shlex
import socket
import subprocess
import sys
import time
from typing import List, Optional, Sequence


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def launch_local(nprocs: int, argv: Sequence[str],
                 devices_per_proc: int = 1,
                 coordinator_port: Optional[int] = None,
                 env_extra: Optional[dict] = None,
                 timeout: float = 600.0) -> List[int]:
    """Spawn ``nprocs`` local worker processes and wait; returns their
    return codes. Workers must call paddle_tpu.distributed.init()."""
    port = coordinator_port or free_port()
    procs = []
    for rank in range(nprocs):
        env = dict(os.environ,
                   PADDLE_COORDINATOR=f"127.0.0.1:{port}",
                   PADDLE_NUM_PROCESSES=str(nprocs),
                   PADDLE_PROCESS_ID=str(rank),
                   PADDLE_PLATFORM="cpu",
                   PADDLE_LOCAL_CPU_DEVICES=str(devices_per_proc),
                   **(env_extra or {}))
        procs.append(subprocess.Popen([sys.executable, *argv], env=env))
    return _wait_all(procs, timeout)


def _wait_all(procs: Sequence[subprocess.Popen],
              timeout: float) -> List[int]:
    deadline = time.time() + timeout
    rcs = []
    for p in procs:
        remain = max(1.0, deadline - time.time())
        try:
            rcs.append(p.wait(timeout=remain))
        except subprocess.TimeoutExpired:
            p.kill()
            rcs.append(-9)
    return rcs


def launch_ssh(hosts: Sequence[str], argv: Sequence[str], *,
               port: int = 6007, workdir: Optional[str] = None,
               env_extra: Optional[dict] = None,
               ssh_cmd: Sequence[str] = ("ssh", "-o", "BatchMode=yes"),
               timeout: float = 86400.0) -> List[int]:
    """SSH fan-out: one worker process per host, rank = position in
    ``hosts``, coordinator = ``hosts[0]:port`` (the reference's
    paddle/scripts/cluster_train/paddle.py slot — but every process is
    identical here: no pserver role, jax.distributed + GSPMD replace it).

    The PADDLE_* env contract is injected via ``env`` on the remote
    command line, so nothing needs to be pre-configured on the hosts
    beyond the code and its interpreter being present (pass ``workdir``
    to cd into the repo checkout first). Workers must call
    ``paddle_tpu.distributed.init()``. Returns per-host return codes
    (ssh propagates the remote exit status)."""
    envs_common = dict(env_extra or {})
    procs = []
    for rank, host in enumerate(hosts):
        envs = {"PADDLE_COORDINATOR": f"{hosts[0]}:{port}",
                "PADDLE_NUM_PROCESSES": str(len(hosts)),
                "PADDLE_PROCESS_ID": str(rank), **envs_common}
        exports = " ".join(f"{k}={shlex.quote(str(v))}"
                           for k, v in envs.items())
        cd = f"cd {shlex.quote(workdir)} && " if workdir else ""
        remote = (cd + "env " + exports + " "
                  + " ".join(shlex.quote(a) for a in argv))
        procs.append(subprocess.Popen([*ssh_cmd, host, remote]))
    return _wait_all(procs, timeout)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="paddle_tpu.runtime.launch",
        description="multi-process launcher: local simulation or ssh "
        "fan-out across hosts (docs/howto_distributed.md)")
    ap.add_argument("--nprocs", type=int, default=2)
    ap.add_argument("--devices-per-proc", type=int, default=1)
    ap.add_argument("--timeout", type=float, default=600.0)
    ap.add_argument("--hosts", default=None,
                    help="comma-separated host list: ssh mode, one "
                    "worker per host, coordinator on the first")
    ap.add_argument("--port", type=int, default=6007,
                    help="coordinator port (ssh mode)")
    ap.add_argument("--workdir", default=None,
                    help="remote directory to cd into (ssh mode)")
    ap.add_argument("--ssh-cmd", default="ssh -o BatchMode=yes",
                    help="ssh command prefix (ssh mode)")
    ap.add_argument("worker", nargs=argparse.REMAINDER,
                    help="worker script and args")
    args = ap.parse_args(argv)
    if not args.worker:
        ap.error("worker script required")
    if args.hosts:
        rcs = launch_ssh(args.hosts.split(","), args.worker,
                         port=args.port, workdir=args.workdir,
                         ssh_cmd=tuple(args.ssh_cmd.split()),
                         timeout=args.timeout)
    else:
        rcs = launch_local(args.nprocs, args.worker,
                           devices_per_proc=args.devices_per_proc,
                           timeout=args.timeout)
    print(f"launch: workers exited {rcs}")
    return 0 if all(rc == 0 for rc in rcs) else 1


if __name__ == "__main__":
    sys.exit(main())
