"""Multi-process launcher — the cluster_train script slot.

Reference: paddle/scripts/cluster_train/paddle.py (SSH fan-out of
pserver+trainer processes with --trainer_id etc.) and submit_local.sh.in
(the `paddle` CLI wrapper).

TPU-native: every process is identical (no pserver role); the launcher
just sets the PADDLE_* env contract consumed by paddle_tpu.distributed.init
and execs the worker. Local mode spawns N processes on this machine with
the CPU platform and K virtual devices each — the no-cluster simulation of
a K-chip x N-host pod used by the tests (SURVEY §4.6's in-process-pserver
strategy, one level up).

Usage:
  python -m paddle_tpu.runtime.launch --nprocs=2 --devices-per-proc=4 \
      worker.py [worker args...]
On a real pod, run one process per host with PADDLE_COORDINATOR pointing
at host 0 (or let TPU metadata auto-configure) instead.
"""

import argparse
import os
import shlex
import socket
import subprocess
import sys
import time
from typing import List, Optional, Sequence


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def spawn_local_procs(nprocs: int, argv: Sequence[str],
                      devices_per_proc: int = 1,
                      coordinator_port: Optional[int] = None,
                      env_extra: Optional[dict] = None,
                      env_per_rank: Optional[Sequence[dict]] = None,
                      cluster: bool = True) -> List[subprocess.Popen]:
    """Spawn ``nprocs`` local worker processes WITHOUT waiting — the
    restartable-gang primitive the elastic supervisor re-forms on every
    coordination epoch. ``cluster=False`` omits PADDLE_COORDINATOR so
    workers run independent single-process JAX runtimes (the CPU
    simulation path where jaxlib lacks multi-process collectives —
    ``multiprocess_cpu_supported``); a fresh coordinator port per call
    is the 'fresh coordination epoch' in cluster mode (no TIME_WAIT or
    zombie can hold the old port hostage)."""
    port = coordinator_port or free_port()
    procs = []
    for rank in range(nprocs):
        # update() chain, not dict(**kw): callers may legitimately
        # override the contract keys (env_extra={"PADDLE_PLATFORM":
        # ...}) and later layers must win, not TypeError
        env = dict(os.environ)
        env.update(PADDLE_NUM_PROCESSES=str(nprocs),
                   PADDLE_PROCESS_ID=str(rank),
                   PADDLE_PLATFORM="cpu",
                   PADDLE_LOCAL_CPU_DEVICES=str(devices_per_proc))
        env.update(env_extra or {})
        env.update(env_per_rank[rank] if env_per_rank else {})
        if cluster:
            env["PADDLE_COORDINATOR"] = f"127.0.0.1:{port}"
        procs.append(subprocess.Popen([sys.executable, *argv], env=env))
    return procs


def terminate_procs(procs: Sequence[subprocess.Popen],
                    grace: float = 3.0) -> None:
    """Tear a gang down: close stdin pipes first (the ssh watchdog path
    — EOF TERM-then-KILLs the REMOTE tree), then TERM every local
    process, then KILL whatever ignored the TERM after ``grace``."""
    for p in procs:
        if p.stdin is not None and not p.stdin.closed:
            try:
                p.stdin.close()
            except OSError:
                pass
        if p.poll() is None:
            try:
                p.terminate()
            except OSError:
                pass
    deadline = time.time() + grace
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
                try:
                    p.wait(timeout=grace)
                except subprocess.TimeoutExpired:
                    pass


def launch_local(nprocs: int, argv: Sequence[str],
                 devices_per_proc: int = 1,
                 coordinator_port: Optional[int] = None,
                 env_extra: Optional[dict] = None,
                 timeout: float = 600.0) -> List[int]:
    """Spawn ``nprocs`` local worker processes and wait; returns their
    return codes. Workers must call paddle_tpu.distributed.init()."""
    procs = spawn_local_procs(nprocs, argv,
                              devices_per_proc=devices_per_proc,
                              coordinator_port=coordinator_port,
                              env_extra=env_extra)
    return _wait_all(procs, timeout)


def _wait_all(procs: Sequence[subprocess.Popen],
              timeout: float, grace: float = 5.0) -> List[int]:
    deadline = time.time() + timeout
    rcs = []
    for p in procs:
        remain = max(1.0, deadline - time.time())
        try:
            rcs.append(p.wait(timeout=remain))
        except subprocess.TimeoutExpired:
            # ssh-mode teardown (ADVICE round-5): killing only the local
            # ssh client leaves the REMOTE worker tree running — and
            # holding the coordinator port. launch_ssh wraps every remote
            # command in a stdin watchdog (_wrap_remote), so closing our
            # end of the stdin pipe delivers EOF to the watchdog, which
            # TERM-then-KILLs the worker's whole process group; only then
            # is the local client killed if it still lingers.
            if p.stdin is not None:
                try:
                    p.stdin.close()
                except OSError:
                    pass
                try:
                    rcs.append(p.wait(timeout=grace))
                    continue
                except subprocess.TimeoutExpired:
                    pass
            p.kill()
            rcs.append(-9)
    for p in procs:                 # close leftover stdin pipes (ssh mode)
        if p.stdin is not None and not p.stdin.closed:
            try:
                p.stdin.close()
            except OSError:
                pass
    return rcs


def _wrap_remote(cmd: str, grace: float = 3.0) -> str:
    """Wrap a remote command so its whole process tree dies when the ssh
    connection goes away (local timeout/kill, network drop). The worker
    runs in its own session (``setsid`` → its pid is the process-group
    id); a watchdog reads stdin and on EOF — which is what a closed ssh
    connection delivers — TERMs, then after ``grace`` seconds KILLs,
    that group. On normal completion the watchdog group is reaped and
    the worker's exit status is preserved (ssh propagates it)."""
    q = shlex.quote(cmd)
    return (
        # the connection's stdin must reach the BACKGROUNDED watchdog
        # explicitly (fd 3): POSIX shells give async jobs /dev/null as
        # stdin, which would EOF the watchdog instantly
        "exec 3<&0; "
        "if command -v setsid >/dev/null 2>&1; then S=setsid; else S=; fi; "
        f"$S sh -c {q} 3<&- & c=$!; "
        # 'kill -s SIG -- "-pid"' is the pgroup form every sh builtin
        # (dash included) actually parses; pid fallback for setsid-less
        # hosts where the group does not exist
        f"C=$c G={grace} $S sh -c "
        "'cat <&3 >/dev/null; kill -s TERM -- \"-$C\" 2>/dev/null || "
        "kill -s TERM \"$C\" 2>/dev/null; sleep $G; "
        "kill -s KILL -- \"-$C\" 2>/dev/null || "
        "kill -s KILL \"$C\" 2>/dev/null' "
        "& k=$!; exec 3<&-; "
        "wait $c; rc=$?; "
        "kill -s KILL -- \"-$k\" 2>/dev/null || kill -s KILL $k 2>/dev/null; "
        "exit $rc")


def launch_ssh(hosts: Sequence[str], argv: Sequence[str], *,
               port: int = 6007, workdir: Optional[str] = None,
               env_extra: Optional[dict] = None,
               ssh_cmd: Sequence[str] = ("ssh", "-o", "BatchMode=yes"),
               timeout: float = 86400.0) -> List[int]:
    """SSH fan-out: one worker process per host, rank = position in
    ``hosts``, coordinator = ``hosts[0]:port`` (the reference's
    paddle/scripts/cluster_train/paddle.py slot — but every process is
    identical here: no pserver role, jax.distributed + GSPMD replace it).

    The PADDLE_* env contract is injected via ``env`` on the remote
    command line, so nothing needs to be pre-configured on the hosts
    beyond the code and its interpreter being present (pass ``workdir``
    to cd into the repo checkout first). Workers must call
    ``paddle_tpu.distributed.init()``. Returns per-host return codes
    (ssh propagates the remote exit status).

    Every remote command runs under a process-group watchdog
    (``_wrap_remote``): if the ssh connection drops — including
    ``_wait_all`` timing out and closing the client's stdin — the whole
    remote worker tree is torn down instead of lingering and holding
    the coordinator port (ADVICE round-5)."""
    procs = spawn_ssh_procs(hosts, argv, port=port, workdir=workdir,
                            env_extra=env_extra, ssh_cmd=ssh_cmd)
    return _wait_all(procs, timeout)


def spawn_ssh_procs(hosts: Sequence[str], argv: Sequence[str], *,
                    port: int = 6007, workdir: Optional[str] = None,
                    env_extra: Optional[dict] = None,
                    env_per_rank: Optional[Sequence[dict]] = None,
                    ssh_cmd: Sequence[str] = ("ssh", "-o", "BatchMode=yes")
                    ) -> List[subprocess.Popen]:
    """The ssh fan-out WITHOUT waiting — the supervisor's remote-gang
    primitive: it re-invokes this with a patched ``hosts`` list
    (replacement-host injection) and a fresh port per coordination
    epoch, and tears the gang down via ``terminate_procs`` (the stdin
    watchdog reaches the remote trees). Each worker also gets
    ``PADDLE_GANG_HOST`` so host-scoped fault policies and logs can
    name the box they ran on."""
    envs_common = dict(env_extra or {})
    procs = []
    for rank, host in enumerate(hosts):
        envs = {"PADDLE_COORDINATOR": f"{hosts[0]}:{port}",
                "PADDLE_NUM_PROCESSES": str(len(hosts)),
                "PADDLE_PROCESS_ID": str(rank),
                "PADDLE_GANG_HOST": host, **envs_common,
                **(env_per_rank[rank] if env_per_rank else {})}
        exports = " ".join(f"{k}={shlex.quote(str(v))}"
                           for k, v in envs.items())
        cd = f"cd {shlex.quote(workdir)} && " if workdir else ""
        # exec so the wrapper's $c IS the worker process, not an
        # intermediate sh — on setsid-less hosts the watchdog's
        # pid-fallback kill then still reaches the worker itself
        remote = _wrap_remote(cd + "exec env " + exports + " "
                              + " ".join(shlex.quote(a) for a in argv))
        procs.append(subprocess.Popen([*ssh_cmd, host, remote],
                                      stdin=subprocess.PIPE))
    return procs


_MP_CPU_PROBE = """
import paddle_tpu.distributed as dist
dist.init()
import jax, jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.sharding import Mesh
import numpy as np
devs = jax.devices()
assert len(devs) == 2, devs
mesh = Mesh(np.asarray(devs), ("d",))
x = jax.device_put(jnp.ones((2,), jnp.float32), NamedSharding(mesh, P("d")))
from paddle_tpu.parallel.compat import shard_map
import jax.lax as lax
total = jax.jit(shard_map(lambda v: lax.psum(jnp.sum(v), "d"), mesh=mesh,
                          in_specs=P("d"), out_specs=P()))(x)
assert float(total) == 2.0, float(total)
"""

_mp_cpu_supported: Optional[bool] = None


def multiprocess_cpu_supported(timeout: float = 240.0) -> bool:
    """Whether THIS jaxlib can actually execute cross-process
    computations on the CPU backend. Several jaxlib releases accept
    ``jax.distributed.initialize`` on CPU but then die at dispatch with
    "Multiprocess computations aren't implemented on the CPU backend" —
    the probe runs a 2-process 1-device-each psum once per process and
    caches the verdict, so the slow multi-process tests can skip with a
    reason instead of failing on an environment limitation. Override
    with PADDLE_TPU_MULTIPROC_CPU=0/1 to skip the probe."""
    global _mp_cpu_supported
    forced = os.environ.get("PADDLE_TPU_MULTIPROC_CPU")
    if forced is not None:
        return forced not in ("0", "false", "no")
    if _mp_cpu_supported is None:
        import tempfile
        with tempfile.TemporaryDirectory() as td:
            probe = os.path.join(td, "probe.py")
            repo = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            with open(probe, "w") as f:
                f.write(f"import sys; sys.path.insert(0, {repo!r})\n"
                        + _MP_CPU_PROBE)
            try:
                rcs = launch_local(2, [probe], devices_per_proc=1,
                                   timeout=timeout)
            except Exception:  # noqa: BLE001 — a broken probe = no
                rcs = [-1]
            _mp_cpu_supported = all(rc == 0 for rc in rcs)
    return _mp_cpu_supported


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="paddle_tpu.runtime.launch",
        description="multi-process launcher: local simulation or ssh "
        "fan-out across hosts (docs/howto_distributed.md)")
    ap.add_argument("--nprocs", type=int, default=2)
    ap.add_argument("--devices-per-proc", type=int, default=1)
    ap.add_argument("--timeout", type=float, default=600.0)
    ap.add_argument("--hosts", default=None,
                    help="comma-separated host list: ssh mode, one "
                    "worker per host, coordinator on the first")
    ap.add_argument("--port", type=int, default=6007,
                    help="coordinator port (ssh mode)")
    ap.add_argument("--workdir", default=None,
                    help="remote directory to cd into (ssh mode)")
    ap.add_argument("--ssh-cmd", default="ssh -o BatchMode=yes",
                    help="ssh command prefix (ssh mode)")
    ap.add_argument("worker", nargs=argparse.REMAINDER,
                    help="worker script and args")
    args = ap.parse_args(argv)
    if not args.worker:
        ap.error("worker script required")
    if args.hosts:
        rcs = launch_ssh(args.hosts.split(","), args.worker,
                         port=args.port, workdir=args.workdir,
                         ssh_cmd=tuple(args.ssh_cmd.split()),
                         timeout=args.timeout)
    else:
        rcs = launch_local(args.nprocs, args.worker,
                           devices_per_proc=args.devices_per_proc,
                           timeout=args.timeout)
    print(f"launch: workers exited {rcs}")
    return 0 if all(rc == 0 for rc in rcs) else 1


if __name__ == "__main__":
    sys.exit(main())
