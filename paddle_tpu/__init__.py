"""paddle_tpu — a TPU-native deep learning framework.

A from-scratch rebuild of the capability surface of 2017-era PaddlePaddle
(reference: wanghaox/Paddle) designed idiomatically for TPU hardware:

- traced pure-function programs compiled by XLA (replaces the ModelConfig /
  ProgramDesc protobuf graphs executed by GradientMachine / Executor,
  reference: paddle/gserver/gradientmachines/, paddle/framework/executor.cc)
- in-graph XLA collectives over ICI/DCN via ``jax.sharding`` meshes
  (replaces the C++/Go parameter servers, reference: paddle/pserver/, go/pserver/)
- ``lax.scan`` / masked segment kernels for variable-length sequences
  (replaces LoDTensor / Argument.sequenceStartPositions,
  reference: paddle/framework/lod_tensor.h:82, paddle/parameter/Argument.h:84)
- Pallas kernels where XLA fusion is insufficient (replaces the hand-written
  CUDA in paddle/cuda/src/).

Public API mirrors the v2 Python API (reference: python/paddle/v2/__init__.py):

    import paddle_tpu as paddle
    img  = paddle.layer.data(name="pixel", type=paddle.data_type.dense_vector(784))
    fc   = paddle.layer.fc(input=img, size=10, act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=fc, label=lbl)
    params  = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(cost=cost, parameters=params,
                                 update_equation=paddle.optimizer.Momentum(...))
    trainer.train(reader=..., event_handler=...)
"""

import importlib

from paddle_tpu.version import __version__

# Submodules exposed lazily (PEP 562) so partial builds stay importable and
# `import paddle_tpu` stays fast.
_SUBMODULES = (
    "utils", "core", "ops", "layer", "activation", "attr", "data_type",
    "initializer", "networks", "optimizer", "parameters", "pooling",
    "topology", "trainer", "event", "reader", "dataset", "inference",
    "evaluator", "parallel", "models", "io", "runtime", "recurrent",
    "projection", "image", "plot", "distributed", "observe", "pipeline",
)


def __getattr__(name):
    if name in _SUBMODULES:
        mod = importlib.import_module(f"paddle_tpu.{name}")
        globals()[name] = mod
        return mod
    if name == "infer":
        from paddle_tpu.inference import infer
        return infer
    if name == "batch":
        from paddle_tpu.reader.minibatch import batch
        return batch
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_SUBMODULES) + ["infer", "batch"])


# historical flag names (paddle/utils/Flags.cpp) mapped to their TPU-native
# equivalents for v2-API source compatibility
_LEGACY_FLAG_ALIASES = {"use_gpu": "use_tpu"}


def init(**kwargs):
    """Global initialisation (reference: paddle.init / initMain,
    paddle/utils/Flags.cpp, python/paddle/v2/__init__.py:123).

    Accepts the historical flags (use_gpu, trainer_count, ...) for source
    compatibility; aliased names map onto their TPU equivalents, other
    unknown flags are ignored as the reference's init did.
    """
    from paddle_tpu.utils import flags as _flags
    from paddle_tpu.utils import rng as _rng
    if kwargs.get("platform"):
        # must run before any jax computation; the JAX_PLATFORMS env var
        # cannot serve here because site hooks may override it
        import jax
        try:
            # best-effort diagnostic only: a private API that any JAX
            # upgrade may rename; the config update below is what matters
            from jax._src import xla_bridge
            already = xla_bridge.backends_are_initialized()
        except (ImportError, AttributeError):
            already = False
        if already:
            raise RuntimeError(
                "paddle.init(platform=...) called after the JAX backend "
                "was already initialized - the setting would be silently "
                "ignored. Call init() before any jax computation.")
        jax.config.update("jax_platforms", kwargs["platform"])
    for k, v in kwargs.items():
        _flags.GLOBAL_FLAGS.set_if_known(_LEGACY_FLAG_ALIASES.get(k, k), v)
    if kwargs.get("seed"):
        _rng.reset_global_seed(int(kwargs["seed"]))
    # FP-exception tripwires (reference: feenableexcept(FE_INVALID|
    # FE_DIVBYZERO|FE_OVERFLOW), paddle/trainer/TrainerMain.cpp:49) — the XLA
    # equivalent re-runs jitted computations op-by-op on a non-finite result
    # and raises at the producing op.
    if _flags.GLOBAL_FLAGS.get("debug_nans") or \
            _flags.GLOBAL_FLAGS.get("debug_infs"):
        import jax
        if _flags.GLOBAL_FLAGS.get("debug_nans"):
            jax.config.update("jax_debug_nans", True)
        if _flags.GLOBAL_FLAGS.get("debug_infs"):
            jax.config.update("jax_debug_infs", True)
    return _flags.GLOBAL_FLAGS
