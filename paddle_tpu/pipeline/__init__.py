"""paddle_tpu.pipeline — asynchronous, checkpointable input pipeline.

The staged data-feeding subsystem (reference slot: the v1
PyDataProvider2 async pool + the Go master's chunk dispatch): a
resumable :class:`Source` flows through parallel transform workers, a
streaming shuffle, and a batcher into a bounded host staging ring; a
device stage converts and transfers batches ahead of the training step.
``Pipeline.state_dict()`` captures the exact stream position (source
cursor, in-flight transform samples, shuffle RNG + buffer, batch
counter) and rides inside checkpoints, so preemption recovery resumes
on the exact next batch.

Typical wiring::

    from paddle_tpu import pipeline

    pipe = pipeline.Pipeline(
        pipeline.ShardSource(["part-00000", "part-00001"], seed=7),
        transform=decode_fn, transform_workers=4,
        shuffle_size=4096, batch_size=128, prefetch=4)
    trainer.train(reader=pipe, num_passes=10,
                  checkpoint_dir="ckpts")      # state saved + restored

or, for any existing batch reader, just ``trainer.train(reader=...,
prefetch=4)`` — the trainer wraps it in a pipeline with replay-skip
resume.
"""

from paddle_tpu.pipeline.core import (  # noqa: F401
    Pipeline, PipelineClosed)
from paddle_tpu.pipeline.source import (  # noqa: F401
    MasterSource, ReaderSource, ShardSource, Source, as_source)
from paddle_tpu.pipeline.stages import (  # noqa: F401
    BatchStage, ShuffleStage, TransformStage)
