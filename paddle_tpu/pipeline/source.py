"""Checkpointable sample sources for the input pipeline.

Reference slot: the v1 data-provider layer (PyDataProvider2 readers) and
the Go master's chunk-task dispatch (go/master/service.go) — the three
ways training data enters the system:

- ``ReaderSource``   — any v2 reader callable (zero-arg, returns an
  iterator of samples). Cursor = sample offset; resume replays and
  skips, so exactness requires the callable to be deterministic
  (seeded shuffle decorators qualify).
- ``ShardSource``    — ``runtime/recordio`` shard files. Cursor =
  (epoch, chunk position, record position) against a per-epoch chunk
  permutation derived from (seed, epoch) — O(one chunk re-read) exact
  resume, no replay.
- ``MasterSource``   — a ``runtime.master.MasterClient`` task stream.
  Position lives in the MASTER's lease queues (a restore re-leases
  unfinished tasks, service.go recover semantics); local state is a
  best-effort record counter.

The Source contract the Pipeline builds on: iterating yields samples of
the CURRENT epoch from the current cursor, advancing the cursor per
sample; exhausting an epoch rolls the cursor to the next epoch's start.
``state_dict()`` is cheap (a few scalars) and must be captured only
while iteration is suspended — the Pipeline's producer does exactly
that, at batch boundaries.
"""

import random
from typing import Callable, Iterator, List, Optional, Sequence

from paddle_tpu.utils import enforce


class Source:
    """Base: a resumable, epoch-aware sample stream."""

    kind = "source"

    def state_dict(self) -> dict:
        raise NotImplementedError

    def load_state_dict(self, state: dict) -> None:
        raise NotImplementedError

    def _check_kind(self, state: dict) -> None:
        got = state.get("kind")
        enforce.enforce(
            got == self.kind,
            f"pipeline source state mismatch: checkpoint carries "
            f"{got!r} state, this pipeline is built on {self.kind!r}")

    def __iter__(self) -> Iterator:
        raise NotImplementedError


def as_source(obj) -> Source:
    """Coerce a pipeline input into a Source: Source instances pass
    through, zero-arg reader callables wrap in ReaderSource."""
    if isinstance(obj, Source):
        return obj
    if callable(obj):
        return ReaderSource(obj)
    raise TypeError(
        f"pipeline source must be a Source or a reader callable, "
        f"got {type(obj).__name__}")


class ReaderSource(Source):
    """Wrap a v2 reader callable. Resume = re-invoke the callable and
    skip ``offset`` samples, so mid-epoch exactness requires the reader
    to be deterministic across invocations (seeded shuffle etc.); the
    skip cost is O(offset) — the shard/master sources avoid it."""

    kind = "reader"

    def __init__(self, reader_fn: Callable):
        self._fn = reader_fn
        self.epoch = 0
        self.offset = 0

    def state_dict(self) -> dict:
        return {"kind": self.kind, "epoch": self.epoch,
                "offset": self.offset}

    def load_state_dict(self, state: dict) -> None:
        self._check_kind(state)
        self.epoch = int(state["epoch"])
        self.offset = int(state["offset"])

    def __iter__(self) -> Iterator:
        it = iter(self._fn())
        for _ in range(self.offset):
            try:
                next(it)
            except StopIteration:
                # the reader shrank under the checkpoint: surface it —
                # silently restarting would replay seen data
                raise RuntimeError(
                    f"ReaderSource resume: reader exhausted before the "
                    f"checkpointed offset {self.offset} (epoch "
                    f"{self.epoch}) — the underlying data changed")
        for sample in it:
            self.offset += 1
            yield sample
        self.epoch += 1
        self.offset = 0


class ShardSource(Source):
    """Recordio shard files with an exact chunk-level cursor.

    Per epoch the chunk list (across all paths) is permuted by an RNG
    derived from ``(seed, epoch)`` — no RNG *state* needs persisting,
    the permutation is recomputed on resume. Resume cost: re-reading
    one chunk and skipping ``record_pos`` records inside it."""

    kind = "shards"

    def __init__(self, paths: Sequence[str], shuffle_chunks: bool = True,
                 seed: int = 0):
        if isinstance(paths, str):
            paths = [paths]
        self.paths = list(paths)
        enforce.enforce(self.paths, "ShardSource needs at least one path")
        self.shuffle_chunks = shuffle_chunks
        self.seed = int(seed)
        self.epoch = 0
        self.chunk_pos = 0
        self.record_pos = 0
        self._index: Optional[List] = None     # [(path, offset, nrecords)]

    def _build_index(self) -> List:
        if self._index is None:
            from paddle_tpu.runtime import recordio
            idx = []
            for p in self.paths:
                for offset, n in recordio.chunk_offsets(p):
                    idx.append((p, offset, n))
            self._index = idx
        return self._index

    def _order(self, epoch: int) -> List[int]:
        order = list(range(len(self._build_index())))
        if self.shuffle_chunks:
            random.Random(self.seed * 1000003 + epoch).shuffle(order)
        return order

    def num_records(self) -> int:
        return sum(n for _, _, n in self._build_index())

    def state_dict(self) -> dict:
        return {"kind": self.kind, "epoch": self.epoch,
                "chunk_pos": self.chunk_pos,
                "record_pos": self.record_pos}

    def load_state_dict(self, state: dict) -> None:
        self._check_kind(state)
        self.epoch = int(state["epoch"])
        self.chunk_pos = int(state["chunk_pos"])
        self.record_pos = int(state["record_pos"])

    def __iter__(self) -> Iterator:
        from paddle_tpu.runtime import recordio
        order = self._order(self.epoch)
        while self.chunk_pos < len(order):
            path, offset, _ = self._build_index()[order[self.chunk_pos]]
            records = list(recordio.read_chunk(path, offset))
            for i in range(self.record_pos, len(records)):
                self.record_pos = i + 1
                yield records[i]
            self.chunk_pos += 1
            self.record_pos = 0
        self.epoch += 1
        self.chunk_pos = 0


class MasterSource(Source):
    """Stream records from the elastic master service. The dispatch
    position is MASTER-side state (lease queues + snapshot file): on a
    trainer restart unfinished leases time out and requeue, so no data
    is lost — but the master's chunk granularity, not this counter,
    decides what replays. ``state_dict`` is therefore informational
    (records consumed), not a replay cursor."""

    kind = "master"

    def __init__(self, client, poll_interval: float = 0.05):
        self.client = client
        self.poll_interval = poll_interval
        self.records = 0
        self.epoch = 0

    def state_dict(self) -> dict:
        return {"kind": self.kind, "records": self.records,
                "epoch": self.epoch}

    def load_state_dict(self, state: dict) -> None:
        self._check_kind(state)
        self.records = int(state.get("records", 0))
        self.epoch = int(state.get("epoch", 0))

    def __iter__(self) -> Iterator:
        # one master pass per iteration — the Pipeline's epoch contract
        gen = self.client.reader(poll_interval=self.poll_interval,
                                 max_epochs=1)()
        for rec in gen:
            self.records += 1
            yield rec
        self.epoch += 1
