"""The asynchronous, checkpointable input pipeline.

Reference slot: the v1 data-provider layer's async double-buffer
(paddle/gserver/dataproviders/PyDataProvider2.cpp:195 pool) grown into
a staged subsystem: a resumable Source feeds parallel transform workers
and a streaming shuffle into a batcher, batches land in a bounded host
staging ring, and a device stage converts + ``device_put``s ahead of the
consumer so step N+1's feeds are already on device while step N
executes (the same hide-the-host-latency-behind-device-compute overlap
PAPERS.md's weight-update sharding paper makes for update cost).

Threads (all named ``pipeline-*`` — the test suite's thread-leak guard
keys on the prefix):

- ``pipeline-produce`` — drives source → transform → shuffle → batch,
  pushing ``(batch, state)`` into the staging ring (maxsize =
  ``prefetch``; a full ring backpressures the producer).
- ``pipeline-feed``    — pops batches, runs the convert fn (the
  trainer's ``DataFeeder.feed``) and the transfer fn (sharded
  ``device_put``), pushes device-bound feeds into the double-buffer
  queue (maxsize = ``device_depth``).
- transform workers    — ``pipeline-xform_*`` inside TransformStage.

Robustness contract: worker exceptions re-raise at ``next()`` (never a
silent hang or truncation), ``close()`` joins every thread, queues are
bounded end to end.

Checkpointing: every batch travels with the snapshot of the stage chain
taken at the moment the batcher emitted it (source cursor, in-flight
transform raws, shuffle RNG + buffer, batch counter). ``state_dict()``
returns the snapshot of the last batch the CONSUMER received — exactly
the resume point for batch k+1 after training on batch k —
and ``io/checkpoint.py`` carries it next to params/opt state, so a
preempted job restarts mid-epoch on the exact next batch.
"""

import queue
import threading
import time
from typing import Callable, Optional

from paddle_tpu import observe
from paddle_tpu.observe import metrics as _metrics
from paddle_tpu.pipeline.source import Source, as_source
from paddle_tpu.pipeline.stages import (BatchStage, ShuffleStage,
                                        TransformStage)
from paddle_tpu.utils import enforce
from paddle_tpu.utils.threadq import drain_join, put_stoppable as _put

_m_depth = _metrics.gauge(
    "pipeline_queue_depth",
    "staged batches per queue (labels: pipeline, stage=ring|device)")
_m_stage = _metrics.histogram(
    "pipeline_stage_seconds",
    "per-batch stage time (labels: pipeline, "
    "stage=produce|convert|transfer)")
_m_wait = _metrics.counter(
    "feed_wait_seconds_total",
    "consumer time blocked waiting for a feed (input-starvation; 0 "
    "means the pipeline fully hides host input behind device compute)")
_m_hits = _metrics.counter(
    "pipeline_prefetch_hits_total",
    "next() calls served without blocking (a feed was staged)")
_m_miss = _metrics.counter(
    "pipeline_prefetch_misses_total",
    "next() calls that had to wait on the pipeline")
_m_batches = _metrics.counter(
    "pipeline_batches_total", "batches delivered to the consumer")

_END = object()
STATE_VERSION = 1


class PipelineClosed(RuntimeError):
    """Raised when iterating a pipeline after close()."""


class _Err:
    """Error envelope: carries a stage thread's exception to next()."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


class Pipeline:
    """Composable staged input pipeline; see the module docstring.

    ``source``: a ``pipeline.Source`` or a zero-arg v2 reader callable.
    ``transform``: optional per-sample fn run by ``transform_workers``
    ordered parallel workers. ``shuffle_size``>0 inserts the streaming
    shuffle (seeded; its RNG + buffer checkpoint with the pipeline).
    ``batch_size=None`` passes source items through as ready batches.
    ``convert``/``transfer`` form the device stage — the trainer wires
    ``DataFeeder.feed`` and the sharded ``device_put`` via ``attach()``;
    both default to identity for host-only pipelines.

    ``track_state=False`` skips the per-batch stage-chain snapshot (a
    copy of the shuffle buffer's references + RNG state per emitted
    batch) for pipelines that will never checkpoint — ``state_dict()``
    then raises instead of returning a stale position.
    """

    def __init__(self, source, *, transform: Optional[Callable] = None,
                 transform_workers: int = 2, shuffle_size: int = 0,
                 seed: int = 0, batch_size: Optional[int] = None,
                 drop_last: bool = True, prefetch: int = 2,
                 device_depth: int = 2, convert: Optional[Callable] = None,
                 transfer: Optional[Callable] = None,
                 name: str = "pipeline", track_state: bool = True):
        self.source: Source = as_source(source)
        self._xform = (TransformStage(transform, transform_workers)
                       if transform is not None else None)
        self._shuffle = (ShuffleStage(shuffle_size, seed)
                         if shuffle_size else None)
        self._batch = BatchStage(batch_size, drop_last)
        self.prefetch = max(1, int(prefetch))
        self.device_depth = max(1, int(device_depth))
        self._convert = convert
        self._transfer = transfer
        self.name = name
        self.track_state = bool(track_state)
        self._restore_pending = []
        self._restore_draining = False
        self._stop = threading.Event()
        self._threads = []
        self._ring: Optional[queue.Queue] = None
        self._out: Optional[queue.Queue] = None
        self._active = False
        self._closed = False
        # identity of the CURRENT iteration: an abandoned epoch
        # generator whose GC-driven finally runs late must not tear
        # down a newer iteration's threads (close() already cleaned
        # the stale one when it invalidated the token)
        self._iter_token = None
        self._state = self._snapshot() if self.track_state else None

    # -- device-stage wiring (trainer) ------------------------------------
    def attach(self, convert: Optional[Callable] = None,
               transfer: Optional[Callable] = None) -> "Pipeline":
        """Install the convert/transfer fns of the device stage (the
        trainer calls this with its DataFeeder + parallel shardings).
        Must happen before iteration starts."""
        enforce.enforce(not self._active,
                        "pipeline.attach() while iterating")
        if convert is not None:
            self._convert = convert
        if transfer is not None:
            self._transfer = transfer
        return self

    # -- checkpoint state --------------------------------------------------
    def _snapshot(self) -> dict:
        """Consistent stage-chain snapshot; only called while the stage
        generators are suspended (producer thread at a batch boundary,
        or with no iteration active)."""
        return {
            "version": STATE_VERSION,
            "source": self.source.state_dict(),
            # in-flight transform raws + whether they are an epoch TAIL
            # (source already rolled): a tail restore must finish the
            # epoch from the raws alone, not splice next-epoch samples
            "pending": {
                "raws": (self._xform.pending() if self._xform
                         else []) + list(self._restore_pending),
                "draining": bool(
                    (self._xform.draining if self._xform else False)
                    or self._restore_draining),
            },
            "shuffle": self._shuffle.state() if self._shuffle else None,
            "batch": self._batch.state(),
        }

    def state_dict(self) -> dict:
        """The resume point: pipeline state as of the last batch the
        consumer received. Persist it next to the model checkpoint
        (``save_checkpoint(..., pipeline_state=...)``); restoring it
        continues the stream on the exact next batch."""
        enforce.enforce(
            self.track_state,
            "pipeline was built with track_state=False — no stream "
            "position is being captured to checkpoint")
        return self._state

    def load_state_dict(self, state: dict) -> None:
        enforce.enforce(not self._active,
                        "pipeline.load_state_dict() while iterating")
        enforce.enforce(
            self.track_state,
            "pipeline.load_state_dict() on a track_state=False pipeline")
        enforce.enforce(
            state.get("version") == STATE_VERSION,
            f"pipeline state version {state.get('version')} != "
            f"{STATE_VERSION}")
        self.source.load_state_dict(state["source"])
        pend = state.get("pending") or {}
        pending = list(pend.get("raws", ()))
        enforce.enforce(
            not pending or self._xform is not None,
            "pipeline state carries in-flight transform samples but "
            "this pipeline has no transform stage")
        if self._xform is not None:
            # the restored state REPLACES any abandoned epoch's leftover
            # in-flight work — keeping both would replay samples twice
            self._xform.take_inflight()
            self._xform.draining = False
        self._restore_pending = pending
        self._restore_draining = bool(pend.get("draining", False))
        if state.get("shuffle") is not None:
            enforce.enforce(
                self._shuffle is not None,
                "pipeline state carries shuffle state but this pipeline "
                "has no shuffle stage")
            self._shuffle.load_state(state["shuffle"])
        self._batch.load_state(state["batch"])
        self._state = self._snapshot()

    @property
    def batches_delivered(self) -> int:
        return self._batch.batches

    # -- stage threads -----------------------------------------------------
    def _produce(self, ring: queue.Queue, stop: threading.Event) -> None:
        try:
            stream = iter(self.source)
            if self._xform is not None:
                preload, self._restore_pending = self._restore_pending, []
                tail, self._restore_draining = self._restore_draining, False
                stream = self._xform.feed(stream, preload,
                                          preload_only=tail)
            if self._shuffle is not None:
                stream = self._shuffle.feed(stream)
            stream = self._batch.feed(stream)
            t0 = time.perf_counter()
            for batch in stream:
                _m_stage.observe(time.perf_counter() - t0,
                                 pipeline=self.name, stage="produce")
                state = self._snapshot() if self.track_state else None
                if not _put(ring, (batch, state), stop):
                    stream.close()     # run stage finalizers now
                    return
                _m_depth.set(ring.qsize(), pipeline=self.name,
                             stage="ring")
                t0 = time.perf_counter()
            _put(ring, _END, stop)
        except BaseException as e:  # noqa: BLE001 — re-raised at next()
            _put(ring, _Err(e), stop)

    def _feed(self, ring: queue.Queue, out: queue.Queue,
              stop: threading.Event) -> None:
        try:
            while True:
                try:
                    item = ring.get(timeout=0.1)
                except queue.Empty:
                    if stop.is_set():
                        return
                    continue
                _m_depth.set(ring.qsize(), pipeline=self.name,
                             stage="ring")
                if item is _END or isinstance(item, _Err):
                    _put(out, item, stop)
                    return
                batch, state = item
                with observe.trace_scope("feed"):
                    t0 = time.perf_counter()
                    if self._convert is not None:
                        with observe.trace_scope("convert"):
                            batch = self._convert(batch)
                    t1 = time.perf_counter()
                    _m_stage.observe(t1 - t0, pipeline=self.name,
                                     stage="convert")
                    if self._transfer is not None:
                        with observe.trace_scope("transfer"):
                            batch = self._transfer(batch)
                    _m_stage.observe(time.perf_counter() - t1,
                                     pipeline=self.name, stage="transfer")
                if not _put(out, (batch, state), stop):
                    return
                _m_depth.set(out.qsize(), pipeline=self.name,
                             stage="device")
        except BaseException as e:  # noqa: BLE001 — re-raised at next()
            _put(out, _Err(e), stop)

    # -- consumption -------------------------------------------------------
    def __iter__(self):
        """Yield device-ready feeds for ONE epoch (resuming mid-epoch
        when state was loaded); iterate again for the next epoch. Only
        one active iteration at a time. Abandoning an iteration
        mid-epoch discards the batches staged in the ring/device queues
        (in-flight TRANSFORM work is preserved and re-submitted) — for
        an exact continuation, restore via ``load_state_dict`` instead
        of abandoning."""
        if self._closed:
            raise PipelineClosed(f"pipeline {self.name!r} is closed")
        enforce.enforce(not self._active,
                        "pipeline already has an active iteration")
        self._active = True
        stop = self._stop = threading.Event()
        token = self._iter_token = object()
        ring = self._ring = queue.Queue(maxsize=self.prefetch)
        out = self._out = queue.Queue(maxsize=self.device_depth)
        threads = [
            threading.Thread(target=self._produce, args=(ring, stop),
                             name="pipeline-produce", daemon=True),
            threading.Thread(target=self._feed, args=(ring, out, stop),
                             name="pipeline-feed", daemon=True),
        ]
        self._threads = threads
        for t in threads:
            t.start()
        try:
            while True:
                t0 = time.perf_counter()
                try:
                    item = out.get_nowait()
                    _m_hits.inc(pipeline=self.name)
                except queue.Empty:
                    _m_miss.inc(pipeline=self.name)
                    with observe.trace_scope("feed"), \
                            observe.trace_scope("wait"):
                        while True:
                            try:
                                item = out.get(timeout=0.1)
                                break
                            except queue.Empty:
                                if stop.is_set():
                                    raise PipelineClosed(
                                        f"pipeline {self.name!r} closed "
                                        f"while iterating") from None
                    _m_wait.inc(time.perf_counter() - t0,
                                pipeline=self.name)
                _m_depth.set(out.qsize(), pipeline=self.name,
                             stage="device")
                if item is _END:
                    return
                if isinstance(item, _Err):
                    raise item.exc
                feeds, state = item
                self._state = state
                _m_batches.inc(pipeline=self.name)
                yield feeds
        finally:
            # a stale generator (abandoned, finalized late by GC after a
            # newer iter() started) was already cleaned up by close();
            # only the iteration that still owns the token tears down
            if self._iter_token is token:
                self._end_iteration()

    def _end_iteration(self) -> None:
        """Stop + join this iteration's threads (normal epoch end, an
        abandoned generator, or an error — all paths come through
        here, so no thread outlives its epoch)."""
        queues = [q for q in (self._ring, self._out) if q is not None]
        alive = drain_join(queues, self._threads, self._stop)
        if alive:
            # a producer stuck >10s inside user reader/transform code
            # cannot be joined; abandon it as a daemon and WARN — this
            # runs from finally blocks during exception propagation and
            # from trainer.train's cleanup, where a raise would mask
            # the original training error and leave close() half-done
            from paddle_tpu.utils.logger import get_logger
            get_logger("pipeline").warning(
                "pipeline %r: thread(s) %s still blocked in user code "
                "after 10s — abandoning them as daemons",
                self.name, [t.name for t in alive])
        self._threads = []
        self._ring = self._out = None
        self._active = False
        self._iter_token = None

    def __next__(self):
        raise TypeError("iterate the pipeline with iter()/for — each "
                        "iteration is one epoch")

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        """Stop all stage threads and release the transform pool.
        Idempotent; the pipeline cannot be iterated afterwards (its
        state_dict stays readable)."""
        if self._closed:
            return
        self._stop.set()
        if self._active:
            self._end_iteration()
        if self._xform is not None:
            self._xform.close()
        self._closed = True

    def __enter__(self) -> "Pipeline":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # best-effort: tests must close() explicitly
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass
