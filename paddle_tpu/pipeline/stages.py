"""Snapshotable pipeline stages: parallel transform, streaming shuffle,
batcher.

All three run lazily in the Pipeline's single producer thread (the
parallelism of the transform stage lives in its worker pool, not in the
stage driver), so when the downstream batcher yields a batch the whole
stage chain is suspended — the moment the Pipeline captures a
consistent snapshot:

- ``TransformStage``  — ordered parallel map over a bounded window of
  futures. Snapshot = the raw (pre-transform) samples still in flight;
  restore re-submits them, so outputs are exact as long as the map fn
  is deterministic per sample.
- ``ShuffleStage``    — reservoir-style streaming shuffle (fill a
  buffer, then swap a random slot per incoming sample). Snapshot = the
  RNG state plus the buffer contents; restore continues the identical
  random sequence.
- ``BatchStage``      — group into fixed-size lists (``size=None``
  passes items through, for sources that already yield batches).
  Snapshot = the partial batch plus the emitted-batch counter.
"""

import itertools
import random
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, Iterator, List, Optional


class TransformStage:
    """Ordered parallel map: up to ``workers`` samples transform
    concurrently inside a sliding window of ``window`` futures; outputs
    come back in input order regardless of worker scheduling. Worker
    exceptions surface in the driver thread at the corresponding
    position in the stream (never a silent drop)."""

    def __init__(self, fn: Callable, workers: int = 2,
                 window: Optional[int] = None):
        if workers < 1:
            raise ValueError(f"transform workers must be >= 1, "
                             f"got {workers}")
        self.fn = fn
        self.workers = workers
        self.window = window or workers * 2
        self._pool: Optional[ThreadPoolExecutor] = None
        self._inflight = deque()           # (future, raw_sample)
        # True once this epoch's input is exhausted and only in-window
        # work remains. A snapshot taken then pairs pending raws with a
        # source cursor that has ALREADY rolled to the next epoch — the
        # restore must finish the epoch from those raws alone
        # (preload_only), never splice next-epoch source samples in
        self.draining = False

    def _ensure_pool(self) -> ThreadPoolExecutor:
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=self.workers,
                thread_name_prefix="pipeline-xform")
        return self._pool

    def pending(self) -> List:
        """Raw samples submitted but not yet yielded downstream — the
        snapshot the Pipeline persists (restore re-submits them)."""
        return [raw for _, raw in self._inflight]

    def take_inflight(self) -> List:
        """Cancel the leftover futures of an abandoned epoch and return
        their raw samples. The raws are un-yielded work: a continued
        iteration re-submits them (fresh futures) ahead of new source
        samples; a state restore replaces them wholesale."""
        raws = [raw for _, raw in self._inflight]
        for fut, _ in self._inflight:
            fut.cancel()
        self._inflight.clear()
        return raws

    def feed(self, samples: Iterable, preload: Iterable = (),
             preload_only: bool = False) -> Iterator:
        """Transformed stream over ``preload`` (restored in-flight
        raws), stale in-flight raws (an abandoned prior epoch's
        drawn-but-undelivered work), then ``samples``; input order
        preserved. Stale futures from the abandoned epoch are cancelled
        and their raws re-submitted — draining them directly would
        raise CancelledError (or replay results out of band).

        ``preload_only=True`` is the restored tail drain: the snapshot
        was taken after the source exhausted this epoch (cursor already
        on the next epoch), so the epoch must finish from ``preload``
        alone — ``samples`` stays untouched for the next feed call."""
        stale = self.take_inflight()
        if self.draining:
            # abandoned mid-tail-drain: that epoch is over; its window
            # raws die with it (same fate as ring-staged batches)
            stale = []
            self.draining = False
        pool = self._ensure_pool()
        inflight = self._inflight
        try:
            if preload_only:
                self.draining = True       # snapshots must stay tail-only
                stream = itertools.chain(preload, stale)
            else:
                stream = itertools.chain(preload, stale, samples)
            for raw in stream:
                inflight.append((pool.submit(self.fn, raw), raw))
                if len(inflight) >= self.window:
                    fut, _ = inflight[0]
                    out = fut.result()     # raises the worker's exception
                    inflight.popleft()
                    yield out
            self.draining = True
            while inflight:
                fut, _ = inflight[0]
                out = fut.result()
                inflight.popleft()
                yield out
            self.draining = False
        finally:
            # abandoned mid-iteration (close/error): the un-yielded raws
            # stay in _inflight for a final snapshot; cancel what hasn't
            # started so close() doesn't wait on queued work
            for fut, _ in inflight:
                fut.cancel()

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
        self._inflight.clear()


class ShuffleStage:
    """Streaming pool shuffle: maintain ``size`` samples; each incoming
    sample evicts (yields) a uniformly random resident. Unlike the
    chunked ``reader.shuffle`` decorator this emits continuously (no
    buf-size latency cliffs) and its full state — RNG + buffer — is
    capturable, which is what makes mid-epoch resume exact."""

    def __init__(self, size: int, seed: int = 0):
        if size < 1:
            raise ValueError(f"shuffle size must be >= 1, got {size}")
        self.size = size
        self.rng = random.Random(seed)
        self.buf: List = []
        # True while the end-of-epoch drain is in flight: a checkpoint
        # taken mid-drain must resume by draining the REST of the buffer
        # (already shuffled), not by mixing next-epoch samples into it
        self.draining = False

    def state(self) -> dict:
        return {"rng": self.rng.getstate(), "buf": list(self.buf),
                "draining": self.draining}

    def load_state(self, state: dict) -> None:
        self.rng.setstate(state["rng"])
        self.buf = list(state["buf"])
        self.draining = bool(state.get("draining", False))

    def feed(self, samples: Iterable) -> Iterator:
        buf, rng = self.buf, self.rng
        if not self.draining:
            for s in samples:
                if len(buf) < self.size:
                    buf.append(s)
                    continue
                j = rng.randrange(self.size)
                out, buf[j] = buf[j], s
                yield out
            # epoch end: drain in random order (shuffle once, then pop —
            # a mid-drain snapshot carries the already-shuffled tail)
            self.draining = True
            rng.shuffle(buf)
        while buf:
            yield buf.pop()
        self.draining = False


class BatchStage:
    """Fixed-size batching with an emitted-batch counter. ``size=None``
    is the passthrough mode for sources that already yield whole
    batches (the trainer wrapping a ``paddle.batch`` reader)."""

    def __init__(self, size: Optional[int] = None, drop_last: bool = True):
        if size is not None and size < 1:
            raise ValueError(f"batch size must be >= 1, got {size}")
        self.size = size
        self.drop_last = drop_last
        self.partial: List = []
        self.batches = 0                   # emitted since construction

    def state(self) -> dict:
        return {"partial": list(self.partial), "batches": self.batches}

    def load_state(self, state: dict) -> None:
        self.partial = list(state["partial"])
        self.batches = int(state["batches"])

    def feed(self, samples: Iterable) -> Iterator:
        if self.size is None:
            for b in samples:
                self.batches += 1
                yield b
            return
        for s in samples:
            self.partial.append(s)
            if len(self.partial) == self.size:
                out, self.partial = self.partial, []
                self.batches += 1
                yield out
        if self.partial:
            if self.drop_last:
                # the ragged tail dies WITH the epoch — carrying it into
                # the next epoch's first batch would mix epochs (and a
                # resumed run replays the same drop, keeping snapshots
                # consistent: both runs discard at the same boundary)
                self.partial = []
            else:
                out, self.partial = self.partial, []
                self.batches += 1
                yield out
