"""Activation declarations (reference: python/paddle/trainer_config_helpers/
activations.py — BaseActivation subclasses with a .name consumed by the
config parser; runtime impls in paddle_tpu.ops.activations)."""


class BaseActivation:
    name = "linear"

    def __init__(self):
        pass

    def __repr__(self):
        return f"{type(self).__name__}()"


class Linear(BaseActivation):
    name = "linear"


class Relu(BaseActivation):
    name = "relu"


class Sigmoid(BaseActivation):
    name = "sigmoid"


class Tanh(BaseActivation):
    name = "tanh"


class STanh(BaseActivation):
    name = "stanh"


class BRelu(BaseActivation):
    name = "brelu"


class SoftRelu(BaseActivation):
    name = "softrelu"


class Exp(BaseActivation):
    name = "exponential"


class Log(BaseActivation):
    name = "log"


class Abs(BaseActivation):
    name = "abs"


class Square(BaseActivation):
    name = "square"


class Softmax(BaseActivation):
    name = "softmax"


class SequenceSoftmax(BaseActivation):
    """Softmax over each sequence's timesteps (reference:
    SequenceSoftmaxActivation; runtime: ops.sequence.seq_softmax)."""
    name = "sequence_softmax"


class Gelu(BaseActivation):
    name = "gelu"


class Silu(BaseActivation):
    name = "silu"


def resolve(act) -> str:
    """Accept an activation object, its name, or None → canonical name."""
    if act is None:
        return "linear"
    if isinstance(act, str):
        return act
    return act.name
