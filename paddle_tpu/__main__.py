"""``python -m paddle_tpu <job> --config=...`` — the trainer CLI
(reference: the `paddle` wrapper script, scripts/submit_local.sh.in)."""

import sys

from paddle_tpu.cli import main

sys.exit(main())
