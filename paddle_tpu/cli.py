"""The ``paddle_tpu`` command-line trainer.

Reference: paddle/trainer/TrainerMain.cpp:32-64 — jobs train / test /
checkgrad / time driven by ``--config=conf.py``; the config is a Python file
evaluated to produce the network (the reference embedded Python via
config_parser; here the config file simply builds layers with this package
and exposes a few names). ``paddle_tpu.scripts.submit`` mirrors the
``paddle`` wrapper (scripts/submit_local.sh.in).

Config file contract (module-level names):
  cost            — required for train/checkgrad/time: the cost LayerOutput
  reader          — callable() -> iterator of data tuples (train/time)
  test_reader     — optional, for --job=test and per-pass testing
  optimizer       — optional paddle_tpu optimizer (default Momentum)
  batch_size      — optional int (default 64)
  feeding         — optional dict name->index
  evaluators      — optional list of evaluator layers
  outputs         — required for job=infer: list of output LayerOutputs

Run: ``python -m paddle_tpu train --config=conf.py --num_passes=2``.
"""

import argparse
import os
import runpy
import sys
import time as _time

import numpy as np


def _load_config(path):
    cfg = runpy.run_path(path)
    return cfg


def _build_trainer(cfg, args):
    import paddle_tpu as paddle
    cost = cfg["cost"]
    params = paddle.parameters.create(cost)
    if args.init_model_path:
        with open(args.init_model_path, "rb") as f:
            params.from_tar_into(f)
    opt = cfg.get("optimizer") or paddle.optimizer.Momentum(
        momentum=0.9, learning_rate=0.01)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params, update_equation=opt,
        extra_layers=cfg.get("evaluators"))
    return trainer, params


def job_train(cfg, args):
    import paddle_tpu as paddle
    trainer, params = _build_trainer(cfg, args)
    health_srv = None
    if args.health_port is not None:
        health_srv = trainer.attach_observability(
            host=args.health_host, port=args.health_port)
        print(f"observability: {health_srv.url}/metrics  "
              f"{health_srv.url}/healthz")
    batch_size = cfg.get("batch_size", 64)
    reader = paddle.batch(cfg["reader"], batch_size)
    test_reader = cfg.get("test_reader")
    save_dir = args.save_dir
    costs = []

    def handler(ev):
        if isinstance(ev, paddle.event.EndIteration):
            costs.append(ev.cost)
            if ev.batch_id % args.log_period == 0:
                print(f"pass {ev.pass_id} batch {ev.batch_id} "
                      f"cost {ev.cost:.5f} {ev.metrics}")
        if isinstance(ev, paddle.event.EndPass):
            if test_reader is not None:
                res = trainer.test(paddle.batch(test_reader, batch_size),
                                   feeding=cfg.get("feeding"))
                print(f"pass {ev.pass_id} test: cost {res.cost:.5f} "
                      f"{res.metrics}")
            if save_dir:
                # per-pass dirs like the reference's save_dir/pass-%05d
                # (trainer/ParamUtil.cpp)
                pdir = os.path.join(save_dir, f"pass-{ev.pass_id:05d}")
                os.makedirs(pdir, exist_ok=True)
                with open(os.path.join(pdir, "params.tar"), "wb") as f:
                    trainer.save_parameter_to_tar(f)

    try:
        trainer.train(reader, num_passes=args.num_passes,
                      event_handler=handler, feeding=cfg.get("feeding"))
    finally:
        if health_srv is not None:
            health_srv.close()
    return 0


def job_test(cfg, args):
    import paddle_tpu as paddle
    trainer, params = _build_trainer(cfg, args)
    reader = paddle.batch(cfg.get("test_reader") or cfg["reader"],
                          cfg.get("batch_size", 64))
    res = trainer.test(reader, feeding=cfg.get("feeding"))
    print(f"test: cost {res.cost:.5f} {res.metrics}")
    return 0


def measure_time(cfg, batch_size=None, time_batches=20, warmup_batches=3,
                 init_model_path=None):
    """Steady-state train-step timing — the measurement core of job=time
    (reference protocol: `paddle train --job=time`,
    benchmark/paddle/image/run.sh:9-17). Returns a dict with ms/batch and
    examples/sec; reused by benchmarks/run_all.py."""
    import jax
    import paddle_tpu as paddle

    import jax.numpy as jnp

    def jnp_int32(i):
        return jnp.asarray(i, jnp.int32)

    class _Args:
        pass

    a = _Args()
    a.init_model_path = init_model_path
    trainer, params = _build_trainer(cfg, a)
    batch_size = batch_size or cfg.get("batch_size", 64)
    reader = paddle.batch(cfg["reader"], batch_size)
    # Two distinct batches cycled over the run: batch CONTENT doesn't affect
    # step time, and device-resident feeds keep host->device transfer out of
    # the timed window (essential on a tunneled TPU where shipping every
    # batch would measure the tunnel, not the chip; input pipeline
    # throughput is a separate measurement).
    batches = []
    for i, b in enumerate(reader()):
        if i >= 2:
            break
        batches.append(b)
    feeder = trainer._feeder(cfg.get("feeding"))
    step = trainer._train_step
    pv, ov, sv = (trainer.parameters.values, trainer.opt_state,
                  trainer.parameters.state)
    key = jax.random.PRNGKey(0)

    from paddle_tpu.utils.sync import host_sync as full_sync

    if not batches:
        raise ValueError("job=time: reader yielded no batches")
    from paddle_tpu import observe
    t_start = _time.perf_counter()
    feeds_list = [jax.device_put(feeder.feed(b)) for b in batches]
    jax.block_until_ready(feeds_list)
    nb = len(feeds_list)
    cost = None
    with observe.trace_scope("time_job/warmup"):
        for i in range(warmup_batches):
            cost, pv, ov, sv, _ = step(pv, ov, sv, feeds_list[i % nb],
                                       jnp_int32(i), key)
        if cost is not None:
            full_sync(pv, cost)
    warmup_s = _time.perf_counter() - t_start
    t0 = _time.perf_counter()
    with observe.trace_scope("time_job/timed"):
        for i in range(time_batches):
            cost, pv, ov, sv, _ = step(pv, ov, sv, feeds_list[i % nb],
                                       jnp_int32(warmup_batches + i), key)
        if cost is not None:
            full_sync(pv, cost)   # one sync for the run: steps are serial
    elapsed = _time.perf_counter() - t0
    ms = 1000 * elapsed / time_batches if time_batches else float("nan")
    return {
        "ms_per_batch": ms,
        "examples_per_sec": batch_size / (ms / 1000) if time_batches else
        float("nan"),
        "batch_size": batch_size,
        "timed_batches": time_batches,
        "compile_plus_warmup_s": warmup_s,
    }


def job_time(cfg, args):
    """Steady-state ms/batch (reference: --job=time,
    benchmark/paddle/image/run.sh:9)."""
    r = measure_time(cfg, time_batches=args.time_batches,
                     warmup_batches=args.warmup_batches,
                     init_model_path=args.init_model_path)
    from paddle_tpu import observe
    if observe.has_consumers():
        # --metrics_out promises a JSONL trail for the time job too
        observe.report(dict(r), kind="time_job")
    print(f"time job: {r['ms_per_batch']:.2f} ms/batch, "
          f"{r['examples_per_sec']:.1f} examples/sec "
          f"(batch_size={r['batch_size']}, "
          f"{r['timed_batches']} timed batches)")
    return 0


def job_infer(cfg, args):
    """Forward-only inference (reference: paddle.v2.infer, inference.py:111;
    capi serving when --model points at a merged artifact).

    Two sources for the model:
    - --model=artifact.tar  (merged-model file; config only supplies data)
    - config ``outputs`` + --init_model_path weights
    Input comes from config ``infer_reader`` (or ``test_reader``/``reader``),
    yielding the same tuples as training minus the label when ``feeding``
    maps only input fields. Results print as shapes + optionally save to
    --output_path (.npz keyed by output layer name).
    """
    import paddle_tpu as paddle
    import numpy as np

    batch_size = cfg.get("batch_size", 64)
    reader = cfg.get("infer_reader") or cfg.get("test_reader") \
        or cfg.get("reader")
    if reader is None:
        print("config must define infer_reader/test_reader/reader",
              file=sys.stderr)
        return 1
    rows = []
    for sample in reader():
        rows.append(sample)
        if args.infer_limit and len(rows) >= args.infer_limit:
            break

    if args.model:
        from paddle_tpu.data_feeder import DataFeeder
        from paddle_tpu.data_type import InputType, Kind, SeqLevel
        from paddle_tpu.io import merged
        from paddle_tpu.topology import Value
        m = merged.load_inference_model(args.model)
        specs = {name: InputType(d, Kind(k), SeqLevel(s))
                 for name, (d, k, s) in m.meta["data_specs"].items()}
        feeder = DataFeeder(specs, cfg.get("feeding"))
        chunks = []
        for i in range(0, len(rows), batch_size):
            feeds = feeder.feed(rows[i:i + batch_size])
            flat = {}
            for k, v in feeds.items():
                if isinstance(v, Value):
                    flat[k] = np.asarray(v.array)
                    if v.lengths is not None:
                        flat[f"{k}.lengths"] = np.asarray(v.lengths)
                else:
                    flat[k] = np.asarray(v)
            chunks.append(m.infer(flat))
        outs = {k: np.concatenate([c[k] for c in chunks], axis=0)
                for k in chunks[0]}
    else:
        outputs = cfg.get("outputs")
        if outputs is None:
            print("config must define `outputs` for job=infer "
                  "(or pass --model)", file=sys.stderr)
            return 1
        if not args.init_model_path:
            print("job=infer needs trained weights: pass "
                  "--init_model_path=params.tar (or --model=artifact.tar)",
                  file=sys.stderr)
            return 1
        params = paddle.parameters.create(
            outputs if isinstance(outputs, (list, tuple)) else [outputs])
        with open(args.init_model_path, "rb") as f:
            params.from_tar_into(f)
        res = paddle.infer(output_layer=outputs, parameters=params,
                           input=rows, feeding=cfg.get("feeding"),
                           batch_size=batch_size)
        names = [o.name for o in (outputs if isinstance(outputs,
                 (list, tuple)) else [outputs])]
        outs = dict(zip(names, res if isinstance(res, list) else [res]))

    for name, arr in outs.items():
        print(f"infer output {name}: shape {np.asarray(arr).shape}")
    if args.output_path:
        np.savez(args.output_path,
                 **{k: np.asarray(v) for k, v in outs.items()})
        print(f"saved outputs to {args.output_path}")
    return 0


def job_serve(args):
    """Continuous-batching LM serving: load an ``lm_serving`` artifact,
    schedule JSONL requests through the decode engine, write one JSONL
    result per request as it completes (NOT in submission order — that
    is the point of continuous batching). Transport is stdio by
    default; ``--port`` binds a TCP socket instead (the fleet replica
    mode), announcing the bound ports as one machine-readable
    ``{"replica_ready": ...}`` line on stdout.

    Request lines:  {"prompt": [ids...], "max_new": 32,
                     "temperature": 0.8, "top_k": 40, "eos_id": 2,
                     "tenant": "acme", "tier": "latency"}
    Result lines:   {"id": ..., "tokens": [ids...], "finish_reason":
                     "eos"|"max_tokens", "ttft_ms": ..., "latency_ms": ...}

    Paged-engine replicas additionally serve the fleet ops
    ``export_prefix`` / ``import_prefix`` (P/D disaggregation — see
    ``serving/replica.py`` for the wire).

    ``tenant``/``tier`` are optional: tier "latency" admits ahead of
    "batch" (and may preempt batch work's blocks on a paged engine); a
    malformed tier is rejected with a counted reason and an error
    line, never a traceback. ``--tenant-budget acme=4096``
    (repeatable) caps a tenant's in-flight tokens — exhaustion queues.

    SIGTERM drains gracefully in both transports: stop admitting new
    requests, finish everything in flight, emit the results, exit 0 —
    the replica-drain contract the fleet router relies on.

    ``--health_port`` exposes the engine's /metrics + /healthz (queue
    depth, slot occupancy, TTFT histograms, per-tier windows) while
    serving.
    """
    import json

    from paddle_tpu.io import lm_serving
    from paddle_tpu.serving import replica as _replica

    budgets = {}
    for spec in args.tenant_budget:
        tenant, eq, tokens = spec.partition("=")
        try:
            if not eq or not tenant or int(tokens) < 1:
                raise ValueError
            budgets[tenant] = int(tokens)
        except ValueError:
            print(f"serve: --tenant-budget expects TENANT=TOKENS "
                  f"(TOKENS >= 1), got {spec!r}", file=sys.stderr)
            return 1
    tiers = None
    if args.tiers_dram_mb or args.tiers_disk_mb:
        if args.tiers_disk_mb and not args.tiers_dir:
            print("serve: --tiers_disk_mb needs --tiers_dir",
                  file=sys.stderr)
            return 1
        tiers = {"dram_bytes": int(args.tiers_dram_mb * 1e6),
                 "disk_bytes": int(args.tiers_disk_mb * 1e6),
                 "disk_dir": args.tiers_dir}
    srv = lm_serving.load_lm_artifact(args.model)
    try:
        eng = srv.engine(tiers=tiers)
    except ValueError as e:
        print(f"serve: {e}", file=sys.stderr)
        return 1
    if budgets:
        if not hasattr(eng, "set_tenant_budget"):
            print("serve: --tenant-budget needs a paged-engine "
                  "artifact (format v4+)", file=sys.stderr)
            return 1
        for tenant, tokens in budgets.items():
            eng.set_tenant_budget(tenant, tokens)
    if args.ttft_slo_ms:
        from paddle_tpu.observe import SloConfig
        eng.configure_slo(SloConfig(
            ttft_s=args.ttft_slo_ms / 1000.0,
            target=args.slo_target,
            window_s=args.slo_window_s))
    health_srv = None
    if args.health_port is not None:
        health_srv = eng.serve(host=args.health_host,
                               port=args.health_port)
        print(f"observability: {health_srv.url}/metrics  "
              f"{health_srv.url}/healthz  {health_srv.url}/requests",
              file=sys.stderr)
    try:
        if args.port is not None:
            tcp = _replica.ReplicaServer(
                eng, host=args.serve_host, port=args.port,
                default_max_new=args.max_new)
            restore = _replica.install_drain_handler(tcp.loop)
            # the ready line is the ONLY stdout in --port mode: fleet
            # launchers (runtime.master.ServingFleet) parse it to learn
            # the ephemeral ports
            print(json.dumps({"replica_ready": {
                "port": tcp.port,
                "health_port": health_srv.port if health_srv else None,
            }}), flush=True)
            try:
                return tcp.serve_forever()
            finally:
                restore()
        return _replica.serve_stdio(eng, default_max_new=args.max_new)
    finally:
        if health_srv is not None:
            health_srv.close()


def job_route(args):
    """Serving-fleet router: front N engine replicas with prefix-aware
    placement, health-driven drain, and optional prefill/decode
    disaggregation (``serving/router.py``). Same stdio wire as
    ``serve`` — JSONL requests in, one JSONL result per request out —
    one tier up: results additionally carry the serving replica.

    Replicas come from either ``--replica HOST:PORT[:HEALTH_PORT]``
    (repeatable; connect to running ``serve --port`` processes) or
    ``--model`` + ``--replicas N`` (spawn the fleet locally via
    ``runtime.master.ServingFleet``). ``--prefill_replicas K`` marks
    the first K replicas as the disaggregated prefill tier. SIGTERM
    drains: stop admitting, finish in-flight, emit, exit 0."""
    import json
    import queue as _queue
    import signal
    import threading

    from paddle_tpu.serving import replica as _replica
    from paddle_tpu.serving.router import Router, fleet_keying

    fleet = None
    handles = []
    budgets = {}
    for spec in args.tenant_budget:
        tenant, _, tokens = spec.partition("=")
        try:
            budgets[tenant] = int(tokens)
        except ValueError:
            print(f"route: --tenant-budget expects TENANT=TOKENS, "
                  f"got {spec!r}", file=sys.stderr)
            return 1
    router_kw = dict(max_in_flight=args.max_in_flight,
                     fetch_flops_per_byte=args.fetch_flops_per_byte,
                     shed_queue_max=args.shed_queue_max,
                     shed_burn_max=args.shed_burn_max,
                     tenant_budgets=budgets or None)
    if args.ttft_slo_ms:
        from paddle_tpu.observe import SloConfig
        router_kw["slo"] = SloConfig(ttft_s=args.ttft_slo_ms / 1000.0,
                                     target=args.slo_target,
                                     window_s=args.slo_window_s)
    try:
        if args.model:
            from paddle_tpu.runtime.master import ServingFleet
            fleet = ServingFleet(args.model, replicas=args.replicas,
                                 prefill=args.prefill_replicas)
            fleet.start()
            router = fleet.router(**router_kw)
        elif args.replica:
            for i, spec in enumerate(args.replica):
                parts = spec.split(":")
                if len(parts) not in (2, 3):
                    print(f"route: --replica expects "
                          f"HOST:PORT[:HEALTH_PORT], got {spec!r}",
                          file=sys.stderr)
                    return 1
                health_url = (f"http://{parts[0]}:{parts[2]}"
                              if len(parts) == 3 else None)
                handles.append(_replica.SocketReplica(
                    f"replica{i}", (parts[0], int(parts[1])),
                    health_url))
            # placement keying comes from the engines themselves: the
            # paged /healthz reports block_size + chunk_tokens
            bs, chunk = fleet_keying(handles)
            prefill = [h.name for h in
                       handles[:max(args.prefill_replicas, 0)]]
            router = Router(handles, block_size=bs, chunk_tokens=chunk,
                            prefill=prefill, **router_kw)
        else:
            print("route: pass --replica HOST:PORT... or --model + "
                  "--replicas N", file=sys.stderr)
            return 1

        controller = None
        ctrl_srv = None
        if args.autoscale or args.wedge_timeout_s > 0:
            if fleet is None:
                print("route: --autoscale needs --model + --replicas "
                      "(a locally spawned fleet the controller can "
                      "respawn into); --replica endpoints have no "
                      "process lifecycle to drive", file=sys.stderr)
                return 1
            from paddle_tpu.serving.autoscale import FleetController
            controller = FleetController(
                router, fleet,
                min_replicas=args.min_replicas,
                max_replicas=args.max_replicas,
                max_restarts=args.heal_max_restarts,
                scale_up_queue=args.scale_up_queue,
                scale_down_idle_s=args.scale_down_idle_s,
                wedge_timeout_s=args.wedge_timeout_s)
            if args.controller_port is not None:
                ctrl_srv = controller.serve(host=args.health_host,
                                            port=args.controller_port)
                print(f"controller: {ctrl_srv.url}/healthz",
                      file=sys.stderr)

        health_srv = None
        if args.health_port is not None:
            health_srv = router.serve(host=args.health_host,
                                      port=args.health_port)
            print(f"observability: {health_srv.url}/metrics  "
                  f"{health_srv.url}/healthz  "
                  f"{health_srv.url}/requests  "
                  f"{health_srv.url}/alerts  (point `paddle_tpu top "
                  f"--url={health_srv.url}` here)", file=sys.stderr)

        inbox: "_queue.Queue" = _queue.Queue()
        draining = threading.Event()

        def _read_stdin():
            for line in sys.stdin:
                inbox.put(line)
            inbox.put(None)

        threading.Thread(target=_read_stdin, daemon=True,
                         name="route-stdin").start()
        if threading.current_thread() is threading.main_thread():
            signal.signal(signal.SIGTERM, lambda *_: draining.set())

        def emit(req):
            print(json.dumps({
                "id": req.xid, "tokens": req.tokens,
                "finish_reason": req.finish_reason
                if req.error is None else "error",
                "error": req.error,
                "replica": req.replica, "requeues": req.requeues,
                "ttft_ms": round(1000 * req.ttft_s, 3)
                if req.ttft_s is not None else None,
                "latency_ms": round(1000 * req.latency_s, 3)
                if req.latency_s is not None else None}), flush=True)

        def ingest(line):
            from paddle_tpu.serving.router import AdmissionError
            try:
                r = json.loads(line)
                router.submit(
                    np.asarray(r["prompt"], np.int32),
                    int(r.get("max_new", args.max_new)),
                    temperature=float(r.get("temperature", 0.0)),
                    top_k=int(r.get("top_k", 0)),
                    eos_id=r.get("eos_id"),
                    tenant=str(r.get("tenant", "default")),
                    tier=str(r.get("tier", "batch")))
            except AdmissionError as e:
                # a counted rejection, never a timeout: the client
                # learns the door's reason NOW and can back off
                print(json.dumps({
                    "error": f"shed: {e.reason}", "shed": e.reason,
                    "finish_reason": "shed"}), flush=True)
            except (ValueError, KeyError, TypeError) as e:
                print(json.dumps({"error": str(e)}), flush=True)

        eof = False
        sealed = False
        try:
            while True:
                if draining.is_set() and not sealed:
                    # seal (the serve-loop contract): lines already
                    # read were accepted — the drain finishes them;
                    # anything arriving after is refused below, so the
                    # drain converges under a streaming client
                    while True:
                        try:
                            item = inbox.get_nowait()
                        except _queue.Empty:
                            break
                        if item is None:
                            eof = True
                        elif item.strip():
                            ingest(item)
                    sealed = True
                if ((eof or sealed) and inbox.empty()
                        and router.idle):
                    break
                try:
                    line = inbox.get(
                        timeout=0.05 if router.idle else 0.0)
                    if line is None:
                        eof = True
                    elif not line.strip():
                        pass
                    elif sealed:
                        print(json.dumps({"error": "draining: router "
                                          "not admitting"}), flush=True)
                    else:
                        ingest(line)
                except _queue.Empty:
                    pass
                if not router.idle:
                    for d in router.step():
                        emit(d)
                elif controller is not None:
                    router.step()   # liveness + health even while
                    #                 idle: deaths must be SEEN for
                    #                 the heal loop to close
                if controller is not None and not sealed:
                    controller.step()
        finally:
            if ctrl_srv is not None:
                ctrl_srv.close()
            if health_srv is not None:
                health_srv.close()
            router.close()
    finally:
        if fleet is not None:
            fleet.close()
    return 0


def _render_top(health: dict, alerts: dict) -> str:
    """One frame of the `top` view: the fleet summary line, a
    per-replica table, and the firing-alert panel — pure function of
    the two endpoint documents so tests can pin the rendering."""
    def fmt(v, spec="", dash="-"):
        if v is None:
            return dash
        return format(v, spec) if spec else str(v)

    win = health.get("window") or {}
    lines = [
        "fleet: {q} queued  {r} requests  {c} completed  {rq} requeued"
        "  hit_rate {hr}  ttft_p99 {p99}s".format(
            q=health.get("queue_depth", 0),
            r=health.get("requests", 0),
            c=health.get("completed", 0),
            rq=health.get("requeued", 0),
            hr=fmt(health.get("placement_hit_rate"), ".2f"),
            p99=fmt(win.get("fleet_ttft_p99_s",
                            win.get("ttft_p99_s")), ".4f"))]
    if health.get("shed"):
        lines[0] += f"  shed {health['shed']}"
    ctl = health.get("controller")
    if ctl:
        lines.append(
            "controller: live {lv} [{mn}..{mx}]  heals {h}  "
            "wedge_kills {w}  scale {s}  spawn_tokens {t}".format(
                lv=ctl.get("live"), mn=ctl.get("min"),
                mx=ctl.get("max"), h=ctl.get("heals", 0),
                w=ctl.get("wedge_kills", 0),
                s=ctl.get("scale_events", 0),
                t=ctl.get("spawn_tokens")))
        if ctl.get("draining"):
            lines[-1] += "  draining " + ",".join(ctl["draining"])
        if ctl.get("abandoned"):
            lines[-1] += "  ABANDONED " + ",".join(ctl["abandoned"])
    hdr = (f"{'REPLICA':<12} {'ROLE':<8} {'STATE':<10} {'INFL':>4} "
           f"{'QUEUE':>5} {'BLOCKS':>11} {'TIERS':>9} {'TTFT_P99':>9} "
           f"{'BURN':>6}")
    lines.append(hdr)
    for name, rep in sorted((health.get("replicas") or {}).items()):
        used, total = rep.get("blocks_in_use"), rep.get("blocks_total")
        blocks = (f"{used}/{total}" if used is not None
                  and total is not None else "-")
        tiers = rep.get("tiers") or {}
        dram, disk = tiers.get("dram"), tiers.get("disk")
        tier_s = (f"{dram}/{disk}" if dram is not None
                  and disk is not None else "-")
        lines.append(
            f"{name:<12.12} {fmt(rep.get('role')):<8.8} "
            f"{fmt(rep.get('state')):<10.10} "
            f"{fmt(rep.get('in_flight')):>4} "
            f"{fmt(rep.get('queue_depth')):>5} {blocks:>11} "
            f"{tier_s:>9} "
            f"{fmt(rep.get('ttft_p99_s'), '.4f'):>9} "
            f"{fmt(rep.get('slo_burn'), '.2f'):>6}")
    firing = (alerts.get("firing") if alerts
              else health.get("alerts_firing")) or []
    if firing:
        lines.append("ALERTS FIRING:")
        for a in firing:
            lines.append(f"  !! {a.get('rule')}: value "
                         f"{fmt(a.get('value'), '.4f')} {a.get('op')} "
                         f"{a.get('threshold')}  {a.get('description')}")
    else:
        lines.append("alerts: none firing")
    return "\n".join(lines)


def _render_gang_top(health: dict, alerts: dict) -> str:
    """One frame of `top --supervisor`: the gang summary line, a
    per-rank table (state/step/recency/step-p50/barrier-p50), the
    straggler + goodput panel, and the firing alerts — pure function
    of the supervisor's /healthz + /alerts documents."""
    def fmt(v, spec="", dash="-"):
        if v is None:
            return dash
        return format(v, spec) if spec else str(v)

    gp = health.get("goodput") or {}
    lines = [
        "gang: state {st}  epoch {ep}  size {n}  restarts {r}  "
        "goodput {g}".format(
            st=health.get("state", "?"), ep=health.get("epoch", "?"),
            n=health.get("gang_size", "?"),
            r=health.get("restarts", 0),
            g=fmt(gp.get("goodput_fraction"), ".3f"))]
    hdr = (f"{'RANK':<6} {'STATE':<8} {'STEP':>8} {'SINCE':>7} "
           f"{'STEP_P50':>9} {'BARR_P50':>9} {'HB_AGE':>7}")
    lines.append(hdr)
    for rank, w in sorted((health.get("workers") or {}).items(),
                          key=lambda kv: int(kv[0])):
        state = "done" if w.get("done") else "ok"
        lines.append(
            f"{rank:<6.6} {state:<8.8} {fmt(w.get('step')):>8} "
            f"{fmt(w.get('since_step_s'), '.1f'):>7} "
            f"{fmt(w.get('step_p50_s'), '.4f'):>9} "
            f"{fmt(w.get('barrier_p50_s'), '.4f'):>9} "
            f"{fmt(w.get('age'), '.1f'):>7}")
    st = health.get("straggler") or {}
    skew = st.get("skew") or {}
    s_rank = st.get("straggler_rank")
    lines.append(
        "skew p50 {p50}s p99 {p99}s  straggler {who}".format(
            p50=fmt(skew.get("p50"), ".4f"),
            p99=fmt(skew.get("p99"), ".4f"),
            who=(f"rank {s_rank} ({st.get('rule')})"
                 if s_rank is not None else "none")))
    if gp.get("totals"):
        t = gp["totals"]
        overhead = ", ".join(
            f"{k} {v:.1f}s" for k, v in sorted(t.items())
            if k != "useful_step" and v)
        lines.append(f"goodput: useful {t.get('useful_step', 0):.1f}s "
                     f"of {gp.get('wall_accounted_s', 0)}s accounted"
                     + (f"  ({overhead})" if overhead else ""))
    firing = (alerts.get("firing") if alerts
              else health.get("alerts_firing")) or []
    if firing:
        lines.append("ALERTS FIRING:")
        for a in firing:
            lines.append(f"  !! {a.get('rule')}: value "
                         f"{fmt(a.get('value'), '.4f')} {a.get('op')} "
                         f"{a.get('threshold')}  {a.get('description')}")
    else:
        lines.append("alerts: none firing")
    return "\n".join(lines)


def job_top(args):
    """Live fleet status: a refresh loop over a running router's
    ``/healthz`` + ``/alerts`` endpoints (``route --health_port``) —
    per-replica state / in-flight / KV blocks / TTFT p99 / SLO burn,
    plus the firing-alert panel. With ``--supervisor`` (or pointed at
    a Supervisor endpoint — auto-detected from the health document's
    ``workers`` key) the frame is the TRAINING-gang view instead:
    per-rank step progress, step/barrier medians, straggler + goodput.
    ``--top_iterations`` bounds the loop (0 = until interrupted); on a
    TTY each frame repaints in place."""
    import json
    import time as _time
    import urllib.request

    if not args.url:
        print("top: pass --url http://HOST:HEALTH_PORT (a route "
              "--health_port endpoint)", file=sys.stderr)
        return 1
    base = args.url.rstrip("/")
    n = 0
    try:
        while True:
            try:
                with urllib.request.urlopen(base + "/healthz",
                                            timeout=2.0) as r:
                    health = json.loads(r.read().decode())
            except Exception as e:
                health, err = {}, e
                print(f"top: {base}/healthz unreachable: {e}",
                      file=sys.stderr)
            try:
                with urllib.request.urlopen(base + "/alerts",
                                            timeout=2.0) as r:
                    alerts = json.loads(r.read().decode())
            except Exception:
                alerts = {}    # router without an evaluator: panel off
            if health:
                if sys.stdout.isatty():
                    print("\x1b[2J\x1b[H", end="")
                gang = (getattr(args, "supervisor", False)
                        or "workers" in health)
                render = _render_gang_top if gang else _render_top
                print(render(health, alerts), flush=True)
            n += 1
            if args.top_iterations and n >= args.top_iterations:
                return 0 if health else 1
            _time.sleep(max(args.top_interval_s, 0.05))
    except KeyboardInterrupt:
        return 0


def _pct(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[i]


def job_stats(cfg, args):
    """Observability snapshot: with --metrics_file, summarize + tail a
    JSONL per-step metrics log written by the trainer/bench
    (`observe.JsonlSink`); with --trace, export the in-process span
    buffer as Chrome-trace JSON; otherwise render the current process's
    default metrics registry (--format=prom gives the Prometheus text
    exposition)."""
    from paddle_tpu import observe

    if args.requests:
        log = observe.default_request_log()
        slow = log.slowest(args.requests, by="ttft_s")
        summary = log.summary()
        print(f"request log: {summary['count']} records "
              f"(capacity {summary['capacity']}, "
              f"{summary['evicted']} evicted) — by dominant component: "
              + (", ".join(f"{k}={v}" for k, v in sorted(
                  summary["by_dominant_component"].items())) or "none"))
        for r in slow:
            a = r["attribution"]
            comps = " ".join(
                f"{c[:-2]} {1000 * a['components'][c]:.1f}ms"
                for c in observe.requests.COMPONENTS)
            print(f"  r{r.get('rid')} ttft {1000 * (r.get('ttft_s') or 0):.1f}ms "
                  f"latency {1000 * (r.get('latency_s') or 0):.1f}ms "
                  f"tokens {r.get('tokens')} "
                  f"cache_hit {r.get('cache_hit_frac', 0):.0%} "
                  f"[{comps}] -> dominated by {a['dominant']} "
                  f"({r.get('finish_reason')})")
        if not slow:
            print("  (no completed requests recorded in this process)")
        if not args.trace and not args.metrics_file:
            return 0

    if getattr(args, "merge", None):
        import json as _json
        if not args.trace:
            print("stats: --merge needs --trace OUT.json for the "
                  "merged timeline", file=sys.stderr)
            return 1
        docs = []
        for path in args.merge:
            try:
                with open(path) as f:
                    docs.append(_json.load(f))
            except (OSError, ValueError) as e:
                print(f"stats: cannot read trace {path}: {e}",
                      file=sys.stderr)
                return 1
        merged = observe.merge_traces(docs, path=args.trace)
        offs = merged["otherData"]["offsets_s"]
        print(f"merged {len(docs)} traces "
              f"({len(merged['traceEvents'])} events) into {args.trace}"
              f" — clock offsets vs first: "
              + ", ".join(f"{k}={v:+.6f}s" for k, v in offs.items()))
        return 0

    if args.trace:
        trace = observe.trace_export(args.trace)
        n = sum(1 for e in trace["traceEvents"] if e.get("ph") == "X")
        names = {e["name"] for e in trace["traceEvents"]
                 if e.get("ph") == "X"}
        print(f"wrote {n} spans ({len(names)} distinct) to {args.trace} "
              f"— open in chrome://tracing or https://ui.perfetto.dev")
        if not args.metrics_file and args.format == "pretty":
            return 0

    if args.metrics_file:
        try:
            recs = observe.read_jsonl(args.metrics_file)
        except OSError as e:
            print(f"stats: cannot read {args.metrics_file}: {e}",
                  file=sys.stderr)
            return 1
        if not recs:
            print(f"stats: no records in {args.metrics_file}")
            return 1
        steps = [r for r in recs if r.get("kind") == "step"]
        passes = [r for r in recs if r.get("kind") == "pass"]
        other = len(recs) - len(steps) - len(passes)
        print(f"{args.metrics_file}: {len(recs)} records "
              f"({len(steps)} steps, {len(passes)} passes"
              + (f", {other} other" if other else "") + ")")
        if steps:
            walls = sorted(float(r["wall_time_s"]) for r in steps
                           if isinstance(r.get("wall_time_s"), (int, float)))
            eps = [float(r["examples_per_sec"]) for r in steps
                   if isinstance(r.get("examples_per_sec"), (int, float))]
            losses = [float(r["loss"]) for r in steps
                      if isinstance(r.get("loss"), (int, float))]
            recompiles = sum(1 for r in steps if r.get("recompile"))
            print(f"  step wall ms: p50 {_pct(walls, .5)*1e3:.2f}  "
                  f"p90 {_pct(walls, .9)*1e3:.2f}  "
                  f"max {walls[-1]*1e3:.2f}" if walls else "")
            if eps:
                print(f"  examples/sec: last {eps[-1]:.1f}  "
                      f"mean {sum(eps)/len(eps):.1f}")
            if losses:
                print(f"  loss: first {losses[0]:.5f}  last {losses[-1]:.5f}")
            print(f"  recompiles tagged: {recompiles}")
        for r in passes:
            print(f"  pass {r.get('pass_id')}: {r.get('examples')} examples "
                  f"in {r.get('wall_time_s')}s "
                  f"({r.get('examples_per_sec')} ex/s) "
                  f"metrics {r.get('metrics', {})}")
        if args.last:
            print(f"--- last {args.last} records ---")
            import json as _json
            for r in recs[-args.last:]:
                print(_json.dumps(r))
        return 0

    reg = observe.default_registry()
    if args.format == "prom":
        print(reg.render_prometheus(), end="")
        return 0
    snap = reg.snapshot()
    if not snap:
        print("stats: default registry is empty (pass --metrics_file=... "
              "to inspect a JSONL metrics log)")
        return 0
    for name, m in snap.items():
        print(f"{name} ({m['kind']})" + (f" — {m['help']}" if m['help']
                                         else ""))
        for s in m["series"]:
            lbl = ",".join(f"{k}={v}" for k, v in s["labels"].items())
            lbl = f"{{{lbl}}}" if lbl else ""
            if m["kind"] == "histogram":
                print(f"  {lbl} count {s['count']} avg {s['avg']:.6f} "
                      f"min {s['min']:.6f} max {s['max']:.6f}")
            else:
                print(f"  {lbl} {s['value']}")
    return 0


def job_checkgrad(cfg, args):
    """Whole-model finite-difference gradient verification (reference:
    Trainer::checkGradient, trainer/Trainer.cpp:299-377)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.topology import Topology, Value

    cost = cfg["cost"]
    topo = Topology(cost)
    params = paddle.parameters.create(cost)
    fwd = topo.compile()
    batch = next(iter(paddle.batch(cfg["reader"],
                                   cfg.get("batch_size", 8))()))
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(learning_rate=0.0))
    feeds = trainer._feeder(cfg.get("feeding"))(batch)

    def loss(vals):
        outs, _ = fwd(vals, params.state, feeds)
        return jnp.mean(outs[cost.name].array.astype(jnp.float32))

    analytic = jax.jit(jax.grad(loss))(params.values)
    loss_f = jax.jit(loss)
    eps = args.checkgrad_eps
    rng = np.random.RandomState(0)
    worst = 0.0
    for name, arr in params.values.items():
        arr = np.asarray(arr, np.float64)
        flat = arr.reshape(-1)
        g = np.asarray(analytic[name], np.float64).reshape(-1)
        # sample a few coordinates per parameter (reference samples too)
        for idx in rng.choice(flat.size, size=min(4, flat.size),
                              replace=False):
            orig = flat[idx]
            vals = dict(params.values)
            pert = arr.copy().reshape(-1)
            pert[idx] = orig + eps
            vals[name] = pert.reshape(arr.shape).astype(np.float32)
            hi = float(loss_f(vals))
            pert[idx] = orig - eps
            vals[name] = pert.reshape(arr.shape).astype(np.float32)
            lo = float(loss_f(vals))
            numeric = (hi - lo) / (2 * eps)
            denom = max(abs(numeric), abs(g[idx]), 1e-6)
            rel = abs(numeric - g[idx]) / denom
            worst = max(worst, rel)
            status = "OK" if rel < args.checkgrad_tol else "FAIL"
            print(f"checkgrad {name}[{idx}]: analytic {g[idx]:+.6f} "
                  f"numeric {numeric:+.6f} rel_err {rel:.2e} {status}")
    print(f"checkgrad worst rel err: {worst:.2e}")
    return 0 if worst < args.checkgrad_tol else 1


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="paddle_tpu",
        description="TPU-native trainer CLI (reference: paddle_trainer, "
                    "TrainerMain.cpp)")
    p.add_argument("job", choices=["train", "test", "time", "checkgrad",
                                   "infer", "stats", "serve", "route",
                                   "top"],
                   help="what to run (TrainerMain.cpp:52-61; stats "
                        "renders an observability snapshot; serve runs "
                        "the continuous-batching LM engine over stdio "
                        "or --port TCP; route fronts N serve replicas "
                        "with the prefix-aware fleet router; top is a "
                        "live status view over a route --health_port)")
    p.add_argument("--config", default=None,
                   help="python config file (required for every job "
                        "except stats)")
    p.add_argument("--num_passes", type=int, default=1)
    p.add_argument("--save_dir", default=None)
    p.add_argument("--init_model_path", default=None)
    p.add_argument("--model", default=None,
                   help="merged-model artifact for job=infer / format-v3 "
                        "lm_serving artifact for job=serve")
    p.add_argument("--max_new", type=int, default=64,
                   help="default max_new for job=serve/route requests "
                        "that omit it")
    p.add_argument("--port", type=int, default=None,
                   help="job=serve: serve the JSONL wire on this TCP "
                        "port instead of stdio (0 = ephemeral; the "
                        "fleet replica mode — bound ports announced "
                        "as a replica_ready line on stdout)")
    p.add_argument("--serve_host", default="127.0.0.1",
                   help="bind address for --port (default loopback)")
    p.add_argument("--replica", action="append", default=[],
                   metavar="HOST:PORT[:HEALTH_PORT]",
                   help="job=route: connect to a running serve --port "
                        "replica (repeatable)")
    p.add_argument("--replicas", type=int, default=2,
                   help="job=route with --model: spawn this many local "
                        "replica processes (runtime.master."
                        "ServingFleet)")
    p.add_argument("--prefill_replicas", type=int, default=0,
                   help="job=route: mark the first K replicas as the "
                        "disaggregated prefill tier (P/D mode; 0 = "
                        "colocated)")
    p.add_argument("--max_in_flight", type=int, default=8,
                   help="job=route: per-replica in-flight cap")
    p.add_argument("--fetch_flops_per_byte", type=float, default=8.0,
                   help="job=route: remote-fetch crossover — ship a "
                        "warm prefix's KV bytes when recomputing them "
                        "costs more than this many FLOPs per byte "
                        "shipped (0 = always fetch, huge = always "
                        "recompute)")
    p.add_argument("--output_path", default=None,
                   help="where job=infer saves outputs (.npz)")
    p.add_argument("--infer_limit", type=int, default=0,
                   help="max samples for job=infer (0 = all)")
    p.add_argument("--log_period", type=int, default=10)
    p.add_argument("--time_batches", type=int, default=20)
    p.add_argument("--warmup_batches", type=int, default=3)
    p.add_argument("--checkgrad_eps", type=float, default=1e-3)
    p.add_argument("--checkgrad_tol", type=float, default=2e-2)
    p.add_argument("--metrics_file", default=None,
                   help="JSONL metrics log to summarize (job=stats)")
    p.add_argument("--last", type=int, default=0,
                   help="also dump the trailing N raw records (job=stats)")
    p.add_argument("--format", choices=["pretty", "prom"], default="pretty",
                   help="registry render format (job=stats)")
    p.add_argument("--metrics_out", default=None,
                   help="write per-step JSONL metrics here (train/time "
                        "jobs; same as PADDLE_TPU_METRICS_PATH)")
    p.add_argument("--trace", default=None,
                   help="export the run's trace-scope spans as Chrome-"
                        "trace JSON to this path when the job finishes "
                        "(job=stats: export the buffer immediately)")
    p.add_argument("--health_port", type=int, default=None,
                   help="serve /metrics + /healthz on this port during "
                        "job=train or job=serve (0 = ephemeral)")
    p.add_argument("--health_host", default="127.0.0.1",
                   help="bind address for --health_port (use 0.0.0.0 "
                        "for out-of-pod probes; default loopback)")
    p.add_argument("--requests", type=int, default=0,
                   help="job=stats: print the N slowest requests of "
                        "this process's request log with attributed "
                        "latency components (0 = off)")
    p.add_argument("--ttft_slo_ms", type=float, default=None,
                   help="job=serve: TTFT SLO in ms — /healthz reports "
                        "degraded when the rolling burn rate exceeds "
                        "the budget (observe.SloConfig)")
    p.add_argument("--slo_target", type=float, default=0.99,
                   help="fraction of requests that must meet the TTFT "
                        "SLO (job=serve; default 0.99)")
    p.add_argument("--slo_window_s", type=float, default=60.0,
                   help="rolling window for SLO evaluation, seconds "
                        "(job=serve)")
    p.add_argument("--url", default=None,
                   help="job=top: the router's observability base URL "
                        "(http://HOST:HEALTH_PORT from route "
                        "--health_port)")
    p.add_argument("--top_interval_s", type=float, default=2.0,
                   help="job=top: refresh interval, seconds")
    p.add_argument("--top_iterations", type=int, default=0,
                   help="job=top: stop after N frames (0 = until "
                        "interrupted; tests use 1)")
    p.add_argument("--supervisor", action="store_true",
                   help="job=top: render the TRAINING-gang view "
                        "(per-rank state/step/step-time/barrier-wait/"
                        "skew + goodput) — point --url at a Supervisor "
                        "http_port endpoint; auto-detected from the "
                        "health document when omitted")
    p.add_argument("--merge", nargs="+", default=None,
                   metavar="TRACE.json",
                   help="job=stats: merge N per-rank Chrome-trace "
                        "exports into ONE aligned gang timeline at "
                        "--trace (clock offsets solved from the "
                        "barrier alignment stamps in each file)")
    p.add_argument("--tenant-budget", "--tenant_budget",
                   action="append", default=[], dest="tenant_budget",
                   metavar="TENANT=TOKENS",
                   help="job=serve: cap TENANT's reserved tokens in "
                        "flight (prompt+max_new of live requests); "
                        "repeatable. Exhaustion queues the tenant's "
                        "requests — it never rejects. Paged-engine "
                        "artifacts only.")
    p.add_argument("--tiers_dram_mb", type=float, default=0.0,
                   help="job=serve: host-DRAM spill tier budget in MB "
                        "(0 disables tiered spill). LRU-evicted prefix "
                        "blocks demote here instead of vanishing; "
                        "admissions that miss HBM re-adopt bitwise.")
    p.add_argument("--tiers_disk_mb", type=float, default=0.0,
                   help="job=serve: disk spill tier budget in MB below "
                        "the DRAM tier (needs --tiers_dir; checksummed "
                        "files, atomic publish, corrupt files served "
                        "as misses)")
    p.add_argument("--tiers_dir", default=None,
                   help="job=serve: directory for the disk spill tier "
                        "(re-adopted across restarts)")
    p.add_argument("--shed_queue_max", type=int, default=0,
                   help="job=route: shed batch-tier admits once the "
                        "router queue holds this many requests "
                        "(latency tier rides 2x the headroom; 0 "
                        "disables — the queue grows unbounded)")
    p.add_argument("--shed_burn_max", type=float, default=0.0,
                   help="job=route: shed batch-tier admits while the "
                        "SLO burn rate exceeds this (needs "
                        "--ttft_slo_ms; 0 disables)")
    p.add_argument("--autoscale", action="store_true",
                   help="job=route: run the fleet controller — heal "
                        "dead replicas under their own name (re-warm "
                        "from survivors), scale up on sustained queue "
                        "pressure, drain down when idle. Needs "
                        "--model + --replicas (a local fleet).")
    p.add_argument("--min_replicas", type=int, default=1,
                   help="job=route --autoscale: scale-down floor")
    p.add_argument("--max_replicas", type=int, default=8,
                   help="job=route --autoscale: scale-up ceiling")
    p.add_argument("--scale_up_queue", type=int, default=8,
                   help="job=route --autoscale: queue depth that, "
                        "sustained past the hysteresis window, spawns "
                        "a replica (0 disables scale-up)")
    p.add_argument("--scale_down_idle_s", type=float, default=30.0,
                   help="job=route --autoscale: drain the newest "
                        "replica after this long fully idle (down to "
                        "--min_replicas)")
    p.add_argument("--wedge_timeout_s", type=float, default=0.0,
                   help="job=route: kill a replica that holds work "
                        "but produces no result/ack/error for this "
                        "long — healing then respawns it (0 disables; "
                        "implies the controller)")
    p.add_argument("--heal_max_restarts", type=int, default=3,
                   help="job=route --autoscale: restart budget per "
                        "replica name before its slot is abandoned "
                        "(a long-stable incarnation refills it)")
    p.add_argument("--controller_port", type=int, default=None,
                   help="job=route --autoscale: serve the "
                        "controller's own /healthz (+ shared "
                        "/metrics) on this port")
    args = p.parse_args(argv)

    if args.metrics_out:
        from paddle_tpu import observe
        observe.configure(args.metrics_out)
    jobs = {"train": job_train, "test": job_test, "time": job_time,
            "checkgrad": job_checkgrad, "infer": job_infer}
    if args.job == "stats":
        return job_stats(None, args)
    if args.job == "serve":
        if not args.model:
            p.error("--model=lm.tar is required for job=serve")
        return job_serve(args)
    if args.job == "route":
        return job_route(args)
    if args.job == "top":
        return job_top(args)
    if not args.config:
        p.error(f"--config is required for job={args.job}")
    cfg = _load_config(args.config)
    try:
        rc = jobs[args.job](cfg, args)
    finally:
        # export even when the job crashes — a timeline of the steps
        # leading up to the failure is the trace most worth having
        if args.trace:
            from paddle_tpu import observe
            observe.trace_export(args.trace)
            print(f"trace written to {args.trace}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
