"""Training events (reference: python/paddle/v2/event.py — BeginPass,
EndPass, BeginIteration, EndIteration, TestResult delivered to the user's
event_handler)."""


class WithMetric:
    def __init__(self, evaluator):
        self.evaluator = evaluator

    @property
    def metrics(self):
        return self.evaluator.result() if self.evaluator else {}


class BeginPass:
    def __init__(self, pass_id):
        self.pass_id = pass_id


class EndPass(WithMetric):
    def __init__(self, pass_id, evaluator=None, gm=None):
        super().__init__(evaluator)
        self.pass_id = pass_id


class BeginIteration:
    def __init__(self, pass_id, batch_id):
        self.pass_id = pass_id
        self.batch_id = batch_id


class EndIteration(WithMetric):
    """End of one trained batch. ``wall_time_s`` / ``examples_per_sec``
    carry the step's observability scalars (None when the trainer didn't
    measure them) — the same numbers observe.report() emits, so existing
    handlers can read them without touching the metrics registry."""

    def __init__(self, pass_id, batch_id, cost, evaluator=None,
                 wall_time_s=None, examples_per_sec=None):
        super().__init__(evaluator)
        self.pass_id = pass_id
        self.batch_id = batch_id
        self.cost = cost
        self.wall_time_s = wall_time_s
        self.examples_per_sec = examples_per_sec


class TestResult(WithMetric):
    def __init__(self, evaluator=None, cost=None):
        super().__init__(evaluator)
        self.cost = cost
