"""Functional kernel layer — jnp/lax compositions (+ Pallas where fusion is
insufficient).

Replaces the reference's four kernel layers with one functional namespace:
- paddle/math/ (Matrix/Vector virtuals, BaseMatrix element-wise engine)
- paddle/cuda/ (hl_* CUDA primitives + CPU stubs)
- paddle/function/ (portable CPU/GPU functor pairs)
- paddle/operators/math/ (new-stack functors)

Everything is a pure function on jax arrays: autodiff comes from jax.grad
(replacing paddle/framework/backward.cc and every hand-written *Grad kernel),
device portability comes from XLA (replacing the CPU/GPU dual implementations
and stub headers), and fusion comes from the compiler (replacing the lazy
tensor-expression templates in paddle/math/TensorExpression.h).
"""

from paddle_tpu.ops import math
from paddle_tpu.ops import activations
from paddle_tpu.ops import conv
from paddle_tpu.ops import pool
from paddle_tpu.ops import norm
from paddle_tpu.ops import loss
from paddle_tpu.ops import sequence
from paddle_tpu.ops import rnn
from paddle_tpu.ops import sparse
from paddle_tpu.ops import topk
from paddle_tpu.ops import crf
from paddle_tpu.ops import ctc

from paddle_tpu.ops.math import matmul, linear
from paddle_tpu.ops.sparse import embedding_lookup
