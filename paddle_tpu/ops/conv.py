"""Convolutions — NHWC, MXU-shaped.

Replaces ExpandConvLayer/GemmConv/DepthwiseConv/cuDNN wrappers (reference:
paddle/gserver/layers/ExpandConvLayer.cpp, paddle/function/GemmConvOp.cpp,
paddle/function/DepthwiseConvOp.cpp, paddle/cuda/src/hl_cuda_cudnn.cc,
paddle/operators/conv_op.cc, conv_cudnn_op.cc, conv_transpose_op.cc).

Layout is NHWC with HWIO filters — TPU-native; XLA tiles the contraction onto
the MXU directly. im2col (paddle/function/Im2ColOp.cpp) is unnecessary: XLA's
conv lowering performs the equivalent internally.
"""

from typing import Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core import dtypes

IntOr2 = Union[int, Tuple[int, int], Sequence[int]]


def _pair(v: IntOr2) -> Tuple[int, int]:
    if isinstance(v, int):
        return (v, v)
    return tuple(v)


def conv2d(x: jax.Array, w: jax.Array, *, stride: IntOr2 = 1,
           padding="SAME", dilation: IntOr2 = 1, groups: int = 1) -> jax.Array:
    """2-D convolution.

    x: [N, H, W, Cin]; w: [kH, kW, Cin//groups, Cout]; padding: "SAME" |
    "VALID" | int | ((ph0,ph1),(pw0,pw1)).
    """
    cdt = dtypes.compute_dtype()
    if isinstance(padding, int):
        p = _pair(padding)
        padding = ((p[0], p[0]), (p[1], p[1]))
    elif isinstance(padding, (tuple, list)) and padding and isinstance(padding[0], int):
        padding = ((padding[0], padding[0]), (padding[1], padding[1]))
    # Both operands in the compute dtype, output in the compute dtype: the MXU
    # accumulates fp32 internally regardless, and a float32
    # preferred_element_type would break the conv VJP transpose rule (the f32
    # cotangent meets a bf16 operand). Activations stay in the compute dtype
    # between ops — upcasting each conv's output to fp32 would double the HBM
    # traffic of every BN/ReLU/residual chain for no accuracy gain (BN stats
    # and master weights are fp32 already).
    return lax.conv_general_dilated(
        x.astype(cdt), w.astype(cdt),
        window_strides=_pair(stride),
        padding=padding,
        rhs_dilation=_pair(dilation),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=groups,
    )


def depthwise_conv2d(x: jax.Array, w: jax.Array, *, stride: IntOr2 = 1,
                     padding="SAME", dilation: IntOr2 = 1) -> jax.Array:
    """Depthwise conv: w is [kH, kW, 1, C*multiplier], groups = Cin
    (reference: paddle/function/DepthwiseConvOp.cpp)."""
    return conv2d(x, w, stride=stride, padding=padding, dilation=dilation,
                  groups=x.shape[-1])


def conv2d_transpose(x: jax.Array, w: jax.Array, *, stride: IntOr2 = 1,
                     padding="SAME") -> jax.Array:
    """Transposed conv (reference: operators/conv_transpose_op.cc)."""
    cdt = dtypes.compute_dtype()
    return lax.conv_transpose(
        x.astype(cdt), w.astype(cdt),
        strides=_pair(stride), padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def space_to_depth(x: jax.Array, block: int = 2) -> jax.Array:
    """[N, H, W, C] -> [N, H/b, W/b, C*b*b] (MLPerf ResNet stem layout:
    trades the lane-starved C=3 input for C=12 and halves the spatial
    grid so the first conv runs stride-1 on MXU-friendly shapes)."""
    n, h, w, c = x.shape
    x = x.reshape(n, h // block, block, w // block, block, c)
    return jnp.transpose(x, (0, 1, 3, 2, 4, 5)).reshape(
        n, h // block, w // block, block * block * c)


def space_to_depth_conv_transform(w: jax.Array, block: int = 2):
    """Transform [kH, kW, Cin, Cout] weights of a stride-``block`` conv
    with padding k//2 into the equivalent stride-1 kernel over
    space-to-depth input. Returns ``(weights, padding)`` — the companion
    explicit padding is part of the derivation, so callers can't drift.

    Derivation: original tap r reads offset e = r − k//2 from the strided
    output origin; writing e = block·j + d places w[r] in s2d kernel cell
    a = j − jmin, channel slot d, with companion padding
    (left −jmin = ceil((k//2)/block), right jmax = (k−1−k//2)//block)."""
    kh, kw, cin, cout = w.shape

    def axis_map(k):
        import numpy as np
        e = np.arange(k) - k // 2
        j = np.floor_divide(e, block)
        return (j - j.min(), e - j * block,
                int(-j.min()), int(j.max()), int(j.max() - j.min() + 1))

    a_h, d_h, pl_h, pr_h, ah = axis_map(kh)
    a_w, d_w, pl_w, pr_w, aw = axis_map(kw)
    ws = jnp.zeros((ah, aw, block, block, cin, cout), w.dtype)
    # one vectorized scatter over all kh*kw taps (tap cells are disjoint)
    ws = ws.at[a_h[:, None], a_w[None, :],
               d_h[:, None], d_w[None, :]].set(w)
    # channel merge order (dy, dx, c) matches space_to_depth's layout
    ws = ws.reshape(ah, aw, block * block * cin, cout)
    return ws, ((pl_h, pr_h), (pl_w, pr_w))
