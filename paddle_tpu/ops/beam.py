"""Fixed-width batched beam search as a lax.while_loop.

Reference: paddle/gserver/gradientmachines/RecurrentGradientMachine.cpp
(generateSequence/beamSearch — per-path dynamic beams on the host, 1,501 LoC)
and the new-stack beam_search_op.cc / beam_search_decode_op.cc; exposed to
users as SWIG SequenceGenerator (paddle/api/PaddleAPI.h:1025).

TPU design: the beam is a static [batch, beam] lattice — every step scores
all beam*vocab continuations with one batched matmul-backed step function,
takes a single top-k, and gathers the recurrent state pytree by parent index.
Finished beams are masked (forced to extend with EOS at zero cost) instead of
being removed, so shapes stay static for XLA. The dynamic per-path pruning
of the reference becomes dense masking — the idiomatic accelerator trade.
"""

from typing import Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30


class BeamState(NamedTuple):
    tokens: jax.Array      # [B, K, T_max] int32, bos-seeded, eos-padded
    scores: jax.Array      # [B, K] cumulative log-prob
    finished: jax.Array    # [B, K] bool
    lengths: jax.Array     # [B, K] int32 generated length (excl. bos)
    state: object          # step-fn recurrent state pytree, leaves [B, K, ...]


def _gather_beams(tree, parent: jax.Array):
    """Gather leaves [B, K, ...] along the beam axis by parent [B, K]."""
    def g(x):
        return jnp.take_along_axis(
            x, parent.reshape(parent.shape + (1,) * (x.ndim - 2)), axis=1)
    return jax.tree_util.tree_map(g, tree)


def beam_search(step_fn: Callable, init_state, batch: int, beam_size: int,
                vocab: int, bos_id: int, eos_id: int, max_len: int,
                length_penalty: float = 0.0) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Run beam search.

    step_fn(tokens_last [B, K] int32, state) -> (logp [B, K, V], new_state);
    init_state leaves must be [B, K, ...] (tile the encoder context over K).
    Returns (tokens [B, K, max_len], lengths [B, K], scores [B, K]) sorted
    best-first, eos included in the length.
    """
    K, V = beam_size, vocab
    tokens0 = jnp.full((batch, K, max_len + 1), eos_id, jnp.int32)
    tokens0 = tokens0.at[:, :, 0].set(bos_id)
    # only beam 0 live at t=0 so identical bos paths aren't duplicated
    scores0 = jnp.where(jnp.arange(K)[None, :] == 0, 0.0, NEG_INF)
    scores0 = jnp.broadcast_to(scores0, (batch, K)).astype(jnp.float32)
    st = BeamState(tokens0, scores0, jnp.zeros((batch, K), bool),
                   jnp.zeros((batch, K), jnp.int32), init_state)

    def cond(carry):
        t, st = carry
        return (t < max_len) & ~jnp.all(st.finished)

    def body(carry):
        t, st = carry
        last = jax.lax.dynamic_slice_in_dim(st.tokens, t, 1, axis=2)[:, :, 0]
        logp, new_state = step_fn(last, st.state)
        logp = logp.astype(jnp.float32)
        # finished beams may only "extend" with eos at zero cost
        eos_only = jnp.full((V,), NEG_INF).at[eos_id].set(0.0)
        logp = jnp.where(st.finished[:, :, None], eos_only[None, None, :], logp)
        total = st.scores[:, :, None] + logp                  # [B, K, V]
        flat = total.reshape(batch, K * V)
        top_scores, top_idx = jax.lax.top_k(flat, K)          # [B, K]
        parent = (top_idx // V).astype(jnp.int32)
        tok = (top_idx % V).astype(jnp.int32)

        tokens = _gather_beams(st.tokens, parent)
        tokens = jax.lax.dynamic_update_slice_in_dim(
            tokens, tok[:, :, None], t + 1, axis=2)
        was_finished = jnp.take_along_axis(st.finished, parent, axis=1)
        lengths = jnp.take_along_axis(st.lengths, parent, axis=1)
        lengths = jnp.where(was_finished, lengths, lengths + 1)
        finished = was_finished | (tok == eos_id)
        state = _gather_beams(new_state, parent)
        return t + 1, BeamState(tokens, top_scores, finished, lengths, state)

    _, st = jax.lax.while_loop(cond, body, (0, st))

    final = st.scores
    if length_penalty > 0.0:
        final = final / (st.lengths.astype(jnp.float32) ** length_penalty)
    order = jnp.argsort(-final, axis=1)
    tokens = jnp.take_along_axis(st.tokens[:, :, 1:],
                                 order[:, :, None], axis=1)
    return tokens, jnp.take_along_axis(st.lengths, order, axis=1), \
        jnp.take_along_axis(final, order, axis=1)


def cross_entropy_over_beam(step_scores: jax.Array, parents: jax.Array,
                            gold_scores: jax.Array, gold_slot: jax.Array,
                            valid_mask: jax.Array = None) -> jax.Array:
    """Globally-normalized beam-training loss, fixed-width.

    The TPU-native form of the reference's cross_entropy_over_beam
    (paddle/gserver/layers/CrossEntropyOverBeam.cpp:158-162 forward,
    globallyNormalizedScore): every complete path in the final beam gets a
    total score — the sum of its selected candidates' scores along its
    ancestry chain — a softmax normalizes over all paths, and the loss is
    −log p(gold). When the gold sequence fell off the beam during search
    its independently-scored path joins as one extra softmax slot
    (CrossEntropyOverBeam.cpp:57-59 goldAsExtraPath). The reference walks
    dynamic -1-terminated candidate lists on the host; here the beam is
    the static [B, S, K] lattice of ops/beam.py and dropped slots are
    masked, so the whole objective (and its gradient) is one jit-able
    expression.

    Args:
      step_scores: [B, S, K] score of the candidate occupying beam slot k
        at expansion step s (model outputs — differentiated through).
      parents: [B, S, K] int32 — the slot at step s-1 each candidate
        extends (step 0 entries ignored).
      gold_scores: [B, S] per-step scores of the gold prefix
        (differentiated through; used when the gold path left the beam).
      gold_slot: [B] int32 — the gold path's slot in the FINAL beam, or
        -1 if it fell off the beam.
      valid_mask: optional [B, K] bool — final slots holding real paths
        (default: all valid).
    Returns: [B] per-sequence loss.
    """
    B, S, K = step_scores.shape
    f32 = jnp.float32

    def accumulate(carry, xs):
        sc, par = xs                                     # [B, K] each
        carry = sc.astype(f32) + jnp.take_along_axis(carry, par, axis=1)
        return carry, None

    # step 0 has no parent: seed with zeros and fold step 0's scores in
    # via a parent gather against a zero carry (any parent index works)
    path, _ = jax.lax.scan(
        accumulate, jnp.zeros((B, K), f32),
        (jnp.moveaxis(step_scores, 1, 0), jnp.moveaxis(parents, 1, 0)))
    if valid_mask is not None:
        path = jnp.where(valid_mask, path, NEG_INF)
    gold_total = jnp.sum(gold_scores.astype(f32), axis=1)     # [B]
    in_beam = gold_slot >= 0                                  # [B]
    # softmax slots: K beam paths + 1 extra that only exists (finite)
    # when the gold path fell off the beam
    extra = jnp.where(in_beam, NEG_INF, gold_total)           # [B]
    logits = jnp.concatenate([path, extra[:, None]], axis=1)  # [B, K+1]
    slot = jnp.where(in_beam, jnp.maximum(gold_slot, 0), K)
    target = jnp.take_along_axis(logits, slot[:, None], axis=1)[:, 0]
    return jax.nn.logsumexp(logits, axis=1) - target


def greedy_search(step_fn: Callable, init_state, batch: int, vocab: int,
                  bos_id: int, eos_id: int, max_len: int):
    """Greedy decode = beam_size 1 (reference: generateSequence with
    beam_size=1 takes the argmax path)."""
    tok, lens, sc = beam_search(step_fn, init_state, batch, 1, vocab,
                                bos_id, eos_id, max_len)
    return tok[:, 0], lens[:, 0], sc[:, 0]
