"""Normalisation ops.

Replaces BatchNormalizationLayer / CudnnBatchNormLayer (reference:
paddle/gserver/layers/BatchNormalizationLayer.cpp, CudnnBatchNormLayer.cpp,
paddle/operators/batch_norm_op.cc) and cross-map response normalisation
(paddle/function/CrossMapNormalOp.cpp, gserver/layers/NormLayer.cpp).

batch_norm returns (y, new_running_mean, new_running_var) in training mode so
running stats thread functionally through the train step — the reference
mutated movingMean/movingVar buffers in place; here they are explicit state.
"""

import functools
from typing import Tuple

import jax
import jax.numpy as jnp


def _bn_stats(x, axes, eps):
    mean = jnp.mean(x, axis=axes, dtype=jnp.float32)
    mean2 = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=axes)
    # fp32 cancellation can push E[x^2]-E[x]^2 slightly negative when the
    # mean dwarfs the spread; rsqrt would then emit NaN
    var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
    inv = jax.lax.rsqrt(var + eps)
    return mean, var, inv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _bn_apply(x, gamma, beta, axes, eps):
    """Normalise-and-affine with a hand-fused backward.

    Autodiff of the two-reduction forward reads x on several distinct
    backward paths (through mean, through mean², through the elementwise
    product); the closed-form VJP needs exactly TWO passes over the big
    tensors — one fused reduction pass (Σdy, Σdy·x̂, recomputing x̂ from x
    in-register) and one elementwise pass writing dx:

        dx = s/N · (N·dy − Σdy − x̂·Σ(dy·x̂)),  s = γ·inv

    (the batch_norm_grad identity; reference slot:
    operators/batch_norm_op.cc backward kernels)."""
    return _bn_apply_fwd(x, gamma, beta, axes, eps)[0]


def _bn_apply_fwd(x, gamma, beta, axes, eps):
    mean, var, inv = _bn_stats(x, axes, eps)
    g32 = gamma.astype(jnp.float32)
    scale = (g32 * inv).astype(x.dtype)
    shift = (beta.astype(jnp.float32) - mean * g32 * inv).astype(x.dtype)
    return x * scale + shift, (x, mean, inv, gamma, beta)


def _bn_apply_bwd(axes, eps, res, dy):
    x, mean, inv, gamma, beta = res
    n = 1
    for a in axes:
        n *= x.shape[a]
    dyf = dy.astype(jnp.float32)
    # fused reduction pass: x̂ recomputed in-register from x
    sum_dy = jnp.sum(dyf, axis=axes)
    xhat = (x.astype(jnp.float32) - mean) * inv
    sum_dy_xhat = jnp.sum(dyf * xhat, axis=axes)
    # elementwise pass
    s = gamma.astype(jnp.float32) * inv / n
    dx = (s * (n * dyf - sum_dy - xhat * sum_dy_xhat)).astype(x.dtype)
    return (dx, sum_dy_xhat.astype(jnp.asarray(gamma).dtype),
            sum_dy.astype(jnp.asarray(beta).dtype))


_bn_apply.defvjp(_bn_apply_fwd, _bn_apply_bwd)


def batch_norm_train(x, gamma, beta, running_mean, running_var, *,
                     momentum=0.9, eps=1e-5, axes=None
                     ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Training-mode BN over all axes except the last (channel).

    HBM-traffic shape: the stats are reduced in fp32 (the dtype cast fuses
    into the reduction — no fp32 copy of the activation is materialised),
    the normalisation is applied as a per-channel affine in x's dtype, and
    the backward is the hand-fused closed form (see _bn_apply) — bf16
    activations are read/written the minimum number of times. An earlier
    version upcast the whole tensor to fp32 first; on a v5e that one
    change was worth ~13% of ResNet-50 step time (the step is HBM-bound)."""
    axes = tuple(axes) if axes is not None else tuple(range(x.ndim - 1))
    y = _bn_apply(x, gamma, beta, axes, eps)
    # running stats (no gradient flows here; stop_gradient keeps autodiff
    # from building a second stats backward)
    xs = jax.lax.stop_gradient(x)
    mean, var, _ = _bn_stats(xs, axes, eps)
    new_mean = momentum * running_mean + (1 - momentum) * mean
    new_var = momentum * running_var + (1 - momentum) * var
    return y, new_mean.astype(running_mean.dtype), \
        new_var.astype(running_var.dtype)


def batch_norm_infer(x, gamma, beta, running_mean, running_var, *, eps=1e-5):
    inv = jax.lax.rsqrt(running_var.astype(jnp.float32) + eps)
    g32 = gamma.astype(jnp.float32)
    scale = (g32 * inv).astype(x.dtype)
    shift = (beta.astype(jnp.float32) -
             running_mean.astype(jnp.float32) * g32 * inv).astype(x.dtype)
    return x * scale + shift


def layer_norm(x, gamma, beta, *, eps=1e-5, axis=-1):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axis, keepdims=True)
    var = jnp.var(xf, axis=axis, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps) * gamma + beta
    return y.astype(x.dtype)


def rms_norm(x, gamma, *, eps=1e-6, axis=-1):
    xf = x.astype(jnp.float32)
    ms = jnp.mean(xf * xf, axis=axis, keepdims=True)
    return (xf * jax.lax.rsqrt(ms + eps) * gamma).astype(x.dtype)


def lrn(x, *, size=5, alpha=1e-4, beta=0.75, k=1.0):
    """Cross-map (channel) local response normalisation, NHWC.
    (reference: paddle/function/CrossMapNormalOp.cpp — same formula as
    AlexNet's LRN: y = x / (k + alpha * sum_local(x^2))^beta)."""
    sq = jnp.square(x.astype(jnp.float32))
    # sum over a window of `size` channels centered at each channel
    half = size // 2
    padded = jnp.pad(sq, [(0, 0)] * (x.ndim - 1) + [(half, size - 1 - half)])
    # cumulative-sum trick over channel windows
    csum = jnp.cumsum(padded, axis=-1)
    zeros = jnp.zeros_like(csum[..., :1])
    csum = jnp.concatenate([zeros, csum], axis=-1)
    local = csum[..., size:] - csum[..., :-size]
    y = x.astype(jnp.float32) / jnp.power(k + alpha * local, beta)
    return y.astype(x.dtype)
