"""Fused conv + batch-norm training op — XLA-level composition.

Capability slot of the reference's fused CudnnBatchNormLayer
(paddle/gserver/layers/CudnnBatchNormLayer.cpp) and its hand-fused conv
epilogues (paddle/cuda/src/hl_cuda_cnn.cu): one op produces the conv
output AND consumes its batch statistics, with a closed-form two-pass
batch-norm VJP and XLA's own conv VJP for the convolution backward.

Everything here is expressed at the XLA level on purpose. Round 3 built
Pallas streaming-stats conv kernels (1x1-as-GEMM and 3x3-as-shifted-GEMM
with in-register Σ/Σ² epilogues, plus fused backward kernels); the
round-4 on-chip A/B measured them at 0.43-0.59x of this plain-XLA
composition (1490.8/1264.7/1093.1 vs 2543.6 img/s on ResNet-50,
benchmarks/runs/2026-07-31_0136_*). The trace showed why: an opaque
custom-call blocks XLA's free epilogue fusions on both neighbours, and
the NHWC→[M,C] reshapes cost copies (190 vs 710 GB/s effective kernel
bandwidth). The kernels were deleted in round 5; the winning levers that
absorb MORE of the layer at the XLA level live in ops/q8.py (the
defer/q8/q8sr stash recipes). This module keeps the XLA-level wins:

- single fused forward: XLA fuses the Σ/Σ² reductions into the conv
  consumer chain and the normalize is a per-channel affine;
- closed-form BN backward (no autodiff through the stats), two passes;
- ``save8``: backward's saved activations (x, centered y) stashed as
  per-channel int8 — halves their backward read traffic and residual
  memory for ~0.4% stash rounding noise (forward values untouched).
"""

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def conv_bn_stats(x, w, *, stride=1, padding="SAME"):
    """(conv(x, w), Σy, Σy²) — sums per output channel over N·H·W.

    The reductions sit right after the conv in one XLA fusion group; no
    separate stats pass over the activation survives optimization."""
    from paddle_tpu.core import dtypes
    from paddle_tpu.ops import conv as ops_conv

    # honor the global MXU compute-dtype policy exactly like
    # ops_conv.conv2d does — fused and unfused paths must emit the SAME
    # dtype or the custom-VJP cotangents mismatch downstream
    cdt = dtypes.compute_dtype()
    y = ops_conv.conv2d(x.astype(cdt), w.astype(cdt), stride=stride,
                        padding=padding)
    yf = y.astype(jnp.float32)
    axes = tuple(range(y.ndim - 1))
    return y, jnp.sum(yf, axis=axes), jnp.sum(yf * yf, axis=axes)


def _quant8(t):
    """Per-channel symmetric int8 quantization of a saved activation:
    halves the backward's read traffic for that residual (bf16 2B →
    int8 1B) at the cost of an extra int8 write in forward — net ~0.5
    byte/element saved, plus halved residual memory. ~0.4% relative
    rounding noise on the stashed tensor (127 levels), applied only to
    backward READS of saved activations, never the forward values."""
    tf = t.astype(jnp.float32)
    amax = jnp.max(jnp.abs(tf), axis=tuple(range(t.ndim - 1)))
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(tf / scale), -127, 127).astype(jnp.int8)
    return q, scale


def _dequant8(q, scale, dtype):
    return (q.astype(jnp.float32) * scale).astype(dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _conv_bn(x, w, gamma, beta, stride, padding, eps, save8):
    return _conv_bn_fwd(x, w, gamma, beta, stride, padding, eps, save8)[0]


def _conv_bn_fwd(x, w, gamma, beta, stride, padding, eps, save8):
    y, s1, s2 = conv_bn_stats(x, w, stride=stride, padding=padding)
    count = y.size // y.shape[-1]
    mean = s1 / count
    var = jnp.maximum(s2 / count - jnp.square(mean), 0.0)
    inv = lax.rsqrt(var + eps)
    g32 = gamma.astype(jnp.float32)
    scale = (g32 * inv).astype(y.dtype)
    shift = (beta.astype(jnp.float32) - mean * g32 * inv).astype(y.dtype)
    out = y * scale + shift
    if save8:
        # x: zero-size dtype token — residual pytrees may hold only JAX
        # values, and bwd must rebuild x in its ORIGINAL dtype so the
        # returned cotangent matches the primal.
        stash_x = (_quant8(x), jnp.zeros((0,), x.dtype))
        # y: quantize the CENTERED conv output (y - mean), not raw y —
        # the backward only ever consumes ŷ = (y - mean)·inv, and for a
        # channel whose |mean| dwarfs its std (exactly what BN fixes)
        # raw-y quantization noise amplified by inv would corrupt dγ/dx;
        # centering bounds the stash noise at ~range/254 in ŷ units
        # regardless of channel statistics.
        stash_y = _quant8(y.astype(jnp.float32) - mean)
    else:
        stash_x = stash_y = None
    # mean/var feed running stats only — gradient-stopped by construction
    # (the VJP ignores their cotangents)
    return ((out, lax.stop_gradient(mean), lax.stop_gradient(var)),
            (None if save8 else x, None if save8 else y, stash_x, stash_y,
             w, mean, inv, gamma))


def _conv_bn_bwd(stride, padding, eps, save8, res, cts):
    from paddle_tpu.ops import conv as ops_conv

    x, y, stash_x, stash_y, w, mean, inv, gamma = res
    if save8:
        (qx, sx), xtok = stash_x
        qz, sz = stash_y
        # the f32 view fuses into the reductions below (no materialized
        # dequant copy)
        centered = qz.astype(jnp.float32) * sz     # = y - mean (stashed)
        x_full = _dequant8(qx, sx, xtok.dtype)
        x_dt = xtok.dtype
    else:
        centered = y.astype(jnp.float32) - mean
        x_full = x
        x_dt = x.dtype
    dout = cts[0].astype(jnp.float32)
    n = centered.size // centered.shape[-1]
    axes = tuple(range(centered.ndim - 1))
    # the cotangent w.r.t. the conv output is EXACTLY the batch-norm dx
    # identity (ops/norm.py _bn_apply_bwd with x := y): two passes —
    # one fused reduction (Σdy, Σdy·ŷ) and the elementwise g stage
    sum_dy = jnp.sum(dout, axis=axes)
    yhat = centered * inv
    sum_dy_yhat = jnp.sum(dout * yhat, axis=axes)
    sc = gamma.astype(jnp.float32) * inv / n
    g = (sc * (n * dout - sum_dy - yhat * sum_dy_yhat)).astype(
        cts[0].dtype)
    # delegate the conv backward to XLA's conv VJP (its MXU conv
    # backward is already optimal — the fused win is forward-traffic)
    _, conv_vjp = jax.vjp(
        lambda x_, w_: ops_conv.conv2d(x_, w_, stride=stride,
                                       padding=padding), x_full, w)
    dx, dw = conv_vjp(g)
    return (dx.astype(x_dt), dw.astype(w.dtype),
            sum_dy_yhat.astype(gamma.dtype), sum_dy.astype(gamma.dtype))


_conv_bn.defvjp(_conv_bn_fwd, _conv_bn_bwd)


def conv_bn_train(x, w, gamma, beta, running_mean, running_var, *,
                  stride=1, padding="SAME", momentum=0.9, eps=1e-5,
                  save8: bool = False
                  ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fused conv→BN training step: the conv output's batch statistics
    are consumed in the same fusion group, the normalize is a
    per-channel affine, and the backward is the closed-form two-pass BN
    VJP + XLA's conv VJP. ``save8`` stashes the backward's saved
    activations (x, centered y) as per-channel int8.
    Returns (out, new_running_mean, new_running_var)."""
    out, mean, var = _conv_bn(x, w, gamma, beta, stride, padding, eps,
                              save8)
    new_mean = momentum * running_mean + (1 - momentum) * mean
    new_var = momentum * running_var + (1 - momentum) * var
    return (out, new_mean.astype(running_mean.dtype),
            new_var.astype(running_var.dtype))


def conv_bn_infer(x, w, gamma, beta, running_mean, running_var, *,
                  stride=1, padding="SAME", eps=1e-5):
    """Inference path: plain conv + folded-affine BN (no stats needed)."""
    from paddle_tpu.ops import conv as ops_conv
    from paddle_tpu.ops import norm as ops_norm

    y = ops_conv.conv2d(x, w, stride=stride, padding=padding)
    return ops_norm.batch_norm_infer(y, gamma, beta, running_mean,
                                     running_var, eps=eps)
