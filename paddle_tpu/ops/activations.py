"""Activation functions.

Replaces the ActivationFunction registry (reference:
paddle/gserver/activations/ActivationFunction.cpp — sigmoid, softmax, relu,
brelu, tanh, stanh, linear, exponential, softrelu, abs, square, log,
sequence_softmax) and paddle/cuda hl_activation kernels. All are elementwise
jnp — XLA fuses them into adjacent matmuls/convs, which is exactly what the
hand-fused hl_* kernels were for.
"""

import jax
import jax.numpy as jnp

linear = lambda x: x
relu = jax.nn.relu
sigmoid = jax.nn.sigmoid
tanh = jnp.tanh
exponential = jnp.exp
softrelu = jax.nn.softplus  # log(1+e^x), clipped internally
square = lambda x: x * x
abs_ = jnp.abs
log = jnp.log
gelu = jax.nn.gelu
silu = jax.nn.silu


def brelu(x, t_min=0.0, t_max=24.0):
    """Bounded relu (reference: BReluActivation)."""
    return jnp.clip(x, t_min, t_max)


def stanh(x, scale_a=2.0 / 3.0, scale_b=1.7159):
    """Scaled tanh (reference: STanhActivation)."""
    return scale_b * jnp.tanh(scale_a * x)


def softmax(x, axis=-1):
    return jax.nn.softmax(x, axis=axis)


def log_softmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


_REGISTRY = {
    "linear": linear, "relu": relu, "sigmoid": sigmoid, "tanh": tanh,
    "exponential": exponential, "softrelu": softrelu, "square": square,
    "abs": abs_, "log": log, "brelu": brelu, "stanh": stanh,
    "softmax": softmax, "gelu": gelu, "silu": silu,
}


def get(name: str):
    """ActivationFunction::create equivalent."""
    if name not in _REGISTRY:
        raise KeyError(f"unknown activation {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[name]
