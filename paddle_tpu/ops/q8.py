"""q8 training pipeline — activations live in HBM only as centered int8.

The recipe that clears the ResNet north star (BENCHMARKS.md "Path to
4000"): every activation tensor between conv/BN blocks is stored as
centered int8 under *delayed scaling* (the previous step's per-channel
absmax and mean are this step's quantization constants, so the quantize
is purely elementwise and rides inside XLA's conv output fusion — no
second pass over the conv output exists). The consumer dequantizes,
applies the producer's deferred BN affine + activation, all inside its
own conv *input* fusion. Nothing bf16-sized is ever materialized between
blocks in either direction.

Round-4's measured lesson drives the form: hand-written Pallas conv
kernels lose to XLA's conv fusions (190 vs 710 GB/s, BENCHMARKS.md
"streaming-BN A/B"), so this recipe is expressed entirely at the XLA
level — `lax.conv_general_dilated` plus elementwise chains the compiler
provably fuses — and controls only what autodiff *saves*.

Mechanics — the (stash, carrier) pair
-------------------------------------
Blocks exchange TWO values per boundary:

- ``q``     int8 [N,H,W,C] — the data path. Consumers read it directly
            in their prologue fusion; backward re-reads it to recompute.
- ``yhat``  bf16 [N,H,W,C] — a *ghost carrier*: the dequantized value
            ``q * s_p + mu_p`` as a traced expression. Forward compute
            never uses it (XLA DCEs it), but it is the differentiable
            edge through which cotangents flow producer-ward. This
            sidesteps JAX's rule that integer inputs carry no tangents,
            without trusting XLA to duplicate a shared dequant chain
            into every consumer.

Cotangent convention: a carrier's cotangent is w.r.t. the DEQUANTIZED
value ŷ ≈ y (the producer's raw conv output), so the producer's backward
uses it directly as dy. Deferred affines (M, B) are therefore expressed
on the ŷ basis — ``x = act(ŷ·M + B)`` with ``M = rsqrt(var+eps)·γ`` —
and each block folds its input stash constants (mu_pi, s_pi) internally.

Each block is one `jax.custom_vjp` whose residuals are exactly the int8
stashes plus O(C) vectors — the backward recomputes the bf16 operands
in-register from the stash (straight-through estimator through the
round; BN batch-stat terms are exact).

Capability slot of the reference's fused cuDNN batch-norm + activation
epilogues (paddle/gserver/layers/CudnnBatchNormLayer.cpp:21,
paddle/cuda/src/hl_cuda_cnn.cu) pushed to its TPU endpoint: the modelled
37.9 GB/step at batch 256 vs 74.9 measured unfused
(benchmarks/traffic_model.py scenario "q8-pipeline").
"""

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from paddle_tpu.core import dtypes
from paddle_tpu.ops import conv as ops_conv

QMAX = 112.0  # quantization target for the delayed absmax: ~12% headroom
              # before the int8 clip saturates on a growing activation


def scale_from_amax(amax: jax.Array) -> jax.Array:
    """Next step's per-channel scale from this step's absmax."""
    return jnp.maximum(amax, 1e-6) / QMAX


_STASHES = ("int8", "bf16")


def _check_stash(stash: str, stochastic: bool = False) -> None:
    if stash not in _STASHES:
        raise ValueError(f"unknown stash dtype {stash!r}; one of {_STASHES}")
    if stochastic and stash != "int8":
        raise ValueError(
            "stochastic rounding applies to the int8 stash only (a bf16 "
            "stash casts, it does not round to a grid)")


def _quantize(z: jax.Array, stash: str = "int8",
              key: "jax.Array" = None) -> jax.Array:
    if stash == "bf16":
        # the "defer" recipe: same deferred-BN/activation machinery and
        # residual discipline, but a bf16 stash — bf16-rounding noise only (~0.4% rel),
        # 2 bytes/elt instead of 1 (BENCHMARKS.md "affine-prologue block
        # remat", modelled 48.5 GB/step)
        return z.astype(jnp.bfloat16)
    if key is not None:
        # stochastic rounding: floor(z + U[0,1)) is an UNBIASED rounding
        # — E[q] == z — which removes the systematic component of the
        # stash noise the parameters would otherwise co-adapt to (the
        # 200-step q8 eval gap, BENCHMARKS.md). The uniform draw is
        # generated inside the fusion (no HBM tensor).
        u = jax.random.uniform(key, z.shape, jnp.float32)
        return jnp.clip(jnp.floor(z + u), -127.0, 127.0).astype(jnp.int8)
    return jnp.clip(jnp.round(z), -127.0, 127.0).astype(jnp.int8)


def _dequant(q: jax.Array, mu_p: jax.Array, s_p: jax.Array) -> jax.Array:
    return q.astype(jnp.float32) * s_p + mu_p


def _red(x, like):
    """Sum a [N,H,W,C] f32 tensor to per-channel, matching `like`'s dtype."""
    return jnp.sum(x, axis=(0, 1, 2)).astype(like.dtype)


def _int_zero(q):
    """Cotangent for an integer primal input (JAX's float0 convention)."""
    return np.zeros(q.shape, dtype=jax.dtypes.float0)


def _stash_zero(q):
    """Zero cotangent matching the stash dtype: float0 for int8 stashes,
    a real zero array for bf16 ("defer") stashes."""
    if jnp.issubdtype(q.dtype, jnp.integer):
        return _int_zero(q)
    return jnp.zeros_like(q)


def _stash(yf, mu_po, s_po, stash: str = "int8", key=None):
    """Center+quantize with the delayed constants; emit stash, carrier,
    and the absmax that becomes next step's scale."""
    amax = jnp.max(jnp.abs(yf - mu_po), axis=(0, 1, 2))
    q = _quantize((yf - mu_po) / s_po, stash, key)
    yhat = _dequant(q, mu_po, s_po).astype(dtypes.compute_dtype())
    return yhat, q, amax


# ---------------------------------------------------------------------------
# entry: dense bf16 -> (q, carrier)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def make_entry(stash: str = "int8", stochastic: bool = False):
    """Entry stash; with ``stochastic`` the signature gains a trailing
    PRNG key (raw uint32) and rounding is unbiased."""
    _check_stash(stash, stochastic)

    @jax.custom_vjp
    def entry_stash(x, mu_p, s_p, *key):
        xf = x.astype(jnp.float32)
        yhat, q, amax = _stash(xf, mu_p, s_p, stash,
                               key[0] if stochastic else None)
        mu = jnp.mean(xf, axis=(0, 1, 2))
        return yhat, q, mu, amax

    def fwd(x, mu_p, s_p, *key):
        return entry_stash(x, mu_p, s_p, *key), (mu_p, s_p, key)

    def bwd(res, cots):
        mu_p, s_p, key = res
        g_yhat, g_mu = cots[0], cots[2]
        # straight-through: ŷ ≈ x, the carrier's cotangent IS the input's;
        # plus the mu output's term d(mean(x))/dx = 1/nhw (today's
        # consumers fold mu with fold_identity and never differentiate
        # it, so g_mu is zeros — but a future consumer that does gets
        # correct gradients instead of silently dropped ones). The amax
        # output is next-step scale STATE, non-differentiated by design
        # (like BN running stats).
        nhw = g_yhat.size // g_yhat.shape[-1]
        g = g_yhat.astype(jnp.float32) + g_mu.astype(jnp.float32) / nhw
        return (g.astype(dtypes.compute_dtype()),
                jnp.zeros_like(mu_p), jnp.zeros_like(s_p),
                *[_int_zero(k) for k in key])

    entry_stash.defvjp(fwd, bwd)
    return entry_stash


def entry_stash(x, mu_p, s_p):
    """Backward-compatible int8 entry (see make_entry)."""
    return make_entry("int8")(x, mu_p, s_p)


# ---------------------------------------------------------------------------
# exit: (q, carrier) -> dense bf16
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def make_exit(relu: bool):
    """Dequantize out of the pipeline: x = act(ŷ·M + B), reading the int8
    stash; backward needs only the stash."""

    @jax.custom_vjp
    def exit_deq(yhat, q, M, B, mu_p, s_p):
        x = _dequant(q, mu_p, s_p) * M + B
        if relu:
            x = jnp.maximum(x, 0.0)
        return x.astype(dtypes.compute_dtype())

    def fwd(yhat, q, M, B, mu_p, s_p):
        return exit_deq(yhat, q, M, B, mu_p, s_p), (q, M, B, mu_p, s_p)

    def bwd(res, g):
        q, M, B, mu_p, s_p = res
        yd = _dequant(q, mu_p, s_p)
        gf = g.astype(jnp.float32)
        if relu:
            gf = gf * (yd * M + B > 0)
        return ((gf * M).astype(dtypes.compute_dtype()), _stash_zero(q),
                _red(gf * yd, M), _red(gf, B),
                jnp.zeros_like(mu_p), jnp.zeros_like(s_p))

    exit_deq.defvjp(fwd, bwd)
    return exit_deq


# ---------------------------------------------------------------------------
# the conv block: prologue(dequant+affine+act) -> conv -> stats+quantize
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def make_conv_q8(stride: int, padding, relu_in: bool,
                 stash: str = "int8", stochastic: bool = False):
    """Build the custom-vjp conv block for a static (stride, padding,
    input-activation) configuration.

    Signature of the returned fn:
      (yhat_in, q_in, w, M, B, mu_pi, s_pi, mu_po, s_po)
        -> (yhat_out, q_out, mu, var, amax)

    yhat_in: ghost carrier of the producer (gradient edge, DCE'd fwd).
    q_in:    int8 stash — the real data path.
    M, B:    per-channel prologue affine ON THE ŷ BASIS folding the
             producer's deferred BN: x = act(ŷ·M + B). Differentiable
             (grads reach the producer's gamma/beta through them).
    mu_pi/s_pi: the INPUT stash's delayed center/scale (state, stop-grad).
    mu_po/s_po: ditto for the output stash.
    mu/var:  this conv's batch stats over its raw output y — the consumer
             folds them into ITS (M, B); their cotangents carry the exact
             BN batch-stat backward terms here.
    """
    _check_stash(stash, stochastic)

    def prologue(q_in, M, B, mu_pi, s_pi):
        x = _dequant(q_in, mu_pi, s_pi) * M + B
        if relu_in:
            x = jnp.maximum(x, 0.0)
        return x.astype(dtypes.compute_dtype())

    def conv(xt, w):
        return ops_conv.conv2d(xt, w, stride=stride, padding=padding)

    @jax.custom_vjp
    def block(yhat_in, q_in, w, M, B, mu_pi, s_pi, mu_po, s_po, *key):
        xt = prologue(q_in, M, B, mu_pi, s_pi)
        y = conv(xt, w)
        yf = y.astype(jnp.float32)
        mu = jnp.mean(yf, axis=(0, 1, 2))
        var = jnp.mean(jnp.square(yf - mu), axis=(0, 1, 2))
        yhat_out, q_out, amax = _stash(yf, mu_po, s_po, stash,
                                       key[0] if stochastic else None)
        return yhat_out, q_out, mu, var, amax

    def fwd(yhat_in, q_in, w, M, B, mu_pi, s_pi, mu_po, s_po, *key):
        out = block(yhat_in, q_in, w, M, B, mu_pi, s_pi, mu_po, s_po,
                    *key)
        q_out, mu = out[1], out[2]
        return out, (q_in, q_out, mu, w, M, B, mu_pi, s_pi, mu_po, s_po,
                     key)

    def bwd(res, cots):
        (q_in, q_out, mu, w, M, B, mu_pi, s_pi, mu_po, s_po, key) = res
        g_yhat, _gq, g_mu, g_var, _ga = cots
        # y reconstructed from its own stash (STE through the round)
        yf = _dequant(q_out, mu_po, s_po)
        nhw = float(np.prod(g_yhat.shape[:3]))
        dy = (g_yhat.astype(jnp.float32)
              + g_mu / nhw
              + g_var * 2.0 * (yf - mu) / nhw)
        dyb = dy.astype(dtypes.compute_dtype())
        xt = prologue(q_in, M, B, mu_pi, s_pi)
        _, conv_vjp = jax.vjp(conv, xt, w)
        dxt, dw = conv_vjp(dyb)
        dpre = dxt.astype(jnp.float32)
        yd_in = _dequant(q_in, mu_pi, s_pi)
        if relu_in:
            dpre = dpre * (yd_in * M + B > 0)
        d_yhat_in = (dpre * M).astype(dtypes.compute_dtype())
        dM = _red(dpre * yd_in, M)
        dB = _red(dpre, B)
        return (d_yhat_in, _stash_zero(q_in), dw, dM, dB,
                jnp.zeros_like(mu_pi), jnp.zeros_like(s_pi),
                jnp.zeros_like(mu_po), jnp.zeros_like(s_po),
                *[_int_zero(k) for k in key])

    block.defvjp(fwd, bwd)
    return block


# ---------------------------------------------------------------------------
# residual add: affine both branches, add, stash pre-ReLU
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def make_add_q8(relu_a: bool, relu_b: bool, stash: str = "int8",
                stochastic: bool = False):
    """Residual-add block. Branch values come in as stashes with their
    deferred ŷ-basis affines (Ma,Ba / Mb,Bb) and optional deferred ReLUs;
    the sum is stashed CENTERED PRE-ReLU (consumers defer the output
    ReLU), so the int8 range isn't halved on the non-negative side.

      (ya, qa, Ma, Ba, mu_pa, s_pa,
       yb, qb, Mb, Bb, mu_pb, s_pb, mu_po, s_po)
        -> (yhat_out, q_out, mu, amax)
    """
    _check_stash(stash, stochastic)

    def branch(q, M, B, mu_p, s_p, relu):
        v = _dequant(q, mu_p, s_p) * M + B
        if relu:
            v = jnp.maximum(v, 0.0)
        return v

    @jax.custom_vjp
    def block(ya, qa, Ma, Ba, mu_pa, s_pa,
              yb, qb, Mb, Bb, mu_pb, s_pb, mu_po, s_po, *key):
        z = (branch(qa, Ma, Ba, mu_pa, s_pa, relu_a)
             + branch(qb, Mb, Bb, mu_pb, s_pb, relu_b))
        mu = jnp.mean(z, axis=(0, 1, 2))
        yhat_out, q_out, amax = _stash(z, mu_po, s_po, stash,
                                       key[0] if stochastic else None)
        return yhat_out, q_out, mu, amax

    def fwd(*args):
        out = block(*args)
        (qa, Ma, Ba, mu_pa, s_pa) = args[1:6]
        (qb, Mb, Bb, mu_pb, s_pb) = args[7:12]
        return out, (qa, Ma, Ba, mu_pa, s_pa, qb, Mb, Bb, mu_pb, s_pb,
                     args[14:])

    def bwd(res, cots):
        qa, Ma, Ba, mu_pa, s_pa, qb, Mb, Bb, mu_pb, s_pb, key = res
        g_yhat, _gq, g_mu, _ga = cots
        nhw = float(np.prod(g_yhat.shape[:3]))
        dz = g_yhat.astype(jnp.float32) + g_mu / nhw

        def back(q, M, B, mu_p, s_p, relu):
            g = dz
            yd = _dequant(q, mu_p, s_p)
            if relu:
                g = g * (yd * M + B > 0)
            return ((g * M).astype(dtypes.compute_dtype()),
                    _red(g * yd, M), _red(g, B))

        dya, dMa, dBa = back(qa, Ma, Ba, mu_pa, s_pa, relu_a)
        dyb, dMb, dBb = back(qb, Mb, Bb, mu_pb, s_pb, relu_b)
        z0 = jnp.zeros_like(Ma)
        return (dya, _stash_zero(qa), dMa, dBa, z0, z0,
                dyb, _stash_zero(qb), dMb, dBb, z0, z0, z0, z0,
                *[_int_zero(k) for k in key])

    block.defvjp(fwd, bwd)
    return block


# ---------------------------------------------------------------------------
# quantized collectives: int8 payloads over ICI
# ---------------------------------------------------------------------------

def ppermute_q8_raw(x: jax.Array, axis_name: str, perm) -> jax.Array:
    """One quantized hop (int8 payload + per-shard fp32 scale) with NO
    autodiff wrapper — for use inside hand-written custom_vjp bodies
    that own their gradient rules (the flash ring)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf))
    sc = jnp.maximum(amax, 1e-8) / 127.0
    q = _quantize(xf / sc)
    qp = lax.ppermute(q, axis_name, perm)
    sp = lax.ppermute(sc, axis_name, perm)
    return (qp.astype(jnp.float32) * sp).astype(x.dtype)


@functools.lru_cache(maxsize=None)
def make_ppermute_q8(axis_name: str, perm: tuple):
    """``lax.ppermute`` with a symmetric per-shard-scalar int8 wire codec
    in BOTH directions: the forward payload and the backward cotangent
    each travel as (int8 tensor, fp32 scale) — half the ICI bytes of a
    bf16 send. Straight-through backward (the round contributes no
    gradient), so the transpose is the reversed permutation with the
    same codec. Use for inter-stage pipeline sends and ring-CP K/V
    rotations (the KV-cache-int8 trick applied to the wire)."""

    inv = tuple((d, s) for s, d in perm)

    def _codec(p):
        def send(x):
            return ppermute_q8_raw(x, axis_name, p)
        return send

    _send, _send_back = _codec(perm), _codec(inv)

    @jax.custom_vjp
    def pq(x):
        return _send(x)

    pq.defvjp(lambda x: (_send(x), None), lambda _, g: (_send_back(g),))
    return pq


def all_to_all_q8_raw(x: jax.Array, axis_name: str) -> jax.Array:
    """One quantized all-to-all (int8 payload + per-destination-block
    fp32 scales) with NO autodiff wrapper. ``x``'s leading axis indexes
    the DESTINATION shard (size = the axis size P); the result's leading
    axis indexes the SOURCE shard. Each of the P blocks gets its own
    symmetric scale, and the [P] scale vector rides the same all-to-all
    — so every (source, destination) block dequantizes with the scale it
    was quantized under."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=tuple(range(1, x.ndim)))
    sc = jnp.maximum(amax, 1e-8) / 127.0                      # [P]
    bshape = (-1,) + (1,) * (x.ndim - 1)
    q = _quantize(xf / sc.reshape(bshape))
    qp = lax.all_to_all(q, axis_name, 0, 0)
    sp = lax.all_to_all(sc, axis_name, 0, 0)
    return (qp.astype(jnp.float32) * sp.reshape(bshape)).astype(x.dtype)


@functools.lru_cache(maxsize=None)
def make_all_to_all_q8(axis_name: str):
    """``lax.all_to_all`` (split=concat=leading axis) with the symmetric
    int8 wire codec in BOTH directions. The block exchange is
    self-inverse (it transposes the (source, destination) block matrix),
    so the straight-through backward is the SAME codec applied to the
    cotangent. Use for MoE expert dispatch/combine — the explicit-
    collective form the round-4 HLO inspection showed GSPMD's einsum
    dispatch cannot express (it all-reduces fp32 partials before any
    constraint-point quantize runs)."""

    @jax.custom_vjp
    def a2a(x):
        return all_to_all_q8_raw(x, axis_name)

    a2a.defvjp(lambda x: (all_to_all_q8_raw(x, axis_name), None),
               lambda _, g: (all_to_all_q8_raw(g, axis_name),))
    return a2a


# ---------------------------------------------------------------------------
# weight quantization (serving): per-output-channel symmetric int8
# ---------------------------------------------------------------------------

def quantize_weight(w: jax.Array, reduce_axis):
    """Symmetric per-output-channel int8: scales are the absmax over the
    CONTRACTION axis/axes, so each output channel dequantizes with one
    multiply that fuses into the consuming matmul's operand read —
    weights live in HBM at 1 byte/elt. Returns {"q8", "scale"} with
    scale keeping w's rank (broadcastable)."""
    wf = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=reduce_axis, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = _quantize(wf / scale)
    return {"q8": q, "scale": scale.astype(jnp.float32)}


def dequantize_weight(node, dtype=jnp.float32) -> jax.Array:
    """Inverse of quantize_weight; elementwise, fuses into the consumer."""
    return (node["q8"].astype(jnp.float32) * node["scale"]).astype(dtype)


def is_quantized_weight(node) -> bool:
    return isinstance(node, dict) and set(node) == {"q8", "scale"}


def dequantize_tree(tree, dtype=jnp.float32):
    """Rebuild a params pytree whose quantized leaves are {"q8","scale"}
    nodes; every other leaf (and any registered container type) passes
    through."""
    return jax.tree_util.tree_map(
        lambda n: dequantize_weight(n, dtype)
        if is_quantized_weight(n) else n,
        tree, is_leaf=is_quantized_weight)


# ---------------------------------------------------------------------------
# KV-cache quantization (serving): per-token, per-head symmetric int8/int4
# ---------------------------------------------------------------------------

# symmetric clip targets: int8 uses the full signed range; int4 packs two
# nibbles per byte, each a two's-complement value in [-7, 7] (the -8 code
# is unused so the grid stays symmetric, like the int8 -128 code)
KV_QMAX = {"int8": 127.0, "int4": 7.0}
KV_DTYPES = tuple(KV_QMAX)


def pack_int4(q: jax.Array) -> jax.Array:
    """int8 values in [-7, 7] over an even last axis -> one byte per
    PAIR: even positions in the low nibble, odd in the high (two's
    complement within each nibble). Shape [..., D] -> [..., D//2]."""
    if q.shape[-1] % 2:
        raise ValueError(f"pack_int4 needs an even last axis, got "
                         f"{q.shape}")
    lo = q[..., 0::2].astype(jnp.int32)
    hi = q[..., 1::2].astype(jnp.int32)
    return ((hi << 4) | (lo & 0xF)).astype(jnp.int8)


def unpack_int4(p: jax.Array) -> jax.Array:
    """Inverse of :func:`pack_int4`: [..., D//2] int8 -> [..., D] int32.
    All-int32 shift arithmetic (sign-extend each nibble) so the exact
    same op chain runs under XLA, Mosaic, and the Pallas interpreter —
    the integers are exact, so any path is bitwise any other."""
    p32 = p.astype(jnp.int32)
    lo = lax.shift_right_arithmetic(lax.shift_left(p32, 28), 28)
    hi = lax.shift_right_arithmetic(lax.shift_left(p32, 24), 28)
    return jnp.stack([lo, hi], axis=-1).reshape(
        p.shape[:-1] + (p.shape[-1] * 2,))


def quantize_kv(x: jax.Array, kv_dtype: str):
    """Symmetric per-row quantization of KV vectors: ``x [..., Dh]`` ->
    ``(q, scale)`` with one fp32 scale per leading index (per token, per
    head — write-local, so incremental decode writes never rescale a
    block's resident neighbours). ``q`` is int8 ``[..., Dh]`` for int8,
    nibble-packed int8 ``[..., Dh//2]`` for int4. Same round/clip
    discipline as the activation stash (:func:`_quantize`)."""
    if kv_dtype not in KV_QMAX:
        raise ValueError(f"kv_dtype {kv_dtype!r}: one of {KV_DTYPES}")
    qmax = KV_QMAX[kv_dtype]
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / qmax
    q = jnp.clip(jnp.round(xf / scale[..., None]),
                 -qmax, qmax).astype(jnp.int8)
    if kv_dtype == "int4":
        q = pack_int4(q)
    return q, scale


def dequantize_kv(q: jax.Array, scale: jax.Array,
                  kv_dtype: str) -> jax.Array:
    """Inverse read: ``(q [..., Dh'], scale [...]) -> fp32 [..., Dh]``.
    Elementwise (unpack is exact integer math, the multiply broadcasts
    the row scale), so it fuses into the consumer — and the identical
    chain runs inside the Pallas kernels, which is what makes the
    fused-dequant kernel bitwise the XLA quantized path."""
    qi = unpack_int4(q) if kv_dtype == "int4" else q
    return qi.astype(jnp.float32) * scale[..., None]


# ---------------------------------------------------------------------------
# generic layer-granular remat with a quantized stash (transformer slot)
# ---------------------------------------------------------------------------

def q8_remat(fn, stash: str = "int8"):
    """Wrap ``fn(x, args) -> out`` so autodiff saves only a quantized
    copy of ``x`` (plus ``args``) and recomputes the block in backward.

    The conv pipeline above defers elementwise epilogues into per-channel
    affines; transformer blocks contain layer-norms (per-token, not
    foldable per-channel), so the right granularity there is the whole
    block: FORWARD USES THE EXACT ``x`` (zero forward error), backward
    rebuilds the block's vjp at ``x̃ = dequant(stash)``. With
    stash="int8" (per-tensor scale from the CURRENT absmax — no state
    needed since the scan carry is materialized anyway) residuals shrink
    from every block intermediate to one int8 tensor per block;
    stash="bf16" is classic block remat. ``args`` may be any pytree
    (weights, PRNG keys); integer leaves get float0 cotangents.

    Reference capability slot: activation memory management of
    paddle/memory + the recompute knobs of RecurrentGradientMachine —
    pushed to the long-context endpoint (fit 4-8x longer sequences)."""
    _check_stash(stash)

    @jax.custom_vjp
    def wrapped(x, args):
        return fn(x, args)

    def fwd(x, args):
        if stash == "bf16":
            q = x.astype(jnp.bfloat16)
            scale = jnp.ones((), jnp.float32)
        else:
            amax = jnp.max(jnp.abs(x.astype(jnp.float32)))
            scale = jnp.maximum(amax, 1e-6) / 127.0
            q = _quantize(x.astype(jnp.float32) / scale)
        # zero-size token carries x's dtype into bwd (residual pytrees
        # hold arrays only)
        token = jnp.zeros((0,), x.dtype)
        return fn(x, args), (q, scale, token, args)

    def bwd(res, g):
        q, scale, token, args = res
        xt = (q.astype(jnp.float32) * scale).astype(token.dtype)
        _, vjp = jax.vjp(fn, xt, args)
        return vjp(g)

    wrapped.defvjp(fwd, bwd)
    return wrapped


# ---------------------------------------------------------------------------
# per-channel affine folding (plain differentiable vector math)
# ---------------------------------------------------------------------------

def fold_bn_affine(mu: jax.Array, var: jax.Array, gamma: jax.Array,
                   beta: jax.Array, eps: float = 1e-5
                   ) -> Tuple[jax.Array, jax.Array]:
    """Fold a producer's deferred batch-norm into one ŷ-basis affine:
        bn(ŷ) = (ŷ − mu)·r·γ + β = ŷ·(r·γ) + (β − mu·r·γ).
    mu/var are the producer's current batch stats; gamma/beta its BN
    parameters (grads flow through all four)."""
    r = lax.rsqrt(var + eps)
    M = r * gamma
    B = beta - mu * r * gamma
    return M, B


def fold_identity(like: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Unit affine for a stash with no deferred BN (add outputs / entry)."""
    return jnp.ones_like(like), jnp.zeros_like(like)
