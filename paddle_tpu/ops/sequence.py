"""Sequence ops over padded+masked batches.

Replaces the LoD/sequence machinery (reference:
paddle/gserver/layers/SequencePoolLayer.cpp, SequenceLastInstanceLayer.cpp,
MaxLayer/AverageLayer (sequence modes), ExpandLayer.cpp,
SequenceConcatLayer.cpp, SequenceReshapeLayer.cpp, SequenceSliceLayer.cpp,
KmaxSeqScoreLayer.cpp, paddle/function/ContextProjectionOp.cpp,
paddle/function/RowConvOp.cpp, operators/sequence_pool_op.cc,
sequence_conv_op.cc, sequence_softmax_op.cc, seq_expand_op.cc).

Inputs are [batch, time, ...] + lengths [batch] (see core.ragged) — masked
compute replaces the reference's zero-padding-free start-position indexing;
on TPU, masking + dense batched ops beat gather/scatter of ragged rows.
"""

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _mask(lengths, max_len, dtype=jnp.float32):
    t = jnp.arange(max_len, dtype=jnp.int32)
    return (t[None, :] < lengths[:, None]).astype(dtype)


def seq_sum(x, lengths):
    """[b,t,...] -> [b,...] sum over valid steps (SequencePoolLayer sum)."""
    m = _mask(lengths, x.shape[1]).reshape(x.shape[:2] + (1,) * (x.ndim - 2))
    return jnp.sum(x * m.astype(x.dtype), axis=1)


def seq_avg(x, lengths):
    denom = jnp.maximum(lengths, 1).astype(x.dtype)
    return seq_sum(x, lengths) / denom.reshape((-1,) + (1,) * (x.ndim - 2))


def seq_sqrt(x, lengths):
    """sum / sqrt(len) (reference: AverageLayer "sqrt" mode)."""
    denom = jnp.sqrt(jnp.maximum(lengths, 1).astype(x.dtype))
    return seq_sum(x, lengths) / denom.reshape((-1,) + (1,) * (x.ndim - 2))


def seq_max(x, lengths):
    m = _mask(lengths, x.shape[1], jnp.bool_).reshape(
        x.shape[:2] + (1,) * (x.ndim - 2))
    return jnp.max(jnp.where(m, x, NEG_INF), axis=1)


def seq_last(x, lengths):
    """Last valid step (reference: SequenceLastInstanceLayer)."""
    idx = jnp.maximum(lengths - 1, 0).astype(jnp.int32)
    return jax.vmap(lambda row, i: row[i])(x, idx)


def seq_first(x, lengths):
    return x[:, 0]


def seq_softmax(x, lengths):
    """Softmax over the time axis per sequence, padding masked out
    (reference: sequence_softmax_op.cc, SequenceSoftmaxActivation)."""
    m = _mask(lengths, x.shape[1], jnp.bool_)
    while m.ndim < x.ndim:
        m = m[..., None]
    logits = jnp.where(m, x.astype(jnp.float32), NEG_INF)
    out = jax.nn.softmax(logits, axis=1)
    return jnp.where(m, out, 0.0).astype(x.dtype)


def seq_expand(x, lengths, max_len: int):
    """Broadcast one vector per sequence across its timesteps
    (reference: ExpandLayer / seq_expand_op): [b, d] -> [b, t, d] masked."""
    out = jnp.broadcast_to(x[:, None], (x.shape[0], max_len) + x.shape[1:])
    return out * _mask(lengths, max_len, x.dtype).reshape(
        x.shape[0], max_len, *([1] * (x.ndim - 1)))


def seq_reverse(x, lengths):
    """Reverse each sequence within its valid region (reference:
    gserver SequenceReverse used for bidirectional RNNs)."""
    t = jnp.arange(x.shape[1], dtype=jnp.int32)
    idx = jnp.where(t[None, :] < lengths[:, None],
                    lengths[:, None] - 1 - t[None, :], t[None, :])
    return jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)).astype(jnp.int32), axis=1)


def context_projection(x, lengths, context_len: int, context_start: int):
    """Sliding context-window concat (reference:
    paddle/function/ContextProjectionOp.cpp — the core of text CNNs):
    out[:, t] = concat(x[:, t+context_start], ..., x[:, t+context_start+len-1])
    with out-of-sequence positions zero."""
    b, tmax, d = x.shape
    m = _mask(lengths, tmax, x.dtype)[..., None]
    xm = x * m
    cols = []
    for k in range(context_len):
        shift = context_start + k
        rolled = jnp.roll(xm, -shift, axis=1)
        t = jnp.arange(tmax)
        valid = (t[None, :] + shift >= 0) & (t[None, :] + shift < lengths[:, None])
        cols.append(jnp.where(valid[..., None], rolled, 0.0))
    return jnp.concatenate(cols, axis=-1)


def row_conv(x, lengths, w):
    """Lookahead row convolution (reference: paddle/function/RowConvOp.cpp,
    gserver RowConvLayer — DeepSpeech2): out[:, t] = sum_k x[:, t+k] * w[k]."""
    k = w.shape[0]
    ctx = context_projection(x, lengths, k, 0)  # [b,t,k*d]
    b, tmax, _ = x.shape
    ctx = ctx.reshape(b, tmax, k, -1)
    return jnp.einsum("btkd,kd->btd", ctx, w.astype(x.dtype))


def kmax_score_indices(scores, lengths, k: int):
    """Top-k step indices per sequence by score (reference:
    KmaxSeqScoreLayer.cpp). scores: [b, t]. Returns [b, k] indices."""
    masked = jnp.where(_mask(lengths, scores.shape[1], jnp.bool_),
                       scores, NEG_INF)
    _, idx = jax.lax.top_k(masked, k)
    return idx


def seq_concat(x, x_len, y, y_len):
    """Per-sequence time-axis concat (reference: SequenceConcatLayer.cpp).
    Output padded to x.max_len + y.max_len."""
    b, tx, d = x.shape
    ty = y.shape[1]
    out_t = tx + ty
    # scatter y after each x's valid length
    t = jnp.arange(out_t, dtype=jnp.int32)
    from_x = t[None, :] < x_len[:, None]
    y_idx = jnp.clip(t[None, :] - x_len[:, None], 0, ty - 1)
    x_idx = jnp.clip(t[None, :], 0, tx - 1)
    gx = jnp.take_along_axis(x, x_idx[..., None].astype(jnp.int32), axis=1)
    gy = jnp.take_along_axis(y, y_idx[..., None].astype(jnp.int32), axis=1)
    out = jnp.where(from_x[..., None], gx, gy)
    valid = t[None, :] < (x_len + y_len)[:, None]
    return jnp.where(valid[..., None], out, 0.0), x_len + y_len


def sub_nested_seq(x, sub_lengths, sel_idx, sel_count):
    """Select sub-sequences from a nested (2-level LoD) sequence batch
    (reference: SubNestedSequenceLayer.cpp calSelectedRows — given
    per-sequence selected sub-sequence indices, emit a new nested
    sequence containing only those sub-sequences, in selection order).

    x: [b, T, D] sub-sequences concatenated on the time axis;
    sub_lengths: [b, S] per-sub-sequence lengths (0-padded);
    sel_idx: [b, K] selected sub-sequence indices (entries past
    sel_count[b] ignored); sel_count: [b].
    Returns (out [b, T, D], new_lengths [b], new_sub_lengths [b, K]).
    Static shapes throughout: the output keeps the input's T bound and a
    position→source gather map is built with comparisons over the K
    selection slots, so backward is a scatter-add for free under autodiff.

    Contract (in-graph code cannot raise on data): a selection index
    outside [0, S) or pointing at a 0-length padded slot contributes an
    EMPTY sub-sequence (never another slot's data — the reference CHECKs
    this on the host, SubNestedSequenceLayer.cpp calSelectedRows);
    selecting the same sub-sequence more than once is supported only
    while the total stays within the input's T bound — beyond that the
    output (and new_lengths) truncate at T.
    """
    b, t_max = x.shape[0], x.shape[1]
    s = sub_lengths.shape[1]
    k = sel_idx.shape[1]
    i32 = jnp.int32
    sel_idx = sel_idx.astype(i32)
    k_valid = ((jnp.arange(k, dtype=i32)[None, :] < sel_count[:, None]) &
               (sel_idx >= 0) & (sel_idx < s))                     # [b,K]
    sidx = jnp.clip(sel_idx, 0, s - 1)
    sel_lens = jnp.where(k_valid,
                         jnp.take_along_axis(sub_lengths.astype(i32), sidx,
                                             axis=1), 0)           # [b,K]
    sub_starts = jnp.concatenate(
        [jnp.zeros((b, 1), i32),
         jnp.cumsum(sub_lengths.astype(i32), axis=1)[:, :-1]], axis=1)
    src_starts = jnp.take_along_axis(sub_starts, sidx, axis=1)     # [b,K]
    out_ends = jnp.cumsum(sel_lens, axis=1)                        # [b,K]
    out_starts = out_ends - sel_lens
    new_lengths = jnp.minimum(out_ends[:, -1], t_max)
    # duplicate selections past the T bound truncate (see contract above);
    # the reported per-slot lengths must agree with the truncated content
    sel_lens = (jnp.minimum(out_ends, t_max) -
                jnp.minimum(out_starts, t_max))
    t = jnp.arange(t_max, dtype=i32)
    in_chunk = ((t[None, :, None] >= out_starts[:, None, :]) &
                (t[None, :, None] < out_ends[:, None, :]))         # [b,T,K]
    chunk = jnp.argmax(in_chunk, axis=2).astype(i32)               # [b,T]
    valid = jnp.any(in_chunk, axis=2)                              # [b,T]
    off = t[None, :] - jnp.take_along_axis(out_starts, chunk, axis=1)
    src = jnp.take_along_axis(src_starts, chunk, axis=1) + off
    src = jnp.clip(src, 0, t_max - 1)
    if x.ndim == 2:
        out = jnp.take_along_axis(x, src, axis=1)
        out = jnp.where(valid, out, jnp.zeros((), x.dtype))
    else:
        out = jnp.take_along_axis(x, src[..., None], axis=1)
        out = jnp.where(valid[..., None], out, jnp.zeros((), x.dtype))
    return out, new_lengths, sel_lens
