"""Loss / cost functions.

Replaces the cost layer family (reference: paddle/gserver/layers/CostLayer.cpp
— MultiClassCrossEntropy, SoftBinaryClassCrossEntropy, SumOfSquaresCostLayer,
HuberTwoClassification, MultiBinaryLabelCrossEntropy, RankingCost,
LambdaCost, SmoothL1Cost) and new-stack ops (operators/cross_entropy_op.cc,
softmax_with_cross_entropy_op.cc, squared_l2_distance_op.cc, rank_loss_op.cc,
smooth_l1_loss_op.cc, huber_loss_op.cc, hinge_loss_op.cc).

All return per-example losses [batch]; reduction is the caller's choice
(the trainer averages). Softmax+CE is fused in log-space for stability —
the same reason the reference had a fused softmax_with_cross_entropy op.
"""

import jax
import jax.numpy as jnp


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Integer labels [batch] against logits [batch, classes]."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32),
                                axis=-1)[..., 0]


def soft_cross_entropy(logits: jax.Array, label_probs: jax.Array) -> jax.Array:
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.sum(label_probs * logp, axis=-1)


def cross_entropy_with_probs(probs: jax.Array, labels: jax.Array,
                             eps=1e-8) -> jax.Array:
    """CE against already-softmaxed probabilities (the v1 layer contract:
    classification_cost ran on softmax output)."""
    p = jnp.take_along_axis(probs, labels[..., None].astype(jnp.int32),
                            axis=-1)[..., 0]
    return -jnp.log(p + eps)


def binary_cross_entropy(p: jax.Array, label: jax.Array, eps=1e-8) -> jax.Array:
    p = p.astype(jnp.float32)
    return -(label * jnp.log(p + eps) + (1 - label) * jnp.log(1 - p + eps))


def multi_binary_cross_entropy(p: jax.Array, labels: jax.Array,
                               eps=1e-8) -> jax.Array:
    """Sum of per-class BCE (reference: MultiBinaryLabelCrossEntropy)."""
    return jnp.sum(binary_cross_entropy(p, labels, eps), axis=-1)


def square_error(pred: jax.Array, target: jax.Array) -> jax.Array:
    """0.5*||pred-t||^2 (reference: SumOfSquaresCostLayer)."""
    d = (pred - target).astype(jnp.float32)
    return 0.5 * jnp.sum(d * d, axis=tuple(range(1, d.ndim)))


def smooth_l1(pred: jax.Array, target: jax.Array, delta=1.0) -> jax.Array:
    d = jnp.abs((pred - target).astype(jnp.float32))
    per = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
    return jnp.sum(per, axis=tuple(range(1, per.ndim)))


def huber_classification(pred: jax.Array, label: jax.Array) -> jax.Array:
    """Two-class huber on {0,1} labels, internally mapped to {-1,1}
    (reference: HuberTwoClassification)."""
    y = 2.0 * label.astype(jnp.float32) - 1.0
    a = y * pred.astype(jnp.float32).squeeze(-1)
    return jnp.where(a < -1.0, -4.0 * a, jnp.where(a < 1.0, jnp.square(1.0 - a), 0.0))


def hinge(pred: jax.Array, label: jax.Array) -> jax.Array:
    y = 2.0 * label.astype(jnp.float32) - 1.0
    return jnp.maximum(0.0, 1.0 - y * pred.astype(jnp.float32).squeeze(-1))


def rank_cost(left: jax.Array, right: jax.Array, label: jax.Array) -> jax.Array:
    """Pairwise ranking (RankNet) cost (reference: RankingCost layer):
    C = -o*label + log(1+exp(o)), o = left - right."""
    o = (left - right).astype(jnp.float32).squeeze(-1)
    return jax.nn.softplus(o) - o * label.astype(jnp.float32)


def huber_regression(pred: jax.Array, target: jax.Array,
                     delta: float = 1.0) -> jax.Array:
    """Classic Huber regression loss summed over output dims (reference:
    HuberRegressionLoss, gserver CostLayer.cpp; huber_loss_op.cc)."""
    a = jnp.abs((pred - target).astype(jnp.float32))
    per_dim = jnp.where(a <= delta, 0.5 * jnp.square(a),
                        delta * (a - 0.5 * delta))
    return jnp.sum(per_dim, axis=-1)


def cross_entropy_with_selfnorm(logits: jax.Array, labels: jax.Array,
                                alpha: float = 0.1) -> jax.Array:
    """CE + alpha * log(Z)^2 self-normalisation penalty (reference:
    MultiClassCrossEntropyWithSelfNorm, CostLayer.cpp:105-141 — drives the
    softmax partition function toward 1 so serving can skip the
    normalisation)."""
    lf = logits.astype(jnp.float32)
    log_z = jax.nn.logsumexp(lf, axis=-1)
    ce = log_z - jnp.take_along_axis(
        lf, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
    return ce + alpha * jnp.square(log_z)


def lambda_rank(scores: jax.Array, relevance: jax.Array, lengths: jax.Array,
                ndcg_num: int = 5) -> jax.Array:
    """LambdaRank NDCG cost per query (reference: LambdaCost,
    gserver CostLayer.h:252 — lambda gradients weighted by |ΔNDCG|).

    scores/relevance: [B, T] padded query lists; returns [B] costs. Each
    mis-ordered pair contributes its RankNet logistic loss weighted by the
    (stop-gradient) |ΔNDCG| of swapping the pair at the current ranking,
    truncated at ndcg_num as the reference truncates."""
    b, t = scores.shape
    mask = jnp.arange(t)[None, :] < lengths[:, None]
    s = jnp.where(mask, scores.astype(jnp.float32), -jnp.inf)
    rel = jnp.where(mask, relevance.astype(jnp.float32), 0.0)
    # current rank of each item (0-based) under the model's scores
    order = jnp.argsort(-s, axis=1)
    ranks = jnp.argsort(order, axis=1).astype(jnp.float32)
    disc = jnp.where(ranks < ndcg_num, 1.0 / jnp.log2(ranks + 2.0), 0.0)
    gain = (jnp.exp2(rel) - 1.0) * mask
    # ideal DCG normaliser from the relevance-sorted list
    rel_best = -jnp.sort(-rel, axis=1)
    pos = jnp.arange(t, dtype=jnp.float32)[None, :]
    ideal_disc = jnp.where((pos < ndcg_num) & (pos < lengths[:, None]),
                           1.0 / jnp.log2(pos + 2.0), 0.0)
    idcg = jnp.sum((jnp.exp2(rel_best) - 1.0) * ideal_disc, axis=1)
    idcg = jnp.maximum(idcg, 1e-8)
    # pairwise |ΔNDCG| of swapping i and j at the current ranking
    dgain = gain[:, :, None] - gain[:, None, :]
    ddisc = disc[:, :, None] - disc[:, None, :]
    delta = jnp.abs(dgain * ddisc) / idcg[:, None, None]
    valid = mask[:, :, None] & mask[:, None, :]
    better = (rel[:, :, None] > rel[:, None, :]) & valid
    diff = s[:, :, None] - s[:, None, :]
    diff = jnp.where(valid, diff, 0.0)
    pair_loss = jax.nn.softplus(-diff)
    w = jax.lax.stop_gradient(jnp.where(better, delta, 0.0))
    return jnp.sum(w * pair_loss, axis=(1, 2))
