"""Sparse / embedding ops.

Replaces the embedding + row-sparse gradient machinery (reference:
paddle/gserver/layers/TableProjection.cpp, operators/lookup_table_op.cc with
SelectedRows grads, paddle/math/SparseRowMatrix.h, framework/selected_rows.h).

On TPU an embedding lookup is a gather feeding the MXU; the row-sparse
gradient materialises through XLA's scatter-add in the backward pass of
``jnp.take`` — the SelectedRows representation is unnecessary on-chip. The
*sharded* table variant (the sparse_remote_update capability,
trainer/RemoteParameterUpdater.h:265) lives in paddle_tpu.parallel.
"""

import jax
import jax.numpy as jnp


def embedding_lookup(table: jax.Array, ids: jax.Array,
                     padding_idx: int = None) -> jax.Array:
    """table: [vocab, dim]; ids: int[...]. Returns [..., dim]."""
    out = jnp.take(table, ids.astype(jnp.int32), axis=0)
    if padding_idx is not None:
        out = jnp.where((ids == padding_idx)[..., None], 0.0, out)
    return out


def one_hot(ids: jax.Array, depth: int, dtype=jnp.float32) -> jax.Array:
    return jax.nn.one_hot(ids, depth, dtype=dtype)


def scatter_add_rows(table: jax.Array, ids: jax.Array,
                     rows: jax.Array) -> jax.Array:
    """table[ids] += rows (duplicate ids accumulate) — the SelectedRows apply
    operation (reference: operators/math/selected_rows_functor.cc)."""
    return table.at[ids.astype(jnp.int32)].add(rows)


def sparse_vector_to_dense(indices, values, dim, batch_offsets=None):
    """Host-side helper used by the data feeder for sparse_vector input types
    (reference: python/paddle/trainer/PyDataProvider2.py sparse slots)."""
    import numpy as np
    n = len(batch_offsets) - 1 if batch_offsets is not None else 1
    out = np.zeros((n, dim), np.float32)
    if batch_offsets is None:
        out[0, indices] = values if values is not None else 1.0
        return out
    for i in range(n):
        lo, hi = batch_offsets[i], batch_offsets[i + 1]
        out[i, indices[lo:hi]] = values[lo:hi] if values is not None else 1.0
    return out
