"""Sparse / embedding ops.

Replaces the embedding + row-sparse gradient machinery (reference:
paddle/gserver/layers/TableProjection.cpp, operators/lookup_table_op.cc with
SelectedRows grads, paddle/math/SparseRowMatrix.h, framework/selected_rows.h).

On TPU an embedding lookup is a gather feeding the MXU; the row-sparse
gradient materialises through XLA's scatter-add in the backward pass of
``jnp.take`` — the SelectedRows representation is unnecessary on-chip. The
*sharded* table variant (the sparse_remote_update capability,
trainer/RemoteParameterUpdater.h:265) lives in paddle_tpu.parallel.
"""

import jax
import jax.numpy as jnp


def embedding_lookup(table: jax.Array, ids: jax.Array,
                     padding_idx: int = None) -> jax.Array:
    """table: [vocab, dim]; ids: int[...]. Returns [..., dim]."""
    out = jnp.take(table, ids.astype(jnp.int32), axis=0)
    if padding_idx is not None:
        out = jnp.where((ids == padding_idx)[..., None], 0.0, out)
    return out


def one_hot(ids: jax.Array, depth: int, dtype=jnp.float32) -> jax.Array:
    return jax.nn.one_hot(ids, depth, dtype=dtype)


def scatter_add_rows(table: jax.Array, ids: jax.Array,
                     rows: jax.Array) -> jax.Array:
    """table[ids] += rows (duplicate ids accumulate) — the SelectedRows apply
    operation (reference: operators/math/selected_rows_functor.cc)."""
    return table.at[ids.astype(jnp.int32)].add(rows)


def sparse_vector_to_dense(indices, values, dim, batch_offsets=None):
    """Host-side helper used by the data feeder for sparse_vector input types
    (reference: python/paddle/trainer/PyDataProvider2.py sparse slots)."""
    import numpy as np
    n = len(batch_offsets) - 1 if batch_offsets is not None else 1
    out = np.zeros((n, dim), np.float32)
    if batch_offsets is None:
        out[0, indices] = values if values is not None else 1.0
        return out
    for i in range(n):
        lo, hi = batch_offsets[i], batch_offsets[i + 1]
        out[i, indices[lo:hi]] = values[lo:hi] if values is not None else 1.0
    return out


class CSRMatrix:
    """Compressed-sparse-row matrix with STATIC nnz — the XLA-compatible
    CSR (reference: paddle/math/CpuSparseMatrix.h / SparseMatrix.h CSR
    storage). indptr [rows+1], indices [nnz], data [nnz]; padding entries
    (beyond a row's true nnz) carry index 0 / data 0 so every op is a
    masked dense gather — no dynamic shapes under jit.

    The reference used CSR for sparse *inputs* (high-dim id features) and
    sparse weight matrices; on TPU the former maps to gathers and the
    latter is usually better dense-bf16, but the format itself round-trips
    for interchange and host-side construction."""

    def __init__(self, indptr, indices, data, shape):
        self.indptr = jnp.asarray(indptr, jnp.int32)
        self.indices = jnp.asarray(indices, jnp.int32)
        self.data = jnp.asarray(data)
        self.shape = tuple(shape)

    @classmethod
    def from_dense(cls, dense):
        import numpy as np
        d = np.asarray(dense)
        rows, cols = d.shape
        indptr = [0]
        indices, data = [], []
        for r in range(rows):
            nz = np.nonzero(d[r])[0]
            indices.extend(nz.tolist())
            data.extend(d[r, nz].tolist())
            indptr.append(len(indices))
        return cls(np.asarray(indptr), np.asarray(indices, np.int64),
                   np.asarray(data, d.dtype), (rows, cols))

    def to_dense(self) -> jax.Array:
        import numpy as np
        out = np.zeros(self.shape, np.asarray(self.data).dtype)
        indptr = np.asarray(self.indptr)
        for r in range(self.shape[0]):
            lo, hi = int(indptr[r]), int(indptr[r + 1])
            np.add.at(out[r], np.asarray(self.indices[lo:hi]),
                      np.asarray(self.data[lo:hi]))
        return jnp.asarray(out)

    @property
    def nnz(self):
        return int(self.indptr[-1])

    def matmul_dense(self, b: jax.Array) -> jax.Array:
        """CSR @ dense via gather + segment-sum (jit-safe: static nnz).
        Replaces Matrix::mul(CpuSparseMatrix, ...) (reference:
        paddle/math/Matrix.cpp sparse paths)."""
        nnz = self.indices.shape[0]
        # row id of each stored entry from indptr (searchsorted broadcast)
        entry = jnp.arange(nnz)
        row_of = jnp.searchsorted(self.indptr[1:], entry, side="right")
        contrib = self.data[:, None] * b[self.indices]      # [nnz, cols]
        return jax.ops.segment_sum(contrib, row_of,
                                   num_segments=self.shape[0])

    def transpose_matmul_dense(self, b: jax.Array) -> jax.Array:
        """CSR^T @ dense — scatter-add into the column space (the CSC-use
        case; the reference kept a separate CSC format, same capability)."""
        nnz = self.indices.shape[0]
        entry = jnp.arange(nnz)
        row_of = jnp.searchsorted(self.indptr[1:], entry, side="right")
        contrib = self.data[:, None] * b[row_of]            # [nnz, cols]
        return jnp.zeros((self.shape[1], b.shape[1]), contrib.dtype).at[
            self.indices].add(contrib)
