"""CTC loss (Connectionist Temporal Classification) as a log-space
alpha-recursion lax.scan.

Reference: the warp-ctc integration (paddle/cuda/src/hl_warpctc_wrap.cc,
gserver/layers/WarpCTCLayer.cpp) and the in-tree CPU DP
(gserver/layers/LinearChainCTC.cpp), plus operators' CTC evaluator
(gserver/evaluators/CTCErrorEvaluator.cpp for edit-distance decoding).

TPU design: one scan over time on the extended label lattice [B, 2L+1];
every step is a batched gather + logsumexp of three shifted lanes — no
per-sequence host loops. Gradients via jax.grad through the scan (warp-ctc
hand-codes the beta recursion).
"""

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _extended_labels(labels: jax.Array, blank: int):
    """labels [B, L] → lattice labels [B, 2L+1]: blank, l1, blank, l2, ..."""
    B, L = labels.shape
    ext = jnp.full((B, 2 * L + 1), blank, labels.dtype)
    return ext.at[:, 1::2].set(labels)


def ctc_loss(log_probs: jax.Array, labels: jax.Array,
             input_lengths: jax.Array, label_lengths: jax.Array,
             blank: int = 0) -> jax.Array:
    """Negative log p(labels | inputs) per sequence.

    log_probs: [B, T, C] log-softmax outputs (C includes the blank class),
    labels: [B, L] int padded, input_lengths/label_lengths: [B].
    """
    lp = log_probs.astype(jnp.float32)
    B, T, C = lp.shape
    labels = labels.astype(jnp.int32)
    ext = _extended_labels(labels, blank)                     # [B, S]
    S = ext.shape[1]

    # alpha[s] may also come from s-2 when ext[s] is a label differing from
    # ext[s-2] (the standard CTC skip rule)
    ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :S]
    can_skip = (ext != blank) & (ext != ext_m2)               # [B, S]

    emit0 = jnp.take_along_axis(lp[:, 0], ext, axis=1)        # [B, S]
    s_idx = jnp.arange(S)[None, :]
    alpha0 = jnp.where(s_idx < 2, emit0, NEG_INF)

    def shift(a, k):
        return jnp.pad(a, ((0, 0), (k, 0)), constant_values=NEG_INF)[:, :S]

    def step(alpha, inputs):
        lp_t, t = inputs                                       # [B, C], scalar
        emit = jnp.take_along_axis(lp_t, ext, axis=1)          # [B, S]
        stay = alpha
        prev1 = shift(alpha, 1)
        prev2 = jnp.where(can_skip, shift(alpha, 2), NEG_INF)
        new = jnp.logaddexp(jnp.logaddexp(stay, prev1), prev2) + emit
        alive = (t < input_lengths)[:, None]
        return jnp.where(alive, new, alpha), None

    ts = jnp.arange(1, T)
    alpha, _ = jax.lax.scan(step, alpha0, (lp[:, 1:].swapaxes(0, 1), ts))

    # total prob = alpha[2*label_len] (final blank) + alpha[2*label_len - 1]
    send = (2 * label_lengths).astype(jnp.int32)[:, None]      # [B, 1]
    a_last = jnp.take_along_axis(alpha, send, axis=1)[:, 0]
    a_prev = jnp.take_along_axis(alpha, jnp.maximum(send - 1, 0), axis=1)[:, 0]
    # empty label sequences (label_len == 0) only have the final-blank path
    a_prev = jnp.where(label_lengths > 0, a_prev, NEG_INF)
    return -jnp.logaddexp(a_last, a_prev)


def ctc_greedy_decode(log_probs: jax.Array, input_lengths: jax.Array,
                      blank: int = 0):
    """Best-path decode: argmax per frame, collapse repeats, drop blanks.
    Returns (decoded [B, T] int32 padded with blank, lengths [B]).
    Reference: CTCErrorEvaluator.cpp best-path decoding."""
    ids = jnp.argmax(log_probs, axis=-1).astype(jnp.int32)    # [B, T]
    B, T = ids.shape
    prev = jnp.pad(ids, ((0, 0), (1, 0)), constant_values=-1)[:, :T]
    frame_valid = jnp.arange(T)[None, :] < input_lengths[:, None]
    keep = (ids != blank) & (ids != prev) & frame_valid       # [B, T]
    # stable left-compaction of kept symbols
    pos = jnp.cumsum(keep, axis=1) - 1                        # target slot
    out = jnp.full((B, T), blank, jnp.int32)
    bidx = jnp.arange(B)[:, None]
    out = out.at[bidx, jnp.where(keep, pos, T)].set(ids, mode="drop")
    dec_len = jnp.sum(keep, axis=1).astype(jnp.int32)
    return out, dec_len
