"""Detection kernels: box IoU, SSD prior boxes, box codec, NMS, ROI pooling.

Reference: the SSD detection suite — gserver/layers/PriorBox.cpp,
MultiBoxLossLayer.cpp, DetectionOutputLayer.cpp, DetectionUtil.cpp
(encodeBBox/decodeBBox/applyNMSFast), ROIPoolLayer.cpp; new stack
operators/prior_box_op.cc, multiclass_nms equivalents.

TPU design: boxes ride as fixed-width padded tensors ([B, N, 4] + validity
masks); matching is a dense IoU matrix + argmax; NMS is a fixed-iteration
suppression loop (fori_loop over the k kept slots) instead of dynamic
queues. Boxes are (xmin, ymin, xmax, ymax), normalized [0, 1].
"""

import math
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp


def iou_matrix(a: jax.Array, b: jax.Array) -> jax.Array:
    """IoU of every pair: a [N, 4] x b [M, 4] → [N, M]."""
    ax1, ay1, ax2, ay2 = jnp.split(a, 4, axis=-1)        # [N,1]
    bx1, by1, bx2, by2 = [v[None, :, 0] for v in jnp.split(b, 4, axis=-1)]
    ix1 = jnp.maximum(ax1, bx1)
    iy1 = jnp.maximum(ay1, by1)
    ix2 = jnp.minimum(ax2, bx2)
    iy2 = jnp.minimum(ay2, by2)
    inter = jnp.clip(ix2 - ix1, 0) * jnp.clip(iy2 - iy1, 0)
    area_a = jnp.clip(ax2 - ax1, 0) * jnp.clip(ay2 - ay1, 0)
    area_b = jnp.clip(bx2 - bx1, 0) * jnp.clip(by2 - by1, 0)
    union = area_a + area_b - inter
    return jnp.where(union > 0, inter / union, 0.0)


def prior_boxes(feat_h: int, feat_w: int, img_h: int, img_w: int,
                min_size: float, max_size: float = None,
                aspect_ratios: Sequence[float] = (2.0,),
                flip: bool = True, clip: bool = True) -> jax.Array:
    """SSD prior boxes for one feature map → [feat_h*feat_w*P, 4]
    (reference: PriorBox.cpp — one square min box, optional sqrt(min*max)
    box, plus aspect-ratio boxes per cell center)."""
    ratios = [1.0]
    for ar in aspect_ratios:
        ratios.append(ar)
        if flip:
            ratios.append(1.0 / ar)
    whs = [(min_size, min_size)]
    if max_size:
        s = math.sqrt(min_size * max_size)
        whs.append((s, s))
    for r in ratios[1:]:
        whs.append((min_size * math.sqrt(r), min_size / math.sqrt(r)))

    cx = (jnp.arange(feat_w) + 0.5) / feat_w
    cy = (jnp.arange(feat_h) + 0.5) / feat_h
    cyx = jnp.stack(jnp.meshgrid(cy, cx, indexing="ij"), -1)  # [H,W,2]
    boxes = []
    for w, h in whs:
        wn, hn = w / img_w, h / img_h
        box = jnp.concatenate([
            cyx[..., 1:2] - wn / 2, cyx[..., 0:1] - hn / 2,
            cyx[..., 1:2] + wn / 2, cyx[..., 0:1] + hn / 2], -1)
        boxes.append(box)
    out = jnp.stack(boxes, 2).reshape(-1, 4)              # [H*W*P, 4]
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


def encode_boxes(gt: jax.Array, priors: jax.Array,
                 variances=(0.1, 0.1, 0.2, 0.2)) -> jax.Array:
    """SSD box targets: center/size offsets scaled by variances
    (reference: DetectionUtil.cpp encodeBBoxWithVar)."""
    pw = priors[..., 2] - priors[..., 0]
    ph = priors[..., 3] - priors[..., 1]
    pcx = (priors[..., 0] + priors[..., 2]) / 2
    pcy = (priors[..., 1] + priors[..., 3]) / 2
    gw = jnp.clip(gt[..., 2] - gt[..., 0], 1e-8)
    gh = jnp.clip(gt[..., 3] - gt[..., 1], 1e-8)
    gcx = (gt[..., 0] + gt[..., 2]) / 2
    gcy = (gt[..., 1] + gt[..., 3]) / 2
    v = variances
    return jnp.stack([
        (gcx - pcx) / pw / v[0], (gcy - pcy) / ph / v[1],
        jnp.log(gw / pw) / v[2], jnp.log(gh / ph) / v[3]], -1)


def decode_boxes(loc: jax.Array, priors: jax.Array,
                 variances=(0.1, 0.1, 0.2, 0.2)) -> jax.Array:
    """Inverse of encode_boxes (reference: decodeBBoxWithVar)."""
    pw = priors[..., 2] - priors[..., 0]
    ph = priors[..., 3] - priors[..., 1]
    pcx = (priors[..., 0] + priors[..., 2]) / 2
    pcy = (priors[..., 1] + priors[..., 3]) / 2
    v = variances
    cx = loc[..., 0] * v[0] * pw + pcx
    cy = loc[..., 1] * v[1] * ph + pcy
    w = jnp.exp(loc[..., 2] * v[2]) * pw
    h = jnp.exp(loc[..., 3] * v[3]) * ph
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], -1)


def match_priors(priors: jax.Array, gt_boxes: jax.Array, gt_valid: jax.Array,
                 overlap_threshold: float = 0.5):
    """SSD bipartite + per-prediction matching (reference:
    DetectionUtil.cpp matchBBox): every gt claims its best prior; remaining
    priors match their best gt if IoU >= threshold.

    priors [P, 4], gt_boxes [G, 4], gt_valid [G] bool →
    (match_idx [P] int32 — gt index or -1, match_iou [P]).
    """
    iou = iou_matrix(priors, gt_boxes)                    # [P, G]
    iou = jnp.where(gt_valid[None, :], iou, -1.0)
    best_gt = jnp.argmax(iou, axis=1).astype(jnp.int32)   # per prior
    best_gt_iou = jnp.max(iou, axis=1)
    match = jnp.where(best_gt_iou >= overlap_threshold, best_gt, -1)
    # bipartite pass: each gt's best prior is forced to that gt
    best_prior = jnp.argmax(iou, axis=0).astype(jnp.int32)  # [G]
    prior_ids = jnp.arange(priors.shape[0])
    for_gt = (prior_ids[:, None] == best_prior[None, :]) & gt_valid[None, :] \
        & (jnp.max(iou, axis=0) > 0)[None, :]
    forced = jnp.argmax(for_gt, axis=1).astype(jnp.int32)
    has_forced = jnp.any(for_gt, axis=1)
    match = jnp.where(has_forced, forced, match)
    match_iou = jnp.where(has_forced,
                          jnp.take_along_axis(iou, forced[:, None],
                                              axis=1)[:, 0],
                          best_gt_iou)
    return match, match_iou


def nms(boxes: jax.Array, scores: jax.Array, max_out: int,
        iou_threshold: float = 0.45, score_threshold: float = 0.01):
    """Greedy NMS with static shapes (reference: applyNMSFast).

    boxes [N, 4], scores [N] → (sel_idx [max_out] int32 (-1 pad),
    sel_scores [max_out]). Iterates max_out times; each step takes the
    best remaining score and suppresses overlaps.
    """
    N = boxes.shape[0]
    iou = iou_matrix(boxes, boxes)                        # [N, N]
    alive = scores >= score_threshold

    def body(i, carry):
        alive, sel, sel_sc = carry
        masked = jnp.where(alive, scores, -jnp.inf)
        best = jnp.argmax(masked).astype(jnp.int32)
        ok = masked[best] > -jnp.inf
        sel = sel.at[i].set(jnp.where(ok, best, -1))
        sel_sc = sel_sc.at[i].set(jnp.where(ok, scores[best], 0.0))
        # suppress: the chosen one and all with IoU above threshold
        suppress = (iou[best] >= iou_threshold) | \
            (jnp.arange(N) == best)
        alive = alive & jnp.where(ok, ~suppress, True)
        return alive, sel, sel_sc

    sel0 = jnp.full((max_out,), -1, jnp.int32)
    sc0 = jnp.zeros((max_out,), jnp.float32)
    _, sel, sel_sc = jax.lax.fori_loop(0, max_out, body, (alive, sel0, sc0))
    return sel, sel_sc


def roi_pool(feat: jax.Array, rois: jax.Array, out_h: int, out_w: int,
             spatial_scale: float = 1.0) -> jax.Array:
    """Max-pool each ROI to a fixed grid (reference: ROIPoolLayer.cpp,
    roi_pool_op.cc). feat [H, W, C] (one image), rois [R, 4] in feature
    coords after spatial_scale → [R, out_h, out_w, C].

    TPU design: instead of per-cell dynamic slices, build a dense
    [cell, position] membership mask and reduce — static shapes, MXU/VPU
    friendly for the moderate ROI counts detection uses.
    """
    H, W, C = feat.shape
    x1 = rois[:, 0] * spatial_scale
    y1 = rois[:, 1] * spatial_scale
    x2 = rois[:, 2] * spatial_scale
    y2 = rois[:, 3] * spatial_scale
    rw = jnp.maximum(x2 - x1, 1e-6)
    rh = jnp.maximum(y2 - y1, 1e-6)

    ys = jnp.arange(H, dtype=jnp.float32)
    xs = jnp.arange(W, dtype=jnp.float32)
    # cell boundaries per roi/cell
    cy0 = y1[:, None] + (jnp.arange(out_h) / out_h)[None, :] * rh[:, None]
    cy1 = y1[:, None] + ((jnp.arange(out_h) + 1) / out_h)[None, :] * rh[:, None]
    cx0 = x1[:, None] + (jnp.arange(out_w) / out_w)[None, :] * rw[:, None]
    cx1 = x1[:, None] + ((jnp.arange(out_w) + 1) / out_w)[None, :] * rw[:, None]
    # membership: [R, out_h, H], [R, out_w, W] — floor/ceil like the ref
    in_y = ((ys[None, None, :] >= jnp.floor(cy0[..., None])) &
            (ys[None, None, :] < jnp.ceil(cy1[..., None])))
    in_x = ((xs[None, None, :] >= jnp.floor(cx0[..., None])) &
            (xs[None, None, :] < jnp.ceil(cx1[..., None])))
    m = (in_y[:, :, None, :, None] & in_x[:, None, :, None, :])
    # [R, oh, ow, H, W] mask; reduce max over H, W
    masked = jnp.where(m[..., None], feat[None, None, None], -jnp.inf)
    out = jnp.max(masked, axis=(3, 4))
    return jnp.where(jnp.isfinite(out), out, 0.0)
