"""Top-k / argmax ops (reference: paddle/cuda/src/hl_top_k.cu,
operators/top_k_op.cc, gserver MaxIdLayer.cpp). lax.top_k lowers to the TPU's
sort/partial-sort; nothing hand-written needed."""

import jax
import jax.numpy as jnp
from jax import lax


def top_k(x: jax.Array, k: int):
    """Returns (values, indices) over the last axis."""
    return lax.top_k(x, k)


def max_id(x: jax.Array) -> jax.Array:
    """Argmax over last axis, kept as [..., 1] (reference: MaxIdLayer)."""
    return jnp.argmax(x, axis=-1, keepdims=True).astype(jnp.int32)
