"""Recurrent cells and scans.

Replaces the fused CUDA LSTM/GRU kernels and their layer wrappers (reference:
paddle/cuda/src/hl_cuda_lstm.cu, hl_gpu_gru.cuh, gserver/layers/LstmLayer.cpp,
GatedRecurrentLayer.cpp, operators/lstm_op.cc, gru_op.cc,
operators/math/lstm_compute.cc, gru_compute.cc, sequence2batch.h).

TPU design: one big input GEMM for all timesteps up front
(x @ W for every gate, batched over time — MXU-friendly), then a ``lax.scan``
over time carrying (h, c) where each step is a single [batch, 4*hidden] GEMM
against the recurrent weights plus fused elementwise gate math. Masking
freezes the state of finished sequences — this replaces the reference's
sequence2batch reordering (operators/math/sequence2batch.h) which existed to
avoid wasted GEMM rows; on the MXU the padded rows are free relative to the
cost of data movement.

Gate order here is i, f, g(candidate), o for LSTM and r(reset), u(update),
c(candidate) for GRU. NOTE: the reference packs gates differently —
(candidate, input, forget, output) for LSTM (operators/math/detail/
lstm_cpu_kernel.h:45-48) and (update, reset, candidate) for GRU
(gru_cpu_kernel.h:36-65) — so weights ported from Paddle checkpoints must be
column-permuted accordingly.
"""

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.ops.math import matmul


class LSTMState(NamedTuple):
    h: jax.Array
    c: jax.Array


def lstm_cell(x_proj: jax.Array, state: LSTMState, w_hh: jax.Array,
              forget_bias: float = 0.0) -> LSTMState:
    """One LSTM step. x_proj: [b, 4H] precomputed x@W_ih + b."""
    h, c = state
    gates = x_proj + matmul(h, w_hh)
    i, f, g, o = jnp.split(gates.astype(jnp.float32), 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f + forget_bias)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    new_c = f * c.astype(jnp.float32) + i * g
    new_h = o * jnp.tanh(new_c)
    return LSTMState(new_h.astype(h.dtype), new_c.astype(c.dtype))


def lstm(x: jax.Array, lengths: jax.Array, w_ih: jax.Array, w_hh: jax.Array,
         b: Optional[jax.Array] = None, *, reverse: bool = False,
         h0: Optional[jax.Array] = None, c0: Optional[jax.Array] = None,
         forget_bias: float = 0.0) -> Tuple[jax.Array, LSTMState]:
    """Full-sequence LSTM.

    x: [b, t, d]; w_ih: [d, 4H]; w_hh: [H, 4H]; b: [4H].
    Returns (outputs [b, t, H], final LSTMState).
    """
    bsz, tmax, _ = x.shape
    hidden = w_hh.shape[0]
    # one big MXU GEMM over all timesteps
    xp = matmul(x.reshape(bsz * tmax, -1), w_ih).reshape(bsz, tmax, 4 * hidden)
    if b is not None:
        xp = xp + b.astype(xp.dtype)
    mask = (jnp.arange(tmax)[None, :] < lengths[:, None])  # [b, t]
    h = h0 if h0 is not None else jnp.zeros((bsz, hidden), x.dtype)
    c = c0 if c0 is not None else jnp.zeros((bsz, hidden), x.dtype)

    xs = jnp.moveaxis(xp, 1, 0)      # [t, b, 4H]
    ms = jnp.moveaxis(mask, 1, 0)    # [t, b]
    if reverse:
        xs, ms = xs[::-1], ms[::-1]

    def step(state, inp):
        xt, mt = inp
        nxt = lstm_cell(xt, state, w_hh, forget_bias)
        mt = mt[:, None]
        # freeze finished rows (padding): carry old state through
        h_ = jnp.where(mt, nxt.h, state.h)
        c_ = jnp.where(mt, nxt.c, state.c)
        return LSTMState(h_, c_), h_

    final, outs = jax.lax.scan(step, LSTMState(h, c), (xs, ms))
    if reverse:
        outs = outs[::-1]
    outs = jnp.moveaxis(outs, 0, 1)  # [b, t, H]
    outs = outs * mask[..., None].astype(outs.dtype)
    return outs, final


def gru_cell(x_proj: jax.Array, h: jax.Array, w_hh: jax.Array) -> jax.Array:
    """One GRU step. x_proj: [b, 3H]; w_hh: [H, 3H] packed (r, u, c)."""
    hidden = h.shape[-1]
    hp = matmul(h, w_hh[:, : 2 * hidden])
    xr, xu, xc = jnp.split(x_proj.astype(jnp.float32), 3, axis=-1)
    hr, hu = jnp.split(hp.astype(jnp.float32), 2, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    u = jax.nn.sigmoid(xu + hu)
    hc = matmul(r * h.astype(jnp.float32), w_hh[:, 2 * hidden:])
    c = jnp.tanh(xc + hc.astype(jnp.float32))
    new_h = u * h.astype(jnp.float32) + (1 - u) * c
    return new_h.astype(h.dtype)


def gru(x: jax.Array, lengths: jax.Array, w_ih: jax.Array, w_hh: jax.Array,
        b: Optional[jax.Array] = None, *, reverse: bool = False,
        h0: Optional[jax.Array] = None) -> Tuple[jax.Array, jax.Array]:
    """Full-sequence GRU. x: [b,t,d]; w_ih: [d,3H]; w_hh: [H,3H]."""
    bsz, tmax, _ = x.shape
    hidden = w_hh.shape[0]
    xp = matmul(x.reshape(bsz * tmax, -1), w_ih).reshape(bsz, tmax, 3 * hidden)
    if b is not None:
        xp = xp + b.astype(xp.dtype)
    mask = (jnp.arange(tmax)[None, :] < lengths[:, None])
    h = h0 if h0 is not None else jnp.zeros((bsz, hidden), x.dtype)
    xs = jnp.moveaxis(xp, 1, 0)
    ms = jnp.moveaxis(mask, 1, 0)
    if reverse:
        xs, ms = xs[::-1], ms[::-1]

    def step(state, inp):
        xt, mt = inp
        nh = gru_cell(xt, state, w_hh)
        nh = jnp.where(mt[:, None], nh, state)
        return nh, nh

    final, outs = jax.lax.scan(step, h, (xs, ms))
    if reverse:
        outs = outs[::-1]
    outs = jnp.moveaxis(outs, 0, 1)
    outs = outs * mask[..., None].astype(outs.dtype)
    return outs, final


def simple_rnn(x: jax.Array, lengths: jax.Array, w_ih: Optional[jax.Array],
               w_hh: jax.Array, b: Optional[jax.Array] = None, *,
               act=jnp.tanh, reverse: bool = False,
               h0: Optional[jax.Array] = None) -> Tuple[jax.Array, jax.Array]:
    """Vanilla RNN (reference: gserver RecurrentLayer.cpp). w_ih=None means
    the input is already projected to hidden size (RecurrentLayer contract)."""
    bsz, tmax, _ = x.shape
    hidden = w_hh.shape[0]
    if w_ih is None:
        xp = x
    else:
        xp = matmul(x.reshape(bsz * tmax, -1), w_ih).reshape(bsz, tmax, hidden)
    if b is not None:
        xp = xp + b.astype(xp.dtype)
    mask = (jnp.arange(tmax)[None, :] < lengths[:, None])
    h = h0 if h0 is not None else jnp.zeros((bsz, hidden), x.dtype)
    xs, ms = jnp.moveaxis(xp, 1, 0), jnp.moveaxis(mask, 1, 0)
    if reverse:
        xs, ms = xs[::-1], ms[::-1]

    def step(state, inp):
        xt, mt = inp
        nh = act((xt + matmul(state, w_hh)).astype(jnp.float32)).astype(state.dtype)
        nh = jnp.where(mt[:, None], nh, state)
        return nh, nh

    final, outs = jax.lax.scan(step, h, (xs, ms))
    if reverse:
        outs = outs[::-1]
    outs = jnp.moveaxis(outs, 0, 1)
    return outs * mask[..., None].astype(outs.dtype), final


class MDLSTMState(NamedTuple):
    h: jax.Array
    c: jax.Array


def mdlstm_cell(x_proj: jax.Array, left: MDLSTMState, up: MDLSTMState,
                w_hx: jax.Array, w_hy: jax.Array) -> MDLSTMState:
    """One 2-D LSTM step (reference: gserver/layers/MDLstmLayer.cpp —
    multi-dimensional LSTM, Graves et al.). Five gates packed as
    (i, f_x, f_y, g, o): the cell takes TWO predecessor states, one per
    spatial dimension, each with its own forget gate:

        c = i*g + f_x*c_left + f_y*c_up;  h = o * tanh(c)

    x_proj: [b, 5H] precomputed x@W_ih (+bias)."""
    gates = x_proj + matmul(left.h, w_hx) + matmul(up.h, w_hy)
    i, fx, fy, g, o = jnp.split(gates.astype(jnp.float32), 5, axis=-1)
    i = jax.nn.sigmoid(i)
    fx = jax.nn.sigmoid(fx)
    fy = jax.nn.sigmoid(fy)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    c = i * g + fx * left.c.astype(jnp.float32) + fy * up.c.astype(jnp.float32)
    h = o * jnp.tanh(c)
    return MDLSTMState(h.astype(left.h.dtype), c.astype(left.c.dtype))


def mdlstm(x: jax.Array, w_ih: jax.Array, w_hx: jax.Array, w_hy: jax.Array,
           b: Optional[jax.Array] = None, *, reverse_x: bool = False,
           reverse_y: bool = False) -> jax.Array:
    """2-D multi-dimensional LSTM over a feature map.

    x: [N, H, W, C]; w_ih: [C, 5D]; w_hx/w_hy: [D, 5D] (left/up recurrent
    weights). Returns hidden maps [N, H, W, D]. Scans rows with an inner
    column scan — the j-th cell of row i sees h[i][j-1] (left) and
    h[i-1][j] (up), the MDLstmLayer recurrence. reverse_x/_y flip the scan
    direction per dimension (the layer's 4-direction variants compose from
    flips)."""
    n, hh, ww, _ = x.shape
    d = w_hx.shape[0]
    xp = matmul(x.reshape(n * hh * ww, -1), w_ih).reshape(n, hh, ww, 5 * d)
    if b is not None:
        xp = xp + b.astype(xp.dtype)
    if reverse_y:
        xp = xp[:, ::-1]
    if reverse_x:
        xp = xp[:, :, ::-1]
    xp = jnp.moveaxis(xp, 1, 0)            # [H, N, W, 5D]
    zeros = jnp.zeros((n, d), x.dtype)

    def row_step(prev_row, xrow):
        # prev_row: (h_up [N, W, D], c_up [N, W, D]); xrow: [N, W, 5D]
        def col_step(left, inp):
            xt, h_up, c_up = inp
            nxt = mdlstm_cell(xt, left, MDLSTMState(h_up, c_up), w_hx, w_hy)
            return nxt, nxt
        h_up, c_up = prev_row
        init = MDLSTMState(zeros, zeros)
        cols = (jnp.moveaxis(xrow, 1, 0), jnp.moveaxis(h_up, 1, 0),
                jnp.moveaxis(c_up, 1, 0))
        _, outs = jax.lax.scan(col_step, init, cols)
        new_row = (jnp.moveaxis(outs.h, 0, 1), jnp.moveaxis(outs.c, 0, 1))
        return new_row, new_row[0]

    init_row = (jnp.zeros((n, ww, d), x.dtype), jnp.zeros((n, ww, d), x.dtype))
    _, hmaps = jax.lax.scan(row_step, init_row, xp)    # [H, N, W, D]
    out = jnp.moveaxis(hmaps, 0, 1)                    # [N, H, W, D]
    if reverse_y:
        out = out[:, ::-1]
    if reverse_x:
        out = out[:, :, ::-1]
    return out
