"""Pooling (reference: paddle/gserver/layers/PoolLayer.cpp,
paddle/function/PoolOp（via hl_pooling）, paddle/operators/pool_op.cc,
pool_cudnn_op.cc). NHWC layout; lax.reduce_window maps directly to the TPU
vector unit's windowed reductions.
"""

from typing import Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

IntOr2 = Union[int, Tuple[int, int]]

from paddle_tpu.ops.conv import _pair


def _resolve_pads(x_shape, padding, k, s):
    """Resolve padding to explicit per-dim pairs for reduce_window.
    Accepts "SAME"/"VALID", int, (ph, pw), or ((ph0,ph1),(pw0,pw1))."""
    if isinstance(padding, str):
        return lax.padtype_to_pads(x_shape, (1, k[0], k[1], 1),
                                   (1, s[0], s[1], 1), padding)
    if isinstance(padding, int):
        padding = ((padding, padding), (padding, padding))
    else:
        padding = tuple(padding)
        if isinstance(padding[0], int):
            padding = ((padding[0], padding[0]), (padding[1], padding[1]))
    return [(0, 0), tuple(padding[0]), tuple(padding[1]), (0, 0)]


def max_pool2d(x: jax.Array, ksize: IntOr2, *, stride: IntOr2 = None,
               padding="VALID") -> jax.Array:
    k, s = _pair(ksize), _pair(stride if stride is not None else ksize)
    pads = _resolve_pads(x.shape, padding, k, s)
    return lax.reduce_window(x, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
                             else jnp.iinfo(x.dtype).min,
                             lax.max, (1, k[0], k[1], 1), (1, s[0], s[1], 1), pads)


def avg_pool2d(x: jax.Array, ksize: IntOr2, *, stride: IntOr2 = None,
               padding="VALID", count_include_pad=False) -> jax.Array:
    """Average pooling; excludes padding from the divisor by default
    (matches cuDNN AVERAGE_COUNT_EXCLUDE_PADDING used by the reference)."""
    k, s = _pair(ksize), _pair(stride if stride is not None else ksize)
    pads = _resolve_pads(x.shape, padding, k, s)
    # accumulate in fp32: summing a window of bf16 values loses mantissa
    xf = x.astype(jnp.float32) if x.dtype == jnp.bfloat16 else x
    summed = lax.reduce_window(xf, 0.0, lax.add, (1, k[0], k[1], 1),
                               (1, s[0], s[1], 1), pads)
    if count_include_pad:
        return (summed / (k[0] * k[1])).astype(x.dtype)
    ones = jnp.ones(x.shape[:3] + (1,), summed.dtype)
    counts = lax.reduce_window(ones, 0.0, lax.add, (1, k[0], k[1], 1),
                               (1, s[0], s[1], 1), pads)
    return (summed / counts).astype(x.dtype)


def global_avg_pool2d(x: jax.Array) -> jax.Array:
    """[N,H,W,C] -> [N,C]; fp32 accumulation."""
    return jnp.mean(x, axis=(1, 2),
                    dtype=jnp.float32).astype(x.dtype)


def global_max_pool2d(x: jax.Array) -> jax.Array:
    return jnp.max(x, axis=(1, 2))


def spp(x: jax.Array, pyramid_height: int, pool_type="max") -> jax.Array:
    """Spatial pyramid pooling (reference: gserver/layers/SpatialPyramidPoolLayer.cpp):
    concat of pooled [1x1, 2x2, ... 2^(h-1) bins] flattened per image.

    Output length is fixed at sum(4^lvl)*C regardless of input resolution —
    each level pads the image up to bins*ceil(dim/bins) so the window grid
    yields exactly bins x bins cells (the SPP contract)."""
    n, h, w, c = x.shape
    fn = max_pool2d if pool_type == "max" else avg_pool2d
    outs = []
    for lvl in range(pyramid_height):
        bins = 2 ** lvl
        kh, kw = -(-h // bins), -(-w // bins)
        ph, pw = kh * bins - h, kw * bins - w
        pooled = fn(x, (kh, kw), stride=(kh, kw),
                    padding=((0, ph), (0, pw)))
        outs.append(pooled.reshape(n, -1))
    return jnp.concatenate(outs, axis=1)
