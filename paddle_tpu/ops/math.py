"""Dense linear algebra with TPU dtype policy.

Replaces Matrix::mul → hl_matrix_mul → cuBLAS GEMM
(reference: paddle/math/Matrix.h:476, paddle/cuda/src/hl_cuda_cublas.cc) and
operators/math/math_function.cc. On TPU the MXU natively consumes bfloat16
with float32 accumulation, so the policy is: cast operands to the compute
dtype (flag `compute_dtype`, default bf16), accumulate fp32 via
``preferred_element_type``, return in the params dtype.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core import dtypes


def matmul(a: jax.Array, b: jax.Array, *, out_dtype=None) -> jax.Array:
    """bf16-in / fp32-accumulate matmul on the MXU. Integer operands skip the
    compute-dtype cast (bf16's 8-bit mantissa would round values > 256)."""
    if not (jnp.issubdtype(a.dtype, jnp.floating) and
            jnp.issubdtype(b.dtype, jnp.floating)):
        return jnp.matmul(a, b, preferred_element_type=out_dtype)
    cdt = dtypes.compute_dtype()
    out_dtype = out_dtype or a.dtype
    out = jnp.matmul(a.astype(cdt), b.astype(cdt),
                     preferred_element_type=jnp.float32)
    return out.astype(out_dtype)


def linear(x: jax.Array, w: jax.Array, b: jax.Array = None) -> jax.Array:
    """x @ w (+ b) — FullyConnectedLayer forward
    (reference: paddle/gserver/layers/FullyConnectedLayer.cpp:73-100)."""
    out = matmul(x, w)
    if b is not None:
        out = out + b.astype(out.dtype)
    return out


def outer(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.einsum("i,j->ij", a, b)
