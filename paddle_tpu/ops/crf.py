"""Linear-chain CRF: log-likelihood and Viterbi decoding as lax.scan
dynamic programs.

Reference: paddle/operators/linear_chain_crf_op.cc (forward/alpha recursion,
the (D+2)-row transition parameterization: w[0]=start weights a, w[1]=end
weights b, w[2:]=transition matrix), paddle/operators/crf_decoding_op.cc
(Viterbi), paddle/gserver/layers/CRFLayer.cpp + LinearChainCRF.cpp.

TPU design: padded batch-major emissions [B, T, N] + per-sequence lengths,
one scan over time (each step is a dense [B, N, N] logsumexp/max — MXU/VPU
friendly), instead of the reference's per-sequence CPU loops over LoD slices.
Gradients come from jax.grad through the scan (the reference hand-codes the
beta recursion in linear_chain_crf_op.h).
"""

import jax
import jax.numpy as jnp


def _split_transitions(transitions: jax.Array):
    """transitions: [N+2, N] — row 0 start, row 1 end, rows 2: pairwise
    (trans[i, j] = score of moving from tag i to tag j)."""
    return transitions[0], transitions[1], transitions[2:]


def crf_log_norm(emissions: jax.Array, lengths: jax.Array,
                 transitions: jax.Array) -> jax.Array:
    """log Z per sequence. emissions [B, T, N] float, lengths [B]."""
    start, end, trans = _split_transitions(transitions)
    em = emissions.astype(jnp.float32)
    B, T, N = em.shape
    alpha0 = start[None, :] + em[:, 0]

    def step(alpha, inputs):
        e_t, t = inputs
        # [B, prev, next]: alpha + trans, logsumexp over prev
        scores = alpha[:, :, None] + trans[None].astype(jnp.float32)
        new = jax.scipy.special.logsumexp(scores, axis=1) + e_t
        alive = (t < lengths)[:, None]
        return jnp.where(alive, new, alpha), None

    ts = jnp.arange(1, T)
    alpha, _ = jax.lax.scan(step, alpha0, (em[:, 1:].swapaxes(0, 1), ts))
    return jax.scipy.special.logsumexp(alpha + end[None, :].astype(jnp.float32),
                                       axis=-1)


def crf_sequence_score(emissions: jax.Array, tags: jax.Array,
                       lengths: jax.Array, transitions: jax.Array) -> jax.Array:
    """Unnormalized score of the given tag paths. tags [B, T] int."""
    start, end, trans = _split_transitions(transitions)
    em = emissions.astype(jnp.float32)
    B, T, N = em.shape
    tags = tags.astype(jnp.int32)
    step_idx = jnp.arange(T)[None, :]
    valid = step_idx < lengths[:, None]                       # [B, T]
    emit = jnp.take_along_axis(em, tags[..., None], axis=-1)[..., 0]
    score = jnp.sum(jnp.where(valid, emit, 0.0), axis=1)
    score = score + start.astype(jnp.float32)[tags[:, 0]]
    pair = trans.astype(jnp.float32)[tags[:, :-1], tags[:, 1:]]   # [B, T-1]
    pair_valid = step_idx[:, 1:] < lengths[:, None]
    score = score + jnp.sum(jnp.where(pair_valid, pair, 0.0), axis=1)
    last = jnp.take_along_axis(tags, (lengths - 1)[:, None], axis=1)[:, 0]
    return score + end.astype(jnp.float32)[last]


def crf_log_likelihood(emissions: jax.Array, tags: jax.Array,
                       lengths: jax.Array, transitions: jax.Array) -> jax.Array:
    """Per-sequence log p(tags | emissions). Negate for the training cost
    (reference: linear_chain_crf_op.cc computes the same -log-likelihood)."""
    return (crf_sequence_score(emissions, tags, lengths, transitions)
            - crf_log_norm(emissions, lengths, transitions))


def crf_decode(emissions: jax.Array, lengths: jax.Array,
               transitions: jax.Array):
    """Viterbi decode → (best_tags [B, T] int32, best_score [B]).
    Padded steps repeat the final tag (reference crf_decoding_op zeroes
    them; callers mask by lengths either way)."""
    start, end, trans = _split_transitions(transitions)
    em = emissions.astype(jnp.float32)
    B, T, N = em.shape
    trans_f = trans.astype(jnp.float32)
    delta0 = start[None, :].astype(jnp.float32) + em[:, 0]

    def fwd(delta, inputs):
        e_t, t = inputs
        scores = delta[:, :, None] + trans_f[None]            # [B, prev, next]
        bp = jnp.argmax(scores, axis=1).astype(jnp.int32)     # [B, next]
        new = jnp.max(scores, axis=1) + e_t
        alive = (t < lengths)[:, None]
        return jnp.where(alive, new, delta), bp

    ts = jnp.arange(1, T)
    delta, bps = jax.lax.scan(fwd, delta0, (em[:, 1:].swapaxes(0, 1), ts))
    final = delta + end[None, :].astype(jnp.float32)
    last_tag = jnp.argmax(final, axis=-1).astype(jnp.int32)
    best_score = jnp.max(final, axis=-1)

    def back(tag, inputs):
        bp, t = inputs
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        # step t only happened for sequences with t < length
        tag_prev = jnp.where(t < lengths, prev, tag)
        return tag_prev, tag

    first, tags_rev = jax.lax.scan(back, last_tag, (bps, ts), reverse=True)
    tags = jnp.concatenate([first[None], tags_rev], axis=0)   # [T, B]
    return tags.swapaxes(0, 1), best_score
