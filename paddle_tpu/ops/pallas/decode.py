"""Flash-decode over the paged KV pool + fused sampling epilogue.

The serving engine's per-token hot path (``transformer.decode_step_paged``)
is gather-heavy under XLA: every step materializes a ``[B, T, Hkv, Dh]``
logical KV view out of the block pool, re-reads it for the score einsum,
and keeps a ``[B, H, T]`` score tensor in HBM between softmax stages.
``flash_decode_attention`` is the Pallas replacement, built around the
HEAD-MAJOR pool layout ``[Hkv, M, Dh]`` (kv-head leading — the standard
TPU paged-KV layout ``transformer.init_block_pool`` adopted with it):

- grid ``(slot, kv-head, page-step)``; the page table and per-slot
  positions ride as **scalar-prefetch** operands
  (``pltpu.PrefetchScalarGridSpec``), so each grid step's K/V block is
  PLACED by indexing the pool's BlockSpec through ``pages[b, j]`` —
  Mosaic's DMA engine streams exactly the slot's MAPPED
  ``(1, block_size, Dh)`` blocks, and no gathered logical view or
  batch-wide score tensor ever exists in HBM;
- each step's partial scores (a ``Dh``-contraction — bitwise the same
  dot the one-shot einsum computes per column) land in a VMEM score-row
  scratch, the V block in a VMEM value scratch; the LAST page step
  masks by the slot's position and applies ONE exact softmax (the same
  max/exp/sum/divide chain ``jax.nn.softmax`` evaluates — written out
  explicitly because ``jax.nn.softmax`` carries a ``stop_gradient``
  Mosaic has no lowering for) before the single ``p @ V`` dot.

Decode's score row is ``O(T)`` per program (one query token), not the
``O(T²)`` of prefill attention, so the whole masked row fits VMEM and
the exact softmax — not an online-rescaling chain — is what keeps the
interpret-mode kernel BITWISE-identical to the XLA paged path on aligned
fp32 shapes (pinned in tests/test_pallas_decode.py): an online softmax
normalizes ``(p@v)/l`` where XLA computes ``(p/l)@v``, a rounding
difference the streaming buys nothing for at decode shapes.

Every BlockSpec in this file is **Mosaic-legal** under the TPU tiling
rule (the last two block dims must each be divisible by the dtype's
native tile — (8, 128) fp32, (16, 128) bf16, (32, 128) int8 — or equal
the array dims): the head-major pool makes each program's block
``(1, block_size, Dh)`` with the singleton on a LEADING dim, quantized
scale columns ride as ``[Hkv, M, 1]`` views (trailing singleton ==
array dim), and the page/pos/seed/temperature/top-k vectors live in
SMEM via scalar prefetch where no tiling rule applies. Whether a given
shape ACTUALLY lowers is never assumed: dispatch asks
:func:`decode_lowering_ok` — a cached deviceless XLA:TPU lowering probe
of the real kernel call — and falls back to the XLA path on a refusal
(``serving_bench.py --tpu-check`` asserts the probes hold and stamps
the legal BlockSpecs + VMEM estimates into its artifact).

``fused_sample`` is the epilogue: greedy / temperature / top-k sampling
(``serving/sampling.sample_tokens`` semantics, per-slot runtime vectors)
as a Pallas kernel, one program per batch row, so the compiled decode
step emits ``[B] int32`` token ids with no full-vocab sort: the runtime-k
threshold is found by a 32-step radix binary search over the
order-preserving integer image of the logits, and the categorical draw is
a Gumbel-max over hashed counter-based uniforms (``pltpu.prng`` is
TPU-only; the hash keeps the kernel interpretable on CPU). Greedy rows
and the kept top-k SET match ``sample_tokens`` exactly; the categorical
draw itself matches in distribution, not per-id (different RNG stream —
the contract tests assert the distribution, greedy ties, and membership).
Counting/argmax reductions run over exact small-integer fp32 images
(integer reductions have no Mosaic lowering; fp32 is exact below 2^24,
far above any vocab).

Dispatch resolves through the package-wide ``PADDLE_TPU_PALLAS`` policy
(``ops/pallas/policy.py``); the pure-XLA gather path in
``transformer.decode_step_paged`` remains the always-available fallback.
"""

import functools
import math
import warnings
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops.pallas.attention import VMEM_BYTES

NEG_INF = -1e30

# The pool layout this kernel generation is built for — the key prefix
# of the MEASURED_* tuning tables, so sweep entries taken on one layout
# are never consulted against another (a pre-relayout slot-major entry
# would otherwise advise tiles for a pool shape that no longer exists).
POOL_LAYOUT = "head_major"

_warned_fallback = set()        # modes that already warned (once per mode)

# cached verdicts of the deviceless Mosaic lowering probes, keyed by
# (kernel kind, shape/dtype signature) — a probe is one tiny XLA:TPU
# lowering with no chip attached (~a second, paid once per signature).
# Refusals keep their diagnostic in _LOWERING_DETAIL (surfaced by the
# once-per-key warning below and serving_bench --tpu-check), so a
# silent XLA fallback on a real chip is never undiagnosable.
_LOWERING_CACHE = {}
_LOWERING_DETAIL = {}


def kernels_dispatchable(mode: str) -> bool:
    """Whether the resolved ``PADDLE_TPU_PALLAS`` mode may place the
    serving kernels in a compiled program on the current default
    backend. ``interpret`` always can (the interpreter runs anywhere);
    ``on`` requires a TPU backend — off-TPU it falls back to the XLA
    path with a once-per-mode warning instead of failing the first
    compile. On TPU the per-site guards still apply on top: the VMEM
    ``*_kernel_fits`` budgets and the :func:`decode_lowering_ok` /
    ``prefill.prefill_lowering_ok`` Mosaic probes (the head-major pool
    relayout made the kernels lowerable; the probe — not a constant —
    is what asserts it for the actual shapes)."""
    if mode == "interpret":
        return True
    if mode != "on":
        return False
    if jax.default_backend() != "tpu":
        if mode not in _warned_fallback:
            _warned_fallback.add(mode)
            warnings.warn(
                "PADDLE_TPU_PALLAS resolved 'on' but the default "
                "backend is not TPU; serving falls back to the "
                "pure-XLA path (use 'interpret' to exercise the "
                "kernels off-TPU).",
                RuntimeWarning, stacklevel=2)
        return False
    return True


def mosaic_lowerable(key, build) -> bool:
    """Cached deviceless XLA:TPU lowering probe: ``build()`` must
    return (fn, abstract args); the probe lowers ``jit(fn)`` for the
    TPU platform with no device attached and records whether Mosaic
    accepts the kernel. This is the real successor of the old
    ``MOSAIC_LOWERABLE`` constant — per kernel, per shape signature,
    measured instead of asserted."""
    if key in _LOWERING_CACHE:
        return _LOWERING_CACHE[key]
    try:
        import jax.export
        fn, args = build()
        jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)
        ok = True
    except Exception as e:                            # noqa: BLE001
        ok = False
        _LOWERING_DETAIL[key] = f"{type(e).__name__}: {str(e)[:300]}"
        warnings.warn(
            f"Pallas kernel {key[0]!r} failed the Mosaic lowering "
            f"probe (falls back to the XLA path): "
            f"{_LOWERING_DETAIL[key]}", RuntimeWarning, stacklevel=2)
    _LOWERING_CACHE[key] = ok
    return ok


def lowering_failures(kind: Optional[str] = None):
    """Diagnostics of every probe REFUSAL so far (``{key: detail}``),
    optionally filtered by kernel kind — what ``serving_bench.py
    --tpu-check`` surfaces next to a failed ``*_ok`` boolean."""
    return {k: v for k, v in _LOWERING_DETAIL.items()
            if kind is None or k[0] == kind}


def _kv_store_dims(Dh: int, dtype, kv_dtype: str):
    """(stored last-dim, stored itemsize, dtype-key name) of the pool's
    KV arrays under a KV storage width: quantized pools store int8
    bytes (nibble-packed for int4) with the fp32 scale tables riding
    beside them."""
    if kv_dtype in (None, "none"):
        return Dh, jnp.dtype(dtype).itemsize, jnp.dtype(dtype).name
    if kv_dtype == "int4":
        return Dh // 2, 1, "int4"
    return Dh, 1, "int8"


def decode_lowering_ok(M: int, P: int, block_size: int, Hkv: int,
                       G: int, Dh: int, dtype,
                       kv_dtype: str = "none",
                       q_dtype=None) -> bool:
    """Mosaic lowering probe for :func:`flash_decode_attention` at the
    given pool geometry (deviceless, cached). ``mode="on"`` dispatch
    asks this before placing the kernel in a program so an unlowerable
    shape degrades to the XLA path instead of failing the compile.
    ``q_dtype`` is the ACTIVATION dtype the caller's q arrives in
    (tiling is dtype-dependent, so the probe must lower the very
    program the dispatch would build); it defaults to the pool dtype —
    right for fp pools, but quantized-pool callers must pass their
    model dtype explicitly."""
    if q_dtype is None:
        q_dtype = dtype if kv_dtype in (None, "none") else jnp.float32
    Dh_st, _, name = _kv_store_dims(Dh, dtype, kv_dtype)
    quant = kv_dtype not in (None, "none")
    key = ("decode", M, P, int(block_size), Hkv, G, Dh, name,
           jnp.dtype(q_dtype).name)

    def build():
        kv = jax.ShapeDtypeStruct(
            (Hkv, M, Dh_st),
            jnp.int8 if quant else jnp.dtype(dtype))
        sc = jax.ShapeDtypeStruct((Hkv, M), jnp.float32)
        args = [jax.ShapeDtypeStruct((2, Hkv, G, Dh),
                                     jnp.dtype(q_dtype)),
                kv, kv,
                jax.ShapeDtypeStruct((2, P), jnp.int32),
                jax.ShapeDtypeStruct((2,), jnp.int32)]
        fn = functools.partial(
            flash_decode_attention, block_size=block_size,
            kv_dtype=kv_dtype)
        if quant:
            return (lambda q, k, v, pg, ps, ks, vs: fn(
                q, k, v, pg, ps, k_scale=ks, v_scale=vs),
                args + [sc, sc])
        return fn, args

    return mosaic_lowerable(key, build)


def sample_lowering_ok(B: int, V: int) -> bool:
    """Mosaic lowering probe for :func:`fused_sample` (cached,
    deviceless) — the epilogue's dispatch guard on TPU."""
    key = ("sample", B, V)

    def build():
        return fused_sample, [
            jax.ShapeDtypeStruct((B, V), jnp.float32),
            jax.ShapeDtypeStruct((), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.float32),
            jax.ShapeDtypeStruct((B,), jnp.int32)]

    return mosaic_lowerable(key, build)


# ---------------------------------------------------------------------------
# tile selection
# ---------------------------------------------------------------------------

# measured-best (block_size, pages-per-grid-step) keyed (POOL layout,
# span bucket, head_dim, dtype_name) — filled from on-chip sweeps
# (benchmarks/tune_flash_blocks.py --decode); consulted before the
# analytic default. The layout key guarantees entries swept on another
# pool layout are never consulted. The block_size entry is ADVISORY
# for engine configuration (the pool layout is the engine's choice);
# the kernel consults the tile only when the entry's block_size matches
# the pool it was actually handed. Span buckets are powers of two
# (lookup rounds up).
MEASURED_DECODE = {
    # (POOL_LAYOUT, span_bucket, head_dim, dtype): (block_size, tile)
}


def decode_vmem_bytes(M: int, P: int, block_size: int, G: int, Dh: int,
                      itemsize: int, kv_dtype: str = "none",
                      tile: int = 1) -> int:
    """Upper-bound VMEM residency of one (slot, kv-head) grid program
    at the head-major layout: the score-row and V scratch buffers
    spanning the slot's ``T = P·bs`` logical positions (scores counted
    twice — the softmax exp/normalize temporaries are row-sized), the
    q/out tiles, and the ``tile`` streamed K/V blocks in flight at
    their STORED width (double-buffered by the pipeline; quantized
    pools add the fp32 scale columns). The pool itself never sits in
    VMEM — scalar-prefetched placement streams only the mapped blocks —
    so the budget no longer scales with the pool size ``M``."""
    del M                        # streamed per-block, never resident
    T = P * int(block_size)
    if kv_dtype in (None, "none"):
        blk = int(block_size) * Dh * itemsize
    else:
        Dh_st = Dh // 2 if kv_dtype == "int4" else Dh
        blk = int(block_size) * (Dh_st + 4)      # values + scale col
    return (2 * G * T * 4                # score row + softmax temps
            + T * Dh * 4                 # V scratch
            + 2 * G * Dh * 4             # q, out
            + 4 * tile * blk)            # 2x tile in-flight K/V blocks


def decode_kernel_fits(M: int, P: int, block_size: int, G: int, Dh: int,
                       dtype, kv_dtype: str = "none") -> bool:
    """Whether the flash-decode working set fits the VMEM budget — the
    dispatch guard: ``mode="on"`` falls back to the XLA gather path when
    this says no, rather than letting Mosaic fail opaquely."""
    itemsize = jnp.dtype(dtype).itemsize
    tile = select_decode_tile(P, block_size, Dh, dtype, kv_dtype)
    return decode_vmem_bytes(M, P, block_size, G, Dh, itemsize,
                             kv_dtype, tile=tile) <= VMEM_BYTES


def select_decode_tile(P: int, block_size: int, head_dim: int,
                       dtype, kv_dtype: str = "none") -> int:
    """Pages streamed per grid step (each page is one scalar-prefetch-
    placed BlockSpec stream — ``tile`` of them run per step, amortizing
    grid overhead): the measured table first (when its advisory
    block_size matches the pool's), then the analytic default — the
    largest power-of-two divisor of P keeping the per-step stream at
    <= 256 rows (past that the extra in-flight blocks stop paying and
    VMEM pressure grows). Quantized pools key the measured table by
    their storage name ("int8"/"int4")."""
    span = P * int(block_size)
    bucket = 1 << max(0, (span - 1)).bit_length()     # next pow2 >= span
    _, _, name = _kv_store_dims(head_dim, dtype, kv_dtype)
    found = MEASURED_DECODE.get((POOL_LAYOUT, bucket, head_dim, name))
    if found and found[0] == block_size and P % found[1] == 0:
        return int(found[1])
    tile = 1
    while (tile * 2 <= P and P % (tile * 2) == 0
           and tile * 2 * block_size <= 256):
        tile *= 2
    return tile


# ---------------------------------------------------------------------------
# flash-decode attention kernel
# ---------------------------------------------------------------------------


def _widen_block(ref, scale_ref, kv_dtype):
    """One streamed pool block ``(1, bs, Dh-stored)`` widened to fp32
    ``[bs, Dh]`` in-register — the fused dequant. The op chain is
    EXACTLY the XLA quantized path's (``ops/q8.dequantize_kv``): exact
    integer unpack, astype(f32), broadcast row-scale multiply — so the
    kernel stays bitwise the XLA path whatever the storage width (the
    nibble unpack is all-integer shift arithmetic, bitwise on any
    backend)."""
    from paddle_tpu.ops import q8 as ops_q8
    rows = ref[0]
    if kv_dtype in (None, "none"):
        return rows.astype(jnp.float32)
    if kv_dtype == "int4":
        rows = ops_q8.unpack_int4(rows)
    return (rows.astype(jnp.float32)
            * scale_ref[0, :, 0][:, None])


def _decode_kernel(pages_ref, pos_ref, q_ref, *refs, block_size, P,
                   tile, G, Dh, scale, kv_dtype):
    """One (slot, kv-head, page-step) program. ``pages``/``pos`` are
    scalar-prefetched (SMEM); q/o blocks are ``(1, 1, G, Dh)``; each of
    the ``tile`` K/V streams is a ``(1, bs, Dh-stored)`` pool block
    placed through ``pages[b, j·tile + t]`` (+ a ``(1, bs, 1)`` scale
    column per stream for quantized pools). Page step ``j`` writes its
    partial scores (a Dh-contraction, bitwise the one-shot einsum's
    columns) and fp32-widened V rows into VMEM scratch at the logical
    offset; the LAST step masks by the slot's position and mirrors the
    XLA gather path's op chain exactly (divide-by-sqrt(Dh), -1e30 mask,
    max/exp/sum/divide softmax) so aligned fp32 shapes — and quantized
    pools, whose dequant chain is elementwise-identical — reproduce its
    logits bitwise."""
    quant = kv_dtype not in (None, "none")
    krefs = refs[:tile]
    vrefs = refs[tile:2 * tile]
    n_in = 2 * tile + (2 * tile if quant else 0)
    if quant:
        ksrefs = refs[2 * tile:3 * tile]
        vsrefs = refs[3 * tile:4 * tile]
    else:
        ksrefs = vsrefs = (None,) * tile
    o_ref, s_scr, v_scr = refs[n_in], refs[n_in + 1], refs[n_in + 2]
    b = pl.program_id(0)
    j = pl.program_id(2)
    bs = int(block_size)
    T = P * bs
    q = q_ref[0, 0].astype(jnp.float32)                  # [G, Dh]
    for t in range(tile):           # static unroll: tile pages/step
        ks = _widen_block(krefs[t], ksrefs[t], kv_dtype)
        vs = _widen_block(vrefs[t], vsrefs[t], kv_dtype)
        s = jax.lax.dot_general(q, ks, (((1,), (1,)), ((), ())))
        off = (j * tile + t) * bs
        s_scr[:, pl.ds(off, bs)] = s
        v_scr[pl.ds(off, bs), :] = vs

    @pl.when(j == P // tile - 1)
    def _finish():
        s = s_scr[...] / scale
        valid = (jax.lax.broadcasted_iota(jnp.int32, (G, T), 1)
                 <= pos_ref[b])                          # logical mask
        s = jnp.where(valid, s, NEG_INF)
        # jax.nn.softmax's exact chain, written out (its stop_gradient
        # has no Mosaic lowering; numerically it is the identity)
        m = jnp.max(s, axis=-1, keepdims=True)
        e = jnp.exp(s - m)
        p = e / jnp.sum(e, axis=-1, keepdims=True)
        o_ref[0, 0] = jax.lax.dot_general(
            p, v_scr[...], (((1,), (0,)), ((), ())))


def flash_decode_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                           pages: jax.Array, pos: jax.Array, *,
                           block_size: int,
                           tile: Optional[int] = None,
                           k_scale: Optional[jax.Array] = None,
                           v_scale: Optional[jax.Array] = None,
                           kv_dtype: str = "none",
                           interpret: bool = False) -> jax.Array:
    """One decode step's attention straight off the head-major paged
    pool.

    q [B, Hkv, G, Dh] (grouped-query layout, G = n_heads/kv_heads),
    k/v the flat pool [Hkv, M, Dh], pages [B, P] int32 physical block
    ids, pos [B] int32 per-slot positions → fp32 [B, Hkv, G, Dh]. The
    caller owns the pool WRITE of the step's new k/v (a cheap scatter)
    and must perform it before this reads — position ``pos[b]`` attends
    to itself.

    Quantized pools (``kv_dtype`` "int8"/"int4") pass the int8 value
    arrays ([Hkv, M, Dh] or nibble-packed [Hkv, M, Dh//2]) plus the
    per-(head, position) fp32 scale tables ``k_scale``/``v_scale``
    [Hkv, M]: blocks stream into VMEM at their stored width and the
    dequant multiply runs in-register — history crosses HBM at 1 (int8)
    or 1/2 (int4) byte/elt.

    Grid (slot, kv-head, page-step) with ``pages``/``pos`` scalar-
    prefetched; the per-program working set must pass
    ``decode_kernel_fits`` and the shape must pass
    ``decode_lowering_ok`` (the dispatch in ``decode_step_paged``
    guards both and falls back to XLA)."""
    B, Hkv, G, Dh = q.shape             # Dh is always the LOGICAL dim
    quant = kv_dtype not in (None, "none")
    M = k.shape[1]
    P = pages.shape[1]
    bs = int(block_size)
    if quant and (k_scale is None or v_scale is None):
        raise ValueError(f"kv_dtype={kv_dtype} needs k_scale/v_scale")
    if tile is None:
        tile = select_decode_tile(P, bs, Dh, k.dtype, kv_dtype)
    if P % tile:
        raise ValueError(f"flash_decode: tile {tile} must divide the "
                         f"page-vector length {P}")
    tile = int(tile)
    Dh_st = k.shape[-1]                 # stored last dim (packed int4)
    T = P * bs
    kernel = functools.partial(
        _decode_kernel, block_size=bs, P=P, tile=tile, G=G, Dh=Dh,
        scale=math.sqrt(Dh), kv_dtype=kv_dtype if quant else "none")

    def kv_spec(t):
        return pl.BlockSpec(
            (1, bs, Dh_st),
            lambda b, h, j, pg, ps, t=t: (h, pg[b, j * tile + t], 0))

    def sc_spec(t):
        return pl.BlockSpec(
            (1, bs, 1),
            lambda b, h, j, pg, ps, t=t: (h, pg[b, j * tile + t], 0))

    in_specs = ([pl.BlockSpec((1, 1, G, Dh),
                              lambda b, h, j, pg, ps: (b, h, 0, 0))]
                + [kv_spec(t) for t in range(tile)] * 2)
    args = [q] + [k] * tile + [v] * tile
    if quant:
        in_specs += [sc_spec(t) for t in range(tile)] * 2
        args += ([k_scale.reshape(Hkv, M, 1)] * tile
                 + [v_scale.reshape(Hkv, M, 1)] * tile)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Hkv, P // tile),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, G, Dh),
                               lambda b, h, j, pg, ps: (b, h, 0, 0)),
        scratch_shapes=[pltpu.VMEM((G, T), jnp.float32),
                        pltpu.VMEM((T, Dh), jnp.float32)],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, G, Dh), jnp.float32),
        interpret=interpret,
    )(pages.astype(jnp.int32), jnp.asarray(pos, jnp.int32).reshape(B),
      *args)


# ---------------------------------------------------------------------------
# fused sampling epilogue
# ---------------------------------------------------------------------------


def _sortable_key(v: jax.Array) -> jax.Array:
    """fp32 -> uint32 order-preserving image (the radix-sort key map):
    positive floats get the sign bit set, negative floats flip every
    bit, so unsigned comparisons order exactly like float compares."""
    u = jax.lax.bitcast_convert_type(v, jnp.uint32)
    flip = ((u >> 31) * jnp.uint32(0x7FFFFFFF)) | jnp.uint32(0x80000000)
    return u ^ flip


def _kth_key(keys: jax.Array, k: jax.Array) -> jax.Array:
    """The k-th largest of ``keys`` [1, V] uint32 (k >= 1, traced) by
    32-step binary search on the integer threshold — count(keys >= t)
    is monotone, so the invariant count(>= lo) >= k pins lo to the
    exact k-th value after the interval collapses. O(32·V) compares, no
    sort (lax.sort has no Mosaic lowering; this runs anywhere). The
    count sums an fp32 0/1 image — exact below 2^24, far above any
    vocab — because integer reductions have no Mosaic lowering
    either."""
    kf = k.astype(jnp.float32)

    def body(_, lh):
        lo, hi = lh
        d = hi - lo
        mid = lo + (d >> 1) + (d & jnp.uint32(1))   # ceil, overflow-safe
        cnt = jnp.sum((keys >= mid).astype(jnp.float32))
        take = cnt >= kf
        return (jnp.where(take, mid, lo),
                jnp.where(take, hi, mid - jnp.uint32(1)))
    lo, _ = jax.lax.fori_loop(
        0, 32, body, (jnp.uint32(0), jnp.uint32(0xFFFFFFFF)))
    return lo


def _hash_uniform(seed: jax.Array, row: jax.Array,
                  shape: Tuple[int, ...]) -> jax.Array:
    """Counter-based uniforms in (0, 1): a splitmix-style integer hash
    of (seed, row, lane) — deterministic for a given seed, independent
    across rows and lanes, and pure jnp (runs under interpret and
    Mosaic alike, unlike the TPU-only pltpu PRNG)."""
    lane = jax.lax.broadcasted_iota(jnp.uint32, shape, len(shape) - 1)
    h = (seed.astype(jnp.uint32)
         + row.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
         + (lane + jnp.uint32(1)) * jnp.uint32(0x85EBCA6B))
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x7FEB352D)
    h = h ^ (h >> 15)
    h = h * jnp.uint32(0x846CA68B)
    h = h ^ (h >> 16)
    return ((h >> 8).astype(jnp.float32) + 0.5) * (1.0 / (1 << 24))


def _first_argmax(x: jax.Array, iota: jax.Array) -> jax.Array:
    """First-index argmax over the last axis ([1, V] -> scalar) — the
    ``jnp.argmax`` tie convention, written as max+where+min because
    ``lax.argmax`` has no Mosaic lowering. ``iota`` is the fp32 lane
    index (exact below 2^24; integer min-reductions don't lower)."""
    m = jnp.max(x, axis=-1, keepdims=True)
    V = x.shape[-1]
    return jnp.min(jnp.where(x == m, iota, float(V))).astype(jnp.int32)


def _sample_kernel(seed_ref, temp_ref, topk_ref, logits_ref, o_ref):
    """One batch row: greedy argmax, radix top-k threshold, temperature
    scale, Gumbel-max categorical — ``sample_tokens`` semantics with no
    full-vocab sort and no second dispatch. The per-row controls are
    scalar-prefetched (SMEM); logits ride as a ``(1, 1, V)`` block of
    the ``[B, 1, V]`` view (tiling-legal: the trailing two block dims
    equal the array dims)."""
    row = pl.program_id(0)
    v = logits_ref[0, 0].astype(jnp.float32)[None, :]     # [1, V]
    V = v.shape[-1]
    iota = jax.lax.broadcasted_iota(jnp.float32, (1, V), 1)
    greedy = _first_argmax(v, iota)
    k = jnp.clip(topk_ref[row], 0, V)
    keys = _sortable_key(v)
    kstar = _kth_key(keys, jnp.maximum(k, 1))
    keep = (k <= 0) | (keys >= kstar)     # ties at the threshold survive
    z = jnp.where(keep, v, -jnp.inf)
    temp = temp_ref[row]
    z = z / jnp.where(temp > 0, temp, 1.0)
    g = -jnp.log(-jnp.log(_hash_uniform(seed_ref[0], row, (1, V))))
    sampled = _first_argmax(z + g, iota)
    pick = jnp.where(temp > 0, sampled, greedy).astype(jnp.int32)
    o_ref[...] = jnp.reshape(pick, (1, 1, 1))


def fused_sample(logits: jax.Array, seed: jax.Array,
                 temperature: jax.Array, top_k: jax.Array, *,
                 interpret: bool = False) -> jax.Array:
    """Sampling epilogue kernel: logits [B, V] fp32, scalar int32
    ``seed``, per-slot runtime ``temperature`` [B] / ``top_k`` [B] →
    sampled ids [B] int32. Greedy rows (temperature <= 0) and the kept
    top-k set match ``serving/sampling.sample_tokens`` exactly; the
    categorical draw matches in distribution (hash-Gumbel stream, not
    jax.random's). Seed/temperature/top-k ride as scalar prefetch, so
    the only tiled operand is the logits view ``[B, 1, V]``."""
    B, V = logits.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B,),
        in_specs=[pl.BlockSpec((1, 1, V),
                               lambda b, sd, tp, tk: (b, 0, 0))],
        out_specs=pl.BlockSpec((1, 1, 1),
                               lambda b, sd, tp, tk: (b, 0, 0)),
    )
    out = pl.pallas_call(
        _sample_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, 1, 1), jnp.int32),
        interpret=interpret,
    )(jnp.reshape(jnp.asarray(seed, jnp.int32), (1,)),
      jnp.asarray(temperature, jnp.float32).reshape(B),
      jnp.asarray(top_k, jnp.int32).reshape(B),
      logits.reshape(B, 1, V))
    return out[:, 0, 0]


def fused_spec_verify(logits: jax.Array, draft: jax.Array,
                      seed: jax.Array, temperature: jax.Array,
                      top_k: jax.Array, valid: jax.Array, *,
                      interpret: bool = False):
    """Speculative-decoding accept/reject epilogue: the PR-9
    ``fused_sample`` kernel run once per VERIFY-WINDOW row (logits
    [B, W, V] flattened to [B·W, V] — per-slot temperature/top_k
    broadcast over the window) followed by the accept fold
    (``serving.sampling.spec_accept``: leading draft-match run + one
    correction/bonus token, capped to ``valid`` rows). Greedy rows are
    the kernel's exact first-index argmax, so the fused path emits
    bitwise the ``spec_verify_tokens`` greedy tokens — the spec
    engine's bitwise-greedy contract holds on either epilogue.
    Returns (sampled [B, W] int32, n_emitted [B] int32)."""
    from paddle_tpu.serving import sampling as _sampling
    B, W, V = logits.shape
    sampled = fused_sample(
        logits.reshape(B * W, V), seed,
        jnp.repeat(temperature, W), jnp.repeat(top_k, W),
        interpret=interpret).reshape(B, W)
    return sampled, _sampling.spec_accept(sampled, draft, valid)
