"""Flash-decode over the paged KV pool + fused sampling epilogue.

The serving engine's per-token hot path (``transformer.decode_step_paged``)
is gather-heavy under XLA: every step materializes a ``[B, T, Hkv, Dh]``
logical KV view out of the block pool, re-reads it for the score einsum,
and keeps a ``[B, H, T]`` score tensor in HBM between softmax stages.
``flash_decode_attention`` is the Pallas replacement: one grid program per
(slot, kv-head) resolves the slot's page-table indices INSIDE the kernel
and streams the mapped K/V blocks straight from the pool into VMEM — no
gathered logical view and no batch-wide score tensor ever exist in HBM.
Per-slot position masking is fused in, accumulation is fp32.

Decode's score row is ``O(T)`` per program (one query token), not the
``O(T²)`` of prefill attention, so the whole masked row fits VMEM and the
kernel applies ONE exact softmax to it (the same max/exp/sum/divide chain
``jax.nn.softmax`` runs) instead of the prefill flash kernel's
online-softmax rescaling chain. That choice is what makes the
interpret-mode kernel BITWISE-identical to the XLA paged path on aligned
fp32 shapes (pinned in tests/test_pallas_decode.py): an online softmax
normalizes ``(p@v)/l`` where XLA computes ``(p/l)@v``, a rounding
difference the streaming buys nothing for at decode shapes.

``fused_sample`` is the epilogue: greedy / temperature / top-k sampling
(``serving/sampling.sample_tokens`` semantics, per-slot runtime vectors)
as a Pallas kernel, one program per batch row, so the compiled decode
step emits ``[B] int32`` token ids with no full-vocab sort: the runtime-k
threshold is found by a 32-step radix binary search over the
order-preserving integer image of the logits, and the categorical draw is
a Gumbel-max over hashed counter-based uniforms (``pltpu.prng`` is
TPU-only; the hash keeps the kernel interpretable on CPU). Greedy rows
and the kept top-k SET match ``sample_tokens`` exactly; the categorical
draw itself matches in distribution, not per-id (different RNG stream —
the contract tests assert the distribution, greedy ties, and membership).

Dispatch resolves through the package-wide ``PADDLE_TPU_PALLAS`` policy
(``ops/pallas/policy.py``); the pure-XLA gather path in
``transformer.decode_step_paged`` remains the always-available fallback.
"""

import functools
import math
import warnings
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops.pallas.attention import VMEM_BYTES

NEG_INF = -1e30

# Whether the SERVING kernels (flash_decode_attention, fused_sample,
# and ops/pallas/prefill.py's pair) can lower through Mosaic to real
# TPU hardware in this jax version: they cannot — their per-slot/
# per-head block layouts put a 1 in the second-to-last block dim of
# multi-row arrays (pages/pos/logits blocks vs a B-row array, pool
# head columns (M, 1, Dh) vs an Hkv-head pool), violating the Pallas
# TPU tiling rule, and the gather loops build their VMEM buffers with
# value-domain dynamic_update_slice, which has no Mosaic lowering.
# ``serving_bench.py --tpu-check`` records the diagnostics verbatim;
# the head-major pool relayout that fixes both is a ROADMAP item.
# Until then ``mode="on"`` must FALL BACK to the XLA path instead of
# crashing the first compile on a real chip — interpret mode (the
# CPU correctness path) is unaffected.
MOSAIC_LOWERABLE = False

_warned_fallback = False


def kernels_dispatchable(mode: str) -> bool:
    """Whether the resolved ``PADDLE_TPU_PALLAS`` mode may actually
    place the serving kernels in a compiled program on the current
    default backend. ``interpret`` always can (the interpreter runs
    anywhere); ``on`` requires a TPU backend AND Mosaic-lowerable
    kernels — today's layouts are not (see ``MOSAIC_LOWERABLE``), so
    ``on`` falls back to the XLA path with a one-time warning rather
    than failing the first compile. Callers still apply their VMEM
    ``*_kernel_fits`` guards on top."""
    global _warned_fallback
    if mode == "interpret":
        return True
    if mode != "on":
        return False
    if jax.default_backend() != "tpu" or not MOSAIC_LOWERABLE:
        if not _warned_fallback:
            _warned_fallback = True
            warnings.warn(
                "PADDLE_TPU_PALLAS resolved 'on' but the serving "
                "kernels cannot lower on this backend (Mosaic tiling "
                "/ missing-primitive limits — see ops/pallas/decode.py "
                "MOSAIC_LOWERABLE); serving falls back to the pure-XLA "
                "path. Interpret mode still exercises the kernels.",
                RuntimeWarning, stacklevel=2)
        return False
    return True

# ---------------------------------------------------------------------------
# tile selection
# ---------------------------------------------------------------------------

# measured-best (block_size, kv-page tile) keyed (span bucket, head_dim,
# dtype_name) — filled from on-chip sweeps (benchmarks/tune_flash_blocks.py
# --decode); consulted before the analytic default. The block_size entry
# is ADVISORY for engine configuration (the pool layout is the engine's
# choice); the kernel consults the tile only when the entry's block_size
# matches the pool it was actually handed. Span buckets are powers of two
# (lookup rounds up).
MEASURED_DECODE = {
    # (span_bucket, head_dim, dtype): (block_size, pages_per_tile)
}


def _kv_store_dims(Dh: int, dtype, kv_dtype: str):
    """(stored last-dim, stored itemsize, dtype-key name) of the pool's
    KV arrays under a KV storage width: quantized pools store int8
    bytes (nibble-packed for int4) with the fp32 scale tables riding
    beside them."""
    if kv_dtype in (None, "none"):
        return Dh, jnp.dtype(dtype).itemsize, jnp.dtype(dtype).name
    if kv_dtype == "int4":
        return Dh // 2, 1, "int4"
    return Dh, 1, "int8"


def decode_vmem_bytes(M: int, P: int, block_size: int, G: int, Dh: int,
                      itemsize: int, kv_dtype: str = "none") -> int:
    """Upper-bound VMEM residency of one (slot, kv-head) grid program:
    the pool's head column for k and v (the kernel's blocks), the
    fp32 gather buffers spanning the slot's T = P·bs logical positions,
    the q/out tiles, and the score row (s and its softmax). Quantized
    pools add the two fp32 scale head columns but shrink the value
    columns to 1 (int8) or 1/2 (int4) byte/elt."""
    T = P * int(block_size)
    if kv_dtype in (None, "none"):
        vals, scales = 2 * M * Dh * itemsize, 0
    else:
        Dh_st = Dh // 2 if kv_dtype == "int4" else Dh
        vals, scales = 2 * M * Dh_st, 2 * M * 4
    return (vals                         # k/v pool head columns
            + scales                     # k/v scale head columns
            + 2 * T * Dh * 4             # fp32 gather buffers
            + 2 * G * Dh * 4             # q, out
            + 2 * G * T * 4)             # scores + softmax row


def decode_kernel_fits(M: int, P: int, block_size: int, G: int, Dh: int,
                       dtype, kv_dtype: str = "none") -> bool:
    """Whether the flash-decode working set fits the VMEM budget — the
    dispatch guard: ``mode="on"`` falls back to the XLA gather path when
    this says no, rather than letting Mosaic fail opaquely."""
    itemsize = jnp.dtype(dtype).itemsize
    return decode_vmem_bytes(M, P, block_size, G, Dh, itemsize,
                             kv_dtype) <= VMEM_BYTES


def select_decode_tile(P: int, block_size: int, head_dim: int,
                       dtype, kv_dtype: str = "none") -> int:
    """Pages gathered per inner-loop iteration: the measured table first
    (when its advisory block_size matches the pool's), then the analytic
    default — the largest power-of-two divisor of P keeping the unrolled
    gather at <= 256 rows per iteration (past that the unroll stops
    paying and VMEM pressure from in-flight slices grows). Quantized
    pools key the measured table by their storage name ("int8"/"int4")."""
    span = P * int(block_size)
    bucket = 1 << max(0, (span - 1)).bit_length()     # next pow2 >= span
    _, _, name = _kv_store_dims(head_dim, dtype, kv_dtype)
    found = MEASURED_DECODE.get((bucket, head_dim, name))
    if found and found[0] == block_size and P % found[1] == 0:
        return int(found[1])
    tile = 1
    while (tile * 2 <= P and P % (tile * 2) == 0
           and tile * 2 * block_size <= 256):
        tile *= 2
    return tile


# ---------------------------------------------------------------------------
# flash-decode attention kernel
# ---------------------------------------------------------------------------


def _read_kv_rows(ref, scale_ref, start, bs, kv_dtype):
    """One block span of a pool head column, widened to fp32 in-register
    — the fused dequant. ``ref`` holds the stored bytes ((bs, Dh) for
    fp/int8 pools, (bs, Dh//2) nibble-packed for int4), ``scale_ref``
    the per-row fp32 scales (quantized pools only). The op chain is
    EXACTLY the XLA quantized path's (``ops/q8.dequantize_kv``): exact
    integer unpack, astype(f32), broadcast row-scale multiply — so the
    kernel stays bitwise the XLA path whatever the storage width."""
    from paddle_tpu.ops import q8 as ops_q8
    rows = ref[pl.ds(start, bs), 0, :]
    if kv_dtype in (None, "none"):
        return rows.astype(jnp.float32)
    if kv_dtype == "int4":
        rows = ops_q8.unpack_int4(rows)
    return (rows.astype(jnp.float32)
            * scale_ref[pl.ds(start, bs), 0][:, None])


def _decode_kernel(pages_ref, pos_ref, q_ref, k_ref, v_ref, *rest,
                   block_size, P, tile, G, Dh, scale, kv_dtype):
    """One (slot, kv-head) program. Blocks: pages (1, P), pos (1, 1),
    q/o (1, 1, G, Dh), k/v the pool's head column (M, 1, Dh-stored) —
    plus, for quantized pools, the fp32 scale head columns (M, 1). The
    page-gather loop touches only the slot's MAPPED physical blocks and
    widens them to fp32 in-register (int8/int4 HBM traffic; the dequant
    never materializes outside VMEM); everything downstream mirrors the
    XLA gather path's op chain exactly (divide-by-sqrt(Dh), -1e30 mask,
    jax.nn.softmax) so aligned fp32 shapes — and quantized pools, whose
    dequant chain is elementwise-identical — reproduce its logits
    bitwise."""
    if kv_dtype in (None, "none"):
        ks_ref = vs_ref = None
        o_ref = rest[0]
    else:
        ks_ref, vs_ref, o_ref = rest
    bs = int(block_size)
    T = P * bs

    def gather(i, carry):
        kbuf, vbuf = carry
        for t in range(tile):           # static unroll: tile pages/iter
            j = i * tile + t
            pg = pages_ref[0, j]
            ks = _read_kv_rows(k_ref, ks_ref, pg * bs, bs, kv_dtype)
            vs = _read_kv_rows(v_ref, vs_ref, pg * bs, bs, kv_dtype)
            kbuf = jax.lax.dynamic_update_slice(kbuf, ks, (j * bs, 0))
            vbuf = jax.lax.dynamic_update_slice(vbuf, vs, (j * bs, 0))
        return kbuf, vbuf

    kbuf = jnp.zeros((T, Dh), jnp.float32)
    vbuf = jnp.zeros((T, Dh), jnp.float32)
    kbuf, vbuf = jax.lax.fori_loop(0, P // tile, gather, (kbuf, vbuf))
    q = q_ref[0, 0].astype(jnp.float32)                  # [G, Dh]
    s = jnp.einsum("gd,td->gt", q, kbuf) / scale
    valid = (jax.lax.broadcasted_iota(jnp.int32, (G, T), 1)
             <= pos_ref[0, 0])                           # logical mask
    s = jnp.where(valid, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o_ref[0, 0] = jnp.einsum("gt,td->gd", p, vbuf)


def flash_decode_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                           pages: jax.Array, pos: jax.Array, *,
                           block_size: int,
                           tile: Optional[int] = None,
                           k_scale: Optional[jax.Array] = None,
                           v_scale: Optional[jax.Array] = None,
                           kv_dtype: str = "none",
                           interpret: bool = False) -> jax.Array:
    """One decode step's attention straight off the paged pool.

    q [B, Hkv, G, Dh] (grouped-query layout, G = n_heads/kv_heads),
    k/v the flat pool [M, Hkv, Dh], pages [B, P] int32 physical block
    ids, pos [B] int32 per-slot positions → fp32 [B, Hkv, G, Dh]. The
    caller owns the pool WRITE of the step's new k/v (a cheap scatter)
    and must perform it before this reads — position ``pos[b]`` attends
    to itself.

    Quantized pools (``kv_dtype`` "int8"/"int4") pass the int8 value
    arrays ([M, Hkv, Dh] or nibble-packed [M, Hkv, Dh//2]) plus the
    per-(position, head) fp32 scale tables ``k_scale``/``v_scale``
    [M, Hkv]: blocks stream into VMEM at their stored width and the
    dequant multiply runs in-register inside the gather loop — history
    crosses HBM at 1 (int8) or 1/2 (int4) byte/elt.

    Grid (slot, kv-head); the per-program working set must pass
    ``decode_kernel_fits`` (the dispatch in ``decode_step_paged``
    guards this and falls back to XLA)."""
    B, Hkv, G, Dh = q.shape             # Dh is always the LOGICAL dim
    quant = kv_dtype not in (None, "none")
    M = k.shape[0]
    P = pages.shape[1]
    bs = int(block_size)
    if quant and (k_scale is None or v_scale is None):
        raise ValueError(f"kv_dtype={kv_dtype} needs k_scale/v_scale")
    if tile is None:
        tile = select_decode_tile(P, bs, Dh, k.dtype, kv_dtype)
    if P % tile:
        raise ValueError(f"flash_decode: tile {tile} must divide the "
                         f"page-vector length {P}")
    Dh_st = k.shape[-1]                 # stored last dim (packed int4)
    kernel = functools.partial(
        _decode_kernel, block_size=bs, P=P, tile=int(tile), G=G, Dh=Dh,
        scale=math.sqrt(Dh), kv_dtype=kv_dtype if quant else "none")
    in_specs = [
        pl.BlockSpec((1, P), lambda b, h: (b, 0)),        # pages
        pl.BlockSpec((1, 1), lambda b, h: (b, 0)),        # pos
        pl.BlockSpec((1, 1, G, Dh), lambda b, h: (b, h, 0, 0)),
        pl.BlockSpec((M, 1, Dh_st), lambda b, h: (0, h, 0)),  # k pool
        pl.BlockSpec((M, 1, Dh_st), lambda b, h: (0, h, 0)),  # v pool
    ]
    args = [pages.astype(jnp.int32),
            jnp.reshape(pos, (B, 1)).astype(jnp.int32), q, k, v]
    if quant:
        in_specs += [pl.BlockSpec((M, 1), lambda b, h: (0, h)),
                     pl.BlockSpec((M, 1), lambda b, h: (0, h))]
        args += [k_scale, v_scale]
    return pl.pallas_call(
        kernel,
        grid=(B, Hkv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, G, Dh), lambda b, h: (b, h, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, Hkv, G, Dh), jnp.float32),
        interpret=interpret,
    )(*args)


# ---------------------------------------------------------------------------
# fused sampling epilogue
# ---------------------------------------------------------------------------


def _sortable_key(v: jax.Array) -> jax.Array:
    """fp32 -> uint32 order-preserving image (the radix-sort key map):
    positive floats get the sign bit set, negative floats flip every
    bit, so unsigned comparisons order exactly like float compares."""
    u = jax.lax.bitcast_convert_type(v, jnp.uint32)
    flip = ((u >> 31) * jnp.uint32(0x7FFFFFFF)) | jnp.uint32(0x80000000)
    return u ^ flip


def _kth_key(keys: jax.Array, k: jax.Array) -> jax.Array:
    """The k-th largest of ``keys`` [1, V] uint32 (k >= 1, traced) by
    32-step binary search on the integer threshold — count(keys >= t)
    is monotone, so the invariant count(>= lo) >= k pins lo to the
    exact k-th value after the interval collapses. O(32·V) compares, no
    sort (lax.sort has no Mosaic lowering; this runs anywhere)."""
    def body(_, lh):
        lo, hi = lh
        d = hi - lo
        mid = lo + (d >> 1) + (d & jnp.uint32(1))   # ceil, overflow-safe
        cnt = jnp.sum((keys >= mid).astype(jnp.int32))
        take = cnt >= k
        return (jnp.where(take, mid, lo),
                jnp.where(take, hi, mid - jnp.uint32(1)))
    lo, _ = jax.lax.fori_loop(
        0, 32, body, (jnp.uint32(0), jnp.uint32(0xFFFFFFFF)))
    return lo


def _hash_uniform(seed: jax.Array, row: jax.Array,
                  shape: Tuple[int, ...]) -> jax.Array:
    """Counter-based uniforms in (0, 1): a splitmix-style integer hash
    of (seed, row, lane) — deterministic for a given seed, independent
    across rows and lanes, and pure jnp (runs under interpret and
    Mosaic alike, unlike the TPU-only pltpu PRNG)."""
    lane = jax.lax.broadcasted_iota(jnp.uint32, shape, len(shape) - 1)
    h = (seed.astype(jnp.uint32)
         + row.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
         + (lane + jnp.uint32(1)) * jnp.uint32(0x85EBCA6B))
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x7FEB352D)
    h = h ^ (h >> 15)
    h = h * jnp.uint32(0x846CA68B)
    h = h ^ (h >> 16)
    return ((h >> 8).astype(jnp.float32) + 0.5) * (1.0 / (1 << 24))


def _first_argmax(x: jax.Array, iota: jax.Array) -> jax.Array:
    """First-index argmax over the last axis ([1, V] -> scalar) — the
    ``jnp.argmax`` tie convention, written as max+where+min because
    ``lax.argmax`` has no Mosaic lowering."""
    m = jnp.max(x, axis=-1, keepdims=True)
    V = x.shape[-1]
    return jnp.min(jnp.where(x == m, iota, V))


def _sample_kernel(logits_ref, seed_ref, temp_ref, topk_ref, o_ref):
    """One batch row: greedy argmax, radix top-k threshold, temperature
    scale, Gumbel-max categorical — ``sample_tokens`` semantics with no
    full-vocab sort and no second dispatch."""
    row = pl.program_id(0)
    v = logits_ref[0].astype(jnp.float32)[None, :]        # [1, V]
    V = v.shape[-1]
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, V), 1)
    greedy = _first_argmax(v, iota)
    k = jnp.clip(topk_ref[0, 0], 0, V)
    keys = _sortable_key(v)
    kstar = _kth_key(keys, jnp.maximum(k, 1))
    keep = (k <= 0) | (keys >= kstar)     # ties at the threshold survive
    z = jnp.where(keep, v, -jnp.inf)
    temp = temp_ref[0, 0]
    z = z / jnp.where(temp > 0, temp, 1.0)
    g = -jnp.log(-jnp.log(_hash_uniform(seed_ref[0, 0], row, (1, V))))
    sampled = _first_argmax(z + g, iota)
    o_ref[0, 0] = jnp.where(temp > 0, sampled, greedy).astype(jnp.int32)


def fused_sample(logits: jax.Array, seed: jax.Array,
                 temperature: jax.Array, top_k: jax.Array, *,
                 interpret: bool = False) -> jax.Array:
    """Sampling epilogue kernel: logits [B, V] fp32, scalar int32
    ``seed``, per-slot runtime ``temperature`` [B] / ``top_k`` [B] →
    sampled ids [B] int32. Greedy rows (temperature <= 0) and the kept
    top-k set match ``serving/sampling.sample_tokens`` exactly; the
    categorical draw matches in distribution (hash-Gumbel stream, not
    jax.random's)."""
    B, V = logits.shape
    out = pl.pallas_call(
        _sample_kernel,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, V), lambda b: (b, 0)),
            pl.BlockSpec((1, 1), lambda b: (0, 0)),
            pl.BlockSpec((1, 1), lambda b: (b, 0)),
            pl.BlockSpec((1, 1), lambda b: (b, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1), lambda b: (b, 0)),
        out_shape=jax.ShapeDtypeStruct((B, 1), jnp.int32),
        interpret=interpret,
    )(logits, jnp.reshape(jnp.asarray(seed, jnp.int32), (1, 1)),
      jnp.reshape(temperature, (B, 1)).astype(jnp.float32),
      jnp.reshape(top_k, (B, 1)).astype(jnp.int32))
    return out[:, 0]


def fused_spec_verify(logits: jax.Array, draft: jax.Array,
                      seed: jax.Array, temperature: jax.Array,
                      top_k: jax.Array, valid: jax.Array, *,
                      interpret: bool = False):
    """Speculative-decoding accept/reject epilogue: the PR-9
    ``fused_sample`` kernel run once per VERIFY-WINDOW row (logits
    [B, W, V] flattened to [B·W, V] — per-slot temperature/top_k
    broadcast over the window) followed by the accept fold
    (``serving.sampling.spec_accept``: leading draft-match run + one
    correction/bonus token, capped to ``valid`` rows). Greedy rows are
    the kernel's exact first-index argmax, so the fused path emits
    bitwise the ``spec_verify_tokens`` greedy tokens — the spec
    engine's bitwise-greedy contract holds on either epilogue.
    Returns (sampled [B, W] int32, n_emitted [B] int32)."""
    from paddle_tpu.serving import sampling as _sampling
    B, W, V = logits.shape
    sampled = fused_sample(
        logits.reshape(B * W, V), seed,
        jnp.repeat(temperature, W), jnp.repeat(top_k, W),
        interpret=interpret).reshape(B, W)
    return sampled, _sampling.spec_accept(sampled, draft, valid)
