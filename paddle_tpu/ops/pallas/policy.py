"""The ``PADDLE_TPU_PALLAS`` dispatch policy, shared by every kernel in
this package (kernels import from here rather than from the package
``__init__`` so the re-export there cannot go circular). See the package
docstring for the knob's semantics."""

import os

PALLAS_MODES = ("auto", "on", "off", "interpret")


def pallas_mode(explicit=None) -> str:
    """Resolve the package-wide Pallas dispatch policy to one of
    ``"on" | "off" | "interpret"``.

    ``explicit`` is the call-site override (``None`` defers to the
    ``PADDLE_TPU_PALLAS`` env var, which defaults to ``auto``). ``auto``
    resolves to ``on`` exactly when the default jax backend is TPU, so
    resolving the policy never forces a backend choice elsewhere."""
    mode = explicit if explicit is not None \
        else os.environ.get("PADDLE_TPU_PALLAS", "auto")
    mode = str(mode).lower()
    if mode not in PALLAS_MODES:
        raise ValueError(
            f"PADDLE_TPU_PALLAS={mode!r}: expected one of "
            f"{PALLAS_MODES} (explicit arg > env > auto)")
    if mode == "auto":
        import jax
        mode = "on" if jax.default_backend() == "tpu" else "off"
    return mode
