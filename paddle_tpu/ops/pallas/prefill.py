"""Chunked-prefill Pallas kernels over the head-major paged KV pool.

The cold-prefill half of TTFT is one ``transformer.prefill_into_blocks``
call per chunk: under XLA each layer gathers the context out of the pool
into an HBM ``[S, Hkv, Dh]`` view, concatenates the chunk's fresh K/V,
and keeps a ``[C, H, S+C]`` score tensor in HBM between the softmax
stages; the chunk's KV then lands in the pool as compiler-emitted
masked-span writes (the exact pattern CUDA-L2 in PAPERS.md shows
library-emitted kernels leave margin on). Two hand-scheduled kernels
replace that, behind the same ``PADDLE_TPU_PALLAS`` knob as the decode
kernels — both built for the head-major pool ``[Hkv, M, Dh]`` and both
Mosaic-legal under the TPU tiling rule (see ops/pallas/decode.py for
the rule and the probe machinery):

- :func:`flash_chunk_prefill` — one chunk's attention against its
  context, straight off the pool: grid ``(kv-head, ctx-page-step)``
  with the slot's context pages **scalar-prefetched**, so each step's
  ``(1, block_size, Dh)`` context block is PLACED by the page table
  (only MAPPED blocks ever stream; for quantized pools the dequant
  multiply fuses into the stream, so history crosses HBM at its stored
  1 or 1/2 byte/elt). Partial scores (Dh-contractions, bitwise the
  one-shot einsum's columns) accumulate into a VMEM score scratch; the
  LAST step appends the chunk's own K/V and applies ONE exact softmax
  under the context-visible + chunk-causal mask. No gathered context
  view and no score tensor ever exist in HBM. Exact softmax (not
  online rescaling) for the same reason as ``flash_decode_attention``:
  it reproduces the XLA fallback's op chain, so the interpret-mode
  kernel is BITWISE the XLA path on aligned fp32 shapes (pinned in
  tests/test_pallas_prefill.py).

- :func:`paged_span_write` — the chunk's masked span writes: grid over
  the chunk's pages, each program's output block mapped THROUGH the
  scalar-prefetched page vector, pool buffers aliased in-place. Padded
  rows keep the span's old bytes (the RMW the XLA fallback expresses
  as slice + where + update-slice), and quantized pools write values
  and scale rows through the same kernel (scale tables ride as
  trailing-singleton ``[L, Hkv, M, 1]`` views — tiling-legal).

Tiling: ``tile`` context pages stream per grid step (each its own
scalar-prefetch-placed BlockSpec) — measured winners from
``benchmarks/tune_flash_blocks.py --prefill`` go in ``MEASURED_PREFILL``
(keyed by POOL LAYOUT first, so entries swept on another layout are
never consulted; the block-size entry stays an engine-configuration
hint, consulted only when it matches the pool actually handed over);
the analytic default mirrors the decode kernel's.
"""

import functools
import math
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops.pallas.attention import VMEM_BYTES
from paddle_tpu.ops.pallas.decode import (NEG_INF, POOL_LAYOUT,
                                          _kv_store_dims, _widen_block,
                                          mosaic_lowerable)

# measured-best (block_size, ctx pages-per-grid-step) keyed (POOL
# layout, context-span bucket, chunk bucket, head_dim, dtype_name) —
# filled from on-chip sweeps (benchmarks/tune_flash_blocks.py
# --prefill); consulted before the analytic default. Advisory semantics
# match MEASURED_DECODE: the block_size entry is a hint for engine
# configuration, and the tile is used only when that advisory matches
# the pool the kernel was handed.
MEASURED_PREFILL = {
    # (POOL_LAYOUT, span_bucket, chunk_bucket, head_dim, dtype):
    #     (block_size, tile)
}


def prefill_vmem_bytes(M: int, S: int, C: int, G: int, Dh: int,
                       itemsize: int, kv_dtype: str = "none",
                       stream_rows: Optional[int] = None) -> int:
    """Upper-bound VMEM residency of one kv-head grid program at the
    head-major layout: the ``[C·G, S+C]`` score scratch (counted twice
    — softmax temporaries are score-sized), the fp32 V scratch over
    context + chunk, the chunk K/V and q/out tiles, and the streamed
    context blocks in flight at their stored width (double-buffered;
    ``stream_rows`` is the per-step stream, ``tile·block_size`` when
    the caller knows its tile — the analytic selector caps it at 256
    rows, the default charged here, but a MEASURED_PREFILL winner may
    exceed it; quantized pools add the fp32 scale columns). The pool
    itself never sits in VMEM — scalar-prefetched placement streams
    only the mapped blocks, so the budget no longer scales with the
    pool size ``M``."""
    del M                        # streamed per-block, never resident
    T = S + C
    if kv_dtype in (None, "none"):
        blk_row = Dh * itemsize
    else:
        Dh_st = Dh // 2 if kv_dtype == "int4" else Dh
        blk_row = Dh_st + 4                  # values + scale col
    if stream_rows is None:
        stream_rows = min(max(S, 1), 256)
    stream = 4 * stream_rows * blk_row
    return (2 * C * G * T * 4            # scores + softmax temps
            + T * Dh * 4                 # fp32 V scratch
            + 2 * C * Dh * 4             # chunk k/v tiles
            + 2 * C * G * Dh * 4         # q, out
            + stream)                    # in-flight context blocks


def prefill_kernel_fits(M: int, S: int, C: int, G: int, Dh: int,
                        dtype, kv_dtype: str = "none",
                        block_size: Optional[int] = None) -> bool:
    """Dispatch guard for ``mode="on"``: fall back to the XLA chunk
    path when the working set exceeds the VMEM budget rather than
    letting Mosaic fail opaquely. Pass ``block_size`` so the in-flight
    stream is charged at the tile ``select_prefill_tile`` would
    actually pick (a MEASURED_PREFILL winner can exceed the analytic
    256-row cap; without it the default cap is charged)."""
    itemsize = jnp.dtype(dtype).itemsize
    stream_rows = None
    if block_size and S:
        bs = int(block_size)
        tile = select_prefill_tile(S // bs, bs, C, Dh, dtype, kv_dtype)
        stream_rows = tile * bs
    return prefill_vmem_bytes(M, S, C, G, Dh, itemsize, kv_dtype,
                              stream_rows=stream_rows) <= VMEM_BYTES


def prefill_lowering_ok(M: int, S: int, C: int, block_size: int,
                        Hkv: int, G: int, Dh: int, dtype,
                        kv_dtype: str = "none",
                        q_dtype=None) -> bool:
    """Mosaic lowering probe for the chunk-prefill ATTENTION kernel at
    the given geometry — deviceless and cached (see
    ``decode.mosaic_lowerable``). The ``mode="on"`` dispatch consults
    this together with :func:`span_write_lowering_ok` (the chunk's
    other kernel). ``q_dtype`` is the caller's ACTIVATION dtype (q and
    the chunk's own K/V arrive in it; tiling is dtype-dependent, so
    the probe lowers the very program dispatch would build); defaults
    to the pool dtype — quantized-pool callers pass their model dtype
    explicitly."""
    bs = int(block_size)
    if q_dtype is None:
        q_dtype = dtype if kv_dtype in (None, "none") else jnp.float32
    Dh_st, _, name = _kv_store_dims(Dh, dtype, kv_dtype)
    quant = kv_dtype not in (None, "none")
    key = ("prefill", M, S, C, bs, Hkv, G, Dh, name,
           jnp.dtype(q_dtype).name)

    def build():
        kvd = jnp.int8 if quant else jnp.dtype(dtype)
        qd = jnp.dtype(q_dtype)
        kv = jax.ShapeDtypeStruct((Hkv, M, Dh_st), kvd)
        sc = jax.ShapeDtypeStruct((Hkv, M), jnp.float32)
        P_ctx = S // bs
        args = [jax.ShapeDtypeStruct((C, Hkv, G, Dh), qd),
                jax.ShapeDtypeStruct((C, Hkv, Dh), qd),
                jax.ShapeDtypeStruct((C, Hkv, Dh), qd),
                kv, kv,
                jax.ShapeDtypeStruct((P_ctx,), jnp.int32)]

        def probe(q, kck, vck, k, v, pages, *scales):
            ks, vs = (scales[0], scales[1]) if quant else (None, None)
            return flash_chunk_prefill(
                q, kck, vck, k, v, pages, block_size=bs,
                k_scale=ks, v_scale=vs, kv_dtype=kv_dtype)

        extra = [sc, sc] if quant else []
        return probe, args + extra

    return mosaic_lowerable(key, build)


def span_write_lowering_ok(M: int, pc: int, block_size: int, L: int,
                           Hkv: int, Dh: int, dtype,
                           kv_dtype: str = "none") -> bool:
    """Mosaic lowering probe for :func:`paged_span_write` (aliased
    pool write, scale tables included for quantized pools) — cached,
    deviceless."""
    bs = int(block_size)
    Dh_st, _, name = _kv_store_dims(Dh, dtype, kv_dtype)
    quant = kv_dtype not in (None, "none")
    key = ("span_write", M, pc, bs, L, Hkv, Dh, name)

    def build():
        kvd = jnp.int8 if quant else jnp.dtype(dtype)
        span = jax.ShapeDtypeStruct((L, Hkv, pc * bs, Dh_st), kvd)
        sspan = jax.ShapeDtypeStruct((L, Hkv, pc * bs), jnp.float32)
        pool_kv = jax.ShapeDtypeStruct((L, Hkv, M, Dh_st), kvd)
        pool_sc = jax.ShapeDtypeStruct((L, Hkv, M), jnp.float32)
        args = [pool_kv, pool_kv, span, span,
                jax.ShapeDtypeStruct((pc,), jnp.int32),
                jax.ShapeDtypeStruct((pc * bs,), jnp.bool_)]

        def probe(pk, pv, sk, sv, pages, valid, *scales):
            pool_in = {"k": pk, "v": pv}
            spans = {"k": sk, "v": sv}
            if quant:
                pool_in.update(k_scale=scales[0], v_scale=scales[1])
                spans.update(k_scale=scales[2], v_scale=scales[3])
            return paged_span_write(pool_in, spans, pages, valid,
                                    block_size=bs)

        extra = [pool_sc, pool_sc, sspan, sspan] if quant else []
        return probe, args + extra

    return mosaic_lowerable(key, build)


def select_prefill_tile(P_ctx: int, block_size: int, chunk: int,
                        head_dim: int, dtype,
                        kv_dtype: str = "none") -> int:
    """Context pages streamed per grid step: the measured table first
    (when its advisory block_size matches the pool's), then the
    analytic default — largest power-of-two divisor of ``P_ctx``
    keeping the per-step stream at <= 256 rows."""
    if P_ctx < 1:
        return 1
    span = P_ctx * int(block_size)
    sb = 1 << max(0, (span - 1)).bit_length()
    cb = 1 << max(0, (int(chunk) - 1)).bit_length()
    _, _, name = _kv_store_dims(head_dim, dtype, kv_dtype)
    found = MEASURED_PREFILL.get((POOL_LAYOUT, sb, cb, head_dim, name))
    if found and found[0] == block_size and P_ctx % found[1] == 0:
        return int(found[1])
    tile = 1
    while (tile * 2 <= P_ctx and P_ctx % (tile * 2) == 0
           and tile * 2 * block_size <= 256):
        tile *= 2
    return tile


# ---------------------------------------------------------------------------
# chunk attention kernel
# ---------------------------------------------------------------------------


def _chunk_kernel(pages_ref, *refs, block_size, P_ctx, tile, C, G, Dh,
                  scale, kv_dtype):
    """One (kv-head, ctx-page-step) program. The context pages are
    scalar-prefetched; blocks are q ``(C, 1, G, Dh)``, chunk k/v
    ``(1, C, Dh)`` (head-major), and per stream one ``(1, bs, Dh-
    stored)`` pool block (+ ``(1, bs, 1)`` scale column when
    quantized). Page step ``j`` writes its partial scores and widened V
    rows into scratch at the logical offset; the LAST step appends the
    chunk's own K/V behind the context and mirrors the XLA chunk
    path's op chain exactly (context fully visible, chunk causal,
    -1e30 mask, max/exp/sum/divide softmax) for the bitwise contract.
    All dots are 2D (``[C·G, ·]``) — Mosaic's dot only takes rank-2 —
    which cannot move a single bit: each score/output element is the
    same length-Dh / length-T contraction either way."""
    quant = kv_dtype not in (None, "none")
    krefs = refs[:tile]
    vrefs = refs[tile:2 * tile]
    off = 2 * tile
    if quant:
        ksrefs = refs[off:off + tile]
        vsrefs = refs[off + tile:off + 2 * tile]
        off += 2 * tile
    else:
        ksrefs = vsrefs = (None,) * tile
    q_ref, kck_ref, vck_ref = refs[off], refs[off + 1], refs[off + 2]
    o_ref, s_scr, v_scr = refs[off + 3], refs[off + 4], refs[off + 5]
    j = pl.program_id(1)
    bs = int(block_size)
    S = P_ctx * bs
    T = S + C
    q = q_ref[:, 0].astype(jnp.float32).reshape(C * G, Dh)
    for t in range(tile):           # static unroll: tile pages/step
        ks = _widen_block(krefs[t], ksrefs[t], kv_dtype)
        vs = _widen_block(vrefs[t], vsrefs[t], kv_dtype)
        s = jax.lax.dot_general(q, ks, (((1,), (1,)), ((), ())))
        o = (j * tile + t) * bs
        s_scr[:, pl.ds(o, bs)] = s
        v_scr[pl.ds(o, bs), :] = vs

    @pl.when(j == P_ctx // tile - 1)
    def _finish():
        kck = kck_ref[0].astype(jnp.float32)             # [C, Dh]
        vck = vck_ref[0].astype(jnp.float32)
        s2 = jax.lax.dot_general(q, kck, (((1,), (1,)), ((), ())))
        s_scr[:, pl.ds(S, C)] = s2
        v_scr[pl.ds(S, C), :] = vck
        s = s_scr[...] / scale
        # context fully visible, chunk causally masked: position t is
        # visible to chunk row c iff t <= S + c (row r of the [C·G, T]
        # image belongs to chunk row r // G)
        row = jax.lax.broadcasted_iota(jnp.int32, (C * G, T), 0) // G
        col = jax.lax.broadcasted_iota(jnp.int32, (C * G, T), 1)
        s = jnp.where(col <= S + row, s, NEG_INF)
        m = jnp.max(s, axis=-1, keepdims=True)
        e = jnp.exp(s - m)
        p = e / jnp.sum(e, axis=-1, keepdims=True)
        out = jax.lax.dot_general(p, v_scr[...],
                                  (((1,), (0,)), ((), ())))
        o_ref[...] = out.reshape(C, 1, G, Dh)


def _cold_chunk_kernel(q_ref, kck_ref, vck_ref, o_ref, *, C, G, Dh,
                       scale):
    """A cold first chunk (no context): pure chunk-causal attention in
    registers — no pool inputs, no scratch, same op chain."""
    q = q_ref[:, 0].astype(jnp.float32).reshape(C * G, Dh)
    kck = kck_ref[0].astype(jnp.float32)
    vck = vck_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(q, kck, (((1,), (1,)), ((), ()))) / scale
    row = jax.lax.broadcasted_iota(jnp.int32, (C * G, C), 0) // G
    col = jax.lax.broadcasted_iota(jnp.int32, (C * G, C), 1)
    s = jnp.where(col <= row, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    p = e / jnp.sum(e, axis=-1, keepdims=True)
    out = jax.lax.dot_general(p, vck, (((1,), (0,)), ((), ())))
    o_ref[...] = out.reshape(C, 1, G, Dh)


def flash_chunk_prefill(q: jax.Array, k_chunk: jax.Array,
                        v_chunk: jax.Array, k: jax.Array, v: jax.Array,
                        pages: jax.Array, *, block_size: int,
                        tile: Optional[int] = None,
                        k_scale: Optional[jax.Array] = None,
                        v_scale: Optional[jax.Array] = None,
                        kv_dtype: str = "none",
                        interpret: bool = False) -> jax.Array:
    """One prefill chunk's attention against its pool-resident context.

    q [C, Hkv, G, Dh] (grouped-query layout), k_chunk/v_chunk
    [C, Hkv, Dh] the chunk's OWN fresh K/V (exact, pre-quantization —
    in-chunk attention reads what the forward computed; only the pool
    write is rounded), k/v the head-major flat pool [Hkv, M, Dh-stored],
    pages [P_ctx] int32 the slot's context pages (context length S =
    P_ctx·block_size is static, like the XLA chunk path's span
    specialization) → fp32 [C, Hkv, G, Dh]. Quantized pools also pass
    ``k_scale``/``v_scale`` [Hkv, M] and the matching ``kv_dtype``.

    A cold first chunk (P_ctx = 0) skips the pool inputs entirely —
    the kernel is then pure chunk-causal attention. Contextful chunks
    run grid (kv-head, ctx-page-step) with the pages scalar-prefetched
    and each step's context block placed through the page table."""
    C, Hkv, G, Dh = q.shape
    quant = kv_dtype not in (None, "none")
    P_ctx = int(pages.shape[0])
    bs = int(block_size)
    if quant and (k_scale is None or v_scale is None):
        raise ValueError(f"kv_dtype={kv_dtype} needs k_scale/v_scale")
    if tile is None:
        tile = select_prefill_tile(P_ctx, bs, C, Dh, k.dtype, kv_dtype)
    if P_ctx and P_ctx % tile:
        raise ValueError(f"flash_chunk_prefill: tile {tile} must "
                         f"divide the context page count {P_ctx}")
    tile = int(tile)
    # chunk K/V ride head-major too: the (1, C, Dh) block keeps the
    # tiling-legal trailing dims (the [C, Hkv, Dh] layout would put the
    # head singleton second-to-last)
    kck = jnp.swapaxes(k_chunk, 0, 1)
    vck = jnp.swapaxes(v_chunk, 0, 1)
    if not P_ctx:
        kernel = functools.partial(_cold_chunk_kernel, C=C, G=G, Dh=Dh,
                                   scale=math.sqrt(Dh))
        return pl.pallas_call(
            kernel,
            grid=(Hkv,),
            in_specs=[
                pl.BlockSpec((C, 1, G, Dh), lambda h: (0, h, 0, 0)),
                pl.BlockSpec((1, C, Dh), lambda h: (h, 0, 0)),
                pl.BlockSpec((1, C, Dh), lambda h: (h, 0, 0)),
            ],
            out_specs=pl.BlockSpec((C, 1, G, Dh),
                                   lambda h: (0, h, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((C, Hkv, G, Dh),
                                           jnp.float32),
            interpret=interpret,
        )(q, kck, vck)
    M = k.shape[1]
    Dh_st = k.shape[-1]                 # stored last dim (packed int4)
    S = P_ctx * bs
    kernel = functools.partial(
        _chunk_kernel, block_size=bs, P_ctx=P_ctx, tile=tile, C=C,
        G=G, Dh=Dh, scale=math.sqrt(Dh),
        kv_dtype=kv_dtype if quant else "none")

    def kv_spec(t):
        return pl.BlockSpec(
            (1, bs, Dh_st),
            lambda h, j, pg, t=t: (h, pg[j * tile + t], 0))

    def sc_spec(t):
        return pl.BlockSpec(
            (1, bs, 1),
            lambda h, j, pg, t=t: (h, pg[j * tile + t], 0))

    in_specs = [kv_spec(t) for t in range(tile)] * 2
    args = [k] * tile + [v] * tile
    if quant:
        in_specs += [sc_spec(t) for t in range(tile)] * 2
        args += ([k_scale.reshape(Hkv, M, 1)] * tile
                 + [v_scale.reshape(Hkv, M, 1)] * tile)
    in_specs += [
        pl.BlockSpec((C, 1, G, Dh), lambda h, j, pg: (0, h, 0, 0)),
        pl.BlockSpec((1, C, Dh), lambda h, j, pg: (h, 0, 0)),
        pl.BlockSpec((1, C, Dh), lambda h, j, pg: (h, 0, 0)),
    ]
    args += [q, kck, vck]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(Hkv, P_ctx // tile),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((C, 1, G, Dh),
                               lambda h, j, pg: (0, h, 0, 0)),
        scratch_shapes=[pltpu.VMEM((C * G, S + C), jnp.float32),
                        pltpu.VMEM((S + C, Dh), jnp.float32)],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((C, Hkv, G, Dh), jnp.float32),
        interpret=interpret,
    )(pages.astype(jnp.int32), *args)


# ---------------------------------------------------------------------------
# masked span-write kernel
# ---------------------------------------------------------------------------


def _span_write_kernel(n: int):
    """Kernel over ``n`` (span, pool) array pairs: one grid program per
    chunk page, output blocks mapped through the scalar-prefetched page
    vector, pool buffers aliased — so each program touches exactly one
    ``block_size``-token span per array. Padded rows (mask 0) keep the
    pool's old bytes: the aliased output ref still HOLDS them, so the
    masked select is a read-modify-write entirely in VMEM."""

    def kernel(pages_ref, mask_ref, *refs):
        spans = refs[:n]
        outs = refs[2 * n:]
        m = mask_ref[0, :, 0] != 0                        # [bs]
        for s_ref, o_ref in zip(spans, outs):
            mv = m.reshape((1, 1, -1) + (1,) * (o_ref.ndim - 3))
            o_ref[...] = jnp.where(mv, s_ref[...], o_ref[...])

    return kernel


def paged_span_write(pool: Dict[str, jax.Array],
                     spans: Dict[str, jax.Array],
                     pages: jax.Array, valid: jax.Array, *,
                     block_size: int,
                     interpret: bool = False) -> Dict[str, jax.Array]:
    """Write one chunk's spans into its pool pages, masked per row.

    ``pool`` maps array names to head-major pool buffers
    [L, Hkv, M, ...]; ``spans`` maps the SAME names to the chunk's
    stacked spans [L, Hkv, pc·bs, ...] (values and, for quantized
    pools, scale rows alike — scale tables are the 3D [L, Hkv, M] /
    [L, Hkv, pc·bs] case and ride as trailing-singleton 4D views);
    ``pages`` [pc] int32 the chunk's physical pages; ``valid`` [pc·bs]
    bool the per-row write mask (False rows keep the pool's old bytes —
    the RMW equivalent of the decode scatter's mode="drop"). Returns
    the updated pool arrays.

    Grid (pc,); each program's blocks are one page's span per array,
    placed by indexing the output BlockSpec through the scalar-
    prefetched page vector — the hand-scheduled form of the masked
    contiguous-span writes XLA emits for the fallback path, with the
    pool aliased in-place instead of round-tripping a pool-sized
    copy. Every block keeps its trailing two dims tiling-legal: the
    page axis sits third-from-last (``(L, Hkv, bs, Dh)`` value blocks,
    ``(L, Hkv, bs, 1)`` scale blocks, ``(1, bs, 1)`` mask blocks)."""
    names = sorted(spans)
    bs = int(block_size)
    pc = int(pages.shape[0])
    n = len(names)
    mask = valid.astype(jnp.int32).reshape(pc, bs, 1)
    # 3D arrays (the scale tables) ride as trailing-singleton 4D views
    # so their blocks end in (bs, 1) — legal under the tiling rule
    three_d = {nm for nm in names if pool[nm].ndim == 3}

    def view(a):
        return a[..., None] if a.ndim == 3 else a

    pools4 = {nm: view(pool[nm]) for nm in names}
    spans4 = {nm: view(spans[nm]) for nm in names}

    def span_spec(a):
        blk = a.shape[:2] + (bs,) + a.shape[3:]
        nd = a.ndim

        def imap(j, pg, nd=nd):
            return (0, 0, j) + (0,) * (nd - 3)

        return pl.BlockSpec(blk, imap)

    def pool_spec(a):
        blk = a.shape[:2] + (bs,) + a.shape[3:]
        nd = a.ndim

        def imap(j, pg, nd=nd):
            return (0, 0, pg[j]) + (0,) * (nd - 3)

        return pl.BlockSpec(blk, imap)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(pc,),
        in_specs=([pl.BlockSpec((1, bs, 1), lambda j, pg: (j, 0, 0))]
                  + [span_spec(spans4[nm]) for nm in names]
                  + [pool_spec(pools4[nm]) for nm in names]),
        out_specs=[pool_spec(pools4[nm]) for nm in names],
    )
    outs = pl.pallas_call(
        _span_write_kernel(n),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(pools4[nm].shape,
                                        pools4[nm].dtype)
                   for nm in names],
        # pool inputs alias the outputs: scalar-prefetch pages ride
        # first, then the mask, the spans, and the pool buffers at
        # kernel-arg indices 1..; the alias indices COUNT the scalar-
        # prefetch operand, matching pallas_call's flat operand order
        input_output_aliases={2 + n + i: i for i in range(n)},
        interpret=interpret,
    )(pages.astype(jnp.int32), mask,
      *[spans4[nm] for nm in names], *[pools4[nm] for nm in names])
    return {nm: (o[..., 0] if nm in three_d else o)
            for nm, o in zip(names, outs)}
