"""Chunked-prefill Pallas kernels over the paged KV pool.

The cold-prefill half of TTFT is one ``transformer.prefill_into_blocks``
call per chunk: under XLA each layer gathers the context out of the pool
into an HBM ``[S, Hkv, Dh]`` view, concatenates the chunk's fresh K/V,
and keeps a ``[C, H, S+C]`` score tensor in HBM between the softmax
stages; the chunk's KV then lands in the pool as compiler-emitted
masked-span writes (the exact pattern CUDA-L2 in PAPERS.md shows
library-emitted kernels leave margin on). Two hand-scheduled kernels
replace that, behind the same ``PADDLE_TPU_PALLAS`` knob as the decode
kernels:

- :func:`flash_chunk_prefill` — one chunk's attention against its
  context, straight off the pool: one grid program per kv-head resolves
  the slot's context pages INSIDE the kernel, streams only the MAPPED
  blocks into VMEM (widened to fp32 in-register — for quantized pools
  the dequant multiply is fused into the gather, so history crosses HBM
  at its stored 1 or 1/2 byte/elt), concatenates the chunk's K/V in
  VMEM, and applies ONE exact softmax over the
  context-visible + chunk-causal mask. No gathered context view and no
  score tensor ever exist in HBM. Exact softmax (not online rescaling)
  for the same reason as ``flash_decode_attention``: it reproduces the
  XLA fallback's op chain, so the interpret-mode kernel is BITWISE the
  XLA path on aligned fp32 shapes (pinned in
  tests/test_pallas_prefill.py).

- :func:`paged_span_write` — the chunk's masked span writes: grid over
  the chunk's pages, each program's output block mapped THROUGH the
  page vector by scalar prefetch (``pltpu.PrefetchScalarGridSpec``),
  pool buffers aliased in-place. Padded rows keep the span's old bytes
  (the RMW the XLA fallback expresses as slice + where + update-slice),
  and quantized pools write values and scale rows through the same
  kernel.

Tiling: the context gather unrolls ``tile`` pages per loop iteration —
measured winners from ``benchmarks/tune_flash_blocks.py --prefill`` go
in ``MEASURED_PREFILL`` (advisory, exactly like ``MEASURED_DECODE``:
the block-size entry is an engine-configuration hint, consulted only
when it matches the pool actually handed over); the analytic default
mirrors the decode kernel's.
"""

import functools
import math
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops.pallas.attention import VMEM_BYTES
from paddle_tpu.ops.pallas.decode import NEG_INF, _read_kv_rows

# measured-best (block_size, ctx pages-per-tile) keyed (context-span
# bucket, chunk bucket, head_dim, dtype_name) — filled from on-chip
# sweeps (benchmarks/tune_flash_blocks.py --prefill); consulted before
# the analytic default. Advisory semantics match MEASURED_DECODE: the
# block_size entry is a hint for engine configuration, and the tile is
# used only when that advisory matches the pool the kernel was handed.
MEASURED_PREFILL = {
    # (span_bucket, chunk_bucket, head_dim, dtype): (block_size, tile)
}


def prefill_vmem_bytes(M: int, S: int, C: int, G: int, Dh: int,
                       itemsize: int, kv_dtype: str = "none") -> int:
    """Upper-bound VMEM residency of one kv-head grid program: the
    pool's head columns (stored width), the fp32 gather buffers over
    context + chunk, the chunk K/V and q/out tiles, and the
    ``[C, G, S+C]`` score block (plus its softmax)."""
    T = S + C
    if kv_dtype in (None, "none"):
        vals, scales = 2 * M * Dh * itemsize, 0
    else:
        Dh_st = Dh // 2 if kv_dtype == "int4" else Dh
        vals, scales = 2 * M * Dh_st, 2 * M * 4
    return (vals + scales                # pool value + scale columns
            + 2 * T * Dh * 4             # fp32 k/v concat buffers
            + 2 * C * Dh * 4             # chunk k/v tiles
            + 2 * C * G * Dh * 4         # q, out
            + 2 * C * G * T * 4)         # scores + softmax


def prefill_kernel_fits(M: int, S: int, C: int, G: int, Dh: int,
                        dtype, kv_dtype: str = "none") -> bool:
    """Dispatch guard for ``mode="on"``: fall back to the XLA chunk
    path when the working set exceeds the VMEM budget rather than
    letting Mosaic fail opaquely."""
    itemsize = jnp.dtype(dtype).itemsize
    return prefill_vmem_bytes(M, S, C, G, Dh, itemsize,
                              kv_dtype) <= VMEM_BYTES


def select_prefill_tile(P_ctx: int, block_size: int, chunk: int,
                        head_dim: int, dtype,
                        kv_dtype: str = "none") -> int:
    """Context pages gathered per inner-loop iteration: the measured
    table first (when its advisory block_size matches the pool's), then
    the analytic default — largest power-of-two divisor of ``P_ctx``
    keeping the unrolled gather at <= 256 rows per iteration."""
    if P_ctx < 1:
        return 1
    span = P_ctx * int(block_size)
    sb = 1 << max(0, (span - 1)).bit_length()
    cb = 1 << max(0, (int(chunk) - 1)).bit_length()
    if kv_dtype in (None, "none"):
        name = jnp.dtype(dtype).name
    else:
        name = kv_dtype
    found = MEASURED_PREFILL.get((sb, cb, head_dim, name))
    if found and found[0] == block_size and P_ctx % found[1] == 0:
        return int(found[1])
    tile = 1
    while (tile * 2 <= P_ctx and P_ctx % (tile * 2) == 0
           and tile * 2 * block_size <= 256):
        tile *= 2
    return tile


# ---------------------------------------------------------------------------
# chunk attention kernel
# ---------------------------------------------------------------------------


def _chunk_kernel(*refs, block_size, P_ctx, tile, C, G, Dh, scale,
                  kv_dtype):
    """One kv-head program. With context: blocks are pages (1, P_ctx),
    q (C, 1, G, Dh), chunk k/v (C, 1, Dh), the pool's head columns
    (M, 1, Dh-stored) (+ scale columns (M, 1) when quantized); without
    (a cold first chunk), only q and the chunk k/v. The page-gather
    loop fills the context prefix of the fp32 concat buffer, the
    chunk's K/V land behind it, and the masked exact softmax mirrors
    the XLA chunk path's op chain (context fully visible, chunk
    causal, -1e30 mask, jax.nn.softmax) for the bitwise contract."""
    quant = kv_dtype not in (None, "none")
    if P_ctx:
        if quant:
            (pages_ref, q_ref, kck_ref, vck_ref, k_ref, v_ref,
             ks_ref, vs_ref, o_ref) = refs
        else:
            (pages_ref, q_ref, kck_ref, vck_ref, k_ref, v_ref,
             o_ref) = refs
            ks_ref = vs_ref = None
    else:
        q_ref, kck_ref, vck_ref, o_ref = refs
    bs = int(block_size)
    S = P_ctx * bs
    T = S + C
    kck = kck_ref[:, 0, :].astype(jnp.float32)            # [C, Dh]
    vck = vck_ref[:, 0, :].astype(jnp.float32)
    if P_ctx:
        def gather(i, carry):
            kbuf, vbuf = carry
            for t in range(tile):       # static unroll: tile pages/iter
                j = i * tile + t
                pg = pages_ref[0, j]
                ks = _read_kv_rows(k_ref, ks_ref, pg * bs, bs, kv_dtype)
                vs = _read_kv_rows(v_ref, vs_ref, pg * bs, bs, kv_dtype)
                kbuf = jax.lax.dynamic_update_slice(kbuf, ks,
                                                    (j * bs, 0))
                vbuf = jax.lax.dynamic_update_slice(vbuf, vs,
                                                    (j * bs, 0))
            return kbuf, vbuf

        kbuf = jnp.zeros((T, Dh), jnp.float32)
        vbuf = jnp.zeros((T, Dh), jnp.float32)
        kbuf, vbuf = jax.lax.fori_loop(0, P_ctx // tile, gather,
                                       (kbuf, vbuf))
        kbuf = jax.lax.dynamic_update_slice(kbuf, kck, (S, 0))
        vbuf = jax.lax.dynamic_update_slice(vbuf, vck, (S, 0))
    else:
        kbuf, vbuf = kck, vck
    q = q_ref[:, 0].astype(jnp.float32)                   # [C, G, Dh]
    s = jnp.einsum("cgd,td->cgt", q, kbuf) / scale
    # context fully visible, chunk causally masked: position t is
    # visible to chunk row c iff t <= S + c
    row = jax.lax.broadcasted_iota(jnp.int32, (C, 1, T), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (C, 1, T), 2)
    s = jnp.where(col <= S + row, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o_ref[:, 0] = jnp.einsum("cgt,td->cgd", p, vbuf)


def flash_chunk_prefill(q: jax.Array, k_chunk: jax.Array,
                        v_chunk: jax.Array, k: jax.Array, v: jax.Array,
                        pages: jax.Array, *, block_size: int,
                        tile: Optional[int] = None,
                        k_scale: Optional[jax.Array] = None,
                        v_scale: Optional[jax.Array] = None,
                        kv_dtype: str = "none",
                        interpret: bool = False) -> jax.Array:
    """One prefill chunk's attention against its pool-resident context.

    q [C, Hkv, G, Dh] (grouped-query layout), k_chunk/v_chunk
    [C, Hkv, Dh] the chunk's OWN fresh K/V (exact, pre-quantization —
    in-chunk attention reads what the forward computed; only the pool
    write is rounded), k/v the flat pool [M, Hkv, Dh-stored], pages
    [P_ctx] int32 the slot's context pages (context length S =
    P_ctx·block_size is static, like the XLA chunk path's span
    specialization) → fp32 [C, Hkv, G, Dh]. Quantized pools also pass
    ``k_scale``/``v_scale`` [M, Hkv] and the matching ``kv_dtype``.

    A cold first chunk (P_ctx = 0) skips the pool inputs entirely —
    the kernel is then pure chunk-causal attention."""
    C, Hkv, G, Dh = q.shape
    quant = kv_dtype not in (None, "none")
    P_ctx = int(pages.shape[0])
    bs = int(block_size)
    if quant and (k_scale is None or v_scale is None):
        raise ValueError(f"kv_dtype={kv_dtype} needs k_scale/v_scale")
    if tile is None:
        tile = select_prefill_tile(P_ctx, bs, C, Dh, k.dtype, kv_dtype)
    if P_ctx and P_ctx % tile:
        raise ValueError(f"flash_chunk_prefill: tile {tile} must "
                         f"divide the context page count {P_ctx}")
    kernel = functools.partial(
        _chunk_kernel, block_size=bs, P_ctx=P_ctx, tile=int(tile),
        C=C, G=G, Dh=Dh, scale=math.sqrt(Dh),
        kv_dtype=kv_dtype if quant else "none")
    in_specs = [
        pl.BlockSpec((C, 1, G, Dh), lambda h: (0, h, 0, 0)),   # q
        pl.BlockSpec((C, 1, Dh), lambda h: (0, h, 0)),         # chunk k
        pl.BlockSpec((C, 1, Dh), lambda h: (0, h, 0)),         # chunk v
    ]
    args = [q, k_chunk, v_chunk]
    if P_ctx:
        M = k.shape[0]
        Dh_st = k.shape[-1]
        in_specs = ([pl.BlockSpec((1, P_ctx), lambda h: (0, 0))]
                    + in_specs
                    + [pl.BlockSpec((M, 1, Dh_st), lambda h: (0, h, 0)),
                       pl.BlockSpec((M, 1, Dh_st),
                                    lambda h: (0, h, 0))])
        args = ([jnp.reshape(pages, (1, P_ctx)).astype(jnp.int32)]
                + args + [k, v])
        if quant:
            in_specs += [pl.BlockSpec((M, 1), lambda h: (0, h)),
                         pl.BlockSpec((M, 1), lambda h: (0, h))]
            args += [k_scale, v_scale]
    return pl.pallas_call(
        kernel,
        grid=(Hkv,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((C, 1, G, Dh), lambda h: (0, h, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((C, Hkv, G, Dh), jnp.float32),
        interpret=interpret,
    )(*args)


# ---------------------------------------------------------------------------
# masked span-write kernel
# ---------------------------------------------------------------------------


def _span_write_kernel(n: int):
    """Kernel over ``n`` (span, pool) array pairs: one grid program per
    chunk page, output blocks mapped through the scalar-prefetched page
    vector, pool buffers aliased — so each program touches exactly one
    ``block_size``-token span per array. Padded rows (mask 0) keep the
    pool's old bytes: the aliased output ref still HOLDS them, so the
    masked select is a read-modify-write entirely in VMEM."""

    def kernel(pages_ref, mask_ref, *refs):
        spans = refs[:n]
        outs = refs[2 * n:]
        m = mask_ref[0] != 0                              # [bs]
        for s_ref, o_ref in zip(spans, outs):
            mv = m.reshape((1, -1) + (1,) * (o_ref.ndim - 2))
            o_ref[...] = jnp.where(mv, s_ref[...], o_ref[...])

    return kernel


def paged_span_write(pool: Dict[str, jax.Array],
                     spans: Dict[str, jax.Array],
                     pages: jax.Array, valid: jax.Array, *,
                     block_size: int,
                     interpret: bool = False) -> Dict[str, jax.Array]:
    """Write one chunk's spans into its pool pages, masked per row.

    ``pool`` maps array names to pool buffers [L, M, ...]; ``spans``
    maps the SAME names to the chunk's stacked spans [L, pc·bs, ...]
    (values and, for quantized pools, scale rows alike); ``pages``
    [pc] int32 the chunk's physical pages; ``valid`` [pc·bs] bool the
    per-row write mask (False rows keep the pool's old bytes — the RMW
    equivalent of the decode scatter's mode="drop"). Returns the
    updated pool arrays.

    Grid (pc,); each program's blocks are one page's span per array,
    placed by indexing the output BlockSpec through the scalar-
    prefetched page vector — the hand-scheduled form of the masked
    contiguous-span writes XLA emits for the fallback path, with the
    pool aliased in-place instead of round-tripping a pool-sized
    copy."""
    names = sorted(spans)
    bs = int(block_size)
    pc = int(pages.shape[0])
    n = len(names)
    mask = valid.astype(jnp.int32).reshape(pc, bs)

    def span_spec(a):
        blk = (a.shape[0], bs) + a.shape[2:]
        nd = a.ndim

        def imap(j, pg, nd=nd):
            return (0, j) + (0,) * (nd - 2)

        return pl.BlockSpec(blk, imap)

    def pool_spec(a):
        blk = (a.shape[0], bs) + a.shape[2:]
        nd = a.ndim

        def imap(j, pg, nd=nd):
            return (0, pg[j]) + (0,) * (nd - 2)

        return pl.BlockSpec(blk, imap)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(pc,),
        in_specs=([pl.BlockSpec((1, bs), lambda j, pg: (j, 0))]
                  + [span_spec(spans[nm]) for nm in names]
                  + [pool_spec(pool[nm]) for nm in names]),
        out_specs=[pool_spec(pool[nm]) for nm in names],
    )
    outs = pl.pallas_call(
        _span_write_kernel(n),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(pool[nm].shape, pool[nm].dtype)
                   for nm in names],
        # pool inputs alias the outputs: index 0 is the scalar-prefetch
        # pages, 1 the mask, 2..n+1 the spans, n+2.. the pool buffers
        input_output_aliases={2 + n + i: i for i in range(n)},
        interpret=interpret,
    )(pages.astype(jnp.int32), mask,
      *[spans[nm] for nm in names], *[pool[nm] for nm in names])
    return dict(zip(names, outs))
