"""Pallas TPU kernels — hand-scheduled fusions where XLA's automatic fusion
is insufficient (reference slot: the hand-written CUDA in
paddle/cuda/src/hl_cuda_*.cu; see /opt/skills/guides/pallas_guide.md).

Each kernel ships with a jnp reference implementation and dispatches to it
off-TPU, so the package runs everywhere; tests exercise the kernels in
Pallas interpret mode on CPU."""

from paddle_tpu.ops.pallas.attention import flash_attention  # noqa: F401
