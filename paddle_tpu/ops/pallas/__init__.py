"""Pallas TPU kernels — hand-scheduled fusions where XLA's automatic fusion
is insufficient (reference slot: the hand-written CUDA in
paddle/cuda/src/hl_cuda_*.cu; see /opt/skills/guides/pallas_guide.md).

Each kernel ships with a jnp reference implementation and dispatches to it
off-TPU, so the package runs everywhere; tests exercise the kernels in
Pallas interpret mode on CPU.

Dispatch policy — ``PADDLE_TPU_PALLAS``
---------------------------------------
One documented knob decides whether the Pallas kernels run, shared by
every kernel in this package (``attention.flash_attention``,
``decode.flash_decode_attention`` / ``decode.fused_sample`` and whatever
lands next):

- ``auto`` (default) — kernels on TPU, jnp/XLA fallback elsewhere;
- ``on``        — compile the kernels on the current backend;
- ``off``       — always the pure-XLA fallback (the path every feature
  keeps available — correctness never depends on Pallas);
- ``interpret`` — run the kernels through the Pallas interpreter (the
  CPU correctness path tier-1 exercises).

Precedence: explicit call-site argument > ``PADDLE_TPU_PALLAS`` env >
``auto`` (tested in tests/test_pallas_decode.py::TestPallasPolicy).
"""

from paddle_tpu.ops.pallas.policy import (  # noqa: F401
    PALLAS_MODES, pallas_mode)

from paddle_tpu.ops.pallas.attention import flash_attention  # noqa: F401
from paddle_tpu.ops.pallas.decode import (  # noqa: F401,E402
    flash_decode_attention, fused_sample)
from paddle_tpu.ops.pallas.prefill import (  # noqa: F401,E402
    flash_chunk_prefill, paged_span_write)
