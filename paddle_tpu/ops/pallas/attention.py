"""Flash attention — streaming-softmax fused attention Pallas kernel.

Replaces the materialised [B, H, T, T] score tensor of plain attention
(parallel/ring.py full_attention) with an online-softmax accumulation over
key blocks, so HBM traffic is O(T·D) instead of O(T²) and long sequences
stop being memory-bound (the capability slot of the reference's hand-fused
CUDA attention-precursors, paddle/cuda/src/hl_cuda_sequence.cu; design per
the public FlashAttention recipe on the MXU).

Layout: q/k/v are [B, T, H, D] (the framework's attention layout). The
kernel grids over (batch·heads, query blocks) with an inner
``lax.fori_loop`` over key blocks; running max/denominator live in VMEM
scratch. Backward is a second Pallas kernel gridded over key blocks that
streams query blocks, reconstructing p exactly from the saved logsumexp —
no O(T²) tensor exists in either direction; dq accumulates in an fp32
output revisited across key-block grid steps.

Off-TPU the public entry falls back to the jnp reference; tests run the
kernel in interpret mode.
"""

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30

# ---------------------------------------------------------------------------
# block-size selection
# ---------------------------------------------------------------------------

# usable per-core VMEM on current TPUs (v4/v5 families ship 16 MiB; leave
# compiler headroom for spills, semaphores and double-buffering)
VMEM_BYTES = int(16 * 2**20 * 0.85)

# measured-best blocks keyed (seq_bucket, head_dim, dtype_name) — filled
# from on-chip sweeps (benchmarks/tune_flash_blocks.py); consulted before
# the analytic default. seq buckets are powers of two (lookup rounds up).
MEASURED_BLOCKS = {
    # (2048, 64, "float32"): (128, 128) measured 1.58x tokens/sec vs plain
    (2048, 64, "float32"): (128, 128),
    (2048, 64, "bfloat16"): (128, 128),
}


def _vmem_working_set(tp: int, d: int, bq: int, bk: int,
                      itemsize: int) -> int:
    """Upper-bound VMEM residency of one grid program, max over the fwd
    and bwd kernels. fwd holds the whole padded K/V ([tp, d] each) plus a
    q/out block; bwd streams q/do/dq whole ([tp, d] each, dq in fp32)
    against one k/v block. Row stats ride in [tp] fp32 pairs."""
    stats = 2 * tp * 4                        # lse + delta (fp32)
    scores = bq * bk * 4                      # p / ds tile (fp32)
    fwd = (2 * tp * d * itemsize              # k, v whole
           + 2 * bq * d * itemsize            # q, out blocks
           + bq * d * 4                       # fp32 accumulator
           + stats + scores)
    bwd = (2 * tp * d * itemsize              # q, do whole
           + tp * d * 4                       # dq whole (fp32 accumulator)
           + 4 * bk * d * itemsize            # k, v, dk, dv blocks
           + stats + scores)
    return max(fwd, bwd)


def select_block_sizes(seq: int, head_dim: int, dtype) -> Tuple[int, int]:
    """(block_q, block_k) for the flash kernels, keyed on the problem
    shape: a measured table first, then the analytic default (128, 128 —
    the MXU-native tile), always validated against the VMEM budget.
    Raises with a actionable message when no block choice can fit —
    the caller should shard the sequence (ring attention) instead of
    letting Mosaic fail opaquely."""
    itemsize = jnp.dtype(dtype).itemsize
    name = jnp.dtype(dtype).name
    bucket = 1 << max(0, (seq - 1)).bit_length()     # next pow2 >= seq
    found = MEASURED_BLOCKS.get((bucket, head_dim, name))
    candidates = ([found] if found else []) + [(128, 128), (128, 256),
                                               (256, 128), (64, 128),
                                               (128, 64), (64, 64)]
    for bq, bk in candidates:
        bq_c, bk_c = min(bq, seq), min(bk, seq)
        tp = _pad_to_blocks(seq, bq_c, bk_c)
        if _vmem_working_set(tp, head_dim, bq_c, bk_c,
                             itemsize) <= VMEM_BYTES:
            return bq_c, bk_c
    raise ValueError(
        f"flash attention: no block size fits seq={seq} head_dim="
        f"{head_dim} dtype={name} in ~{VMEM_BYTES >> 20} MiB VMEM — the "
        f"whole K/V must reside per grid program. Shard the sequence "
        f"(use_ring_attention over a seq mesh axis) or reduce head_dim.")


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale, causal,
                block_q, block_k, seq_len):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale          # [block_q, D]
    m = jnp.full((block_q,), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    padded_len = k_ref.shape[1]
    num_k = padded_len // block_k
    if causal:
        # only key blocks at or before this query block contribute
        num_k = jax.lax.min(num_k, (qi * block_q + block_q + block_k - 1)
                            // block_k)

    def body(ki, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(ki * block_k, block_k)].astype(jnp.float32)
        v = v_ref[0, pl.ds(ki * block_k, block_k)].astype(jnp.float32)
        s = q @ k.T                                      # [block_q, block_k]
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = k_pos < seq_len                          # mask tail padding
        if causal:
            valid = valid & (q_pos >= k_pos)
        s = jnp.where(valid, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + p @ v
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_k, body, (m, l, acc))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # row stats are stored [BH, num_q_blocks, block_q] with block_q on the
    # TPU lane dim — a [T]-shaped output would need a (1, block_q) block,
    # which the (8, 128) tiling rejects, and lane-replicating to 128 wide
    # costs 128x VMEM in the backward's whole-array block. The lse block
    # here spans ALL q-blocks and is revisited consecutively across the
    # inner q grid dim (each program writes its own row), so it flushes
    # once per batch·head.
    lse_ref[0, qi] = m + jnp.log(l_safe)


def _pad_to_blocks(t, block_q, block_k):
    """Common padded length for fwd and bwd — they must agree exactly (the
    backward reconstructs p from the forward's lse), and it must be a
    multiple of BOTH block sizes: the compact row-stats layout reshapes
    [tp] -> [tp // block_q, block_q]."""
    lcm = math.lcm(block_q, block_k)
    return -(-t // lcm) * lcm


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    """q/k/v: [BH, T, D] → (out [BH, T, D], lse [BH, T]). T is padded up to
    a block multiple so dynamic slices never clamp; padded keys are masked
    by position, padded query rows are sliced away."""
    bh, t, d = q.shape
    tp = _pad_to_blocks(t, block_q, block_k)
    if tp != t:
        pad = ((0, 0), (0, tp - t), (0, 0))
        q, k, v = (jnp.pad(a, pad) for a in (q, k, v))
    nq = tp // block_q
    grid = (bh, nq)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, seq_len=t)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, tp, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, tp, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, nq, block_q), lambda b, i: (b, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tp, d), q.dtype),
            jax.ShapeDtypeStruct((bh, nq, block_q), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out[:, :t], lse.reshape(bh, tp)[:, :t]


def _reference(q, k, v, sm_scale, causal):
    """jnp reference ([BH, T, D] layout), also the off-TPU fallback."""
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        t = q.shape[1]
        i = jnp.arange(t)
        s = jnp.where(i[:, None] >= i[None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)
                      ).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k,
                        interpret)
    return out


def _flash_vjp_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k,
                          interpret)
    return out, (q, k, v, out, lse)


def _bwd_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dq_ref, dk_ref, dv_ref, *, sm_scale, causal, block_q,
                block_k, seq_len):
    """Backward over one KEY block (grid: batch·heads × key blocks).

    Inner loop streams query blocks; p is reconstructed exactly from the
    stored logsumexp, ds from the precomputed delta = Σ(do·out), so no
    [T, T] tensor ever exists. dk/dv accumulate locally; dq accumulates
    into its output ref across key-block grid steps (revisited output
    block — the TPU grid is sequential, so += is race-free); the dq
    output is fp32 so the repeated read-modify-write never rounds in
    bf16."""
    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)                     # [block_k, D]
    v = v_ref[0].astype(jnp.float32)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    padded_len = q_ref.shape[1]
    num_q = padded_len // block_q
    q_start = (ki * block_k) // block_q if causal else 0

    dk = jnp.zeros_like(k)
    dv = jnp.zeros_like(v)

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qi * block_q, block_q)].astype(jnp.float32)
        do = do_ref[0, pl.ds(qi * block_q, block_q)].astype(jnp.float32)
        lse = lse_ref[0, qi]                             # [block_q]
        delta = delta_ref[0, qi]
        s = (q @ k.T) * sm_scale                         # [block_q, block_k]
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        valid = (k_pos < seq_len) & (q_pos < seq_len)
        if causal:
            valid = valid & (q_pos >= k_pos)
        p = jnp.where(valid, jnp.exp(s - lse[:, None]), 0.0)
        dv = dv + p.T @ do
        dp = do @ v.T
        ds = p * (dp - delta[:, None]) * sm_scale
        dq_ref[0, pl.ds(qi * block_q, block_q)] += (ds @ k).astype(
            dq_ref.dtype)
        dk = dk + ds.T @ q
        return dk, dv

    @pl.when(ki == 0)
    def _init():
        dq_ref[0] = jnp.zeros_like(dq_ref[0])

    dk, dv = jax.lax.fori_loop(q_start, num_q, body, (dk, dv))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, out, lse, do, sm_scale, causal, block_q,
                      block_k, interpret):
    bh, t, d = q.shape
    tp = _pad_to_blocks(t, block_q, block_k)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                              # [BH, T]
    if tp != t:
        pad3 = ((0, 0), (0, tp - t), (0, 0))
        pad2 = ((0, 0), (0, tp - t))
        q, k, v, do = (jnp.pad(a, pad3) for a in (q, k, v, do))
        # padded lse must stay finite: exp(s - lse) with lse=0 on padded
        # rows is masked out by `valid` anyway
        lse = jnp.pad(lse, pad2)
        delta = jnp.pad(delta, pad2)
    # compact row-stats layout, block_q on the lane dim (see _fwd_kernel)
    nq = tp // block_q
    lse = lse.reshape(bh, nq, block_q)
    delta = delta.reshape(bh, nq, block_q)
    kernel = functools.partial(
        _bwd_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, seq_len=t)
    grid = (bh, tp // block_k)
    dq, dk, dv = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, tp, d), lambda b, i: (b, 0, 0)),   # q
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),  # k
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),  # v
            pl.BlockSpec((1, tp, d), lambda b, i: (b, 0, 0)),   # do
            pl.BlockSpec((1, nq, block_q), lambda b, i: (b, 0, 0)),  # lse
            pl.BlockSpec((1, nq, block_q), lambda b, i: (b, 0, 0)),  # delta
        ],
        out_specs=[
            pl.BlockSpec((1, tp, d), lambda b, i: (b, 0, 0)),   # dq
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),  # dk
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),  # dv
        ],
        out_shape=[
            # dq accumulates across key-block revisits: keep it fp32 so
            # a bf16 read-modify-write chain can't round away increments
            jax.ShapeDtypeStruct((bh, tp, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, tp, d), k.dtype),
            jax.ShapeDtypeStruct((bh, tp, d), v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq[:, :t].astype(q.dtype), dk[:, :t], dv[:, :t]


def _flash_vjp_bwd(sm_scale, causal, block_q, block_k, interpret, res, do):
    """Backward from saved (q, k, v, out, lse) — a Pallas kernel streaming
    query blocks per key block, so no O(T²) tensor exists in backward
    either; p/ds reconstruct exactly from the stored logsumexp."""
    q, k, v, out, lse = res
    return _flash_bwd_pallas(q, k, v, out, lse, do, sm_scale, causal,
                             block_q, block_k, interpret)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_block_fwd(q, k, v, sm_scale, causal, block_q=128, block_k=128,
                    interpret=False):
    """Public block-level entry for composed attentions (ring/context
    parallelism): returns (normalized out, logsumexp) for one q-shard
    against one k/v-block, both [BH, T, D]. The caller folds blocks with
    the logsumexp combination rule and drives the backward itself via
    flash_block_bwd (see parallel/ring.ring_flash_attention)."""
    return _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k,
                      interpret)


def flash_block_bwd(q, k, v, out, lse, do, sm_scale, causal,
                    block_q=128, block_k=128, interpret=False):
    """Block-level backward: gradients of sum(out·do) for one q-shard
    against one k/v-block, given the GLOBAL logsumexp (the flash backward
    identity p = exp(s − lse) is exact under any block partition of the
    keys when lse is the all-blocks logsumexp)."""
    return _flash_bwd_pallas(q, k, v, out, lse, do, sm_scale, causal,
                             block_q, block_k, interpret)


def flash_attention(q, k, v, *, causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None):
    """Fused attention. q: [B, T, H, D], k/v: [B, T, Hkv, D] with
    H % Hkv == 0 → [B, T, H, D].

    Hkv < H (grouped-query attention) is expanded to the q-head layout
    here — a single-device layout concern only; the distributed ring path
    (parallel/ring.py) keeps collectives at Hkv heads and expands locally
    per ring step. Dispatch resolves through the package-wide
    ``PADDLE_TPU_PALLAS`` policy (``ops/pallas/policy.py``): ``auto``
    keeps the historical behaviour — kernel on TPU, jnp reference
    elsewhere — while the env var (or the ``interpret`` arg, which wins
    over it: True pins the interpreter, False the compiled kernel) can
    force any path on any backend."""
    from paddle_tpu.ops.pallas import policy as _policy
    b, t, h, d = q.shape
    if k.shape[2] != h:
        k = jnp.repeat(k, h // k.shape[2], axis=2)
        v = jnp.repeat(v, h // v.shape[2], axis=2)
    sm_scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    qr = q.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    mode = _policy.pallas_mode(
        None if interpret is None else
        ("interpret" if interpret else "on"))
    if mode == "off":
        out = _reference(qr, kr, vr, sm_scale, causal)
    else:
        # shape-keyed selection (measured table + VMEM-fit validation)
        # only when the caller didn't pin blocks — explicit args must
        # keep working on shapes the analytic model would reject
        # (tuning sweeps, CPU interpret runs)
        if block_q and block_k:
            bq, bk = min(block_q, t), min(block_k, t)
        else:
            bq_auto, bk_auto = select_block_sizes(t, d, q.dtype)
            bq = min(block_q, t) if block_q else bq_auto
            bk = min(block_k, t) if block_k else bk_auto
        out = _flash(qr, kr, vr, sm_scale, causal, bq, bk,
                     mode == "interpret")
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
