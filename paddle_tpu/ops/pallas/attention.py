"""Flash attention — streaming-softmax fused attention Pallas kernel.

Replaces the materialised [B, H, T, T] score tensor of plain attention
(parallel/ring.py full_attention) with an online-softmax accumulation over
key blocks, so HBM traffic is O(T·D) instead of O(T²) and long sequences
stop being memory-bound (the capability slot of the reference's hand-fused
CUDA attention-precursors, paddle/cuda/src/hl_cuda_sequence.cu; design per
the public FlashAttention recipe on the MXU).

Layout: q/k/v are [B, T, H, D] (the framework's attention layout). The
kernel grids over (batch·heads, query blocks) with an inner
``lax.fori_loop`` over key blocks; running max/denominator live in VMEM
scratch. Backward is a custom VJP that recomputes attention blockwise with
XLA from the saved (out, logsumexp) — fwd memory stays O(T·D).

Off-TPU the public entry falls back to the jnp reference; tests run the
kernel in interpret mode.
"""

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale, causal,
                block_q, block_k, seq_len):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale          # [block_q, D]
    m = jnp.full((block_q,), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    padded_len = k_ref.shape[1]
    num_k = padded_len // block_k
    if causal:
        # only key blocks at or before this query block contribute
        num_k = jax.lax.min(num_k, (qi * block_q + block_q + block_k - 1)
                            // block_k)

    def body(ki, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(ki * block_k, block_k)].astype(jnp.float32)
        v = v_ref[0, pl.ds(ki * block_k, block_k)].astype(jnp.float32)
        s = q @ k.T                                      # [block_q, block_k]
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = k_pos < seq_len                          # mask tail padding
        if causal:
            valid = valid & (q_pos >= k_pos)
        s = jnp.where(valid, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + p @ v
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_k, body, (m, l, acc))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l_safe)


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    """q/k/v: [BH, T, D] → (out [BH, T, D], lse [BH, T]). T is padded up to
    a block multiple so dynamic slices never clamp; padded keys are masked
    by position, padded query rows are sliced away."""
    bh, t, d = q.shape
    tq = -(-t // block_q) * block_q
    tk = -(-t // block_k) * block_k
    tp = max(tq, tk)
    if tp != t:
        pad = ((0, 0), (0, tp - t), (0, 0))
        q, k, v = (jnp.pad(a, pad) for a in (q, k, v))
    grid = (bh, tp // block_q)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, seq_len=t)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, tp, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, tp, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tp, d), q.dtype),
            jax.ShapeDtypeStruct((bh, tp), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out[:, :t], lse[:, :t]


def _reference(q, k, v, sm_scale, causal):
    """jnp reference ([BH, T, D] layout), also the off-TPU fallback."""
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        t = q.shape[1]
        i = jnp.arange(t)
        s = jnp.where(i[:, None] >= i[None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)
                      ).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k,
                        interpret)
    return out


def _flash_vjp_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k,
                          interpret)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(sm_scale, causal, block_q, block_k, interpret, res, do):
    """Backward from saved (q, k, v, out, lse): p is recomputed exactly via
    the stored logsumexp, so no O(T²) tensor was saved in forward. XLA
    handles the recompute contraction chain (it is matmul-shaped and
    MXU-friendly); the kernel win is the forward's memory profile."""
    q, k, v, out, lse = res
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    s = jnp.einsum("bqd,bkd->bqk", qf, kf) * sm_scale
    if causal:
        t = q.shape[1]
        i = jnp.arange(t)
        s = jnp.where(i[:, None] >= i[None, :], s, NEG_INF)
    p = jnp.exp(s - lse[..., None])                       # exact softmax
    dv = jnp.einsum("bqk,bqd->bkd", p, dof)
    dp = jnp.einsum("bqd,bkd->bqk", dof, vf)
    delta = jnp.sum(dof * out.astype(jnp.float32), axis=-1)  # [BH, T]
    ds = p * (dp - delta[..., None]) * sm_scale
    dq = jnp.einsum("bqk,bkd->bqd", ds, kf).astype(q.dtype)
    dk = jnp.einsum("bqk,bqd->bkd", ds, qf).astype(k.dtype)
    return dq, dk, dv.astype(v.dtype)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, *, causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None):
    """Fused attention. q/k/v: [B, T, H, D] → [B, T, H, D].

    Dispatches to the Pallas kernel on TPU (or interpret mode when forced);
    off-TPU uses the jnp reference so behaviour is identical everywhere."""
    b, t, h, d = q.shape
    sm_scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    qr = q.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    on_tpu = jax.devices()[0].platform == "tpu"
    if interpret is None and not on_tpu:
        out = _reference(qr, kr, vr, sm_scale, causal)
    else:
        bq = min(block_q, t)
        bk = min(block_k, t)
        out = _flash(qr, kr, vr, sm_scale, causal, bq, bk,
                     bool(interpret))
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
