"""Streaming conv + batch-norm statistics — Pallas kernels.

The ResNet-50 train step is HBM-bandwidth-bound (BENCHMARKS.md roofline:
~99% of peak, 74.9 GB/step); the reducible traffic is whole-activation
passes. Standard BN reads the conv output once just to reduce per-channel
Σy and Σy² before the normalize pass re-reads it. These kernels emit the
statistics from the convolution's OWN epilogue — the fp32 accumulator tile
is reduced in-register before it is cast and written — eliminating the
stats pass over every BN'd activation (capability slot of the reference's
fused CudnnBatchNormLayer, paddle/gserver/layers/CudnnBatchNormLayer.cpp;
hand-fused conv epilogues, paddle/cuda/src/hl_cuda_cnn.cu).

Two kernels cover ResNet's conv menu:
- ``matmul_bn_stats`` — 1×1 convs (any stride, via pre-slice) as a GEMM
  over [M, C] with a per-channel Σ/Σ² epilogue. In bottleneck ResNet the
  1×1 convs carry 2 of every 3 BN'd activations.
- ``conv3x3_bn_stats`` — 3×3 stride-1 SAME convs as 9 shifted GEMMs
  accumulated in VMEM (whole padded image resident per batch element),
  same epilogue.
Everything else (the 7×7/s2d stem) falls back to XLA conv + jnp reduce.

``conv_bn_train`` is the fused train-mode op with a closed-form VJP: the
cotangent w.r.t. the conv output is exactly the batch-norm dx formula
(two passes over dy/y), after which the conv backward itself is delegated
to XLA's conv VJP (its MXU conv backward is already optimal — the win
here is forward-traffic only).
"""

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl


def _on_tpu():
    return jax.devices()[0].platform == "tpu"


def _dispatch(stride, padding, interpret):
    """Shared forward/backward kernel gating: normalized stride, SAME-ness,
    1x1-eligibility and whether the Pallas path runs (identical conditions
    both ways). pad0: paddings under which a 1x1 conv is a plain GEMM —
    a nonzero integer padding changes the output spatial dims, which the
    GEMM path would silently ignore, so it must fall back to XLA conv."""
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    same = (padding == "SAME" or padding == ((1, 1), (1, 1))
            or padding == 1)
    pad0 = padding in ("SAME", "VALID", 0, (0, 0), ((0, 0), (0, 0)))
    if interpret is None and FORCE_INTERPRET:
        interpret = True
    use_kernel = interpret if interpret is not None else _on_tpu()
    return s, same, pad0, use_kernel, interpret


# tests monkeypatch this to drive the Pallas kernels in interpret mode
# through the full layer/model stack on CPU
FORCE_INTERPRET = False

# Mitigation knob for Mosaic tiling limits on small-spatial stages (the
# 7x7 blocks; int8 min tile is (32, 128), bf16 (16, 128)): 3x3 kernel
# paths are taken only when the image W dim is >= this. 0 = always take
# the kernel (default; flip to 16/32 from the on-chip session if the
# smoke step shows small-spatial lowering failures — affected layers
# then fall back to XLA conv + jnp stats, losing only their share of
# the fused saving).
MIN_SPATIAL_FOR_KERNEL = 0


# ---------------------------------------------------------------------------
# GEMM + stats (1x1 convs)
# ---------------------------------------------------------------------------

# stats rows ride in an (8, K) block: 8 matches the sublane tile (a
# (1..2, K) output block is exactly the shape this chip's Mosaic tiling
# rejects — see the lse layout note in ops/pallas/attention.py) and the
# row updates are iota-selects, not 1-D row stores. Row 0 = Σy,
# row 1 = Σy²; rows 2..7 are padding.
_STATS_ROWS = 8


def _stats_update(s1, s2, bk):
    """(8, bk) update tensor holding s1 in row 0 and s2 in row 1."""
    rows = lax.broadcasted_iota(jnp.int32, (_STATS_ROWS, bk), 0)
    return (jnp.where(rows == 0, s1[None, :], 0.0)
            + jnp.where(rows == 1, s2[None, :], 0.0))


def _mm_stats_kernel(x_ref, w_ref, y_ref, stats_ref, *, bm, bk, m_total):
    ki = pl.program_id(0)
    mi = pl.program_id(1)
    del ki  # the stats block is selected by the BlockSpec index map —
    # a dynamic lane-dim slice here is what Mosaic rejects ("cannot
    # statically prove index in dimension 1 is a multiple of 128")

    @pl.when(mi == 0)
    def _init():
        stats_ref[...] = jnp.zeros_like(stats_ref)

    x = x_ref[...].astype(jnp.float32)              # [bm, C]
    w = w_ref[...].astype(jnp.float32)              # [C, bk]
    acc = x @ w                                     # fp32 on the MXU
    y_ref[...] = acc.astype(y_ref.dtype)
    # epilogue: per-channel sums of the UNROUNDED accumulator; padded
    # rows (beyond m_total) are masked out of the statistics
    rows = mi * bm + lax.broadcasted_iota(jnp.int32, (bm, 1), 0)
    valid = (rows < m_total).astype(jnp.float32)
    accv = acc * valid
    stats_ref[...] += _stats_update(jnp.sum(accv, axis=0),
                                    jnp.sum(accv * acc, axis=0), bk)


def matmul_bn_stats(x2: jax.Array, w2: jax.Array, *, out_dtype=None,
                    block_m: int = 256, block_k: int = 128,
                    interpret: bool = False):
    """y = x2 @ w2 with per-output-channel (Σy, Σy²) from the epilogue.

    x2: [M, C]; w2: [C, K] → (y [M, K], sum [K], sumsq [K]); sums are over
    the fp32 accumulator (pre-cast), masked to the true M rows."""
    m, c = x2.shape
    k = w2.shape[1]
    out_dtype = out_dtype or x2.dtype
    bm = min(block_m, max(8, m))
    bk = min(block_k, k)
    mp = -(-m // bm) * bm
    kp = -(-k // bk) * bk
    if mp != m:
        x2 = jnp.pad(x2, ((0, mp - m), (0, 0)))
    if kp != k:
        w2 = jnp.pad(w2, ((0, 0), (0, kp - k)))
    # ki is the OUTER grid dim: for each stats block the mi sweep is a
    # run of consecutive revisits (accumulate in VMEM, one writeback);
    # the block's lane offset comes from the index map, never a dynamic
    # in-kernel slice.
    grid = (kp // bk, mp // bm)
    kernel = functools.partial(_mm_stats_kernel, bm=bm, bk=bk, m_total=m)
    y, stats = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, c), lambda ki, mi: (mi, 0)),
            pl.BlockSpec((c, bk), lambda ki, mi: (0, ki)),
        ],
        out_specs=[
            pl.BlockSpec((bm, bk), lambda ki, mi: (mi, ki)),
            pl.BlockSpec((_STATS_ROWS, bk), lambda ki, mi: (0, ki)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, kp), out_dtype),
            jax.ShapeDtypeStruct((_STATS_ROWS, kp), jnp.float32),
        ],
        interpret=interpret,
    )(x2, w2)
    return y[:m, :k], stats[0, :k], stats[1, :k]


# ---------------------------------------------------------------------------
# 3x3 stride-1 SAME conv + stats
# ---------------------------------------------------------------------------

def _conv3_stats_kernel(x_ref, w_ref, y_ref, stats_ref, *, bh, wdim, kdim):
    ni = pl.program_id(0)
    hi = pl.program_id(1)

    @pl.when((ni == 0) & (hi == 0))
    def _init():
        stats_ref[...] = jnp.zeros_like(stats_ref)

    h0 = hi * bh
    acc = jnp.zeros((bh * wdim, kdim), jnp.float32)
    for dy in range(3):
        for dx in range(3):
            xs = x_ref[0, pl.ds(h0 + dy, bh), pl.ds(dx, wdim), :]
            xs = xs.reshape(bh * wdim, xs.shape[-1]).astype(jnp.float32)
            acc += xs @ w_ref[dy, dx].astype(jnp.float32)
    y_ref[0] = acc.reshape(bh, wdim, kdim).astype(y_ref.dtype)
    stats_ref[...] += _stats_update(jnp.sum(acc, axis=0),
                                    jnp.sum(acc * acc, axis=0), kdim)


def conv3x3_bn_stats(x: jax.Array, w: jax.Array, *, out_dtype=None,
                     block_h: Optional[int] = None,
                     interpret: bool = False):
    """3×3 stride-1 SAME conv with the stats epilogue.

    x: [N, H, W, C]; w: [3, 3, C, K] → (y [N, H, W, K], sum [K],
    sumsq [K]). The whole zero-padded image of one batch element is VMEM-
    resident per grid step (ResNet's 3×3 shapes top out at ~0.5 MB)."""
    n, h, wd, c = x.shape
    k = w.shape[-1]
    out_dtype = out_dtype or x.dtype
    if block_h is None:
        # largest divisor of H keeping the accumulator tile under ~1 MiB
        budget = (1 << 20) // max(1, wd * k * 4)
        block_h = max(d for d in range(1, h + 1)
                      if h % d == 0 and d <= max(1, budget))
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    grid = (n, h // block_h)
    kernel = functools.partial(_conv3_stats_kernel, bh=block_h, wdim=wd,
                               kdim=k)
    y, stats = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, h + 2, wd + 2, c), lambda ni, hi: (ni, 0, 0, 0)),
            pl.BlockSpec((3, 3, c, k), lambda ni, hi: (0, 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_h, wd, k), lambda ni, hi: (ni, hi, 0, 0)),
            pl.BlockSpec((_STATS_ROWS, k), lambda ni, hi: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h, wd, k), out_dtype),
            jax.ShapeDtypeStruct((_STATS_ROWS, k), jnp.float32),
        ],
        interpret=interpret,
    )(xp, w)
    return y, stats[0], stats[1]


# ---------------------------------------------------------------------------
# fused BACKWARD kernels (1x1 path): the BN-backward elementwise stage
# g = γ·inv/n · (n·dy − A − ẑ·inv·B) is recomputed IN-REGISTER inside the
# conv-backward GEMMs, so the g tensor is never written to or read from
# HBM (the write + two reads the unfused backward pays). ẑ is the
# centered conv output — exactly what save8 stashes.
# ---------------------------------------------------------------------------

# per-channel backward constants ride in ONE (8, K) block — single rows
# like (1, K) are exactly the block shape this chip's Mosaic tiling
# rejects (see ops/pallas/attention.py lse layout note); 8 rows match
# the sublane tile. Row layout: 0=γ·inv/n, 1=inv·B, 2=A=Σdy, 3=z scale.
#
# int8 tiling caveat (pallas_guide: int8 min tile is (32, 128)): the
# int8 stash blocks at the 7×7 stages have sublane dims below 32, which
# Mosaic may pad or reject on real hardware — the on-chip queue's smoke
# step exercises both extreme shapes before any A/B; if the small-
# spatial case fails to lower, gate save8's kernel path on H*W ≥ 32
# (the fallback dequantizes outside, losing only that stage's savings).
_CHAN_ROWS = 8


def _pack_chan(coef, inv_b, a_sum, z_scale):
    k = coef.shape[0]
    chan = jnp.zeros((_CHAN_ROWS, k), jnp.float32)
    return chan.at[0].set(coef).at[1].set(inv_b).at[2].set(a_sum)                .at[3].set(z_scale)


def _g_tile(z_raw, dy, chan, n):
    """g for one [bm, K] tile, fp32. z_raw is the centered conv output —
    int8 stash (dequantized in-register via chan row 3) or full-width."""
    z = z_raw.astype(jnp.float32)
    if z_raw.dtype == jnp.int8:
        z = z * chan[3]
    return chan[0] * (n * dy - chan[2] - z * chan[1])


def _mm_bwd_dx_kernel(z_ref, dy_ref, wt_ref, chan_ref, dx_ref, *,
                      n_total):
    dy = dy_ref[...].astype(jnp.float32)
    g = _g_tile(z_ref[...], dy, chan_ref[...], n_total)
    dx_ref[...] = (g @ wt_ref[...].astype(jnp.float32)).astype(
        dx_ref.dtype)


def _mm_bwd_dw_kernel(x_ref, z_ref, dy_ref, chan_ref, xs_ref, dw_ref, *,
                      n_total):
    mi = pl.program_id(0)

    @pl.when(mi == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)

    dy = dy_ref[...].astype(jnp.float32)
    g = _g_tile(z_ref[...], dy, chan_ref[...], n_total)
    x = x_ref[...].astype(jnp.float32)
    if x_ref.dtype == jnp.int8:
        x = x * xs_ref[0]                    # in-register dequant
    dw_ref[...] += x.T @ g


def matmul_bn_bwd(x2, z2, dy2, w2, gamma, inv, a_sum, b_sum, *,
                  x_scale=None, z_scale=None, out_dtype=None,
                  block_m: int = 256, interpret: bool = False):
    """Fused backward for the 1x1 path: given the centered conv output
    z2 [M, K] (full-width, or the int8 stash with per-channel z_scale),
    upstream dy2 [M, K], and the per-channel BN reduction results
    A = Σdy, B = Σdy·ẑ (ẑ = z·inv), returns (dx [M, C], dw [C, K]) with
    g recomputed per tile — no g tensor in HBM. x2 may likewise be the
    int8 stash (pass x_scale); dequantization happens IN-REGISTER so
    the kernels genuinely read 1 byte/element."""
    m, c = x2.shape
    k = w2.shape[1]
    n_total = float(m)
    out_dtype = out_dtype or (x2.dtype if x2.dtype != jnp.int8
                              else jnp.float32)
    coef = gamma.astype(jnp.float32) * inv / n_total
    inv_b = inv * b_sum.astype(jnp.float32)
    a_row = a_sum.astype(jnp.float32)
    zs = (z_scale.astype(jnp.float32) if z_scale is not None
          else jnp.ones((k,), jnp.float32))
    chan = _pack_chan(coef, inv_b, a_row, zs)
    xs_row = jnp.zeros((_CHAN_ROWS, c), jnp.float32).at[0].set(
        x_scale.astype(jnp.float32) if x_scale is not None
        else jnp.ones((c,), jnp.float32))
    bm = min(block_m, max(8, m))
    mp = -(-m // bm) * bm
    if mp != m:
        pad = ((0, mp - m), (0, 0))
        x2, z2, dy2 = (jnp.pad(t, pad) for t in (x2, z2, dy2))
        # zero-padded rows would get g = coef·(−A) ≠ 0 (the −A constant
        # term survives); pad dy with A/n instead so g_pad ≡ 0 exactly
        # (z_pad = 0): then padded dx rows are sliced off and padded x
        # rows (zeros) contribute nothing to dw either way
        fill = (a_row[None, :] / n_total).astype(dy2.dtype)   # [1, K]
        dy2 = dy2.at[m:, :].set(jnp.broadcast_to(fill, (mp - m, k)))
    grid = (mp // bm,)
    # dx: g @ w^T
    dx = pl.pallas_call(
        functools.partial(_mm_bwd_dx_kernel, n_total=n_total),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, k), lambda mi: (mi, 0)),      # z
            pl.BlockSpec((bm, k), lambda mi: (mi, 0)),      # dy
            pl.BlockSpec((k, c), lambda mi: (0, 0)),        # w^T
            pl.BlockSpec((_CHAN_ROWS, k), lambda mi: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bm, c), lambda mi: (mi, 0)),
        out_shape=jax.ShapeDtypeStruct((mp, c), out_dtype),
        interpret=interpret,
    )(z2, dy2, jnp.swapaxes(w2, 0, 1), chan)
    # dw: x^T @ g accumulated across the m grid (sequential revisits)
    dw = pl.pallas_call(
        functools.partial(_mm_bwd_dw_kernel, n_total=n_total),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, c), lambda mi: (mi, 0)),      # x
            pl.BlockSpec((bm, k), lambda mi: (mi, 0)),      # z
            pl.BlockSpec((bm, k), lambda mi: (mi, 0)),      # dy
            pl.BlockSpec((_CHAN_ROWS, k), lambda mi: (0, 0)),
            pl.BlockSpec((_CHAN_ROWS, c), lambda mi: (0, 0)),
        ],
        out_specs=pl.BlockSpec((c, k), lambda mi: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((c, k), jnp.float32),
        interpret=interpret,
    )(x2, z2, dy2, chan, xs_row)
    return dx[:m], dw


def _conv3_bwd_dx_kernel(z_ref, dy_ref, wr_ref, chan_ref, dx_ref, *,
                         n_total):
    dy = dy_ref[0].astype(jnp.float32)               # [H, W, K]
    g = _g_tile(z_ref[0], dy, chan_ref[...], n_total)
    gp = jnp.pad(g, ((1, 1), (1, 1), (0, 0)))
    h, w, k = dy.shape
    c = wr_ref.shape[-1]
    acc = jnp.zeros((h * w, c), jnp.float32)
    for dyy in range(3):
        for dxx in range(3):
            gs = gp[dyy:dyy + h, dxx:dxx + w].reshape(h * w, k)
            acc += gs @ wr_ref[dyy, dxx].astype(jnp.float32)
    dx_ref[0] = acc.reshape(h, w, c).astype(dx_ref.dtype)


def _conv3_bwd_dw_kernel(x_ref, z_ref, dy_ref, chan_ref, xs_ref, dw_ref,
                         *, n_total):
    ni = pl.program_id(0)

    @pl.when(ni == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)

    dy = dy_ref[0].astype(jnp.float32)               # [H, W, K]
    g = _g_tile(z_ref[0], dy, chan_ref[...], n_total)
    h, w, k = dy.shape
    gf = g.reshape(h * w, k)
    for dyy in range(3):
        for dxx in range(3):
            xs = x_ref[0, pl.ds(dyy, h), pl.ds(dxx, w), :]
            xs = xs.reshape(h * w, xs.shape[-1]).astype(jnp.float32)
            if x_ref.dtype == jnp.int8:
                xs = xs * xs_ref[0]          # in-register dequant
            dw_ref[dyy, dxx] += xs.T @ gf


def conv3x3_bn_bwd(x, z, dy, w, gamma, inv, a_sum, b_sum, *,
                   x_scale=None, z_scale=None, out_dtype=None,
                   interpret: bool = False):
    """Fused backward for the 3×3 stride-1 SAME path: g recomputed
    in-register per batch element from the centered output z and dy;
    dx = conv(g, w rotated), dw = Σ x⊗g — no g tensor in HBM.
    x [N,H,W,C] and z [N,H,W,K] may be the int8 stashes (pass the
    per-channel scales; dequant happens in-register)."""
    n_, h, wd, c = x.shape
    k = w.shape[-1]
    n_total = float(n_ * h * wd)
    out_dtype = out_dtype or (x.dtype if x.dtype != jnp.int8
                              else jnp.float32)
    chan = _pack_chan(
        gamma.astype(jnp.float32) * inv / n_total,
        inv * b_sum.astype(jnp.float32),
        a_sum.astype(jnp.float32),
        z_scale.astype(jnp.float32) if z_scale is not None
        else jnp.ones((k,), jnp.float32))
    xs_row = jnp.zeros((_CHAN_ROWS, c), jnp.float32).at[0].set(
        x_scale.astype(jnp.float32) if x_scale is not None
        else jnp.ones((c,), jnp.float32))
    # rotated filters: dx's conv uses w[2-dy, 2-dx] with in/out swapped
    wr = jnp.flip(jnp.flip(w, 0), 1).transpose(0, 1, 3, 2)  # [3,3,K,C]
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    grid = (n_,)
    dx = pl.pallas_call(
        functools.partial(_conv3_bwd_dx_kernel, n_total=n_total),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, h, wd, k), lambda ni: (ni, 0, 0, 0)),
            pl.BlockSpec((1, h, wd, k), lambda ni: (ni, 0, 0, 0)),
            pl.BlockSpec((3, 3, k, c), lambda ni: (0, 0, 0, 0)),
            pl.BlockSpec((_CHAN_ROWS, k), lambda ni: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, wd, c), lambda ni: (ni, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n_, h, wd, c), out_dtype),
        interpret=interpret,
    )(z, dy, wr, chan)
    dw = pl.pallas_call(
        functools.partial(_conv3_bwd_dw_kernel, n_total=n_total),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, h + 2, wd + 2, c), lambda ni: (ni, 0, 0, 0)),
            pl.BlockSpec((1, h, wd, k), lambda ni: (ni, 0, 0, 0)),
            pl.BlockSpec((1, h, wd, k), lambda ni: (ni, 0, 0, 0)),
            pl.BlockSpec((_CHAN_ROWS, k), lambda ni: (0, 0)),
            pl.BlockSpec((_CHAN_ROWS, c), lambda ni: (0, 0)),
        ],
        out_specs=pl.BlockSpec((3, 3, c, k), lambda ni: (0, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((3, 3, c, k), jnp.float32),
        interpret=interpret,
    )(xp, z, dy, chan, xs_row)
    return dx, dw


# ---------------------------------------------------------------------------
# dispatch + fused train op
# ---------------------------------------------------------------------------

def conv_bn_stats(x, w, *, stride=1, padding="SAME",
                  interpret: Optional[bool] = None):
    """(conv(x, w), Σy, Σy²) with the stats from the conv epilogue when a
    streaming kernel covers the shape; XLA conv + jnp reduce otherwise.
    Returns (y, sum, sumsq) — sums per output channel over N·H·W."""
    from paddle_tpu.ops import conv as ops_conv

    from paddle_tpu.core import dtypes

    # honor the global MXU compute-dtype policy exactly like
    # ops_conv.conv2d does — the fused and unfused paths must emit the
    # SAME dtype or the custom-VJP cotangents mismatch downstream
    cdt = dtypes.compute_dtype()
    x = x.astype(cdt)
    w = w.astype(cdt)
    kh, kw = w.shape[0], w.shape[1]
    s, same, pad0, use_kernel, interpret = _dispatch(stride, padding,
                                                     interpret)
    if use_kernel and kh == 1 and kw == 1 and pad0:
        xs = x[:, ::s[0], ::s[1], :]
        n, ho, wo, c = xs.shape
        y2, s1, s2 = matmul_bn_stats(
            xs.reshape(n * ho * wo, c), w.reshape(c, -1),
            interpret=bool(interpret))
        return y2.reshape(n, ho, wo, -1), s1, s2
    if (use_kernel and kh == 3 and kw == 3 and s == (1, 1) and same
            and x.shape[2] >= MIN_SPATIAL_FOR_KERNEL):
        return conv3x3_bn_stats(x, w, interpret=bool(interpret))
    y = ops_conv.conv2d(x, w, stride=stride, padding=padding)
    yf = y.astype(jnp.float32)
    axes = tuple(range(y.ndim - 1))
    return y, jnp.sum(yf, axis=axes), jnp.sum(yf * yf, axis=axes)


def _quant8(t):
    """Per-channel symmetric int8 quantization of a saved activation:
    halves the backward's read traffic for that residual (bf16 2B →
    int8 1B) at the cost of an extra int8 write in forward — net ~0.5
    byte/element saved, plus halved residual memory. ~0.4% relative
    rounding noise on the stashed tensor (127 levels), applied only to
    backward REANDS of saved activations, never the forward values."""
    tf = t.astype(jnp.float32)
    amax = jnp.max(jnp.abs(tf), axis=tuple(range(t.ndim - 1)))
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(tf / scale), -127, 127).astype(jnp.int8)
    return q, scale


def _dequant8(q, scale, dtype):
    return (q.astype(jnp.float32) * scale).astype(dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _conv_bn(x, w, gamma, beta, stride, padding, eps, interpret, save8,
             fused_bwd):
    return _conv_bn_fwd(x, w, gamma, beta, stride, padding, eps,
                        interpret, save8, fused_bwd)[0]


def _conv_bn_fwd(x, w, gamma, beta, stride, padding, eps, interpret,
                 save8, fused_bwd):
    y, s1, s2 = conv_bn_stats(x, w, stride=stride, padding=padding,
                              interpret=interpret)
    count = y.size // y.shape[-1]
    mean = s1 / count
    var = jnp.maximum(s2 / count - jnp.square(mean), 0.0)
    inv = lax.rsqrt(var + eps)
    g32 = gamma.astype(jnp.float32)
    scale = (g32 * inv).astype(y.dtype)
    shift = (beta.astype(jnp.float32) - mean * g32 * inv).astype(y.dtype)
    out = y * scale + shift
    if save8:
        # x: zero-size dtype token — residual pytrees may hold only JAX
        # values, and bwd must rebuild x in its ORIGINAL dtype so the
        # returned cotangent matches the primal.
        stash_x = (_quant8(x), jnp.zeros((0,), x.dtype))
        # y: quantize the CENTERED conv output (y - mean), not raw y —
        # the backward only ever consumes ŷ = (y - mean)·inv, and for a
        # channel whose |mean| dwarfs its std (exactly what BN fixes)
        # raw-y quantization noise amplified by inv would corrupt dγ/dx;
        # centering bounds the stash noise at ~range/254 in ŷ units
        # regardless of channel statistics.
        stash_y = _quant8(y.astype(jnp.float32) - mean)
    else:
        stash_x = stash_y = None
    # mean/var feed running stats only — gradient-stopped by construction
    # (the VJP ignores their cotangents)
    return ((out, lax.stop_gradient(mean), lax.stop_gradient(var)),
            (None if save8 else x, None if save8 else y, stash_x, stash_y,
             w, mean, inv, gamma))


def _conv_bn_bwd(stride, padding, eps, interpret, save8, fused_bwd, res,
                 cts):
    from paddle_tpu.ops import conv as ops_conv

    x, y, stash_x, stash_y, w, mean, inv, gamma = res
    if save8:
        (qx, sx), xtok = stash_x
        qz, sz = stash_y
        # the f32 view fuses into the reductions below (no materialized
        # dequant copy); the fused kernels read the raw int8 stashes
        centered = qz.astype(jnp.float32) * sz     # = y - mean (stashed)
        x_full = None                              # dequantize lazily
    else:
        qx = sx = qz = sz = None
        centered = y.astype(jnp.float32) - mean
        x_full = x
    dout = cts[0].astype(jnp.float32)
    n = centered.size // centered.shape[-1]
    axes = tuple(range(centered.ndim - 1))
    # the cotangent w.r.t. the conv output is EXACTLY the batch-norm dx
    # identity (ops/norm.py _bn_apply_bwd with x := y): two passes —
    # one fused reduction (Σdy, Σdy·ŷ) and the elementwise g stage
    sum_dy = jnp.sum(dout, axis=axes)
    yhat = centered * inv
    sum_dy_yhat = jnp.sum(dout * yhat, axis=axes)

    kh, kw = w.shape[0], w.shape[1]
    s, same, pad0, kernel_ok, interpret = _dispatch(stride, padding,
                                                    interpret)
    use_kernel = fused_bwd and kernel_ok
    out_dt = cts[0].dtype
    # the dx cotangent must carry the PRIMAL x dtype exactly
    x_dt = xtok.dtype if save8 else x.dtype
    if use_kernel and kh == 1 and kw == 1 and pad0:
        # g recomputed inside the dx/dw GEMM kernels — never hits HBM;
        # with save8 the kernels read the raw int8 stashes directly
        c = x.shape[-1] if not save8 else qx.shape[-1]
        k = w.shape[-1]
        if save8:
            x_in = qx[:, ::s[0], ::s[1], :]
            z_in, dy_in = qz, dout.astype(out_dt)
            xsc, zsc = sx, sz
        else:
            x_in = x_full[:, ::s[0], ::s[1], :]
            z_in = centered.astype(out_dt)
            dy_in, xsc, zsc = dout.astype(out_dt), None, None
        nb, ho, wo = x_in.shape[:3]
        dxs, dw2 = matmul_bn_bwd(
            x_in.reshape(nb * ho * wo, c),
            z_in.reshape(nb * ho * wo, k),
            dy_in.reshape(nb * ho * wo, k),
            w.reshape(c, k), gamma, inv, sum_dy, sum_dy_yhat,
            x_scale=xsc, z_scale=zsc, out_dtype=x_dt,
            interpret=bool(interpret))
        dxs = dxs.reshape(nb, ho, wo, c)
        full_shape = qx.shape if save8 else x_full.shape
        if s != (1, 1):
            dx = jnp.zeros(full_shape, x_dt).at[
                :, ::s[0], ::s[1], :].set(dxs.astype(x_dt))
        else:
            dx = dxs.astype(x_dt)
        dw = dw2.reshape(w.shape).astype(w.dtype)
    elif (use_kernel and kh == 3 and kw == 3 and s == (1, 1) and same
          and (qz.shape[2] if save8 else y.shape[2])
          >= MIN_SPATIAL_FOR_KERNEL):
        if save8:
            dx, dw3 = conv3x3_bn_bwd(
                qx, qz, dout.astype(out_dt), w, gamma, inv, sum_dy,
                sum_dy_yhat, x_scale=sx, z_scale=sz, out_dtype=x_dt,
                interpret=bool(interpret))
        else:
            dx, dw3 = conv3x3_bn_bwd(
                x_full, centered.astype(out_dt), dout.astype(out_dt), w,
                gamma, inv, sum_dy, sum_dy_yhat, out_dtype=x_dt,
                interpret=bool(interpret))
        dw = dw3.astype(w.dtype)
    else:
        if save8 and x_full is None:
            x_full = _dequant8(qx, sx, xtok.dtype)
        sc = gamma.astype(jnp.float32) * inv / n
        g = (sc * (n * dout - sum_dy - yhat * sum_dy_yhat)).astype(
            out_dt)
        # delegate the conv backward to XLA's conv VJP
        _, conv_vjp = jax.vjp(
            lambda x_, w_: ops_conv.conv2d(x_, w_, stride=stride,
                                           padding=padding), x_full, w)
        dx, dw = conv_vjp(g)
    return (dx, dw, sum_dy_yhat.astype(gamma.dtype),
            sum_dy.astype(gamma.dtype))


_conv_bn.defvjp(_conv_bn_fwd, _conv_bn_bwd)


def conv_bn_train(x, w, gamma, beta, running_mean, running_var, *,
                  stride=1, padding="SAME", momentum=0.9, eps=1e-5,
                  interpret: Optional[bool] = None, save8: bool = False,
                  fused_bwd: bool = False
                  ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fused conv→BN training step: one kernel produces the conv output
    AND its batch statistics, the normalize is a per-channel affine, and
    the backward is the closed-form two-pass BN VJP + XLA's conv VJP.
    ``save8`` stashes the backward's saved activations (x, y) as
    per-channel int8 — halves their backward read traffic and residual
    memory for ~0.4% stash rounding noise (forward values untouched).
    ``fused_bwd`` recomputes the BN-backward g stage INSIDE Pallas
    conv-backward kernels (1x1 GEMM pair / 3x3 shifted-GEMM pair) so g
    never exists in HBM — pairs naturally with save8 (the kernels read
    the centered int8 stash directly).
    Returns (out, new_running_mean, new_running_var)."""
    out, mean, var = _conv_bn(x, w, gamma, beta, stride, padding, eps,
                              interpret, save8, fused_bwd)
    new_mean = momentum * running_mean + (1 - momentum) * mean
    new_var = momentum * running_var + (1 - momentum) * var
    return (out, new_mean.astype(running_mean.dtype),
            new_var.astype(running_var.dtype))


def conv_bn_infer(x, w, gamma, beta, running_mean, running_var, *,
                  stride=1, padding="SAME", eps=1e-5):
    """Inference path: plain conv + folded-affine BN (no stats needed)."""
    from paddle_tpu.ops import conv as ops_conv
    from paddle_tpu.ops import norm as ops_norm

    y = ops_conv.conv2d(x, w, stride=stride, padding=padding)
    return ops_norm.batch_norm_infer(y, gamma, beta, running_mean,
                                     running_var, eps=eps)
