"""Streaming conv + batch-norm statistics — Pallas kernels.

The ResNet-50 train step is HBM-bandwidth-bound (BENCHMARKS.md roofline:
~99% of peak, 74.9 GB/step); the reducible traffic is whole-activation
passes. Standard BN reads the conv output once just to reduce per-channel
Σy and Σy² before the normalize pass re-reads it. These kernels emit the
statistics from the convolution's OWN epilogue — the fp32 accumulator tile
is reduced in-register before it is cast and written — eliminating the
stats pass over every BN'd activation (capability slot of the reference's
fused CudnnBatchNormLayer, paddle/gserver/layers/CudnnBatchNormLayer.cpp;
hand-fused conv epilogues, paddle/cuda/src/hl_cuda_cnn.cu).

Two kernels cover ResNet's conv menu:
- ``matmul_bn_stats`` — 1×1 convs (any stride, via pre-slice) as a GEMM
  over [M, C] with a per-channel Σ/Σ² epilogue. In bottleneck ResNet the
  1×1 convs carry 2 of every 3 BN'd activations.
- ``conv3x3_bn_stats`` — 3×3 stride-1 SAME convs as 9 shifted GEMMs
  accumulated in VMEM (whole padded image resident per batch element),
  same epilogue.
Everything else (the 7×7/s2d stem) falls back to XLA conv + jnp reduce.

``conv_bn_train`` is the fused train-mode op with a closed-form VJP: the
cotangent w.r.t. the conv output is exactly the batch-norm dx formula
(two passes over dy/y), after which the conv backward itself is delegated
to XLA's conv VJP (its MXU conv backward is already optimal — the win
here is forward-traffic only).
"""

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl


def _on_tpu():
    return jax.devices()[0].platform == "tpu"


# tests monkeypatch this to drive the Pallas kernels in interpret mode
# through the full layer/model stack on CPU
FORCE_INTERPRET = False


# ---------------------------------------------------------------------------
# GEMM + stats (1x1 convs)
# ---------------------------------------------------------------------------

def _mm_stats_kernel(x_ref, w_ref, y_ref, stats_ref, *, bm, bk, m_total):
    mi = pl.program_id(0)
    ki = pl.program_id(1)

    @pl.when((mi == 0) & (ki == 0))
    def _init():
        stats_ref[...] = jnp.zeros_like(stats_ref)

    x = x_ref[...].astype(jnp.float32)              # [bm, C]
    w = w_ref[...].astype(jnp.float32)              # [C, bk]
    acc = x @ w                                     # fp32 on the MXU
    y_ref[...] = acc.astype(y_ref.dtype)
    # epilogue: per-channel sums of the UNROUNDED accumulator; padded
    # rows (beyond m_total) are masked out of the statistics
    rows = mi * bm + lax.broadcasted_iota(jnp.int32, (bm, 1), 0)
    valid = (rows < m_total).astype(jnp.float32)
    accv = acc * valid
    stats_ref[0, pl.ds(ki * bk, bk)] += jnp.sum(accv, axis=0)
    stats_ref[1, pl.ds(ki * bk, bk)] += jnp.sum(accv * acc, axis=0)


def matmul_bn_stats(x2: jax.Array, w2: jax.Array, *, out_dtype=None,
                    block_m: int = 256, block_k: int = 128,
                    interpret: bool = False):
    """y = x2 @ w2 with per-output-channel (Σy, Σy²) from the epilogue.

    x2: [M, C]; w2: [C, K] → (y [M, K], sum [K], sumsq [K]); sums are over
    the fp32 accumulator (pre-cast), masked to the true M rows."""
    m, c = x2.shape
    k = w2.shape[1]
    out_dtype = out_dtype or x2.dtype
    bm = min(block_m, max(8, m))
    bk = min(block_k, k)
    mp = -(-m // bm) * bm
    kp = -(-k // bk) * bk
    if mp != m:
        x2 = jnp.pad(x2, ((0, mp - m), (0, 0)))
    if kp != k:
        w2 = jnp.pad(w2, ((0, 0), (0, kp - k)))
    grid = (mp // bm, kp // bk)
    kernel = functools.partial(_mm_stats_kernel, bm=bm, bk=bk, m_total=m)
    y, stats = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, c), lambda mi, ki: (mi, 0)),
            pl.BlockSpec((c, bk), lambda mi, ki: (0, ki)),
        ],
        out_specs=[
            pl.BlockSpec((bm, bk), lambda mi, ki: (mi, ki)),
            # whole-array stats block: revisited by every grid step, so
            # the += accumulation is safe on the sequential TPU grid
            pl.BlockSpec((2, kp), lambda mi, ki: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, kp), out_dtype),
            jax.ShapeDtypeStruct((2, kp), jnp.float32),
        ],
        interpret=interpret,
    )(x2, w2)
    return y[:m, :k], stats[0, :k], stats[1, :k]


# ---------------------------------------------------------------------------
# 3x3 stride-1 SAME conv + stats
# ---------------------------------------------------------------------------

def _conv3_stats_kernel(x_ref, w_ref, y_ref, stats_ref, *, bh, wdim, kdim):
    ni = pl.program_id(0)
    hi = pl.program_id(1)

    @pl.when((ni == 0) & (hi == 0))
    def _init():
        stats_ref[...] = jnp.zeros_like(stats_ref)

    h0 = hi * bh
    acc = jnp.zeros((bh * wdim, kdim), jnp.float32)
    for dy in range(3):
        for dx in range(3):
            xs = x_ref[0, pl.ds(h0 + dy, bh), pl.ds(dx, wdim), :]
            xs = xs.reshape(bh * wdim, xs.shape[-1]).astype(jnp.float32)
            acc += xs @ w_ref[dy, dx].astype(jnp.float32)
    y_ref[0] = acc.reshape(bh, wdim, kdim).astype(y_ref.dtype)
    stats_ref[0] += jnp.sum(acc, axis=0)
    stats_ref[1] += jnp.sum(acc * acc, axis=0)


def conv3x3_bn_stats(x: jax.Array, w: jax.Array, *, out_dtype=None,
                     block_h: Optional[int] = None,
                     interpret: bool = False):
    """3×3 stride-1 SAME conv with the stats epilogue.

    x: [N, H, W, C]; w: [3, 3, C, K] → (y [N, H, W, K], sum [K],
    sumsq [K]). The whole zero-padded image of one batch element is VMEM-
    resident per grid step (ResNet's 3×3 shapes top out at ~0.5 MB)."""
    n, h, wd, c = x.shape
    k = w.shape[-1]
    out_dtype = out_dtype or x.dtype
    if block_h is None:
        # largest divisor of H keeping the accumulator tile under ~1 MiB
        budget = (1 << 20) // max(1, wd * k * 4)
        block_h = max(d for d in range(1, h + 1)
                      if h % d == 0 and d <= max(1, budget))
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    grid = (n, h // block_h)
    kernel = functools.partial(_conv3_stats_kernel, bh=block_h, wdim=wd,
                               kdim=k)
    y, stats = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, h + 2, wd + 2, c), lambda ni, hi: (ni, 0, 0, 0)),
            pl.BlockSpec((3, 3, c, k), lambda ni, hi: (0, 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_h, wd, k), lambda ni, hi: (ni, hi, 0, 0)),
            pl.BlockSpec((2, k), lambda ni, hi: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h, wd, k), out_dtype),
            jax.ShapeDtypeStruct((2, k), jnp.float32),
        ],
        interpret=interpret,
    )(xp, w)
    return y, stats[0], stats[1]


# ---------------------------------------------------------------------------
# dispatch + fused train op
# ---------------------------------------------------------------------------

def conv_bn_stats(x, w, *, stride=1, padding="SAME",
                  interpret: Optional[bool] = None):
    """(conv(x, w), Σy, Σy²) with the stats from the conv epilogue when a
    streaming kernel covers the shape; XLA conv + jnp reduce otherwise.
    Returns (y, sum, sumsq) — sums per output channel over N·H·W."""
    from paddle_tpu.ops import conv as ops_conv

    kh, kw = w.shape[0], w.shape[1]
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    if interpret is None and FORCE_INTERPRET:
        interpret = True
    use_kernel = interpret if interpret is not None else _on_tpu()
    same = padding == "SAME" or padding == ((1, 1), (1, 1)) or padding == 1
    if use_kernel and kh == 1 and kw == 1:
        xs = x[:, ::s[0], ::s[1], :]
        n, ho, wo, c = xs.shape
        y2, s1, s2 = matmul_bn_stats(
            xs.reshape(n * ho * wo, c), w.reshape(c, -1),
            interpret=bool(interpret))
        return y2.reshape(n, ho, wo, -1), s1, s2
    if use_kernel and kh == 3 and kw == 3 and s == (1, 1) and same:
        return conv3x3_bn_stats(x, w, interpret=bool(interpret))
    y = ops_conv.conv2d(x, w, stride=stride, padding=padding)
    yf = y.astype(jnp.float32)
    axes = tuple(range(y.ndim - 1))
    return y, jnp.sum(yf, axis=axes), jnp.sum(yf * yf, axis=axes)


def _quant8(t):
    """Per-channel symmetric int8 quantization of a saved activation:
    halves the backward's read traffic for that residual (bf16 2B →
    int8 1B) at the cost of an extra int8 write in forward — net ~0.5
    byte/element saved, plus halved residual memory. ~0.4% relative
    rounding noise on the stashed tensor (127 levels), applied only to
    backward REANDS of saved activations, never the forward values."""
    tf = t.astype(jnp.float32)
    amax = jnp.max(jnp.abs(tf), axis=tuple(range(t.ndim - 1)))
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(tf / scale), -127, 127).astype(jnp.int8)
    return q, scale


def _dequant8(q, scale, dtype):
    return (q.astype(jnp.float32) * scale).astype(dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _conv_bn(x, w, gamma, beta, stride, padding, eps, interpret, save8):
    return _conv_bn_fwd(x, w, gamma, beta, stride, padding, eps,
                        interpret, save8)[0]


def _conv_bn_fwd(x, w, gamma, beta, stride, padding, eps, interpret,
                 save8):
    y, s1, s2 = conv_bn_stats(x, w, stride=stride, padding=padding,
                              interpret=interpret)
    count = y.size // y.shape[-1]
    mean = s1 / count
    var = jnp.maximum(s2 / count - jnp.square(mean), 0.0)
    inv = lax.rsqrt(var + eps)
    g32 = gamma.astype(jnp.float32)
    scale = (g32 * inv).astype(y.dtype)
    shift = (beta.astype(jnp.float32) - mean * g32 * inv).astype(y.dtype)
    out = y * scale + shift
    if save8:
        # x: zero-size dtype token — residual pytrees may hold only JAX
        # values, and bwd must rebuild x in its ORIGINAL dtype so the
        # returned cotangent matches the primal.
        stash_x = (_quant8(x), jnp.zeros((0,), x.dtype))
        # y: quantize the CENTERED conv output (y - mean), not raw y —
        # the backward only ever consumes ŷ = (y - mean)·inv, and for a
        # channel whose |mean| dwarfs its std (exactly what BN fixes)
        # raw-y quantization noise amplified by inv would corrupt dγ/dx;
        # centering bounds the stash noise at ~range/254 in ŷ units
        # regardless of channel statistics.
        stash_y = _quant8(y.astype(jnp.float32) - mean)
    else:
        stash_x = stash_y = None
    # mean/var feed running stats only — gradient-stopped by construction
    # (the VJP ignores their cotangents)
    return ((out, lax.stop_gradient(mean), lax.stop_gradient(var)),
            (None if save8 else x, None if save8 else y, stash_x, stash_y,
             w, mean, inv, gamma))


def _conv_bn_bwd(stride, padding, eps, interpret, save8, res, cts):
    from paddle_tpu.ops import conv as ops_conv

    x, y, stash_x, stash_y, w, mean, inv, gamma = res
    if save8:
        (qx, sx), xtok = stash_x
        x = _dequant8(qx, sx, xtok.dtype)
        qz, sz = stash_y
        centered = qz.astype(jnp.float32) * sz     # = y - mean (stashed)
    else:
        centered = y.astype(jnp.float32) - mean
    dout = cts[0].astype(jnp.float32)
    n = centered.size // centered.shape[-1]
    axes = tuple(range(centered.ndim - 1))
    # the cotangent w.r.t. the conv output is EXACTLY the batch-norm dx
    # identity (ops/norm.py _bn_apply_bwd with x := y): two passes —
    # one fused reduction (Σdy, Σdy·ŷ), one elementwise
    sum_dy = jnp.sum(dout, axis=axes)
    yhat = centered * inv
    sum_dy_yhat = jnp.sum(dout * yhat, axis=axes)
    sc = gamma.astype(jnp.float32) * inv / n
    g = (sc * (n * dout - sum_dy - yhat * sum_dy_yhat)).astype(
        cts[0].dtype)
    # delegate the conv backward to XLA's conv VJP (MXU-optimal already)
    _, conv_vjp = jax.vjp(
        lambda x_, w_: ops_conv.conv2d(x_, w_, stride=stride,
                                       padding=padding), x, w)
    dx, dw = conv_vjp(g)
    return (dx, dw, sum_dy_yhat.astype(gamma.dtype),
            sum_dy.astype(gamma.dtype))


_conv_bn.defvjp(_conv_bn_fwd, _conv_bn_bwd)


def conv_bn_train(x, w, gamma, beta, running_mean, running_var, *,
                  stride=1, padding="SAME", momentum=0.9, eps=1e-5,
                  interpret: Optional[bool] = None, save8: bool = False
                  ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fused conv→BN training step: one kernel produces the conv output
    AND its batch statistics, the normalize is a per-channel affine, and
    the backward is the closed-form two-pass BN VJP + XLA's conv VJP.
    ``save8`` stashes the backward's saved activations (x, y) as
    per-channel int8 — halves their backward read traffic and residual
    memory for ~0.4% stash rounding noise (forward values untouched).
    Returns (out, new_running_mean, new_running_var)."""
    out, mean, var = _conv_bn(x, w, gamma, beta, stride, padding, eps,
                              interpret, save8)
    new_mean = momentum * running_mean + (1 - momentum) * mean
    new_var = momentum * running_var + (1 - momentum) * var
    return (out, new_mean.astype(running_mean.dtype),
            new_var.astype(running_var.dtype))


def conv_bn_infer(x, w, gamma, beta, running_mean, running_var, *,
                  stride=1, padding="SAME", eps=1e-5):
    """Inference path: plain conv + folded-affine BN (no stats needed)."""
    from paddle_tpu.ops import conv as ops_conv
    from paddle_tpu.ops import norm as ops_norm

    y = ops_conv.conv2d(x, w, stride=stride, padding=padding)
    return ops_norm.batch_norm_infer(y, gamma, beta, running_mean,
                                     running_var, eps=eps)
