"""Composite network builders (reference: python/paddle/trainer_config_helpers/
networks.py — simple_img_conv_pool, simple_lstm, bidirectional_lstm,
sequence_conv_pool, simple_gru...)."""

from typing import Optional

from paddle_tpu import activation as act_mod
from paddle_tpu import layer
from paddle_tpu import pooling as pooling_mod


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         num_channel=None, pool_stride=None, act=None,
                         pool_type=None, name=None, padding=None):
    """(reference: networks.py simple_img_conv_pool)"""
    conv = layer.img_conv(input, filter_size=filter_size,
                          num_filters=num_filters, num_channels=num_channel,
                          act=act, padding=padding,
                          name=f"{name}_conv" if name else None)
    return layer.img_pool(conv, pool_size=pool_size, stride=pool_stride,
                          pool_type=pool_type,
                          name=f"{name}_pool" if name else None)


def simple_lstm(input, size, reverse=False, name=None, act=None,
                mat_param_attr=None, bias_param_attr=None,
                inner_param_attr=None):
    """fc(4*size) + lstmemory (reference: networks.py simple_lstm)."""
    proj = layer.fc(input, size * 4, param_attr=mat_param_attr,
                    bias_attr=False,
                    name=f"{name}_transform" if name else None)
    return layer.lstmemory(proj, size=size, reverse=reverse,
                           param_attr=inner_param_attr,
                           bias_attr=bias_param_attr,
                           name=name)


def simple_gru(input, size, reverse=False, name=None, act=None):
    """fc(3*size) + grumemory (reference: networks.py simple_gru)."""
    proj = layer.fc(input, size * 3, bias_attr=False,
                    name=f"{name}_transform" if name else None)
    return layer.grumemory(proj, size=size, reverse=reverse, name=name)


def bidirectional_lstm(input, size, name=None, return_seq=False):
    """Forward + backward LSTM, concat (reference: networks.py
    bidirectional_lstm)."""
    fwd = simple_lstm(input, size, reverse=False,
                      name=f"{name}_fw" if name else None)
    bwd = simple_lstm(input, size, reverse=True,
                      name=f"{name}_bw" if name else None)
    if return_seq:
        return layer.concat([fwd, bwd], name=name)
    last_f = layer.last_seq(fwd)
    first_b = layer.first_seq(bwd)
    return layer.concat([last_f, first_b], name=name)


def sequence_conv_pool(input, context_len, hidden_size, context_start=None,
                       pool_type=None, context_proj_name=None, fc_name=None,
                       pool_name=None, fc_act=None, name=None):
    """Text CNN block: context window -> fc -> seq pool (reference:
    networks.py sequence_conv_pool, the quick-start text model)."""
    ctx = layer.context_projection(input, context_len=context_len,
                                   context_start=context_start,
                                   name=context_proj_name)
    hidden = layer.fc(ctx, hidden_size, act=fc_act or act_mod.Tanh(),
                      name=fc_name)
    return layer.pool(hidden, pooling_type=pool_type or pooling_mod.Max(),
                      name=pool_name or name)


def img_conv_bn_pool(input, filter_size, num_filters, pool_size,
                     num_channel=None, pool_stride=None, act=None,
                     pool_type=None, name=None):
    """conv -> batch_norm -> pool (reference: networks.py img_conv_bn_pool)."""
    conv = layer.img_conv(input, filter_size=filter_size,
                          num_filters=num_filters, num_channels=num_channel,
                          act=None, bias_attr=False,
                          name=f"{name}_conv" if name else None)
    bn = layer.batch_norm(conv, act=act,
                          name=f"{name}_bn" if name else None)
    return layer.img_pool(bn, pool_size=pool_size, stride=pool_stride,
                          pool_type=pool_type,
                          name=f"{name}_pool" if name else None)


def simple_attention(encoded_sequence, encoded_proj, decoder_state,
                     name=None, transform_param_attr=None):
    """Bahdanau-style additive attention inside a recurrent_group step.

    Reference: simple_attention (trainer_config_helpers/networks.py) — score
    each encoder position by tanh(enc_proj + W·state)·v via a sequence
    softmax, return the weighted sum of ``encoded_sequence``.

    ``encoded_sequence``/``encoded_proj`` are step placeholders fed from
    StaticInput(..., is_seq=True); ``decoder_state`` is a memory.
    """
    from paddle_tpu.core.param import ParamSpec, ParamAttr
    from paddle_tpu.layer import _param_attr
    from paddle_tpu.topology import LayerOutput, Value, auto_name
    import jax.numpy as jnp

    name = name or auto_name("attention")
    proj_size = encoded_proj.size
    a = _param_attr(transform_param_attr, f"{name}.decoder_proj.w")
    w_spec = ParamSpec(a.name, (decoder_state.size, proj_size), attr=a,
                       fan_in=decoder_state.size)
    v_attr = ParamAttr(name=f"{name}.v")
    v_spec = ParamSpec(v_attr.name, (proj_size,), attr=v_attr,
                       fan_in=proj_size)

    def fwd(params, parents, ctx):
        enc, enc_proj, state = parents
        # enc.array [B, T, F]; state.array [B, H]
        dec = jnp.matmul(state.array, params[w_spec.name])       # [B, P]
        e = jnp.tanh(enc_proj.array + dec[:, None, :])           # [B, T, P]
        scores = jnp.einsum("btp,p->bt", e, params[v_spec.name])
        from paddle_tpu.ops import sequence as ops_seq
        w = ops_seq.seq_softmax(scores[..., None], enc.lengths)[..., 0]
        cvec = jnp.einsum("bt,btf->bf", w, enc.array)
        return Value(cvec)

    return LayerOutput(name, "attention",
                       [encoded_sequence, encoded_proj, decoder_state],
                       fwd, [w_spec, v_spec], size=encoded_sequence.size)


def gru_decoder_with_attention(encoded_sequence, encoded_proj, current_word,
                               decoder_size, boot_layer, name="gru_decoder"):
    """One decoder step: attention context + previous word → GRU → softmax
    (reference: the seqToseq demo's gru_decoder_with_attention,
    v1_api_demo-era seqToseq_net). Use inside recurrent_group/beam_search."""
    state = layer.memory(name=name, size=decoder_size,
                         boot_layer=boot_layer)
    context = simple_attention(encoded_sequence, encoded_proj, state,
                               name=f"{name}_att")
    inputs = layer.fc([context, current_word], size=decoder_size * 3,
                      act="linear", name=f"{name}_input", bias_attr=False)
    gru = layer.gru_step(inputs, state=state, size=decoder_size, name=name)
    return gru


# composite nets are thin wrappers over recorded layer calls — the inner
# records suffice for serialization, but install anyway so composites whose
# inner calls are unrecordable still get a fallback record when possible
def _install_recording():
    import sys
    from paddle_tpu import record
    record.install(sys.modules[__name__])


_install_recording()
