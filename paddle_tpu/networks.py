"""Composite network builders (reference: python/paddle/trainer_config_helpers/
networks.py — simple_img_conv_pool, simple_lstm, bidirectional_lstm,
sequence_conv_pool, simple_gru...)."""

from typing import Optional

from paddle_tpu import activation as act_mod
from paddle_tpu import layer
from paddle_tpu import pooling as pooling_mod


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         num_channel=None, pool_stride=None, act=None,
                         pool_type=None, name=None, padding=None):
    """(reference: networks.py simple_img_conv_pool)"""
    conv = layer.img_conv(input, filter_size=filter_size,
                          num_filters=num_filters, num_channels=num_channel,
                          act=act, padding=padding,
                          name=f"{name}_conv" if name else None)
    return layer.img_pool(conv, pool_size=pool_size, stride=pool_stride,
                          pool_type=pool_type,
                          name=f"{name}_pool" if name else None)


def simple_lstm(input, size, reverse=False, name=None, act=None,
                mat_param_attr=None, bias_param_attr=None,
                inner_param_attr=None):
    """fc(4*size) + lstmemory (reference: networks.py simple_lstm)."""
    proj = layer.fc(input, size * 4, param_attr=mat_param_attr,
                    bias_attr=False,
                    name=f"{name}_transform" if name else None)
    return layer.lstmemory(proj, size=size, reverse=reverse,
                           param_attr=inner_param_attr,
                           bias_attr=bias_param_attr,
                           name=name)


def simple_gru(input, size, reverse=False, name=None, act=None):
    """fc(3*size) + grumemory (reference: networks.py simple_gru)."""
    proj = layer.fc(input, size * 3, bias_attr=False,
                    name=f"{name}_transform" if name else None)
    return layer.grumemory(proj, size=size, reverse=reverse, name=name)


def bidirectional_lstm(input, size, name=None, return_seq=False):
    """Forward + backward LSTM, concat (reference: networks.py
    bidirectional_lstm)."""
    fwd = simple_lstm(input, size, reverse=False,
                      name=f"{name}_fw" if name else None)
    bwd = simple_lstm(input, size, reverse=True,
                      name=f"{name}_bw" if name else None)
    if return_seq:
        return layer.concat([fwd, bwd], name=name)
    last_f = layer.last_seq(fwd)
    first_b = layer.first_seq(bwd)
    return layer.concat([last_f, first_b], name=name)


def sequence_conv_pool(input, context_len, hidden_size, context_start=None,
                       pool_type=None, context_proj_name=None, fc_name=None,
                       pool_name=None, fc_act=None, name=None):
    """Text CNN block: context window -> fc -> seq pool (reference:
    networks.py sequence_conv_pool, the quick-start text model)."""
    ctx = layer.context_projection(input, context_len=context_len,
                                   context_start=context_start,
                                   name=context_proj_name)
    hidden = layer.fc(ctx, hidden_size, act=fc_act or act_mod.Tanh(),
                      name=fc_name)
    return layer.pool(hidden, pooling_type=pool_type or pooling_mod.Max(),
                      name=pool_name or name)


def img_conv_bn_pool(input, filter_size, num_filters, pool_size,
                     num_channel=None, pool_stride=None, act=None,
                     pool_type=None, name=None):
    """conv -> batch_norm -> pool (reference: networks.py img_conv_bn_pool)."""
    conv = layer.img_conv(input, filter_size=filter_size,
                          num_filters=num_filters, num_channels=num_channel,
                          act=None, bias_attr=False,
                          name=f"{name}_conv" if name else None)
    bn = layer.batch_norm(conv, act=act,
                          name=f"{name}_bn" if name else None)
    return layer.img_pool(bn, pool_size=pool_size, stride=pool_stride,
                          pool_type=pool_type,
                          name=f"{name}_pool" if name else None)


def simple_attention(encoded_sequence, encoded_proj, decoder_state,
                     name=None, transform_param_attr=None):
    """Bahdanau-style additive attention inside a recurrent_group step.

    Reference: simple_attention (trainer_config_helpers/networks.py) — score
    each encoder position by tanh(enc_proj + W·state)·v via a sequence
    softmax, return the weighted sum of ``encoded_sequence``.

    ``encoded_sequence``/``encoded_proj`` are step placeholders fed from
    StaticInput(..., is_seq=True); ``decoder_state`` is a memory.
    """
    from paddle_tpu.core.param import ParamSpec, ParamAttr
    from paddle_tpu.layer import _param_attr
    from paddle_tpu.topology import LayerOutput, Value, auto_name
    import jax.numpy as jnp

    name = name or auto_name("attention")
    proj_size = encoded_proj.size
    a = _param_attr(transform_param_attr, f"{name}.decoder_proj.w")
    w_spec = ParamSpec(a.name, (decoder_state.size, proj_size), attr=a,
                       fan_in=decoder_state.size)
    v_attr = ParamAttr(name=f"{name}.v")
    v_spec = ParamSpec(v_attr.name, (proj_size,), attr=v_attr,
                       fan_in=proj_size)

    def fwd(params, parents, ctx):
        enc, enc_proj, state = parents
        # enc.array [B, T, F]; state.array [B, H]
        dec = jnp.matmul(state.array, params[w_spec.name])       # [B, P]
        e = jnp.tanh(enc_proj.array + dec[:, None, :])           # [B, T, P]
        scores = jnp.einsum("btp,p->bt", e, params[v_spec.name])
        from paddle_tpu.ops import sequence as ops_seq
        w = ops_seq.seq_softmax(scores[..., None], enc.lengths)[..., 0]
        cvec = jnp.einsum("bt,btf->bf", w, enc.array)
        return Value(cvec)

    return LayerOutput(name, "attention",
                       [encoded_sequence, encoded_proj, decoder_state],
                       fwd, [w_spec, v_spec], size=encoded_sequence.size)


def gru_decoder_with_attention(encoded_sequence, encoded_proj, current_word,
                               decoder_size, boot_layer, name="gru_decoder"):
    """One decoder step: attention context + previous word → GRU → softmax
    (reference: the seqToseq demo's gru_decoder_with_attention,
    v1_api_demo-era seqToseq_net). Use inside recurrent_group/beam_search."""
    state = layer.memory(name=name, size=decoder_size,
                         boot_layer=boot_layer)
    context = simple_attention(encoded_sequence, encoded_proj, state,
                               name=f"{name}_att")
    inputs = layer.fc([context, current_word], size=decoder_size * 3,
                      act="linear", name=f"{name}_input", bias_attr=False)
    gru = layer.gru_step(inputs, state=state, size=decoder_size, name=name)
    return gru


# composite nets are thin wrappers over recorded layer calls — the inner
# records suffice for serialization, but install anyway so composites whose
# inner calls are unrecordable still get a fallback record when possible
def img_conv_group(input, conv_num_filter, pool_size, num_channels=None,
                   conv_padding=1, conv_filter_size=3, conv_act=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0,
                   pool_stride=None, pool_type=None, name=None):
    """Chain of convs (optionally BN + dropout each) ending in one pool
    (reference: networks.py img_conv_group — the VGG building block)."""
    def listify(v):
        return v if isinstance(v, (list, tuple)) \
            else [v] * len(conv_num_filter)

    pads = listify(conv_padding)
    ksz = listify(conv_filter_size)
    acts = listify(conv_act)
    bns = listify(conv_with_batchnorm)
    drops = listify(conv_batchnorm_drop_rate)
    tmp = input
    for i, nf in enumerate(conv_num_filter):
        lname = f"{name}_conv{i}" if name else None
        tmp = layer.img_conv(
            tmp, filter_size=ksz[i], num_filters=nf,
            num_channels=num_channels if i == 0 else None,
            padding=pads[i],
            act=None if bns[i] else (acts[i] or act_mod.Relu()),
            name=lname)
        if bns[i]:
            tmp = layer.batch_norm(tmp, act=acts[i] or act_mod.Relu(),
                                   name=f"{lname}_bn" if lname else None)
            if drops[i]:
                tmp = layer.dropout(tmp, drops[i])
    return layer.img_pool(tmp, pool_size=pool_size, stride=pool_stride or
                          pool_size, pool_type=pool_type,
                          name=f"{name}_pool" if name else None)


def small_vgg(input_image, num_channels, num_classes, name="svgg"):
    """(reference: networks.py small_vgg — the CIFAR VGG)"""
    tmp = input_image
    ch = num_channels
    for g, (nf, times, drop) in enumerate(
            [(64, 2, [0.3, 0]), (128, 2, [0.4, 0]),
             (256, 3, [0.4, 0.4, 0]), (512, 3, [0.4, 0.4, 0])]):
        tmp = img_conv_group(tmp, [nf] * times, pool_size=2,
                             num_channels=ch if g == 0 else None,
                             conv_with_batchnorm=True,
                             conv_batchnorm_drop_rate=drop,
                             name=f"{name}_g{g}")
        ch = None
    tmp = layer.dropout(tmp, 0.5)
    tmp = layer.fc(tmp, 512, act=None, name=f"{name}_fc1")
    tmp = layer.batch_norm(tmp, act=act_mod.Relu(), name=f"{name}_bn")
    return layer.fc(tmp, num_classes, act=act_mod.Softmax(),
                    name=f"{name}_out")


def vgg_16_network(input_image, num_channels, num_classes=1000,
                   name="vgg16"):
    """(reference: networks.py vgg_16_network)"""
    tmp = input_image
    ch = num_channels
    for g, (nf, times) in enumerate([(64, 2), (128, 2), (256, 3),
                                     (512, 3), (512, 3)]):
        tmp = img_conv_group(tmp, [nf] * times, pool_size=2,
                             num_channels=ch if g == 0 else None,
                             conv_act=act_mod.Relu(), name=f"{name}_g{g}")
        ch = None
    tmp = layer.fc(tmp, 4096, act=act_mod.Relu(), name=f"{name}_fc1")
    tmp = layer.dropout(tmp, 0.5)
    tmp = layer.fc(tmp, 4096, act=act_mod.Relu(), name=f"{name}_fc2")
    tmp = layer.dropout(tmp, 0.5)
    return layer.fc(tmp, num_classes, act=act_mod.Softmax(),
                    name=f"{name}_out")


def simple_gru2(input, size, reverse=False, name=None):
    """Pure alias of simple_gru (reference: networks.py simple_gru2 —
    same wiring; the reference variant differed only in mixed-layer
    parameter-attr defaults, which collapse to the same init here)."""
    return simple_gru(input, size, reverse=reverse, name=name)


def bidirectional_gru(input, size, return_seq=False, name=None):
    """Forward + backward GRU, concat (or concat of last steps)
    (reference: networks.py bidirectional_gru)."""
    fwd = simple_gru(input, size, name=f"{name}_fw" if name else None)
    bwd = simple_gru(input, size, reverse=True,
                     name=f"{name}_bw" if name else None)
    if return_seq:
        return layer.concat([fwd, bwd],
                            name=f"{name}_concat" if name else None)
    last_f = layer.last_seq(fwd)
    first_b = layer.first_seq(bwd)
    return layer.concat([last_f, first_b],
                        name=f"{name}_concat" if name else None)


def dot_product_attention(encoded_sequence, attended_sequence,
                          transformed_state, name=None):
    """Dot-product attention over an encoded sequence (reference:
    networks.py dot_product_attention): per-step scores
    ``<state, encoded[t]>``, masked sequence-softmax weights, context =
    weighted sum of the attended sequence."""
    name = name or "dot_attn"
    import paddle_tpu.ops.sequence as ops_seq
    from paddle_tpu.topology import LayerOutput, Value

    def fwd(params, parents, ctx):
        import jax.numpy as jnp
        state, enc, att = parents
        # [B, D] x [B, T, D] -> [B, T] scores
        s = jnp.einsum("bd,btd->bt", state.array.astype(jnp.float32),
                       enc.array.astype(jnp.float32))
        w = ops_seq.seq_softmax(s[..., None], enc.lengths)[..., 0]
        ctxv = jnp.einsum("bt,btd->bd", w,
                          att.array.astype(jnp.float32))
        return Value(ctxv.astype(att.array.dtype))

    return LayerOutput(name, "dot_attention",
                       [transformed_state, encoded_sequence,
                        attended_sequence],
                       fwd, [], size=attended_sequence.size)


def _install_recording():
    import sys
    from paddle_tpu import record
    record.install(sys.modules[__name__])


_install_recording()
