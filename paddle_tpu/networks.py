"""Composite network builders (reference: python/paddle/trainer_config_helpers/
networks.py — simple_img_conv_pool, simple_lstm, bidirectional_lstm,
sequence_conv_pool, simple_gru...)."""

from typing import Optional

from paddle_tpu import activation as act_mod
from paddle_tpu import layer
from paddle_tpu import pooling as pooling_mod


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         num_channel=None, pool_stride=None, act=None,
                         pool_type=None, name=None, padding=None):
    """(reference: networks.py simple_img_conv_pool)"""
    conv = layer.img_conv(input, filter_size=filter_size,
                          num_filters=num_filters, num_channels=num_channel,
                          act=act, padding=padding,
                          name=f"{name}_conv" if name else None)
    return layer.img_pool(conv, pool_size=pool_size, stride=pool_stride,
                          pool_type=pool_type,
                          name=f"{name}_pool" if name else None)


def simple_lstm(input, size, reverse=False, name=None, act=None,
                mat_param_attr=None, bias_param_attr=None,
                inner_param_attr=None):
    """fc(4*size) + lstmemory (reference: networks.py simple_lstm)."""
    proj = layer.fc(input, size * 4, param_attr=mat_param_attr,
                    bias_attr=False,
                    name=f"{name}_transform" if name else None)
    return layer.lstmemory(proj, size=size, reverse=reverse,
                           param_attr=inner_param_attr,
                           bias_attr=bias_param_attr,
                           name=name)


def simple_gru(input, size, reverse=False, name=None, act=None):
    """fc(3*size) + grumemory (reference: networks.py simple_gru)."""
    proj = layer.fc(input, size * 3, bias_attr=False,
                    name=f"{name}_transform" if name else None)
    return layer.grumemory(proj, size=size, reverse=reverse, name=name)


def bidirectional_lstm(input, size, name=None, return_seq=False):
    """Forward + backward LSTM, concat (reference: networks.py
    bidirectional_lstm)."""
    fwd = simple_lstm(input, size, reverse=False,
                      name=f"{name}_fw" if name else None)
    bwd = simple_lstm(input, size, reverse=True,
                      name=f"{name}_bw" if name else None)
    if return_seq:
        return layer.concat([fwd, bwd], name=name)
    last_f = layer.last_seq(fwd)
    first_b = layer.first_seq(bwd)
    return layer.concat([last_f, first_b], name=name)


def sequence_conv_pool(input, context_len, hidden_size, context_start=None,
                       pool_type=None, context_proj_name=None, fc_name=None,
                       pool_name=None, fc_act=None, name=None):
    """Text CNN block: context window -> fc -> seq pool (reference:
    networks.py sequence_conv_pool, the quick-start text model)."""
    ctx = layer.context_projection(input, context_len=context_len,
                                   context_start=context_start,
                                   name=context_proj_name)
    hidden = layer.fc(ctx, hidden_size, act=fc_act or act_mod.Tanh(),
                      name=fc_name)
    return layer.pool(hidden, pooling_type=pool_type or pooling_mod.Max(),
                      name=pool_name or name)


def img_conv_bn_pool(input, filter_size, num_filters, pool_size,
                     num_channel=None, pool_stride=None, act=None,
                     pool_type=None, name=None):
    """conv -> batch_norm -> pool (reference: networks.py img_conv_bn_pool)."""
    conv = layer.img_conv(input, filter_size=filter_size,
                          num_filters=num_filters, num_channels=num_channel,
                          act=None, bias_attr=False,
                          name=f"{name}_conv" if name else None)
    bn = layer.batch_norm(conv, act=act,
                          name=f"{name}_bn" if name else None)
    return layer.img_pool(bn, pool_size=pool_size, stride=pool_stride,
                          pool_type=pool_type,
                          name=f"{name}_pool" if name else None)
