"""Version info (reference: paddle/utils/Version.cpp, cmake version stamping)."""

__version__ = "0.3.0"

major = 0
minor = 1
patch = 0
rc = 0
istaged = False
with_tpu = True


def show():
    print("paddle_tpu", __version__)
