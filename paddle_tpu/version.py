"""Version info (reference: paddle/utils/Version.cpp, cmake version stamping).

major/minor/patch are derived from __version__ so the two can never drift.
"""

__version__ = "0.4.0"

major, minor, patch = (int(p) for p in __version__.split("."))
rc = 0
istaged = False
with_tpu = True


def show():
    print("paddle_tpu", __version__)
