#!/usr/bin/env python
"""Continuous-batching vs lockstep LM serving under Poisson load.

Replays ONE request trace (Poisson arrivals, mixed prompt/output
lengths) against both serving surfaces:

- ``engine``   — ``serving.DecodeEngine``: slot-based KV arena, bucketed
  slot prefill, per-slot positions, on-device sampling ([B] ids are the
  only per-step host traffic).
- ``lockstep`` — the ``LMServer.generate``-shaped baseline: FIFO batch
  formation (wait to fill a batch), one shared prompt bucket, every row
  decodes to the LONGEST request's max_new, host-side argmax over the
  full [B, vocab] logits each token.

Reports goodput tokens/sec (only tokens a request asked for count) and
p50/p99 request latency + TTFT per variant, one JSON line each, plus a
``serving_engine_speedup`` line — the continuous-batching win. The
engine's compile discipline (at most one compile per prefill bucket +
one for decode) is asserted via the observe compile tracker.

Usage: python benchmarks/serving_bench.py [--requests 32] [--batch 4]
           [--rate 4] [--prompt-lens 6,12,24] [--max-new 8,16,32]
           [--metrics-out=serving.jsonl] [--smoke]
Prints one JSON line per variant (``--smoke``: tiny model + near-zero
inter-arrival gaps, the tier-1 fast path).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from bench_metrics import metrics_write as _metrics_write  # noqa: E402
from bench_metrics import resolve_metrics_out  # noqa: E402

# --metrics-out=PATH (or BENCH_METRICS_OUT): JSONL trail next to the
# stdout JSON lines, bench.py conventions (inline append, never fatal)
METRICS_OUT = resolve_metrics_out()


def metrics_write(**rec):
    _metrics_write(METRICS_OUT, **rec)


def _pct(vals, q):
    if not vals:
        return 0.0
    s = sorted(vals)
    return s[min(len(s) - 1, int(q * (len(s) - 1) + 0.5))]


def build_workload(n, rate, prompt_lens, max_news, vocab, seed):
    """[(arrival_s, prompt ids, max_new)] — Poisson arrivals, mixed
    prompt/output lengths (the batch-formation-hostile shape)."""
    rng = np.random.RandomState(seed)
    t, work = 0.0, []
    for _ in range(n):
        t += rng.exponential(1.0 / rate)
        tp = int(prompt_lens[rng.randint(len(prompt_lens))])
        work.append((t, rng.randint(0, vocab, tp).astype(np.int32),
                     int(max_news[rng.randint(len(max_news))])))
    return work


def run_engine(params, cfg, work, *, batch, cache_len, buckets):
    """Wall-clock replay through DecodeEngine; returns the result dict.
    A warmup pass (one request per bucket in the trace) pays every
    compile before the clock starts; the tracker then proves the timed
    run added none."""
    from paddle_tpu.observe.compile_tracker import CompileTracker
    from paddle_tpu.serving import DecodeEngine

    tracker = CompileTracker()
    eng = DecodeEngine.from_params(params, cfg, batch=batch,
                                   cache_len=cache_len, buckets=buckets,
                                   seed=0, tracker=tracker)
    from paddle_tpu.core import ragged
    for b in sorted({ragged.bucket_length(len(p), eng.buckets)
                     for _, p, _ in work}):
        eng.submit(np.zeros(min(b, cache_len - 2), np.int32), 2)
    eng.run_until_idle()
    warm = dict(eng.compile_counts())

    reqs, i, t0 = [], 0, time.perf_counter()
    while len(reqs) < len(work) or not eng.idle:
        now = time.perf_counter() - t0
        while i < len(work) and work[i][0] <= now:
            _, prompt, max_new = work[i]
            reqs.append(eng.submit(prompt, max_new))
            i += 1
        if eng.idle:
            time.sleep(min(max(work[i][0] - now, 0.0), 0.05))
            continue
        eng.step()
    wall = time.perf_counter() - t0

    assert eng.compile_counts() == warm, (
        f"timed run recompiled: {warm} -> {eng.compile_counts()}")
    assert eng.compile_counts()["decode"] == 1
    assert eng.compile_counts()["prefill"] <= len(eng.buckets)
    toks = sum(len(r.tokens) for r in reqs)
    lat = [r.latency_s for r in reqs]
    ttft = [r.ttft_s for r in reqs]
    return {"variant": "engine", "requests": len(reqs), "tokens": toks,
            "wall_s": round(wall, 4),
            "tokens_per_sec": round(toks / wall, 2),
            "p50_latency_s": round(_pct(lat, 0.5), 4),
            "p99_latency_s": round(_pct(lat, 0.99), 4),
            "ttft_p50_s": round(_pct(ttft, 0.5), 4),
            "ttft_p99_s": round(_pct(ttft, 0.99), 4),
            "compiles": eng.compile_counts()}


def run_lockstep(params, cfg, work, *, batch, cache_len, buckets):
    """The pre-engine serving discipline on the same trace: fill a
    FIFO batch (pad the tail group), share one prompt bucket, decode
    max(max_new) steps for everyone, sample on host from full logits."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core import ragged
    from paddle_tpu.models import transformer

    prefill = jax.jit(
        lambda p, t: transformer.prefill(p, t, cfg, cache_len))
    step = jax.jit(
        lambda p, c, t, pos: transformer.decode_step(p, c, t, pos, cfg))

    def serve_group(group):
        """One lockstep batch decode, max(max_new) steps for all rows."""
        bucket = ragged.bucket_length(max(len(p) for _, p, _ in group),
                                      buckets)
        toks = np.zeros((batch, bucket), np.int32)
        for r, (_, p, _) in enumerate(group):
            # lockstep needs ONE shared prompt length: left-pad to the
            # group bucket (padding content doesn't affect step timing;
            # a real lockstep server refuses mixed lengths outright)
            toks[r, -len(p):] = p
        steps = max(m for _, _, m in group)
        logits, cache = prefill(params, jnp.asarray(toks))
        out = np.asarray(logits).argmax(-1).astype(np.int32)
        for j in range(steps - 1):
            # host-side sampling baseline: the full [B, vocab] logits
            # cross to numpy every token
            logits, cache = step(params, cache, jnp.asarray(out),
                                 jnp.asarray(bucket + j, jnp.int32))
            out = np.asarray(logits).argmax(-1).astype(np.int32)

    # warmup: compile each bucket the trace uses + the decode step
    for b in sorted({ragged.bucket_length(len(p), buckets)
                     for _, p, _ in work}):
        serve_group([(0.0, np.zeros(b, np.int32), 2)])

    done, i, pending = 0, 0, []
    lat, ttfts, goodput = [], [], 0
    t0 = time.perf_counter()
    while i < len(work) or pending:
        now = time.perf_counter() - t0
        while i < len(work) and work[i][0] <= now:
            pending.append(work[i])
            i += 1
        if len(pending) >= batch or (i == len(work) and pending):
            group = pending[:batch]
            pending = pending[batch:]
            serve_group(group)
            end = time.perf_counter() - t0
            for arr, _p, m in group:
                lat.append(end - arr)
                ttfts.append(end - arr)   # lockstep: tokens land at the
                goodput += m              # END of the batch decode
            done += len(group)
        elif i < len(work):
            time.sleep(min(max(work[i][0] - now, 0.0), 0.05))
    wall = time.perf_counter() - t0
    return {"variant": "lockstep", "requests": done,
            "tokens": goodput, "wall_s": round(wall, 4),
            "tokens_per_sec": round(goodput / wall, 2),
            "p50_latency_s": round(_pct(lat, 0.5), 4),
            "p99_latency_s": round(_pct(lat, 0.99), 4),
            "ttft_p50_s": round(_pct(ttfts, 0.5), 4),
            "ttft_p99_s": round(_pct(ttfts, 0.99), 4)}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--batch", type=int, default=8,
                    help="KV-arena slots (= lockstep batch size)")
    ap.add_argument("--rate", type=float, default=16.0,
                    help="Poisson arrival rate, requests/sec")
    ap.add_argument("--vocab", type=int, default=2048)
    ap.add_argument("--d-model", type=int, default=128)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--cache-len", type=int, default=256)
    ap.add_argument("--prompt-lens", default="8,16,32,64",
                    help="mixed prompt lengths (lockstep pads each "
                         "group to the max)")
    ap.add_argument("--max-new", default="4,8,16,64",
                    help="mixed output budgets (lockstep decodes every "
                         "row to the group max)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--platform", default=None)
    ap.add_argument("--metrics-out", default=None,
                    help="append JSONL records here (bench.py trail "
                         "conventions)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny preset for the tier-1 fast test: few "
                         "requests, near-zero inter-arrival gaps")
    args = ap.parse_args(argv)
    if args.smoke:
        args.requests, args.batch, args.rate = 6, 2, 1e6
        args.vocab, args.d_model, args.layers = 64, 16, 2
        args.cache_len = 64
        args.prompt_lens, args.max_new = "4,10", "4,8"

    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from paddle_tpu.models import transformer

    cfg = transformer.TransformerConfig(
        vocab=args.vocab, d_model=args.d_model,
        n_heads=max(2, args.d_model // 32), n_kv_heads=0,
        n_layers=args.layers, d_ff=args.d_model * 4,
        max_len=args.cache_len,
        dtype=jnp.float32 if jax.default_backend() == "cpu"
        else jnp.bfloat16, use_rope=True)
    params = transformer.init_params(jax.random.PRNGKey(0), cfg)
    prompt_lens = [int(x) for x in args.prompt_lens.split(",")]
    max_news = [int(x) for x in args.max_new.split(",")]
    buckets = tuple(sorted({
        2 ** int(np.ceil(np.log2(max(t, 2)))) for t in prompt_lens}))
    work = build_workload(args.requests, args.rate, prompt_lens,
                          max_news, args.vocab, args.seed)

    results = {}
    for runner in (run_engine, run_lockstep):
        r = runner(params, cfg, work, batch=args.batch,
                   cache_len=args.cache_len, buckets=buckets)
        r.update({"bench": "serving", "platform": jax.default_backend(),
                  "batch": args.batch, "rate": args.rate,
                  "requests_total": args.requests})
        results[r["variant"]] = r
        print(json.dumps(r), flush=True)
        metrics_write(**r)

    speedup = (results["engine"]["tokens_per_sec"]
               / max(results["lockstep"]["tokens_per_sec"], 1e-9))
    final = {"bench": "serving", "metric": "serving_engine_speedup",
             "value": round(speedup, 3),
             "platform": jax.default_backend()}
    print(json.dumps(final), flush=True)
    metrics_write(**final)
    return results


if __name__ == "__main__":
    main()
