#!/usr/bin/env python
"""Paged vs row-arena vs lockstep LM serving under Poisson load.

Replays request traces against three serving surfaces:

- ``engine_paged`` — ``serving.PagedDecodeEngine``: block-table KV
  pool, chunked prefill interleaved with decode, content-hash prefix
  cache (shared prompts prefill once, concurrent same-prefix requests
  adopt each other's blocks mid-flight), on-device sampling.
- ``engine_slots`` — the PR-3 ``serving.DecodeEngine``: whole-row KV
  arena, monolithic bucketed prefill (one long prompt stalls every
  in-flight decoder for its full duration).
- ``lockstep``    — the ``LMServer.generate``-shaped baseline: FIFO
  batch formation, one shared prompt bucket, every row decodes to the
  LONGEST request's max_new, host-side argmax per token.

The throughput phase additionally A/Bs the paged engine's hot-path
levers (figures of merit: tokens/sec and decode MFU per variant):

- ``engine_paged_int8``  — same engine, ``quantize_lm_params`` int8
  weights consumed natively by the decode step (in-scan dequant,
  1-byte weight reads per token; prefill dequantizes wholesale).
- ``engine_paged_kv8``   — same engine over an int8-quantized KV POOL
  (``kv_dtype="int8"``: write-time per-(position, head) quantization,
  dequant fused into the gather) — the decode-side KV-stream lever:
  throughput must hold while the pool holds ~4x the tokens per byte.
- ``engine_paged_pallas`` — same engine, flash-decode + chunked-prefill
  Pallas kernels + fused sampling epilogue (``ops/pallas/``), timed
  only where the ``PADDLE_TPU_PALLAS`` policy resolves ``on`` (TPU
  under ``auto``); off-TPU the artifact records the mode and skips the
  timed run, and every invocation instead replays tiny greedy traces
  through the interpret-mode kernels — fp32 AND quantized-KV pools —
  asserting ids identical to the XLA paths.

Beyond the two trace phases, three KV-quantization scoreboards:

- **capacity** — slots-at-equal-HBM: at the fp32 pool's byte budget,
  how many requests can be RESIDENT at once (admission control is the
  pool-capacity semantic: reservation math binds, slots don't) for
  fp32 vs int8 vs int4 pools. Figures ``slots_at_equal_hbm_*`` and the
  ``slots_int8_ge_2x_fp32`` contract.
- **cold_prefill** — a shared-prefix-free Poisson trace on a fresh
  engine: ``ttft_p50_cold_ms`` isolates the chunked-prefill path with
  zero cache hits (the TTFT half the prefill kernel targets).
- **quality** — ``kv_int8_rel_l2`` / ``kv_int4_rel_l2``: global rel-L2
  of quantized-pool decode logits vs the fp32 pool on a cold chunk
  walk, asserted under ``transformer.kv_rel_l2_budget`` (the PR-5
  tolerance-contract recipe).

TWO phases, each its own trace over the same request mix:

- **throughput** — every request arrives at t=0 (offered load
  saturates the engine), no adversary: wall clock measures CAPACITY,
  which is where the prefix cache pays (tokens/sec, block occupancy,
  hit counts). ``serving_paged_speedup`` = paged/row-arena tokens/sec.
- **latency** — Poisson arrivals at ``--rate`` (chosen so the engines
  keep up): TTFT percentiles measure the SCHEDULING path.
  ``--long-prompt-adversarial`` drops ONE near-``cache_len`` prompt
  mid-burst — the row-arena engine stalls everything for its
  monolithic prefill, the paged engine interleaves chunks with decode
  steps. ``serving_paged_ttft_p99_ratio`` = paged/row-arena TTFT p99.

Trace shaping: ``--shared-prefix-frac F`` injects one common system
prompt (``--shared-prefix-len`` tokens) into fraction F of each trace
— the "millions of users share a system prompt" regime.

Each (variant, phase) replays ``--repeats`` times on a FRESH engine
(cold prefix cache; compiled programs shared via one jit + tracker)
and reports the best run — the least-machine-interference estimate on
a noisy host. Engine compile discipline (one compile per prefill
bucket / (chunk bucket, context span) pair + one decode) is asserted
via the compile tracker. A JSON artifact lands in benchmarks/runs/
(``--out`` to override; skipped under ``--smoke`` unless --out given).

Usage: python benchmarks/serving_bench.py [--requests 96] [--batch 8]
           [--rate 16] [--shared-prefix-frac 0.5]
           [--long-prompt-adversarial] [--block-size 16]
           [--chunk-tokens 64] [--repeats 3]
           [--metrics-out=serving.jsonl] [--smoke]
"""

import argparse
import datetime
import gc
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from bench_metrics import metrics_write as _metrics_write  # noqa: E402
from bench_metrics import resolve_metrics_out  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# --metrics-out=PATH (or BENCH_METRICS_OUT): JSONL trail next to the
# stdout JSON lines, bench.py conventions (inline append, never fatal)
METRICS_OUT = resolve_metrics_out()


def metrics_write(**rec):
    _metrics_write(METRICS_OUT, **rec)


def write_artifact(results, suffix, args):
    """Date-stamped artifact write shared by the serving and fleet
    phases: same-day reruns get an ordering-preserving _b/_c suffix
    instead of overwriting the artifact the regression sentinel
    compares against (the zero_bench convention); --smoke skips the
    write unless --out was given explicitly."""
    out = args.out
    if out is None:
        base = os.path.join(REPO, "benchmarks", "runs",
                            f"{datetime.date.today()}_{suffix}")
        out = base + ".json"
        i = 0
        while os.path.exists(out) and not args.smoke:
            i += 1
            out = f"{base}_{chr(ord('a') + i)}.json"
    if args.out or not args.smoke:
        d = os.path.dirname(out)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(out, "w") as f:
            json.dump(results, f, indent=2)
        print(f"wrote {out}", file=sys.stderr)


def _pct(vals, q):
    if not vals:
        return 0.0
    s = sorted(vals)
    return s[min(len(s) - 1, int(q * (len(s) - 1) + 0.5))]


def build_workload(n, rate, prompt_lens, max_news, vocab, seed, *,
                   shared_frac=0.0, shared_len=0, adversarial=False,
                   cache_len=0, adversarial_max_new=8, burst=0):
    """[(arrival_s, prompt ids, max_new)] — Poisson arrivals, mixed
    prompt/output lengths (the batch-formation-hostile shape).

    ``shared_frac`` of the requests get one common ``shared_len``-token
    system prompt prepended (prefix-cache traffic); ``adversarial``
    additionally inserts ONE near-``cache_len`` prompt arriving
    MID-BURST: the ``burst`` trace arrivals after the midpoint are
    compressed to land milliseconds behind it — the field study's
    long-multimodal-prompt-vs-interactive-traffic collision. A
    row-arena engine must run its monolithic prefill (and then each
    victim's, sequentially) before the burst sees first tokens; the
    paged engine interleaves the victims' (often prefix-cache-hit)
    chunks with the adversary's."""
    rng = np.random.RandomState(seed)
    prefix = rng.randint(0, vocab, shared_len).astype(np.int32)
    t, work = 0.0, []
    for _ in range(n):
        t += rng.exponential(1.0 / rate)
        tp = int(prompt_lens[rng.randint(len(prompt_lens))])
        prompt = rng.randint(0, vocab, tp).astype(np.int32)
        if shared_frac > 0 and rng.rand() < shared_frac:
            prompt = np.concatenate([prefix, prompt])
        work.append((t, prompt,
                     int(max_news[rng.randint(len(max_news))])))
    if adversarial:
        tp_adv = cache_len - adversarial_max_new
        mid = len(work) // 2
        t_mid = work[mid][0]
        for j in range(mid, min(mid + burst, len(work))):
            work[j] = ((t_mid + (j - mid + 1) * 1e-3,) + work[j][1:])
        work.append((t_mid, rng.randint(0, vocab, tp_adv).astype(np.int32),
                     adversarial_max_new))
        work.sort(key=lambda w: w[0])
    return work


def _replay(eng, work):
    """Wall-clock trace replay against either engine; samples slot and
    block occupancy per scheduler step."""
    reqs, i, t0 = [], 0, time.perf_counter()
    occ_slots, occ_blocks = [], []
    while len(reqs) < len(work) or not eng.idle:
        now = time.perf_counter() - t0
        while i < len(work) and work[i][0] <= now:
            _, prompt, max_new = work[i]
            reqs.append(eng.submit(prompt, max_new))
            i += 1
        if eng.idle:
            time.sleep(min(max(work[i][0] - now, 0.0), 0.05))
            continue
        eng.step()
        occ_slots.append(eng.active_count)
        if hasattr(eng, "pool"):
            occ_blocks.append(eng.pool.in_use)
    wall = time.perf_counter() - t0
    return reqs, wall, occ_slots, occ_blocks


def _result(variant, eng, reqs, wall, occ_slots, occ_blocks):
    toks = sum(len(r.tokens) for r in reqs)
    lat = [r.latency_s for r in reqs]
    ttft = [r.ttft_s for r in reqs]
    mfu = eng.decode_mfu()
    r = {"variant": variant, "requests": len(reqs), "tokens": toks,
         "wall_s": round(wall, 4),
         "tokens_per_sec": round(toks / wall, 2),
         # decode MFU (PR-2 accounting): decode FLOPs / (mean step s ×
         # declared chip peak) — nominal-peak on CPU, honest on TPU
         "decode_mfu": round(mfu, 9) if mfu is not None else None,
         "pallas": eng.pallas_mode,
         "p50_latency_s": round(_pct(lat, 0.5), 4),
         "p99_latency_s": round(_pct(lat, 0.99), 4),
         "ttft_p50_s": round(_pct(ttft, 0.5), 4),
         "ttft_p99_s": round(_pct(ttft, 0.99), 4),
         "slot_occupancy_mean": round(
             float(np.mean(occ_slots)) / eng.batch, 3) if occ_slots
         else 0.0,
         "compiles": eng.compile_counts()}
    if occ_blocks:
        r.update({
            "kv_dtype": eng.kv_dtype,
            "kv_bytes_per_token": eng.kv_bytes_per_token,
            "blocks_total": eng.pool.num_blocks,
            "blocks_in_use_peak": int(max(occ_blocks)),
            "blocks_in_use_mean": round(float(np.mean(occ_blocks)), 1),
            "prefix_hit_blocks": int(eng.metrics.get(
                "engine_prefix_cache_hit_blocks_total").value()),
            "prefix_miss_blocks": int(eng.metrics.get(
                "engine_prefix_cache_miss_blocks_total").value()),
            "prefix_hit_tokens_total": sum(
                r_.prefix_hit_tokens for r_ in reqs)})
    return r


def attribution_section(work, reqs, burst, request_log):
    """Per-request tail-latency attribution of one latency-phase
    replay: top-10 slowest by TTFT with their component split, plus —
    when the trace carries the long-prompt adversary — the VICTIM
    summary: the burst requests arriving just behind the adversary,
    whose TTFT the chunked-prefill design promises is dominated by
    prefill-stall (bounded, one chunk at a time) rather than queue
    wait (the row-arena failure mode) or decode.

    Records come from the ENGINE's own ring (``eng.request_log``) —
    one source of truth for the field mapping — joined to the trace's
    arrival times by rid."""
    from paddle_tpu.observe import requests as _oreq
    by_rid = {r["rid"]: r for r in request_log.records()}
    recs = []
    for i, r in enumerate(reqs):
        rec = by_rid.get(r.rid)
        assert rec is not None, (
            f"r{r.rid} missing from the engine request ring "
            f"(capacity {request_log.capacity}, "
            f"{request_log.evicted()} evicted) — trace too large "
            f"for the ring; raise PADDLE_TPU_REQUEST_LOG")
        rec = dict(rec)
        rec["arrival_s"] = round(work[i][0], 6)
        rec["attribution"] = _oreq.attribute(rec)
        recs.append(rec)
    slowest = sorted(recs, key=lambda r: r["ttft_s"] or 0.0,
                     reverse=True)[:10]
    out = {"requests": len(recs), "slowest_by_ttft": slowest}
    adversary = max(range(len(work)), key=lambda i: len(work[i][1]))
    t_adv = work[adversary][0]
    victims = [recs[i] for i in range(len(work))
               if i != adversary
               and t_adv <= work[i][0] <= t_adv + burst * 1e-3 + 1e-9]
    if victims:
        # dominance over the TTFT components (queue/own/stall): the
        # victims' damage is time-to-first-token — a long generation
        # afterwards (decode) is not the adversary's doing
        dom = {}
        for v in victims:
            d = v["attribution"]["ttft_dominant"]
            dom[d] = dom.get(d, 0) + 1
        out["victims"] = {
            "count": len(victims),
            "adversary_prompt_tokens": len(work[adversary][1]),
            "ttft_dominant_counts": dom,
            "ttft_dominant": max(dom, key=dom.get),
            "ttft_p50_s": round(_pct(
                [v["ttft_s"] for v in victims], 0.5), 6),
            "prefill_stall_p50_s": round(_pct(
                [v["prefill_stall_s"] for v in victims], 0.5), 6),
            "queue_wait_p50_s": round(_pct(
                [v["queue_wait_s"] for v in victims], 0.5), 6)}
    return out


def assert_lifecycles_joined(trace, reqs, buf):
    """Every completed request of the replay must have a fully-joined
    lifecycle in the exported trace: its async track present, every
    opened slice closed (b/e balanced), and a first_token marker — no
    orphan spans, no foreign tracks."""
    assert buf.dropped() == 0, (
        f"trace ring dropped {buf.dropped()} events — joins "
        f"unverifiable; raise PADDLE_TPU_TRACE_BUFFER")
    evs = [e for e in trace["traceEvents"] if e.get("cat") == "request"]
    by_id = {}
    for e in evs:
        by_id.setdefault(e["id"], []).append(e)
    for r in reqs:
        assert r.finish_reason is not None, f"r{r.rid} never finished"
        es = by_id.get(r.trace_id)
        assert es, f"request {r.trace_id}: no lifecycle events"
        b = sum(1 for e in es if e["ph"] == "b")
        e_ = sum(1 for e in es if e["ph"] == "e")
        assert b == e_ >= 1, (
            f"request {r.trace_id}: orphan async spans "
            f"({b} opened, {e_} closed)")
        assert any(e["name"] == "first_token" for e in es), (
            f"request {r.trace_id}: no first_token marker")
    extra = set(by_id) - {r.trace_id for r in reqs}
    assert not extra, f"orphan request tracks in trace: {sorted(extra)}"


def assert_fleet_lifecycles_joined(trace, reqs, buf):
    """Router-aware join check for a fleet replay: every completed
    request's track must be ONE connected tree — balanced b/e, exactly
    one router-side ``route`` root, the engine lifecycle (queued/
    prefill/decode/first_token) present on the SAME id — and a
    requeued request must still be single-rooted (its second placement
    re-joins the original trace, with the requeue marker and a second
    ``queued`` open on the track). No orphan tracks."""
    assert buf.dropped() == 0, (
        f"trace ring dropped {buf.dropped()} events — joins "
        f"unverifiable; raise PADDLE_TPU_TRACE_BUFFER")
    evs = [e for e in trace["traceEvents"]
           if e.get("cat") == "request" and e.get("ph") in "bne"]
    by_id = {}
    for e in evs:
        by_id.setdefault(e["id"], []).append(e)
    for r in reqs:
        assert r.status == "done", f"x{r.xid} ended {r.status!r}"
        es = by_id.get(r.trace_id)
        assert es, f"request {r.trace_id}: no lifecycle events"
        b = sum(1 for e in es if e["ph"] == "b")
        e_ = sum(1 for e in es if e["ph"] == "e")
        assert b == e_ >= 1, (
            f"request {r.trace_id}: orphan async spans "
            f"({b} opened, {e_} closed)")
        roots = [e for e in es if e["name"] == "route"
                 and e["ph"] == "b"]
        assert len(roots) == 1, (
            f"request {r.trace_id}: {len(roots)} route roots")
        names = [e["name"] for e in es]
        for engine_side in ("queued", "prefill", "decode",
                            "first_token"):
            assert engine_side in names, (
                f"request {r.trace_id}: missing {engine_side}")
        if r.requeues > 0:
            assert "requeue" in names and names.count("queued") >= 2, (
                f"requeued {r.trace_id} did not re-join: {names}")
    extra = set(by_id) - {r.trace_id for r in reqs}
    assert not extra, f"orphan request tracks in trace: {sorted(extra)}"


def _paged_programs(lens, chunk, bs, buckets):
    """The (chunk bucket, page-vector length) program set a COLD walk
    of the given prompt lengths reaches — one compile each (prefix
    hits and mid-flight adoption only ever SKIP chunk calls)."""
    from paddle_tpu.core import ragged
    progs = set()
    for n in lens:
        off = 0
        while off < n:
            c = min(n - off, chunk)
            b = ragged.bucket_length(c, buckets)
            progs.add((b, off // bs + -(-b // bs)))
            off += c
    return progs


def paged_factory(params, cfg, *, batch, cache_len, block_size,
                  chunk_tokens, num_blocks, tracker, pallas=None,
                  kv_dtype=None):
    """() -> fresh PagedDecodeEngine (cold pool + prefix cache) around
    ONE jitted program pair and ONE tracker, so repeat replays reuse
    the compile cache and the compile invariant spans all of them.
    ``pallas`` pins the PADDLE_TPU_PALLAS policy for the step programs;
    ``params`` may be the quantize_lm_params int8 tree (the int8
    serving variant); ``kv_dtype`` quantizes the KV pool itself
    ("int8"/"int4" — the engine_paged_kv8 variant)."""
    import jax

    from paddle_tpu.models import transformer
    from paddle_tpu.ops.pallas import policy as _pallas_policy
    from paddle_tpu.serving import PagedDecodeEngine, sampling
    from paddle_tpu.serving.engine import _decode_step_flops
    nb = int(num_blocks if num_blocks is not None
             else batch * (cache_len // block_size))
    prefill_fn, decode_fn = sampling.paged_step_fns(cfg, block_size,
                                                    pallas=pallas)
    jpf, jdf = jax.jit(prefill_fn), jax.jit(decode_fn)
    pool0 = transformer.init_block_pool(cfg, nb, block_size,
                                        kv_dtype=kv_dtype)
    flops = _decode_step_flops(
        jdf, params, pool0, batch,
        np.zeros((batch, cache_len // block_size), np.int32))
    mode = _pallas_policy.pallas_mode(pallas)

    def make():
        pool = transformer.init_block_pool(cfg, nb, block_size,
                                           kv_dtype=kv_dtype)
        return PagedDecodeEngine(
            jpf, jdf, params, pool, batch=batch, cache_len=cache_len,
            block_size=block_size, num_blocks=nb,
            chunk_tokens=chunk_tokens, seed=0, tracker=tracker,
            decode_flops=flops, pallas_mode=mode, kv_dtype=kv_dtype)

    return make


def slots_factory(params, cfg, *, batch, cache_len, buckets, tracker):
    """() -> fresh row-arena DecodeEngine, same shared-compile setup."""
    import jax

    from paddle_tpu.models import transformer
    from paddle_tpu.serving import DecodeEngine, sampling
    from paddle_tpu.serving.engine import _decode_step_flops
    prefill_fn, decode_fn = sampling.engine_step_fns(cfg, pallas="off")
    jpf, jdf = jax.jit(prefill_fn), jax.jit(decode_fn)
    cache0 = transformer.init_cache(cfg, batch, cache_len)
    flops = _decode_step_flops(jdf, params, cache0, batch)

    def make():
        cache = transformer.init_cache(cfg, batch, cache_len)
        return DecodeEngine(jpf, jdf, params, cache, batch=batch,
                            cache_len=cache_len, buckets=buckets,
                            seed=0, tracker=tracker, decode_flops=flops,
                            pallas_mode="off")

    return make


def warm_engine(factory, work, vocab):
    """One cold submit per distinct trace length covers every program
    the replay can reach; returns the compile counts to hold fixed."""
    wrng = np.random.RandomState(7)
    eng = factory()
    for n in sorted({len(p) for _, p, _ in work}):
        eng.submit(wrng.randint(0, vocab, n).astype(np.int32), 2)
        eng.run_until_idle()
    return dict(eng.compile_counts())


def engine_once(factory, variant, work, warm):
    """One replay on a FRESH engine (cold pool + prefix cache; the
    compiled programs and tracker are the factory's, shared)."""
    eng = factory()
    reqs, wall, occ_s, occ_b = _replay(eng, work)
    assert eng.compile_counts() == warm, (
        f"{variant}: timed replay recompiled: "
        f"{warm} -> {eng.compile_counts()}")
    return _result(variant, eng, reqs, wall, occ_s, occ_b)


def capacity_phase(params, cfg, *, cache_len, block_size, chunk_tokens,
                   batch, num_blocks, vocab, seed):
    """Slots-at-equal-HBM: at the fp32 pool's byte budget, how many
    requests can be RESIDENT at once per KV dtype. Admission is the
    measurement — the engine's worst-case reservation math is the
    pool-capacity semantic (decode never stalls mid-flight, so what
    admits is what serves) — taken as ``batch - free_slots`` after one
    scheduler step with a saturating submit wave and slot count sized
    past the pool's theoretical ceiling, so blocks, not slots, bind."""
    import jax.numpy as jnp

    from paddle_tpu.models import transformer
    from paddle_tpu.observe.compile_tracker import CompileTracker
    from paddle_tpu.serving import PagedDecodeEngine
    nb_fp = int(num_blocks if num_blocks is not None
                else batch * (cache_len // block_size))
    budget = nb_fp * block_size * transformer.kv_pool_bytes_per_token(
        cfg)
    prompt_len = min(chunk_tokens, cache_len // 2)
    max_new = min(16, cache_len - prompt_len)
    per_req = -(-(prompt_len + max_new) // block_size)
    # the baseline pool stores the MODEL dtype: "fp32" on the CPU bench
    # config, bf16 on TPU — name the keys honestly, because the >= 2x
    # contract is only reachable against a 4-byte baseline (vs bf16 the
    # int8+scale byte ratio is 4Dh/(2Dh+8) < 2 for every head_dim)
    base_key = ("fp32" if jnp.dtype(cfg.dtype).itemsize >= 4
                else jnp.dtype(cfg.dtype).name)
    out = {"pool_bytes_budget": int(budget),
           "prompt_tokens": prompt_len, "max_new": max_new,
           "blocks_per_request": per_req, "baseline_kv": base_key}
    rng = np.random.RandomState(seed + 17)
    slots = {}
    for kvd in (None, "int8", "int4"):
        bytes_tok = transformer.kv_pool_bytes_per_token(cfg, kvd)
        nb = max(int(budget // (block_size * bytes_tok)), per_req)
        cap = nb // per_req + 2           # slots can never be binding
        eng = PagedDecodeEngine.from_params(
            params, cfg, batch=cap, cache_len=cache_len,
            block_size=block_size, chunk_tokens=chunk_tokens,
            num_blocks=nb, seed=0, kv_dtype=kvd, pallas="off",
            tracker=CompileTracker(), decode_flops=None)
        for _ in range(cap):
            eng.submit(rng.randint(0, vocab, prompt_len)
                       .astype(np.int32), max_new)
        eng.step()                        # one admission wave
        key = base_key if kvd is None else kvd
        slots[key] = eng.batch - eng.free_slots
        out[f"slots_at_equal_hbm_{key}"] = slots[key]
        out[f"blocks_at_equal_hbm_{key}"] = nb
        out[f"kv_bytes_per_token_{key}"] = bytes_tok
    base = slots[base_key]
    out["slots_int8_ratio"] = round(slots["int8"] / max(base, 1), 3)
    out["slots_int4_ratio"] = round(slots["int4"] / max(base, 1), 3)
    # the contract: >= 2x against an fp32 baseline (the ISSUE figure);
    # against a narrower baseline the honest bound is the byte-ratio
    # arithmetic itself, minus admission-granularity slack
    byte_ratio = (out[f"kv_bytes_per_token_{base_key}"]
                  / out["kv_bytes_per_token_int8"])
    if base_key == "fp32":
        out["slots_int8_ge_2x_fp32"] = bool(slots["int8"] >= 2 * base)
        out["capacity_contract_ok"] = out["slots_int8_ge_2x_fp32"]
    else:
        out["capacity_contract_ok"] = bool(
            out["slots_int8_ratio"] >= 0.9 * byte_ratio)
    return out


def _chunk_walk(params, cfg, prompt, kv_dtype, *, block_size,
                chunk_tokens, pallas="off"):
    """Cold chunk-walk of one prompt on the engine's chunk grid (the
    same program shapes the engine compiles) into a fresh pool;
    returns (decode-step logits at position len(prompt), pool)."""
    import jax.numpy as jnp

    from paddle_tpu.core import ragged
    from paddle_tpu.models import transformer
    from paddle_tpu.serving import default_chunk_buckets
    bs = block_size
    n = len(prompt)
    pages_needed = -(-(n + 1) // bs)
    pool = transformer.init_block_pool(cfg, pages_needed + 1, bs,
                                       kv_dtype=kv_dtype)
    buckets = default_chunk_buckets(chunk_tokens)
    pages = np.arange(pages_needed + 1, dtype=np.int32)
    off, lg = 0, None
    while off < n:
        c = min(n - off, chunk_tokens)
        b = ragged.bucket_length(c, buckets)
        padded = np.zeros((1, b), np.int32)
        padded[0, :c] = prompt[off:off + c]
        pv = pages[:off // bs + -(-b // bs)]
        lg, pool = transformer.prefill_into_blocks(
            params, pool, jnp.asarray(padded), np.int32(c),
            jnp.asarray(pv), cfg, block_size=bs, pallas=pallas)
        off += c
    tok = jnp.argmax(lg, -1).astype(jnp.int32)
    logits, _ = transformer.decode_step_paged(
        params, pool, tok, jnp.asarray([n], jnp.int32),
        jnp.ones((1,), bool),
        jnp.asarray(pages[:pages_needed][None]), cfg, block_size=bs,
        pallas=pallas)
    return np.asarray(logits), pool


def kv_quality_probe(params, cfg, *, block_size, chunk_tokens, vocab,
                     seed):
    """Global rel-L2 of quantized-pool decode logits vs the fp32 pool
    on one cold multi-chunk prompt — recorded per dtype and ASSERTED
    under the documented grid-noise budget, so a committed artifact
    certifies generation quality on the host that produced it."""
    from paddle_tpu.models import transformer
    rng = np.random.RandomState(seed + 23)
    prompt = rng.randint(0, vocab, 2 * chunk_tokens + 5).astype(
        np.int32)
    ref, _ = _chunk_walk(params, cfg, prompt, None,
                         block_size=block_size,
                         chunk_tokens=chunk_tokens)
    out = {}
    for kvd in ("int8", "int4"):
        lg, _ = _chunk_walk(params, cfg, prompt, kvd,
                            block_size=block_size,
                            chunk_tokens=chunk_tokens)
        rel = float(np.linalg.norm(lg - ref) / np.linalg.norm(ref))
        budget = transformer.kv_rel_l2_budget(cfg, kvd)
        assert rel < budget, (
            f"kv_{kvd}_rel_l2 {rel:.4f} breaches the grid-noise "
            f"budget {budget:.4f} — wrong-scale-class bug")
        out[f"kv_{kvd}_rel_l2"] = round(rel, 6)
        out[f"kv_{kvd}_rel_l2_budget"] = round(budget, 6)
    return out


def tpu_export_check(params, cfg, *, block_size, chunk_tokens, batch,
                     cache_len):
    """Deviceless XLA:TPU export of the paged step programs (decode +
    one contextful chunk prefill) per KV dtype on the XLA attention
    path — the quantized pool's scatter writes, int8/int4 gathers and
    fused dequant all compile for TPU with no chip attached — PLUS
    direct per-kernel Mosaic lowering probes of all four serving
    kernels (flash-decode, chunk-prefill attention, span-write, fused
    sampler) per KV dtype. Since the head-major pool relayout every
    probe must SUCCEED: ``mosaic_ok`` aggregates them, the caller
    asserts it, and the regression sentinel
    (``check_regression.py mosaic_lowerable_ok``) keeps a layout
    regression from ever landing silently. The artifact also stamps
    each kernel's legal BlockSpec geometry and VMEM estimate — the
    evidence a reader needs to see WHY the shapes are tiling-legal."""
    import jax
    import jax.export  # noqa: F401
    import jax.numpy as jnp

    from paddle_tpu.models import transformer
    from paddle_tpu.ops.pallas import decode as _fd
    from paddle_tpu.ops.pallas import prefill as _fp
    from paddle_tpu.serving import sampling
    os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")
    bs = block_size
    B = batch
    P = cache_len // bs
    Hkv, Dh = cfg.kv_heads, cfg.head_dim
    G = cfg.n_heads // Hkv
    p_shapes = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(np.shape(a),
                                       np.asarray(a).dtype), params)
    i32 = jax.ShapeDtypeStruct((), jnp.int32)
    out = {"pool_layout": transformer.POOL_LAYOUT,
           "blockspecs": {}, "vmem_bytes": {}}
    ok_all = True
    for kvd in (None, "int8", "int4"):
        key = "fp32" if kvd is None else kvd
        # one zero pool per dtype serves both the exported-program
        # shapes and the probe/blockspec geometry below
        pool = transformer.init_block_pool(cfg, B * P, bs,
                                           kv_dtype=kvd)
        pool_shapes = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), pool)
        dargs = (p_shapes, pool_shapes,
                 jax.ShapeDtypeStruct((B,), jnp.int32),
                 jax.ShapeDtypeStruct((B,), jnp.int32),
                 jax.ShapeDtypeStruct((B,), jnp.bool_),
                 jax.ShapeDtypeStruct((B, P), jnp.int32),
                 jax.ShapeDtypeStruct((B,), jnp.float32),
                 jax.ShapeDtypeStruct((B,), jnp.int32), i32)
        ctx_pages = chunk_tokens // bs          # one contextful chunk
        pargs = (p_shapes, pool_shapes,
                 jax.ShapeDtypeStruct((1, chunk_tokens), jnp.int32),
                 i32,
                 jax.ShapeDtypeStruct((2 * ctx_pages,), jnp.int32),
                 jax.ShapeDtypeStruct((), jnp.float32), i32, i32)
        pf, df = sampling.paged_step_fns(cfg, bs, pallas="off")
        try:
            nd = len(jax.export.export(
                jax.jit(df), platforms=["tpu"])(*dargs).serialize())
            np_ = len(jax.export.export(
                jax.jit(pf), platforms=["tpu"])(*pargs).serialize())
            out[f"xla_{key}_ok"] = True
            out[f"xla_{key}_bytes"] = nd + np_
        except Exception as e:                  # noqa: BLE001
            out[f"xla_{key}_ok"] = False
            out[f"xla_{key}_detail"] = (
                f"{type(e).__name__}: {str(e)[:300]}")
        # DIRECT per-kernel Mosaic lowering probes — the head-major
        # relayout is exactly what makes these succeed, so a refusal
        # is a regression, not a diagnostic to record and move past.
        # These are the same cached probes the mode="on" dispatch
        # consults (decode.decode_lowering_ok & co) plus the fused
        # sampler, run at the bench geometry AND the bench model's
        # activation dtype (q_dtype=cfg.dtype — the probe must lower
        # the very program the engine would dispatch; a bf16-only
        # tiling regression would otherwise slip past an fp32 probe).
        kvq = kvd or "none"
        dt = pool["k"].dtype
        M = B * P * bs
        S = ctx_pages * bs
        probes = {
            "pallas_decode": lambda: _fd.decode_lowering_ok(
                M, P, bs, Hkv, G, Dh, dt, kv_dtype=kvq,
                q_dtype=cfg.dtype),
            "pallas_prefill": lambda: _fp.prefill_lowering_ok(
                M, S, chunk_tokens, bs, Hkv, G, Dh, dt, kv_dtype=kvq,
                q_dtype=cfg.dtype),
            "pallas_span_write": lambda: _fp.span_write_lowering_ok(
                M, -(-chunk_tokens // bs), bs, cfg.n_layers, Hkv, Dh,
                dt, kv_dtype=kvq),
            "pallas_sample": lambda: _fd.sample_lowering_ok(
                B, cfg.vocab),
        }
        kinds = {"pallas_decode": "decode", "pallas_prefill": "prefill",
                 "pallas_span_write": "span_write",
                 "pallas_sample": "sample"}
        for tag, probe in probes.items():
            seen = set(_fd.lowering_failures())
            got = bool(probe())
            out[f"{tag}_{key}_ok"] = got
            ok_all &= got
            if not got:
                # prefer the diagnostic this very probe just recorded;
                # a cached refusal recorded no fresh entry, so fall
                # back to every same-kind diagnostic rather than
                # guessing one signature's
                det = {k: v for k, v in _fd.lowering_failures().items()
                       if k not in seen}
                det = det or _fd.lowering_failures(kinds[tag])
                out[f"{tag}_{key}_detail"] = (
                    "; ".join(sorted(set(det.values())))
                    if det else "no detail")
        Dh_st = pool["k"].shape[-1]
        tile = _fd.select_decode_tile(P, bs, Dh, dt, kvq)
        ptile = _fp.select_prefill_tile(ctx_pages, bs, chunk_tokens,
                                        Dh, dt, kvq)
        out["blockspecs"][key] = {
            "pool": list(pool["k"].shape),
            "decode_pool_block": [1, bs, Dh_st],
            "decode_grid": [B, Hkv, P // tile],
            "decode_tile": tile,
            "prefill_pool_block": [1, bs, Dh_st],
            "prefill_grid": [Hkv, ctx_pages // ptile],
            "prefill_tile": ptile,
            "span_write_block": [cfg.n_layers, Hkv, bs, Dh_st],
            "scalar_prefetch": {
                "decode": ["pages", "pos"],
                "prefill": ["pages"], "span_write": ["pages"],
                "sample": ["seed", "temperature", "top_k"]},
        }
        out["vmem_bytes"][key] = {
            "decode": _fd.decode_vmem_bytes(
                M, P, bs, G, Dh, jnp.dtype(dt).itemsize, kvq,
                tile=tile),
            "prefill": _fp.prefill_vmem_bytes(
                M, S, chunk_tokens, G, Dh, jnp.dtype(dt).itemsize,
                kvq),
        }
    out["mosaic_ok"] = ok_all
    return out


def build_draft_pair(vocab, d_model, layers, heads, max_len, *,
                     alpha=0.05, draft_layers=1, seed=0):
    """A synthetically distilled (target, draft) pair: the target's
    layers beyond ``draft_layers`` get their residual-output weights
    (attn_out / mlp_out) scaled by ``alpha``, and the draft IS the
    target's first ``draft_layers`` layers + the shared embedding head.
    The target's compute cost is untouched (matmul shapes identical —
    small values are not faster), but its logits land close to the
    draft's, standing in for the trained/distilled draft a production
    deployment ships. What the spec phase measures is the ENGINE
    mechanics (propose/verify dispatch structure) at the acceptance
    rate this pair reaches — the acceptance itself is reported in the
    artifact, never assumed."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.models import transformer
    cfg = transformer.TransformerConfig(
        vocab=vocab, d_model=d_model, n_heads=heads, n_kv_heads=0,
        n_layers=layers, d_ff=d_model * 4, max_len=max_len,
        dtype=jnp.float32, use_rope=True)
    params = transformer.init_params(jax.random.PRNGKey(seed), cfg)
    blocks = dict(params["blocks"])
    for leaf in ("attn_out", "mlp_out"):
        w = np.array(blocks[leaf])
        w[draft_layers:] *= alpha
        blocks[leaf] = jnp.asarray(w)
    params = dict(params, blocks=blocks)
    draft_cfg = transformer.TransformerConfig(
        vocab=vocab, d_model=d_model, n_heads=heads, n_kv_heads=0,
        n_layers=draft_layers, d_ff=d_model * 4, max_len=max_len,
        dtype=jnp.float32, use_rope=True)
    draft_params = dict(params, blocks={
        k: v[:draft_layers] for k, v in params["blocks"].items()})
    return cfg, params, draft_cfg, draft_params


def spec_phase(args):
    """Speculative decoding A/B: the SAME greedy trace through a
    target-only paged engine and a SpecDecodeEngine sharing the pool.
    Figure of merit: ``spec_decode_speedup`` (tokens/sec ratio) — with
    output BITWISE-identical between the two engines asserted on every
    repeat (acceptance moves throughput, never tokens). The phase runs
    its own config (small draft-friendly model, decode-step-bound
    trace); the main phases' figures are untouched by it."""
    import jax

    from paddle_tpu.models import transformer
    from paddle_tpu.observe.compile_tracker import CompileTracker
    from paddle_tpu.serving import (PagedDecodeEngine, SpecDecodeEngine,
                                    sampling)
    if args.smoke:
        vocab, d_model, layers, heads = 64, 16, 2, 2
        cache_len, batch, k, n_req = 64, 2, 2, 4
        tp, max_new, bs, chunk, repeats = 8, 8, 8, 16, 1
    else:
        # decode-step-bound config (modest batch, long pool view):
        # where one verify dispatch replacing k+1 decode dispatches —
        # and one pool-view stream serving W rows — actually pays on
        # this backend; the draft pair's acceptance is ~0.95
        vocab, d_model, layers, heads = 256, 64, 2, 2
        cache_len, batch, k, n_req = 512, 6, 6, 24
        tp, max_new, bs, chunk, repeats = 16, 64, 16, 64, \
            max(1, args.repeats)
    cfg, params, draft_cfg, draft_params = build_draft_pair(
        vocab, d_model, layers, heads, cache_len + 32, seed=args.seed)
    rng = np.random.RandomState(args.seed + 31)
    prompts = [rng.randint(0, vocab, tp).astype(np.int32)
               for _ in range(n_req)]
    nb = batch * (cache_len // bs)
    kw = dict(batch=batch, cache_len=cache_len, block_size=bs,
              chunk_tokens=chunk, num_blocks=nb, seed=0,
              decode_flops=None)
    prefill_fn, decode_fn = sampling.paged_step_fns(cfg, bs,
                                                    pallas="off")
    jpf, jdf = jax.jit(prefill_fn), jax.jit(decode_fn)
    spec_fns = sampling.paged_spec_fns(cfg, draft_cfg, bs, k,
                                       pallas="off")
    jspec = {n: jax.jit(f) for n, f in spec_fns.items()}
    tr_t = CompileTracker(storm_threshold=99)
    tr_s = CompileTracker(storm_threshold=99)

    def mk_target():
        pool = transformer.init_block_pool(cfg, nb, bs)
        return PagedDecodeEngine(jpf, jdf, params, pool, tracker=tr_t,
                                 **kw)

    def mk_spec():
        pool = transformer.init_block_pool(cfg, nb, bs)
        dpool = transformer.init_block_pool(draft_cfg, nb, bs)
        return SpecDecodeEngine(
            jpf, jdf, params, pool, draft_params=draft_params,
            draft_cache=dpool, draft_prefill=jspec["draft_prefill"],
            propose=jspec["propose"], verify=jspec["verify"],
            draft_verify=jspec["draft_verify"], spec_k=k,
            tracker=tr_s, **kw)

    def once(mk):
        eng = mk()
        t0 = time.perf_counter()
        reqs = [eng.submit(p, max_new=max_new) for p in prompts]
        eng.run_until_idle()
        wall = time.perf_counter() - t0
        toks = sum(len(r.tokens) for r in reqs)
        return toks / wall, [list(r.tokens) for r in reqs], eng

    for mk in (mk_target, mk_spec):            # warm the programs
        eng = mk()
        eng.submit(prompts[0], max_new=4)
        eng.run_until_idle()
    best = {"target": 0.0, "spec": 0.0}
    acc = None
    for _ in range(repeats):                   # interleaved repeats
        tps_t, out_t, _ = once(mk_target)
        tps_s, out_s, eng_s = once(mk_spec)
        assert out_t == out_s, (
            "spec-decode greedy output diverged from the target-only "
            "engine — the bitwise verify contract is broken")
        best["target"] = max(best["target"], tps_t)
        best["spec"] = max(best["spec"], tps_s)
        acc = eng_s.acceptance_rate()
    # compile discipline: the spec engine adds its OWN program set
    # (draft prefill mirroring the chunk grid + one propose + one
    # verify) while the TARGET program set is unchanged — same chunk
    # programs, and the plain decode program never dispatches
    assert tr_s.count("serving_engine.prefill") == \
        tr_t.count("serving_engine.prefill"), (
        "spec engine changed the TARGET chunk-program set: "
        f"{tr_s.count('serving_engine.prefill')} vs "
        f"{tr_t.count('serving_engine.prefill')}")
    assert tr_s.count("serving_engine.draft_prefill") == \
        tr_t.count("serving_engine.prefill")
    assert tr_s.count("serving_engine.propose") == 1
    assert tr_s.count("serving_engine.verify") == 1
    assert tr_s.count("serving_engine.decode") == 0
    assert tr_t.count("serving_engine.decode") == 1
    speedup = best["spec"] / max(best["target"], 1e-9)
    out = {"spec_k": k, "vocab": vocab, "d_model": d_model,
           "layers": layers, "cache_len": cache_len, "batch": batch,
           "requests": n_req, "max_new": max_new,
           "draft_layers": draft_cfg.n_layers,
           "acceptance_rate": round(acc, 4) if acc is not None else None,
           "target_tokens_per_sec": round(best["target"], 1),
           "spec_tokens_per_sec": round(best["spec"], 1),
           "spec_decode_speedup": round(speedup, 3),
           "greedy_bitwise_ok": True}
    if not args.smoke:
        assert speedup >= 1.5, (
            f"spec_decode_speedup {speedup:.3f} below the 1.5 floor "
            f"(acceptance {acc}) — artifact would certify a broken "
            f"figure")
    return out


def build_tiered_workload(n, rate, vocab, seed, *, lat_frac=0.4,
                          lat_lens=(12, 16, 24), lat_new=(8, 12, 16),
                          bulk_lens=(48, 64, 96),
                          bulk_new=(32, 48, 64)):
    """[(arrival_s, prompt, max_new, tenant, tier)]: an interactive
    tenant (short prompts, short outputs, latency tier) sharing the
    engine with a bulk tenant (long prompts, long outputs, batch
    tier) — the tiered-traffic collision the Ascend field study names
    as the dominant serving regime."""
    rng = np.random.RandomState(seed)
    t, work = 0.0, []
    for _ in range(n):
        t += rng.exponential(1.0 / rate)
        if rng.rand() < lat_frac:
            tp = int(rng.choice(lat_lens))
            mn = int(rng.choice(lat_new))
            work.append((t, rng.randint(0, vocab, tp).astype(np.int32),
                         mn, "interactive", "latency"))
        else:
            tp = int(rng.choice(bulk_lens))
            mn = int(rng.choice(bulk_new))
            work.append((t, rng.randint(0, vocab, tp).astype(np.int32),
                         mn, "bulk", "batch"))
    return work


def _replay_tiered(eng, work, *, tiered):
    """Replay a tiered workload; ``tiered=False`` submits everything
    batch-tier (the single-class FIFO baseline) while keeping each
    request's INTENDED tier for the per-tier percentile split."""
    reqs, i, t0 = [], 0, time.perf_counter()
    while len(reqs) < len(work) or not eng.idle:
        now = time.perf_counter() - t0
        while i < len(work) and work[i][0] <= now:
            _, prompt, mn, tenant, tier = work[i]
            reqs.append((eng.submit(
                prompt, mn, tenant=tenant,
                tier=tier if tiered else "batch"), tier))
            i += 1
        if eng.idle:
            time.sleep(min(max(work[i][0] - now, 0.0), 0.05))
            continue
        eng.step()
    wall = time.perf_counter() - t0
    return reqs, wall


def multitenant_phase(args):
    """Multi-tenant scheduling A/B on ONE Poisson trace mixing an
    interactive (latency-tier) and a bulk (batch-tier) tenant over a
    deliberately TIGHT pool: ``tiered`` (real tiers — priority
    admission + preempt-to-blocks) vs ``fifo`` (everything batch-tier,
    the single-tenant PR-6 discipline). The scheduler must buy
    latency-tier TTFT separation (latency p99 < batch p99 under
    contention) without giving up aggregate goodput — under block
    pressure it actually GAINS goodput, because tiered admission skips
    past a reservation-blocked bulk head that FIFO would idle the pool
    behind."""
    import jax

    from paddle_tpu.models import transformer
    from paddle_tpu.observe.compile_tracker import CompileTracker
    from paddle_tpu.serving import PagedDecodeEngine, sampling
    if args.smoke:
        vocab, d_model, layers, heads = 64, 16, 2, 2
        cache_len, batch, n_req, rate = 64, 2, 8, 1e6
        bs, chunk, nb, repeats = 8, 16, 12, 1
        shape = dict(lat_lens=(4, 6), lat_new=(3, 4),
                     bulk_lens=(16, 24), bulk_new=(8, 16))
    else:
        vocab, d_model, layers, heads = 256, 64, 2, 2
        cache_len, batch, n_req, rate = 512, 8, 64, 150.0
        bs, chunk = 16, 64
        # tight pool + offered load far above capacity (the burst
        # regime): ~3 bulk requests' worst case fills the pool within
        # the first admission waves, so reservations (not slots) are
        # the contended resource and latency-tier arrivals landing
        # behind them actually preempt — on any machine speed
        nb, repeats = 30, max(1, args.repeats)
        shape = dict(bulk_new=(48, 64, 96))
    cfg = transformer.TransformerConfig(
        vocab=vocab, d_model=d_model, n_heads=heads, n_kv_heads=0,
        n_layers=layers, d_ff=d_model * 4, max_len=cache_len + 32,
        dtype=jax.numpy.float32, use_rope=True)
    params = transformer.init_params(jax.random.PRNGKey(args.seed), cfg)
    work = build_tiered_workload(n_req, rate, vocab, args.seed + 41,
                                 **shape)
    prefill_fn, decode_fn = sampling.paged_step_fns(cfg, bs,
                                                    pallas="off")
    jpf, jdf = jax.jit(prefill_fn), jax.jit(decode_fn)
    tracker = CompileTracker(storm_threshold=99)

    def mk():
        pool = transformer.init_block_pool(cfg, nb, bs)
        return PagedDecodeEngine(
            jpf, jdf, params, pool, batch=batch, cache_len=cache_len,
            block_size=bs, chunk_tokens=chunk, num_blocks=nb, seed=0,
            tracker=tracker, decode_flops=None)

    eng = mk()                                  # warm every program
    for n in sorted({len(p) for _, p, _, _, _ in work}):
        eng.submit(np.arange(n) % vocab, 2)
        eng.run_until_idle()

    def once(tiered):
        eng = mk()
        reqs, wall = _replay_tiered(eng, work, tiered=tiered)
        toks = sum(len(r.tokens) for r, _ in reqs)
        by_tier = {}
        for r, tier in reqs:
            by_tier.setdefault(tier, []).append(r.ttft_s)
        out = {"tokens_per_sec": round(toks / wall, 2),
               "wall_s": round(wall, 3),
               "preemptions": int(eng.metrics.get(
                   "engine_preemptions_total").value()),
               "resumes_remap": int(eng.metrics.get(
                   "engine_resumes_total").value(mode="remap")),
               "resumes_replay": int(eng.metrics.get(
                   "engine_resumes_total").value(mode="replay"))}
        for tier, tt in sorted(by_tier.items()):
            out[f"ttft_p50_{tier}_s"] = round(_pct(tt, 0.5), 4)
            out[f"ttft_p99_{tier}_s"] = round(_pct(tt, 0.99), 4)
            out[f"requests_{tier}"] = len(tt)
        assert eng.pool.idle, "block leak after multi-tenant trace"
        return out

    runs_t, runs_f = [], []
    for _ in range(repeats):
        runs_t.append(once(True))
        runs_f.append(once(False))
    # the reported run per variant is its best at ITS OWN figure of
    # merit (tiered = latency-tier p99, the SLO the scheduler serves;
    # fifo = goodput, the bar it sets) — but the GOODPUT comparison
    # must be best-vs-best at the SAME figure, or a machine-load spike
    # during tiered's best-latency run would masquerade as scheduler
    # overhead
    best_t = min(runs_t, key=lambda r: r["ttft_p99_latency_s"])
    best_f = max(runs_f, key=lambda r: r["tokens_per_sec"])
    sep_ok = (best_t["ttft_p99_latency_s"]
              < best_t["ttft_p99_batch_s"])
    goodput_ratio = (max(r["tokens_per_sec"] for r in runs_t)
                     / max(best_f["tokens_per_sec"], 1e-9))
    out = {"requests": n_req, "rate": rate, "batch": batch,
           "num_blocks": nb, "cache_len": cache_len,
           "tiered": best_t, "fifo": best_f,
           "tier_p99_separation_ok": bool(sep_ok),
           "tier_ttft_p99_ratio": round(
               best_t["ttft_p99_latency_s"]
               / max(best_t["ttft_p99_batch_s"], 1e-9), 4),
           # the scheduler's OWN effect: the latency tier's p99 under
           # tiered admission vs the SAME requests under FIFO — the
           # separation a short prompt gets for free cancels out of
           # this ratio
           "latency_p99_vs_fifo": round(
               best_t["ttft_p99_latency_s"]
               / max(best_f["ttft_p99_latency_s"], 1e-9), 4),
           "goodput_ratio_vs_fifo": round(goodput_ratio, 4),
           # >= within a 5% noise band: the two replays race the same
           # wall clock on a shared host; the tight-pool design makes
           # tiered genuinely >= 1.0 in the mean (admission skips the
           # blocked bulk head FIFO idles behind)
           "goodput_ge_fifo": bool(goodput_ratio >= 0.95)}
    if not args.smoke:
        assert sep_ok, (
            f"latency-tier p99 {best_t['ttft_p99_latency_s']} not "
            f"separated below batch-tier p99 "
            f"{best_t['ttft_p99_batch_s']}")
        assert sum(r["preemptions"] for r in runs_t) >= 1, (
            "multitenant trace never exercised preemption — the "
            "artifact would certify an idle scheduler")
    return out


def build_chat_workload(n_convos, turns, prefix_tokens, tail_tokens,
                        max_new, vocab, seed):
    """[(arrival_s, prompt, max_new)] — a multi-turn chat trace: each
    conversation carries its OWN ``prefix_tokens``-token system prompt
    and re-arrives once per turn with a fresh ``tail_tokens`` user
    message appended. Conversations are ROUND-ROBIN interleaved, so by
    the time a conversation's next turn lands, every other prefix has
    marched through the pool — with the working set sized past HBM
    (``--working-set-mult``) the prefix is always LRU-evicted before
    its reuse, the regime the tiered spill exists for."""
    rng = np.random.RandomState(seed)
    prefixes = [rng.randint(0, vocab, prefix_tokens).astype(np.int32)
                for _ in range(n_convos)]
    work = []
    for _ in range(turns):
        for c in range(n_convos):
            tail = rng.randint(0, vocab, tail_tokens).astype(np.int32)
            work.append((0.0, np.concatenate([prefixes[c], tail]),
                         max_new))
    return work


def tiered_cache_phase(args):
    """Tiered prefix cache (HBM -> host DRAM -> disk) vs
    evict-and-recompute on a multi-turn chat trace whose prefix
    working set is ``--working-set-mult``x the block pool.

    Both variants replay the SAME saturating trace on the SAME pool
    size; the baseline's only recourse on prefix reuse is a cold
    chunked prefill, the tiered engine re-admits demoted blocks
    through ``import_prefix`` (bitwise — the hit-vs-cold contract
    crosses tiers). The DRAM arena is sized to ~1/3 of the working
    set so the disk tier is genuinely exercised, not decorative.

    Figures: ``cold_prefill_tokens_avoided_frac`` (counter-derived,
    near-deterministic — the fraction of the baseline's cold-prefill
    block misses the tiers absorbed) and ``tiered_ttft_p99_ratio``
    (tiered/baseline TTFT p99 — < 1 wherever promotion is cheaper
    than the prefill FLOPs it replaces). Under ``--smoke`` the phase
    shrinks the trace and instead pins the BITWISE contract: every
    tiered-run output identical to a never-evicting big-pool engine's,
    with DRAM and disk promotions both proven live."""
    import shutil
    import tempfile

    import jax

    from paddle_tpu.models import transformer
    from paddle_tpu.observe.compile_tracker import CompileTracker
    from paddle_tpu.serving import PagedDecodeEngine, sampling
    mult = max(float(args.working_set_mult), 1.5)
    if args.smoke:
        vocab, d_model, layers, heads = 64, 16, 2, 2
        cache_len, batch = 64, 2
        bs, chunk, nb, repeats = 8, 16, 12, 1
        prefix_tokens, tail_tokens, turns, max_new = 16, 8, 2, 3
        mult = min(mult, 2.0)
    else:
        # d_model sized so a 256-token cold prefill costs MATERIAL
        # compute: the tiers trade a per-block host round-trip
        # (~size-independent python dispatch) against the prefill
        # FLOPs it replaces, and a toy width would measure the
        # dispatch, not the trade the feature exists for
        vocab, d_model, layers, heads = 256, 192, 2, 6
        cache_len, batch = 384, 4
        bs, chunk = 16, 32
        # two timed replays, not --repeats: the avoided-fraction
        # figure is counter arithmetic (deterministic), only the TTFT
        # ratio benefits from a best-of — and each replay pair costs
        # tens of seconds at this width
        nb, repeats = 64, max(1, min(2, args.repeats))
        prefix_tokens, tail_tokens, turns, max_new = 256, 32, 3, 8
    prefix_blocks = prefix_tokens // bs
    n_convos = max(2, -(-int(mult * nb) // prefix_blocks))
    work = build_chat_workload(n_convos, turns, prefix_tokens,
                               tail_tokens, max_new, vocab,
                               args.seed + 71)
    cfg = transformer.TransformerConfig(
        vocab=vocab, d_model=d_model, n_heads=heads, n_kv_heads=0,
        n_layers=layers, d_ff=d_model * 4, max_len=cache_len + 32,
        dtype=jax.numpy.float32, use_rope=True)
    params = transformer.init_params(jax.random.PRNGKey(args.seed), cfg)
    prefill_fn, decode_fn = sampling.paged_step_fns(cfg, bs,
                                                    pallas="off")
    jpf, jdf = jax.jit(prefill_fn), jax.jit(decode_fn)
    tracker = CompileTracker(storm_threshold=99)
    tmp = tempfile.mkdtemp(prefix="paddle_tpu_tiers_bench_")
    run_seq = [0]

    def mk(tiers=None, num_blocks=nb):
        pool = transformer.init_block_pool(cfg, num_blocks, bs)
        return PagedDecodeEngine(
            jpf, jdf, params, pool, batch=batch, cache_len=cache_len,
            block_size=bs, chunk_tokens=chunk, num_blocks=num_blocks,
            seed=0, tracker=tracker, decode_flops=None, tiers=tiers)

    # tier sizing off the REAL pool byte rate: DRAM holds ~1/3 of the
    # prefix working set (forcing the overflow onto disk), disk holds
    # the rest with room
    probe = mk()
    ws_bytes = int(n_convos * prefix_tokens * probe.kv_bytes_per_token)
    dram_bytes, disk_bytes = int(ws_bytes * 0.35), int(ws_bytes * 2)
    # warm every chunk program once (jpf/jdf are shared across engines,
    # so each timed replay below starts compiled)
    warm = mk()
    warm.submit(work[0][1], max_new)
    warm.run_until_idle()

    def once(tiered):
        tiers = None
        if tiered:
            run_seq[0] += 1
            d = os.path.join(tmp, f"run{run_seq[0]}")
            os.makedirs(d)
            tiers = {"dram_bytes": dram_bytes,
                     "disk_bytes": disk_bytes, "disk_dir": d}
        eng = mk(tiers)
        # GC off for the timed replay: the spill path allocates one
        # host buffer per demoted/promoted block, and in a process
        # carrying the earlier phases' object graph each of those
        # allocations can trigger a full-heap gc scan — a tax on the
        # tiered variant that scales with BENCH history, not with the
        # feature (standalone the ratio is ~0.8; late in the full
        # sweep it read >1 from gc pauses alone)
        gc.collect()
        gc.disable()
        try:
            reqs, wall, _, occ_blocks = _replay(eng, work)
        finally:
            gc.enable()
        ttft = [r.ttft_s for r in reqs]
        m = eng.metrics
        out = {"tokens_per_sec": round(
                   sum(len(r.tokens) for r in reqs) / wall, 2),
               "wall_s": round(wall, 3),
               "ttft_p50_s": round(_pct(ttft, 0.5), 4),
               "ttft_p99_s": round(_pct(ttft, 0.99), 4),
               "prefix_hit_blocks": int(m.get(
                   "engine_prefix_cache_hit_blocks_total").value()),
               "prefix_miss_blocks": int(m.get(
                   "engine_prefix_cache_miss_blocks_total").value()),
               "blocks_in_use_peak": int(max(occ_blocks))}
        if tiered:
            out["tier_hit_blocks"] = {
                t: int(m.get("engine_prefix_tier_hit_blocks_total")
                       .value(tier=t)) for t in ("hbm", "dram", "disk")}
            out["demotions"] = {
                t: int(m.get("engine_tier_demotions_total")
                       .value(tier=t)) for t in ("dram", "disk")}
            out["tier_corrupt"] = int(m.get(
                "engine_tier_corrupt_total").value())
        assert eng.pool.idle, "block leak after tiered-cache trace"
        return out, [r.output.tolist() for r in reqs]

    try:
        runs_t, runs_b = [], []
        for _ in range(repeats):
            runs_t.append(once(True))
            runs_b.append(once(False))
        best_t = min(runs_t, key=lambda r: r[0]["ttft_p99_s"])
        best_b = min(runs_b, key=lambda r: r[0]["ttft_p99_s"])
        if args.smoke:
            # bitwise across tiers: a never-evicting big-pool engine
            # serves every request warm — the tiered run (which
            # demoted, spilled to disk, and promoted back) must emit
            # IDENTICAL ids for all of them
            big = mk(num_blocks=len(work) * (
                -(-(prefix_tokens + tail_tokens + max_new) // bs)) + 8)
            ref_reqs, _, _, _ = _replay(big, work)
            ref_out = [r.output.tolist() for r in ref_reqs]
            assert best_t[1] == ref_out, (
                "tiered outputs diverged from the big-pool reference "
                "(hit-vs-cold contract broken across tiers)")
            assert best_b[1] == ref_out, (
                "baseline outputs diverged from the big-pool reference")
        th = best_t[0]["tier_hit_blocks"]
        assert th["dram"] + th["disk"] > 0, (
            "tiered trace never promoted a block — the figures would "
            "certify an idle spill path")
        assert best_t[0]["tier_corrupt"] == 0, best_t[0]
        miss_t = best_t[0]["prefix_miss_blocks"]
        miss_b = best_b[0]["prefix_miss_blocks"]
        avoided = 1.0 - miss_t / max(miss_b, 1)
        ratio = (best_t[0]["ttft_p99_s"]
                 / max(best_b[0]["ttft_p99_s"], 1e-9))
        out = {"requests": len(work), "conversations": n_convos,
               "turns": turns, "working_set_mult": round(mult, 2),
               "num_blocks": nb, "prefix_tokens": prefix_tokens,
               "dram_bytes": dram_bytes, "disk_bytes": disk_bytes,
               "tiered": best_t[0], "baseline": best_b[0],
               "cold_prefill_tokens_avoided_frac": round(avoided, 4),
               "tiered_ttft_p99_ratio": round(ratio, 4)}
        if not args.smoke:
            # the avoided fraction is counter arithmetic on a fixed
            # trace — assert the >= 0.5 claim outright (the TTFT ratio
            # breathes with the host and is gated by the sentinel's
            # absolute ceiling instead)
            assert avoided >= 0.5, (
                f"tiers absorbed only {avoided:.1%} of the baseline's "
                f"cold-prefill misses: {out}")
            assert th["disk"] > 0, (
                "disk tier never promoted on the full trace — DRAM "
                "sizing no longer forces the overflow down a tier")
        return out
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _replay_router(router, work):
    """Wall-clock trace replay against a fleet Router (mirrors
    ``_replay``'s arrival discipline; one router.step() per
    iteration pumps every in-process replica one engine step)."""
    reqs, i, t0 = [], 0, time.perf_counter()
    while len(reqs) < len(work) or not router.idle:
        now = time.perf_counter() - t0
        while i < len(work) and work[i][0] <= now:
            _, prompt, max_new = work[i]
            reqs.append(router.submit(prompt, max_new))
            i += 1
        if router.idle:
            time.sleep(min(max(work[i][0] - now, 0.0), 0.05))
            continue
        router.step()
    return reqs, time.perf_counter() - t0


def _fleet_victims(work, burst):
    """Indices of the burst arrivals compressed behind the adversarial
    long prompt (the longest prompt in the trace) — the victim set the
    TTFT figure scores."""
    lens = [len(p) for _, p, _ in work]
    adv = int(np.argmax(lens))
    return adv, set(range(adv + 1, min(adv + 1 + burst, len(work))))


def fleet_phase(args):
    """Serving-fleet A/B: a prefix-aware Router over R in-process
    replicas vs ONE engine at EQUAL total slots and pool blocks, on
    the shared-prefix trace with the long-prompt adversary mid-burst.

    Figures: router goodput ratio (fleet tokens/sec over the
    equal-chip single engine), victim TTFT p99 ratio (the burst
    arrivals stuck behind the adversary — the fleet quarantines the
    adversary's chunked prefill on ONE replica while the others keep
    serving, where the single engine makes every decoder share the
    stall), placement hit rate (shared-prefix traffic converging onto
    warm pools), an all-requests-completed bool, a P/D
    disaggregation bitwise check (prefill replica exports the KV
    prefix over the transfer wire, decode replica adopts it via the
    prefix-cache publish path, outputs equal the colocated run —
    asserted outright, it must never rot), an observability_overhead
    figure (fleet goodput with tracing+aggregation ON over OFF — the
    observability plane must stay off the hot path), and a chaos run
    (replica kill mid-burst) whose joined multi-replica trace, fleet
    /metrics render, and dead-replica firing→resolved alert pair are
    asserted outright (exported via --trace-out)."""
    from paddle_tpu.observe.compile_tracker import CompileTracker
    from paddle_tpu.serving import EngineReplica, default_chunk_buckets
    from paddle_tpu.serving.router import Router

    import jax
    import jax.numpy as jnp
    from paddle_tpu.models import transformer

    R = 2 if args.smoke else 3
    per_batch = max(2, args.batch // 2)
    pages = args.cache_len // args.block_size
    cfg = transformer.TransformerConfig(
        vocab=args.vocab, d_model=args.d_model,
        n_heads=max(2, args.d_model // 32), n_kv_heads=0,
        n_layers=args.layers, d_ff=args.d_model * 4,
        max_len=args.cache_len,
        dtype=jnp.float32 if jax.default_backend() == "cpu"
        else jnp.bfloat16, use_rope=True)
    params = transformer.init_params(jax.random.PRNGKey(2), cfg)
    prompt_lens = [int(x) for x in args.prompt_lens.split(",")]
    max_news = [int(x) for x in args.max_new.split(",")]
    burst = R * per_batch
    work = build_workload(
        args.requests, args.rate, prompt_lens, max_news, args.vocab,
        args.seed + 5, shared_frac=max(args.shared_prefix_frac, 0.5),
        shared_len=args.shared_prefix_len, adversarial=True,
        cache_len=args.cache_len, burst=burst)
    adv_i, victims = _fleet_victims(work, burst)

    chunk = min(args.chunk_tokens, args.cache_len)
    storm = (args.cache_len // chunk) * len(
        default_chunk_buckets(chunk)) + 2
    mk_rep = paged_factory(
        params, cfg, batch=per_batch, cache_len=args.cache_len,
        block_size=args.block_size, chunk_tokens=args.chunk_tokens,
        num_blocks=per_batch * pages,
        tracker=CompileTracker(storm_threshold=storm), pallas="off")
    mk_single = paged_factory(
        params, cfg, batch=R * per_batch, cache_len=args.cache_len,
        block_size=args.block_size, chunk_tokens=args.chunk_tokens,
        num_blocks=R * per_batch * pages,
        tracker=CompileTracker(storm_threshold=storm), pallas="off")
    warm_rep = warm_engine(mk_rep, work, args.vocab)
    warm_single = warm_engine(mk_single, work, args.vocab)

    def once_single():
        eng = mk_single()
        reqs, wall, occ_s, occ_b = _replay(eng, work)
        assert eng.compile_counts() == warm_single, "single recompiled"
        toks = sum(len(r.tokens) for r in reqs)
        vt = sorted(r.ttft_s for i, r in enumerate(reqs)
                    if i in victims)
        return {"tokens_per_sec": round(toks / wall, 2),
                "wall_s": round(wall, 4), "tokens": toks,
                "victim_ttft_p99_s": round(_pct(vt, 0.99), 4),
                "requests": len(reqs),
                "completed": sum(1 for r in reqs
                                 if r.finish_reason is not None)}

    def once_fleet(observed=True):
        # observed=True is the PRODUCTION configuration (request
        # tracing + fleet metrics aggregation on, the router default);
        # observed=False is the dark baseline the observability_
        # overhead figure compares against
        reps = [EngineReplica(mk_rep(), f"r{i}") for i in range(R)]
        router = Router(reps, block_size=args.block_size,
                        chunk_tokens=args.chunk_tokens,
                        max_in_flight=per_batch * 2,
                        health_poll_s=0.5, trace=observed,
                        aggregate=observed)
        reqs, wall = _replay_router(router, work)
        for eng in (r.eng for r in reps):
            assert eng.compile_counts() == warm_rep, "fleet recompiled"
        toks = sum(len(r.tokens) for r in reqs)
        vt = sorted(r.ttft_s for i, r in enumerate(reqs)
                    if i in victims and r.ttft_s is not None)
        return {"tokens_per_sec": round(toks / wall, 2),
                "wall_s": round(wall, 4), "tokens": toks,
                "victim_ttft_p99_s": round(_pct(vt, 0.99), 4),
                "requests": len(reqs),
                "completed": sum(1 for r in reqs
                                 if r.status == "done"),
                "failed": sum(1 for r in reqs
                              if r.status == "failed"),
                "requeued": int(router._m_requeued.value()),
                "replicas": R, "slots_per_replica": per_batch,
                "placement_hit_rate": round(
                    router.placement_hit_rate(), 4)}

    repeats = max(1, args.repeats)
    single = fleet = fleet_dark = None
    for _ in range(repeats):       # interleaved, best goodput per side
        s, f = once_single(), once_fleet(observed=True)
        fd = once_fleet(observed=False)
        if single is None or s["tokens_per_sec"] > \
                single["tokens_per_sec"]:
            single = s
        if fleet is None or f["tokens_per_sec"] > \
                fleet["tokens_per_sec"]:
            fleet = f
        if fleet_dark is None or fd["tokens_per_sec"] > \
                fleet_dark["tokens_per_sec"]:
            fleet_dark = fd

    # P/D disaggregation bitwise check: colocated reference vs a
    # 1-prefill + 1-decode router fleet over the SAME compiled programs
    pd_prompts = [p for _, p, _ in work
                  if len(p) > args.chunk_tokens][:3]
    ref_eng = mk_rep()
    ref_out = []
    for p in pd_prompts:
        r = ref_eng.submit(p, 8)
        ref_eng.run_until_idle()
        ref_out.append(r.output)
    pf, dc = EngineReplica(mk_rep(), "pf"), EngineReplica(mk_rep(), "dc")
    pd_router = Router([pf, dc], block_size=args.block_size,
                       chunk_tokens=args.chunk_tokens, prefill=["pf"],
                       health_poll_s=0.5)
    pd_reqs = [pd_router.submit(p, 8) for p in pd_prompts]
    pd_router.run_until_idle()
    pd_ok = all(np.array_equal(r.output, w)
                for r, w in zip(pd_reqs, ref_out))
    assert pd_ok, "P/D disaggregated generation diverged from the " \
                  "colocated run"
    assert int(pd_router._m_pd_exports.value()) >= 1

    # chaos + trace-join: the observability acceptance run. One more
    # fleet with the span buffer captured end-to-end; kill the replica
    # holding the first placed request mid-run. Every request must
    # still complete, the requeued requests' spans must re-join their
    # ORIGINAL trace id (balanced b/e, exactly one router-side `route`
    # root), the fleet metrics render (what router /metrics serves)
    # must carry replica-labeled series and the pooled-TTFT quantile
    # gauges, and the dead-replica alert must fire and then resolve on
    # admin removal — asserted outright, the joined-timeline contract
    # must never rot.
    from paddle_tpu import observe
    buf = observe.default_buffer()
    if not buf.enabled or buf.capacity < 65536:
        buf = observe.set_trace_capacity(65536)
    buf.clear()
    ch_reps = [EngineReplica(mk_rep(), f"r{i}") for i in range(R)]
    ch_router = Router(ch_reps, block_size=args.block_size,
                       chunk_tokens=args.chunk_tokens,
                       max_in_flight=per_batch * 2, health_poll_s=0.0)
    ch_reqs = [ch_router.submit(p, m) for _, p, m in work]
    for _ in range(3):
        ch_router.step()
    placed = [r for r in ch_reqs if r.replica is not None]
    assert placed, "chaos run placed nothing before the kill"
    victim = placed[0].replica
    next(st.handle for st in ch_router._all
         if st.name == victim).kill()
    ch_router.run_until_idle()
    assert all(r.status == "done" for r in ch_reqs), \
        "chaos run lost requests"
    ch_requeued = [r for r in ch_reqs if r.requeues > 0]
    assert ch_requeued, "kill injection requeued nothing"
    mtext = ch_router.metrics_text()
    assert "fleet_ttft_window_seconds" in mtext, \
        "fleet /metrics missing pooled quantile gauges"
    assert 'fleet_engine_queue_depth{replica="' in mtext, \
        "fleet /metrics missing replica-labeled series"
    assert any(a["rule"] == "fleet_dead_replicas"
               for a in ch_router.alerts.firing()), \
        "replica death did not fire the dead-replica alert"
    ch_router.remove_replica(victim)
    ch_router.step()
    assert ch_router.alerts.firing() == [], \
        "dead-replica alert did not resolve after removal"
    alert_events = [(e["rule"], e["event"])
                    for e in ch_router.alerts.events]
    assert ("fleet_dead_replicas", "firing") in alert_events
    assert ("fleet_dead_replicas", "resolved") in alert_events
    trace = observe.trace_export(args.trace_out) if args.trace_out \
        else observe.trace_export()
    assert_fleet_lifecycles_joined(trace, ch_reqs, buf)
    if args.trace_out:
        print(f"wrote fleet trace to {args.trace_out} "
              f"({len(ch_reqs)} requests, {len(ch_requeued)} "
              f"requeued through the kill, all lifecycles joined)",
              file=sys.stderr)

    completed_ok = (fleet["failed"] == 0
                    and fleet["completed"] == len(work)
                    and fleet["requeued"] == 0)
    out = {
        "single": single, "fleet": fleet,
        "fleet_untraced": fleet_dark,
        "adversary_prompt_tokens": len(work[adv_i][1]),
        "victims": len(victims),
        "router_goodput_ratio": round(
            fleet["tokens_per_sec"]
            / max(single["tokens_per_sec"], 1e-9), 3),
        "victim_ttft_ratio": round(
            fleet["victim_ttft_p99_s"]
            / max(single["victim_ttft_p99_s"], 1e-9), 3),
        "placement_hit_rate": fleet["placement_hit_rate"],
        # goodput with tracing+aggregation ON over OFF on the same
        # machine — ~1.0 when the observability plane is off the hot
        # path; the sentinel holds it inside the noise band
        "observability_overhead": round(
            fleet["tokens_per_sec"]
            / max(fleet_dark["tokens_per_sec"], 1e-9), 3),
        "all_requests_completed": completed_ok,
        "pd_bitwise_ok": pd_ok,
        "pd_blocks_shipped": int(pd_router._m_pd_blocks.value()),
        "chaos_joined_ok": True,      # the asserts above are the proof
        "chaos": {"requests": len(ch_reqs),
                  "requeued": len(ch_requeued),
                  "killed_replica": victim,
                  "alert_pair_ok": True}}
    assert completed_ok, f"fleet lost requests: {fleet}"
    return out


def build_chaos_workload(n, rate, prompt_lens, max_news, vocab, seed,
                         *, shared_len, cache_len, peak_mult=4.0,
                         lat_frac=0.4):
    """Diurnal Poisson trace for the fleet-chaos phase: the arrival
    rate follows one sinusoidal day (trough -> peak at the middle ->
    trough, peak = ``peak_mult`` x base), ~``lat_frac`` of requests
    ride the latency tier, and EVERY request opens with one shared
    system prompt — so survivors hold the prefix warm and the
    rewarm-after-heal figure has something real to measure."""
    rng = np.random.default_rng(seed)
    shared = rng.integers(1, vocab, size=shared_len).astype(np.int32)
    work, t = [], 0.0
    for i in range(n):
        frac = i / max(n - 1, 1)
        r = rate * (1.0 + (peak_mult - 1.0) * 0.5
                    * (1.0 - math.cos(2.0 * math.pi * frac)))
        t += float(rng.exponential(1.0 / r))
        plen = int(rng.choice(prompt_lens))
        mn = int(rng.choice(max_news))
        tail = rng.integers(1, vocab, size=plen).astype(np.int32)
        prompt = np.concatenate([shared, tail])[:cache_len - mn]
        tier = "latency" if rng.random() < lat_frac else "batch"
        work.append((t, prompt, mn, tier))
    return work


def _replay_fleet_chaos(router, work, *, ctrl=None, fleet=None,
                        kill_at=None):
    """Wall-clock chaos replay: submit through the door (admission
    sheds are counted, not errors), kill the busiest replica at
    ``kill_at`` seconds, step the controller (when given) every
    router step, and time the recovery."""
    from paddle_tpu.serving.router import AdmissionError
    reqs, shed, i = [], 0, 0
    killed = kill_t = healed_t = None
    max_q = 0
    t0 = time.perf_counter()
    while (i < len(work) or not router.idle
           or (ctrl is not None and killed is not None
               and healed_t is None
               and time.perf_counter() - t0 - kill_t < 30.0)):
        now = time.perf_counter() - t0
        while i < len(work) and work[i][0] <= now:
            _, prompt, mn, tier = work[i]
            try:
                reqs.append(router.submit(prompt, mn, tier=tier))
            except AdmissionError:
                shed += 1
            i += 1
        if kill_at is not None and killed is None and now >= kill_at:
            live = [st for st in router._all if st.state != "dead"]
            if any(st.in_flight > 0 for st in live):
                victim = max(live, key=lambda st: st.in_flight)
                if fleet is not None:
                    fleet.kill_name(victim.name)
                else:
                    victim.handle.kill()
                killed, kill_t = victim.name, now
        router.step()
        max_q = max(max_q, router.queue_depth)
        if ctrl is not None:
            ctrl.step()
            if (killed is not None and healed_t is None
                    and router.replica_states().get(killed) == "ok"):
                healed_t = time.perf_counter() - t0
        if router.idle:
            if i < len(work):
                time.sleep(min(max(work[i][0] - now, 0.0), 0.01))
            elif killed is not None and healed_t is None:
                time.sleep(0.002)   # drained: waiting out the heal
                #                     backoff alone
    return {"reqs": reqs, "shed": shed,
            "wall": time.perf_counter() - t0, "killed": killed,
            "kill_t": kill_t, "healed_t": healed_t, "max_queue": max_q}


def fleet_chaos_phase(args):
    """Fleet-control-plane A/B on a diurnal trace with an injected
    kill at the peak: a CONTROLLED fleet (FleetController healing +
    rewarm, door-side admission shedding batch past the queue bound)
    vs a STATIC baseline (same replicas, no controller, no admission
    — the dead replica stays dead and the door queues everything).

    Figures: latency-tier TTFT p99 under chaos (absolute ceiling —
    the band the control plane must hold), controlled-over-static
    TTFT ratio (the control plane must not be WORSE than doing
    nothing), healed capacity fraction (live replicas at the end over
    the provisioned fleet — the heal loop closed), recovery seconds
    (kill to the replacement reporting ok), rewarm blocks shipped to
    the replacement (cold-prefill work the KV relay avoided), and a
    shed-before-saturate boolean (the door shed batch work AND the
    queue never blew past the latency headroom — rejections happened
    at the door, not as timeouts in the queue)."""
    from paddle_tpu.observe import SloConfig
    from paddle_tpu.serving import EngineReplica
    from paddle_tpu.serving.autoscale import (FleetController,
                                              InProcessFleet)
    from paddle_tpu.serving.router import Router
    from paddle_tpu.observe.compile_tracker import CompileTracker

    import jax
    import jax.numpy as jnp
    from paddle_tpu.models import transformer

    R = 2 if args.smoke else 3
    per_batch = max(2, args.batch // 2)
    pages = args.cache_len // args.block_size
    cfg = transformer.TransformerConfig(
        vocab=args.vocab, d_model=args.d_model,
        n_heads=max(2, args.d_model // 32), n_kv_heads=0,
        n_layers=args.layers, d_ff=args.d_model * 4,
        max_len=args.cache_len,
        dtype=jnp.float32 if jax.default_backend() == "cpu"
        else jnp.bfloat16, use_rope=True)
    params = transformer.init_params(jax.random.PRNGKey(3), cfg)
    prompt_lens = [int(x) for x in args.prompt_lens.split(",")]
    max_news = [int(x) for x in args.max_new.split(",")]
    rate = min(args.rate, 64.0)     # smoke's all-at-once 1e6 would
    #                                 erase the diurnal shape entirely
    work = build_chaos_workload(
        args.requests, rate, prompt_lens, max_news, args.vocab,
        args.seed + 7, shared_len=args.shared_prefix_len,
        cache_len=args.cache_len)
    kill_at = work[len(work) // 2][0]       # the peak of the day
    shed_max = max(2, args.requests // 8)

    mk_rep = paged_factory(
        params, cfg, batch=per_batch, cache_len=args.cache_len,
        block_size=args.block_size, chunk_tokens=args.chunk_tokens,
        num_blocks=per_batch * pages,
        tracker=CompileTracker(storm_threshold=10**9), pallas="off")
    warm_engine(mk_rep, [w[:3] for w in work], args.vocab)

    def lat_p99(reqs):
        # admitted latency-tier requests only: the tier the SLO prices
        vt = sorted(r.ttft_s for r in reqs
                    if r.tier == "latency" and r.ttft_s is not None)
        return round(_pct(vt, 0.99), 4)

    def side(reqs, res):
        return {"requests": len(reqs),
                "completed": sum(1 for r in reqs
                                 if r.status == "done"),
                "shed": res["shed"],
                "max_queue": res["max_queue"],
                "latency_ttft_p99_s": lat_p99(reqs),
                "wall_s": round(res["wall"], 4),
                "killed_replica": res["killed"]}

    # -- controlled: controller + admission --------------------------------
    fleet = InProcessFleet(lambda name: mk_rep())
    for i in range(R):
        fleet.spawn(f"r{i}")
    handles = [fleet.handle(f"r{i}") for i in range(R)]
    router = Router(handles, block_size=args.block_size,
                    chunk_tokens=args.chunk_tokens,
                    max_in_flight=per_batch * 2, health_poll_s=0.05,
                    shed_queue_max=shed_max,
                    slo=SloConfig(ttft_s=0.5, target=0.99,
                                  window_s=30.0))
    ctrl = FleetController(
        router, fleet, min_replicas=R, max_replicas=R,
        max_restarts=5, backoff_base=0.02, backoff_cap=0.1,
        rewarm=True, scale_up_queue=0, scale_down_idle_s=1e9)
    res_c = _replay_fleet_chaos(router, work, ctrl=ctrl, fleet=fleet,
                                kill_at=kill_at)
    reqs_c = res_c["reqs"]
    assert res_c["killed"] is not None, "chaos kill never fired"
    assert res_c["healed_t"] is not None, \
        "the controller never healed the killed replica"
    for _ in range(500):    # land the rewarm export/import ops the
        #                     replay left outstanding
        if router.outstanding == 0:
            break
        router.step()
        time.sleep(0.001)
    live_end = sum(1 for s in router.replica_states().values()
                   if s == "ok")
    rewarm_shipped = int(router._m_rewarm.value(result="shipped"))
    # no P/D tier in this phase: every imported block is a rewarm
    # relay — KV the replacement did NOT have to cold-prefill
    rewarm_blocks = int(router._m_pd_blocks.value())
    recovery_s = round(res_c["healed_t"] - res_c["kill_t"], 4)
    controlled = side(reqs_c, res_c)
    router.close()

    # -- static: same fleet shape, nobody at the wheel ----------------------
    s_handles = [EngineReplica(mk_rep(), f"r{i}") for i in range(R)]
    s_router = Router(s_handles, block_size=args.block_size,
                      chunk_tokens=args.chunk_tokens,
                      max_in_flight=per_batch * 2, health_poll_s=0.05)
    res_s = _replay_fleet_chaos(s_router, work, kill_at=kill_at)
    reqs_s = res_s["reqs"]
    static = side(reqs_s, res_s)
    s_router.close()

    admitted_ok = all(r.status == "done" for r in reqs_c)
    assert admitted_ok, "controlled run lost admitted requests"
    assert all(r.status == "done" for r in reqs_s), \
        "static run lost requests"
    c_p99, s_p99 = controlled["latency_ttft_p99_s"], \
        static["latency_ttft_p99_s"]
    shed_ok = (res_c["shed"] > 0
               and res_c["max_queue"] <= 2 * shed_max)
    return {
        "controlled": controlled, "static": static,
        "replicas": R, "shed_queue_max": shed_max,
        "kill_at_s": round(kill_at, 4),
        "chaos_latency_ttft_p99_s": c_p99,
        "chaos_ttft_ratio": round(c_p99 / max(s_p99, 1e-9), 3),
        "healed_capacity_frac": round(live_end / R, 3),
        "recovery_s": recovery_s,
        "rewarm_exports": rewarm_shipped,
        "rewarm_blocks_avoided": rewarm_blocks,
        "shed_before_saturate_ok": shed_ok,
        "all_admitted_completed": admitted_ok,
    }


def lockstep_factory(params, cfg, *, batch, cache_len, buckets):
    """(warm_fn, once_fn) for the pre-engine serving discipline: fill a
    FIFO batch (pad the tail group), share one prompt bucket, decode
    max(max_new) steps for everyone, sample on host from full logits."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core import ragged
    from paddle_tpu.models import transformer

    prefill = jax.jit(
        lambda p, t: transformer.prefill(p, t, cfg, cache_len))
    step = jax.jit(
        lambda p, c, t, pos: transformer.decode_step(p, c, t, pos, cfg))

    def serve_group(group):
        """One lockstep batch decode, max(max_new) steps for all rows."""
        bucket = ragged.bucket_length(max(len(p) for _, p, _ in group),
                                      buckets)
        toks = np.zeros((batch, bucket), np.int32)
        for r, (_, p, _) in enumerate(group):
            # lockstep needs ONE shared prompt length: left-pad to the
            # group bucket (padding content doesn't affect step timing;
            # a real lockstep server refuses mixed lengths outright)
            toks[r, -len(p):] = p
        steps = max(m for _, _, m in group)
        logits, cache = prefill(params, jnp.asarray(toks))
        out = np.asarray(logits).argmax(-1).astype(np.int32)
        for j in range(steps - 1):
            # host-side sampling baseline: the full [B, vocab] logits
            # cross to numpy every token
            logits, cache = step(params, cache, jnp.asarray(out),
                                 jnp.asarray(bucket + j, jnp.int32))
            out = np.asarray(logits).argmax(-1).astype(np.int32)

    def warm(work):
        # compile each bucket the trace uses + the decode step
        for b in sorted({ragged.bucket_length(len(p), buckets)
                         for _, p, _ in work}):
            serve_group([(0.0, np.zeros(b, np.int32), 2)])

    def once(work):
        done, i, pending = 0, 0, []
        lat, ttfts, goodput = [], [], 0
        t0 = time.perf_counter()
        while i < len(work) or pending:
            now = time.perf_counter() - t0
            while i < len(work) and work[i][0] <= now:
                pending.append(work[i])
                i += 1
            if len(pending) >= batch or (i == len(work) and pending):
                group = pending[:batch]
                pending = pending[batch:]
                serve_group(group)
                end = time.perf_counter() - t0
                for arr, _p, m in group:
                    lat.append(end - arr)
                    ttfts.append(end - arr)   # lockstep: tokens land
                    goodput += m              # at the END of the batch
                done += len(group)
            elif i < len(work):
                time.sleep(min(max(work[i][0] - now, 0.0), 0.05))
        wall = time.perf_counter() - t0
        return {"variant": "lockstep", "requests": done,
                "tokens": goodput, "wall_s": round(wall, 4),
                "tokens_per_sec": round(goodput / wall, 2),
                "p50_latency_s": round(_pct(lat, 0.5), 4),
                "p99_latency_s": round(_pct(lat, 0.99), 4),
                "ttft_p50_s": round(_pct(ttfts, 0.5), 4),
                "ttft_p99_s": round(_pct(ttfts, 0.99), 4)}

    return warm, once


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=96,
                    help="trace length; sized so ONE adversarial "
                         "request cannot occupy the p99 index (TTFT "
                         "p99 measures the 99%, not the adversary)")
    ap.add_argument("--batch", type=int, default=8,
                    help="decode slots (= lockstep batch size)")
    ap.add_argument("--rate", type=float, default=16.0,
                    help="latency-phase Poisson arrival rate, req/s "
                         "(the throughput phase arrives all-at-once). "
                         "The default offers a load BETWEEN the two "
                         "engines' measured capacities: the row engine "
                         "falls steadily behind while the paged engine "
                         "keeps up — the SLO band the prefix cache "
                         "buys")
    ap.add_argument("--vocab", type=int, default=2048)
    ap.add_argument("--d-model", type=int, default=128)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--cache-len", type=int, default=512)
    ap.add_argument("--prompt-lens", default="16,32,64,96",
                    help="mixed prompt lengths (lockstep pads each "
                         "group to the max)")
    ap.add_argument("--max-new", default="4,8,16,32",
                    help="mixed output budgets (lockstep decodes every "
                         "row to the group max)")
    ap.add_argument("--shared-prefix-frac", type=float, default=0.5,
                    help="fraction of requests carrying one common "
                         "system prompt (prefix-cache traffic)")
    ap.add_argument("--shared-prefix-len", type=int, default=256,
                    help="length of the shared system prompt (long "
                         "enough that the row engine's bucket-padded "
                         "prefill cost is material — the field study's "
                         "system-prompt regime)")
    ap.add_argument("--long-prompt-adversarial", action="store_true",
                    help="insert ONE near-cache_len prompt mid-burst "
                         "into the latency trace (the chunked-prefill "
                         "stress)")
    ap.add_argument("--block-size", type=int, default=16,
                    help="paged-engine KV block size (tokens)")
    ap.add_argument("--chunk-tokens", type=int, default=64,
                    help="paged-engine prefill chunk size (tokens)")
    ap.add_argument("--num-blocks", type=int, default=None,
                    help="paged pool size (default: HBM parity with "
                         "the row arena, batch*cache_len/block_size)")
    ap.add_argument("--pallas", default=None,
                    choices=("auto", "on", "off", "interpret"),
                    help="PADDLE_TPU_PALLAS override for the "
                         "engine_paged_pallas variant (default: env > "
                         "auto — Pallas on TPU, skipped elsewhere; the "
                         "interpreter is a correctness path, far too "
                         "slow for a timed trace off --smoke)")
    ap.add_argument("--working-set-mult", type=float, default=10.0,
                    help="tiered_cache phase: prefix working set as a "
                         "multiple of the block pool (10x = the "
                         "capacity-starved regime the HBM->DRAM->disk "
                         "spill is for)")
    ap.add_argument("--repeats", type=int, default=3,
                    help="replays per (variant, phase); the best run "
                         "is reported (noise-robust on shared hosts)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--platform", default=None)
    ap.add_argument("--metrics-out", default=None,
                    help="append JSONL records here (bench.py trail "
                         "conventions)")
    ap.add_argument("--out", default=None,
                    help="JSON artifact path (default: "
                         "benchmarks/runs/<date>_serving_paged.json; "
                         "skipped under --smoke unless given)")
    ap.add_argument("--trace-out", default=None,
                    help="export the per-request lifecycle trace of a "
                         "dedicated latency-phase replay (Chrome-trace "
                         "JSON) and assert every completed request's "
                         "lifecycle is fully joined — no orphan "
                         "spans. With --fleet: export the joined "
                         "multi-replica trace of the chaos run "
                         "(router route/queue/place spans + engine "
                         "lifecycles + the kill-and-requeue, one "
                         "connected tree per request)")
    ap.add_argument("--tpu-check", action="store_true",
                    help="deviceless XLA:TPU export of the paged step "
                         "programs per KV dtype (fp32/int8/int4, XLA "
                         "attention path) — proves the quantized-pool "
                         "writes/gathers compile for TPU without a "
                         "chip; ASSERTS every Pallas serving kernel "
                         "(flash-decode, chunk-prefill, span-write, "
                         "fused sampler) lowers through Mosaic at the "
                         "head-major pool layout and stamps the legal "
                         "BlockSpecs + VMEM estimates")
    ap.add_argument("--fleet", action="store_true",
                    help="run ONLY the serving-fleet phase (router "
                         "goodput + victim TTFT vs one engine at "
                         "equal total slots, placement hit rate, P/D "
                         "bitwise check) and write the date-stamped "
                         "serving_fleet artifact the router sentinel "
                         "family compares")
    ap.add_argument("--fleet-chaos", action="store_true",
                    help="run ONLY the fleet-control-plane chaos "
                         "phase (diurnal trace + kill at the peak: "
                         "controlled fleet with healing/rewarm/"
                         "admission vs a static baseline) and write "
                         "the date-stamped fleet_chaos artifact the "
                         "fleet sentinel family compares")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny preset for the tier-1 fast test: few "
                         "requests, near-zero inter-arrival gaps")
    args = ap.parse_args(argv)
    if args.smoke:
        args.requests, args.batch, args.rate = 6, 2, 1e6
        args.vocab, args.d_model, args.layers = 64, 16, 2
        args.cache_len = 64
        args.prompt_lens, args.max_new = "4,10", "4,8"
        args.shared_prefix_frac = max(args.shared_prefix_frac, 0.5)
        args.shared_prefix_len = 16
        args.block_size, args.chunk_tokens = 8, 16
        args.long_prompt_adversarial = True
        args.repeats = 1

    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    if args.fleet_chaos:
        # standalone control-plane chaos run: its own figures, its
        # own fleet_chaos artifact (the `fleet` sentinel family's
        # glob — distinct from serving_fleet, which the `router`
        # family matches)
        results = {"fleet_chaos": fleet_chaos_phase(args)}
        line = {"bench": "serving", "phase": "fleet_chaos",
                "platform": jax.default_backend(),
                **{k: v for k, v in results["fleet_chaos"].items()
                   if not isinstance(v, dict)}}
        print(json.dumps(line), flush=True)
        metrics_write(**line)
        for key in ("chaos_latency_ttft_p99_s", "chaos_ttft_ratio",
                    "healed_capacity_frac", "recovery_s",
                    "rewarm_exports", "rewarm_blocks_avoided",
                    "shed_before_saturate_ok",
                    "all_admitted_completed"):
            results[key] = results["fleet_chaos"][key]
        write_artifact(results, "fleet_chaos", args)
        return results

    if args.fleet:
        # standalone fleet run: its own figures, its own date-stamped
        # artifact (the check_regression `router` family's glob) —
        # the colocated serving figures above stay untouched
        results = {"fleet": fleet_phase(args)}
        line = {"bench": "serving", "phase": "fleet",
                "platform": jax.default_backend(),
                **{k: v for k, v in results["fleet"].items()
                   if not isinstance(v, dict)}}
        print(json.dumps(line), flush=True)
        metrics_write(**line)
        for key in ("router_goodput_ratio", "victim_ttft_ratio",
                    "placement_hit_rate", "observability_overhead",
                    "all_requests_completed", "pd_bitwise_ok",
                    "chaos_joined_ok"):
            results[key] = results["fleet"][key]
        results["fleet_tokens_per_sec"] = \
            results["fleet"]["fleet"]["tokens_per_sec"]
        write_artifact(results, "serving_fleet", args)
        return results

    from paddle_tpu.core import ragged
    from paddle_tpu.models import transformer
    from paddle_tpu.observe.compile_tracker import CompileTracker

    prompt_lens = [int(x) for x in args.prompt_lens.split(",")]
    max_news = [int(x) for x in args.max_new.split(",")]
    # the lockstep baseline LEFT-pads a group to its prompt bucket and
    # decodes every row from position bucket onward, so ITS cache (and
    # the model's position budget) must provision bucket + output on
    # top of the worst bucket = cache_len — the engines, which track
    # true prompt lengths, stay at cache_len (the HBM-parity point)
    lk_cache_len = args.cache_len + max(max_news)
    cfg = transformer.TransformerConfig(
        vocab=args.vocab, d_model=args.d_model,
        n_heads=max(2, args.d_model // 32), n_kv_heads=0,
        n_layers=args.layers, d_ff=args.d_model * 4,
        max_len=lk_cache_len,
        dtype=jnp.float32 if jax.default_backend() == "cpu"
        else jnp.bfloat16, use_rope=True)
    params = transformer.init_params(jax.random.PRNGKey(0), cfg)
    shaping = dict(shared_frac=args.shared_prefix_frac,
                   shared_len=args.shared_prefix_len,
                   cache_len=args.cache_len)
    # throughput: offered load saturates the engine (capacity);
    # latency: Poisson at --rate (scheduling-path TTFT)
    work_tp = build_workload(args.requests, 1e9, prompt_lens, max_news,
                             args.vocab, args.seed, **shaping)
    work_lat = build_workload(
        args.requests, args.rate, prompt_lens, max_news, args.vocab,
        args.seed + 1, adversarial=args.long_prompt_adversarial,
        burst=args.batch, **shaping)
    all_lens = {len(p) for _, p, _ in work_tp + work_lat}
    # row-arena/lockstep prompt buckets must cover every trace length
    # (the paged engine needs no such bucket: chunked prefill)
    buckets = tuple(sorted({min(
        2 ** int(np.ceil(np.log2(max(n, 2)))), args.cache_len)
        for n in all_lens}))

    trace_cfg = {"trace_requests": args.requests, "rate": args.rate,
                 "shared_prefix_frac": args.shared_prefix_frac,
                 "shared_prefix_len": args.shared_prefix_len,
                 "long_prompt_adversarial": args.long_prompt_adversarial,
                 "block_size": args.block_size,
                 "chunk_tokens": args.chunk_tokens,
                 "cache_len": args.cache_len, "batch": args.batch,
                 "repeats": args.repeats}

    # the paged tracker's storm threshold sits above the chunk-grid
    # program ceiling: one compile per (bucket, span) is the DESIGN,
    # not a storm (the invariant below still pins the exact count)
    from paddle_tpu.io import lm_serving
    from paddle_tpu.ops.pallas import policy as pallas_policy
    from paddle_tpu.serving import default_chunk_buckets
    chunk = min(args.chunk_tokens, args.cache_len)
    n_chunk_buckets = len(default_chunk_buckets(chunk))
    storm = (args.cache_len // chunk) * n_chunk_buckets + 2
    paged_kw = dict(batch=args.batch, cache_len=args.cache_len,
                    block_size=args.block_size,
                    chunk_tokens=args.chunk_tokens,
                    num_blocks=args.num_blocks)
    paged_tr = CompileTracker(storm_threshold=storm)
    slots_tr = CompileTracker()
    int8_tr = CompileTracker(storm_threshold=storm)
    # the baselines PIN pallas="off": on TPU the ambient policy would
    # otherwise resolve "on" and the "XLA engine" baseline would BE the
    # Pallas path — serving_pallas_speedup comparing Pallas vs Pallas
    mk_paged = paged_factory(params, cfg, tracker=paged_tr,
                             pallas="off", **paged_kw)
    mk_slots = slots_factory(
        params, cfg, batch=args.batch, cache_len=args.cache_len,
        buckets=buckets, tracker=slots_tr)
    # fp32-vs-int8: the same paged engine over quantize_lm_params
    # weights — decode reads int8 (in-scan dequant), prefill dequantizes
    # wholesale; XLA attention either way so the figure isolates the
    # weight dtype
    params_q8 = lm_serving.quantize_lm_params(params)
    mk_int8 = paged_factory(params_q8, cfg, tracker=int8_tr,
                            pallas="off", **paged_kw)
    # fp32-vs-int8-KV: the same engine over an int8-quantized POOL —
    # the decode-side KV stream at 1 byte/elt (+ scale rows); XLA
    # attention and fp32 weights either way so the figure isolates the
    # KV storage width
    kv8_tr = CompileTracker(storm_threshold=storm)
    mk_kv8 = paged_factory(params, cfg, tracker=kv8_tr, pallas="off",
                           kv_dtype="int8", **paged_kw)
    # XLA-vs-Pallas: one more paged variant with the flash-decode
    # kernel + fused sampling epilogue, run only where the policy turns
    # it on (auto = TPU; the interpreter is correctness-speed and gets
    # its own dedicated check under --smoke below)
    pallas_mode = pallas_policy.pallas_mode(args.pallas)
    # timed only where the kernels would actually be IN the program:
    # off-TPU the dispatch guard (decode.kernels_dispatchable) routes
    # "on" to the XLA path, and timing that as "engine_paged_pallas"
    # would report a fake 1.0x kernel speedup; on TPU the head-major
    # kernels dispatch for real (per-shape lowering probes + VMEM
    # budgets permitting)
    from paddle_tpu.ops.pallas import decode as _pallas_decode_mod
    pallas_timed = (pallas_mode == "on"
                    and _pallas_decode_mod.kernels_dispatchable(
                        pallas_mode))
    pallas_tr = CompileTracker(storm_threshold=storm)
    mk_pallas = paged_factory(params, cfg, tracker=pallas_tr,
                              pallas=args.pallas, **paged_kw) \
        if pallas_timed else None

    lk_warm, lk_once = lockstep_factory(
        params, cfg, batch=args.batch, cache_len=lk_cache_len,
        buckets=buckets)

    results = {"pallas": {"mode": pallas_mode, "timed": pallas_timed}}
    repeats = max(1, args.repeats)
    for phase, work in (("throughput", work_tp), ("latency", work_lat)):
        engines = [("engine_paged", mk_paged),
                   ("engine_slots", mk_slots)]
        if phase == "throughput":
            # the throughput phase carries the kernel/int8/kv8 A/Bs
            # (their figures of merit are tokens/sec and decode MFU)
            if mk_pallas is not None:
                engines.insert(1, ("engine_paged_pallas", mk_pallas))
            engines.insert(len(engines) - 1,
                           ("engine_paged_int8", mk_int8))
            engines.insert(len(engines) - 1,
                           ("engine_paged_kv8", mk_kv8))
        warms = {name: warm_engine(mk, work, args.vocab)
                 for name, mk in engines}
        lk_warm(work)
        # repeats INTERLEAVED across variants so ambient machine load
        # lands on all of them, not on whichever ran first; each phase
        # keeps the repeat best at ITS OWN figure of merit (capacity:
        # tokens/sec; scheduling: TTFT p99) for every variant alike
        def better(r, b):
            if phase == "latency":
                return r["ttft_p99_s"] < b["ttft_p99_s"]
            return r["tokens_per_sec"] > b["tokens_per_sec"]

        runners = [(name, (lambda mk=mk, name=name: engine_once(
            mk, name, work, warms[name]))) for name, mk in engines]
        runners.append(("lockstep", lambda: lk_once(work)))
        best = {}
        for _ in range(repeats):
            for variant, once in runners:
                r = once()
                if variant not in best or better(r, best[variant]):
                    best[variant] = r
        results[phase] = {}
        for variant, r in best.items():
            r.update({"bench": "serving", "phase": phase,
                      "platform": jax.default_backend(), **trace_cfg})
            results[phase][variant] = r
            print(json.dumps(r), flush=True)
            metrics_write(**r)

    # compile discipline across BOTH phases and all repeats: one
    # program per (chunk bucket, context span) / prompt bucket + one
    # decode, regardless of paging, hits, adoption, weight dtype, or
    # attention engine
    progs = _paged_programs(all_lens, chunk, args.block_size,
                            default_chunk_buckets(chunk))
    # the int8/pallas A/B variants replay the throughput trace only —
    # their reachable program set is that phase's, not the union
    progs_tp = _paged_programs({len(p) for _, p, _ in work_tp}, chunk,
                               args.block_size,
                               default_chunk_buckets(chunk))
    for name, tr, want in (("paged", paged_tr, progs),
                           ("int8", int8_tr, progs_tp),
                           ("kv8", kv8_tr, progs_tp)) + (
            (("pallas", pallas_tr, progs_tp),) if pallas_timed else ()):
        assert tr.count("serving_engine.decode") == 1, name
        assert tr.count("serving_engine.prefill") == len(want), (
            f"{name} compile invariant: expected {len(want)} chunk "
            f"programs {sorted(want)}, saw "
            f"{tr.count('serving_engine.prefill')}")
    assert slots_tr.count("serving_engine.decode") == 1
    assert slots_tr.count("serving_engine.prefill") <= len(buckets)

    # the interpret-mode kernels must not rot on CPU-only CI: replay a
    # tiny greedy trace on pallas=interpret engines and demand ids
    # identical to the XLA engines' (greedy sampling is exact on both
    # paths). One prompt exceeds chunk_tokens so the CHUNKED-PREFILL
    # kernel runs with real context; the second pass repeats the whole
    # check over an int8-KV pool, so the FUSED-DEQUANT reads (decode +
    # prefill) are certified too. Runs under --smoke (tier-1) AND in
    # the full bench.
    srng = np.random.RandomState(11)
    n_long = min(chunk + 5, args.cache_len - 8)
    tiny = [srng.randint(0, args.vocab, n).astype(np.int32)
            for n in (5, 9, n_long)]
    for kvd in (None, "int8"):
        # XLA side reuses the throughput factories' compiled programs
        # (mk_paged / mk_kv8 are the same config at pallas="off"); the
        # compile-invariant asserts above already ran, so the tiny
        # replay's extra chunk shapes cannot contaminate them
        interp_tr = CompileTracker(storm_threshold=storm)
        variant_mks = [
            paged_factory(params, cfg, tracker=interp_tr,
                          pallas="interpret", kv_dtype=kvd, **paged_kw),
            mk_paged if kvd is None else mk_kv8]
        outs = []
        for mk in variant_mks:
            eng = mk()
            reqs = [eng.submit(p, max_new=4) for p in tiny]
            eng.run_until_idle()
            outs.append([r.output.tolist() for r in reqs])
        assert outs[0] == outs[1], (
            f"pallas interpret (kv_dtype={kvd}) diverged from the "
            f"XLA path:\n{outs[0]}\nvs\n{outs[1]}")
        key = ("interpret_check_ok" if kvd is None
               else f"interpret_check_kv{kvd[3:]}_ok")
        results["pallas"][key] = True
        line = {"bench": "serving", "phase": "pallas_interpret_check",
                "mode": "interpret", "kv_dtype": kvd or "none",
                "requests": len(tiny), "ok": True}
        print(json.dumps(line), flush=True)
        metrics_write(**line)

    # KV-quantization scoreboards: slots-at-equal-HBM (capacity),
    # cold-prefill TTFT (no cache hits — the chunked-prefill path
    # isolated), and the rel-L2 quality contracts
    results["capacity"] = capacity_phase(
        params, cfg, cache_len=args.cache_len,
        block_size=args.block_size, chunk_tokens=args.chunk_tokens,
        batch=args.batch, num_blocks=args.num_blocks, vocab=args.vocab,
        seed=args.seed)
    line = {"bench": "serving", "phase": "capacity",
            "platform": jax.default_backend(), **results["capacity"]}
    print(json.dumps(line), flush=True)
    metrics_write(**line)
    assert results["capacity"]["capacity_contract_ok"], (
        "int8-KV pool capacity fell short of its contract (2x vs an "
        "fp32 baseline; the byte-ratio bound vs a narrower one): "
        f"{results['capacity']}")

    work_cold = build_workload(
        args.requests, args.rate, prompt_lens, max_news, args.vocab,
        args.seed + 2, shared_frac=0.0, shared_len=0)
    cold_variants = [("xla", "off")] + (
        [("pallas", args.pallas)] if pallas_timed else [])
    results["cold_prefill"] = {"requests": args.requests,
                               "rate": args.rate}
    for cname, cmode in cold_variants:
        cold_tr = CompileTracker(storm_threshold=storm)
        mk_cold = paged_factory(params, cfg, tracker=cold_tr,
                                pallas=cmode, **paged_kw)
        warm_cold = warm_engine(mk_cold, work_cold, args.vocab)
        best_cold = None
        for _ in range(repeats):
            r = engine_once(mk_cold, f"engine_paged_cold_{cname}",
                            work_cold, warm_cold)
            if best_cold is None or r["ttft_p50_s"] < \
                    best_cold["ttft_p50_s"]:
                best_cold = r
        suffix = "" if cname == "xla" else "_pallas"
        results["cold_prefill"][f"ttft_p50_cold_ms{suffix}"] = round(
            best_cold["ttft_p50_s"] * 1000, 3)
        results["cold_prefill"][f"ttft_p99_cold_ms{suffix}"] = round(
            best_cold["ttft_p99_s"] * 1000, 3)
    line = {"bench": "serving", "phase": "cold_prefill",
            "platform": jax.default_backend(),
            **results["cold_prefill"]}
    print(json.dumps(line), flush=True)
    metrics_write(**line)

    results["quality"] = kv_quality_probe(
        params, cfg, block_size=args.block_size,
        chunk_tokens=args.chunk_tokens, vocab=args.vocab,
        seed=args.seed)
    line = {"bench": "serving", "phase": "kv_quality",
            **results["quality"]}
    print(json.dumps(line), flush=True)
    metrics_write(**line)

    # multi-tenant scheduling A/B (tiered vs FIFO on a tight pool) and
    # the speculative-decoding A/B — each on its own phase config, so
    # the figures above are untouched; both run under --smoke too
    # (compile asserts + bitwise contracts must not rot on tier-1)
    results["multitenant"] = multitenant_phase(args)
    line = {"bench": "serving", "phase": "multitenant",
            "platform": jax.default_backend(),
            **{k: v for k, v in results["multitenant"].items()
               if not isinstance(v, dict)}}
    print(json.dumps(line), flush=True)
    metrics_write(**line)
    results["tier_p99_separation_ok"] = \
        results["multitenant"]["tier_p99_separation_ok"]
    results["goodput_ge_fifo"] = \
        results["multitenant"]["goodput_ge_fifo"]

    # tiered prefix cache (HBM -> DRAM -> disk) vs evict-and-recompute
    # on the 10x-working-set chat trace; its two figures ride the
    # artifact top level for the sentinel's absolute floor/ceiling
    results["tiered_cache"] = tiered_cache_phase(args)
    line = {"bench": "serving", "phase": "tiered_cache",
            "platform": jax.default_backend(),
            **{k: v for k, v in results["tiered_cache"].items()
               if not isinstance(v, dict)}}
    print(json.dumps(line), flush=True)
    metrics_write(**line)
    results["cold_prefill_tokens_avoided_frac"] = \
        results["tiered_cache"]["cold_prefill_tokens_avoided_frac"]
    results["tiered_ttft_p99_ratio"] = \
        results["tiered_cache"]["tiered_ttft_p99_ratio"]

    results["spec_decode"] = spec_phase(args)
    line = {"bench": "serving", "phase": "spec_decode",
            "platform": jax.default_backend(),
            **results["spec_decode"]}
    print(json.dumps(line), flush=True)
    metrics_write(**line)
    results["spec_decode_speedup"] = \
        results["spec_decode"]["spec_decode_speedup"]

    if args.smoke:
        # fleet phase rides the tier-1 smoke so its bitwise contracts
        # (P/D disaggregation == colocated, zero lost requests) can't
        # rot; the goodput/victim-TTFT CLAIMS come from dedicated
        # --fleet runs and their own artifact
        results["fleet"] = fleet_phase(args)
        line = {"bench": "serving", "phase": "fleet",
                "platform": jax.default_backend(),
                **{k: v for k, v in results["fleet"].items()
                   if not isinstance(v, dict)}}
        print(json.dumps(line), flush=True)
        metrics_write(**line)

    if args.tpu_check:
        results["tpu_check"] = tpu_export_check(
            params, cfg, block_size=args.block_size,
            chunk_tokens=args.chunk_tokens, batch=args.batch,
            cache_len=args.cache_len)
        line = {"bench": "serving", "phase": "tpu_check",
                **{k: v for k, v in results["tpu_check"].items()
                   if not k.endswith("_detail")
                   and k not in ("blockspecs", "vmem_bytes")}}
        print(json.dumps(line), flush=True)
        metrics_write(**line)
        assert all(results["tpu_check"][f"xla_{d}_ok"]
                   for d in ("fp32", "int8", "int4")), \
            results["tpu_check"]
        # head-major relayout contract: every serving kernel lowers
        # through Mosaic at every KV dtype — a failed probe here is a
        # layout regression, asserted outright AND exported as a
        # sentinel boolean so it can never land silently
        assert results["tpu_check"]["mosaic_ok"], {
            k: v for k, v in results["tpu_check"].items()
            if k.startswith("pallas_")}
        results["mosaic_lowerable_ok"] = \
            results["tpu_check"]["mosaic_ok"]

    # dedicated attribution replay: one more latency-phase run on a
    # fresh paged engine with request-lifecycle tracing captured — the
    # per-request tail-latency evidence (and, with --trace-out, the
    # joined-timeline export). Programs are already compiled, so this
    # replay adds no compiles (the invariant above already swept it).
    if args.trace_out or args.long_prompt_adversarial:
        from paddle_tpu import observe
        buf = observe.default_buffer()
        if not buf.enabled or buf.capacity < 4096:
            buf = observe.set_trace_capacity(65536)
        buf.clear()
        eng = mk_paged()
        reqs, _, _, _ = _replay(eng, work_lat)
        attribution = attribution_section(work_lat, reqs,
                                          burst=args.batch,
                                          request_log=eng.request_log)
        results["attribution"] = attribution
        line = {"bench": "serving", "phase": "attribution",
                "requests": attribution["requests"]}
        if "victims" in attribution:
            line.update({f"victims_{k}": v for k, v in
                         attribution["victims"].items()})
        print(json.dumps(line), flush=True)
        metrics_write(**line)
        if args.trace_out:
            trace = observe.trace_export(args.trace_out)
            assert_lifecycles_joined(trace, reqs, buf)
            print(f"wrote per-request trace to {args.trace_out} "
                  f"({len(reqs)} requests, all lifecycles joined)",
                  file=sys.stderr)

    tp, lat = results["throughput"], results["latency"]
    speedup = (tp["engine_paged"]["tokens_per_sec"]
               / max(tp["engine_slots"]["tokens_per_sec"], 1e-9))
    ttft_ratio = (lat["engine_paged"]["ttft_p99_s"]
                  / max(lat["engine_slots"]["ttft_p99_s"], 1e-9))
    int8_speedup = (tp["engine_paged_int8"]["tokens_per_sec"]
                    / max(tp["engine_paged"]["tokens_per_sec"], 1e-9))
    kv8_speedup = (tp["engine_paged_kv8"]["tokens_per_sec"]
                   / max(tp["engine_paged"]["tokens_per_sec"], 1e-9))
    figures = [("serving_paged_speedup", speedup),
               ("serving_paged_ttft_p99_ratio", ttft_ratio),
               # int8-vs-fp32 on the SAME engine: >1 where weight reads
               # bound decode (TPU); CPU pays the dequant ALU instead
               # and reports honestly below 1
               ("serving_int8_speedup", int8_speedup),
               # int8-KV-pool vs fp32-pool throughput on the SAME
               # engine: ~1 on CPU (the dequant ALU offsets the byte
               # win); TPU is where the KV-stream-bound step pays. The
               # capacity win (slots_at_equal_hbm) is dtype-arithmetic
               # and holds everywhere.
               ("serving_kv8_speedup", kv8_speedup)]
    if "engine_paged_pallas" in tp:
        figures.append((
            "serving_pallas_speedup",
            tp["engine_paged_pallas"]["tokens_per_sec"]
            / max(tp["engine_paged"]["tokens_per_sec"], 1e-9)))
    for metric, value in figures:
        line = {"bench": "serving", "metric": metric,
                "value": round(value, 3),
                "platform": jax.default_backend(), **trace_cfg}
        print(json.dumps(line), flush=True)
        metrics_write(**line)
        results[metric] = round(value, 3)

    write_artifact(results, "serving_paged", args)
    return results


if __name__ == "__main__":
    main()
