#!/usr/bin/env python
"""q8/q8sr/defer quality ladder at ImageNet-class channel widths.

The round-4 quality evidence lived on a 16-channel toy net; the claim
that per-channel scales average better at real widths was extrapolation
(VERDICT r4 "Missing #4"). This runs the decision-relevant arms
(unfused / defer / q8sr / q8) on the model_zoo CIFAR ResNet widened to
the 64–256-channel ladder (models/resnet.resnet_cifar10(width=64) —
stage widths 64/128/256, the same span as ResNet-50's 3x3 trunk convs),
≥1k steps, identical init/data order across arms, held-out accuracy
sampled mid-training (where deterministic q8's transient dip lives) and
at the end.

Reference analog: the book-test convergence suite
(/root/reference/python/paddle/v2/framework/tests/book/
test_image_classification_train.py) — train a real topology for real
steps and check the quality metric, not just the loss.

Run: python benchmarks/q8_quality_width.py [--steps 1000] [--width 64]
Artifact: benchmarks/runs/q8_quality_width<W>_s<steps>.json
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=1000)
    ap.add_argument("--width", type=int, default=64)
    ap.add_argument("--depth", type=int, default=20)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--eval-every", type=int, default=200)
    ap.add_argument("--modes", default="0,defer,q8sr,q8")
    ap.add_argument("--noise", type=float, default=3.0,
                    help="sample noise sigma; must be large enough that "
                    "the width-64 net does NOT saturate held-out "
                    "accuracy, or arm differences become invisible")
    ap.add_argument("--platform", default=None)
    args = ap.parse_args()

    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import layer
    from paddle_tpu.models import resnet
    from paddle_tpu.topology import Topology, Value
    from paddle_tpu.utils.rng import KeySource

    # synthetic CIFAR-shaped task (no dataset egress in this
    # environment): 10 classes of smoothed prototype images + noise at
    # an SNR where a ResNet-20 reaches high-but-not-saturated held-out
    # accuracy within ~1k steps — quality differences stay visible.
    rng = np.random.RandomState(0)
    dim = 3 * 32 * 32
    raw = rng.randn(10, 3, 32, 32).astype(np.float32)
    # smooth spatially so convs have structure to exploit
    protos = raw
    for _ in range(2):
        protos = (protos
                  + np.roll(protos, 1, 2) + np.roll(protos, -1, 2)
                  + np.roll(protos, 1, 3) + np.roll(protos, -1, 3)) / 5.0
    protos = protos.reshape(10, dim)
    protos /= np.abs(protos).max(1, keepdims=True)
    n_train, n_test = 2048, 512

    def make(n, seed):
        r = np.random.RandomState(seed)
        ys = r.randint(0, 10, n)
        xs = (protos[ys]
              + r.randn(n, dim).astype(np.float32) * args.noise)
        return xs.astype(np.float32), ys.astype(np.int32)

    xs, ys = make(n_train, 1)
    xt, yt = make(n_test, 2)

    def held_out_acc(fwd, p, s):
        accs = []
        bs = 128
        for j in range(0, n_test, bs):
            probs, _ = fwd(p, s, {"img": Value(jnp.asarray(xt[j:j + bs])),
                                  "lbl": Value(jnp.asarray(yt[j:j + bs]))},
                           is_training=False)
            accs.append(np.asarray(probs["rc_fc"].array).argmax(-1)
                        == yt[j:j + bs])
        return float(np.concatenate(accs).mean())

    results = {}
    for mode_s in args.modes.split(","):
        mode = {"0": False, "1": True}.get(mode_s, mode_s)
        t0 = time.time()
        img = layer.data("img", paddle.data_type.dense_vector(dim))
        lbl = layer.data("lbl", paddle.data_type.integer_value(10))
        sm = resnet.resnet_cifar10(img, depth=args.depth, class_num=10,
                                   fused_bn=mode, width=args.width)
        cost = layer.classification_cost(sm, lbl, name="w_cost")
        topo = Topology([cost, sm])
        params = paddle.parameters.create(cost, KeySource(7))
        fwd = topo.compile()
        opt = paddle.optimizer.Momentum(momentum=0.9, learning_rate=0.05)
        o = opt.init_state(params.values)

        @jax.jit
        def step(p, o, s, bx, by, key):
            def loss_fn(p):
                outs, ns = fwd(p, s, {"img": Value(bx), "lbl": Value(by)},
                               is_training=True, dropout_key=key)
                return (jnp.mean(outs["w_cost"].array.astype(
                    jnp.float32)), ns)
            (l, ns), g = jax.value_and_grad(loss_fn, has_aux=True)(p)
            np_, no_ = opt.update(jnp.asarray(0, jnp.int32), g, p, o)
            return l, np_, no_, ns

        p, s = params.values, params.state
        bs = args.batch
        losses, curve = [], []
        for i in range(args.steps):
            j = (i * bs) % (n_train - bs + 1)
            l, p, o, s = step(p, o, s, jnp.asarray(xs[j:j + bs]),
                              jnp.asarray(ys[j:j + bs]),
                              jax.random.PRNGKey(1000 + i))
            losses.append(float(l))
            if (i + 1) % args.eval_every == 0:
                acc = held_out_acc(fwd, p, s)
                curve.append({"step": i + 1, "acc": round(acc, 4)})
                print(f"  mode={mode_s:6} step {i+1:5d} "
                      f"loss {losses[-1]:.4f} heldout {acc:.4f}",
                      flush=True)
        results[mode_s] = {
            "final_loss": round(losses[-1], 4),
            "first_loss": round(losses[0], 4),
            "curve": curve,
            "final_acc": curve[-1]["acc"] if curve else None,
            "min_acc_after_first_eval": (min(c["acc"] for c in curve)
                                         if curve else None),
            "wall_s": round(time.time() - t0, 1),
        }
        print(f"mode={mode_s:6} done in {results[mode_s]['wall_s']}s: "
              f"final acc {results[mode_s]['final_acc']}", flush=True)

        # write after EVERY arm so a wall-clock cutoff still leaves the
        # completed arms' evidence on disk
        out = {
            "config": {"width": args.width, "depth": args.depth,
                       "batch": args.batch, "steps": args.steps,
                       "noise": args.noise,
                       "channel_ladder": [args.width, 2 * args.width,
                                          4 * args.width],
                       "task": "synthetic 10-class CIFAR-shaped"},
            "results": results,
        }
        path = os.path.join(
            REPO, "benchmarks", "runs",
            f"q8_quality_width{args.width}_s{args.steps}.json")
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
        print(f"wrote {path} ({len(results)} arm(s))", flush=True)
    if "0" in results and results["0"]["final_acc"] is not None:
        base = results["0"]["final_acc"]
        for m, r in results.items():
            if m == "0":
                continue
            print(f"{m}: final {r['final_acc']:+.4f} vs base {base:.4f} "
                  f"(delta {r['final_acc'] - base:+.4f}); "
                  f"mid-training min {r['min_acc_after_first_eval']:.4f}")


if __name__ == "__main__":
    main()
